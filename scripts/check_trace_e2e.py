#!/usr/bin/env python
"""End-to-end trace-propagation chaos smoke on CPU: a REAL 3-replica
``llama:tiny`` :class:`ReplicaGroup` (separate supervised processes,
all tracing into one ``ZOO_TRACE_DIR``), hedged ``generate`` traffic
through :class:`HAServingClient`, one replica SIGKILLed mid-stream —
and the observability contract holds:

* ZERO client-visible failures (failover-resume absorbs the kill);
* for a stream that crossed the kill, the timeline merger reconstructs
  — from the per-process JSONL files alone — ONE trace containing the
  client's attempt spans (>= 2: the original plus the failover resume)
  AND engine/server spans from BOTH replicas (the killed one's partial
  spans survive in its torn file);
* a postmortem bundle for the killed replica is harvested into the
  group dir (the SIGKILL left no chance to dump — the bundle is
  rebuilt from the continuously-flushed flight spill);
* every shed/error reply carries the request's trace id (probed via a
  deliberately unserved model-version predict).

Run directly (``python scripts/check_trace_e2e.py``) or from the suite
(``tests/test_obs_trace.py`` runs it under the ``obs`` marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# small pool + small buckets bound the per-replica compile time, same
# rationale as scripts/check_llm_serving.py
SPEC = "llama:tiny:slots=4,block=8,blocks=96,tables=8,buckets=16/32"


def check(verbose: bool = True) -> int:
    import numpy as np

    import zoo_tpu.obs as obs
    from zoo_tpu.obs.timeline import group_traces, load_events
    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient

    work = tempfile.mkdtemp(prefix="zoo-trace-e2e-")
    trace_dir = os.path.join(work, "trace")
    log_dir = os.path.join(work, "logs")
    # the CLIENT traces too — its attempt spans land beside the
    # replicas' files in the same dir
    obs.trace_to(trace_dir)

    group = ReplicaGroup(
        SPEC, num_replicas=3, max_restarts=2, log_dir=log_dir,
        env={"ZOO_TRACE_DIR": trace_dir, "ZOO_OBS_FLIGHT_CAP": "512"})
    group.start(timeout=240)
    client = HAServingClient(group.endpoints(), deadline_ms=240_000,
                             hedge=True, hedge_delay_ms=500)

    rs = np.random.RandomState(0)
    n_streams = 8
    prompts = [rs.randint(0, 256, (int(rs.randint(3, 15)),)).astype(
        np.int32) for _ in range(n_streams)]
    max_new = [24 if i % 2 == 0 else 8 for i in range(n_streams)]
    trace_ids = [f"{i:02d}" + os.urandom(15).hex() for i in
                 range(n_streams)]

    # warm both executables on every replica off the chaos clock
    from zoo_tpu.serving.tcp_client import _Connection
    for host, port in group.endpoints():
        conn = _Connection(host, port)
        for _ in conn.stream({"op": "generate", "prompt": prompts[0],
                              "max_new_tokens": 2}):
            pass
        conn.close()

    errors, done_ok = [], [0]
    lock = threading.Lock()
    first_tokens = threading.Event()
    killed = threading.Event()

    def stream_worker(i):
        try:
            got = []
            for tok in client.generate(prompts[i], max_new[i],
                                       trace_id=trace_ids[i]):
                got.append(tok)
                first_tokens.set()
            if len(got) != max_new[i]:
                raise AssertionError(
                    f"stream {i}: {len(got)} tokens, wanted "
                    f"{max_new[i]}")
            with lock:
                done_ok[0] += 1
        except Exception as e:  # noqa: BLE001 — every failure counts
            with lock:
                errors.append(f"stream {i}: {e!r}")

    def chaos():
        first_tokens.wait(timeout=180)
        group.kill_replica(0)
        killed.set()

    try:
        threads = [threading.Thread(target=stream_worker, args=(i,))
                   for i in range(n_streams)]
        threads.append(threading.Thread(target=chaos))
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        assert killed.is_set(), "the chaos kill never fired"
        assert not errors, (
            f"{len(errors)} client-visible failure(s):\n"
            + "\n".join(errors[:10]))
        assert done_ok[0] == n_streams, done_ok

        # ---- the timeline acceptance: SOME stream crossed the kill and
        # reconstructs into one trace with >= 2 client attempts and
        # engine/server spans from >= 2 distinct replica processes
        obs.stop_tracing()  # flush the client's file
        events = load_events(trace_dir)
        traces = group_traces(events)
        crossed = None
        for tid in trace_ids:
            evs = traces.get(tid, [])
            attempts = [e for e in evs
                        if e.get("name") == "client.attempt"]
            server_files = {e.get("file") for e in evs
                            if str(e.get("name", "")).startswith(
                                ("server.", "llm."))}
            if len(attempts) >= 2 and len(server_files) >= 2:
                crossed = (tid, len(attempts), len(server_files), evs)
                break
        assert crossed is not None, (
            "no stream reconstructs with >=2 client attempts and "
            ">=2 replicas' spans under one trace id; the kill was "
            "absorbed without failover?")
        tid, n_att, n_files, evs = crossed
        # one trace id throughout, engine lifecycle present
        assert all(e.get("trace") == tid for e in evs)
        names = {e.get("name") for e in evs}
        assert "llm.admit" in names, names
        assert "client.generate" in names, names

        # ---- postmortem: the killed replica left a flight spill; the
        # harvest packages it into the group dir
        deadline = time.monotonic() + 30
        bundles = []
        while time.monotonic() < deadline:
            bundles = group.harvest_postmortems()
            if bundles:
                break
            time.sleep(0.3)
        existing = []
        pm_dir = group.postmortem_dir()
        if pm_dir and os.path.isdir(pm_dir):
            existing = [f for f in os.listdir(pm_dir)
                        if f.endswith(".json")]
        assert bundles or existing, (
            "no postmortem bundle harvested from the killed replica")
        import json as _json
        bpath = bundles[0] if bundles else os.path.join(pm_dir,
                                                        existing[0])
        with open(bpath, encoding="utf-8") as f:
            bundle = _json.load(f)
        assert bundle.get("ring"), "harvested bundle has an empty ring"

        # ---- shed/error replies echo the trace id: a version-pinned
        # predict against llm-only replicas errors (llm replicas serve
        # generate only), and the reply must still carry the trace
        conn = _Connection(*group.endpoints()[1])
        probe_tid = "ee" * 16
        resp = conn.rpc({"op": "predict", "uri": "u",
                         "data": np.zeros((1, 2), np.float32),
                         "trace": probe_tid})
        conn.close()
        assert "error" in resp and resp.get("trace") == probe_tid, resp
    finally:
        obs.stop_tracing()
        group.stop()

    if verbose:
        print(f"TRACE E2E OK: {done_ok[0]}/{n_streams} hedged streams "
              f"across a replica SIGKILL, 0 failures; trace {tid[:8]}… "
              f"reconstructed with {n_att} client attempts over "
              f"{n_files} replica processes; postmortem bundle "
              f"harvested with {len(bundle['ring'])} ring event(s); "
              "shed/error replies echo trace ids")
    return 0


if __name__ == "__main__":
    sys.exit(check())
