#!/usr/bin/env bash
# Launch a zoo_tpu training script on every host of a TPU pod slice.
#
# Rebuild of the reference's spark-submit wrappers (scripts/spark-submit-*.sh):
# there the cluster manager distributed the python env and launched
# executors; on TPU the hosts are fixed, so deployment is "copy the wheel,
# run the same script on every worker" — jax.distributed discovers the
# topology from the TPU metadata, and init_orca_context(cluster_mode="tpu")
# does the rest.
#
# Usage:
#   scripts/run_tpu_pod.sh <tpu-name> <zone> <script.py> [args...]
set -euo pipefail
TPU_NAME=${1:?tpu name}; ZONE=${2:?zone}; SCRIPT=${3:?script}; shift 3

# ship the package and the entry script to every worker
gcloud compute tpus tpu-vm scp --worker=all --zone="$ZONE" --recurse \
    "$(dirname "$0")/.." "$TPU_NAME":~/zoo_tpu_pkg
gcloud compute tpus tpu-vm scp --worker=all --zone="$ZONE" \
    "$SCRIPT" "$TPU_NAME":~/job.py

# run one process per host; jax.distributed auto-detects coordinator/rank
gcloud compute tpus tpu-vm ssh --worker=all --zone="$ZONE" "$TPU_NAME" \
    --command="cd ~/zoo_tpu_pkg && PYTHONPATH=~/zoo_tpu_pkg python ~/job.py $*"
