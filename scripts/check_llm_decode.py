#!/usr/bin/env python
"""Decode hot-path smoke END TO END on CPU: a REAL 2-replica
:class:`ReplicaGroup` serving a CHUNKED-PREFILL ``llama:`` spec
(separate supervised processes, bit-identical seed-0 weights) under
concurrent mixed prefill/decode load — long prompts admitted while
short streams decode — and the PR 10 decode contracts hold:

* **chunked-prefill streams byte-identical to unchunked** — every
  stream through the chunked group matches a local engine built from
  the same spec WITHOUT chunking (same seed-0 weights, greedy + seeded
  sampling both);
* **decode-compiles == 1** on every replica after the storm (the
  overlapped pipeline + chunk scheduling never broke the fixed-shape
  contract), and the prompt census compiled ONE chunk executable, not
  one per bucket;
* **zero leaked KV blocks** on every replica (``llm_stats``);
* **overlap ratio above threshold** — the engine's device-busy / wall
  gauge shows the async tick pipeline actually overlapped host
  scheduling with device execution, even on CPU.

Run directly (``python scripts/check_llm_decode.py``) or from the
suite (``tests/test_llm_serving.py`` runs it under the ``perf``
marker).
"""

import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASE = "llama:tiny:slots=4,block=8,blocks=96,tables=10,buckets=16/64"
SPEC = BASE + ",chunk=8"
OVERLAP_FLOOR = 0.15


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.spec import build_llm_engine
    from zoo_tpu.serving.tcp_client import _Connection

    rs = np.random.RandomState(0)
    n_streams = 10
    # mixed load: every 3rd stream is a LONG prompt (multiple chunks)
    # admitted while the short ones decode — the interleave the chunk
    # executable exists for
    prompts = [rs.randint(0, 256, (int(rs.randint(40, 60))
                                   if i % 3 == 0 else
                                   int(rs.randint(3, 15)),)).astype(
        np.int32) for i in range(n_streams)]
    max_new = [6 if i % 3 == 0 else 20 for i in range(n_streams)]
    sampling = [dict(temperature=0.9, top_k=24, top_p=0.95,
                     seed=1000 + i) if i % 2 else {}
                for i in range(n_streams)]

    # ground truth: the SAME spec, unchunked, in-process — bit-identical
    # seed-0 weights, so chunked remote streams must match byte-for-byte
    ref_eng = build_llm_engine(BASE)
    try:
        handles = [ref_eng.submit(p, n, sampling=s or None,
                                  rid=f"ref-{i}")
                   for i, (p, n, s) in enumerate(
                       zip(prompts, max_new, sampling))]
        import time as _t
        deadline = _t.monotonic() + 300
        while not all(h.done for h in handles):
            assert _t.monotonic() < deadline, "reference streams stuck"
            _t.sleep(0.01)
        assert all(h.outcome == "ok" for h in handles), \
            [(h.outcome, h.error) for h in handles]
        refs = [list(h.tokens) for h in handles]
    finally:
        ref_eng.stop()

    log_dir = tempfile.mkdtemp(prefix="zoo-llm-decode-smoke-")
    group = ReplicaGroup(SPEC, num_replicas=2, max_restarts=2,
                         log_dir=log_dir)
    group.start(timeout=180)
    client = HAServingClient(group.endpoints(), deadline_ms=240_000,
                             hedge=False)
    errors, lock = [], threading.Lock()

    def stream_worker(i):
        try:
            got = list(client.generate(prompts[i], max_new[i],
                                       **sampling[i]))
            if got != refs[i]:
                raise AssertionError(
                    f"stream {i} (chunked) != unchunked reference: "
                    f"{got} vs {refs[i]}")
        except Exception as e:  # noqa: BLE001 — every failure counts
            with lock:
                errors.append(f"stream {i}: {e!r}")

    try:
        # warm both replicas' executables off the measurement clock
        for host, port in group.endpoints():
            conn = _Connection(host, port)
            for _ in conn.stream({"op": "generate",
                                  "prompt": prompts[1][:4],
                                  "max_new_tokens": 2}):
                pass
            conn.close()

        threads = [threading.Thread(target=stream_worker, args=(i,))
                   for i in range(n_streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, (
            f"{len(errors)} failure(s):\n" + "\n".join(errors[:10]))

        ratios = []
        for host, port in group.endpoints():
            conn = _Connection(host, port)
            stats = conn.rpc({"op": "llm_stats"})["stats"]
            conn.close()
            compiles = stats.get("compiles", {})
            assert compiles.get("decode") == 1, (
                f"replica {host}:{port}: decode executable census "
                f"{compiles} (must be exactly 1)")
            assert compiles.get("prefill_chunk", 0) <= 1, compiles
            assert compiles.get("prefill", 0) == 0, (
                f"bucket prefill compiled under chunking: {compiles}")
            assert stats["blocks_used"] == 0, (
                f"replica {host}:{port} leaked {stats['blocks_used']} "
                "KV block(s)")
            assert stats.get("prefill_chunk") == 8, stats
            ratios.append(float(stats.get("overlap_ratio", 0.0)))
        # the overlapped pipeline must actually overlap: device-busy /
        # wall over the recent decode window, measured ON the replica
        assert max(ratios) >= OVERLAP_FLOOR, (
            f"overlap ratio {ratios} below the {OVERLAP_FLOOR} CPU "
            "floor — the tick pipeline is not overlapping")
    finally:
        client.close()
        group.stop()

    if verbose:
        print(f"LLM DECODE OK: {n_streams}/{n_streams} chunked-prefill "
              f"streams byte-identical to unchunked reference, "
              f"decode-compiles==1 on 2/2 replicas, 0 leaked KV "
              f"blocks, overlap ratio {max(ratios):.2f} "
              f">= {OVERLAP_FLOOR}")
    return 0


if __name__ == "__main__":
    sys.exit(check())
