#!/usr/bin/env python
"""Smoke-check the shard-exchange data plane end to end: a REAL
2-process exchange on CPU (a child process serves shards over TCP, this
process fetches), asserting that

* the pipelined+pooled multi-get beats the per-connection serial fetch
  on bytes/s for a 64-shard exchange,
* it dials at least 4x fewer TCP connections doing so,
* the same exchange fetched once over the TCP lane and once forcing the
  same-host shared-memory lane (``ZOO_SHARD_LANE=shm``) returns
  **byte-identical** shard contents — the default wire settings are
  lossless end to end, whatever the transport — and the shm lane
  leaves no segment files behind, and
* the pool/lane metrics (``zoo_shard_pool_connections_total``,
  ``zoo_shard_lane_total``, ``zoo_shard_fetch_bytes_total``) export on
  a live ``/metrics`` scrape.

Run directly (``python scripts/check_data_plane.py``) or from the test
suite (``tests/test_data_plane.py`` runs it under the ``perf`` marker) —
CI exercises the same wire an actual rebalance does. Deliberately
jax-free so a subprocess run costs milliseconds, not an XLA import.
"""

import glob
import os
import subprocess
import sys
import time
import urllib.request

# runnable from anywhere without an installed package: the repo root is
# this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_SHARDS = 64
ROWS, COLS = 128, 64  # 32 KB/shard: per-connection latency dominates,
# which is exactly the regime the pooled multi-get exists for


def _make_shards():
    import numpy as np
    rs = np.random.RandomState(0)
    return {i: {"x": rs.randn(ROWS, COLS).astype(np.float32)}
            for i in range(N_SHARDS)}


def serve() -> int:
    """Child mode: serve the deterministic shard set until stdin
    closes (the parent's exit tears us down)."""
    from zoo_tpu.orca.data.plane import ShardExchange
    ex = ShardExchange(_make_shards(), bind="127.0.0.1")
    print(f"PORT {ex.port}", flush=True)
    sys.stdin.read()  # EOF when the parent closes the pipe
    ex.close()
    return 0


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.obs import MetricsExporter
    from zoo_tpu.obs.metrics import get_registry
    from zoo_tpu.orca.data.plane import (
        ExchangeConfig,
        ShardExchange,
        _pool,
        iter_fetch,
    )
    from zoo_tpu.orca.data.shm import SEGMENT_PREFIX, shm_dir

    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    problems = []
    try:
        line = child.stdout.readline()
        if not line.startswith("PORT "):
            raise RuntimeError(f"server child failed to start: {line!r}")
        addr = ("127.0.0.1", int(line.split()[1]))
        expect = _make_shards()
        total = sum(v.nbytes for s in expect.values() for v in s.values())
        tcp = ExchangeConfig(lane="tcp")
        shm = ExchangeConfig(lane="shm")

        def counter_value(name, **want) -> float:
            fam = get_registry().counter(name,
                                         labels=tuple(sorted(want)))
            return sum(c.value for c in fam.children()
                       if all(dict(c.labels_kv).get(k) == v
                              for k, v in want.items()))

        def opened() -> float:
            return counter_value("zoo_shard_pool_connections_total",
                                 event="opened")

        # warm both paths once (page cache, import costs), then time
        ShardExchange.fetch(addr, 0, pool=False, config=tcp)
        list(iter_fetch([(addr, list(range(N_SHARDS)))], config=tcp))

        c0 = opened()
        t0 = time.perf_counter()
        got_serial = {g: ShardExchange.fetch(addr, g, pool=False,
                                             config=tcp)
                      for g in range(N_SHARDS)}
        serial_s = time.perf_counter() - t0
        conns_serial = opened() - c0

        c0 = opened()
        t0 = time.perf_counter()
        got_piped = dict(iter_fetch([(addr, list(range(N_SHARDS)))],
                                    config=tcp))
        piped_s = time.perf_counter() - t0
        # the pool was warmed above, so a steady-state exchange re-dials
        # nothing; count the warm-up's dials as the honest cold cost
        conns_piped = max(opened() - c0, 1.0)

        # ---- the shared-memory lane: same shards, forced shm payloads
        _pool.clear()  # fresh connection so the lane re-negotiates
        shm0 = counter_value("zoo_shard_lane_total", lane="shm")
        t0 = time.perf_counter()
        got_shm = dict(iter_fetch([(addr, list(range(N_SHARDS)))],
                                  config=shm))
        shm_s = time.perf_counter() - t0
        if counter_value("zoo_shard_lane_total", lane="shm") - shm0 \
                < N_SHARDS:
            problems.append("forced shm lane did not actually carry the "
                            "shards (lane metric unmoved)")

        for got, tag in ((got_serial, "serial"), (got_piped, "pipelined"),
                         (got_shm, "shm")):
            if sorted(got) != list(range(N_SHARDS)):
                problems.append(f"{tag} fetch returned wrong gid set")
                continue
            for g in (0, N_SHARDS // 2, N_SHARDS - 1):
                if not np.array_equal(np.asarray(got[g]["x"]),
                                      expect[g]["x"]):
                    problems.append(f"{tag} fetch corrupted shard {g}")
        # cross-lane bit-identity: the acceptance bar for "lossless by
        # default" — not allclose, BYTE-equal, across every shard
        for g in range(N_SHARDS):
            a = np.asarray(got_piped[g]["x"])
            b = np.asarray(got_shm[g]["x"])
            if a.dtype != b.dtype or a.shape != b.shape \
                    or a.tobytes() != b.tobytes():
                problems.append(
                    f"lane mismatch on shard {g}: tcp and shm lanes "
                    "disagree byte-for-byte")
                break
        leftovers = glob.glob(os.path.join(
            shm_dir(), f"{SEGMENT_PREFIX}p{child.pid}_*"))
        if leftovers:
            problems.append(f"shm lane leaked segments: {leftovers}")

        if piped_s >= serial_s:
            problems.append(
                f"pipelined multi-get ({total / piped_s / 1e6:.0f} MB/s) "
                f"did not beat serial per-connection fetch "
                f"({total / serial_s / 1e6:.0f} MB/s)")
        if conns_serial < 4 * conns_piped:
            problems.append(
                f"expected >=4x fewer connections: serial opened "
                f"{conns_serial:.0f}, pipelined {conns_piped:.0f}")

        exporter = MetricsExporter(registry=get_registry()).start()
        try:
            with urllib.request.urlopen(exporter.url + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
        finally:
            exporter.stop()
        for needle in ("zoo_shard_pool_connections_total",
                       "zoo_shard_fetch_bytes_total",
                       "zoo_shard_lane_total"):
            if needle not in text:
                problems.append(f"/metrics is missing {needle}")
        if 'event="reused"' not in text:
            problems.append("/metrics shows no pooled-connection reuse")
        if 'lane="shm"' not in text:
            problems.append("/metrics shows no shm-lane traffic")
    finally:
        child.stdin.close()
        child.wait(timeout=30)
        _pool.clear()

    if verbose:
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
        else:
            print(f"ok: pipelined tcp {total / piped_s / 1e6:.0f} MB/s "
                  f"over {conns_piped:.0f} conn(s), shm lane "
                  f"{total / shm_s / 1e6:.0f} MB/s (byte-identical "
                  f"across lanes), serial {total / serial_s / 1e6:.0f} "
                  f"MB/s over {conns_serial:.0f}; lane metrics live on "
                  f"/metrics")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(serve() if "--serve" in sys.argv else check())
