#!/usr/bin/env python
"""Smoke-check the shard-exchange data plane end to end: a REAL
2-process exchange on CPU (a child process serves shards over TCP, this
process fetches), asserting that

* the pipelined+pooled multi-get beats the per-connection serial fetch
  on bytes/s for a 64-shard exchange,
* it dials at least 4x fewer TCP connections doing so, and
* the pool-reuse metrics (``zoo_shard_pool_connections_total``,
  ``zoo_shard_fetch_bytes_total``) export on a live ``/metrics`` scrape.

Run directly (``python scripts/check_data_plane.py``) or from the test
suite (``tests/test_data_plane.py`` runs it under the ``perf`` marker) —
CI exercises the same wire an actual rebalance does. Deliberately
jax-free so a subprocess run costs milliseconds, not an XLA import.
"""

import os
import subprocess
import sys
import time
import urllib.request

# runnable from anywhere without an installed package: the repo root is
# this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_SHARDS = 64
ROWS, COLS = 128, 64  # 32 KB/shard: per-connection latency dominates,
# which is exactly the regime the pooled multi-get exists for


def _make_shards():
    import numpy as np
    rs = np.random.RandomState(0)
    return {i: {"x": rs.randn(ROWS, COLS).astype(np.float32)}
            for i in range(N_SHARDS)}


def serve() -> int:
    """Child mode: serve the deterministic shard set until stdin
    closes (the parent's exit tears us down)."""
    from zoo_tpu.orca.data.plane import ShardExchange
    ex = ShardExchange(_make_shards(), bind="127.0.0.1")
    print(f"PORT {ex.port}", flush=True)
    sys.stdin.read()  # EOF when the parent closes the pipe
    ex.close()
    return 0


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.obs import MetricsExporter
    from zoo_tpu.obs.metrics import get_registry
    from zoo_tpu.orca.data.plane import ShardExchange, _pool, iter_fetch

    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    problems = []
    try:
        line = child.stdout.readline()
        if not line.startswith("PORT "):
            raise RuntimeError(f"server child failed to start: {line!r}")
        addr = ("127.0.0.1", int(line.split()[1]))
        expect = _make_shards()
        total = sum(v.nbytes for s in expect.values() for v in s.values())

        def opened() -> float:
            fam = get_registry().counter(
                "zoo_shard_pool_connections_total", labels=("event",))
            return sum(c.value for c in fam.children()
                       if dict(c.labels_kv).get("event") == "opened")

        # warm both paths once (page cache, import costs), then time
        ShardExchange.fetch(addr, 0, pool=False)
        list(iter_fetch([(addr, list(range(N_SHARDS)))]))

        c0 = opened()
        t0 = time.perf_counter()
        got_serial = {g: ShardExchange.fetch(addr, g, pool=False)
                      for g in range(N_SHARDS)}
        serial_s = time.perf_counter() - t0
        conns_serial = opened() - c0

        c0 = opened()
        t0 = time.perf_counter()
        got_piped = dict(iter_fetch([(addr, list(range(N_SHARDS)))]))
        piped_s = time.perf_counter() - t0
        # the pool was warmed above, so a steady-state exchange re-dials
        # nothing; count the warm-up's dials as the honest cold cost
        conns_piped = max(opened() - c0, 1.0)

        for got, tag in ((got_serial, "serial"), (got_piped, "pipelined")):
            if sorted(got) != list(range(N_SHARDS)):
                problems.append(f"{tag} fetch returned wrong gid set")
                continue
            for g in (0, N_SHARDS // 2, N_SHARDS - 1):
                if not np.array_equal(np.asarray(got[g]["x"]),
                                      expect[g]["x"]):
                    problems.append(f"{tag} fetch corrupted shard {g}")
        if piped_s >= serial_s:
            problems.append(
                f"pipelined multi-get ({total / piped_s / 1e6:.0f} MB/s) "
                f"did not beat serial per-connection fetch "
                f"({total / serial_s / 1e6:.0f} MB/s)")
        if conns_serial < 4 * conns_piped:
            problems.append(
                f"expected >=4x fewer connections: serial opened "
                f"{conns_serial:.0f}, pipelined {conns_piped:.0f}")

        exporter = MetricsExporter(registry=get_registry()).start()
        try:
            with urllib.request.urlopen(exporter.url + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
        finally:
            exporter.stop()
        for needle in ("zoo_shard_pool_connections_total",
                       "zoo_shard_fetch_bytes_total"):
            if needle not in text:
                problems.append(f"/metrics is missing {needle}")
        if 'event="reused"' not in text:
            problems.append("/metrics shows no pooled-connection reuse")
    finally:
        child.stdin.close()
        child.wait(timeout=30)
        _pool.clear()

    if verbose:
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
        else:
            print(f"ok: pipelined {total / piped_s / 1e6:.0f} MB/s over "
                  f"{conns_piped:.0f} conn(s) vs serial "
                  f"{total / serial_s / 1e6:.0f} MB/s over "
                  f"{conns_serial:.0f}; pool metrics live on /metrics")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(serve() if "--serve" in sys.argv else check())
