#!/usr/bin/env python
"""Model-lifecycle chaos smoke END TO END on CPU
(docs/model_lifecycle.md): a REAL 3-replica :class:`ReplicaGroup`
serving from a versioned :class:`ModelRegistry` under sustained
verified client load, driven through the full zero-downtime lifecycle:

1. **publish v2 → shadow-eval → promote** — a canary replica serves the
   candidate, a :class:`PromotionGate` mirrors traffic to it and only
   then moves the ``prod`` alias;
2. **rolling hot-swap with a SIGKILL injected mid-update** — one
   replica is killed while ``rolling_update`` walks the group; the
   supervisor respawn re-resolves the alias and boots straight onto
   v2, and the update still completes with every replica on v2;
3. **bad-candidate auto-rollback** — a published-but-broken v3 is
   pushed at the group; warm-priming fails on the first replica, the
   whole group auto-rolls-back, and the alias is returned to v2.

Throughout all three phases the client load keeps flowing and EVERY
response must be the verified ``2x`` answer: zero client-visible
failures, full stop. Final state: zero mixed-version replicas, all
three reporting v2 on the wire AND on the obs ``/metrics``
``zoo_registry_version_info`` gauge.

Synthetic models keep the whole run jax-free so it fits tier-1 time.
Run directly (``python scripts/check_lifecycle.py``) or from the suite
(``tests/test_lifecycle.py`` runs it under the ``lifecycle`` marker).
"""

import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.orca.learn.continuous import PromotionGate
    from zoo_tpu.serving.ha import ReplicaGroup, RollingUpdateError
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.registry import ModelRegistry

    tmp = tempfile.mkdtemp(prefix="zoo-lifecycle-smoke-")
    reg = ModelRegistry(os.path.join(tmp, "registry"))
    v1 = reg.publish(spec="synthetic:double:2", alias="prod")
    group = ReplicaGroup(f"registry:{reg.root}:prod", num_replicas=3,
                         max_restarts=2, batch_size=8, max_wait_ms=2.0,
                         log_dir=os.path.join(tmp, "logs"))
    group.start(timeout=60)
    client = HAServingClient(group.endpoints(), deadline_ms=8000)

    errors, ok = [], [0]
    lock = threading.Lock()
    stop = threading.Event()

    def worker(cid):
        i = 0
        while not stop.is_set():
            i += 1
            x = np.full((1, 4), float(cid * 10000 + i), np.float32)
            try:
                out = np.asarray(client.predict(x))
                if out.shape != x.shape or not np.allclose(out, x * 2.0):
                    raise AssertionError(
                        f"wrong answer for {x[0, 0]}: {out!r}")
                with lock:
                    ok[0] += 1
            except Exception as e:  # noqa: BLE001 — every failure counts
                with lock:
                    errors.append(f"client {cid} req {i}: {e!r}")
            time.sleep(0.002)

    threads = [threading.Thread(target=worker, args=(c,), daemon=True)
               for c in range(4)]
    canary_group = None
    try:
        for t in threads:
            t.start()
        time.sleep(0.5)  # all replicas see live traffic (warm shapes)

        # -- phase 1: publish v2, shadow-eval on a canary replica, promote
        v2 = reg.publish(spec="synthetic:double:2", alias="canary")
        canary_group = ReplicaGroup(
            f"registry:{reg.root}:canary", num_replicas=1,
            max_restarts=1, batch_size=8, max_wait_ms=2.0)
        canary_group.start(timeout=60)
        canary_client = HAServingClient(canary_group.endpoints(),
                                        deadline_ms=8000)
        gate = PromotionGate(client.predict, canary_client.predict,
                             candidate=v2, registry=reg,
                             sample=1.0, window=24)
        rs = np.random.RandomState(7)

        def shadow_traffic():
            for _ in range(64):
                x = rs.randn(1, 4).astype(np.float32)
                yield x, x * 2.0

        verdict = gate.run(shadow_traffic())
        assert verdict.promoted, f"good canary rejected: {verdict}"
        assert reg.alias_version("prod") == v2, reg.aliases()
        canary_client.close()
        canary_group.stop()
        canary_group = None

        # -- phase 2: rolling hot-swap with a SIGKILL injected mid-update
        killed = threading.Event()

        def chaos_kill():
            time.sleep(0.15)  # land INSIDE the rolling walk
            killed.set()
            group.kill_replica(1)

        killer = threading.Thread(target=chaos_kill, daemon=True)
        killer.start()
        info = group.rolling_update(v2, settle=0.3)
        killer.join()
        assert killed.is_set(), "the chaos kill never fired"
        versions = [d and d.get("version")
                    for d in group.version_info(timeout=30)]
        assert versions == [v2] * 3, \
            f"mixed-version group after update: {versions}"

        # -- phase 3: broken v3 pushed at the group -> auto-rollback
        v3 = reg.publish(spec="synthetic:broken", alias="prod")
        rolled_back = False
        try:
            group.rolling_update(v3, settle=0.3)
        except RollingUpdateError:
            rolled_back = True
        assert rolled_back, "broken candidate was promoted!"
        versions = [d and d.get("version")
                    for d in group.version_info(timeout=30)]
        assert versions == [v2] * 3, \
            f"group not 100% on the incumbent after rollback: {versions}"
        assert reg.alias_version("prod") == v2, \
            f"prod alias not restored: {reg.aliases()}"

        time.sleep(0.3)  # a last verified-traffic window on v2
        stop.set()
        for t in threads:
            t.join(timeout=30)

        assert not errors, (
            f"{len(errors)} client-visible failure(s) across the "
            "lifecycle:\n" + "\n".join(errors[:10]))
        assert ok[0] > 100, f"too little verified traffic ({ok[0]})"

        # every replica advertises v2 on its /metrics door
        for mport in group.metrics_ports:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/metrics",
                    timeout=5) as resp:
                text = resp.read().decode()
            assert 'zoo_registry_version_info{version="v2"} 1' in text, \
                f"replica on :{mport} does not report v2:\n" + "\n".join(
                    ln for ln in text.splitlines()
                    if "version_info" in ln)
            assert 'zoo_registry_version_info{version="v3"} 1' \
                not in text
    finally:
        stop.set()
        if canary_group is not None:
            canary_group.stop()
        group.stop()

    if verbose:
        print(f"LIFECYCLE OK: {ok[0]} verified responses across "
              f"shadow-eval promotion ({v1}->{v2}), a rolling swap "
              f"with a mid-update SIGKILL ({group.restarts()} "
              f"respawn(s)), and a broken-candidate auto-rollback "
              f"({v3} rejected) — 0 client-visible failures, "
              f"0 mixed-version replicas")
    return 0


if __name__ == "__main__":
    sys.exit(check())
