#!/usr/bin/env python
"""Disaggregated-serving chaos smoke END TO END on CPU (jax-free).

A REAL 3-replica :class:`ReplicaGroup` split into roles — 1 prefill +
2 decode seats over the deterministic ``synthllm`` engine — under a
mixed storm of long prompts (routed through the two-leg ``kv_migrate``
KV handoff, docs/disaggregated_serving.md) and short prompts (plain
single-leg streams on the decode seats), then a SIGKILL of the prefill
replica **mid-handoff** (a chaos delay armed on the
``serving.kv_migrate.push`` seam holds the push open long enough to
die inside it).

The contract this smoke asserts:

1. every stream — long and short, before, during, and after the kill —
   is byte-identical to the fault-free single-replica ``reference()``:
   ZERO client-visible failures, no gap, duplicate, or garbage token;
2. handoffs actually happened: the decode seats adopted migrated KV
   blocks (``zoo_llm_kv_migrated_blocks_total`` > 0 on their /metrics,
   ``handoffs_in`` > 0 in their ``llm_stats``);
3. zero leaked KV blocks on every surviving seat once the storm
   drains (the killed seat respawns with a fresh, empty allocator);
4. the killed prefill replica respawned on its original port with its
   role preserved — 3/3 healthy, role topology re-learned.

Run directly (``python scripts/check_disagg.py``) or from the suite
(``tests/test_disagg.py`` runs it under the ``chaos`` marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEED = int(os.environ.get("ZOO_CHAOS_SEED", "20817") or 20817)
MODEL = "synthllm:slots=2,block=4,blocks=96,tables=8,max_prompt=24"
ROLES = ["prefill", "decode", "decode"]
STORM_S = 3.5          # phase-1 mixed storm horizon
LONG_PROMPT = 18       # >= migrate_min -> handoff path
SHORT_PROMPT = 3       # < migrate_min  -> plain decode-seat stream
MIGRATE_MIN = 16


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.synthetic import reference
    from zoo_tpu.serving.tcp_client import _Connection

    log_dir = tempfile.mkdtemp(prefix="zoo-disagg-chaos-")
    group = ReplicaGroup(MODEL, num_replicas=3, max_restarts=2,
                        log_dir=log_dir, roles=ROLES,
                        env={"ZOO_CHAOS_ALLOW": "1",
                             "ZOO_LLM_PREFIX_CACHE": "1"})
    group.start(timeout=60)
    # hedge OFF: this smoke measures the handoff + failover layer, not
    # the hedging layer on top of it
    cli = HAServingClient(group.endpoints(), deadline_ms=15000,
                          hedge=False, migrate_min_tokens=MIGRATE_MIN)

    def migrated_blocks(i):
        return sum(group._metrics_counter(
            i, "zoo_llm_kv_migrated_blocks_total").values())

    def llm_stats(port):
        conn = _Connection(group.host, port)
        try:
            return conn.rpc({"op": "llm_stats"})["stats"]
        finally:
            conn.close()

    errors, lock = [], threading.Lock()
    n_long, n_short = [0], [0]

    def run_stream(rs, n_prompt, counter):
        n = int(rs.randint(4, 9))
        prompt = [int(t) for t in rs.randint(0, 97, size=n_prompt)]
        seeded = bool(rs.randint(0, 2))
        kw = {"temperature": 0.9, "seed": 11} if seeded else {}
        toks = list(cli.generate(prompt, n, **kw))
        exp = reference(prompt, n, temp=0.9 if seeded else 0.0,
                        seed=11 if seeded else 0)
        if toks != exp:
            raise AssertionError(
                f"stream diverged from reference: {toks} != {exp}")
        with lock:
            counter[0] += 1

    def worker(cid, n_prompt, counter, stop_at):
        rs = np.random.RandomState(SEED + cid)
        while time.monotonic() < stop_at:
            try:
                run_stream(rs, n_prompt, counter)
            except Exception as e:  # noqa: BLE001 — every failure counts
                with lock:
                    errors.append(f"worker[{cid}]: {e!r}")

    try:
        # learn the role topology up front (the storm would learn it
        # passively too — this just makes the first long prompt a
        # handoff instead of a shed-and-retry)
        topo = cli.update_topology()
        assert sum(1 for s in topo.values()
                   if s and s.get("role") == "prefill") == 1, topo
        assert sum(1 for s in topo.values()
                   if s and s.get("role") == "decode") == 2, topo

        # -- phase 1: mixed storm over the split pool ------------------
        stop_at = time.monotonic() + STORM_S
        threads = [threading.Thread(
            target=worker, args=(c, LONG_PROMPT, n_long, stop_at))
            for c in range(2)]
        threads += [threading.Thread(
            target=worker, args=(10 + c, SHORT_PROMPT, n_short, stop_at))
            for c in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, (
            f"{len(errors)} client-visible failure(s):\n"
            + "\n".join(errors[:10]))
        assert n_long[0] >= 5 and n_short[0] >= 5, \
            f"storm too thin: {n_long[0]} long / {n_short[0]} short"
        migrated = migrated_blocks(1) + migrated_blocks(2)
        assert migrated > 0, \
            "decode seats never adopted a migrated KV block"
        assert sum(llm_stats(group.ports[i])["handoffs_in"]
                   for i in (1, 2)) > 0, "no handoff reached a decode seat"

        # -- phase 2: SIGKILL the prefill replica MID-handoff ----------
        # hold the push open on the kv_migrate seam, start a long
        # stream, and kill the prefill seat while it is inside the push
        group.chaos_rpc(0, "serving.kv_migrate.push", delay_ms=800.0)
        rs = np.random.RandomState(SEED + 99)
        kill_done = []

        def killer():
            time.sleep(0.3)
            group.kill_replica(0)
            kill_done.append(True)

        kt = threading.Thread(target=killer)
        kt.start()
        run_stream(rs, LONG_PROMPT, n_long)   # must still be byte-exact
        kt.join()
        assert kill_done, "kill thread never fired"

        # -- the group heals: respawn recorded, 3/3 healthy, role
        # preserved on the respawned seat (supervision is async — poll)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and group.restarts() < 1:
            time.sleep(0.2)
        assert group.restarts() >= 1, "no respawn recorded"
        healthy = 0
        while time.monotonic() < deadline:
            hz = group.healthz()
            healthy = sum(1 for h in hz if h and h.get("ok"))
            if healthy == 3:
                break
            time.sleep(0.3)
        assert healthy == 3, f"only {healthy}/3 replicas healthy"
        assert llm_stats(group.ports[0])["role"] == "prefill", \
            "respawned replica lost its prefill role"

        # post-heal: the handoff path works again end to end
        run_stream(rs, LONG_PROMPT, n_long)

        # -- zero leaked KV blocks on every seat -----------------------
        deadline = time.monotonic() + 10
        leaked = None
        while time.monotonic() < deadline:
            leaked = {i: llm_stats(p)["blocks_used"]
                      for i, p in enumerate(group.ports)}
            if not any(leaked.values()):
                break
            time.sleep(0.3)
        assert not any(leaked.values()), f"leaked KV blocks: {leaked}"
    finally:
        cli.close()
        group.stop()

    if verbose:
        print(f"DISAGG CHAOS OK: seed {SEED}, {n_long[0]} handoff-path "
              f"+ {n_short[0]} plain byte-exact streams, 0 failures, "
              f"{int(migrated)} KV block(s) migrated onto decode seats, "
              f"prefill seat SIGKILLed mid-push and respawned with its "
              f"role ({group.restarts()} respawn(s)), 0 leaked KV "
              "blocks")
    return 0


if __name__ == "__main__":
    sys.exit(check())
