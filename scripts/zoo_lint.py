#!/usr/bin/env python
"""zoo-lint — static contract checks over the tree.

The build-time teeth behind the platform's conventions
(docs/static_analysis.md): knob registration / parse-site discipline,
jax-free import purity, lock-guarded attribute discipline, and the
telemetry catalog. Compiled-HLO passes (donation, host-transfer,
sharding plans) live in :mod:`zoo_tpu.analysis.hlo` and piggyback on
executables the test suite already compiles — this CLI runs the
sub-second AST/doc passes.

    python scripts/zoo_lint.py                 # report findings
    python scripts/zoo_lint.py --strict        # exit 1 on any active
    python scripts/zoo_lint.py --json LINT.json
    python scripts/zoo_lint.py --fix-docs      # rewrite generated
                                               # knob tables in docs
    python scripts/zoo_lint.py --passes knobs,purity

The runner itself never imports jax (asserted at exit and by
tests/test_zoo_lint.py): every pass is AST/text analysis, which is
what keeps the whole suite under a second.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _git_rev(root: str) -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=5,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — stripped checkout
        return "unknown"


def fix_docs(ctx) -> int:
    """Rewrite every marked ``zoo-knob-table`` region from the knob
    registry; returns the number of pages changed."""
    from zoo_tpu.analysis.knob_pass import render_doc_with_tables
    from zoo_tpu.common import knobs

    changed = 0
    for doc_rel in knobs.TABLE_DOCS:
        path = os.path.join(ctx.root, doc_rel)
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        out = render_doc_with_tables(doc_rel, text)
        if out != text:
            with open(path, "w", encoding="utf-8") as f:
                f.write(out)
            changed += 1
            print(f"rewrote knob tables in {doc_rel}")
    return changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="scripts/zoo_lint.py")
    ap.add_argument("--root", default=_ROOT)
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on active findings or stale "
                         "allowlist entries")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the machine-readable findings report")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset (default: all AST "
                         "passes)")
    ap.add_argument("--fix-docs", action="store_true",
                    help="rewrite the generated knob tables from the "
                         "registry, then re-check")
    ap.add_argument("--allowlist", default=None,
                    help="override the allowlist path")
    ap.add_argument("-q", "--quiet", action="store_true")
    ns = ap.parse_args(argv)

    from zoo_tpu.analysis import (
        Context,
        apply_allowlist,
        findings_json,
        load_allowlist,
        run_passes,
    )

    ctx = Context(ns.root, allowlist_path=ns.allowlist)
    if ns.fix_docs:
        fix_docs(ctx)
        ctx = Context(ns.root, allowlist_path=ns.allowlist)

    names = ns.passes.split(",") if ns.passes else None
    findings = run_passes(ctx, names)
    entries = load_allowlist(ctx.allowlist_path)
    active, suppressed = apply_allowlist(findings, entries)
    stale = [e for e in entries if not e.used]

    if ns.json:
        meta = {"git_rev": _git_rev(ctx.root),
                "passes": names or "all"}
        with open(ns.json, "w", encoding="utf-8") as f:
            f.write(findings_json(active, suppressed, meta))

    if not ns.quiet:
        for f in active:
            print(f.format())
        if suppressed:
            print(f"({len(suppressed)} finding(s) allowlisted)")
        for e in stale:
            print(f"{ctx.allowlist_path}:{e.line}: stale allowlist "
                  f"entry matches nothing: {e.rule} {e.file} "
                  f"{e.detail}")
    verdict = "clean" if not active else f"{len(active)} finding(s)"
    if not ns.quiet:
        print(f"zoo-lint: {verdict}, {len(suppressed)} allowlisted, "
              f"{len(stale)} stale allowlist entr"
              f"{'y' if len(stale) == 1 else 'ies'}")

    # the purity contract applies to the linter itself
    assert "jax" not in sys.modules, \
        "zoo-lint imported jax — a lint-pass module lost its purity"

    if ns.strict and (active or stale):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
