#!/usr/bin/env python
"""Smoke-check the training guardian's escalation ladder END TO END —
NaN-inject → skip → rollback → finish — against a synthetic training
loop, deliberately **jax-free** (asserted!) so a subprocess run costs
milliseconds: the guard's host controller (window stats, streak
escalation, rollback budget, preemption state machine, quarantine
journal, obs counters) is pure Python by design; only the in-step fold
helpers touch jax, and the real-model path is covered by
``tests/test_guard.py``.

The simulated run:

1. trains fine for a few windows (loss decays),
2. a :class:`FaultInjector` site poisons a bounded run of steps → the
   per-step health check "skips" them (bad counter + streak, exactly the
   values the device counters would read back),
3. the streak crosses ``max_skips`` → the guard restores the last
   verified snapshot (stub save/restore over an in-memory dict) with LR
   backoff,
4. the fault schedule ends → training resumes from the snapshot and
   converges,
5. a second phase exercises the loss-SPIKE trigger, the rollback-budget
   exhaustion (→ ``TrainingDiverged``) and the preemption request
   (→ ``Preempted`` carrying exit code 75).

Run directly (``python scripts/check_guard.py``) or from the suite
(``tests/test_guard.py`` runs it under the ``guard`` marker).
"""

import json
import math
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check(verbose: bool = True) -> int:
    from zoo_tpu.obs.metrics import get_registry
    from zoo_tpu.orca.learn.guard import (
        PREEMPT_EXIT_CODE,
        GuardConfig,
        Preempted,
        TrainingDiverged,
        TrainingGuard,
    )
    from zoo_tpu.util.resilience import inject

    assert "jax" not in sys.modules, \
        "guard host controller must stay importable without jax"

    qdir = tempfile.mkdtemp(prefix="zoo-guard-smoke-")
    qpath = os.path.join(qdir, "quarantine.jsonl")

    # -- a stub "trainable" + checkpoint store ----------------------------
    snapshots = {}

    class Sim:
        """loss = w decays 10%/good step; a poisoned step yields NaN."""

        def __init__(self):
            self.w = 1.0
            self.step = 0
            self.streak = 0
            self.bad = 0

        def train_window(self, k):
            """k steps; returns (window_loss_sum, steps) as fit would."""
            total = 0.0
            for _ in range(k):
                self.step += 1
                loss = self.w
                try:
                    from zoo_tpu.util.resilience import fault_point
                    fault_point("guard.smoke.batch", step=self.step)
                except _Poison:
                    loss = float("nan")
                if math.isnan(loss):
                    # what the jitted fold does: skip the update, count
                    self.bad += 1
                    self.streak += 1
                    continue
                self.streak = 0
                self.w *= 0.9
                total += loss
            return total, k

    class _Poison(RuntimeError):
        pass

    sim = Sim()

    def save():
        snapshots["s"] = {"params": sim.w, "epoch": sim.step}

    def restore():
        sim.w = snapshots["s"]["params"]
        return snapshots["s"], None

    cfg = GuardConfig(enabled=True, max_skips=4, rollback_budget=2,
                      spike_factor=5.0, min_window=3, window=16)
    guard = TrainingGuard(config=cfg, save_fn=save, restore_fn=restore,
                          quarantine_path=qpath, name="smoke")
    guard.begin_fit()
    save()  # the verified starting snapshot

    # -- phase 1: clean -> NaN window -> skip -> rollback -> finish -------
    rolled = False
    with inject("guard.smoke.batch", exc=_Poison("poison"), times=6):
        for window in range(12):
            wl, ws = sim.train_window(4)
            act = guard.on_boundary(
                bad_total=sim.bad, streak=sim.streak, window_loss=wl,
                window_steps=ws, global_step=sim.step, epoch=0,
                batch_hint=(window * 4, window * 4 + 3))
            if act == "rollback":
                state, _aux, lr_scale = guard.rollback()
                # the fit loop re-inits the device counters on rollback
                sim.streak = 0
                sim.bad = 0
                rolled = True
                assert lr_scale == cfg.lr_backoff
            elif act is None and sim.step % 8 == 0:
                save()  # periodic verified snapshot

    assert rolled, "streak of skipped steps must trigger a rollback"
    assert guard.rollbacks == 1
    # nonfinite_steps is CUMULATIVE: 4 pre-rollback + the fault
    # schedule's 2-injection tail after it; training still converges
    # once the schedule runs dry
    assert guard.nonfinite_steps == 6, guard.nonfinite_steps
    assert sim.w < 0.5, \
        f"post-rollback training must converge (w={sim.w})"

    # -- phase 2: spike trigger + budget exhaustion -----------------------
    for _ in range(4):  # refill the rolling window with sane losses
        guard.on_boundary(bad_total=0, streak=0, window_loss=0.4,
                          window_steps=4, global_step=sim.step)
    act = guard.on_boundary(bad_total=0, streak=0,
                            window_loss=0.4 * 4 * 100,  # 100x spike
                            window_steps=4, global_step=sim.step)
    assert act == "rollback", f"spike must trigger rollback, got {act!r}"
    guard.rollback()  # burns the budget (2/2)
    try:
        guard.rollback()
        raise AssertionError("budget exhaustion must raise")
    except TrainingDiverged:
        pass

    # -- phase 3: preemption ----------------------------------------------
    g2 = TrainingGuard(config=cfg, save_fn=save, quarantine_path=qpath,
                       name="smoke-preempt")
    g2.begin_fit()
    g2.request_preempt()
    act = g2.on_boundary(bad_total=0, streak=0, window_loss=0.1,
                         window_steps=4, global_step=sim.step)
    assert act == "preempt"
    try:
        g2.preempt_checkpoint(step=sim.step)
        raise AssertionError("preempt_checkpoint must raise Preempted")
    except Preempted as e:
        assert e.code == PREEMPT_EXIT_CODE == 75
    assert g2.preempt_checkpoints == 1
    assert snapshots["s"]["epoch"] == sim.step

    # -- forensics + metrics ----------------------------------------------
    events = [json.loads(line) for line in open(qpath)]
    kinds = [e["event"] for e in events]
    assert "nonfinite_steps" in kinds and "rollback" in kinds \
        and "diverged" in kinds and "preempt_checkpoint" in kinds, kinds
    quarantined = next(e for e in events
                       if e["event"] == "nonfinite_steps")
    assert quarantined["batch_lo"] is not None \
        and quarantined["bad_in_window"] > 0, quarantined
    snap = get_registry().snapshot()

    def metric(name):
        return sum(c["value"] for c in snap["counters"]
                   if c["name"] == name)

    assert metric("zoo_guard_nonfinite_steps_total") >= 6
    assert metric("zoo_guard_rollbacks_total") >= 2
    assert metric("zoo_guard_preempt_checkpoints_total") >= 1
    assert "jax" not in sys.modules, "smoke stayed jax-free end to end"
    if verbose:
        print(f"nonfinite={metric('zoo_guard_nonfinite_steps_total')} "
              f"rollbacks={metric('zoo_guard_rollbacks_total')} "
              f"preempt_ckpts="
              f"{metric('zoo_guard_preempt_checkpoints_total')} "
              f"journal_events={len(events)}")
        print("GUARD OK (jax-free): NaN-inject -> skip -> rollback -> "
              "finish; spike + budget + preempt verified")
    return 0


if __name__ == "__main__":
    sys.exit(check())
