#!/usr/bin/env python
"""Serving-HA chaos smoke END TO END on CPU: a REAL 3-replica
:class:`ReplicaGroup` (separate supervised processes) under sustained
client load, one replica SIGKILLed mid-run — and the
:class:`HAServingClient` contract holds: ZERO client-visible failures
beyond the hedging/retry budget (here: zero, full stop — every request
must return the verified ``2x`` answer inside its deadline), the dead
replica is respawned on its original port, and all three seats probe
healthy again on the obs ``/healthz`` door.

Synthetic replicas keep the whole run jax-free, so the three replica
boots cost milliseconds and the smoke fits tier-1 time. Run directly
(``python scripts/check_serving_ha.py``) or from the suite
(``tests/test_serving_ha.py`` runs it under the ``chaos`` marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient

    log_dir = tempfile.mkdtemp(prefix="zoo-serving-ha-smoke-")
    group = ReplicaGroup("synthetic:double:5", num_replicas=3,
                         max_restarts=2, batch_size=8, max_wait_ms=2.0,
                         log_dir=log_dir)
    group.start(timeout=60)
    client = HAServingClient(group.endpoints(), deadline_ms=8000)

    n_clients, per_client = 4, 40
    errors, ok = [], [0]
    lock = threading.Lock()
    killed = threading.Event()

    def worker(cid):
        for i in range(per_client):
            x = np.full((1, 4), float(cid * 1000 + i), np.float32)
            try:
                out = np.asarray(client.predict(x))
                if out.shape != x.shape or not np.allclose(out, x * 2.0):
                    raise AssertionError(
                        f"wrong answer for {x[0, 0]}: {out!r}")
                with lock:
                    ok[0] += 1
            except Exception as e:  # noqa: BLE001 — every failure counts
                with lock:
                    errors.append(f"client {cid} req {i}: {e!r}")
            # the SIGKILL lands while load is flowing, from inside the
            # traffic so it cannot race past the end of the run
            if not killed.is_set() and cid == 0 and i == per_client // 4:
                killed.set()
                group.kill_replica(1)

    try:
        threads = [threading.Thread(target=worker, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        assert killed.is_set(), "the chaos kill never fired"
        assert not errors, (
            f"{len(errors)} client-visible failure(s) past the "
            f"hedge/retry budget:\n" + "\n".join(errors[:10]))
        assert ok[0] == n_clients * per_client, ok

        # the supervisor must respawn the dead seat on its old port and
        # the whole group must probe healthy again
        deadline = time.monotonic() + 30
        healthy = 0
        while time.monotonic() < deadline:
            hz = group.healthz()
            healthy = sum(1 for h in hz if h is not None and h.get("ok"))
            if healthy == 3:
                break
            time.sleep(0.3)
        assert healthy == 3, f"only {healthy}/3 replicas healthy"
        assert group.restarts() >= 1, "no respawn recorded"
    finally:
        group.stop()

    if verbose:
        print(f"SERVING HA OK: {ok[0]}/{n_clients * per_client} verified "
              f"responses across a replica SIGKILL, 0 client-visible "
              f"failures, {group.restarts()} respawn(s), 3/3 healthy")
    return 0


if __name__ == "__main__":
    sys.exit(check())
