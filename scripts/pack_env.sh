#!/usr/bin/env bash
# Bundle the zoo_tpu repo + python environment for air-gapped pod hosts.
#
# Rebuild of the reference's conda-pack deployment story
# (docs "Python User Guide": conda-pack the driver env, ship the tarball
# to YARN executors via --archives). On TPU pods the equivalent need is
# hosts without network egress: this script produces ONE tarball holding
#   - the zoo_tpu repo (the package is run from source, PYTHONPATH-based)
#   - the environment, packed the best way available:
#       conda-pack / venv-pack  -> bundle/env.tgz (relocatable env)
#       fallback                -> bundle/requirements.lock (pip freeze);
#                                  PACK_FULL_ENV=1 additionally copies
#                                  the live venv verbatim (relocatable
#                                  only to the same absolute prefix; the
#                                  docker image in docker/ is the
#                                  supported route when neither packer
#                                  exists)
#
# Usage:
#   scripts/pack_env.sh [out.tgz]        # default: zoo_tpu_bundle.tgz
#   PACK_FULL_ENV=1 scripts/pack_env.sh  # force the verbatim env copy
#
# Unpack on each worker:
#   tar -xzf zoo_tpu_bundle.tgz && cd bundle
#   if [ -f env.tgz ]; then mkdir -p env && tar -xzf env.tgz -C env \
#       && source env/bin/activate && conda-unpack 2>/dev/null || true; \
#   elif [ -d env ]; then source env/bin/activate; \
#   else pip install -r requirements.lock; fi
#   PYTHONPATH=$PWD/repo python repo/examples/ncf_movielens.py
set -euo pipefail
OUT=${1:-zoo_tpu_bundle.tgz}
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
# python3-only hosts (stock TPU VMs) have no bare `python`
PY=${PYTHON:-$(command -v python3 || command -v python)}
STAGE=$(mktemp -d)
trap 'rm -rf "$STAGE"' EXIT
mkdir -p "$STAGE/bundle"

# 1. the repo (source tree minus caches/VCS/envs/previous bundles)
mkdir -p "$STAGE/bundle/repo"
tar -C "$REPO_DIR" --exclude='.git' --exclude='__pycache__' \
    --exclude='*.pyc' --exclude='build' --exclude='*.tgz' \
    --exclude='*.tar.gz' --exclude='.venv' --exclude='venv' \
    --exclude='.pytest_cache' --exclude='*.egg-info' -cf - . \
    | tar -C "$STAGE/bundle/repo" -xf -

# 2. the environment
if "$PY" -c "import conda_pack" 2>/dev/null; then
    "$PY" -m conda_pack -o "$STAGE/bundle/env.tgz"
    echo "env packed with conda-pack -> bundle/env.tgz"
elif "$PY" -c "import venv_pack" 2>/dev/null; then
    "$PY" -m venv_pack -o "$STAGE/bundle/env.tgz"
    echo "env packed with venv-pack -> bundle/env.tgz"
else
    "$PY" -m pip freeze --all > "$STAGE/bundle/requirements.lock" \
        2>/dev/null || \
        "$PY" -m pip freeze > "$STAGE/bundle/requirements.lock"
    echo "no conda-pack/venv-pack in this env: wrote requirements.lock"
    if [[ "${PACK_FULL_ENV:-0}" == "1" && -n "${VIRTUAL_ENV:-}" ]]; then
        echo "PACK_FULL_ENV=1: copying $VIRTUAL_ENV verbatim (works only"
        echo "at the same absolute prefix on the workers)"
        mkdir -p "$STAGE/bundle/env"
        tar -C "$VIRTUAL_ENV" --exclude='__pycache__' -cf - . \
            | tar -C "$STAGE/bundle/env" -xf -
    else
        echo "workers will need: pip install -r requirements.lock"
        echo "(or use the docker image in docker/ — the supported route)"
    fi
fi

tar -C "$STAGE" -czf "$OUT" bundle
echo "wrote $OUT ($(du -h "$OUT" | cut -f1))"
