#!/usr/bin/env bash
# Hermetic test run on the virtual CPU mesh (the reference's
# pyzoo/dev/run-pytests role).
#
# Two tiers (reference: pyzoo/dev splits run-pytests / run-pytests-ray /
# ...-horovod by runtime weight):
#   scripts/run_tests.sh          fast tier (default pytest selection,
#                                 `-m "not slow"`, < ~10 min)
#   scripts/run_tests.sh --all    full matrix incl. the subprocess-heavy
#                                 slow tier (bootstrap supervision,
#                                 multi-process clusters, example scripts)
set -euo pipefail
cd "$(dirname "$0")/.."
# compile-bound JAX tests parallelize well across cores; a 1-core box
# (this dev image) runs serially — the README records both timings
XDIST=()
if [[ "$(nproc)" -gt 1 ]] && python -c "import xdist" 2>/dev/null; then
    XDIST=(-n auto)
fi
if [[ "${1:-}" == "--all" ]]; then
    shift
    exec python -m pytest tests/ -q -m "" "${XDIST[@]}" "$@"
fi
exec python -m pytest tests/ -q "${XDIST[@]}" "$@"
