#!/usr/bin/env bash
# Hermetic test run on the virtual CPU mesh (the reference's
# pyzoo/dev/run-pytests role).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m pytest tests/ -q "$@"
