#!/usr/bin/env bash
# Hermetic test run on the virtual CPU mesh (the reference's
# pyzoo/dev/run-pytests role).
#
# Two tiers (reference: pyzoo/dev splits run-pytests / run-pytests-ray /
# ...-horovod by runtime weight):
#   scripts/run_tests.sh          fast tier (default pytest selection,
#                                 `-m "not slow"`, < ~10 min)
#   scripts/run_tests.sh --all    full matrix incl. the subprocess-heavy
#                                 slow tier (bootstrap supervision,
#                                 multi-process clusters, example scripts)
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ "${1:-}" == "--all" ]]; then
    shift
    exec python -m pytest tests/ -q -m "" "$@"
fi
exec python -m pytest tests/ -q "$@"
