#!/usr/bin/env bash
# Hermetic test run on the virtual CPU mesh (the reference's
# pyzoo/dev/run-pytests role).
#
# Two tiers (reference: pyzoo/dev splits run-pytests / run-pytests-ray /
# ...-horovod by runtime weight):
#   scripts/run_tests.sh          fast tier (default pytest selection,
#                                 `-m "not slow and not heavy"`) —
#                                 measured 476s on the 1-core dev image
#                                 (round 5), inside the ~10 min budget
#   scripts/run_tests.sh --all    full matrix: + the `heavy` tier
#                                 (compile-bound stragglers, >10s each;
#                                 the `not slow` matrix measured 1152s)
#                                 and the subprocess-heavy `slow` tier
#                                 (bootstrap supervision, multi-process
#                                 clusters, example scripts)
set -euo pipefail
cd "$(dirname "$0")/.."
# compile-bound JAX tests parallelize well across cores; a 1-core box
# (this dev image) runs serially — the README records both timings
XDIST=()
if [[ "$(nproc)" -gt 1 ]] && python -c "import xdist" 2>/dev/null; then
    XDIST=(-n auto)
fi
if [[ "${1:-}" == "--all" ]]; then
    shift
    exec python -m pytest tests/ -q -m "" "${XDIST[@]}" "$@"
fi
exec python -m pytest tests/ -q "${XDIST[@]}" "$@"
