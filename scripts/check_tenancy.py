#!/usr/bin/env python
"""Multi-tenant QoS chaos smoke END TO END on CPU (jax-free).

A REAL 3-replica mixed-role :class:`ReplicaGroup` over the
deterministic ``synthllm`` engine, serving an **adversarial mix**: a
greedy tenant flooding unpaced from several threads against a paced,
higher-class victim tenant — then a SIGKILL of one replica mid-storm
(docs/multitenancy.md).

The contract this smoke asserts:

1. every VICTIM stream — before, during, and after both the flood and
   the kill — is byte-identical to the fault-free single-replica
   ``reference()``: ZERO client-visible victim failures;
2. the victim was never shed: ``zoo_tenant_shed_total`` for the victim
   is 0 on every surviving seat (its rate is unlimited and its class
   outranks the flood — overload lands on the flooder, not on it);
3. the greedy tenant was visibly throttled: rate sheds recorded on its
   label, and the client-side paced its retries on the per-tenant
   backoff instead of erroring the storm out;
4. tenant KV isolation held: ZERO cross-tenant prefix-cache evictions
   (``zoo_tenant_kv_cross_evictions_total``) — the flood churned its
   own partition, never the victim's hot prefixes;
5. the killed replica respawned on its original port — 3/3 healthy.

Run directly (``python scripts/check_tenancy.py``) or from the suite
(``tests/test_tenancy.py`` runs it under the ``chaos`` marker).
"""

import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEED = int(os.environ.get("ZOO_CHAOS_SEED", "20820") or 20820)
MODEL = "synthllm:slots=2,block=4,blocks=96,tables=8,max_prompt=24"
# greedy: rate-limited best-effort with slot+KV quotas; victim: paid
# class, unlimited rate, 4x weight — the isolation the smoke verifies
TENANT_CONFIG = ("victim:class=0,weight=4,rate=0;"
                 "greedy:class=1,weight=1,rate=6,burst=6,slots=1,kv=32")
# shared prefix, cache-hot; NOT block-aligned (13 tokens, block=4) so
# the repeat hit recomputes inside the partial tail block instead of
# needing a CoW fork (synthllm has no copy_block)
VICTIM_PROMPT = list(range(1, 14))


def check(duration: float = 8.0, verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.synthetic import reference

    log_dir = tempfile.mkdtemp(prefix="zoo-tenancy-chaos-")
    group = ReplicaGroup(MODEL, num_replicas=3, max_restarts=2,
                         log_dir=log_dir,
                         env={"ZOO_TENANT_CONFIG": TENANT_CONFIG,
                              "ZOO_LLM_PREFIX_CACHE": "1"})
    group.start(timeout=60)
    cli = HAServingClient(group.endpoints(), deadline_ms=15000,
                          hedge=False)

    def tenant_counter(name, tenant):
        total = 0.0
        for i in range(3):
            for sig, v in group._metrics_counter(i, name).items():
                if f'tenant="{tenant}"' in sig:
                    total += v
        return total

    lock = threading.Lock()
    victim_errors, victim_ok = [], [0]
    greedy_throttled, greedy_ok, greedy_errors = [0], [0], []

    def one_stream(rs, prompt, tenant):
        n = int(rs.randint(4, 9))
        toks = list(cli.generate(prompt, n, tenant=tenant))
        exp = reference(prompt, n)
        if toks != exp:
            raise AssertionError(
                f"stream diverged from reference: {toks} != {exp}")

    def victim_worker(cid, stop_at):
        rs = np.random.RandomState(SEED + cid)
        while time.monotonic() < stop_at:
            try:
                one_stream(rs, VICTIM_PROMPT, "victim")
                with lock:
                    victim_ok[0] += 1
            except Exception as e:  # noqa: BLE001 — every failure counts
                with lock:
                    victim_errors.append(f"victim[{cid}]: {e!r}")
            time.sleep(0.1)        # paced, well within any budget

    def greedy_worker(cid, stop_at):
        from zoo_tpu.serving.ha_client import NoReplicaAvailable
        rs = np.random.RandomState(SEED + 100 + cid)
        while time.monotonic() < stop_at:
            prompt = [int(t) for t in rs.randint(0, 97, size=6)]
            try:
                one_stream(rs, prompt, "greedy")
                with lock:
                    greedy_ok[0] += 1
            except NoReplicaAvailable:
                # rate-shed fleet-wide: the throttle working as built
                with lock:
                    greedy_throttled[0] += 1
            except Exception as e:  # noqa: BLE001
                with lock:
                    greedy_errors.append(f"greedy[{cid}]: {e!r}")

    try:
        stop_at = time.monotonic() + duration
        threads = [threading.Thread(target=victim_worker,
                                    args=(c, stop_at))
                   for c in range(2)]
        threads += [threading.Thread(target=greedy_worker,
                                     args=(c, stop_at))
                    for c in range(3)]
        for t in threads:
            t.start()

        # -- mid-storm SIGKILL of one (mixed-role) replica -------------
        time.sleep(duration * 0.4)
        group.kill_replica(1)
        for t in threads:
            t.join()

        # 1-2. victims byte-identical, never failed, never shed
        assert not victim_errors, (
            f"{len(victim_errors)} victim failure(s):\n"
            + "\n".join(victim_errors[:10]))
        assert victim_ok[0] >= 10, \
            f"victim traffic too thin: {victim_ok[0]} streams"
        victim_sheds = tenant_counter("zoo_tenant_shed_total", "victim")
        assert victim_sheds == 0, \
            f"victim was shed {int(victim_sheds)} time(s)"

        # 3. the flood was real and the throttle bit it
        greedy_sheds = tenant_counter("zoo_tenant_shed_total", "greedy")
        assert greedy_sheds > 0, "greedy tenant was never throttled"
        assert greedy_ok[0] > 0, "no greedy stream ever admitted"
        assert not greedy_errors, (
            f"{len(greedy_errors)} non-shed greedy failure(s):\n"
            + "\n".join(greedy_errors[:10]))

        # 4. KV isolation: zero cross-tenant prefix-cache evictions
        cross = tenant_counter("zoo_tenant_kv_cross_evictions_total",
                               "greedy")
        assert cross == 0, \
            f"{int(cross)} cross-tenant KV eviction(s) by the flood"

        # 5. the killed seat respawned: 3/3 healthy again
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and group.restarts() < 1:
            time.sleep(0.2)
        assert group.restarts() >= 1, "no respawn recorded"
        healthy = 0
        while time.monotonic() < deadline:
            hz = group.healthz()
            healthy = sum(1 for h in hz if h and h.get("ok"))
            if healthy == 3:
                break
            time.sleep(0.3)
        assert healthy == 3, f"only {healthy}/3 replicas healthy"

        # post-heal, post-flood: the victim still byte-exact
        one_stream(np.random.RandomState(SEED + 999),
                   VICTIM_PROMPT, "victim")
    finally:
        cli.close()
        group.stop()

    if verbose:
        print(f"TENANCY OK: seed {SEED}, {victim_ok[0]} byte-exact "
              f"victim streams with 0 failures and 0 sheds through a "
              f"greedy flood ({greedy_ok[0]} admitted / "
              f"{int(greedy_sheds)} rate-shed / {greedy_throttled[0]} "
              f"client-throttled) + a mid-storm SIGKILL "
              f"({group.restarts()} respawn(s)), 0 cross-tenant KV "
              "evictions")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=8.0,
                    help="storm horizon in seconds")
    args = ap.parse_args()
    sys.exit(check(duration=args.duration))
