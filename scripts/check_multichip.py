#!/usr/bin/env python
"""Multichip smoke: REAL sharded numbers on the 8-device CPU sim.

What MULTICHIP_r0*.json scores (previously just ``dryrun_multichip
ok``): the three acceptance properties of GSPMD sharded training &
serving, measured, not dry-run —

* **sharded fit == single-device fit**: the same model/seed/data trained
  on a ``data x fsdp`` mesh produces the same loss curve as one device
  (tolerance 1e-5; on XLA CPU it is bit-exact), with params/opt-state
  ACTUALLY sharded — per-device param bytes ~ 1/n_devices — and the
  compiled step passing the HLO lint (weight all-gather + grad
  reduction present, no full-parameter all-gather into a replicated
  output, ``zoo_tpu.parallel.hlo_check``);
* **resharding-on-restore**: a checkpoint saved from the 8-device mesh
  restores onto a 4-device mesh and a single device bit-exactly
  (``CheckpointManager.restore(sharding=mesh)`` — the ``run_elastic``
  re-mesh path);
* **sharded paged decode == unsharded decode**: ``llama:...:tp=2``
  spans one set of weights + one paged KV cache over 2 devices and
  streams token-identical output to the single-device engine, with
  ``decode compiles == 1`` and zero leaked KV blocks.

Run directly (``python scripts/check_multichip.py`` — self-provisions
the 8-device virtual CPU platform in a child process) or from the test
suite (``tests/test_multichip.py`` runs it under the ``multichip``
marker). ``__graft_entry__.dryrun_multichip`` prints the same metrics
line, so the driver's MULTICHIP tail carries real numbers.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_DEVICES = 8
LOSS_TOL = 1e-5


def _fit_losses(mesh_axes, devices, batch_size=32, seed=0, plan=None,
                body_layers=0):
    """Train the probe model under a fresh orca context; returns
    (losses, model, placed-params, step-HLO). ``body_layers`` inserts a
    homogeneous Dense run (the pipeline plan's stackable body);
    ``plan`` is forwarded to ``compile``."""
    import numpy as np

    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    rs = np.random.RandomState(seed)
    x = rs.randn(4 * batch_size, 8).astype(np.float32)
    y = (x @ rs.randn(8, 1).astype(np.float32))
    init_orca_context(cluster_mode="local", devices=devices,
                      mesh_axes=mesh_axes)
    try:
        m = Sequential()
        m.add(Dense(16, activation="relu", input_shape=(8,)))
        for _ in range(body_layers):
            m.add(Dense(16, activation="relu"))
        m.add(Dense(1))
        m.compile(optimizer=Adam(lr=0.01), loss="mse", plan=plan)
        losses = m.fit(x, y, batch_size=batch_size, nb_epoch=3,
                       verbose=0)["loss"]
        hlo = m.lower_train_hlo(x, y, batch_size=batch_size)
        placed = m._place(m.params)
        return losses, m, placed, hlo
    finally:
        stop_orca_context()


def _tree_bytes_frac(placed):
    import jax
    import numpy as np
    local = total = 0
    for leaf in jax.tree_util.tree_leaves(placed):
        total += np.asarray(leaf).nbytes
        local += leaf.addressable_shards[0].data.nbytes \
            if hasattr(leaf, "addressable_shards") else np.asarray(
                leaf).nbytes
    return local / max(total, 1)


def _bit_exact(a, b) -> bool:
    import jax
    import numpy as np
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y),  # NaN-safe
                       equal_nan=True)
        for x, y in zip(la, lb)
        if hasattr(x, "ndim") or hasattr(y, "ndim"))


def collect_metrics(n_devices: int = N_DEVICES, verbose: bool = True
                    ) -> dict:
    """The measured multichip properties; raises on any violation."""
    import numpy as np

    import jax

    from zoo_tpu.parallel import build_mesh
    from zoo_tpu.analysis.hlo import (
        assert_collectives,
        assert_fsdp_sharded,
        assert_llm_executable,
        assert_pipeline_sharded,
        assert_plan_sharded,
    )
    from zoo_tpu.parallel.plans import plan_lint_shapes

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, (
        f"need {n_devices} devices, have {len(jax.devices())}")
    m = {"n_devices": n_devices}
    # the same provenance stamp BENCH_* lines carry (bench._bench_meta:
    # git rev + PR), so the MULTICHIP_r0*.json trajectory is
    # attributable to the code state that produced it
    try:
        from bench import _bench_meta
        m["bench_meta"] = _bench_meta()
    except Exception:  # noqa: BLE001 — a stripped deploy image may
        # ship without bench.py; the smoke still scores
        m["bench_meta"] = {"git_rev": "unknown", "pr": None}

    # 1. sharded fit matches the single-device loss curve ----------------
    # full-width ZeRO: params sharded n_devices ways (the batch rides
    # the fsdp axis too — data_axes() treats them as one data group).
    # batch_size must divide by the data shards; 32 covers the 8-device
    # harness, other world sizes scale it
    bs = 32 if 32 % n_devices == 0 else 4 * n_devices
    ref, _, _, _ = _fit_losses(None, devices[:1], batch_size=bs)
    shd, model, placed, hlo = _fit_losses(
        {"fsdp": n_devices}, devices, batch_size=bs)
    diff = max(abs(a - b) for a, b in zip(ref, shd))
    m["fsdp_loss_max_abs_diff"] = diff
    assert diff <= LOSS_TOL, (
        f"sharded loss curve diverged from single-device by {diff} "
        f"(> {LOSS_TOL}): {shd} vs {ref}")
    frac = _tree_bytes_frac(placed)
    m["fsdp_param_bytes_frac"] = round(frac, 4)
    # ~1/n of the replicated bytes per device (small biases stay
    # replicated, hence the slack)
    assert frac <= 1.0 / n_devices + 0.05, (
        f"per-device param bytes {frac:.3f} of replicated — params are "
        "not actually ZeRO-sharded")

    # 2. the compiled step really is FSDP (HLO lint) ---------------------
    mesh = build_mesh(devices, axis_sizes={"fsdp": n_devices})
    sharded_shapes, replicated_shapes, local_shapes = plan_lint_shapes(
        model.params, mesh)
    counts = assert_collectives(
        hlo, require=["all-gather"],
        require_any=["reduce-scatter", "all-to-all", "all-reduce"],
        label="fsdp train step")
    assert_fsdp_sharded(hlo, sharded_shapes, replicated_shapes,
                        local_shapes=local_shapes,
                        label="fsdp train step")
    m["fsdp_collectives"] = counts
    m["hlo_lint"] = "pass"

    # 3. resharding-on-restore: save@8 -> restore@4 -> restore@1 --------
    import tempfile

    from zoo_tpu.orca.learn.ckpt import CheckpointManager
    with tempfile.TemporaryDirectory() as td:
        cm = CheckpointManager(td)
        state = {"params": model.params, "epoch": 3}
        cm.save(3, state)
        host = cm.restore(3)  # world-size-free host bytes
        half = max(2, n_devices // 2)
        mesh4 = build_mesh(devices[:half],
                           axis_sizes={"data": half // 2, "fsdp": 2})
        at4 = cm.restore(3, sharding=mesh4)
        mesh1 = build_mesh(devices[:1], axis_sizes={"data": 1})
        at1 = cm.restore(3, sharding=mesh1)
        ok4 = _bit_exact(host["params"], at4["params"])
        ok1 = _bit_exact(host["params"], at1["params"])
        m["reshard_save8_restore4_bitexact"] = ok4
        m["reshard_restore1_bitexact"] = ok1
        assert ok4 and ok1, "resharded restore is not bit-exact"
        frac4 = _tree_bytes_frac(at4["params"])
        m["reshard_restore4_param_bytes_frac"] = round(frac4, 4)

    # 4. sharded paged decode == unsharded reference ---------------------
    from zoo_tpu.serving.llm.spec import build_llm_engine
    ref_eng = build_llm_engine("llama:tiny:slots=2,blocks=32",
                               start=True)
    tp_eng = build_llm_engine("llama:tiny:slots=2,blocks=32,tp=2",
                              start=True)
    try:
        rs = np.random.RandomState(0)
        prompts = [rs.randint(0, 256, n).tolist() for n in (5, 23)]
        outs = []
        for eng in (ref_eng, tp_eng):
            toks = []
            hs = [eng.submit(p, 12, rid=f"r{i}")
                  for i, p in enumerate(prompts)]
            for h in hs:
                got, done = [], False
                while not done:
                    new, done = h.wait_new(len(got), 30.0)
                    assert new or done, "decode stalled"
                    got.extend(new)
                toks.append(got)
            outs.append(toks)
        ident = outs[0] == outs[1]
        m["llm_tp_token_identical"] = ident
        assert ident, f"tp decode diverged: {outs}"
        stats = tp_eng.stats()
        m["llm_decode_compiles"] = stats["compiles"]["decode"]
        m["llm_tp"] = stats["tp"]
        m["llm_kv_blocks_leaked"] = stats["blocks_used"]
        assert stats["compiles"]["decode"] == 1, stats
        assert stats["blocks_used"] == 0, stats
        m["llm_tp_param_bytes_frac"] = round(
            _tree_bytes_frac(tp_eng.model.params), 4)
        # plan-aware HLO lint on the compiled tp decode executable:
        # megatron-sharded weights must enter at LOCAL (1/tp) shape —
        # a full-global-shape entry parameter is "TP that isn't" — and
        # the donated cache must stay aliased with the token outfeed
        # at slots x 1 int32 (zoo-lint HLO-SHARDING / HLO-DONATION /
        # HLO-HOST-TRANSFER, docs/static_analysis.md)
        tp_model = tp_eng.model
        tp_sh, tp_rep, tp_loc = plan_lint_shapes(
            tp_model.params, tp_model.mesh)
        tp_hlo = tp_model.compiled_hlo("decode")
        assert_plan_sharded(tp_hlo, tp_sh, tp_rep,
                            local_shapes=tp_loc, plan="tp",
                            label="tp=2 decode executable")
        assert_llm_executable(tp_model, "decode")
        m["tp_hlo_lint"] = "pass"
        m["llm_decode_artifact_lint"] = "pass"
    finally:
        ref_eng.stop()
        tp_eng.stop()

    # 5. pipeline plan: GPipe microbatch schedule == plain dp ------------
    # same model/seed/data with a 4-layer homogeneous body trained once
    # without a plan (per-layer scan) and once under plan="pipeline" on
    # a data x pipe mesh; the loss curves must agree (on XLA CPU they
    # are bit-exact), the stacked body must ACTUALLY shard over the pipe
    # axis (~1/stages of its bytes per device), and the compiled step
    # must carry collective-permute — the "pipeline that isn't" lint
    from zoo_tpu.parallel.plans import PIPE_BODY_KEY

    pipe = 4 if n_devices % 4 == 0 else 2
    ref_p, _, _, _ = _fit_losses(None, devices[:1], batch_size=bs,
                                 body_layers=pipe)
    pshd, pmodel, pplaced, phlo = _fit_losses(
        {"data": n_devices // pipe, "pipe": pipe}, devices,
        batch_size=bs, plan="pipeline", body_layers=pipe)
    pdiff = max(abs(a - b) for a, b in zip(ref_p, pshd))
    m["pipeline_loss_max_abs_diff"] = pdiff
    assert pdiff <= LOSS_TOL, (
        f"pipeline loss curve diverged from dp by {pdiff} "
        f"(> {LOSS_TOL}): {pshd} vs {ref_p}")
    body_frac = _tree_bytes_frac(pplaced[PIPE_BODY_KEY])
    m["pipeline_body_bytes_frac"] = round(body_frac, 4)
    assert body_frac <= 1.0 / pipe + 0.05, (
        f"per-device stacked-body bytes {body_frac:.3f} of replicated — "
        "the body is not actually pipe-sharded")
    mesh_p = build_mesh(devices, axis_sizes={"data": n_devices // pipe,
                                             "pipe": pipe})
    psh, prep, ploc = plan_lint_shapes(pmodel.params, mesh_p, "pipeline")
    assert_pipeline_sharded(phlo, psh, prep, local_shapes=ploc,
                            label="pipeline train step")
    m["pipeline_collectives"] = assert_collectives(
        phlo, require=["collective-permute"],
        label="pipeline train step")
    m["pipeline_hlo_lint"] = "pass"

    # 6. moe plan: expert-sharded FFN == replicated reference ------------
    from zoo_tpu.ops.moe import init_moe_params, moe_ffn
    from zoo_tpu.parallel.plans import place_params

    mesh_e = build_mesh(devices, axis_sizes={"expert": n_devices})
    mp = init_moe_params(jax.random.PRNGKey(0), hidden=16,
                         intermediate=32, n_experts=n_devices)
    xt = np.asarray(np.random.RandomState(1).randn(2, 64, 16),
                    np.float32)
    moe_step = jax.jit(lambda p, t: moe_ffn(p, t, top_k=2,
                                            capacity_factor=1.25))
    y_ref, aux_ref = jax.tree_util.tree_map(
        np.asarray, moe_step(mp, xt))
    eplaced = place_params(mp, mesh_e, "moe")
    y_sh, aux_sh = jax.tree_util.tree_map(
        np.asarray, moe_step(eplaced, xt))
    mdiff = max(float(np.abs(y_ref - y_sh).max()),
                float(np.abs(aux_ref - aux_sh).max()))
    m["moe_out_max_abs_diff"] = mdiff
    assert mdiff <= LOSS_TOL, (
        f"expert-sharded moe_ffn diverged from replicated by {mdiff}")
    efrac = _tree_bytes_frac(
        {k: eplaced[k] for k in ("w_gate", "w_up", "w_down")})
    m["moe_expert_bytes_frac"] = round(efrac, 4)
    assert efrac <= 1.0 / n_devices + 0.05, (
        f"per-device expert-weight bytes {efrac:.3f} of replicated — "
        "experts are not actually sharded")
    moe_compiled = jax.jit(
        lambda p, t: moe_ffn(p, t, top_k=2, capacity_factor=1.25)
    ).lower(eplaced, xt).compile()
    m["moe_collectives"] = assert_collectives(
        moe_compiled,
        require_any=["all-to-all", "all-gather", "all-reduce",
                     "reduce-scatter", "collective-permute"],
        label="moe ffn")
    m["moe_hlo_lint"] = "pass"

    if verbose:
        print("ok: sharded fit matches 1-device within "
              f"{LOSS_TOL} (diff {diff:.3g}), per-device param bytes "
              f"{frac:.3f} of replicated")
        print("ok: HLO lint passed", counts)
        print("ok: save@8 -> restore@4/restore@1 bit-exact")
        print("ok: tp=2 paged decode token-identical, decode "
              "compiles == 1, 0 leaked KV blocks")
        print(f"ok: pipeline plan matches dp (diff {pdiff:.3g}), body "
              f"bytes {body_frac:.3f} of replicated, collective-permute "
              "present")
        print(f"ok: moe plan matches replicated (diff {mdiff:.3g}), "
              f"expert bytes {efrac:.3f} of replicated")
    return m


def check() -> int:
    m = collect_metrics()
    print("MULTICHIP_METRICS " + json.dumps(m, sort_keys=True))
    return 0


def main() -> int:
    # self-provision the virtual multichip platform: XLA only honors
    # --xla_force_host_platform_device_count before the backend
    # initializes, so the real checks always run in a child process
    # with the env forced (same bootstrap as __graft_entry__)
    if os.environ.get("_ZOO_MULTICHIP_INPROC") == "1":
        return check()
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={N_DEVICES}"])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env["_ZOO_MULTICHIP_INPROC"] = "1"
    return subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, timeout=840).returncode


if __name__ == "__main__":
    sys.exit(main())
