#!/usr/bin/env python
"""Deterministic fleet chaos storm END TO END on CPU (jax-free).

A REAL 3-replica :class:`ReplicaGroup` serving BOTH ops (predict via
``synthetic:double``, streaming generate via the deterministic
``synthllm`` engine) under sustained mixed client load, while a seeded
:class:`ChaosSchedule` (docs/fault_tolerance.md) composes the gray
failures that dominate production incidents:

* **slow replica** — replica 1 turns 45x slower mid-storm (a per-op
  delay armed over the wire ``chaos`` op; /healthz keeps passing);
* **frame corruption** — a seeded fraction of the client's outbound
  CRC frames get one bit flipped in transit;
* **SIGKILL** — replica 2 dies at a seeded instant and is respawned by
  the supervisor;
* **connection drops** + a **spill-dir disk-full** window on replica 0.

The contract the storm asserts:

1. every predict answers exactly ``2x`` and every generate stream is
   byte-identical to the fault-free local reference — ZERO failures,
   ZERO garbage decodes;
2. corrupt frames were DETECTED (``zoo_wire_corrupt_frames_total`` on
   the replicas' /metrics) and retried, never decoded;
3. the slow replica is EJECTED from the client rotation within seconds
   (detect-to-eject bound), tail latency recovers once it is out, and
   the seat is RE-ADMITTED after the fault clears;
4. zero leaked KV blocks on every replica after the storm;
5. the killed replica respawned — 3/3 healthy at the end;
6. the SAME ``ZOO_CHAOS_SEED`` resolves the SAME fault sequence
   (replay contract), a different seed resolves a different one.

Run directly (``python scripts/check_chaos_storm.py``) or from the
suite (``tests/test_chaos.py`` runs it under the ``chaos`` marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEED = int(os.environ.get("ZOO_CHAOS_SEED", "20140") or 20140)
SLOW_REPLICA = 1
SLOW_MS = 90.0        # predict batcher delay while gray
SLOW_TICK_MS = 60.0   # per-decode-tick delay while gray
SLOW_T0, SLOW_T1 = 0.6, 4.5
SPEC = (f"slow@{SLOW_T0}-{SLOW_T1}:replica={SLOW_REPLICA},"
        f"delay_ms={SLOW_MS};"
        "corrupt@0.8-3.5:p=0.15;"
        "kill@2.0~2.6:replica=2;"
        "drop@1.2:times=2;"
        "diskfull@0.3-4.8:replica=0")
RUN_S = 7.0           # storm horizon 4.8s + recovery tail
MODEL = ("synthetic:double:2"
         "+synthllm:slots=2,block=4,blocks=96,tables=8,max_prompt=24")


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return float("nan")
    return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ejection import EjectionConfig
    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.synthetic import reference
    from zoo_tpu.util.integrity import corrupt_action
    from zoo_tpu.util.resilience import (
        ChaosSchedule,
        clear_faults,
        default_injector,
        inject,
    )

    # -- the replay contract first: same seed => same fault sequence ---
    sched = ChaosSchedule(SPEC, seed=SEED, replicas=3)
    again = ChaosSchedule(SPEC, seed=SEED, replicas=3)
    assert sched.resolved() == again.resolved(), \
        "same seed resolved a different fault sequence"
    other = ChaosSchedule(SPEC, seed=SEED + 1, replicas=3)
    assert sched.resolved() != other.resolved(), \
        "seed does not drive the schedule (no randomness resolved?)"

    log_dir = tempfile.mkdtemp(prefix="zoo-chaos-storm-")
    group = ReplicaGroup(MODEL, num_replicas=3, max_restarts=2,
                         batch_size=8, max_wait_ms=1.0, log_dir=log_dir,
                         env={"ZOO_CHAOS_ALLOW": "1"})
    group.start(timeout=60)
    # hedge OFF: the hedge would mask the slow replica's latency before
    # ejection does — this storm measures the MEMBERSHIP layer
    cli = HAServingClient(
        group.endpoints(), deadline_ms=15000, hedge=False,
        ejection_config=EjectionConfig(
            enabled=True, min_ms=20.0, min_samples=4, probation_s=0.4,
            probe_interval_s=0.3, readmit_base_s=0.4))

    def corrupt_total():
        # label-blind sum: the counter is labelled by wire plane
        return sum(v for i in range(3)
                   for v in group._metrics_counter(
                       i, "zoo_wire_corrupt_frames_total").values())

    corrupt0 = corrupt_total()

    # -- chaos actions (the schedule's kinds -> this harness) ----------
    def act_slow(ev, phase):
        r = int(ev.params["replica"])
        if phase == "start":
            group.chaos_rpc(r, "serving.infer",
                            delay_ms=float(ev.params["delay_ms"]))
            group.chaos_rpc(r, "llm.decode", delay_ms=SLOW_TICK_MS)
        else:
            group.chaos_rpc(r, "serving.infer", clear=True)
            group.chaos_rpc(r, "llm.decode", clear=True)

    def act_corrupt(ev, phase):
        if phase == "start":
            inject("serving.wire.corrupt", action=corrupt_action,
                   p=float(ev.params["p"]))
        else:
            clear_faults("serving.wire.corrupt")

    def act_kill(ev, phase):
        group.kill_replica(int(ev.params["replica"]))

    def act_drop(ev, phase):
        inject("serving.client.recv",
               exc=ConnectionResetError("chaos drop"),
               times=int(ev.params["times"]))

    def act_diskfull(ev, phase):
        r = int(ev.params["replica"])
        if phase == "start":
            group.chaos_rpc(r, "flight.spill", error="oserror")
        else:
            group.chaos_rpc(r, "flight.spill", clear=True)

    actions = {"slow": act_slow, "corrupt": act_corrupt,
               "kill": act_kill, "drop": act_drop,
               "diskfull": act_diskfull}

    # -- mixed load ----------------------------------------------------
    errors, lats = [], []   # lats: (t_rel, seconds)
    gen_streams = [0]
    lock = threading.Lock()
    t_start = time.monotonic()
    stop_at = t_start + RUN_S

    def now_rel():
        return time.monotonic() - t_start

    def predict_worker(cid):
        rs = np.random.RandomState(1000 + cid)
        while time.monotonic() < stop_at:
            x = rs.randn(1, 8).astype(np.float32)
            t0 = time.monotonic()
            try:
                out = np.asarray(cli.predict(x))
                if not np.allclose(out, x * 2.0, atol=1e-6):
                    raise AssertionError(f"garbage decode: {out!r}")
                with lock:
                    lats.append((now_rel(), time.monotonic() - t0))
            except Exception as e:  # noqa: BLE001 — every failure counts
                with lock:
                    errors.append(f"predict[{cid}]: {e!r}")

    def generate_worker(cid):
        rs = np.random.RandomState(2000 + cid)
        while time.monotonic() < stop_at:
            n = int(rs.randint(4, 16))
            prompt = [int(t) for t in rs.randint(0, 97, size=3)]
            try:
                toks = list(cli.generate(prompt, n))
                exp = reference(prompt, n)
                if toks != exp:
                    raise AssertionError(
                        f"stream diverged from reference: {toks} != "
                        f"{exp}")
                with lock:
                    gen_streams[0] += 1
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"generate[{cid}]: {e!r}")

    threads = [threading.Thread(target=predict_worker, args=(c,))
               for c in range(3)]
    threads += [threading.Thread(target=generate_worker, args=(c,))
                for c in range(2)]
    try:
        sched.run(actions, injector=default_injector)
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sched.join(timeout=10)

        # 1. zero failures, zero garbage decodes, streams byte-exact
        assert not errors, (
            f"{len(errors)} client-visible failure(s):\n"
            + "\n".join(errors[:10]))
        assert gen_streams[0] >= 10, \
            f"only {gen_streams[0]} generate streams completed"

        # 2. corruption detected and counted (on the replicas' doors),
        # and — by assertion 1 — retried, never decoded
        corrupt = corrupt_total() - corrupt0
        assert corrupt > 0, \
            "no corrupt frame was ever detected (seam dead?)"

        # 3. ejection: detect-to-eject bound, tail recovery, readmission
        events = cli.ejection_events()
        kinds = [e[1] for e in events]
        assert "ejected" in kinds, f"slow replica never ejected: {events}"
        t0_mono = t_start + SLOW_T0
        t_eject = next(ts for ts, k, _ in events if k == "ejected")
        detect_s = t_eject - t0_mono
        assert 0 < detect_s < 3.0, \
            f"detect-to-eject took {detect_s:.2f}s (bound 3s)"
        assert "readmitted" in kinds, \
            f"recovered replica never re-admitted: {events}"
        states = cli.ejection_states()
        assert all(s["state"] == "active" for s in states.values()), \
            f"seats still degraded after recovery: {states}"
        # the fault actually bit pre-ejection...
        t_eject_rel = t_eject - t_start
        pre = [dt for ts, dt in lats if SLOW_T0 <= ts <= t_eject_rel]
        assert pre and max(pre) >= SLOW_MS / 1000.0, \
            "no request ever observed the slow replica pre-ejection"
        # ...and the tail recovered once the storm ended
        tail = [dt for ts, dt in lats if ts >= RUN_S - 1.5]
        tail_p99 = _percentile(tail, 99)
        assert len(tail) >= 20 and tail_p99 < SLOW_MS / 2000.0, (
            f"tail p99 did not recover: {tail_p99 * 1e3:.1f}ms over "
            f"{len(tail)} requests (bound {SLOW_MS / 2:.0f}ms)")

        # 4. zero leaked KV blocks on every replica
        from zoo_tpu.serving.tcp_client import _Connection
        for i, port in enumerate(group.ports):
            conn = _Connection(group.host, port)
            stats = conn.rpc({"op": "llm_stats"})["stats"]
            conn.close()
            assert stats["blocks_used"] == 0, (
                f"replica {i} leaked {stats['blocks_used']} KV "
                "block(s)")

        # 5. the killed replica respawned; whole group healthy
        assert group.restarts() >= 1, "no respawn recorded"
        deadline = time.monotonic() + 30
        healthy = 0
        while time.monotonic() < deadline:
            hz = group.healthz()
            healthy = sum(1 for h in hz if h and h.get("ok"))
            if healthy == 3:
                break
            time.sleep(0.3)
        assert healthy == 3, f"only {healthy}/3 replicas healthy"
    finally:
        sched.stop()
        clear_faults()
        cli.close()
        group.stop()

    if verbose:
        all_lats = [dt for _, dt in lats]
        print(f"CHAOS STORM OK: seed {SEED}, {len(lats)} predicts + "
              f"{gen_streams[0]} byte-exact streams, 0 failures, "
              f"{int(corrupt)} corrupt frame(s) caught, "
              f"detect-to-eject {detect_s * 1e3:.0f}ms, "
              f"tail p99 {tail_p99 * 1e3:.1f}ms "
              f"(storm p99 {_percentile(all_lats, 99) * 1e3:.1f}ms), "
              f"{group.restarts()} respawn(s), 0 leaked KV blocks, "
              "replay sequence verified")
    return 0


if __name__ == "__main__":
    sys.exit(check())
