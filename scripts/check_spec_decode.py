#!/usr/bin/env python
"""Speculative-decoding chaos smoke END TO END on CPU: a REAL
2-replica :class:`ReplicaGroup` serving a ``llama:`` spec with
**speculative decoding ON** (``spec_k=4``, the n-gram prompt-lookup
drafter + the multi-token paged VERIFY executable) under a concurrent
mixed repetitive/non-repetitive stream storm, one replica SIGKILLed
mid-storm — and the classic spec-decode guarantee holds end to end:

* **byte-identical to the dense non-speculative reference** — every
  stream through the speculative group matches a local engine built
  from the same spec WITHOUT speculation (same seed-0 weights), greedy
  and seeded sampling both, across the kill and the HA client's
  failover-with-resume;
* **the drafter actually earned its keep** — the surviving replica's
  ``llm_stats`` accept counters show accepted draft tokens (the
  repetitive half of the mix is the prompt-lookup shape);
* **verify-compiles == 1** on every replica after the storm (the
  fixed ``slots x (k+1)`` verify census survived continuous batching,
  per-request ``spec_k`` caps, preemption, and failover), decode
  compiles bounded by 1 (plain-decode lanes of spec_k=0 streams);
* **zero leaked KV blocks** on every replica — rejected draft rows
  are rollback-by-length-reset, never allocator state.

Run directly (``python scripts/check_spec_decode.py``) or from the
suite (``tests/test_spec_decode.py`` runs it under the ``perf``
marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASE = "llama:tiny:slots=4,block=8,blocks=128,tables=12,buckets=16/64"
SPEC = BASE + ",spec_k=4"
N_STREAMS = 10
MIN_ACCEPTED = 8   # across replicas: the repetitive streams must have
#                    produced SOME accepted draft tokens


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.spec import build_llm_engine
    from zoo_tpu.serving.tcp_client import _Connection

    rs = np.random.RandomState(0)
    prompts = []
    for i in range(N_STREAMS):
        if i % 2 == 0:
            # repetitive (prompt-lookup hits): a tiled motif
            motif = rs.randint(0, 256, (int(rs.randint(4, 8)),))
            prompts.append(np.tile(motif, 6)[:36].astype(np.int32))
        else:
            # adversarial for the drafter: pure noise
            prompts.append(rs.randint(
                0, 256, (int(rs.randint(5, 14)),)).astype(np.int32))
    max_new = [24 if i % 2 == 0 else 10 for i in range(N_STREAMS)]
    sampling = [dict(temperature=0.9, top_k=24, top_p=0.95,
                     seed=3000 + i) if i % 3 == 0 else {}
                for i in range(N_STREAMS)]
    # one stream pins spec_k=0 over the wire: the per-request knob must
    # ride the frame and stay byte-identical
    spec_caps = [0 if i == 4 else None for i in range(N_STREAMS)]

    # ground truth: the SAME spec WITHOUT speculation, in-process —
    # bit-identical seed-0 weights, so speculative remote streams must
    # match byte for byte
    ref_eng = build_llm_engine(BASE)
    try:
        handles = [ref_eng.submit(p, n, sampling=s or None,
                                  rid=f"ref-{i}")
                   for i, (p, n, s) in enumerate(
                       zip(prompts, max_new, sampling))]
        deadline = time.monotonic() + 600
        while not all(h.done for h in handles):
            assert time.monotonic() < deadline, "reference streams stuck"
            time.sleep(0.01)
        assert all(h.outcome == "ok" for h in handles), \
            [(h.outcome, h.error) for h in handles]
        refs = [list(h.tokens) for h in handles]
        assert ref_eng.stats()["spec_k"] == 0
    finally:
        ref_eng.stop()

    log_dir = tempfile.mkdtemp(prefix="zoo-spec-decode-smoke-")
    group = ReplicaGroup(SPEC, num_replicas=2, max_restarts=2,
                         log_dir=log_dir)
    group.start(timeout=180)
    client = HAServingClient(group.endpoints(), deadline_ms=300_000,
                             hedge=False)
    errors, lock = [], threading.Lock()

    def stream_worker(i, notify=None):
        try:
            kw = dict(sampling[i])
            if spec_caps[i] is not None:
                kw["spec_k"] = spec_caps[i]
            got = []
            for tok in client.generate(prompts[i], max_new[i], **kw):
                got.append(tok)
                if notify is not None:
                    notify.set()
            if got != refs[i]:
                raise AssertionError(
                    f"stream {i} (speculative) != non-speculative "
                    f"reference: {got} vs {refs[i]}")
        except Exception as e:  # noqa: BLE001 — every failure counts
            with lock:
                errors.append(f"stream {i}: {e!r}")

    try:
        # warm both replicas' executables off the measurement clock
        for host, port in group.endpoints():
            conn = _Connection(host, port)
            for _ in conn.stream({"op": "generate",
                                  "prompt": prompts[0][:6],
                                  "max_new_tokens": 3}):
                pass
            conn.close()

        # phase 1: half the streams over the healthy group
        threads = [threading.Thread(target=stream_worker, args=(i,))
                   for i in range(N_STREAMS // 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, "\n".join(errors[:10])

        # phase 2 + chaos: SIGKILL one replica while streams are
        # mid-flight — failover resumes on the survivor; speculative
        # or not, the resumed stream replays byte-identically
        first_tokens = threading.Event()
        threads = [threading.Thread(target=stream_worker,
                                    args=(i, first_tokens))
                   for i in range(N_STREAMS // 2, N_STREAMS)]
        for t in threads:
            t.start()
        first_tokens.wait(timeout=120)   # kill lands mid-decode
        group.kill_replica(0)
        for t in threads:
            t.join()
        assert not errors, (
            f"{len(errors)} failure(s):\n" + "\n".join(errors[:10]))

        # the supervisor must respawn the dead seat
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            hz = group.healthz()
            if sum(1 for h in hz if h is not None and h.get("ok")) == 2:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("killed replica never respawned")

        stats = []
        for host, port in group.endpoints():
            end = time.monotonic() + 60
            while time.monotonic() < end:
                try:
                    conn = _Connection(host, port)
                    stats.append(conn.rpc({"op": "llm_stats"})["stats"])
                    conn.close()
                    break
                except OSError:
                    time.sleep(0.3)   # respawn window
            else:
                raise AssertionError(f"no llm_stats from {host}:{port}")

        accepted = sum(s.get("spec_accepted_tokens", 0) for s in stats)
        assert accepted >= MIN_ACCEPTED, (
            f"accepted draft tokens {accepted} < {MIN_ACCEPTED} — "
            f"speculation never engaged "
            f"({[s.get('spec_accepted_tokens') for s in stats]})")
        for s, (host, port) in zip(stats, group.endpoints()):
            assert s["spec_k"] == 4, s
            assert s["blocks_used"] == 0, (
                f"replica {host}:{port} leaked {s['blocks_used']} "
                "KV block(s)")
            compiles = s.get("compiles", {})
            assert compiles.get("verify") == 1 or (
                compiles.get("verify") == 0 and s["decode_steps"] == 0
            ), (f"replica {host}:{port}: verify executable census "
                f"{compiles} (must be exactly 1 once it decoded)")
            assert compiles.get("decode", 0) <= 1, compiles
        assert group.restarts() >= 1, "no respawn recorded"
    finally:
        client.close()
        group.stop()

    if verbose:
        print(f"SPEC DECODE OK: {N_STREAMS}/{N_STREAMS} speculative "
              f"streams byte-identical to the non-speculative "
              f"reference across a replica SIGKILL, {accepted} "
              f"accepted draft tokens (>= {MIN_ACCEPTED}), 0 leaked "
              f"KV blocks, verify-compiles==1")
    return 0


if __name__ == "__main__":
    sys.exit(check())
