#!/usr/bin/env python
"""Prefix-cache chaos smoke END TO END on CPU: a REAL 2-replica
:class:`ReplicaGroup` serving a ``llama:`` spec with **prefix caching
ON** (``prefix_cache=1``) and chunked prefill, many concurrent streams
sharing one 400-token system prefix, one replica SIGKILLed mid-storm —
and the bytes-per-token contracts of this PR hold:

* **byte-identical to the no-cache reference** — every stream through
  the cached group matches a local engine built from the same spec
  WITHOUT prefix caching (same seed-0 weights), greedy and seeded
  sampling both, across the kill/failover;
* **the shared prefix is actually shared** — the per-replica
  ``llm_stats`` prefix hit counters account for at least the expected
  number of full-prefix hits (cold prefills are bounded by one per
  replica boot + one per respawn);
* **zero leaked blocks** — after all frees every replica's allocator
  accounts to zero live blocks, with the remainder split between the
  free list and the parked (refcount-0, matchable) prefix-cache LRU;
* **a respawned replica re-warms** — the post-kill phase runs more
  shared-prefix streams through the fresh process without correctness
  loss (its first one re-registers the prefix, the rest hit).

Run directly (``python scripts/check_prefix_cache.py``) or from the
suite (``tests/test_llm_serving.py`` runs it under the ``perf``
marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PREFIX_LEN = 400
BASE = ("llama:tiny:slots=4,block=8,blocks=192,tables=64,"
        "buckets=16/512,chunk=32")
SPEC = BASE + ",prefix_cache=1"
N_STREAMS = 8           # phase 1 (warm cache) + phase 2 (chaos) halves
# hit floor: every replica's cache is warmed by ONE explicit cold
# stream before its phase, so all 8 client streams should hit the
# 400-token prefix. The SIGKILL wipes the dead replica's counters with
# its process, so the floor only counts what provably lands on the
# survivor: its phase-1 share (>= 2 of 4 round-robin streams) plus all
# 4 phase-2 streams (routed or failed-over there), minus slack for
# routing skew
EXPECTED_HIT_TOKENS = 4 * PREFIX_LEN


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.spec import build_llm_engine
    from zoo_tpu.serving.tcp_client import _Connection

    rs = np.random.RandomState(0)
    prefix = rs.randint(0, 256, (PREFIX_LEN,)).astype(np.int32)
    prompts = [np.concatenate([prefix, rs.randint(
        0, 256, (int(rs.randint(3, 12)),)).astype(np.int32)])
        for _ in range(N_STREAMS)]
    max_new = [8 if i % 2 else 5 for i in range(N_STREAMS)]
    sampling = [dict(temperature=0.9, top_k=24, top_p=0.95,
                     seed=2000 + i) if i % 3 == 0 else {}
                for i in range(N_STREAMS)]

    # ground truth: the SAME spec WITHOUT prefix caching, in-process —
    # bit-identical seed-0 weights, so cached remote streams must match
    # byte for byte
    ref_eng = build_llm_engine(BASE)
    try:
        handles = [ref_eng.submit(p, n, sampling=s or None,
                                  rid=f"ref-{i}")
                   for i, (p, n, s) in enumerate(
                       zip(prompts, max_new, sampling))]
        deadline = time.monotonic() + 600
        while not all(h.done for h in handles):
            assert time.monotonic() < deadline, "reference streams stuck"
            time.sleep(0.01)
        assert all(h.outcome == "ok" for h in handles), \
            [(h.outcome, h.error) for h in handles]
        refs = [list(h.tokens) for h in handles]
        assert ref_eng.stats()["prefix_hit_tokens"] == 0
    finally:
        ref_eng.stop()

    log_dir = tempfile.mkdtemp(prefix="zoo-prefix-cache-smoke-")
    group = ReplicaGroup(SPEC, num_replicas=2, max_restarts=2,
                         log_dir=log_dir)
    group.start(timeout=180)
    client = HAServingClient(group.endpoints(), deadline_ms=300_000,
                             hedge=False)
    errors, lock = [], threading.Lock()

    def stream_worker(i, notify=None):
        try:
            got = []
            for tok in client.generate(prompts[i], max_new[i],
                                       **sampling[i]):
                got.append(tok)
                if notify is not None:
                    notify.set()
            if got != refs[i]:
                raise AssertionError(
                    f"stream {i} (prefix-cached) != no-cache "
                    f"reference: {got} vs {refs[i]}")
        except Exception as e:  # noqa: BLE001 — every failure counts
            with lock:
                errors.append(f"stream {i}: {e!r}")

    def run_phase(indices):
        threads = [threading.Thread(target=stream_worker, args=(i,))
                   for i in indices]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def replica_stats():
        out = []
        for host, port in group.endpoints():
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    conn = _Connection(host, port)
                    stats = conn.rpc({"op": "llm_stats"})["stats"]
                    conn.close()
                    out.append(stats)
                    break
                except OSError:
                    time.sleep(0.3)   # respawn window
            else:
                raise AssertionError(f"no llm_stats from {host}:{port}")
        return out

    def warm_replica(host, port):
        """One explicit cold stream per replica registers the shared
        prefix (executables compile on the same call), so the
        concurrent storm measures SHARING, not a thundering herd of
        simultaneous cold admissions."""
        conn = _Connection(host, port)
        for _ in conn.stream({"op": "generate",
                              "prompt": np.concatenate(
                                  [prefix, prefix[:2]]),
                              "max_new_tokens": 2}):
            pass
        conn.close()

    try:
        for host, port in group.endpoints():
            warm_replica(host, port)

        # phase 1: concurrent shared-prefix streams over the warm group
        run_phase(range(N_STREAMS // 2))
        assert not errors, "\n".join(errors[:10])

        # phase 2 + chaos: SIGKILL one replica while its streams are
        # mid-flight — failover resumes on the survivor, whose warm
        # cache turns even the resumed re-prefills into hits
        first_tokens = threading.Event()
        threads = [threading.Thread(target=stream_worker,
                                    args=(i, first_tokens))
                   for i in range(N_STREAMS // 2, N_STREAMS)]
        for t in threads:
            t.start()
        first_tokens.wait(timeout=120)   # kill lands mid-decode
        group.kill_replica(0)
        for t in threads:
            t.join()
        assert not errors, (
            f"{len(errors)} failure(s):\n" + "\n".join(errors[:10]))

        # the supervisor must respawn the dead seat; its cache died
        # with it, and ONE re-warm stream restores fleet-wide sharing
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            hz = group.healthz()
            if sum(1 for h in hz if h is not None and h.get("ok")) == 2:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("killed replica never respawned")
        respawned = group.endpoints()[0]
        warm_replica(*respawned)
        conn = _Connection(*respawned)
        st = conn.rpc({"op": "llm_stats"})["stats"]
        conn.close()
        assert st.get("blocks_cached", 0) > 0, (
            f"respawned replica did not re-warm the prefix cache: {st}")

        stats = replica_stats()
        hits = sum(s.get("prefix_hit_tokens", 0) for s in stats)
        assert hits >= EXPECTED_HIT_TOKENS, (
            f"prefix hit tokens {hits} < expected "
            f"{EXPECTED_HIT_TOKENS} — the cache is not being shared "
            f"({[s.get('prefix_hit_tokens') for s in stats]})")
        for s, (host, port) in zip(stats, group.endpoints()):
            assert s["prefix_cache"] is True, s
            assert s["blocks_used"] == 0, (
                f"replica {host}:{port} leaked {s['blocks_used']} "
                "KV block(s)")
            assert s["blocks_free"] + s["blocks_cached"] == \
                s["num_blocks"] - 1, (
                f"replica {host}:{port} pool does not account: {s}")
            compiles = s.get("compiles", {})
            assert compiles.get("decode") == 1, compiles
            assert compiles.get("prefill_chunk", 0) <= 1, compiles
        assert group.restarts() >= 1, "no respawn recorded"
    finally:
        client.close()
        group.stop()

    if verbose:
        print(f"PREFIX CACHE OK: {N_STREAMS}/{N_STREAMS} shared-prefix "
              f"streams byte-identical to the no-cache reference "
              f"across a replica SIGKILL, {hits} prefix hit tokens "
              f"(>= {EXPECTED_HIT_TOKENS}), 0 leaked blocks, "
              f"decode-compiles==1 on 2/2 replicas")
    return 0


if __name__ == "__main__":
    sys.exit(check())
