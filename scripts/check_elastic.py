#!/usr/bin/env python
"""Smoke-check ``run_elastic`` scale-down END TO END on CPU: a REAL
2-worker supervised launch where worker 1 dies permanently mid-job
(restart budget 0), the supervisor relaunches the survivor as a
1-worker world, and the relaunched run RESUMES from the checkpoint that
``ZOO_ELASTIC_ATTEMPT > 0`` signals — proving the contract the
``docs/fault_tolerance.md`` elastic layer promises, in tier-1 time
(each worker trains a 2-unit Dense head for a couple of epochs; the
cost is the two jax imports, not the math).

Heartbeat liveness is enabled across both attempts, so this also
regression-checks the stale-heartbeat-file carryover fixes: a worker
must never inherit the supervisor's ``ZOO_HEARTBEAT_FILE``, and attempt
N+1 must not read attempt N's stale stamp as its own first beat.

Run directly (``python scripts/check_elastic.py``) or from the suite
(``tests/test_elastic.py`` runs it under the ``chaos`` marker).
"""

import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_WORKER = r"""
import os, sys, time
import numpy as np

rank = int(os.environ.get("ZOO_PROCESS_ID", "0"))
attempt = int(os.environ.get("ZOO_ELASTIC_ATTEMPT", "0"))
model_dir = sys.argv[1]

# prove the launcher handed THIS worker its own heartbeat file (never
# the supervisor's) and start beating on it
hb = os.environ.get("ZOO_HEARTBEAT_FILE", "")
assert f"worker-{rank}" in hb, f"wrong heartbeat file for rank {rank}: {hb!r}"
from zoo_tpu.util.resilience import start_heartbeat_thread
start_heartbeat_thread()

if rank == 1:
    # the doomed worker: wait until rank 0 has committed a checkpoint,
    # then die permanently (budget 0 -> scale-down to world 1)
    flag = os.path.join(model_dir, "ckpt.ready")
    for _ in range(600):
        if os.path.exists(flag):
            break
        time.sleep(0.1)
    print(f"rank 1 exiting permanently (attempt {attempt})", flush=True)
    os._exit(1)

from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

rs = np.random.RandomState(0)
x = rs.randn(64, 4).astype(np.float32)
y = (x @ rs.randn(4, 1)).astype(np.float32)

m = Sequential()
m.add(Dense(2, input_shape=(4,)))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
est = Estimator.from_keras(m, model_dir=model_dir)
if attempt > 0:
    est.load_orca_checkpoint(path=model_dir)
    print(f"RESUMED attempt={attempt} at epoch {est._epoch}", flush=True)
    assert est._epoch >= 1, "resume must start from the saved epoch"

TOTAL = 3
est.fit({"x": x, "y": y}, epochs=2 - min(est._epoch, 1), batch_size=16)
open(os.path.join(model_dir, "ckpt.ready"), "w").close()
if attempt == 0:
    # keep the world alive so the sibling's crash lands mid-job, not
    # after a clean exit (the supervisor tears us down)
    print(f"EPOCH {est._epoch} attempt=0", flush=True)
    time.sleep(600)
while est._epoch < TOTAL:
    est.fit({"x": x, "y": y}, epochs=1, batch_size=16)
print(f"DONE attempt={attempt} epoch={est._epoch}", flush=True)
"""


def check(verbose: bool = True) -> int:
    from zoo_tpu.orca.bootstrap import run_elastic

    tmp = tempfile.mkdtemp(prefix="zoo-elastic-smoke-")
    script = os.path.join(tmp, "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    model_dir = os.path.join(tmp, "model")
    os.makedirs(model_dir, exist_ok=True)
    log_dir = os.path.join(tmp, "logs")
    env = {
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""),
        "JAX_COMPILATION_CACHE_DIR": os.path.join(tmp, "jaxcache"),
        # the guard's SIGTERM handler must not turn the teardown of the
        # still-sleeping attempt-0 survivor into a preempt checkpoint
        "ZOO_PREEMPT": "none",
    }
    final_world = run_elastic(
        2, script, [model_dir], min_workers=1, max_restarts=0,
        log_dir=log_dir, env=env, wait_timeout=240,
        heartbeat_timeout=60.0)
    assert final_world == 1, f"expected scale-down to 1, got {final_world}"

    logs = ""
    import glob
    for path in sorted(glob.glob(os.path.join(log_dir, "*.log"))):
        with open(path) as f:
            logs += f.read()
    resumed = re.search(r"RESUMED attempt=(\d+) at epoch (\d+)", logs)
    assert resumed, f"relaunched world never resumed:\n{logs[-2000:]}"
    assert int(resumed.group(1)) >= 1 and int(resumed.group(2)) >= 1, \
        resumed.group(0)
    assert re.search(r"DONE attempt=\d+ epoch=3", logs), \
        f"resumed run never completed:\n{logs[-2000:]}"
    if verbose:
        print(f"ELASTIC OK: world 2 -> 1, {resumed.group(0)!r}, "
              "completed epoch 3 from the ZOO_ELASTIC_ATTEMPT checkpoint")
    return 0


if __name__ == "__main__":
    sys.exit(check())
