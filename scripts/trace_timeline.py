#!/usr/bin/env python
"""Join the fleet's per-process trace files into per-request timelines.

Every serving process writes ``trace-<host>-<pid>.jsonl`` under
``$ZOO_TRACE_DIR`` (docs/observability.md); a request's trace id rides
the wire, so its spans are scattered across the client's file, every
replica it touched (hedges and failovers included), and — after a
mid-stream SIGKILL — a dead process's torn file. This CLI reassembles
them:

    # which requests are in this trace dir?
    python scripts/trace_timeline.py /tmp/trace --list

    # one request's merged timeline, as a terminal tree
    python scripts/trace_timeline.py /tmp/trace --trace <id>

    # the same, as Chrome/Perfetto trace JSON
    python scripts/trace_timeline.py /tmp/trace --trace <id> \\
        --chrome request.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/trace_timeline.py",
        description="merge per-process trace JSONL into per-request "
                    "timelines")
    ap.add_argument("trace_dir", help="directory of trace-*.jsonl files "
                                      "($ZOO_TRACE_DIR)")
    ap.add_argument("--trace", help="trace id to reconstruct")
    ap.add_argument("--list", action="store_true",
                    help="list trace ids with event/process counts")
    ap.add_argument("--chrome", metavar="OUT.json",
                    help="write the timeline as Chrome trace-event JSON")
    ns = ap.parse_args(argv)

    from zoo_tpu.obs.timeline import (
        build_timeline,
        group_traces,
        load_events,
        render_text,
        to_chrome_trace,
    )

    events = load_events(ns.trace_dir)
    if not events:
        print(f"no trace events under {ns.trace_dir}", file=sys.stderr)
        return 1
    traces = group_traces(events)

    if ns.list or not ns.trace:
        print(f"{len(traces)} trace(s) across "
              f"{len({e.get('file') for e in events})} process file(s):")
        for tid, evs in sorted(traces.items(),
                               key=lambda kv: kv[1][0].get("ts", 0.0)):
            names = [e.get("name") for e in evs]
            roots = [n for n in names
                     if n in ("client.generate", "client.rpc",
                              "http.predict")]
            procs = len({e.get("file") for e in evs})
            print(f"  {tid}  {len(evs):4d} events  {procs} process(es)"
                  + (f"  [{roots[0]}]" if roots else ""))
        return 0

    timeline = build_timeline(traces.get(ns.trace, []))
    if not timeline:
        print(f"trace {ns.trace} not found (use --list)",
              file=sys.stderr)
        return 1
    if ns.chrome:
        with open(ns.chrome, "w", encoding="utf-8") as f:
            json.dump(to_chrome_trace(timeline, trace_id=ns.trace), f)
        print(f"wrote {len(timeline)} events to {ns.chrome} "
              "(open in chrome://tracing or ui.perfetto.dev)")
        return 0
    print(f"trace {ns.trace}: {len(timeline)} events across "
          f"{len({e.get('file') for e in timeline})} process(es)")
    print(render_text(timeline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
