#!/usr/bin/env python
"""Smoke-check the obs exporter end to end: start a MetricsExporter over
a populated registry, GET /metrics and /healthz over real HTTP, and
validate the Prometheus text-format syntax (line grammar, TYPE coverage,
cumulative-histogram consistency).

Run directly (``python scripts/check_metrics_export.py``) or from the
test suite (``tests/test_obs.py`` runs it as a subprocess) — CI exercises
the same path an operator's first curl does. Deliberately jax-free so a
subprocess run costs milliseconds, not an XLA import.
"""

import json
import os
import sys
import urllib.request

# runnable from anywhere without an installed package: the repo root is
# this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check(verbose: bool = True) -> int:
    """Returns a process exit code: 0 = the exporter serves valid
    Prometheus text and a healthy /healthz."""
    from zoo_tpu.obs import (
        MetricsExporter,
        MetricsRegistry,
        validate_prometheus_text,
    )

    reg = MetricsRegistry()
    # one of each metric kind, with and without labels, so the validator
    # sees every rendering shape the real registry can produce
    reg.counter("zoo_smoke_requests_total", "smoke counter",
                labels=("outcome",)).labels(outcome="ok").inc(3)
    reg.gauge("zoo_smoke_queue_depth", "smoke gauge").set(2)
    hist = reg.histogram("zoo_smoke_latency_seconds", "smoke histogram",
                         labels=("stage",))
    for v in (0.0002, 0.004, 0.1, 2.5):
        hist.labels(stage="infer").observe(v)

    exporter = MetricsExporter(registry=reg).start()
    try:
        with urllib.request.urlopen(exporter.url + "/metrics",
                                    timeout=10) as resp:
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode()
        problems = validate_prometheus_text(text)
        if "text/plain" not in ctype:
            problems.append(f"unexpected /metrics Content-Type: {ctype}")
        for needle in ("zoo_smoke_requests_total", "zoo_smoke_queue_depth",
                       "zoo_smoke_latency_seconds_bucket"):
            if needle not in text:
                problems.append(f"/metrics is missing {needle}")
        with urllib.request.urlopen(exporter.url + "/healthz",
                                    timeout=10) as resp:
            health = json.loads(resp.read().decode())
        if not health.get("ok"):
            problems.append(f"/healthz not ok: {health}")
    finally:
        exporter.stop()

    if verbose:
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
        else:
            print(f"ok: {len(text.splitlines())} lines of valid "
                  "Prometheus text, /healthz healthy")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(check())
