#!/usr/bin/env python
"""LLM-serving chaos smoke END TO END on CPU: a REAL 2-replica
:class:`ReplicaGroup` serving a tiny ``llama:`` spec (separate
supervised processes, bit-identical seed-0 weights), N concurrent
mixed-length token streams through :class:`HAServingClient.generate`,
one replica SIGKILLed mid-stream — and the HA streaming contract holds:

* ZERO client-visible failures — every stream completes and is
  byte-identical to its pre-chaos reference (failover-resume regenerates
  the suffix on the surviving replica; no gap, duplicate, or error);
* the dead seat is respawned on its original port and probes healthy;
* ZERO leaked KV blocks — after the storm both engines' paged
  allocators account to zero (``llm_stats`` over the wire), so aborted
  streams returned every block to the free list.

Run directly (``python scripts/check_llm_serving.py``) or from the
suite (``tests/test_llm_serving.py`` runs it under the ``chaos``
marker).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# small pool + small buckets: replica boot compiles 2 prefill buckets +
# 1 decode executable, which is what bounds this smoke's wall clock
SPEC = "llama:tiny:slots=4,block=8,blocks=96,tables=8,buckets=16/32"


def check(verbose: bool = True) -> int:
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.tcp_client import _Connection

    log_dir = tempfile.mkdtemp(prefix="zoo-llm-serving-smoke-")
    group = ReplicaGroup(SPEC, num_replicas=2, max_restarts=2,
                         log_dir=log_dir)
    group.start(timeout=180)
    client = HAServingClient(group.endpoints(), deadline_ms=240_000,
                             hedge=False)

    rs = np.random.RandomState(0)
    n_streams = 8
    prompts = [rs.randint(0, 256, (int(rs.randint(3, 15)),)).astype(
        np.int32) for _ in range(n_streams)]
    max_new = [20 if i % 2 == 0 else 6 for i in range(n_streams)]

    # reference pass — one stream per replica first (warms BOTH
    # replicas' executables off the chaos clock), then every prompt's
    # expected tokens; greedy decode over bit-identical weights makes
    # these the ground truth for the chaos pass on either replica
    for host, port in group.endpoints():
        conn = _Connection(host, port)
        for f in conn.stream({"op": "generate", "prompt": prompts[0],
                              "max_new_tokens": 2}):
            pass
        conn.close()
    refs = [list(client.generate(p, n))
            for p, n in zip(prompts, max_new)]
    assert all(len(r) == n for r, n in zip(refs, max_new)), \
        [len(r) for r in refs]

    errors, done_ok = [], [0]
    lock = threading.Lock()
    first_tokens = threading.Event()
    killed = threading.Event()

    def stream_worker(i):
        try:
            got = []
            for tok in client.generate(prompts[i], max_new[i]):
                got.append(tok)
                first_tokens.set()
            if got != refs[i]:
                raise AssertionError(
                    f"stream {i} diverged after failover: "
                    f"{got} vs {refs[i]}")
            with lock:
                done_ok[0] += 1
        except Exception as e:  # noqa: BLE001 — every failure counts
            with lock:
                errors.append(f"stream {i}: {e!r}")

    def chaos():
        # the SIGKILL lands while streams are decoding — after the
        # first token is on the wire, never after the storm drained
        first_tokens.wait(timeout=120)
        group.kill_replica(0)
        killed.set()

    try:
        threads = [threading.Thread(target=stream_worker, args=(i,))
                   for i in range(n_streams)]
        threads.append(threading.Thread(target=chaos))
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        assert killed.is_set(), "the chaos kill never fired"
        assert not errors, (
            f"{len(errors)} client-visible failure(s):\n"
            + "\n".join(errors[:10]))
        assert done_ok[0] == n_streams, done_ok

        # the supervisor must respawn the dead seat on its old port
        deadline = time.monotonic() + 60
        healthy = 0
        while time.monotonic() < deadline:
            hz = group.healthz()
            healthy = sum(1 for h in hz if h is not None and h.get("ok"))
            if healthy == 2:
                break
            time.sleep(0.3)
        assert healthy == 2, f"only {healthy}/2 replicas healthy"
        assert group.restarts() >= 1, "no respawn recorded"

        # zero leaked KV blocks: every replica's paged allocator must
        # account to zero once the storm is over (cancelled/abandoned
        # streams freed their blocks; the respawned engine is fresh)
        for host, port in group.endpoints():
            deadline = time.monotonic() + 30
            used = None
            while time.monotonic() < deadline:
                try:
                    conn = _Connection(host, port)
                    stats = conn.rpc({"op": "llm_stats"})["stats"]
                    conn.close()
                    used = stats["blocks_used"]
                    if used == 0:
                        break
                except OSError:
                    pass  # respawn window
                time.sleep(0.3)
            assert used == 0, (
                f"replica {host}:{port} leaked {used} KV block(s)")
    finally:
        group.stop()

    if verbose:
        print(f"LLM SERVING OK: {done_ok[0]}/{n_streams} token streams "
              f"byte-identical to reference across a replica SIGKILL, "
              f"0 client-visible failures, {group.restarts()} "
              f"respawn(s), 2/2 healthy, 0 leaked KV blocks")
    return 0


if __name__ == "__main__":
    sys.exit(check())
