"""The fleet observability layer (PR 13): request-scoped trace
propagation (client → wire → server → engine), the per-request timeline
merger, torn-line tolerance, the crash flight recorder + postmortem
harvest, the SLO watchdog, shed-reply trace echo, and the 3-replica
SIGKILL trace-reconstruction chaos smoke."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import zoo_tpu.obs as obs
from zoo_tpu.obs import flight as flight_mod
from zoo_tpu.obs.slo import SLORule, SLOWatchdog
from zoo_tpu.obs.timeline import (
    build_timeline,
    group_traces,
    load_events,
    render_text,
    to_chrome_trace,
)
from zoo_tpu.obs.tracing import (
    ambient_trace_id,
    emit_event,
    emit_span,
    iter_jsonl,
    trace_context,
)

pytestmark = pytest.mark.obs


@pytest.fixture()
def fresh_flight(tmp_path, monkeypatch):
    """A flight recorder spilling into tmp (and restored afterwards)."""
    monkeypatch.setenv("ZOO_OBS_POSTMORTEM_DIR", str(tmp_path / "pm"))
    monkeypatch.setenv("ZOO_OBS_FLIGHT_CAP", "8")
    flight_mod.reset_for_tests()
    yield flight_mod.flight_recorder()
    flight_mod.reset_for_tests()


# ----------------------------------------------------- trace contexts

def test_trace_context_adoption_and_parenting(tmp_path):
    d = str(tmp_path / "t")
    obs.trace_to(d)
    try:
        assert ambient_trace_id() is None
        with trace_context("req1" * 8, "par1" * 4):
            assert ambient_trace_id() == "req1" * 8
            with obs.span("inner"):
                pass
        assert ambient_trace_id() is None
        with obs.span("outer"):  # back on the process trace
            pass
    finally:
        obs.stop_tracing()
    evs = obs.read_trace(d)
    inner_b = next(e for e in evs if e["name"] == "inner"
                   and e["ev"] == "B")
    assert inner_b["trace"] == "req1" * 8
    assert inner_b["parent"] == "par1" * 4
    outer_b = next(e for e in evs if e["name"] == "outer"
                   and e["ev"] == "B")
    assert outer_b["trace"] != "req1" * 8
    assert outer_b["parent"] is None


def test_emit_span_and_event_identity(tmp_path):
    d = str(tmp_path / "t")
    obs.trace_to(d)
    try:
        sid = emit_span("work", 100.0, 0.25, trace="tt" * 16,
                        parent="pp" * 8, ok=False, rid="r1")
        emit_event("mark", trace="tt" * 16, parent=sid, note="x")
    finally:
        obs.stop_tracing()
    evs = obs.read_trace(d)
    x = next(e for e in evs if e["ev"] == "X")
    assert (x["trace"], x["parent"], x["dur_s"], x["ok"]) == \
        ("tt" * 16, "pp" * 8, 0.25, False)
    assert x["attrs"] == {"rid": "r1"}
    i = next(e for e in evs if e["ev"] == "I")
    assert i["parent"] == x["span"] == sid


def test_emit_disabled_is_noop():
    obs.stop_tracing()
    assert emit_span("x", 0.0, 0.0) is None
    assert emit_event("x") is None


# ------------------------------------------------- torn-line tolerance

def test_read_trace_skips_truncated_live_file(tmp_path):
    """A replica SIGKILLed mid-write tears its last line; the readers
    must keep the intact prefix instead of raising."""
    d = str(tmp_path / "t")
    obs.trace_to(d)
    try:
        for i in range(3):
            with obs.span(f"s{i}"):
                pass
    finally:
        obs.stop_tracing()
    (fname,) = [f for f in os.listdir(d) if f.startswith("trace-")]
    path = os.path.join(d, fname)
    # truncate the LIVE file mid-line (the SIGKILL shape) ...
    raw = open(path, "rb").read()
    assert raw.endswith(b"\n")
    with open(path, "wb") as f:
        f.write(raw[:-9])  # tears the final record
    evs = obs.read_trace(d)
    names = [e["name"] for e in evs]
    assert "s0" in names and "s1" in names
    assert len(evs) == 5  # 6 B/E records minus the torn one
    # ... and with appended garbage (torn + invalid utf-8 + partial)
    with open(path, "ab") as f:
        f.write(b'{"ev":"B","name":"torn\xff\xfe\n{"half')
    assert len(obs.read_trace(d)) == 5
    # the timeline loader shares the tolerance
    assert len(load_events(d)) == 5
    assert list(iter_jsonl(os.path.join(d, "missing.jsonl"))) == []


# ------------------------------------------------------------ timeline

def test_timeline_merger_open_spans_and_chrome():
    tid = "ab" * 16
    events = [
        # client root (X), one attempt that completed (B+E), one the
        # kill tore open (B only), an instant, and a foreign trace
        {"ev": "X", "name": "client.generate", "trace": tid,
         "span": "root", "ts": 1.0, "dur_s": 5.0, "ok": True,
         "file": "trace-h-1.jsonl"},
        {"ev": "B", "name": "server.generate", "trace": tid,
         "span": "a1", "parent": "root", "pid": 2, "ts": 1.5,
         "file": "trace-h-2.jsonl"},
        {"ev": "E", "name": "server.generate", "trace": tid,
         "span": "a1", "ts": 2.0, "dur_s": 0.5, "ok": True},
        {"ev": "B", "name": "llm.decode", "trace": tid, "span": "a2",
         "pid": 3, "ts": 2.5, "file": "trace-h-3.jsonl"},
        {"ev": "I", "name": "llm.admit", "trace": tid, "span": "i1",
         "ts": 1.6, "pid": 2, "file": "trace-h-2.jsonl"},
        {"ev": "B", "name": "other", "trace": "zz" * 16, "span": "zz",
         "ts": 0.5},
    ]
    traces = group_traces(events)
    assert set(traces) == {tid, "zz" * 16}
    tl = build_timeline(traces[tid])
    assert [e["name"] for e in tl] == [
        "client.generate", "server.generate", "llm.admit",
        "llm.decode"]
    by = {e["name"]: e for e in tl}
    assert by["server.generate"]["open"] is False
    assert by["server.generate"]["dur_s"] == 0.5
    assert by["llm.decode"]["open"] is True  # the killed replica
    assert by["llm.decode"]["dur_s"] is None
    chrome = to_chrome_trace(tl, trace_id=tid)
    xs = [e for e in chrome["traceEvents"] if e.get("ph") == "X"]
    assert any("[open]" in e["name"] for e in xs)
    # one pid row per source process + the metadata naming them
    assert len({e["pid"] for e in xs}) == 3
    assert chrome["otherData"]["trace_id"] == tid
    text = render_text(tl)
    assert "OPEN" in text and "client.generate" in text


def test_trace_timeline_cli(tmp_path):
    d = str(tmp_path / "t")
    obs.trace_to(d)
    tid = "cd" * 16
    try:
        with trace_context(tid):
            with obs.span("cli.work"):
                pass
    finally:
        obs.stop_tracing()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(root, "scripts", "trace_timeline.py")
    out = subprocess.run([sys.executable, script, d, "--list"],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0 and tid in out.stdout
    chrome_path = str(tmp_path / "chrome.json")
    out = subprocess.run(
        [sys.executable, script, d, "--trace", tid, "--chrome",
         chrome_path], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    data = json.load(open(chrome_path))
    assert any(e.get("name", "").startswith("cli.work")
               for e in data["traceEvents"])


# ------------------------------------------------------ flight recorder

def test_flight_ring_bounds_spill_and_bundle(fresh_flight, tmp_path):
    rec = fresh_flight
    for i in range(20):
        rec.record("tick", i=i)
    ring = rec.events()
    assert len(ring) == 8  # capacity-bounded
    assert ring[-1]["i"] == 19 and ring[0]["i"] == 12
    # the spill kept EVERYTHING (it is the SIGKILL postmortem)
    spilled = flight_mod.read_spill(rec.spill_path)
    assert [e["i"] for e in spilled] == list(range(20))
    # torn spill tail parses to the intact prefix
    with open(rec.spill_path, "ab") as f:
        f.write(b'{"ts": 1, "kind": "to')
    assert len(flight_mod.read_spill(rec.spill_path)) == 20
    # the bundle: ring + metrics + config + a reason
    path = rec.dump("unit-test")
    assert path is not None and os.path.exists(path)
    bundle = json.load(open(path))
    assert bundle["reason"] == "unit-test"
    assert [e["i"] for e in bundle["ring"]] == list(range(12, 20))
    assert "counters" in bundle["metrics"]
    assert any(k.startswith("ZOO_") for k in bundle["config"])


def test_flight_disabled_costs_nothing(monkeypatch):
    monkeypatch.setenv("ZOO_OBS_FLIGHT_CAP", "0")
    flight_mod.reset_for_tests()
    try:
        rec = flight_mod.flight_recorder()
        rec.record("x")
        assert rec.events() == []
        assert rec.dump("x") is None or True  # no spill dir armed
    finally:
        monkeypatch.delenv("ZOO_OBS_FLIGHT_CAP")
        flight_mod.reset_for_tests()


def test_breaker_and_retry_feed_flight_ring(fresh_flight):
    from zoo_tpu.util.resilience import (
        CircuitBreaker,
        RetryError,
        RetryPolicy,
    )
    br = CircuitBreaker(failure_threshold=1, recovery_timeout=60)
    br.record_failure()
    pol = RetryPolicy(max_attempts=2, sleep=lambda s: None)

    def dead():
        raise ConnectionError("down")

    with pytest.raises(RetryError):
        pol.call(dead)
    kinds = [e["kind"] for e in fresh_flight.events()]
    assert "breaker_open" in kinds and "retry_giveup" in kinds


def test_replica_group_harvests_dead_spill(tmp_path):
    """A spill file whose pid is not the live replica (the SIGKILL
    leftovers) is packaged into a group-dir bundle, torn tail and
    all."""
    from zoo_tpu.serving.ha import ReplicaGroup
    log_dir = str(tmp_path / "group")
    group = ReplicaGroup("synthetic:double", num_replicas=1,
                         log_dir=log_dir)  # never started: no processes
    fdir = os.path.join(log_dir, "flight", "replica-0")
    os.makedirs(fdir)
    with open(os.path.join(fdir, "flight-99999.jsonl"), "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "shed",
                            "reason": "queue_full"}) + "\n")
        f.write(json.dumps({"ts": 2.0, "kind": "engine_tick"}) + "\n")
        f.write('{"ts": 3.0, "kind": "to')  # torn by the kill
    harvested = group.harvest_postmortems()
    assert len(harvested) == 1
    bundle = json.load(open(harvested[0]))
    assert bundle["reason"] == "harvested" and bundle["pid"] == 99999
    assert [e["kind"] for e in bundle["ring"]] == ["shed",
                                                   "engine_tick"]
    assert not os.path.exists(os.path.join(fdir,
                                           "flight-99999.jsonl"))
    assert group.harvest_postmortems() == []  # idempotent


def test_crash_handler_dumps_on_excepthook(tmp_path, monkeypatch):
    """The unhandled-exception path, end to end in a subprocess."""
    pm = str(tmp_path / "pm")
    code = (
        "import os\n"
        "os.environ['ZOO_OBS_POSTMORTEM_DIR'] = r'%s'\n"
        "from zoo_tpu.obs.flight import install_crash_handlers, "
        "record_event\n"
        "install_crash_handlers()\n"
        "record_event('about_to_die', step=7)\n"
        "raise RuntimeError('boom')\n" % pm)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0 and "boom" in proc.stderr
    bundles = [f for f in os.listdir(pm)
               if f.startswith("postmortem-")]
    assert bundles, os.listdir(pm)
    bundle = json.load(open(os.path.join(pm, bundles[0])))
    assert bundle["reason"] == "unhandled_exception"
    kinds = [e["kind"] for e in bundle["ring"]]
    assert "about_to_die" in kinds and "unhandled_exception" in kinds


# --------------------------------------------------------- SLO watchdog

def _mk_registry():
    r = obs.MetricsRegistry()
    req = r.counter("zoo_serving_requests_total", "x",
                    labels=("outcome",))
    return r, req


def test_slo_watchdog_breach_and_clear(fresh_flight):
    from zoo_tpu.obs.slo import _error_rate, last_status
    r, req = _mk_registry()
    w = SLOWatchdog(
        rules=[SLORule("error_rate", _error_rate, 0.1)],
        registry=r, window_s=0.0, interval_s=60.0)
    req.labels(outcome="ok").inc(10)
    s0 = w.evaluate()  # first pass: delta vs itself, no verdict
    assert s0["ok"] and "measured" not in s0["rules"]["error_rate"]
    req.labels(outcome="ok").inc(5)
    req.labels(outcome="error").inc(5)
    s1 = w.evaluate()
    rule = s1["rules"]["error_rate"]
    assert not s1["ok"] and s1["breaches"] == ["error_rate"]
    assert abs(rule["measured"] - 0.5) < 1e-9
    assert abs(rule["burn_rate"] - 5.0) < 1e-9
    assert last_status() is s1
    # quiet window: the breach clears, and both edges hit the ring
    w.evaluate()
    s2 = w.evaluate()
    assert s2["ok"]
    kinds = [e["kind"] for e in fresh_flight.events()]
    assert "slo_breach" in kinds and "slo_clear" in kinds


def test_slo_quantile_and_floor_rules():
    from zoo_tpu.obs.slo import quantile_from_counts
    assert quantile_from_counts([0.1, 1.0], [0, 0, 0], 0.99) is None
    assert quantile_from_counts([0.1, 1.0], [98, 1, 1], 0.5) == 0.1
    assert quantile_from_counts([0.1, 1.0], [0, 0, 5], 0.99) == 1.0
    # floor rule: accept-rate below the floor burns
    rule = SLORule("accept", lambda d, l: 0.2, 0.4, floor=True)
    measured, burn = rule.evaluate({}, {})
    assert measured == 0.2 and abs(burn - 2.0) < 1e-9


def test_slo_env_rules_and_healthz(monkeypatch):
    monkeypatch.setenv("ZOO_SLO_ERROR_RATE", "0.25")
    monkeypatch.setenv("ZOO_SLO_TTFT_P99_S", "0.5")
    from zoo_tpu.obs.slo import _set_status, default_rules
    rules = default_rules()
    assert sorted(r.name for r in rules) == ["error_rate", "ttft_p99"]
    # /healthz attaches the last verdict; 200 by default on a breach,
    # 503 only under the explicit opt-in
    import urllib.error
    import urllib.request
    monkeypatch.delenv("ZOO_HEARTBEAT_FILE", raising=False)
    _set_status({"ok": False, "breaches": ["error_rate"], "rules": {}})
    ex = obs.MetricsExporter(registry=obs.MetricsRegistry()).start()
    try:
        with urllib.request.urlopen(ex.url + "/healthz",
                                    timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["ok"] is True
        assert body["slo"]["breaches"] == ["error_rate"]
        monkeypatch.setenv("ZOO_SLO_FAIL_HEALTHZ", "1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(ex.url + "/healthz", timeout=10)
        assert ei.value.code == 503
    finally:
        ex.stop()
        _set_status(None)


def test_promotion_gate_slo_veto():
    from zoo_tpu.obs.slo import _set_status
    from zoo_tpu.orca.learn.continuous import PromotionGate
    rng = np.random.RandomState(0)
    gate = PromotionGate(lambda x: x, lambda x: x, candidate="v2",
                         sample=1.0, window=1, rng=rng,
                         max_latency_ratio=1e9)  # not under test:
    # single-sample p50 ratios are scheduler noise
    gate.offer(np.ones(2))
    assert gate.ready()
    _set_status({"ok": False, "breaches": ["ttft_p99"], "rules": {}})
    try:
        d = gate.decision()
        assert not d.promoted and "SLO" in d.reason
    finally:
        _set_status(None)
    assert gate.decision().promoted


# ------------------------------------------- shed replies echo the trace

def test_shed_reply_echoes_trace_id(fresh_flight):
    """Regression (the old bug): a queue-full shed short-circuits
    before request bookkeeping, but its reply must still carry the
    request's trace id — rejected requests are traceable too."""
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import _Connection

    release = threading.Event()

    class _Block:
        def predict(self, x, batch_size=None):
            release.wait(timeout=15)
            return np.asarray(x) * 2.0

    srv = ServingServer(_Block(), port=0, batch_size=1,
                        max_wait_ms=0.0, max_queue=1).start()
    tid = "fe" * 16
    x = np.zeros((1, 2), np.float32)

    def fire_and_forget():
        conn = _Connection(srv.host, srv.port)
        try:
            conn.rpc({"op": "predict", "uri": "u", "data": x})
        finally:
            conn.close()

    try:
        # request 1 occupies the (single) batcher, request 2 fills the
        # bounded queue, request 3 must shed at the door
        t1 = threading.Thread(target=fire_and_forget)
        t1.start()
        time.sleep(0.5)
        t2 = threading.Thread(target=fire_and_forget)
        t2.start()
        time.sleep(0.3)
        conn = _Connection(srv.host, srv.port)
        resp = conn.rpc({"op": "predict", "uri": "u", "data": x,
                         "trace": tid, "pspan": "ps" * 8})
        conn.close()
        assert resp.get("shed") and resp.get("retryable"), resp
        assert resp.get("trace") == tid, resp
        release.set()
        t1.join(timeout=20)
        t2.join(timeout=20)
    finally:
        release.set()
        srv.stop()
    # the shed also landed in the flight ring with its reason
    sheds = [e for e in fresh_flight.events() if e["kind"] == "shed"]
    assert any(e.get("reason") == "queue_full" for e in sheds)


def test_debug_dump_wire_op(fresh_flight):
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import _Connection

    class _M:
        def predict(self, x, batch_size=None):
            return np.asarray(x)

    flight_mod.record_event("marker", n=1)
    srv = ServingServer(_M(), port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        conn = _Connection(srv.host, srv.port)
        resp = conn.rpc({"op": "debug_dump"})
        conn.close()
    finally:
        srv.stop()
    assert resp.get("ok")
    bundle = resp["bundle"]
    assert bundle["reason"] == "debug_dump"
    assert any(e["kind"] == "marker" for e in bundle["ring"])
    assert "counters" in bundle["metrics"]


# ------------------------------------------------------ the chaos smoke

def test_check_trace_e2e_script_runs():
    """The 3-replica hedged-generate SIGKILL smoke
    (scripts/check_trace_e2e.py): one trace id reconstructs the whole
    request across the kill, the dead replica's postmortem is
    harvested, zero client-visible failures — as a subprocess, the
    operator invocation."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_trace_e2e.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    assert "TRACE E2E OK" in proc.stdout
