"""Speculative decoding (docs/llm_serving.md): the n-gram
prompt-lookup drafter, the multi-token paged VERIFY executable, and the
engine's accept/rollback scheduling.

The load-bearing contract is the classic spec-decode guarantee made
byte-exact: every emitted token is the CANONICAL per-position sample
(same logits row, same stateless PRNG key non-speculative decode would
use), so a speculative stream is byte-identical to plain decode —
greedy and seeded sampling alike, across preemption, chunked prefill,
prefix caching, int8 KV, and tensor parallelism. Drafter/scheduler
tests run jax-free against a deterministic fake model; the interaction
matrix runs the real ``PagedLlamaModel``. The 2-replica SIGKILL smoke
(scripts/check_spec_decode.py) runs as a subprocess under the ``perf``
marker like its siblings.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from zoo_tpu.serving.llm.engine import LLMEngine
from zoo_tpu.serving.llm.speculative import (
    PromptLookup,
    accept_length,
    propose_tokens,
)


# ------------------------------------------------------------- drafter

class TestDrafter:
    def test_periodic_prompt_proposes_continuation(self):
        # suffix [3,1,2] re-occurs; the period-3 cycle extrapolates
        assert list(propose_tokens([1, 2, 3, 1, 2, 3, 1, 2], 7)) == \
            [3, 1, 2, 3, 1, 2, 3]

    def test_non_repeating_context_proposes_nothing(self):
        assert propose_tokens([5, 6, 7, 8], 4).size == 0

    def test_longest_ngram_wins(self):
        # 1-gram [2] matches at idx 1 (cont 9) but the 2-gram [5, 2]
        # match at idx 3 is more reliable and must win
        ctx = [7, 2, 9, 5, 2, 8, 5, 2]
        assert list(propose_tokens(ctx, 1, ngram_max=2)) == [8]

    def test_k_zero_and_tiny_context(self):
        assert propose_tokens([1, 2, 1, 2], 0).size == 0
        assert propose_tokens([1], 4).size == 0
        assert propose_tokens([], 4).size == 0

    def test_accept_length(self):
        assert accept_length([3, 4, 9], [3, 4, 1, 2]) == 2
        assert accept_length([], [7]) == 0
        assert accept_length([5], [5, 6]) == 1
        assert accept_length([9], [5, 6]) == 0

    def test_prompt_lookup_matches_reference_drafter(self):
        """The incremental index and the rescanning reference must be
        behaviorally identical — random contexts, random splits."""
        rs = np.random.RandomState(7)
        for _ in range(300):
            L = rs.randint(2, 40)
            ctx = rs.randint(0, 5, (L,)).astype(np.int32)
            k = int(rs.randint(1, 8))
            n = int(rs.randint(1, 5))
            split = int(rs.randint(1, L)) if L > 1 else 1
            lk = PromptLookup(ctx[:split], n)
            lk.extend(ctx[split:])
            assert list(lk.propose(k)) == \
                list(propose_tokens(ctx, k, n)), (ctx, k, n, split)


# ------------------------------------------- scheduler over a fake model

class _SpecFake:
    """Deterministic jax-free model: the canonical next token after x
    is (x + 1) % mod, for decode AND verify alike — so a cyclic prompt
    0..mod-1 makes prompt-lookup drafts fully acceptable, and a
    non-repeating prompt yields no proposals."""

    def __init__(self, num_slots=2, spec_k=3, mod=4):
        self.num_slots, self.spec_k, self.mod = num_slots, spec_k, mod
        self.block_size, self.num_blocks = 4, 64
        self.max_blocks_per_seq, self.max_prompt_len = 8, 30
        self.max_context, self.prefill_chunk_size = 32, 0
        self.eos_id = None
        self.suffix_chunk_size = 4
        self.verify_calls = 0
        self.verify_widths = set()

    def prefill(self, prompt, row, sampling=None):
        return (int(prompt[-1]) + 1) % self.mod

    def decode_step(self, prev, host, use, tables, pos, lanes):
        return (np.where(np.asarray(use), host, prev if prev
                         is not None else 0) + 1) % self.mod

    def verify_step(self, tokens, tables, positions, lanes):
        tokens = np.asarray(tokens)
        assert tokens.shape == (self.num_slots, self.spec_k + 1), \
            tokens.shape
        self.verify_calls += 1
        self.verify_widths.add(tokens.shape)
        return (tokens + 1) % self.mod

    def read_tokens(self, batch):
        return np.asarray(batch)


def _drain(handles, budget=60.0):
    end = time.monotonic() + budget
    while not all(h.done for h in handles):
        assert time.monotonic() < end, \
            [(h.outcome, h.error) for h in handles]
        time.sleep(0.002)
    assert all(h.outcome == "ok" for h in handles), \
        [(h.outcome, h.error) for h in handles]
    return [list(h.tokens) for h in handles]


CYCLIC = np.array([0, 1, 2, 3, 0, 1, 2, 3], np.int32)
NOISE = np.array([9, 17, 23], np.int32)


class TestEngineSpecFake:
    @pytest.mark.parametrize("overlap", [True, False])
    def test_spec_stream_identical_to_plain(self, overlap):
        ref = _drain([LLMEngine(_SpecFake(spec_k=0), overlap=overlap)
                      .start().submit(CYCLIC, 10, rid="p")])
        fake = _SpecFake(spec_k=3)
        eng = LLMEngine(fake, overlap=overlap).start()
        try:
            got = _drain([eng.submit(CYCLIC, 10, rid="s")])
            assert got == ref
            st = eng.stats()
            # the cyclic prompt drafts perfectly: every proposal is
            # the canonical (x+1)%4 continuation
            assert st["spec_accepted_tokens"] > 0
            assert st["spec_accept_rate"] == 1.0
            assert fake.verify_calls < 10, (
                "full acceptance should need far fewer passes than "
                "tokens")
        finally:
            eng.stop()

    def test_acyclic_prompt_degenerates_to_plain_decode(self):
        fake = _SpecFake(spec_k=3, mod=50)
        eng = LLMEngine(fake).start()
        try:
            _drain([eng.submit(NOISE, 6, rid="n")])
            st = eng.stats()
            assert st["spec_proposed_tokens"] == 0
            assert st["spec_draft_hit_rate"] < 1.0
        finally:
            eng.stop()

    def test_fixed_verify_census_shape(self):
        """Every verify batch is the ONE (slots, k+1) shape regardless
        of how many lanes drafted — the compile-census contract."""
        fake = _SpecFake(num_slots=2, spec_k=3)
        eng = LLMEngine(fake).start()
        try:
            _drain([eng.submit(CYCLIC, 8, rid="a"),
                    eng.submit(NOISE, 4, rid="b")])
            assert fake.verify_widths == {(2, 4)}
        finally:
            eng.stop()

    def test_per_request_spec_cap(self):
        fake = _SpecFake(spec_k=3)
        eng = LLMEngine(fake).start()
        try:
            ref = _drain([eng.submit(CYCLIC, 8, rid="full")])
            got = _drain([eng.submit(CYCLIC, 8, rid="capped",
                                     spec_k=0)])
            assert got == ref  # identity holds with drafting off
        finally:
            eng.stop()
        with pytest.raises(ValueError):
            LLMEngine(_SpecFake()).submit(CYCLIC, 4, spec_k=-1)

    def test_engine_budget_clamped_to_model_width(self):
        """An engine cannot speculate wider than the model's fixed
        verify executable; spec_k=0 disables cleanly (the A/B rig)."""
        assert LLMEngine(_SpecFake(spec_k=3), spec_k=99).spec_k == 3
        eng = LLMEngine(_SpecFake(spec_k=3), spec_k=0)
        assert eng.spec_k == 0 and not eng._spec

    def test_eos_inside_accepted_run_stops_stream(self):
        fake = _SpecFake(spec_k=3)
        fake.eos_id = 2
        eng = LLMEngine(fake).start()
        try:
            toks = _drain([eng.submit(CYCLIC, 10, rid="e")])[0]
            assert toks[-1] == 2 and 2 not in toks[:-1]
            assert eng.stats()["blocks_used"] == 0
        finally:
            eng.stop()

    def test_max_new_respected_mid_batch(self):
        """A verify pass can accept past max_new; emission must stop
        exactly at the budget."""
        fake = _SpecFake(spec_k=3)
        eng = LLMEngine(fake).start()
        try:
            for n in (1, 2, 5):
                toks = _drain([eng.submit(CYCLIC, n, rid=f"m{n}")])[0]
                assert len(toks) == n
            assert eng.stats()["blocks_used"] == 0
        finally:
            eng.stop()


# ---------------------------------------------------- allocator support

class TestGrowTo:
    def test_grow_to_funds_without_preemption(self):
        from zoo_tpu.serving.llm.kv_cache import BlockAllocator
        alloc = BlockAllocator(num_blocks=8, block_size=4)
        alloc.allocate("s", 1)
        assert alloc.grow_to("s", 10) == 12      # 3 blocks x 4
        assert alloc.grow_to("s", 100) == 28     # pool-capped: 7 blocks
        assert alloc.free_blocks == 0
        assert alloc.grow_to("ghost", 8) == 0    # unknown sequence
        alloc.free("s")
        assert alloc.free_blocks == 7

    def test_grow_to_never_steals_referenced_blocks(self):
        from zoo_tpu.serving.llm.kv_cache import BlockAllocator
        alloc = BlockAllocator(num_blocks=6, block_size=4)
        alloc.allocate("a", 3)
        alloc.allocate("b", 1)
        assert alloc.grow_to("b", 40) == 8       # only the free block
        assert len(alloc.blocks_of("a")) == 3


# -------------------------------------------------- real-model identity

@pytest.fixture(scope="module")
def tiny_cfg():
    from zoo_tpu.models.llm.llama import tiny_llama_config
    return tiny_llama_config(vocab=64)


def _generate(model, prompts, n, engine_kw=None, sampling=None,
              budget=300.0):
    eng = LLMEngine(model, **(engine_kw or {})).start()
    try:
        hs = [eng.submit(p, n, rid=f"g{i}",
                         sampling=(sampling[i] if sampling else None))
              for i, p in enumerate(prompts)]
        toks = _drain(hs, budget=budget)
        return toks, eng.stats()
    finally:
        eng.stop()


class TestRealModelMatrix:
    """The interaction matrix: speculative decode x prefix-cache x
    int8 KV x chunked prefill, all token-identical to the f32 dense
    non-speculative reference (the tp=2 leg runs under the multichip
    marker below)."""

    PROMPTS = None
    SAMPLING = None
    REF = None

    @pytest.fixture(scope="class")
    def reference(self, tiny_cfg):
        from zoo_tpu.serving.llm.model import PagedLlamaModel
        cls = TestRealModelMatrix
        if cls.REF is None:
            rs = np.random.RandomState(3)
            motif = rs.randint(0, 64, (5,))
            cls.PROMPTS = [
                np.tile(motif, 4).astype(np.int32),       # repetitive
                rs.randint(0, 64, (9,)).astype(np.int32),  # noise
                np.tile(motif, 4).astype(np.int32),       # shared prefix
            ]
            cls.SAMPLING = [None,
                            dict(temperature=0.9, top_k=16,
                                 top_p=0.95, seed=11),
                            dict(temperature=1.1, seed=5)]
            base = PagedLlamaModel(
                tiny_cfg, seed=0, num_slots=2, block_size=4,
                num_blocks=48, max_blocks_per_seq=10,
                prefill_buckets=(8, 32))
            assert base.kv_cache_dtype == "f32"
            cls.REF, st = _generate(base, cls.PROMPTS, 12,
                                    sampling=cls.SAMPLING)
            assert st["spec_k"] == 0
        return cls.REF

    @pytest.mark.parametrize("variant", [
        "spec", "spec_chunk", "spec_int8", "spec_prefix",
        "spec_int8_prefix_chunk"])
    def test_variant_token_identical(self, tiny_cfg, reference,
                                     variant):
        from zoo_tpu.serving.llm.model import PagedLlamaModel
        kw = dict(seed=0, num_slots=2, block_size=4, num_blocks=48,
                  max_blocks_per_seq=10, prefill_buckets=(8, 32),
                  spec_k=3)
        ekw = {}
        if "chunk" in variant:
            kw["prefill_chunk"] = 4
        if "int8" in variant:
            kw["kv_dtype"] = "int8"
        if "prefix" in variant:
            ekw["prefix_cache"] = True
        model = PagedLlamaModel(tiny_cfg, **kw)
        got, st = _generate(model, self.PROMPTS, 12, engine_kw=ekw,
                            sampling=self.SAMPLING)
        assert got == reference, f"{variant} diverged"
        c = st["compiles"]
        assert c["verify"] == 1 and c["decode"] == 0, c
        assert c["prefill_chunk"] <= 1, c
        assert st["blocks_used"] == 0, st
        if variant == "spec":
            # compiled-artifact contracts on the ONE verify
            # executable: donated cache aliased, outfeed stays
            # slots x (k+1) int32 rows, never logits (zoo-lint
            # HLO-DONATION / HLO-HOST-TRANSFER); one variant is
            # enough — the census asserts the others share it
            from zoo_tpu.analysis.hlo import assert_llm_executable
            assert_llm_executable(model, "verify")
        assert st["spec_accepted_tokens"] > 0, (
            "the repetitive streams should accept some drafts")
        if "prefix" in variant:
            assert st["prefix_hit_tokens"] > 0, st

    def test_seeded_sampling_deterministic_across_runs(self, tiny_cfg,
                                                       reference):
        from zoo_tpu.serving.llm.model import PagedLlamaModel
        model = PagedLlamaModel(
            tiny_cfg, seed=0, num_slots=2, block_size=4,
            num_blocks=48, max_blocks_per_seq=10,
            prefill_buckets=(8, 32), spec_k=3)
        a, _ = _generate(model, self.PROMPTS, 12,
                         sampling=self.SAMPLING)
        b, _ = _generate(model, self.PROMPTS, 12,
                         sampling=self.SAMPLING)
        assert a == b == reference

    def test_spec_across_real_preemption(self, tiny_cfg):
        """A pool sized to force eviction mid-stream: the speculative
        engine preempts, resumes by re-prefill, and stays
        byte-identical to the non-speculative reference."""
        from zoo_tpu.models.llm.llama import LlamaConfig
        from zoo_tpu.obs.metrics import counter
        from zoo_tpu.serving.llm.model import PagedLlamaModel

        cfg = LlamaConfig(vocab=64, hidden=32, n_block=2, n_head=4,
                          n_kv_head=2, intermediate=64,
                          rope_theta=10000.0)
        kw = dict(seed=0, num_slots=2, block_size=4, num_blocks=8,
                  max_blocks_per_seq=8, prefill_buckets=(8, 32))
        prompts = [np.arange(2, 8) % 64, np.arange(3, 9) % 64]
        ref, _ = _generate(PagedLlamaModel(cfg, **kw), prompts, 14)
        p0 = counter("zoo_llm_preempt_total").value
        got, st = _generate(PagedLlamaModel(cfg, spec_k=3, **kw),
                            prompts, 14)
        assert counter("zoo_llm_preempt_total").value > p0, \
            "pool sizing failed to force a preemption"
        assert got == ref
        assert st["blocks_used"] == 0

    def test_verify_step_enforces_census_shape(self, tiny_cfg):
        from zoo_tpu.serving.llm.model import PagedLlamaModel
        m = PagedLlamaModel(tiny_cfg, seed=0, num_slots=2,
                            block_size=4, num_blocks=16,
                            max_blocks_per_seq=4,
                            prefill_buckets=(8,), spec_k=2)
        lanes = (np.zeros(2, np.float32), np.zeros(2, np.int32),
                 np.ones(2, np.float32), np.zeros(2, np.uint32))
        with pytest.raises(ValueError, match="census"):
            m.verify_step(np.zeros((2, 5), np.int32),
                          np.zeros((2, 4), np.int32),
                          np.zeros(2, np.int32), lanes)
        m0 = PagedLlamaModel(tiny_cfg, seed=0, num_slots=2,
                             block_size=4, num_blocks=16,
                             max_blocks_per_seq=4,
                             prefill_buckets=(8,))
        with pytest.raises(RuntimeError, match="spec_k"):
            m0.verify_step(np.zeros((2, 1), np.int32),
                           np.zeros((2, 4), np.int32),
                           np.zeros(2, np.int32), lanes)


# --------------------------------------------------------- spec grammar

class TestSpecGrammar:
    def test_parse_spec_knobs(self):
        from zoo_tpu.serving.llm.spec import parse_llm_spec
        _, eng = parse_llm_spec(
            "llama:tiny:spec_k=4,spec_ngram=2,prefill_impl=dense")
        assert eng["spec_k"] == 4 and eng["spec_ngram"] == 2
        assert eng["prefill_impl"] == "dense"

    def test_build_engine_spec_on_off(self):
        from zoo_tpu.serving.llm.spec import build_llm_engine
        e = build_llm_engine(
            "llama:tiny:spec_k=3,slots=2,block=4,blocks=16,tables=4,"
            "buckets=8", start=False)
        assert e.spec_k == 3 and e.model.spec_k == 3 and e._spec
        e2 = build_llm_engine(
            "llama:tiny:slots=2,block=4,blocks=16,tables=4,buckets=8",
            start=False)
        assert e2.spec_k == 0 and not e2._spec

    def test_env_spec_k(self, monkeypatch):
        from zoo_tpu.models.llm.llama import tiny_llama_config
        from zoo_tpu.serving.llm.model import PagedLlamaModel
        monkeypatch.setenv("ZOO_LLM_SPEC_K", "2")
        m = PagedLlamaModel(tiny_llama_config(), num_blocks=8,
                            prefill_buckets=(8,))
        assert m.spec_k == 2

    def test_negative_spec_k_refused(self):
        from zoo_tpu.models.llm.llama import tiny_llama_config
        from zoo_tpu.serving.llm.model import PagedLlamaModel
        with pytest.raises(ValueError, match="spec_k"):
            PagedLlamaModel(tiny_llama_config(), num_blocks=8,
                            prefill_buckets=(8,), spec_k=-1)


# --------------------------------------------------- tensor parallelism

@pytest.mark.multichip
def test_spec_tp2_token_identical():
    """tp=2 verify (docs/multichip.md): the verify executable jitted
    with explicit shardings over the model axis emits the same streams
    as the single-device non-speculative reference."""
    import jax

    from zoo_tpu.models.llm.llama import tiny_llama_config
    from zoo_tpu.parallel import build_mesh
    from zoo_tpu.serving.llm.model import PagedLlamaModel

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    cfg = tiny_llama_config(vocab=64)
    kw = dict(seed=0, num_slots=2, block_size=4, num_blocks=24,
              max_blocks_per_seq=6, prefill_buckets=(8, 16))
    rs = np.random.RandomState(5)
    motif = rs.randint(0, 64, (4,))
    prompts = [np.tile(motif, 3).astype(np.int32),
               rs.randint(0, 64, (9,)).astype(np.int32)]
    ref, _ = _generate(PagedLlamaModel(cfg, **kw), prompts, 6)
    mesh = build_mesh(jax.devices()[:2], axis_sizes={"model": 2})
    tp = PagedLlamaModel(cfg, mesh=mesh, spec_k=3, **kw)
    assert tp.tp == 2
    got, st = _generate(tp, prompts, 6)
    assert got == ref
    assert st["compiles"]["verify"] == 1
    assert st["blocks_used"] == 0


# ------------------------------------------------------------ chaos smoke

@pytest.mark.perf
def test_check_spec_decode_script_runs():
    """The spec-decode chaos smoke (scripts/check_spec_decode.py): a
    2-replica spec_k=4 group under a mixed repetitive/noise storm —
    byte-identical to the non-speculative reference across a mid-storm
    SIGKILL, accepted-draft floor, zero leaked KV blocks,
    verify-compiles==1."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_spec_decode.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "SPEC DECODE OK" in proc.stdout
