"""Zero-downtime model lifecycle (docs/model_lifecycle.md): hot-swap
reload semantics, version pinning + A/B routing, rolling updates with
auto-rollback, the shadow-eval promotion gate, and the chaos matrix the
ISSUE names (SIGKILL mid-reload, corrupt publish, injected canary
error-rate, dedup across a version flip).

In-process tests run against stand-in models (jax-free, tier-1 fast);
the subprocess chaos pieces carry the ``chaos``/``lifecycle`` markers
like their serving-HA siblings.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from zoo_tpu.serving.ha import SyntheticModel, resolve_model_spec
from zoo_tpu.serving.registry import ModelRegistry
from zoo_tpu.serving.server import ServingServer
from zoo_tpu.serving.tcp_client import TCPInputQueue, _Connection
from zoo_tpu.util.resilience import clear_faults, inject


def _x(v, n=1, feat=4):
    return np.full((n, feat), float(v), np.float32)


class _MarkerModel:
    """y = factor * x, recording the marker (column 0) of every row it
    actually computed — the witness that deduped requests never reached
    inference, across version flips included."""

    def __init__(self, factor=2.0, delay=0.0):
        self.factor = factor
        self.delay = delay
        self.rows = []

    def predict(self, x, batch_size=None):
        if self.delay:
            time.sleep(self.delay)
        self.rows.extend(np.asarray(x)[:, 0].tolist())
        return np.asarray(x) * self.factor

    def seen(self, marker):
        return sum(1 for r in self.rows if r == float(marker))


def _registry_with(tmp_path, *specs, alias="prod"):
    reg = ModelRegistry(str(tmp_path / "registry"))
    versions = [reg.publish(spec=s) for s in specs]
    if alias and versions:
        reg.set_alias(alias, versions[0])
    return reg, versions


# ---------------------------------------------------------- hot-swap

def test_reload_flips_version_and_keeps_serving(tmp_path):
    reg, (v1, v2) = _registry_with(tmp_path, "synthetic:double:0",
                                   "synthetic:double:0")
    model, version = resolve_model_spec(f"registry:{reg.root}:prod")
    assert version == v1
    server = ServingServer(model, batch_size=4, version=version,
                           model_spec=f"registry:{reg.root}:prod").start()
    try:
        q = TCPInputQueue(server.host, server.port)
        np.testing.assert_allclose(q.predict(_x(1.0)), _x(1.0) * 2)
        assert q.version()["version"] == v1
        conn = _Connection(server.host, server.port)
        resp = conn.rpc({"op": "reload",
                         "spec": f"registry:{reg.root}:{v2}"})
        assert resp.get("ok"), resp
        assert resp["version"] == v2 and resp["previous"] == v1
        # the warm pass primed the input signature live traffic used
        assert resp["warmed"] == 1
        assert q.version()["version"] == v2
        np.testing.assert_allclose(q.predict(_x(2.0)), _x(2.0) * 2)
        # every reply now advertises v2 (the A/B client learns from it)
        assert conn.rpc({"op": "ping"})["version"] == v2
        conn.close()
        q.close()
    finally:
        server.stop()


def test_failed_reload_never_flips(tmp_path):
    """A candidate that fails load OR warm leaves the incumbent
    serving: corrupt registry version (load fails) and broken model
    (warm fails) both reject without a flip."""
    reg, (v1, v2, v3) = _registry_with(
        tmp_path, "synthetic:double:0", "synthetic:broken",
        "synthetic:double:0")
    # corrupt v3 on disk
    path = reg.resolve(v3)[1]
    with open(os.path.join(path, "MODEL"), "ab") as f:
        f.write(b"rot")
    reg._verified_ok.discard(3)
    model, version = resolve_model_spec(f"registry:{reg.root}:prod")
    server = ServingServer(model, batch_size=4, version=version).start()
    try:
        q = TCPInputQueue(server.host, server.port)
        q.predict(_x(1.0))  # teach the warm shape
        conn = _Connection(server.host, server.port)
        # broken model: loads, then the warm inference raises
        resp = conn.rpc({"op": "reload",
                         "spec": f"registry:{reg.root}:{v2}"})
        assert resp.get("reload_failed") and "broken" in resp["error"]
        assert q.version()["version"] == v1
        # corrupt version: the registry quarantines at load
        resp = conn.rpc({"op": "reload",
                         "spec": f"registry:{reg.root}:{v3}"})
        assert resp.get("reload_failed")
        assert "Corrupt" in resp["error"] or "corrupt" in resp["error"]
        assert q.version()["version"] == v1
        np.testing.assert_allclose(q.predict(_x(5.0)), _x(5.0) * 2)
        conn.close()
        q.close()
    finally:
        server.stop()


def test_swap_is_atomic_under_concurrent_load(tmp_path):
    """Clients hammering predict across a flip never see an error or a
    wrong answer — both versions compute 2x, so ANY response is
    verifiable while the flip lands between batches."""
    reg, (v1, v2) = _registry_with(tmp_path, "synthetic:double:1",
                                   "synthetic:double:1")
    model, version = resolve_model_spec(f"registry:{reg.root}:prod")
    server = ServingServer(model, batch_size=4, max_wait_ms=1.0,
                           version=version).start()
    errors = []
    stop = threading.Event()

    def hammer():
        q = TCPInputQueue(server.host, server.port)
        i = 0
        while not stop.is_set():
            i += 1
            try:
                out = np.asarray(q.predict(_x(i)))
                if not np.allclose(out, _x(i) * 2):
                    raise AssertionError(f"bad answer for {i}")
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
        q.close()

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.2)
        assert server.reload_model(
            f"registry:{reg.root}:{v2}")["version"] == v2
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        server.stop()
    assert not errors, errors[:5]


def test_dedup_preserved_across_version_flip(tmp_path):
    """Chaos satellite: a mid-RPC reset retry whose re-send lands AFTER
    a hot-swap still joins the original execution — the request id is
    the identity, not the model version, so the model (either version)
    runs the marker exactly once."""
    m1, m2 = _MarkerModel(delay=0.2), _MarkerModel()
    server = ServingServer(m1, batch_size=2, max_wait_ms=1.0,
                           version="v1",
                           model_loader=lambda s: (m2, "v2")).start()
    try:
        clear_faults()
        flipped = threading.Event()

        def flip_mid_retry():
            # land the flip while the first attempt's batch (0.2s
            # inference) is still in flight and the client is about to
            # retry after its injected reset
            time.sleep(0.05)
            server.reload_model("whatever", version="v2")
            flipped.set()

        threading.Thread(target=flip_mid_retry, daemon=True).start()
        with inject("serving.client.recv",
                    exc=ConnectionResetError("mid-RPC reset"),
                    times=1) as armed:
            q = TCPInputQueue(server.host, server.port)
            out = np.asarray(q.predict(_x(13.0)))
            assert armed.fired == 1
        flipped.wait(timeout=5)
        np.testing.assert_allclose(out, _x(13.0) * 2.0)
        assert m1.seen(13.0) + m2.seen(13.0) == 1, \
            "retry across the version flip double-executed the request"
        q.close()
    finally:
        clear_faults()
        server.stop()


# ------------------------------------------------- version pinning / A/B

def _two_version_servers():
    """Two in-process servers standing in for a mid-rollout group:
    one on v1, one on v2 (both y=2x, so answers verify either way)."""
    s1 = ServingServer(SyntheticModel(), batch_size=4, max_wait_ms=1.0,
                       version="v1").start()
    s2 = ServingServer(SyntheticModel(), batch_size=4, max_wait_ms=1.0,
                       version="v2").start()
    return s1, s2


def test_version_mismatch_bounced_and_routed():
    from zoo_tpu.serving.ha_client import HAServingClient

    s1, s2 = _two_version_servers()
    try:
        # single-endpoint client: the bounce surfaces as a shed
        conn = _Connection(s1.host, s1.port)
        resp = conn.rpc({"op": "predict", "uri": "u", "data": _x(1.0),
                         "model_version": "v2"})
        assert resp.get("shed") and resp.get("version_mismatch")
        assert resp["version"] == "v1"  # teaches the client the truth
        conn.close()
        # HA client: failover lands the pinned request on the right seat
        cli = HAServingClient([(s1.host, s1.port), (s2.host, s2.port)],
                              deadline_ms=8000, hedge=False)
        for _ in range(4):
            out = cli.predict(_x(3.0), model_version="v2")
            np.testing.assert_allclose(out, _x(3.0) * 2)
        # the learned seat versions now steer the plan directly
        assert sorted(ep.seen_version for ep in cli._eps
                      if ep.seen_version) == ["v1", "v2"]
        cli.close()
    finally:
        s1.stop()
        s2.stop()


def test_ab_split_routes_fraction():
    from zoo_tpu.serving.ha_client import HAServingClient

    s1, s2 = _two_version_servers()
    try:
        cli = HAServingClient([(s1.host, s1.port), (s2.host, s2.port)],
                              deadline_ms=8000, hedge=False,
                              ab_split={"v2": 0.5})
        cli._ab_rng.seed(42)
        for i in range(40):
            np.testing.assert_allclose(cli.predict(_x(i)), _x(i) * 2)
        # a 50% split at n=40 lands well inside (5, 35) w.h.p.
        drawn = sum(cli._draw_version() == "v2" for _ in range(200))
        assert 60 <= drawn <= 140
        # pin_version(None) clears
        cli.pin_version(None)
        assert cli._draw_version() is None
        cli.pin_version("v2", 1.0)
        assert cli._draw_version() == "v2"
        with pytest.raises(ValueError):
            cli.set_ab_split({"v2": 0.8, "v3": 0.5})  # sums past 1
        with pytest.raises(ValueError):
            cli.set_ab_split({"v2": -0.1})
        cli.close()
    finally:
        s1.stop()
        s2.stop()


def test_ab_split_env_parsing(monkeypatch):
    from zoo_tpu.serving import ha_client as hc

    assert hc._parse_ab_split("v2=0.1, v3=0.05") == {"v2": 0.1,
                                                     "v3": 0.05}
    assert hc._parse_ab_split("") == {}
    s1 = ServingServer(SyntheticModel(), batch_size=2,
                       version="v1").start()
    try:
        monkeypatch.setenv("ZOO_SERVE_AB_SPLIT", "v1=1.0")
        cli = hc.HAServingClient([(s1.host, s1.port)], hedge=False)
        assert cli._draw_version() == "v1"
        np.testing.assert_allclose(cli.predict(_x(1.0)), _x(1.0) * 2)
        cli.close()
    finally:
        s1.stop()


def test_refresh_endpoints_keeps_surviving_state():
    from zoo_tpu.serving.ha_client import HAServingClient

    s1, s2 = _two_version_servers()
    s3 = ServingServer(SyntheticModel(), batch_size=4,
                       version="v2").start()
    try:
        cli = HAServingClient([(s1.host, s1.port), (s2.host, s2.port)],
                              deadline_ms=8000, hedge=False)
        cli.predict(_x(1.0))
        cli.predict(_x(2.0))
        survivor = next(ep for ep in cli._eps
                        if (ep.host, ep.port) == (s1.host, s1.port))
        survivor.breaker.record_failure()  # distinctive state
        seen = survivor.seen_version
        # rolling resize: s2 leaves, s3 joins, s1 survives
        cli.refresh_endpoints([(s1.host, s1.port), (s3.host, s3.port)])
        kept = next(ep for ep in cli._eps
                    if (ep.host, ep.port) == (s1.host, s1.port))
        assert kept is survivor, "surviving endpoint was rebuilt"
        assert kept.seen_version == seen
        assert kept.breaker._failures == 1, \
            "surviving endpoint's breaker state was reset"
        assert {(ep.host, ep.port) for ep in cli._eps} == {
            (s1.host, s1.port), (s3.host, s3.port)}
        np.testing.assert_allclose(cli.predict(_x(9.0)), _x(9.0) * 2)
        with pytest.raises(ValueError):
            cli.refresh_endpoints([])
        cli.close()
    finally:
        s1.stop()
        s2.stop()
        s3.stop()


# ------------------------------------------------------ drain satellite

def test_drain_honors_env_timeout_and_metric(monkeypatch):
    from zoo_tpu.obs.metrics import get_registry

    monkeypatch.setenv("ZOO_SERVE_DRAIN_TIMEOUT_S", "0.05")
    model = _MarkerModel(delay=0.5)
    server = ServingServer(model, batch_size=2, max_wait_ms=1.0).start()
    done = []

    def slow_req():
        q = TCPInputQueue(server.host, server.port)
        try:
            done.append(np.asarray(q.predict(_x(1.0))))
        except Exception:  # noqa: BLE001 — the drain may cut it off
            pass

    t = threading.Thread(target=slow_req, daemon=True)
    t.start()
    time.sleep(0.1)  # request is mid-inference (0.5s)
    t0 = time.perf_counter()
    drained = server.drain()  # timeout=None -> env 0.05s
    dt = time.perf_counter() - t0
    assert drained is False, "0.05s budget cannot cover 0.5s inference"
    # well under the 30s default (the tail past 0.05s is socketserver's
    # shutdown poll interval, not the drain wait)
    assert dt < 2.0, f"env drain timeout not honored ({dt:.2f}s)"
    snap = get_registry().snapshot()
    fam = [h for h in snap["histograms"]
           if h["name"] == "zoo_serve_drain_seconds"]
    assert fam and sum(h["count"] for h in fam) >= 1, \
        "zoo_serve_drain_seconds not observed"


# -------------------------------------------------- promotion gate

def test_promotion_gate_rejects_injected_canary_errors(tmp_path):
    """Chaos satellite: fault_point("serving.canary") injects a
    regressed canary error rate; the gate must reject, leave prod on
    the incumbent, and drop the canary alias."""
    from zoo_tpu.orca.learn.continuous import PromotionGate

    reg, (v1, v2) = _registry_with(tmp_path, "synthetic:double:0",
                                   "synthetic:double:0")
    reg.set_alias("canary", v2)
    good = lambda x: np.asarray(x) * 2.0  # noqa: E731

    def traffic(n=100):
        rs = np.random.RandomState(3)
        for _ in range(n):
            x = rs.randn(1, 4).astype(np.float32)
            yield x, x * 2.0

    clear_faults()
    try:
        with inject("serving.canary", exc=RuntimeError("canary 500"),
                    p=0.3) as armed:
            gate = PromotionGate(good, good, candidate=v2, registry=reg,
                                 sample=1.0, window=30,
                                 rng=np.random.RandomState(0))
            verdict = gate.run(traffic())
            assert armed.fired >= 1
        assert not verdict.promoted
        assert "error rate" in verdict.reason
        assert reg.alias_version("prod") == v1
        assert reg.alias_version("canary") is None  # demoted
    finally:
        clear_faults()


def test_promotion_gate_rejects_latency_and_loss_regression(tmp_path):
    from zoo_tpu.orca.learn.continuous import PromotionGate

    reg, (v1, v2) = _registry_with(tmp_path, "synthetic:double:0",
                                   "synthetic:double:0")
    fast = lambda x: np.asarray(x) * 2.0  # noqa: E731

    def slow(x):
        time.sleep(0.01)
        return np.asarray(x) * 2.0

    def wrong(x):
        return np.asarray(x) * 2.5  # regressed loss vs y_true = 2x

    def traffic(n=60):
        rs = np.random.RandomState(5)
        for _ in range(n):
            x = rs.randn(1, 4).astype(np.float32) + 1.0
            yield x, x * 2.0

    gate = PromotionGate(fast, slow, candidate=v2, registry=reg,
                         sample=1.0, window=16, max_latency_ratio=2.0,
                         rng=np.random.RandomState(0))
    verdict = gate.run(traffic())
    assert not verdict.promoted and "p50" in verdict.reason
    gate = PromotionGate(fast, wrong, candidate=v2, registry=reg,
                         sample=1.0, window=16, max_loss_ratio=1.1,
                         rng=np.random.RandomState(0))
    verdict = gate.run(traffic())
    assert not verdict.promoted and "loss" in verdict.reason
    assert reg.alias_version("prod") == v1


def test_continuous_loop_demotes_diverged_candidate(tmp_path):
    from zoo_tpu.orca.learn.continuous import ContinuousTrainingLoop
    from zoo_tpu.orca.learn.guard import TrainingDiverged

    reg, (v1,) = _registry_with(tmp_path, "synthetic:double:0")

    def bad_train(window):
        raise TrainingDiverged("loss spiked 10x over rolling median")

    loop = ContinuousTrainingLoop(bad_train, reg)
    out = loop.step(window=None)
    assert out["outcome"] == "demoted"
    assert reg.versions() == [1], "a diverged candidate was published"
    assert reg.alias_version("prod") == v1


def test_continuous_chronos_loop_end_to_end(tmp_path):
    """The paper's Chronos + Serving pillars composed: a REAL Chronos
    forecaster retrains on a streaming window, the ``.zoo`` artifact is
    published as an immutable registry version, shadow-evaled against
    the serving incumbent on live-shaped traffic, and promoted — twice,
    so the second crank exercises a real incumbent-vs-candidate gate
    over models loaded back from the registry."""
    from zoo_tpu.chronos.forecaster.lstm_forecaster import LSTMForecaster
    from zoo_tpu.orca.learn.continuous import (
        ContinuousTrainingLoop,
        PromotionGate,
        chronos_train_fn,
    )

    past, feat = 8, 2
    rs = np.random.RandomState(0)

    def stream_window(n=96):
        # y = mean of the last row's features: learnable in one epoch
        x = rs.randn(n, past, feat).astype(np.float32)
        y = x[:, -1:, :1] * 0.5 + x[:, -1:, 1:] * 0.5
        return x, y

    reg = ModelRegistry(str(tmp_path / "registry"))
    train_fn = chronos_train_fn(
        lambda: LSTMForecaster(past_seq_len=past, input_feature_num=feat,
                               output_feature_num=1, hidden_dim=8),
        epochs=2, batch_size=32, out_dir=str(tmp_path / "artifacts"))

    # crank 1: empty registry, no incumbent -> direct promotion
    loop = ContinuousTrainingLoop(train_fn, reg)
    out1 = loop.step(stream_window())
    assert out1["outcome"] == "promoted" and out1["version"] == "v1"
    assert reg.alias_version("prod") == "v1"
    _, artifact = reg.model_spec("prod")
    assert artifact.endswith("model.zoo")

    # crank 2: gate the new candidate against the serving incumbent,
    # both loaded back from the registry (the replica load path)
    def gate_factory(candidate):
        inc = resolve_model_spec(f"registry:{reg.root}:prod")[0]
        can = resolve_model_spec(f"registry:{reg.root}:{candidate}")[0]
        return PromotionGate(
            lambda x: inc.predict(x), lambda x: can.predict(x),
            candidate=candidate, registry=reg, sample=1.0, window=12,
            max_latency_ratio=50.0,  # CPU timing noise is not the point
            rng=np.random.RandomState(1))

    loop = ContinuousTrainingLoop(train_fn, reg,
                                  gate_factory=gate_factory)
    xs, ys = stream_window(32)
    traffic = [(xs[i:i + 1], ys[i:i + 1].reshape(1, -1))
               for i in range(len(xs))]
    out2 = loop.step(stream_window(), traffic)
    assert out2["outcome"] == "promoted", out2
    assert out2["version"] == "v2"
    assert reg.alias_version("prod") == "v2"
    assert out2["gate"]["mirrored"] >= 12
    # both versions remain immutable history in the registry
    assert reg.versions() == [1, 2]


# ------------------------------------------------------- chaos (group)

@pytest.mark.chaos
def test_sigkill_mid_reload_respawns_on_aliased_version(tmp_path):
    """Chaos satellite: a replica SIGKILLed while reload is warming the
    incoming model must never serve a half-loaded model — the
    supervisor respawn re-resolves the alias and boots on the NEW
    version (the alias moved before the swap), not the stale one."""
    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.util.resilience import RetryError, RetryPolicy

    reg = ModelRegistry(str(tmp_path / "registry"))
    reg.publish(spec="synthetic:double:1", alias="prod")
    # v2's 300ms per-predict delay makes the warm pass a wide window
    v2 = reg.publish(spec="synthetic:double:300")
    group = ReplicaGroup(f"registry:{reg.root}:prod", num_replicas=1,
                         max_restarts=2, batch_size=4, max_wait_ms=1.0,
                         log_dir=str(tmp_path / "logs"))
    group.start(timeout=60)
    try:
        conn = _Connection(group.host, group.ports[0],
                           retry=RetryPolicy(max_attempts=1))
        np.testing.assert_allclose(
            np.asarray(conn.rpc({"op": "predict", "uri": "u",
                                 "data": _x(1.0)})["result"]),
            _x(1.0) * 2)  # teach the warm shape
        reg.set_alias("prod", v2)  # alias moves BEFORE the swap

        def kill_mid_warm():
            time.sleep(0.1)  # inside the 300ms warm inference
            group.kill_replica(0)

        threading.Thread(target=kill_mid_warm, daemon=True).start()
        with pytest.raises((OSError, RetryError)):
            conn.rpc({"op": "reload",
                      "spec": f"registry:{reg.root}:{v2}"})
        conn.close()
        # the respawn resolves prod -> v2 at boot
        deadline = time.monotonic() + 60
        version = None
        while time.monotonic() < deadline:
            try:
                c = _Connection(group.host, group.ports[0],
                                retry=RetryPolicy(max_attempts=1))
                version = c.rpc({"op": "version"}).get("version")
                c.close()
                break
            except (OSError, RetryError):
                time.sleep(0.1)
        assert version == v2, \
            f"respawn came up on {version}, not the aliased {v2}"
        assert group.restarts() >= 1
    finally:
        group.stop()


@pytest.mark.chaos
def test_rolling_update_rejects_corrupt_target_before_touching(tmp_path):
    """A corrupt published version fails rolling_update at resolution —
    BEFORE any replica is contacted — and is quarantined."""
    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.registry import RegistryCorruptError

    reg = ModelRegistry(str(tmp_path / "registry"))
    v1 = reg.publish(spec="synthetic:double:1", alias="prod")
    v2 = reg.publish(spec="synthetic:double:1")
    path = reg.resolve(v2)[1]
    with open(os.path.join(path, "MODEL"), "ab") as f:
        f.write(b"rot")
    reg._verified_ok.discard(2)
    group = ReplicaGroup(f"registry:{reg.root}:prod", num_replicas=1,
                         max_restarts=1, batch_size=4)
    group.start(timeout=60)
    try:
        with pytest.raises(RegistryCorruptError):
            group.rolling_update(v2)
        assert [d and d.get("version")
                for d in group.version_info()] == [v1]
        assert any(".corrupt" in n for n in os.listdir(reg.versions_dir))
    finally:
        group.stop()


@pytest.mark.lifecycle
@pytest.mark.slow
def test_registry_published_llm_spec_boots_llm_replica(tmp_path):
    """A registry version may hold an llm MODEL pointer (llama:*): the
    replica resolves the alias at boot and mounts the generate engine
    — streaming works through the registry indirection, and the
    version travels on the wire identity."""
    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient

    reg = ModelRegistry(str(tmp_path / "registry"))
    v1 = reg.publish(
        spec="llama:tiny:slots=4,block=8,blocks=96,tables=8,"
             "buckets=16/32", alias="prod")
    group = ReplicaGroup(f"registry:{reg.root}:prod", num_replicas=1,
                         max_restarts=1,
                         log_dir=str(tmp_path / "logs"))
    group.start(timeout=300)  # one jax import + tiny-llama build
    try:
        assert group.version_info()[0].get("version") == v1
        cli = HAServingClient(group.endpoints(), deadline_ms=60000)
        toks = list(cli.generate(np.arange(1, 7), max_new_tokens=4))
        assert len(toks) == 4
        cli.close()
    finally:
        group.stop()


# ------------------------------------------------------ lifecycle smoke

@pytest.mark.lifecycle
@pytest.mark.chaos
def test_check_lifecycle_script_runs():
    """The end-to-end lifecycle chaos smoke
    (scripts/check_lifecycle.py): 3-replica group under sustained
    verified load — publish v2 → shadow-eval → promote → rolling swap
    with one SIGKILL injected → broken-candidate auto-rollback; zero
    client-visible failures, zero mixed-version replicas, all replicas
    reporting v2 on /metrics. Run as a subprocess, the operator
    invocation."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_lifecycle.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LIFECYCLE OK" in proc.stdout
