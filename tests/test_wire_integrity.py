"""Wire-frame integrity (CRC trailers) on both planes, deadline-budget
sharing across retries, and reconnect jitter.

docs/fault_tolerance.md: a corrupt frame must surface as
:class:`FrameCorrupt` (a ConnectionError — retried/failed-over like a
reset) and NEVER reach a decoder; old peers that pre-date the trailer
interoperate through negotiation on both planes.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from zoo_tpu.util.integrity import (
    FrameCorrupt,
    corrupt_action,
    flip_bit,
    frame_crc,
    verify_crc,
)
from zoo_tpu.util.resilience import (
    DeadlineExceeded,
    RetryPolicy,
    clear_faults,
    inject,
)


def _counter_value(name, **labels):
    from zoo_tpu.obs.metrics import get_registry
    total = 0.0
    for c in get_registry().snapshot()["counters"]:
        if c["name"] == name and all(
                c["labels"].get(k) == v for k, v in labels.items()):
            total += c["value"]
    return total


# ----------------------------------------------------------- primitives

def test_verify_crc_raises_connectionerror_subclass_and_counts():
    payload = b"hello frame"
    verify_crc(payload, frame_crc(payload), "serving")  # clean: no-op
    before = _counter_value("zoo_wire_corrupt_frames_total",
                            plane="serving")
    with pytest.raises(FrameCorrupt) as ei:
        verify_crc(flip_bit(payload), frame_crc(payload), "serving",
                   context="unit")
    assert isinstance(ei.value, ConnectionError)  # retry/failover path
    assert _counter_value("zoo_wire_corrupt_frames_total",
                          plane="serving") == before + 1


def test_flip_bit_changes_exactly_one_bit():
    buf = bytes(range(16))
    flipped = flip_bit(buf, bit=13)
    assert len(flipped) == len(buf)
    diff = [a ^ b for a, b in zip(buf, flipped)]
    assert sum(bin(d).count("1") for d in diff) == 1


# -------------------------------------------------- serving-plane frames

class _MarkerModel:
    """Counts executions per distinct input value (dedup proof)."""

    def __init__(self):
        self.calls = []
        self._lock = threading.Lock()

    def predict(self, x, batch_size=None):
        with self._lock:
            self.calls.append(np.asarray(x).ravel()[0])
        return np.asarray(x) * 2.0

    def seen(self, v):
        with self._lock:
            return sum(1 for c in self.calls if c == v)


def test_serving_crc_negotiates_and_survives_reply_corruption():
    """Happy path: first exchange upgrades the connection to CRC
    frames; an injected in-transit bit flip on a reply raises
    FrameCorrupt client-side, the retry replays from the dedup cache —
    the answer stays exact and the model ran ONCE."""
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    model = _MarkerModel()
    srv = ServingServer(model, port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        q = TCPInputQueue(srv.host, srv.port)
        out = q.predict(np.full((1, 4), 3.0, np.float32))
        np.testing.assert_allclose(out, 6.0)
        assert q._conn._crc_on, "connection never upgraded to CRC"
        before = _counter_value("zoo_wire_corrupt_frames_total",
                                plane="serving")
        with inject("serving.wire.corrupt", action=corrupt_action,
                    times=1) as armed:
            out = q.predict(np.full((1, 4), 5.0, np.float32))
            np.testing.assert_allclose(out, 10.0)
            assert armed.fired == 1
        assert _counter_value("zoo_wire_corrupt_frames_total",
                              plane="serving") == before + 1
        assert model.seen(5.0) == 1, \
            "corrupt-reply retry re-executed the model"
        q.close()
    finally:
        clear_faults()
        srv.stop()


def test_serving_crc_off_server_interop(monkeypatch):
    """A server with ZOO_WIRE_CRC=0 (stand-in for a pre-CRC build)
    ignores the client's ``crc`` ask and answers plain — the client
    stays on the plain protocol and everything works."""
    monkeypatch.setenv("ZOO_WIRE_CRC", "0")
    from zoo_tpu.serving.ha import SyntheticModel
    from zoo_tpu.serving.server import ServingServer
    srv = ServingServer(SyntheticModel(), port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    monkeypatch.setenv("ZOO_WIRE_CRC", "1")  # client side wants it
    from zoo_tpu.serving.tcp_client import TCPInputQueue
    try:
        q = TCPInputQueue(srv.host, srv.port)
        out = q.predict(np.full((1, 4), 2.0, np.float32))
        np.testing.assert_allclose(out, 4.0)
        assert not q._conn._crc_on
        q.close()
    finally:
        srv.stop()


def test_serving_plain_legacy_client_interop():
    """A raw plain-protocol peer (no crc field, no CRC frames — the
    pre-trailer wire exactly) gets plain replies from a CRC-enabled
    server: old clients keep working unchanged."""
    from zoo_tpu.serving.codec import dumps, loads
    from zoo_tpu.serving.ha import SyntheticModel
    from zoo_tpu.serving.server import ServingServer

    srv = ServingServer(SyntheticModel(), port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        sock = socket.create_connection((srv.host, srv.port))
        payload = dumps({"op": "predict", "uri": "u",
                         "data": np.full((1, 4), 7.0, np.float32)})
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        (word,) = struct.unpack(">I", sock.recv(4, socket.MSG_WAITALL))
        assert not (word & 0x80000000), \
            "server sent a CRC frame to a plain-protocol peer"
        body = b""
        while len(body) < word:
            body += sock.recv(word - len(body))
        resp = loads(body)
        np.testing.assert_allclose(resp["result"], 14.0)
        sock.close()
    finally:
        srv.stop()


def test_corrupt_request_dropped_and_retry_is_idempotent():
    """Client→server corruption: the server cannot trust a corrupt
    frame, drops the connection (counted), and the client's retry —
    same request id, fresh connection — executes exactly once."""
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    model = _MarkerModel()
    srv = ServingServer(model, port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        q = TCPInputQueue(srv.host, srv.port)
        q.predict(np.full((1, 4), 1.0, np.float32))  # upgrade to CRC
        # fire on the SECOND send this connection makes (the request),
        # and never on the retry
        with inject("serving.wire.corrupt", action=corrupt_action,
                    times=1) as armed:
            out = q.predict(np.full((1, 4), 9.0, np.float32))
            np.testing.assert_allclose(out, 18.0)
            assert armed.fired == 1
        assert model.seen(9.0) == 1
        q.close()
    finally:
        clear_faults()
        srv.stop()


# --------------------------------------------------- shard-plane frames

def test_shard_crc_negotiated_and_corruption_refetched():
    from zoo_tpu.orca.data.plane import (
        ExchangeConfig,
        ShardExchange,
        _pool,
        fetch_many,
    )

    shards = {0: {"x": np.arange(2048, dtype=np.float32),
                  "y": np.arange(64, dtype=np.int64)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    cfg = ExchangeConfig()
    assert cfg.crc, "ZOO_WIRE_CRC default should be on"
    try:
        before = _counter_value("zoo_wire_corrupt_frames_total",
                                plane="shard")
        with inject("shard.wire.corrupt", action=corrupt_action,
                    times=1) as armed:
            out = fetch_many(("127.0.0.1", ex.port), [0], config=cfg)
            assert armed.fired == 1
        np.testing.assert_array_equal(out[0]["x"], shards[0]["x"])
        np.testing.assert_array_equal(out[0]["y"], shards[0]["y"])
        assert _counter_value("zoo_wire_corrupt_frames_total",
                              plane="shard") == before + 1
    finally:
        clear_faults()
        ex.close()
        _pool.clear()


def test_shard_crc_on_shm_lane(monkeypatch):
    """The trailer covers the SEGMENT bytes on the shm lane: a bit
    flipped in the mapped payload is caught before decode and the
    chunk refetches clean."""
    monkeypatch.setenv("ZOO_SHARD_LANE", "shm")
    from zoo_tpu.orca.data.plane import (
        ExchangeConfig,
        ShardExchange,
        _pool,
        fetch_many,
    )

    shards = {0: {"x": np.arange(4096, dtype=np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    cfg = ExchangeConfig()
    try:
        with inject("shard.wire.corrupt", action=corrupt_action,
                    times=1) as armed:
            out = fetch_many(("127.0.0.1", ex.port), [0], config=cfg)
            assert armed.fired == 1
        np.testing.assert_array_equal(out[0]["x"], shards[0]["x"])
    finally:
        clear_faults()
        ex.close()
        _pool.clear()


def test_shard_legacy_peer_negotiates_crc_off():
    """A ZSX2-only exchange (negotiate=False — the pre-negotiation
    build) still serves a CRC-wanting client over the plain protocol."""
    from zoo_tpu.orca.data.plane import (
        ExchangeConfig,
        ShardExchange,
        _pool,
        fetch_many,
    )

    shards = {0: {"x": np.arange(256, dtype=np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1", negotiate=False)
    try:
        out = fetch_many(("127.0.0.1", ex.port), [0],
                         config=ExchangeConfig())
        np.testing.assert_array_equal(out[0]["x"], shards[0]["x"])
    finally:
        ex.close()
        _pool.clear()


# ------------------------------------------- deadline budget is SHARED

class _RecordingServer:
    """Minimal ZSRV fake: records each request's stamped deadline_ms
    and answers; can drop the first N connections after a delay."""

    def __init__(self, drop_first: int = 0, drop_delay: float = 0.0):
        self._listener = socket.socket()
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(8)
        self.host, self.port = self._listener.getsockname()
        self.deadlines = []
        self._drop_first = drop_first
        self._drop_delay = drop_delay
        self._accepted = 0
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        from zoo_tpu.serving.server import _recv_frame, _send_msg
        while True:
            try:
                s, _ = self._listener.accept()
            except OSError:
                return
            self._accepted += 1
            if self._accepted <= self._drop_first:
                time.sleep(self._drop_delay)
                s.close()
                continue

            def handle(sock=s):
                try:
                    while True:
                        msg, _crc = _recv_frame(sock)
                        if msg is None:
                            return
                        self.deadlines.append(msg.get("deadline_ms"))
                        _send_msg(sock, {
                            "id": msg.get("id"),
                            "result": np.zeros((1, 2), np.float32)})
                except OSError:
                    pass

            threading.Thread(target=handle, daemon=True).start()

    def close(self):
        self._listener.close()


def test_deadline_budget_shared_across_connection_retries():
    """Regression (the deadline-propagation audit): a slow, failing
    first attempt must leave the RETRY only the remaining budget — the
    re-stamped deadline_ms shrinks by the time already burned, and the
    whole call never outlives the original budget."""
    from zoo_tpu.serving.tcp_client import _Connection

    fake = _RecordingServer()
    try:
        conn = _Connection(fake.host, fake.port,
                           retry=RetryPolicy(max_attempts=3,
                                             base_delay=0.01,
                                             max_delay=0.02))
        # first attempt burns 400ms then fails at the transport
        with inject("serving.request",
                    exc=ConnectionResetError("slow then dead"),
                    action=lambda **k: time.sleep(0.4), times=1):
            t0 = time.monotonic()
            from zoo_tpu.util.resilience import Deadline
            resp = conn.rpc({"op": "predict", "uri": "u",
                             "data": np.zeros((1, 2), np.float32)},
                            deadline=Deadline(1.0))
            wall = time.monotonic() - t0
        assert "result" in resp
        assert len(fake.deadlines) == 1
        stamped = fake.deadlines[0]
        # the retry rode the REMAINING budget: 1000ms minus the 400ms
        # the slow attempt burned (plus backoff), never a fresh 1000
        assert stamped is not None and stamped <= 600.0, stamped
        assert stamped > 0
        assert wall < 1.2
        conn.close()
    finally:
        clear_faults()


def test_deadline_expired_by_slow_attempt_is_terminal():
    """When the first attempt burns the WHOLE budget, the retry raises
    DeadlineExceeded before sending — it never resets to a fresh
    budget and never hangs."""
    from zoo_tpu.serving.tcp_client import _Connection
    from zoo_tpu.util.resilience import Deadline

    fake = _RecordingServer()
    try:
        conn = _Connection(fake.host, fake.port,
                           retry=RetryPolicy(max_attempts=3,
                                             base_delay=0.01,
                                             max_delay=0.02))
        with inject("serving.request",
                    exc=ConnectionResetError("slow then dead"),
                    action=lambda **k: time.sleep(0.35), times=1):
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                conn.rpc({"op": "predict", "uri": "u",
                          "data": np.zeros((1, 2), np.float32)},
                         deadline=Deadline(0.3))
            wall = time.monotonic() - t0
        assert wall < 0.8, "expired budget still cost extra attempts"
        assert fake.deadlines == [], "an expired request hit the wire"
        conn.close()
    finally:
        clear_faults()


def test_deadline_budget_shared_across_ha_failover():
    """HA-level: the failover attempt after a dropped-slow seat stamps
    the REMAINING budget onto the next replica's wire frame."""
    from zoo_tpu.serving.ha_client import HAServingClient

    dead = _RecordingServer(drop_first=99, drop_delay=0.4)
    live = _RecordingServer()
    try:
        cli = HAServingClient(
            [(dead.host, dead.port), (live.host, live.port)],
            deadline_ms=2000, hedge=False, eject=False)
        # force the plan to start at the dead seat
        cli._rr = 0
        resp = cli.rpc({"op": "predict", "uri": "u",
                        "data": np.zeros((1, 2), np.float32)})
        assert "result" in resp
        assert len(live.deadlines) == 1
        assert live.deadlines[0] <= 1700.0, live.deadlines
        cli.close()
    finally:
        dead.close()
        live.close()


# ------------------------------------------------- reconnect jitter

def test_reconnect_jitter_after_poisoned_drop_only():
    from zoo_tpu.serving.ha import SyntheticModel
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import _Connection

    srv = ServingServer(SyntheticModel(), port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        # deterministic jitter: rng pinned to 1.0 => full backoff
        conn = _Connection(srv.host, srv.port,
                           retry=RetryPolicy(max_attempts=1,
                                             base_delay=0.2,
                                             max_delay=0.5,
                                             rng=lambda: 1.0))
        msg = {"op": "predict", "uri": "u",
               "data": np.ones((1, 2), np.float32)}
        conn.rpc(dict(msg))
        # a POISONED drop (server reset / corrupt frame) jitters the
        # reconnect with RetryPolicy.backoff — here backoff(1)=0.2s
        conn._drop()
        t0 = time.monotonic()
        conn.rpc(dict(msg))
        assert time.monotonic() - t0 >= 0.2, \
            "no jitter on reconnect after a poisoned drop"
        # a CLEAN close (pool hygiene) reconnects immediately
        conn.close()
        t0 = time.monotonic()
        conn.rpc(dict(msg))
        assert time.monotonic() - t0 < 0.15, \
            "clean reopen paid the respawn jitter"
        # success reset the streak: the NEXT poisoned drop starts the
        # ladder at backoff(1) again, not backoff(3)
        conn._drop()
        t0 = time.monotonic()
        conn.rpc(dict(msg))
        dt = time.monotonic() - t0
        assert 0.2 <= dt < 0.45, dt
        conn.close()
    finally:
        srv.stop()
