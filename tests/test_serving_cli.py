"""The serving launcher CLI boots the whole pipeline (embedded RESP
server + engine + HTTP frontend) from a saved model file, and clients
round-trip through both wire protocols."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # CLI entry points in fresh subprocesses


def _free_ports(n):
    """Distinct ports: hold all sockets open until every port is drawn."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _launch_cli(args, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "zoo_tpu.serving.run", *args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _wait_for_port(proc, port, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            assert proc.poll() is None, proc.stdout.read()[-2000:]
            time.sleep(0.3)
    raise TimeoutError("serving CLI never opened the HTTP port")


def _http_predict(port, x):
    body = json.dumps({"instances": [{"t": x.tolist()}]}).encode()
    resp = json.loads(urllib.request.urlopen(
        urllib.request.Request(
            f"http://127.0.0.1:{port}/predict", data=body,
            headers={"Content-Type": "application/json"}),
        timeout=60).read())
    val = json.loads(json.loads(resp["predictions"][0])["value"])
    return np.asarray(val["data"], np.float32).reshape(-1)


def _terminate(proc):
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_serving_cli_roundtrip(tmp_path):
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential(name="cli_served")
    m.add(Dense(8, activation="relu", input_shape=(4,)))
    m.add(Dense(3, activation="softmax"))
    m.build()
    model_path = str(tmp_path / "m.zoo")
    m.save(model_path)
    x = np.random.RandomState(0).randn(4).astype(np.float32)
    ref = np.asarray(m.predict(x[None], batch_size=1))[0]

    redis_port, http_port = _free_ports(2)
    proc = _launch_cli(["--model", model_path,
                        "--redis-port", str(redis_port),
                        "--http-port", str(http_port),
                        "--batch-size", "4"])
    try:
        _wait_for_port(proc, http_port)

        # redis-protocol path
        from zoo_tpu.serving.client import InputQueue, OutputQueue
        iq = InputQueue(host="127.0.0.1", port=redis_port)
        iq.enqueue("req1", t=x)
        oq = OutputQueue(host="127.0.0.1", port=redis_port)
        got = "[]"
        for _ in range(300):
            got = oq.query("req1")
            if not isinstance(got, str):
                break
            time.sleep(0.1)
        assert not isinstance(got, str), got
        np.testing.assert_allclose(
            np.asarray(got).reshape(-1), ref, atol=1e-4)

        # http path
        np.testing.assert_allclose(_http_predict(http_port, x), ref,
                                   atol=1e-4)
        metrics = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}/metrics", timeout=30).read())
        assert any("inference" in str(k) for k in metrics)
    finally:
        _terminate(proc)


def test_serving_cli_encrypted_model(tmp_path):
    """Trusted-serving parity: the CLI serves an encrypted-at-rest model
    with the key from env (explicit --encrypted opt-in)."""
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.inference.inference_model import save_encrypted

    m = Sequential(name="enc_served")
    m.add(Dense(4, input_shape=(2,)))
    m.build()
    enc = str(tmp_path / "m.enc")
    save_encrypted(m, enc, "kms-secret", "kms-salt", mode="gcm")
    x = np.random.RandomState(0).randn(2).astype(np.float32)
    ref = np.asarray(m.predict(x[None], batch_size=1))[0]

    redis_port, http_port = _free_ports(2)
    proc = _launch_cli(
        ["--model", enc, "--encrypted",
         "--redis-port", str(redis_port), "--http-port", str(http_port)],
        extra_env={"ZOO_MODEL_SECRET": "kms-secret",
                   "ZOO_MODEL_SALT": "kms-salt",
                   "ZOO_MODEL_ENC_MODE": "gcm"})
    try:
        _wait_for_port(proc, http_port)
        np.testing.assert_allclose(_http_predict(http_port, x), ref,
                                   atol=1e-4)
    finally:
        _terminate(proc)


def test_plaintext_model_ignores_stray_secret_env(tmp_path):
    """A stray ZOO_MODEL_SECRET in the environment must not reroute a
    plaintext model through decryption."""
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential(name="plain_served")
    m.add(Dense(2, input_shape=(2,)))
    m.build()
    path = str(tmp_path / "m.zoo")
    m.save(path)
    x = np.random.RandomState(1).randn(2).astype(np.float32)
    ref = np.asarray(m.predict(x[None], batch_size=1))[0]

    redis_port, http_port = _free_ports(2)
    proc = _launch_cli(
        ["--model", path, "--redis-port", str(redis_port),
         "--http-port", str(http_port)],
        extra_env={"ZOO_MODEL_SECRET": "leftover-from-other-deploy"})
    try:
        _wait_for_port(proc, http_port)
        np.testing.assert_allclose(_http_predict(http_port, x), ref,
                                   atol=1e-4)
    finally:
        _terminate(proc)
