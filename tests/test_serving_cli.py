"""The serving launcher CLI boots the whole pipeline (embedded RESP
server + engine + HTTP frontend) from a saved model file, and clients
round-trip through both wire protocols."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest


def _free_ports(n):
    """Distinct ports: hold all sockets open until every port is drawn."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def test_serving_cli_roundtrip(tmp_path):
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential(name="cli_served")
    m.add(Dense(8, activation="relu", input_shape=(4,)))
    m.add(Dense(3, activation="softmax"))
    m.build()
    model_path = str(tmp_path / "m.zoo")
    m.save(model_path)
    x = np.random.RandomState(0).randn(4).astype(np.float32)
    ref = np.asarray(m.predict(x[None], batch_size=1))[0]

    redis_port, http_port = _free_ports(2)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "zoo_tpu.serving.run", "--model", model_path,
         "--redis-port", str(redis_port), "--http-port", str(http_port),
         "--batch-size", "4"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", http_port),
                                              timeout=1):
                    break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()[-2000:]
                time.sleep(0.3)
        else:
            raise TimeoutError("serving CLI never opened the HTTP port")

        # redis-protocol path
        from zoo_tpu.serving.client import InputQueue, OutputQueue
        iq = InputQueue(host="127.0.0.1", port=redis_port)
        iq.enqueue("req1", t=x)
        oq = OutputQueue(host="127.0.0.1", port=redis_port)
        got = "[]"
        for _ in range(300):
            got = oq.query("req1")
            if not isinstance(got, str):
                break
            time.sleep(0.1)
        assert not isinstance(got, str), got
        np.testing.assert_allclose(
            np.asarray(got).reshape(-1), ref, atol=1e-4)

        # http path
        body = json.dumps(
            {"instances": [{"t": x.tolist()}]}).encode()
        resp = json.loads(urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{http_port}/predict", data=body,
                headers={"Content-Type": "application/json"}),
            timeout=60).read())
        val = json.loads(json.loads(resp["predictions"][0])["value"])
        pred = np.asarray(val["data"], np.float32).reshape(-1)
        np.testing.assert_allclose(pred, ref, atol=1e-4)

        metrics = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}/metrics", timeout=30).read())
        assert any("inference" in str(k) for k in metrics)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
