"""Hermetic multi-device test rig.

The reference's trick — run the *real* framework on a *local* multi-worker
topology (Spark ``local[4]``, single-node Ray; SURVEY §4.1/§4.3) — ports to
JAX as an 8-device virtual CPU mesh: every DP/FSDP/TP sharding test runs the
actual pjit/collective path in CI without TPUs.

Must set the env vars before jax is imported anywhere.
"""

import os

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The ambient environment may have force-registered a TPU backend via
# sitecustomize before this file runs; the config update below (post-import)
# wins regardless.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
os.environ.setdefault("ZOO_NUM_CORES", "4")

import pytest  # noqa: E402


@pytest.fixture()
def orca_ctx():
    """Function-scoped orca context over the 8-device CPU mesh (mirrors the
    reference's package-scoped ``init_orca_context(cores=4)`` conftest,
    ``test/zoo/orca/learn/spark/conftest.py:20-25``)."""
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    ctx = init_orca_context(cluster_mode="local", cores=4)
    yield ctx
    stop_orca_context()


@pytest.fixture()
def tmp_model_dir(tmp_path):
    return str(tmp_path / "model")
