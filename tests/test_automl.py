import numpy as np
import pandas as pd
import pytest

from zoo_tpu.automl import hp
from zoo_tpu.automl.search import LocalSearchEngine, _expand_configs
from zoo_tpu.orca.automl import AutoEstimator


def test_hp_samplers():
    rng = np.random.RandomState(0)
    assert hp.choice([1, 2, 3]).sample(rng) in (1, 2, 3)
    v = hp.uniform(0.0, 1.0).sample(rng)
    assert 0 <= v <= 1
    v = hp.loguniform(1e-4, 1e-1).sample(rng)
    assert 1e-4 <= v <= 1e-1
    assert 1 <= hp.randint(1, 5).sample(rng) < 5
    assert hp.grid_search([4, 8]).grid() == [4, 8]
    q = hp.quniform(1, 10, q=2).sample(rng)
    assert q % 2 == 0


def test_expand_configs_grid_cross():
    rng = np.random.RandomState(0)
    space = {"a": hp.grid_search([1, 2]), "b": hp.grid_search([10, 20]),
             "c": 7}
    cfgs = _expand_configs(space, n_sampling=3, rng=rng)
    assert len(cfgs) == 4  # pure grid dedupes n_sampling
    assert {(c["a"], c["b"]) for c in cfgs} == {(1, 10), (1, 20), (2, 10),
                                               (2, 20)}
    space["d"] = hp.uniform(0, 1)
    cfgs = _expand_configs(space, n_sampling=2, rng=rng)
    assert len(cfgs) == 8  # 4 grid points × 2 samples


def test_local_search_engine_minimizes():
    eng = LocalSearchEngine()
    eng.compile(lambda cfg: {"mse": (cfg["x"] - 3) ** 2},
                {"x": hp.grid_search([0, 1, 2, 3, 4])}, metric="mse",
                mode="min")
    eng.run()
    assert eng.get_best_trial().config["x"] == 3


def test_auto_estimator_keras(orca_ctx):
    rs = np.random.RandomState(0)
    x = rs.randn(128, 4).astype(np.float32)
    w = rs.randn(4, 1).astype(np.float32)
    y = x @ w

    def creator(config):
        from zoo_tpu.pipeline.api.keras import Sequential, optimizers
        from zoo_tpu.pipeline.api.keras.layers import Dense

        m = Sequential()
        m.add(Dense(config["hidden"], activation="relu", input_shape=(4,)))
        m.add(Dense(1))
        m.compile(optimizer=optimizers.Adam(lr=config["lr"]), loss="mse")
        return m

    auto = AutoEstimator.from_keras(model_creator=creator)
    auto.fit((x, y), epochs=3, batch_size=32,
             search_space={"hidden": hp.grid_search([4, 16]),
                           "lr": hp.choice([0.01])},
             metric="mse")
    best = auto.get_best_model()
    assert auto.get_best_config()["hidden"] in (4, 16)
    assert np.isfinite(auto.best_metric)
    assert best.predict(x[:8]).shape == (8, 1)


@pytest.mark.heavy
def test_autots_estimator(orca_ctx, tmp_path):
    from zoo_tpu.chronos.autots import AutoTSEstimator, TSPipeline
    from zoo_tpu.chronos.data import TSDataset

    t = pd.date_range("2024-01-01", periods=300, freq="h")
    v = np.sin(np.arange(300) * 2 * np.pi / 24)
    df = pd.DataFrame({"ts": t, "value": v})
    train, _, test = TSDataset.from_pandas(
        df, dt_col="ts", target_col="value", with_split=True,
        test_ratio=0.2)

    auto = AutoTSEstimator(model="lstm",
                           search_space={"hidden_dim": hp.grid_search([8]),
                                         "lr": hp.choice([0.01])},
                           past_seq_len=hp.grid_search([12]),
                           future_seq_len=1, metric="mse")
    pipeline = auto.fit(train, validation_data=test, epochs=2,
                        batch_size=32)
    assert isinstance(pipeline, TSPipeline)
    preds = pipeline.predict(test)
    assert preds.shape[1:] == (1, 1)
    res = pipeline.evaluate(test, metrics=["mse"])
    assert np.isfinite(res["mse"])

    pipeline.save(str(tmp_path / "pipe"))
    again = TSPipeline.load(str(tmp_path / "pipe"))
    np.testing.assert_allclose(preds, again.predict(test), rtol=1e-5)


def test_tpe_beats_random_equal_budget():
    """Seeded toy objective (quadratic bowl + categorical penalty): at an
    equal 40-trial budget, TPE's best must beat random's best on average
    across seeds (the model-based-search acceptance bar)."""
    from zoo_tpu.automl.search import LocalSearchEngine

    space = {"x": hp.uniform(-5.0, 5.0),
             "y": hp.loguniform(1e-3, 1e1),
             "k": hp.choice(["a", "b", "c"])}

    def objective(cfg):
        pen = {"a": 0.0, "b": 1.0, "c": 2.0}[cfg["k"]]
        return {"mse": (cfg["x"] - 1.7) ** 2
                + (np.log10(cfg["y"]) - (-1.0)) ** 2 + pen}

    tpe_wins, margins = 0, []
    for seed in range(5):
        rnd = LocalSearchEngine()
        rnd.compile(objective, space, n_sampling=40, metric="mse",
                    mode="min", seed=seed)
        rnd.run()
        best_rnd = rnd.get_best_trial().metric

        tpe = LocalSearchEngine(search_alg="tpe")
        tpe.compile(objective, space, n_sampling=40, metric="mse",
                    mode="min", seed=seed)
        tpe.run()
        best_tpe = tpe.get_best_trial().metric
        tpe_wins += best_tpe <= best_rnd
        margins.append(best_rnd - best_tpe)
    assert tpe_wins >= 4, (tpe_wins, margins)
    assert np.mean(margins) > 0, margins


def test_tpe_categorical_converges():
    from zoo_tpu.automl.tpe import TPESampler

    space = {"k": hp.choice([0, 1, 2, 3])}
    tpe = TPESampler(space, mode="min", n_startup=8)
    rng = np.random.RandomState(0)
    history = []
    for _ in range(40):
        cfg = tpe.suggest(rng, history)
        history.append((cfg, 0.0 if cfg["k"] == 2 else 1.0))
    late = [c["k"] for c, _ in history[-10:]]
    assert late.count(2) >= 6, late  # the model homes in on the optimum


def test_asha_stops_underperformers():
    """Trials report per-epoch; ASHA must cut clearly-bad trials at rung
    boundaries so they run fewer epochs than the good ones."""
    from zoo_tpu.automl.search import ASHAScheduler, LocalSearchEngine

    epochs_run = {}

    def trial(cfg, reporter=None):
        # quality is the config value itself: lower = better from epoch 1
        q = cfg["q"]
        steps = 0
        for e in range(9):
            steps = e + 1
            if reporter is not None and reporter(steps, q + 0.01 * e):
                break
        epochs_run[q] = steps
        return {"mse": q}

    eng = LocalSearchEngine(
        scheduler=ASHAScheduler(max_t=9, grace_period=1,
                                reduction_factor=3, mode="min"))
    eng.compile(trial, {"q": hp.grid_search(list(range(9)))},
                metric="mse", mode="min", seed=0)
    eng.run()
    assert eng.get_best_trial().config["q"] == 0
    good = epochs_run[0]
    worst = max(epochs_run[q] for q in (6, 7, 8))
    assert good == 9, epochs_run
    assert worst < 9, epochs_run  # the bad tail was cut early


@pytest.mark.heavy
def test_autots_accepts_search_alg_and_scheduler(orca_ctx):
    from zoo_tpu.chronos.autots import AutoTSEstimator, TSPipeline
    from zoo_tpu.chronos.data import TSDataset

    t = pd.date_range("2024-01-01", periods=200, freq="h")
    v = np.sin(np.arange(200) * 2 * np.pi / 24)
    df = pd.DataFrame({"ts": t, "value": v})
    train, _, test = TSDataset.from_pandas(
        df, dt_col="ts", target_col="value", with_split=True,
        test_ratio=0.2)
    auto = AutoTSEstimator(model="lstm",
                           search_space={"hidden_dim": hp.choice([8]),
                                         "lr": hp.loguniform(1e-3, 1e-2)},
                           past_seq_len=hp.randint(8, 16),
                           future_seq_len=1, metric="mse")
    pipeline = auto.fit(train, validation_data=test, epochs=2,
                        batch_size=32, n_sampling=3, search_alg="tpe",
                        scheduler="asha")
    assert isinstance(pipeline, TSPipeline)
    assert np.isfinite(pipeline.evaluate(test, metrics=["mse"])["mse"])


@pytest.mark.heavy
def test_auto_estimator_accepts_tpe(orca_ctx):
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    rs = np.random.RandomState(0)
    x = rs.randn(64, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)

    def creator(cfg):
        m = Sequential()
        m.add(Dense(int(cfg["hidden"]), input_shape=(4,),
                    activation="relu"))
        m.add(Dense(1))
        m.compile(optimizer="adam", loss="mse")
        return m

    est = AutoEstimator.from_keras(model_creator=creator)
    est.fit((x, y), epochs=2, batch_size=16, metric="mse",
            search_space={"hidden": hp.choice([4, 8])}, n_sampling=3,
            search_alg="tpe", scheduler="asha")
    assert np.isfinite(est.best_metric)
    assert est.get_best_config()["hidden"] in (4, 8)
