"""Concurrent AutoML trials over disjoint sub-meshes (SURVEY §7.4 #6 —
the TPU-native form of Ray Tune's parallel trials,
reference ``automl/search/ray_tune_search_engine.py:29,64-103``)."""

import threading
import time

import numpy as np
import pytest



# compile-bound on a 1-core box: the --all tier runs these
pytestmark = pytest.mark.heavy

def test_submesh_partition_and_concurrency(orca_ctx):
    """8 virtual devices / 4 concurrent trials: every trial runs under
    its own disjoint 2-device mesh, results match the sequential run,
    and wall-clock beats sequential."""
    import jax

    from zoo_tpu.automl import hp
    from zoo_tpu.automl.search import LocalSearchEngine
    from zoo_tpu.common.context import get_runtime_context

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")

    seen_meshes = []
    seen_lock = threading.Lock()

    def trial_fn(config):
        ctx = get_runtime_context()
        ids = tuple(d.id for d in ctx.devices)
        with seen_lock:
            seen_meshes.append(ids)
        # compute on THIS trial's mesh: a tiny jitted reduction placed
        # onto the sub-mesh devices proves the scope is honored
        from zoo_tpu.parallel.mesh import batch_sharding
        x = jax.device_put(np.ones((16, 4), np.float32) * config["a"],
                           batch_sharding(ctx.mesh, 2))
        val = float(jax.jit(lambda v: v.sum())(x))
        time.sleep(0.25)  # stands in for the input pipeline
        return {"mse": abs(val - 64.0)}

    space = {"a": hp.grid_search([0.5, 1.0, 2.0, 4.0])}

    seq = LocalSearchEngine(n_parallel=1)
    seq.compile(trial_fn, space, metric="mse", mode="min", seed=0)
    t0 = time.perf_counter()
    seq.run()
    t_seq = time.perf_counter() - t0
    best_seq = seq.get_best_trial()

    seen_meshes.clear()
    par = LocalSearchEngine(n_parallel=4, partition_devices=True)
    par.compile(trial_fn, space, metric="mse", mode="min", seed=0)
    t0 = time.perf_counter()
    par.run()
    t_par = time.perf_counter() - t0
    best_par = par.get_best_trial()

    # same winner as sequential
    assert best_par.config == best_seq.config == {"a": 1.0}
    # each concurrent trial saw a 2-device mesh; the groups are disjoint
    assert all(len(ids) == 2 for ids in seen_meshes)
    used = [set(ids) for ids in seen_meshes]
    for i in range(len(used)):
        for j in range(i + 1, len(used)):
            assert used[i] == used[j] or not (used[i] & used[j])
    assert len({tuple(sorted(s)) for s in used}) == 4
    # concurrency is real: 4 trials overlap their sleep windows
    assert t_par < t_seq, (t_par, t_seq)


def test_submesh_falls_back_when_too_few_devices(orca_ctx):
    """More parallel trials than devices: trials share the full mesh
    rather than failing."""
    import jax

    from zoo_tpu.automl import hp
    from zoo_tpu.automl.search import LocalSearchEngine
    from zoo_tpu.common.context import get_runtime_context

    n = len(jax.devices())

    def trial_fn(config):
        ctx = get_runtime_context()
        assert len(ctx.devices) == n  # ambient mesh, not a partition
        return {"mse": config["a"]}

    eng = LocalSearchEngine(n_parallel=n + 4, partition_devices=True)
    eng.compile(trial_fn, {"a": hp.grid_search([3.0, 1.0, 2.0])},
                metric="mse", mode="min", seed=0)
    eng.run()
    assert eng.get_best_trial().config == {"a": 1.0}


def test_autoestimator_concurrent_trials(orca_ctx):
    """The user surface: AutoEstimator.fit(n_parallel=4) searches over
    sub-meshes and returns the same best config as sequential."""
    from zoo.orca.automl.auto_estimator import AutoEstimator
    from zoo_tpu.automl import hp

    rs = np.random.RandomState(0)
    x = rs.randn(64, 6).astype(np.float32)
    y = (x @ rs.randn(6, 1)).astype(np.float32)

    def model_builder(config):
        from zoo.pipeline.api.keras.layers import Dense
        from zoo.pipeline.api.keras.models import Sequential
        from zoo.pipeline.api.keras.optimizers import Adam

        m = Sequential()
        m.add(Dense(int(config["hidden"]), input_shape=(6,),
                    activation="relu"))
        m.add(Dense(1))
        m.compile(optimizer=Adam(lr=config["lr"]), loss="mse")
        return m

    space = {"hidden": hp.grid_search([4, 8]),
             "lr": hp.grid_search([0.01, 0.001])}
    results = {}
    for n_parallel in (1, 4):
        est = AutoEstimator(model_builder=model_builder)
        est.fit((x, y), epochs=3, batch_size=32, metric="mse",
                search_space=dict(space), seed=0, n_parallel=n_parallel)
        results[n_parallel] = est.get_best_config()
        assert est.get_best_model() is not None
    # full-mesh vs sub-mesh runs differ in reduction order, so near-tied
    # hidden sizes may flip; the lr choice (10x apart) must agree
    assert results[1]["lr"] == results[4]["lr"] == 0.01


def test_autots_concurrent_path(orca_ctx):
    """AutoTS searches with concurrent sub-mesh trials."""
    import pandas as pd

    from zoo.chronos.autots import AutoTSEstimator
    from zoo.chronos.data import TSDataset
    from zoo_tpu.automl import hp

    n = 300
    df = pd.DataFrame({
        "datetime": pd.date_range("2020-01-01", periods=n, freq="h"),
        "value": np.sin(np.arange(n) / 6.0).astype(np.float32)})
    ds = TSDataset.from_pandas(df, dt_col="datetime",
                               target_col="value")
    est = AutoTSEstimator(model="lstm",
                          search_space={
                              "hidden_dim": hp.grid_search([8, 16]),
                              "lr": 0.01},
                          past_seq_len=12, future_seq_len=1)
    ppl = est.fit(ds, epochs=2, n_sampling=1, seed=0, n_parallel=2)
    pred = ppl.predict(ds)
    assert np.asarray(pred).ndim >= 2
