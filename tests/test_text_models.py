"""TFPark text models (NER / SequenceTagger / IntentEntity) —
tiny-shape convergence + serialization round trips + CRF math checks
(VERDICT r2 missing #3)."""

import itertools

import numpy as np
import jax.numpy as jnp
import pytest

from zoo_tpu.models.text import (
    NER,
    IntentEntity,
    SequenceTagger,
    crf_decode,
    crf_negative_log_likelihood,
)

T, W = 6, 4
VOCAB, CHARS = 40, 12


def _data(n=48, n_tags=4, seed=0):
    rs = np.random.RandomState(seed)
    words = rs.randint(0, VOCAB, (n, T)).astype(np.int32)
    chars = rs.randint(0, CHARS, (n, T, W)).astype(np.int32)
    # learnable rule: tag = word id mod n_tags
    tags = (words % n_tags).astype(np.int32)
    return words, chars, tags


# ------------------------------------------------------------------ CRF

def _pack(emissions, trans):
    b = emissions.shape[0]
    return jnp.concatenate(
        [jnp.asarray(emissions),
         jnp.broadcast_to(jnp.asarray(trans), (b,) + trans.shape)], axis=1)


def test_crf_nll_matches_bruteforce():
    rs = np.random.RandomState(0)
    e_dim, t_len = 3, 4
    em = rs.randn(2, t_len, e_dim).astype(np.float32)
    tr = rs.randn(e_dim, e_dim).astype(np.float32)
    y = rs.randint(0, e_dim, (2, t_len)).astype(np.int32)

    def score(b, path):
        s = sum(em[b, t, path[t]] for t in range(t_len))
        s += sum(tr[path[t], path[t + 1]] for t in range(t_len - 1))
        return s

    want = 0.0
    for b in range(2):
        logz = np.log(sum(
            np.exp(score(b, p))
            for p in itertools.product(range(e_dim), repeat=t_len)))
        want += logz - score(b, y[b])
    want /= 2
    got = float(crf_negative_log_likelihood(jnp.asarray(y), _pack(em, tr)))
    assert abs(got - want) < 1e-4, (got, want)


def test_crf_viterbi_matches_bruteforce():
    rs = np.random.RandomState(1)
    e_dim, t_len = 3, 5
    em = rs.randn(2, t_len, e_dim).astype(np.float32)
    tr = rs.randn(e_dim, e_dim).astype(np.float32)

    def best(b):
        return max(itertools.product(range(e_dim), repeat=t_len),
                   key=lambda p: sum(em[b, t, p[t]] for t in range(t_len))
                   + sum(tr[p[t], p[t + 1]] for t in range(t_len - 1)))

    got = np.asarray(crf_decode(_pack(em, tr)))
    for b in range(2):
        assert tuple(got[b]) == best(b)


# --------------------------------------------------------------- models

@pytest.mark.slow
def test_ner_crf_converges_and_roundtrips(tmp_path):
    words, chars, tags = _data(n_tags=4)
    m = NER(num_entities=4, word_vocab_size=VOCAB, char_vocab_size=CHARS,
            sequence_length=T, word_length=W, word_emb_dim=16,
            char_emb_dim=8, tagger_lstm_dim=16, dropout=0.0)
    from zoo_tpu.pipeline.api.keras.optimizers import Adam
    m.compile(optimizer=Adam(lr=0.02), loss=m.default_loss())
    h = m.fit([words, chars], tags, batch_size=16, nb_epoch=12, verbose=0)
    assert h["loss"][-1] < h["loss"][0] * 0.7, h["loss"]
    pred = m.predict_tags(words[:8], chars[:8])
    assert pred.shape == (8, T)
    acc = float((pred == tags[:8]).mean())
    assert acc > 0.5, acc

    p = str(tmp_path / "ner.zoo")
    m.save(p)
    m2 = NER.load_model(p)
    np.testing.assert_array_equal(m2.predict_tags(words[:8], chars[:8]),
                                  pred)


def test_ner_softmax_variant():
    words, chars, tags = _data(n=32)
    m = NER(num_entities=4, word_vocab_size=VOCAB, char_vocab_size=CHARS,
            sequence_length=T, word_length=W, word_emb_dim=8,
            char_emb_dim=8, tagger_lstm_dim=8, dropout=0.0,
            classifier="softmax")
    m.compile(optimizer="adam", loss=m.default_loss())
    m.fit([words, chars], tags, batch_size=16, nb_epoch=1, verbose=0)
    assert m.predict_tags(words[:4], chars[:4]).shape == (4, T)


def test_ner_rejects_pad_mode():
    with pytest.raises(ValueError, match="pad"):
        NER(4, VOCAB, CHARS, crf_mode="pad")


@pytest.mark.slow
def test_sequence_tagger_two_heads(tmp_path):
    words, chars, tags = _data(n=48, n_tags=3)
    chunk = (tags > 0).astype(np.int32)
    m = SequenceTagger(num_pos_labels=3, num_chunk_labels=2,
                       word_vocab_size=VOCAB, char_vocab_size=CHARS,
                       sequence_length=T, word_length=W, feature_size=12,
                       dropout=0.0)
    from zoo_tpu.pipeline.api.keras.optimizers import Adam
    m.compile(optimizer=Adam(lr=0.01), loss=m.default_loss())
    h = m.fit([words, chars], [tags, chunk], batch_size=16, nb_epoch=6,
              verbose=0)
    assert h["loss"][-1] < h["loss"][0], h["loss"]
    pos, chk = m.predict([words[:4], chars[:4]], batch_size=4)
    assert pos.shape == (4, T, 3) and chk.shape == (4, T, 2)

    p = str(tmp_path / "st.zoo")
    m.save(p)
    m2 = SequenceTagger.load_model(p)
    pos2, _ = m2.predict([words[:4], chars[:4]], batch_size=4)
    np.testing.assert_allclose(np.asarray(pos2), np.asarray(pos),
                               atol=1e-5)


def test_sequence_tagger_words_only():
    words, _, tags = _data(n=32, n_tags=3)
    chunk = (tags > 0).astype(np.int32)
    m = SequenceTagger(num_pos_labels=3, num_chunk_labels=2,
                       word_vocab_size=VOCAB, sequence_length=T,
                       feature_size=8, dropout=0.0)
    m.compile(optimizer="adam", loss=m.default_loss())
    m.fit(words, [tags, chunk], batch_size=16, nb_epoch=1, verbose=0)


@pytest.mark.heavy
def test_intent_entity_multitask(tmp_path):
    words, chars, tags = _data(n=48, n_tags=4)
    intent = (words.sum(axis=1) % 3).astype(np.int32)
    m = IntentEntity(num_intents=3, num_entities=4, word_vocab_size=VOCAB,
                     char_vocab_size=CHARS, sequence_length=T,
                     word_length=W, word_emb_dim=12, char_emb_dim=8,
                     char_lstm_dim=8, tagger_lstm_dim=12, dropout=0.0)
    m.compile(optimizer="adam", loss=m.default_loss())
    h = m.fit([words, chars], [intent, tags], batch_size=16, nb_epoch=4,
              verbose=0)
    assert np.isfinite(h["loss"]).all()
    iout, tout = m.predict([words[:4], chars[:4]], batch_size=4)
    assert iout.shape == (4, 3) and tout.shape == (4, T, 4)

    p = str(tmp_path / "ie.zoo")
    m.save(p)
    i2, _ = IntentEntity.load_model(p).predict([words[:4], chars[:4]],
                                               batch_size=4)
    np.testing.assert_allclose(np.asarray(i2), np.asarray(iout),
                               atol=1e-5)
