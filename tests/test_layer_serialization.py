"""Every exported layer round-trips through whole-model save/load with
identical inference output — the rebuild of the reference's Scala
``SerializerSpec`` (which runs save/load over every registered layer) on
the cloudpickle serialization path.

The spec table must cover every name in ``layers.__all__``; adding a new
layer without a row (or an explicit skip reason) fails the suite.
"""

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras import layers as L
from zoo_tpu.pipeline.api.keras.engine.topology import (
    Input,
    KerasNet,
    Model,
    Sequential,
)

_EMB_MAT = np.random.RandomState(0).randn(20, 6).astype(np.float32)

# name -> (constructor, input_shape (no batch), input kind)
SPEC = {
    # core
    "Activation": (lambda: L.Activation("relu"), (6,), "f"),
    "BatchNormalization": (lambda: L.BatchNormalization(), (6,), "f"),
    "Dense": (lambda: L.Dense(4), (6,), "f"),
    "SparseDense": (lambda: L.SparseDense(4), (6,), "f"),
    "SparseEmbedding": (lambda: L.SparseEmbedding(10, 4), (5,), "i"),
    "Mul": (lambda: L.Mul(), (6,), "f"),
    "Dropout": (lambda: L.Dropout(0.5), (6,), "f"),
    "Embedding": (lambda: L.Embedding(10, 4), (5,), "i"),
    "Flatten": (lambda: L.Flatten(), (2, 3), "f"),
    "GaussianNoise": (lambda: L.GaussianNoise(0.1), (6,), "f"),
    "Lambda": (lambda: L.Lambda(lambda x: x * 2.0), (6,), "f"),
    "Permute": (lambda: L.Permute((2, 1)), (3, 4), "f"),
    "RepeatVector": (lambda: L.RepeatVector(3), (6,), "f"),
    "Reshape": (lambda: L.Reshape((4, -1)), (2, 6), "f"),
    # convolutional
    "Conv1D": (lambda: L.Conv1D(4, 3), (8, 5), "f"),
    "Conv2D": (lambda: L.Conv2D(4, 3, 3), (3, 8, 8), "f"),
    "Cropping1D": (lambda: L.Cropping1D((1, 1)), (8, 5), "f"),
    "Cropping2D": (lambda: L.Cropping2D(((1, 1), (1, 1))), (3, 8, 8), "f"),
    "SpatialDropout1D": (lambda: L.SpatialDropout1D(0.5), (8, 5), "f"),
    "SpatialDropout2D": (lambda: L.SpatialDropout2D(0.5), (3, 6, 6), "f"),
    "UpSampling1D": (lambda: L.UpSampling1D(2), (4, 5), "f"),
    "UpSampling2D": (lambda: L.UpSampling2D((2, 2)), (3, 4, 4), "f"),
    "ZeroPadding1D": (lambda: L.ZeroPadding1D(1), (4, 5), "f"),
    "ZeroPadding2D": (lambda: L.ZeroPadding2D((1, 2)), (3, 4, 4), "f"),
    # pooling
    "AveragePooling1D": (lambda: L.AveragePooling1D(2), (8, 5), "f"),
    "AveragePooling2D": (lambda: L.AveragePooling2D((2, 2)),
                         (3, 8, 8), "f"),
    "GlobalAveragePooling1D": (lambda: L.GlobalAveragePooling1D(),
                               (8, 5), "f"),
    "GlobalAveragePooling2D": (lambda: L.GlobalAveragePooling2D(),
                               (3, 8, 8), "f"),
    "GlobalMaxPooling1D": (lambda: L.GlobalMaxPooling1D(), (8, 5), "f"),
    "GlobalMaxPooling2D": (lambda: L.GlobalMaxPooling2D(),
                           (3, 8, 8), "f"),
    "MaxPooling1D": (lambda: L.MaxPooling1D(2), (8, 5), "f"),
    "MaxPooling2D": (lambda: L.MaxPooling2D((2, 2)), (3, 8, 8), "f"),
    # recurrent
    "GRU": (lambda: L.GRU(4), (6, 5), "f"),
    "LSTM": (lambda: L.LSTM(4), (6, 5), "f"),
    "SimpleRNN": (lambda: L.SimpleRNN(4), (6, 5), "f"),
    "Bidirectional": (lambda: L.Bidirectional(L.LSTM(4)), (6, 5), "f"),
    "TimeDistributed": (lambda: L.TimeDistributed(L.Dense(4)),
                        (6, 5), "f"),
    # advanced activations
    "ELU": (lambda: L.ELU(), (6,), "f"),
    "Highway": (lambda: L.Highway(activation="relu"), (6,), "f"),
    "LeakyReLU": (lambda: L.LeakyReLU(0.1), (6,), "f"),
    "MaxoutDense": (lambda: L.MaxoutDense(3, nb_feature=2), (6,), "f"),
    "PReLU": (lambda: L.PReLU(), (6,), "f"),
    "SReLU": (lambda: L.SReLU(), (6,), "f"),
    "ThresholdedReLU": (lambda: L.ThresholdedReLU(0.5), (6,), "f"),
    # attention
    "LayerNorm": (lambda: L.LayerNorm(), (6,), "f"),
    "TransformerLayer": (lambda: L.TransformerLayer(
        vocab=16, seq_len=6, n_block=1, hidden_size=8, n_head=2,
        hidden_drop=0.0, attn_drop=0.0), (6,), "i"),
    "BERT": (lambda: L.BERT(
        vocab=16, hidden_size=8, n_block=1, n_head=2, seq_len=6,
        intermediate_size=16, hidden_p_drop=0.0, attn_p_drop=0.0,
        max_position_len=8), (6,), "i"),
    # extras
    "AddConstant": (lambda: L.AddConstant(1.0), (6,), "f"),
    "BinaryThreshold": (lambda: L.BinaryThreshold(0.0), (6,), "f"),
    "CAdd": (lambda: L.CAdd((6,)), (6,), "f"),
    "CMul": (lambda: L.CMul((6,)), (6,), "f"),
    "Exp": (lambda: L.Exp(), (6,), "f"),
    "ExpandDim": (lambda: L.ExpandDim(1), (6,), "f"),
    "GaussianDropout": (lambda: L.GaussianDropout(0.3), (6,), "f"),
    "GetShape": (lambda: L.GetShape(), (6,), "f"),
    "HardShrink": (lambda: L.HardShrink(0.5), (6,), "f"),
    "HardTanh": (lambda: L.HardTanh(), (6,), "f"),
    "Identity": (lambda: L.Identity(), (6,), "f"),
    "LRN2D": (lambda: L.LRN2D(), (3, 6, 6), "f"),
    "Log": (lambda: L.Log(), (6,), "pos"),
    "Masking": (lambda: L.Masking(0.0), (4, 6), "f"),
    "Max": (lambda: L.Max(1), (4, 6), "f"),
    "MulConstant": (lambda: L.MulConstant(2.0), (6,), "f"),
    "Narrow": (lambda: L.Narrow(1, 1, 3), (6,), "f"),
    "Negative": (lambda: L.Negative(), (6,), "f"),
    "Power": (lambda: L.Power(2.0, scale=2.0, shift=1.0), (6,), "pos"),
    "RReLU": (lambda: L.RReLU(), (6,), "f"),
    "ResizeBilinear": (lambda: L.ResizeBilinear(6, 6), (3, 4, 4), "f"),
    "Scale": (lambda: L.Scale((6,)), (6,), "f"),
    "Select": (lambda: L.Select(1, 2), (6,), "f"),
    "SoftShrink": (lambda: L.SoftShrink(0.5), (6,), "f"),
    "Sqrt": (lambda: L.Sqrt(), (6,), "pos"),
    "Square": (lambda: L.Square(), (6,), "f"),
    "Squeeze": (lambda: L.Squeeze(1), (1, 6), "f"),
    "Threshold": (lambda: L.Threshold(0.0, -7.0), (6,), "f"),
    "WithinChannelLRN2D": (lambda: L.WithinChannelLRN2D(),
                           (3, 6, 6), "f"),
    # conv extras
    "AtrousConvolution1D": (lambda: L.AtrousConvolution1D(
        4, 3, atrous_rate=2), (8, 5), "f"),
    "AtrousConvolution2D": (lambda: L.AtrousConvolution2D(
        4, 3, 3, atrous_rate=2), (3, 8, 8), "f"),
    "AveragePooling3D": (lambda: L.AveragePooling3D(), (2, 4, 4, 4), "f"),
    "ConvLSTM2D": (lambda: L.ConvLSTM2D(4, 3), (3, 2, 6, 6), "f"),
    "Convolution3D": (lambda: L.Convolution3D(4, 3, 3, 3),
                      (2, 5, 5, 5), "f"),
    "Cropping3D": (lambda: L.Cropping3D(), (2, 5, 5, 5), "f"),
    "Deconvolution2D": (lambda: L.Deconvolution2D(
        4, 3, 3, subsample=(2, 2)), (3, 6, 6), "f"),
    "DepthwiseConvolution2D": (lambda: L.DepthwiseConvolution2D(3, 3),
                               (3, 6, 6), "f"),
    "GlobalAveragePooling3D": (lambda: L.GlobalAveragePooling3D(),
                               (2, 4, 4, 4), "f"),
    "GlobalMaxPooling3D": (lambda: L.GlobalMaxPooling3D(),
                           (2, 4, 4, 4), "f"),
    "LocallyConnected1D": (lambda: L.LocallyConnected1D(4, 3), (8, 5),
                           "f"),
    "LocallyConnected2D": (lambda: L.LocallyConnected2D(4, 3, 3),
                           (3, 6, 6), "f"),
    "MaxPooling3D": (lambda: L.MaxPooling3D(), (2, 4, 4, 4), "f"),
    "SeparableConvolution2D": (lambda: L.SeparableConvolution2D(6, 3, 3),
                               (3, 6, 6), "f"),
    "ShareConvolution2D": (lambda: L.ShareConvolution2D(4, 3, 3),
                           (3, 8, 8), "f"),
    "SpatialDropout3D": (lambda: L.SpatialDropout3D(0.5),
                         (2, 4, 4, 4), "f"),
    "UpSampling3D": (lambda: L.UpSampling3D(), (2, 3, 3, 3), "f"),
    "WordEmbedding": (lambda: L.WordEmbedding(_EMB_MAT), (5,), "i"),
    "ZeroPadding3D": (lambda: L.ZeroPadding3D(), (2, 3, 3, 3), "f"),
}

# structural symbols, pure aliases, and functional-only layers get an
# explicit reason instead of a row
SKIP = {
    "InputLayer": "structural placeholder, exercised by every model",
    "Merge": "multi-input functional layer — covered below",
    "merge": "function alias of Merge",
    "GaussianSampler": "two-input VAE sampler — covered below",
    "Convolution1D": "alias of Conv1D",
    "Convolution2D": "alias of Conv2D",
    "Input": "tensor factory function, not a layer",
    "KerasLayerWrapper": "tf.keras-layer conversion factory (returns a "
                         "bridged layer; covered by the keras bridge "
                         "tests)",
}


def test_spec_covers_every_layer():
    missing = [n for n in L.__all__ if n not in SPEC and n not in SKIP]
    assert not missing, f"layers without a serialization spec: {missing}"


def _input_for(shape, kind, n=3):
    rs = np.random.RandomState(7)
    if kind == "i":
        return rs.randint(0, 10, (n,) + shape).astype(np.int32)
    x = rs.randn(n, *shape).astype(np.float32)
    return np.abs(x) + 0.1 if kind == "pos" else x


@pytest.mark.parametrize("name", sorted(SPEC))
def test_layer_roundtrip(name, tmp_path):
    ctor, shape, kind = SPEC[name]
    m = Sequential(name=f"ser_{name}")
    layer = ctor()
    layer.input_shape = (None,) + shape
    m.add(layer)
    x = _input_for(shape, kind)
    y0 = np.asarray(m.predict(x, batch_size=3))
    p = str(tmp_path / "m.zoo")
    m.save(p)
    m2 = KerasNet.load(p)
    y1 = np.asarray(m2.predict(x, batch_size=3))
    np.testing.assert_allclose(y1, y0, atol=1e-5,
                               err_msg=f"{name} changed after save/load")


def test_merge_and_sampler_roundtrip(tmp_path):
    a, b = Input(shape=(4,)), Input(shape=(4,))
    out = L.merge([a, b], mode="concat")
    g = Model(input=[a, b], output=L.Dense(2)(out))
    xs = [np.random.RandomState(1).randn(3, 4).astype(np.float32)
          for _ in range(2)]
    y0 = np.asarray(g.predict(xs, batch_size=3))
    p = str(tmp_path / "g.zoo")
    g.save(p)
    y1 = np.asarray(KerasNet.load(p).predict(xs, batch_size=3))
    np.testing.assert_allclose(y1, y0, atol=1e-5)

    mean, logv = Input(shape=(4,)), Input(shape=(4,))
    vae = Model(input=[mean, logv],
                output=L.GaussianSampler()([mean, logv]))
    y = np.asarray(vae.predict(xs, batch_size=3))  # eval: mean passthrough
    assert y.shape == (3, 4)


def test_load_weights_structure_mismatch_raises(tmp_path):
    """Position-keyed params must never silently mis-restore (round-1
    weak point #9): structure changes are hard errors."""
    m = Sequential(name="ckpt_a")
    m.add(L.Dense(8, input_shape=(4,)))
    m.add(L.Dense(2))
    m.build()
    p = str(tmp_path / "w.pkl")
    m.save_weights(p)

    # layer inserted -> different keys
    m2 = Sequential(name="ckpt_b")
    m2.add(L.Dense(8, input_shape=(4,)))
    m2.add(L.Activation("relu"))
    m2.add(L.Dense(2))
    m2.build()
    with pytest.raises(ValueError, match="structure"):
        m2.load_weights(p)

    # same topology, different width -> shape mismatch
    m3 = Sequential(name="ckpt_c")
    m3.add(L.Dense(16, input_shape=(4,)))
    m3.add(L.Dense(2))
    m3.build()
    with pytest.raises(ValueError, match="structure"):
        m3.load_weights(p)

    # matching model restores fine
    m4 = Sequential(name="ckpt_d")
    m4.add(L.Dense(8, input_shape=(4,)))
    m4.add(L.Dense(2))
    m4.build()
    m4.load_weights(p)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m4.predict(x, batch_size=3)),
                               np.asarray(m.predict(x, batch_size=3)),
                               atol=1e-6)


def test_load_weights_validates_unbuilt_model(tmp_path):
    """An unbuilt model with inferable shapes builds itself to validate."""
    m = Sequential(name="ckpt_e")
    m.add(L.Dense(8, input_shape=(4,)))
    m.build()
    p = str(tmp_path / "w2.pkl")
    m.save_weights(p)
    wrong = Sequential(name="ckpt_f")
    wrong.add(L.Dense(16, input_shape=(4,)))  # never built
    with pytest.raises(ValueError, match="structure"):
        wrong.load_weights(p)
