"""Chaos tests: crash-safety of CheckpointManager under SIGKILL.

The elastic supervisor (``run_elastic``) tears down a failed world and
relaunches it "resuming from the latest checkpoint" — so a worker killed
at ANY instant during ``save`` must never poison ``restore``. Each test
forks a real child process, SIGKILLs it at a chosen (or random) point
mid-save via the fault-injection registry, then asserts the parent
restores the newest VERIFIED step with intact content.

The children run the pickle codec (orbax is disabled pre-fork: its async
machinery is not fork-safe, and the crash protocol under test — temp dir,
fsync, manifest, atomic rename — is codec-independent).
"""

import json
import os
import signal

import numpy as np
import pytest

from zoo_tpu.orca.learn.ckpt import (
    MANIFEST,
    CheckpointCorruptError,
    CheckpointManager,
)
from zoo_tpu.util.resilience import default_injector

# forked children run pure file I/O (pickle + rename) then os._exit —
# they never touch JAX's thread pools, so its fork warning doesn't apply
pytestmark = [
    pytest.mark.chaos,
    pytest.mark.filterwarnings(
        "ignore:os.fork\\(\\) was called:RuntimeWarning"),
]

KILL_SITES = ["ckpt.pre_write", "ckpt.pre_manifest", "ckpt.pre_rename"]


def _mgr(tmp_path):
    m = CheckpointManager(str(tmp_path / "ck"))
    m._ckptr = None  # pickle codec: fork-safe (see module docstring)
    m._ocp = None
    return m


def _state(step):
    return {"step": step,
            "w": np.full((64, 64), float(step), np.float32)}


def _assert_step(state, step):
    assert state["step"] == step
    np.testing.assert_array_equal(
        state["w"], np.full((64, 64), float(step), np.float32))


def _fork_save_and_kill(mgr, step, site):
    """Fork; the child arms a self-SIGKILL at ``site`` and saves ``step``.
    Returns once the child is dead."""
    pid = os.fork()
    if pid == 0:  # child — never touch pytest machinery, never return
        try:
            default_injector.inject(
                site, action=lambda **_: os.kill(os.getpid(),
                                                 signal.SIGKILL))
            mgr.save(step, _state(step))
        finally:
            os._exit(0)  # only reached if the kill site never fired
    _, status = os.waitpid(pid, 0)
    return status


@pytest.mark.parametrize("site", KILL_SITES)
def test_sigkill_mid_save_preserves_previous_step(tmp_path, site):
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    status = _fork_save_and_kill(mgr, 2, site)
    assert os.WIFSIGNALED(status) and os.WTERMSIG(status) == signal.SIGKILL

    _assert_step(mgr.restore(), 1)  # never raises, never step-2 debris
    assert mgr.latest_verified_step() == 1


def test_sigkill_after_rename_commits_the_step(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    status = _fork_save_and_kill(mgr, 2, "ckpt.post_rename")
    assert os.WIFSIGNALED(status)
    # rename happened before the kill: step 2 is fully committed
    _assert_step(mgr.restore(), 2)
    assert mgr.latest_verified_step() == 2


def test_sigkill_at_random_instants_never_corrupts_resume(tmp_path):
    """Timing-based kills: the child saves steps continuously while the
    parent SIGKILLs it after an arbitrary delay. Whatever the instant,
    restore() must yield SOME verified step with self-consistent
    content."""
    import time

    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    for i, delay_ms in enumerate([2, 5, 9, 14, 23]):
        pid = os.fork()
        if pid == 0:  # child: hammer saves until killed
            try:
                step = 2
                while True:
                    mgr.save(step, _state(step))
                    step += 1
            finally:
                os._exit(0)
        time.sleep(delay_ms / 1000.0)
        os.kill(pid, signal.SIGKILL)
        os.waitpid(pid, 0)

        state = mgr.restore()
        _assert_step(state, state["step"])  # content matches its step
        assert state["step"] >= 1


def test_stale_staging_dirs_are_garbage_collected(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    _fork_save_and_kill(mgr, 2, "ckpt.pre_rename")
    # the killed child's staging dir may linger; the next save's GC
    # removes it once the owning pid is gone
    mgr.save(3, _state(3))
    leftovers = [n for n in os.listdir(mgr.directory)
                 if n.startswith(".tmp-")]
    assert leftovers == []
    _assert_step(mgr.restore(), 3)


def test_bitrot_quarantined_and_older_step_restored(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    mgr.save(2, _state(2))
    with open(os.path.join(mgr.directory, "2", "state.pkl"), "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad\xbe\xef")  # flip bytes: size same, hash not

    _assert_step(mgr.restore(), 1)
    names = os.listdir(mgr.directory)
    assert "2.corrupt" in names and "2" not in names  # quarantined
    # explicit request for the corrupt step fails loudly, never silently
    with pytest.raises((CheckpointCorruptError, FileNotFoundError)):
        mgr.restore(2)


def test_missing_manifest_file_is_incomplete(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    mgr.save(2, _state(2))
    os.remove(os.path.join(mgr.directory, "2", "state.pkl"))
    # manifest promises state.pkl; its absence means a torn step
    _assert_step(mgr.restore(), 1)
    assert mgr.latest_verified_step() == 1


def test_truncated_manifest_is_corrupt(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1))
    mgr.save(2, _state(2))
    mpath = os.path.join(mgr.directory, "2", MANIFEST)
    with open(mpath, "r+b") as f:
        f.truncate(os.path.getsize(mpath) // 2)
    _assert_step(mgr.restore(), 1)


def test_legacy_manifestless_checkpoint_still_restores(tmp_path):
    """Steps written before the manifest era have no manifest.json; they
    predate the atomic-rename protocol so presence implies completion —
    they must keep restoring (no quarantine of old training runs)."""
    import pickle

    mgr = _mgr(tmp_path)
    legacy = os.path.join(mgr.directory, "7")
    os.makedirs(legacy)
    with open(os.path.join(legacy, "state.pkl"), "wb") as f:
        pickle.dump(_state(7), f)
    _assert_step(mgr.restore(), 7)
    assert mgr.latest_verified_step() == 7


def test_restore_aux_follows_verified_step(tmp_path):
    """restore() falling back to step N must pair with restore_aux()
    from the SAME step — params and optimizer state from different
    snapshots would silently diverge the trajectory."""
    mgr = _mgr(tmp_path)
    mgr.save(1, _state(1), aux={"moment": np.ones(3)})
    mgr.save(2, _state(2), aux={"moment": np.full(3, 2.0)})
    with open(os.path.join(mgr.directory, "2", "state.pkl"), "r+b") as f:
        f.write(b"garbage")
    _assert_step(mgr.restore(), 1)
    aux = mgr.restore_aux()
    np.testing.assert_array_equal(aux["moment"], np.ones(3))
