"""C++ native runtime: TFRecord I/O + tiered cache (and their Python
fallbacks — both paths are exercised)."""

import os
import pickle

import numpy as np
import pytest

from zoo_tpu import native
from zoo_tpu.orca.data import tfrecord as tfr
from zoo_tpu.orca.data.cache import (CachedDataset, DoubleBufferedIterator,
                                     TieredSampleCache)


def test_native_library_builds():
    assert native.available(), "g++ build of native/zoo_native.cc failed"


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert tfr.crc32c(b"") == 0x0
    assert tfr.crc32c(b"123456789") == 0xE3069283
    assert tfr.crc32c(bytes(32)) == 0x8A9136AA


def test_tfrecord_roundtrip_native(tmp_path):
    path = str(tmp_path / "data.tfrecord")
    recs = [os.urandom(n) for n in (1, 10, 1000, 65536)]
    tfr.write_tfrecord(path, recs)
    back = tfr.read_tfrecord(path)
    assert back == recs


def test_tfrecord_matches_tensorflow_format(tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "ours.tfrecord")
    recs = [b"hello", b"world" * 100]
    tfr.write_tfrecord(path, recs)
    got = [bytes(r.numpy()) for r in tf.data.TFRecordDataset(path)]
    assert got == recs
    # and read TF-written files back
    path2 = str(tmp_path / "tf.tfrecord")
    with tf.io.TFRecordWriter(path2) as w:
        for r in recs:
            w.write(r)
    assert tfr.read_tfrecord(path2) == recs


def test_tfrecord_python_fallback_interops(tmp_path, monkeypatch):
    path = str(tmp_path / "n.tfrecord")
    recs = [b"abc", os.urandom(500)]
    tfr.write_tfrecord(path, recs)  # native write
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_lib_tried", True)
    assert not native.available()
    assert tfr.read_tfrecord(path) == recs  # python read
    path2 = str(tmp_path / "p.tfrecord")
    tfr.write_tfrecord(path2, recs)  # python write
    monkeypatch.setattr(native, "_lib_tried", False)
    assert native.available()
    assert tfr.read_tfrecord(path2) == recs  # native read


def test_tfrecord_corruption_detected(tmp_path):
    path = str(tmp_path / "c.tfrecord")
    tfr.write_tfrecord(path, [b"x" * 100])
    raw = bytearray(open(path, "rb").read())
    raw[40] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(tfr.TFRecordCorruptError):
        tfr.read_tfrecord(path)
    assert len(tfr.read_tfrecord(path, check_crc=False)) == 1


def test_tfrecord_shards(tmp_path):
    for i in range(3):
        tfr.write_tfrecord(str(tmp_path / f"part-{i}.tfrecord"),
                           [f"rec{i}-{j}".encode() for j in range(4)])
    shards = tfr.read_tfrecord_shards(str(tmp_path / "part-*.tfrecord"))
    assert shards.num_partitions() == 3
    flat = [r for part in shards.collect() for r in part]
    assert len(flat) == 12


@pytest.mark.parametrize("force_python", [False, True])
def test_tiered_cache_spills_and_reads_back(tmp_path, force_python,
                                            monkeypatch):
    if force_python:
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_tried", True)
    rs = np.random.RandomState(0)
    batches = [rs.randn(8, 4).astype(np.float32) for _ in range(20)]
    blob = pickle.dumps(batches[0], protocol=pickle.HIGHEST_PROTOCOL)
    # budget fits ~5 blobs → the rest must spill to disk
    cache = TieredSampleCache(dram_budget=len(blob) * 5,
                              spill_dir=str(tmp_path))
    ids = [cache.put(b) for b in batches]
    assert ids == list(range(20))
    assert len(cache) == 20
    assert cache.dram_used() <= len(blob) * 5
    for i in (0, 4, 5, 19, 7):  # DRAM entries and spilled entries
        np.testing.assert_array_equal(cache.get(i), batches[i])
    cache.close()


def test_cache_dram_mode_no_spill():
    cache = TieredSampleCache(store="DRAM")
    for i in range(10):
        cache.put({"x": np.arange(i + 1)})
    np.testing.assert_array_equal(cache.get(3)["x"], np.arange(4))
    cache.close()


def test_disk_tier_from_context_flag():
    from zoo_tpu.common.context import ZooContext
    old = ZooContext.train_data_store
    try:
        ZooContext.train_data_store = "DISK_4"
        cache = TieredSampleCache(total_bytes_hint=4000)
        assert cache._budget == 1000
        cache.close()
    finally:
        ZooContext.train_data_store = old


def test_cached_dataset_epochs():
    data = [np.full((2, 2), i) for i in range(5)]
    ds = CachedDataset(data, store="DRAM")
    for _ in range(2):  # two epochs, same content
        got = list(ds)
        assert len(got) == 5
        np.testing.assert_array_equal(got[3], data[3])
    ds.close()


def test_double_buffered_iterator_order_and_staging():
    staged = []

    def stage(x):
        staged.append(x)
        return x * 10

    out = list(DoubleBufferedIterator(range(50), stage_fn=stage))
    assert out == [i * 10 for i in range(50)]
    assert staged == list(range(50))


def test_double_buffered_iterator_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = DoubleBufferedIterator(gen())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)
