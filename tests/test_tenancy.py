"""Multi-tenant QoS (docs/multitenancy.md): tenant spec parsing,
token-bucket admission with per-tenant retry hints, the weighted-fair /
priority-class scheduler, per-tenant prefix-cache partitions, the HA
client's per-tenant A/B pins + rate backoff, and the per-tenant SLO
burn evaluator — all against jax-free fakes, so the file is tier-1
cheap.

The two acceptance bits asserted here:

* **isolation** — one greedy tenant's flood never inflates another
  tenant's retry hint, never evicts its cached prefixes while other
  supply exists, and never delays its client-side attempts;
* **bit-identity off** — with no tenant config (or all-unlabeled
  traffic) every admission, scheduling, and hashing decision is exactly
  the pre-tenancy one, asserted byte-for-byte against a disabled-QoS
  reference run.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from zoo_tpu.serving.llm.engine import (
    AdmissionError,
    LLMEngine,
    _tenant_preempted,
)
from zoo_tpu.serving.llm.kv_cache import (
    BlockAllocator,
    _cross_evictions,
    prefix_block_hashes,
)
from zoo_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenantRegistry,
    _TokenBucket,
    parse_tenant_spec,
    registry,
    reset_registry,
)


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch):
    """Tenancy off by default for every test: no env config, and the
    process singleton dropped so it re-reads the (clean) environment.
    Tests that want QoS inject an explicit TenantRegistry."""
    for var in ("ZOO_TENANT_CONFIG", "ZOO_QOS", "ZOO_TENANT",
                "ZOO_TENANT_AB_PINS"):
        monkeypatch.delenv(var, raising=False)
    reset_registry(None)
    yield
    reset_registry(None)


# ----------------------------------------------------------- spec parsing

def test_parse_tenant_spec_fields():
    cfgs = parse_tenant_spec(
        "gold:weight=4,class=0,rate=50,burst=100,kv=64,slots=2;"
        "free:rate=5")
    g = cfgs["gold"]
    assert g.weight == 4.0 and g.priority == 0
    assert g.rate == 50.0 and g.burst == 100.0
    assert g.max_kv_blocks == 64 and g.max_slots == 2
    f = cfgs["free"]
    assert f.rate == 5.0
    assert f.weight == 1.0 and f.priority == 1          # defaults
    assert f.max_kv_blocks == 0 and f.max_slots == 0    # unlimited


def test_parse_tenant_spec_malformed_entries_skipped():
    cfgs = parse_tenant_spec(
        "good:rate=5;:rate=1;bad:nope=3;worse:rate=abc;also_good")
    # malformed entries warn-and-skip; the well-formed survive
    assert set(cfgs) == {"good", "also_good"}
    assert cfgs["good"].rate == 5.0
    assert cfgs["also_good"].rate == 0.0


def test_parse_tenant_spec_respects_defaults():
    cfgs = parse_tenant_spec("a;b:weight=9", default_weight=2.0,
                             default_class=3, default_rate=7.0)
    assert cfgs["a"].weight == 2.0 and cfgs["a"].priority == 3
    assert cfgs["a"].rate == 7.0
    assert cfgs["b"].weight == 9.0 and cfgs["b"].priority == 3


# ----------------------------------------------------------- token bucket

def test_token_bucket_admission_and_hint():
    b = _TokenBucket(rate=10.0, burst=2.0)
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()               # burst spent
    hint = b.retry_after_ms()
    assert 0 < hint <= 200                   # ~100ms to refill 1 @ 10/s
    # unlimited bucket: always admits, zero hint
    u = _TokenBucket(rate=0.0)
    for _ in range(100):
        assert u.try_acquire()
    assert u.retry_after_ms() == 0


def test_token_bucket_refills():
    b = _TokenBucket(rate=200.0, burst=1.0)
    assert b.try_acquire() and not b.try_acquire()
    time.sleep(0.02)                         # 200/s -> ~4 tokens, cap 1
    assert b.try_acquire()


# ----------------------------------------------- registry enable / salt

def test_registry_disabled_without_config():
    assert registry().enabled is False       # clean env singleton
    assert TenantRegistry(spec="", qos=True).enabled is False
    assert TenantRegistry(spec="a:rate=1", qos=False).enabled is False
    assert TenantRegistry(spec="a:rate=1", qos=True).enabled is True


def test_registry_disabled_is_inert():
    reg = TenantRegistry(spec="", qos=True)
    assert reg.admit("anyone") == (True, 0)
    assert reg.salt("anyone") == b""
    # unknown tenants map to the default config
    assert reg.config("nobody").name == DEFAULT_TENANT


def test_registry_salt_partitions_prefix_hashes():
    reg = TenantRegistry(spec="a:rate=0;b:rate=0", qos=True)
    tokens = list(range(8))
    ha = prefix_block_hashes(tokens, 4, salt=reg.salt("a"))
    hb = prefix_block_hashes(tokens, 4, salt=reg.salt("b"))
    h0 = prefix_block_hashes(tokens, 4, salt=reg.salt(None))
    # distinct tenants can never collide; unlabeled == pre-tenancy
    assert ha != hb and ha != h0 and hb != h0
    assert h0 == prefix_block_hashes(tokens, 4)
    assert reg.salt(DEFAULT_TENANT) == b""


def test_retry_hint_is_per_tenant():
    """Satellite regression: a shed for tenant A is hinted from A's
    OWN bucket refill — B's hint stays its own (fundable) clock."""
    reg = TenantRegistry(spec="greedy:rate=0.001,burst=1;victim:rate=1000",
                         qos=True)
    ok, hint = reg.admit("greedy")
    assert ok and hint == 0
    ok, hint = reg.admit("greedy")           # burst of 1 is spent
    assert not ok and hint > 100_000         # ~1000s at 0.001/s
    # the flood changed NOTHING for the victim
    ok, hint = reg.admit("victim")
    assert ok and hint == 0
    assert reg.bucket("victim").retry_after_ms() == 1


# --------------------------------------------------- fake engine harness

class _FakeModel:
    """Deterministic jax-free model with the PagedLlamaModel surface
    (same contract as test_kv_prefix's): the next token is a pure
    function of (last token, position), so streams are byte-comparable
    across QoS on/off and across preempt-resume."""

    def __init__(self, num_slots=2, block_size=4, num_blocks=32,
                 max_blocks_per_seq=8, max_prompt_len=24):
        self.num_slots = num_slots
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks_per_seq
        self.max_context = block_size * max_blocks_per_seq
        self.max_prompt_len = max_prompt_len
        self.prefill_chunk_size = 0
        self.suffix_chunk_size = block_size
        self.eos_id = None

    @staticmethod
    def _next(tok, pos):
        return (2 * int(tok) + int(pos)) % 97

    def prefill(self, prompt, row, sampling=None):
        return self._next(prompt[-1], len(prompt))

    def prefill_chunk(self, chunk, start, total_len, row, sampling=None):
        return self._next(chunk[-1], total_len)

    def copy_block(self, src, dst):
        pass

    def decode(self, tokens, block_tables, positions, sampling=None):
        return np.array([self._next(t, p + 1)
                         for t, p in zip(tokens, positions)], np.int32)


def _tick(eng):
    eng._sweep()
    eng._admit()
    eng._prefill_tick()
    eng._grow_or_preempt()
    eng._decode_tick()


def _run_to_completion(eng, handles, ticks=400):
    for _ in range(ticks):
        _tick(eng)
        if all(h.done for h in handles):
            return
    raise AssertionError(
        [(h.outcome, h.error, list(h.tokens)) for h in handles])


def _reference(prompt, max_new):
    """Solo greedy run on a roomy single-tenant engine — the byte
    oracle every QoS-scheduled stream must still match."""
    eng = LLMEngine(_FakeModel(num_blocks=64, num_slots=1),
                    tenancy=TenantRegistry(spec="", qos=False))
    h = eng.submit(prompt, max_new, rid="ref")
    _run_to_completion(eng, [h])
    assert h.outcome == "ok"
    return list(h.tokens)


# -------------------------------------------------- engine admission QoS

def test_engine_rate_shed_and_queue_hint_isolation():
    """Satellite regression at the engine door: the greedy tenant's
    rate shed carries ITS refill hint; a victim shed on queue depth a
    moment later gets the generic backlog hint, not greedy's."""
    reg = TenantRegistry(spec="greedy:rate=0.001,burst=1;victim:rate=0",
                         qos=True)
    eng = LLMEngine(_FakeModel(), max_waiting=2, tenancy=reg)
    eng.submit([1, 2, 3], 4, rid="g1", tenant="greedy")
    with pytest.raises(AdmissionError) as ei:
        eng.submit([1, 2, 3], 4, rid="g2", tenant="greedy")
    assert ei.value.reason == "rate"
    assert ei.value.tenant == "greedy"
    assert ei.value.retry_after_ms > 100_000
    # a duplicate id joins the live stream — never re-billed, so the
    # HA client's retries / failover resumes can't drain the bucket
    assert eng.submit([1, 2, 3], 4, rid="g1", tenant="greedy") is \
        eng.get("g1")
    # victim admits freely...
    eng.submit([4, 5, 6], 4, rid="v1", tenant="victim")
    # ...until the queue bound, where its hint is the generic backlog
    # figure — NOT the greedy tenant's ~1000s refill
    with pytest.raises(AdmissionError) as ei2:
        eng.submit([7, 8, 9], 4, rid="v2", tenant="victim")
    assert ei2.value.retry_after_ms == 200


def test_engine_unlabeled_traffic_bit_identical():
    """The acceptance bit: with tenancy disabled — or enabled with all
    traffic unlabeled — admission order is plain FIFO and every stream
    is byte-identical to the pre-tenancy engine."""
    prompts = [[1, 2, 3, 4], [9, 8, 7], [5, 5, 5, 5, 5], [2, 4]]

    def run(reg):
        eng = LLMEngine(_FakeModel(num_slots=1), tenancy=reg)
        hs = [eng.submit(p, 5, rid=f"r{i}")
              for i, p in enumerate(prompts)]
        _run_to_completion(eng, hs)
        return [list(h.tokens) for h in hs], \
            [h.admit_seq for h in hs], eng.stats()

    off_toks, off_order, off_st = run(TenantRegistry(spec="", qos=True))
    on_toks, on_order, on_st = run(
        TenantRegistry(spec="gold:weight=4,class=0,rate=50", qos=True))
    assert off_st["qos"] is False and on_st["qos"] is True
    assert off_order == [1, 2, 3, 4] == on_order     # FIFO both ways
    assert on_toks == off_toks
    assert off_toks == [_reference(p, 5) for p in prompts]


# ------------------------------------------------- weighted-fair picking

def test_pop_next_waiter_priority_then_deficit_then_fifo():
    reg = TenantRegistry(
        spec="paid:class=0,weight=1;a:weight=3;b:weight=1", qos=True)
    eng = LLMEngine(_FakeModel(num_slots=4), tenancy=reg)
    ha1 = eng.submit([1, 2], 4, rid="a1", tenant="a")
    ha2 = eng.submit([1, 2], 4, rid="a2", tenant="a")
    hb = eng.submit([3, 4], 4, rid="b1", tenant="b")
    hp = eng.submit([5, 6], 4, rid="p1", tenant="paid")
    # lowest priority class wins outright, whatever the deficit says
    eng._tenant_served = {"paid": 10_000, "a": 0, "b": 0}
    with eng._lock:
        assert eng._pop_next_waiter() is hp
    # equal class: lowest served/weight — a at 29/3 beats b at 11/1
    eng._tenant_served = {"a": 29, "b": 11}
    with eng._lock:
        assert eng._pop_next_waiter() is ha1     # FIFO within tenant
    eng._tenant_served = {"a": 34, "b": 11}      # now a at 11.3 loses
    with eng._lock:
        assert eng._pop_next_waiter() is hb
    with eng._lock:
        assert eng._pop_next_waiter() is ha2
    with eng._lock:
        assert eng._pop_next_waiter() is None


def test_slot_quota_skips_tenant_without_blocking_queue():
    """A tenant at its slot cap is skipped IN PLACE: its second stream
    waits, but the tenant behind it admits immediately — no
    head-of-line blocking."""
    reg = TenantRegistry(spec="capped:slots=1;other:rate=0", qos=True)
    eng = LLMEngine(_FakeModel(num_slots=2), tenancy=reg)
    c1 = eng.submit([1, 2, 3], 6, rid="c1", tenant="capped")
    c2 = eng.submit([1, 2, 3], 6, rid="c2", tenant="capped")
    o1 = eng.submit([4, 5, 6], 3, rid="o1", tenant="other")
    _tick(eng)
    live = {s.handle.id for s in eng._slots if s.handle is not None}
    assert live == {"c1", "o1"}
    assert eng.stats()["tenants"]["capped"]["waiting"] == 1
    # the cap is a cap, not a wedge: c2 runs once c1's slot frees
    _run_to_completion(eng, [c1, c2, o1])
    assert [h.outcome for h in (c1, c2, o1)] == ["ok"] * 3
    assert c2.admit_seq > o1.admit_seq


def test_kv_quota_skips_tenant_without_blocking_queue():
    reg = TenantRegistry(spec="capped:kv=2;other:rate=0", qos=True)
    eng = LLMEngine(_FakeModel(num_slots=2, block_size=4),
                    tenancy=reg)
    # 9 prompt tokens + 1 decode token -> 3 blocks > the kv=2 cap
    big = eng.submit(list(range(1, 10)), 2, rid="big", tenant="capped")
    ok = eng.submit([4, 5, 6], 3, rid="ok", tenant="other")
    small = eng.submit([7, 8], 3, rid="small", tenant="capped")
    for _ in range(200):
        _tick(eng)
        if ok.done and small.done:
            break
    # over-quota stream parks; within-quota traffic flows around it
    assert ok.outcome == "ok" and small.outcome == "ok"
    assert not big.done
    assert eng.stats()["tenants"]["capped"]["waiting"] == 1


def test_weighted_fair_victim_jumps_greedy_backlog():
    """num_slots=1 and a greedy tenant's 3-deep backlog ahead of the
    victim in the queue: the deficit scheduler admits the victim right
    after greedy's FIRST stream (served/weight resets the race), and
    the victim's bytes are untouched by the reordering."""
    reg = TenantRegistry(spec="greedy:rate=0;victim:rate=0", qos=True)
    eng = LLMEngine(_FakeModel(num_slots=1), tenancy=reg)
    gs = [eng.submit([10 + i, 11 + i], 4, rid=f"g{i}", tenant="greedy")
          for i in range(3)]
    v = eng.submit([1, 2, 3], 4, rid="v", tenant="victim")
    _run_to_completion(eng, gs + [v])
    assert v.admit_seq == 2                  # not 4 (the FIFO slot)
    assert list(v.tokens) == _reference([1, 2, 3], 4)


# -------------------------------------------------- class-based preempts

def test_class_preemption_resumes_victim_byte_identical():
    """Both slots held by best-effort streams; a paid (class 0) stream
    arrives. The youngest best-effort stream is preempted for it, then
    resumes via re-prefill — all three streams byte-identical to solo
    references, and the preemption is attributed to the tenant with
    reason=\"class\"."""
    reg = TenantRegistry(spec="paid:class=0;free:class=1", qos=True)
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=32),
                    tenancy=reg)
    before = _tenant_preempted.labels(tenant="free",
                                      reason="class").value
    f1 = eng.submit([1, 2, 3, 4], 8, rid="f1", tenant="free")
    f2 = eng.submit([5, 6, 7, 8], 8, rid="f2", tenant="free")
    for _ in range(3):
        _tick(eng)
    assert not f1.done and not f2.done       # both decoding
    p = eng.submit([9, 10, 11], 6, rid="p", tenant="paid")
    _tick(eng)                               # preempts f2 at admit end
    _tick(eng)                               # the freed slot admits p
    # the YOUNGEST best-effort stream lost its slot to the paid class
    live = {s.handle.id for s in eng._slots if s.handle is not None}
    assert live == {"p", "f1"}
    assert _tenant_preempted.labels(tenant="free",
                                    reason="class").value == before + 1
    _run_to_completion(eng, [f1, f2, p])
    assert [h.outcome for h in (f1, f2, p)] == ["ok"] * 3
    assert f2.preempts >= 1
    assert list(f1.tokens) == _reference([1, 2, 3, 4], 8)
    assert list(f2.tokens) == _reference([5, 6, 7, 8], 8)
    assert list(p.tokens) == _reference([9, 10, 11], 6)


def test_class_preemption_never_evicts_a_peer():
    """Single class: a full house of equals is NEVER churned by a
    same-class waiter — preemption only crosses class boundaries."""
    reg = TenantRegistry(spec="a:class=1;b:class=1", qos=True)
    eng = LLMEngine(_FakeModel(num_slots=1, num_blocks=32),
                    tenancy=reg)
    a = eng.submit([1, 2, 3], 6, rid="a", tenant="a")
    for _ in range(2):
        _tick(eng)
    b = eng.submit([4, 5, 6], 6, rid="b", tenant="b")
    _tick(eng)
    assert eng._slots[0].handle is not None
    assert eng._slots[0].handle.id == "a"    # undisturbed
    _run_to_completion(eng, [a, b])
    assert a.preempts == 0


# ------------------------------------------- prefix-cache partitioning

def test_partition_eviction_prefers_own_then_shared():
    """A greedy tenant under KV pressure evicts its OWN parked blocks
    first, then the shared partition — the victim's cached prefix
    survives until there is literally nothing else, and the final
    cross-tenant resort is counted."""
    a = BlockAllocator(num_blocks=10, block_size=4, prefix_cache=True)
    hv = prefix_block_hashes(list(range(12)), 4,
                             salt=b"tenant:victim")
    hg = prefix_block_hashes(list(range(100, 116)), 4,
                             salt=b"tenant:greedy")
    a.set_tenant("v1", "victim")
    assert a.allocate("v1", 3) is not None
    a.register_blocks("v1", hv)
    a.free("v1")                             # 3 parked in victim's part
    a.set_tenant("g1", "greedy")
    assert a.allocate("g1", 4) is not None
    a.register_blocks("g1", hg)
    a.free("g1")                             # 4 parked in greedy's part
    cross0 = _cross_evictions.labels(tenant="greedy").value
    # greedy churn: needs 5 = 2 free + 3 evictions, all from its OWN
    # partition even though the victim's blocks are older (global LRU)
    a.set_tenant("g2", "greedy")
    assert a.allocate("g2", 5) is not None
    assert a.match_prefix(hv) == 3           # victim's cache intact
    assert _cross_evictions.labels(tenant="greedy").value == cross0
    # exhaustion: own partition has 1 left, shared has none -> the
    # remaining 2 come cross-tenant, and the counter says so
    a.set_tenant("g3", "greedy")
    assert a.allocate("g3", 3) is not None
    assert _cross_evictions.labels(tenant="greedy").value == cross0 + 2
    assert a.match_prefix(hv) < 3


def test_untagged_eviction_is_plain_lru():
    """No tenant tags: eviction pops the global LRU head, exactly the
    pre-tenancy order (the bit-identity contract for the off path)."""
    a = BlockAllocator(num_blocks=4, block_size=4, prefix_cache=True)
    h1 = prefix_block_hashes([1, 2, 3, 4], 4)
    h2 = prefix_block_hashes([5, 6, 7, 8], 4)
    for seq, h in (("x", h1), ("y", h2)):
        a.allocate(seq, 1)
        a.register_blocks(seq, h)
    a.free("x")                              # LRU
    a.free("y")                              # MRU
    a.allocate("z", 2)                       # 1 free + 1 eviction
    assert a.match_prefix(h1) == 0           # the LRU one went
    assert a.match_prefix(h2) == 1


def test_partition_property_random_churn_matches_shadow():
    """Random tagged alloc/park/grow churn vs a shadow model of the
    partitioned LRU: per-partition cached counts and the cross-tenant
    eviction counters track exactly, and the pool never leaks."""
    rs = np.random.RandomState(42)
    tenants = ["", "a", "b"]
    for trial in range(15):
        nb = int(rs.randint(8, 24))
        a = BlockAllocator(num_blocks=nb, block_size=4,
                           prefix_cache=True)
        # shadow: the _cached LRU as an ordered list of partition tags
        shadow_lru = []
        shadow_free = nb - 1
        shadow_cross = {t: 0 for t in tenants}
        cross0 = {t: _cross_evictions.labels(tenant=t).value
                  for t in ("a", "b")}
        live = {}                            # seq -> (tenant, nblocks)
        serial = 0

        def shadow_evict(t):
            idx = None
            if t:
                for i, tag in enumerate(shadow_lru):
                    if tag == t:
                        idx = i
                        break
                if idx is None:
                    for i, tag in enumerate(shadow_lru):
                        if not tag:
                            idx = i
                            break
                if idx is None:
                    idx = 0
                    shadow_cross[t] += 1
            else:
                idx = 0
            shadow_lru.pop(idx)

        def shadow_take(n, t):
            nonlocal shadow_free
            while shadow_free < n and shadow_lru:
                shadow_evict(t)
                shadow_free += 1
            if shadow_free < n:
                return False
            shadow_free -= n
            return True

        for _ in range(80):
            op = rs.randint(0, 3)
            if op == 0 and len(live) < 5:            # new tagged seq
                t = tenants[rs.randint(0, 3)]
                n = int(rs.randint(1, 4))
                sid = f"s{trial}-{serial}"
                serial += 1
                a.set_tenant(sid, t)
                got = a.allocate(sid, n)
                ok = shadow_take(n, t)
                assert (got is not None) == ok
                if got is not None:
                    live[sid] = (t, n)
            elif op == 1 and live:                   # register + park
                sid = list(live)[rs.randint(0, len(live))]
                t, n = live.pop(sid)
                # unique per-seq tokens: hashes never collide/share
                tokens = [1000 * serial + i for i in range(4 * n)]
                serial += 1
                a.register_blocks(
                    sid, prefix_block_hashes(
                        tokens, 4, salt=b"t:" + t.encode()))
                a.free(sid)
                shadow_lru.extend([t] * n)
            elif op == 2 and live:                   # decode growth
                sid = list(live)[rs.randint(0, len(live))]
                t, n = live[sid]
                if a.allocate(sid, 1) is not None:
                    live[sid] = (t, n + 1)
                    assert shadow_take(1, t)
                else:
                    assert not shadow_take(1, t)
            # -- invariants, every step --
            st = a.stats()
            assert st["blocks_free"] == shadow_free
            assert st["blocks_cached"] == len(shadow_lru)
            assert st["blocks_used"] + st["blocks_free"] + \
                st["blocks_cached"] == nb - 1, "leak"
            by_part = {}
            for blk, tag in a._part_of.items():
                by_part[tag] = by_part.get(tag, 0) + 1
            want = {}
            for tag in shadow_lru:
                if tag:
                    want[tag] = want.get(tag, 0) + 1
            assert by_part == want
            for t in ("a", "b"):
                assert _cross_evictions.labels(tenant=t).value == \
                    cross0[t] + shadow_cross[t]


# ------------------------------------------------ HA client tenant bits

def _client(**kw):
    from zoo_tpu.serving.ha_client import HAServingClient
    return HAServingClient([("127.0.0.1", 1)], deadline_ms=0,
                           hedge=False, **kw)


def test_parse_tenant_pins():
    from zoo_tpu.serving.ha_client import _parse_tenant_pins
    assert _parse_tenant_pins("gold=v2, free=v1") == \
        {"gold": "v2", "free": "v1"}
    assert _parse_tenant_pins("") == {}
    with pytest.raises(ValueError):
        _parse_tenant_pins("gold")
    with pytest.raises(ValueError):
        _parse_tenant_pins("=v2")


def test_client_tenant_pin_overrides_split():
    c = _client(tenant_pins={"gold": "v2"})
    c.pin_version("v1")                      # 100% fractional split
    assert c._draw_version("free") == "v1"
    assert c._draw_version(None) == "v1"
    assert c._draw_version("gold") == "v2"   # pin beats the split
    c.pin_version("v3", tenant="gold")
    assert c._draw_version("gold") == "v3"
    c.pin_version(None, tenant="gold")       # unpin -> back to split
    assert c._draw_version("gold") == "v1"


def test_client_tenant_backoff_is_isolated_and_capped():
    c = _client()
    # only a RATE shed arms the clock — queue sheds fail over instead
    c._note_tenant_backoff("victim", {"retry_after_ms": 5000})
    c._note_tenant_backoff("victim", {"reason": "queue_full",
                                      "retry_after_ms": 5000})
    assert "victim" not in c._tenant_retry_at
    c._note_tenant_backoff("greedy", {"reason": "rate",
                                      "retry_after_ms": 60_000})
    until = c._tenant_retry_at["greedy"]
    # capped by ZOO_TENANT_BACKOFF_CAP_MS (default 2000ms), not 60s
    assert 0 < until - time.monotonic() <= 2.05
    # the victim's attempts are never delayed by greedy's clock
    t0 = time.monotonic()
    c._tenant_backoff_wait("victim", None)
    c._tenant_backoff_wait(None, None)
    assert time.monotonic() - t0 < 0.05


def test_client_tenant_backoff_waits_out_the_hint():
    c = _client()
    c._note_tenant_backoff("g", {"reason": "rate",
                                 "retry_after_ms": 120})
    t0 = time.monotonic()
    c._tenant_backoff_wait("g", None)
    waited = time.monotonic() - t0
    assert 0.08 <= waited <= 1.0
    # the clock is spent: a second wait is a no-op
    t0 = time.monotonic()
    c._tenant_backoff_wait("g", None)
    assert time.monotonic() - t0 < 0.05


# ------------------------------------------------- per-tenant SLO burn

def test_slo_per_tenant_burn_and_breach(monkeypatch):
    monkeypatch.setenv("ZOO_SLO_TENANT_SHED_RATE", "0.1")
    from zoo_tpu.obs.slo import SLOWatchdog
    from zoo_tpu.obs.metrics import counter, gauge
    shed = counter("zoo_tenant_shed_total",
                   "Requests shed per tenant",
                   labels=("tenant", "reason"))
    adm = counter("zoo_tenant_admitted_total",
                  "Requests admitted per tenant", labels=("tenant",))
    w = SLOWatchdog(rules=[])
    w.evaluate()                             # baseline snapshot
    for _ in range(5):
        shed.labels(tenant="slo-greedy", reason="rate").inc()
        adm.labels(tenant="slo-greedy").inc()
    for _ in range(10):
        adm.labels(tenant="slo-victim").inc()
    status = w.evaluate()
    g = status["tenants"]["slo-greedy"]
    assert g["breached"] and g["shed_rate"] == pytest.approx(0.5)
    assert g["burn_rate"] == pytest.approx(5.0)
    v = status["tenants"]["slo-victim"]
    assert not v["breached"] and v["shed_rate"] == 0.0
    assert "tenant_shed_rate[slo-greedy]" in status["breaches"]
    assert status["ok"] is False
    burn = gauge("zoo_tenant_burn_rate",
                 "Per-tenant burn rate", labels=("tenant", "slo"))
    assert burn.labels(tenant="slo-greedy",
                       slo="shed_rate").value == pytest.approx(5.0)


def test_slo_tenant_objective_arms_the_watchdog(monkeypatch):
    from zoo_tpu.obs.slo import SLOWatchdog
    assert SLOWatchdog(rules=[]).start()._thread is None
    monkeypatch.setenv("ZOO_SLO_TENANT_SHED_RATE", "0.05")
    w = SLOWatchdog(rules=[]).start()
    try:
        assert w._thread is not None
    finally:
        w.stop()


# ------------------------------------------------------------ chaos smoke

@pytest.mark.chaos
def test_check_tenancy_script_runs():
    """The adversarial-mix smoke (scripts/check_tenancy.py): a greedy
    tenant floods a 3-replica group with a mid-storm SIGKILL while a
    paced victim streams on — victims byte-identical, zero victim
    sheds, the greedy tenant visibly throttled — as a subprocess, the
    operator invocation."""
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "check_tenancy.py"),
         "--duration", "8"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "TENANCY OK" in proc.stdout
