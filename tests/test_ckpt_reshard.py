"""Resharding-on-restore (CheckpointManager.restore(sharding=...)):
checkpoints are world-size-free host bytes — a snapshot saved from an
N-device mesh restores bit-exactly onto any M-device layout, pre-placed
for the target mesh. The ``run_elastic`` scale-down path composes with
this: the relaunched world builds a smaller mesh and resumes from the
same bytes."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from zoo_tpu.orca.learn.ckpt import CheckpointManager
from zoo_tpu.parallel import build_mesh
from zoo_tpu.parallel.plans import place_params


def _state(seed=0):
    rs = np.random.RandomState(seed)
    return {"params": {"w": rs.randn(16, 8).astype(np.float32),
                       "b": rs.randn(8).astype(np.float32),
                       "odd": rs.randn(7, 5).astype(np.float32)},
            "epoch": 3}


def test_restore_with_mesh_reshards_bit_exact(tmp_path):
    """save@8 (sharded) -> restore@4 -> restore@1: every leaf byte-for-
    byte equal, and the restored leaves actually live on the target
    mesh at its shard sizes."""
    cm = CheckpointManager(str(tmp_path))
    state = _state()
    mesh8 = build_mesh(axis_sizes={"fsdp": 8})
    cm.save(1, {"params": place_params(state["params"], mesh8),
                "epoch": state["epoch"]})

    mesh4 = build_mesh(jax.devices()[:4], axis_sizes={"fsdp": 4})
    at4 = cm.restore(1, sharding=mesh4)
    assert at4["epoch"] == 3  # metadata untouched (still a plain int)
    for k, v in state["params"].items():
        np.testing.assert_array_equal(np.asarray(at4["params"][k]), v)
    # (16,8) sharded 4 ways on dim0 -> per-device (4,8)
    assert at4["params"]["w"].sharding.mesh == mesh4
    assert at4["params"]["w"].addressable_shards[0].data.shape == (4, 8)
    # nothing divides (7,5): replicated, still bit-exact
    assert at4["params"]["odd"].sharding.is_fully_replicated

    mesh1 = build_mesh(jax.devices()[:1], axis_sizes={"data": 1})
    at1 = cm.restore(1, sharding=mesh1)
    for k, v in state["params"].items():
        np.testing.assert_array_equal(np.asarray(at1["params"][k]), v)


def test_restore_with_callable_and_pytree_sharding(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    params = _state()["params"]
    cm.save(2, params)
    mesh = build_mesh(axis_sizes={"fsdp": 8})
    rep = NamedSharding(mesh, P())

    by_call = cm.restore(2, sharding=lambda a: rep)
    for k in params:
        assert by_call[k].sharding.is_fully_replicated
        np.testing.assert_array_equal(np.asarray(by_call[k]), params[k])

    tree = {"w": NamedSharding(mesh, P("fsdp")), "b": rep, "odd": rep}
    by_tree = cm.restore(2, sharding=tree)
    assert by_tree["w"].addressable_shards[0].data.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(by_tree["w"]), params["w"])


def test_restore_with_aux_sharding(tmp_path):
    """The rollback/resume primitive reshards BOTH pytrees from one
    verified step."""
    cm = CheckpointManager(str(tmp_path))
    state = _state()
    aux = {"mu": {"w": np.ones((16, 8), np.float32)},
           "count": np.int32(7)}
    cm.save(5, state, aux=aux)
    mesh = build_mesh(jax.devices()[:2], axis_sizes={"fsdp": 2})
    step, got, got_aux = cm.restore_with_aux(
        None, sharding=mesh, aux_sharding=mesh)
    assert step == 5
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  state["params"]["w"])
    assert got["params"]["w"].addressable_shards[0].data.shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(got_aux["mu"]["w"]),
                                  aux["mu"]["w"])
    assert got_aux["mu"]["w"].addressable_shards[0].data.shape == (8, 8)


def test_restore_without_sharding_unchanged(tmp_path):
    """sharding=None keeps the pre-PR behavior exactly: host numpy."""
    cm = CheckpointManager(str(tmp_path))
    state = _state()
    cm.save(1, state)
    got = cm.restore()
    assert isinstance(got["params"]["w"], np.ndarray)
    np.testing.assert_array_equal(got["params"]["w"],
                                  state["params"]["w"])
