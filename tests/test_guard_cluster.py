"""Preemption protocol on a REAL 2-process cluster: SIGTERM lands on
ONE rank mid-fit, the request propagates over the JAX coordination-
service KV store, both ranks stop at the SAME agreed global step, rank 0
writes exactly one checkpoint, every worker exits with
``PREEMPT_EXIT_CODE``, and ``run_elastic`` relaunches at the SAME world
size — the resumed run completes from the preemption snapshot.
"""

import json
import os
import re
import sys

import numpy as np
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.guard, pytest.mark.chaos]

_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

# join the coordination service (the KV store the preempt protocol
# rides) WITHOUT a cross-process device mesh: XLA's CPU backend has no
# multiprocess computations, so each rank fits its own replica of the
# deterministic SPMD program — identical steps, identical counters —
# which is exactly the lockstep the protocol assumes on a real pod
jax.distributed.initialize(os.environ["ZOO_COORDINATOR_ADDRESS"],
                           int(os.environ["ZOO_NUM_PROCESSES"]),
                           int(os.environ["ZOO_PROCESS_ID"]))
world, pid = jax.process_count(), jax.process_index()
attempt = int(os.environ.get("ZOO_ELASTIC_ATTEMPT", "0"))
model_dir = sys.argv[1]

from zoo_tpu.orca.learn.ckpt import CheckpointManager
from zoo_tpu.orca.learn.guard import GuardConfig, TrainingGuard
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

rs = np.random.RandomState(0)
x = rs.randn(96, 8).astype(np.float32)
w = rs.randn(8, 1).astype(np.float32)
y = (x @ w).astype(np.float32)

m = Sequential()
m.add(Dense(8, input_shape=(8,), activation="relu"))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
guard = TrainingGuard(
    config=GuardConfig(enabled=True),
    quarantine_path=os.path.join(model_dir, f"guard-rank{pid}.jsonl"))
# rank 0 owns the checkpoint dir (DP params are replicated); every rank
# can READ it on a shared filesystem, so rollback capability is global
est = Estimator.from_keras(m, model_dir=model_dir if pid == 0 else None,
                           guard=guard)
if pid > 0:
    mgr = CheckpointManager(os.path.join(model_dir, "ckpts"))
    guard.bind(restore_fn=lambda: mgr.restore_with_aux(None)[1:])
if attempt > 0:
    est.load_orca_checkpoint(path=model_dir)
    print(f"proc {pid} RESUMED attempt={attempt} epoch={est._epoch}",
          flush=True)

TOTAL = 3
if attempt == 0:
    est.fit({"x": x, "y": y}, epochs=1, batch_size=24)
    if pid == 0:
        # the TPU maintenance event: SIGTERM on ONE host, mid-fit;
        # the KV protocol must stop BOTH ranks at the same step
        import signal
        from zoo_tpu.util.resilience import inject

        def kick(**_):
            os.kill(os.getpid(), signal.SIGTERM)

        inject("fit.batch", action=kick, exc=None, times=1)
    est.fit({"x": x, "y": y}, epochs=TOTAL - est._epoch, batch_size=24)
    print(f"proc {pid} UNEXPECTED completion", flush=True)
else:
    while est._epoch < TOTAL:
        est.fit({"x": x, "y": y}, epochs=1, batch_size=24)
    print(f"proc {pid} DONE epoch={est._epoch}", flush=True)
"""


@pytest.mark.timeout(480)
def test_sigterm_coordinated_checkpoint_and_resume(tmp_path):
    from zoo_tpu.orca.bootstrap import run_elastic

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    log_dir = tmp_path / "logs"
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.getcwd() + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
        "JAX_COMPILATION_CACHE_DIR": str(tmp_path / "jaxcache"),
    }
    final_world = run_elastic(
        2, str(script), [str(model_dir)], min_workers=1,
        max_restarts=0, log_dir=str(log_dir), env=env,
        wait_timeout=420)
    # preemption must NOT scale the world down
    assert final_world == 2

    logs = ""
    for f in sorted(log_dir.glob("*.log")):
        logs += f.read_text()
    assert "UNEXPECTED completion" not in logs, logs[-2000:]
    assert re.search(r"proc \d+ RESUMED attempt=1", logs), logs[-2000:]
    assert re.search(r"proc \d+ DONE epoch=3", logs), logs[-2000:]

    # exactly ONE coordinated checkpoint, both ranks at the SAME step
    steps = {}
    for pid in (0, 1):
        events = [json.loads(line) for line in
                  open(model_dir / f"guard-rank{pid}.jsonl")]
        pre = [e for e in events if e["event"] == "preempt_checkpoint"]
        assert len(pre) == 1, (pid, events)
        steps[pid] = pre[0]["step"]
        # only rank 0 holds the save callback
        assert pre[0]["saved"] == (pid == 0)
    assert steps[0] == steps[1], f"ranks checkpointed different steps: " \
                                 f"{steps}"
