"""Profiling: per-phase step timers in fit, XLA trace capture, and the
TrainSummary scalar plumbing (SURVEY §5.1 rebuild)."""

import glob
import os

import numpy as np

from zoo_tpu.common.profiling import PhaseTimer, StepProfiler, trace
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense


def _model():
    m = Sequential(name="prof")
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(1))
    m.compile(optimizer="sgd", loss="mse")
    return m


def _data(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 8).astype(np.float32)
    return x, x[:, :1] * 2.0


def test_phase_timer_stats():
    t = PhaseTimer()
    for dt in (0.01, 0.03):
        t.record(dt)
    s = t.stats()
    assert s["count"] == 2
    assert abs(s["avg_ms"] - 20.0) < 1e-6
    assert abs(s["max_ms"] - 30.0) < 1e-6


def test_fit_records_phases():
    m = _model()
    prof = m.set_profile()
    x, y = _data()
    m.fit(x, y, batch_size=16, nb_epoch=2, verbose=0)
    # epoch_scalars resets per epoch; after fit the current-epoch stats
    # are drained, but the summary got the scalars
    steps = m.train_summary.read_scalar("StepTimeMs")
    waits = m.train_summary.read_scalar("DataTimeMs")
    assert len(steps) == 2 and len(waits) == 2
    assert all(v > 0 for _, v in steps)
    m.clear_profile()
    assert m.get_profile_stats() == {}
    assert prof is not None


def test_fit_without_profiler_unchanged():
    m = _model()
    x, y = _data()
    h = m.fit(x, y, batch_size=16, nb_epoch=1, verbose=0)
    assert len(h["loss"]) == 1
    assert m.get_profile_stats() == {}


def test_xla_trace_capture(tmp_path):
    m = _model()
    m.set_profile(trace_dir=str(tmp_path), trace_epochs=1)
    x, y = _data(32)
    m.fit(x, y, batch_size=16, nb_epoch=2, verbose=0)
    produced = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                         recursive=True)
    assert produced, "expected an XPlane trace under the profile dir"


def test_standalone_trace_window(tmp_path):
    m = _model()
    x, _ = _data(16)
    with trace(str(tmp_path)):
        m.predict(x, batch_size=16)
    produced = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                         recursive=True)
    assert produced


def test_profiler_via_estimator():
    from zoo_tpu.orca.learn.keras.estimator import Estimator
    m = _model()
    est = Estimator.from_keras(m)
    est.set_profile()
    x, y = _data()
    est.fit({"x": x, "y": y}, batch_size=16, epochs=1)
    assert "step" in est.get_profile_stats()


def test_eval_phase_and_save_strips_profiler(tmp_path):
    m = _model()
    m.set_profile()
    x, y = _data()
    m.fit(x, y, batch_size=16, nb_epoch=1, verbose=0,
          validation_data=(x[:16], y[:16]))
    assert "eval" in m.get_profile_stats()
    assert len(m.train_summary.read_scalar("EvalTimeMs")) == 1
    p = str(tmp_path / "m.zoo")
    m.save(p)
    from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
    loaded = KerasNet.load(p)
    assert getattr(loaded, "_profiler", None) is None
    assert m._profiler is not None  # original untouched


# -- hand-built XSpace wire-format helpers (shared by the xplane tests) --

def _varint(v):
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _field(n, payload):
    return _varint((n << 3) | 2) + _varint(len(payload)) + payload


def _vfield(n, v):
    return _varint(n << 3) + _varint(v)


def _meta(mid, name):
    return _field(4, _vfield(1, mid) + _field(2, _vfield(1, mid)
                                              + _field(2, name)))


def test_xplane_parser_roundtrip(tmp_path):
    """device_op_times on a hand-built XSpace: one TPU plane, two events
    with durations carried via the device_duration_ps stat."""
    from zoo_tpu.common.xplane import device_op_times, op_breakdown

    ev_meta = _meta(7, b"%fusion.1 = f32[2]{0} fusion(...), kind=kLoop")
    stat_meta = _field(5, _vfield(1, 2) + _field(2, _vfield(1, 2) + _field(
        2, b"device_duration_ps")))
    stat = _field(4, _vfield(1, 2) + _vfield(3, 5_000_000))  # 5 us
    event = _field(4, _vfield(1, 7) + stat)
    line = _field(3, _field(2, b"XLA Ops") + event + event)
    plane = _field(1, _field(2, b"/device:TPU:0") + ev_meta + stat_meta
                   + line)
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(plane)

    times = device_op_times(str(p))
    (name, (ms, cnt)), = times.items()
    assert "fusion.1" in name and cnt == 2
    assert abs(ms - 0.01) < 1e-9
    rows = op_breakdown(str(p))
    assert rows[0][0] == "fusion/kLoop" and rows[0][2] == 2


def test_xplane_parser_skips_step_and_module_lines(tmp_path):
    """Real device planes carry Steps / XLA Modules lines whose events
    span whole training steps; only the XLA Ops line may feed the op
    breakdown (the round-3 parser summed everything and reported
    step-length 'ops' named by their step number)."""
    from zoo_tpu.common.xplane import device_op_times, op_breakdown

    op_meta = _meta(7, b"%convolution.5 = f32[2]{0} convolution(...)")
    step_meta = _meta(9, b"17")  # steps are named by their number
    wrap_meta = _meta(11, b"%while.6 = while(...)")
    op_event = _field(4, _vfield(1, 7) + _vfield(3, 2_000_000))    # 2 us
    step_event = _field(4, _vfield(1, 9) + _vfield(3, 900_000_000))
    wrap_event = _field(4, _vfield(1, 11) + _vfield(3, 800_000_000))
    ops_line = _field(3, _field(2, b"XLA Ops") + op_event + op_event
                      + wrap_event)
    steps_line = _field(3, _field(2, b"Steps") + step_event)
    mod_line = _field(3, _field(2, b"XLA Modules") + step_event)
    plane = _field(1, _field(2, b"/device:TPU:0") + op_meta + step_meta
                   + wrap_meta + ops_line + steps_line + mod_line)
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(plane)

    times = device_op_times(str(p))
    names = set(times)
    assert any("convolution.5" in n for n in names)
    assert not any(n == "17" for n in names), names  # Steps excluded
    # the while wrapper rides the XLA Ops line but must not dominate
    # the breakdown (its children are counted individually)
    rows = op_breakdown(str(p))
    assert rows[0][0] == "convolution" and rows[0][2] == 2, rows
    assert not any(r[0].startswith("while") for r in rows)
