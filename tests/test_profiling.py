"""Profiling: per-phase step timers in fit, XLA trace capture, and the
TrainSummary scalar plumbing (SURVEY §5.1 rebuild)."""

import glob
import os

import numpy as np

from zoo_tpu.common.profiling import PhaseTimer, StepProfiler, trace
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense


def _model():
    m = Sequential(name="prof")
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(1))
    m.compile(optimizer="sgd", loss="mse")
    return m


def _data(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 8).astype(np.float32)
    return x, x[:, :1] * 2.0


def test_phase_timer_stats():
    t = PhaseTimer()
    for dt in (0.01, 0.03):
        t.record(dt)
    s = t.stats()
    assert s["count"] == 2
    assert abs(s["avg_ms"] - 20.0) < 1e-6
    assert abs(s["max_ms"] - 30.0) < 1e-6


def test_fit_records_phases():
    m = _model()
    prof = m.set_profile()
    x, y = _data()
    m.fit(x, y, batch_size=16, nb_epoch=2, verbose=0)
    # epoch_scalars resets per epoch; after fit the current-epoch stats
    # are drained, but the summary got the scalars
    steps = m.train_summary.read_scalar("StepTimeMs")
    waits = m.train_summary.read_scalar("DataTimeMs")
    assert len(steps) == 2 and len(waits) == 2
    assert all(v > 0 for _, v in steps)
    m.clear_profile()
    assert m.get_profile_stats() == {}
    assert prof is not None


def test_fit_without_profiler_unchanged():
    m = _model()
    x, y = _data()
    h = m.fit(x, y, batch_size=16, nb_epoch=1, verbose=0)
    assert len(h["loss"]) == 1
    assert m.get_profile_stats() == {}


def test_xla_trace_capture(tmp_path):
    m = _model()
    m.set_profile(trace_dir=str(tmp_path), trace_epochs=1)
    x, y = _data(32)
    m.fit(x, y, batch_size=16, nb_epoch=2, verbose=0)
    produced = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                         recursive=True)
    assert produced, "expected an XPlane trace under the profile dir"


def test_standalone_trace_window(tmp_path):
    m = _model()
    x, _ = _data(16)
    with trace(str(tmp_path)):
        m.predict(x, batch_size=16)
    produced = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                         recursive=True)
    assert produced


def test_profiler_via_estimator():
    from zoo_tpu.orca.learn.keras.estimator import Estimator
    m = _model()
    est = Estimator.from_keras(m)
    est.set_profile()
    x, y = _data()
    est.fit({"x": x, "y": y}, batch_size=16, epochs=1)
    assert "step" in est.get_profile_stats()


def test_eval_phase_and_save_strips_profiler(tmp_path):
    m = _model()
    m.set_profile()
    x, y = _data()
    m.fit(x, y, batch_size=16, nb_epoch=1, verbose=0,
          validation_data=(x[:16], y[:16]))
    assert "eval" in m.get_profile_stats()
    assert len(m.train_summary.read_scalar("EvalTimeMs")) == 1
    p = str(tmp_path / "m.zoo")
    m.save(p)
    from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
    loaded = KerasNet.load(p)
    assert getattr(loaded, "_profiler", None) is None
    assert m._profiler is not None  # original untouched


def test_xplane_parser_roundtrip(tmp_path):
    """device_op_times on a hand-built XSpace: one TPU plane, two events
    with durations carried via the device_duration_ps stat."""
    from zoo_tpu.common.xplane import device_op_times, op_breakdown

    def varint(v):
        out = b""
        while True:
            b7 = v & 0x7F
            v >>= 7
            if v:
                out += bytes([b7 | 0x80])
            else:
                return out + bytes([b7])

    def field(n, payload):
        return varint((n << 3) | 2) + varint(len(payload)) + payload

    def vfield(n, v):
        return varint(n << 3) + varint(v)

    ev_meta = field(4, vfield(1, 7) + field(2, vfield(1, 7) + field(
        2, b"%fusion.1 = f32[2]{0} fusion(...), kind=kLoop")))
    stat_meta = field(5, vfield(1, 2) + field(2, vfield(1, 2) + field(
        2, b"device_duration_ps")))
    stat = field(4, vfield(1, 2) + vfield(3, 5_000_000))  # 5 us
    event = field(4, vfield(1, 7) + stat)
    line = field(3, event + event)
    plane = field(1, field(2, b"/device:TPU:0") + ev_meta + stat_meta + line)
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(plane)

    times = device_op_times(str(p))
    (name, (ms, cnt)), = times.items()
    assert "fusion.1" in name and cnt == 2
    assert abs(ms - 0.01) < 1e-9
    rows = op_breakdown(str(p))
    assert rows[0][0] == "fusion/kLoop" and rows[0][2] == 2
