"""Prefix-cached KV block allocator (docs/llm_serving.md): rolling
content hashes, refcounted sharing, copy-on-write forks, LRU eviction
over refcount-0 blocks only, and the engine-level admission contract —
all against pure-python fakes, so the whole file is tier-1 cheap.

The property test drives random alloc/share/write-fork/free
interleavings against a shadow model and asserts the pool never leaks
a block, never double-hands one out, and never evicts a block a live
sequence still references.
"""

import time

import numpy as np
import pytest

from zoo_tpu.serving.llm.engine import LLMEngine
from zoo_tpu.serving.llm.kv_cache import (
    BlockAllocator,
    prefix_block_hashes,
)


# ----------------------------------------------------------- rolling hash

def test_rolling_hash_full_blocks_only():
    assert prefix_block_hashes([1, 2, 3], 4) == []
    assert len(prefix_block_hashes([1, 2, 3, 4], 4)) == 1
    assert len(prefix_block_hashes(list(range(11)), 4)) == 2


def test_rolling_hash_binds_the_whole_prefix():
    """Block 1's key must differ when block 0 differs, even though
    block 1's own tokens are identical — a hash hit implies the entire
    prefix matches, which is what makes aliasing its KV safe."""
    a = prefix_block_hashes([1, 2, 3, 4, 9, 9, 9, 9], 4)
    b = prefix_block_hashes([5, 6, 7, 8, 9, 9, 9, 9], 4)
    assert a[0] != b[0]
    assert a[1] != b[1]          # same block tokens, different prefix
    c = prefix_block_hashes([1, 2, 3, 4, 9, 9, 9, 9, 1], 4)
    assert c[:2] == a[:2]        # longer prompt, same leading blocks


# ------------------------------------------------- share / fork / evict

def _alloc(n=16, bs=4):
    return BlockAllocator(num_blocks=n, block_size=bs,
                          prefix_cache=True)


def test_acquire_bumps_refs_and_counts_blocks_once():
    a = _alloc()
    h = prefix_block_hashes(list(range(8)), 4)
    a.allocate("w", 2)
    a.register_blocks("w", h)
    got = a.acquire_prefix("r", h)
    assert got == a.blocks_of("w")
    # pool pressure counts the shared blocks ONCE
    assert a.used_blocks == 2
    assert a.shared_blocks == 2
    assert a.stats()["blocks_shared"] == 2
    a.free("r")
    assert a.shared_blocks == 0
    assert a.used_blocks == 2     # writer still owns them


def test_free_parks_registered_blocks_for_reuse():
    a = _alloc()
    h = prefix_block_hashes(list(range(8)), 4)
    a.allocate("w", 2)
    a.register_blocks("w", h)
    assert a.free("w") == 2
    assert a.used_blocks == 0
    assert a.cached_blocks == 2   # matchable, not leaked
    # a later stream re-binds them without any writer alive
    got = a.acquire_prefix("r", h)
    assert len(got) == 2 and a.cached_blocks == 0
    assert a.used_blocks == 2


def test_match_stops_at_first_miss():
    a = _alloc()
    h = prefix_block_hashes(list(range(12)), 4)
    a.allocate("w", 3)
    a.register_blocks("w", h[:2])      # only two of three published
    assert a.match_prefix(h) == 2
    got = a.acquire_prefix("r", h)
    assert len(got) == 2


def test_eviction_is_lru_and_never_touches_refcounted_blocks():
    a = BlockAllocator(num_blocks=6, block_size=4, prefix_cache=True)
    h1 = prefix_block_hashes([1, 2, 3, 4], 4)
    h2 = prefix_block_hashes([5, 6, 7, 8], 4)
    h3 = prefix_block_hashes([9, 10, 11, 12], 4)
    for seq, h in (("a", h1), ("b", h2), ("c", h3)):
        a.allocate(seq, 1)
        a.register_blocks(seq, h)
    a.free("a")                   # LRU
    time.sleep(0)                  # order is insertion, not wall clock
    a.free("b")                   # MRU
    keep = a.acquire_prefix("r", h3)   # c's block: refcounted, live
    assert len(keep) == 1
    # pool: 5 usable, 1 held by r (shared w/ nothing), a+b cached, 2 free
    got = a.allocate("x", 4)      # needs both free + both cached
    assert got is not None
    # the refcounted block was NOT evicted and survives intact
    assert a.blocks_of("r") == keep
    assert a.match_prefix(h3) == 1
    # the parked ones were deregistered when reclaimed
    assert a.match_prefix(h1) == 0 and a.match_prefix(h2) == 0


def test_cow_forks_shared_and_writes_private_in_place():
    a = _alloc()
    h = prefix_block_hashes(list(range(8)), 4)
    a.allocate("w", 2)
    a.register_blocks("w", h)
    a.acquire_prefix("r", h)
    before = a.blocks_of("r")
    fork = a.make_writable("r", 1)
    assert fork is not None
    src, dst = fork
    assert src == before[1] and dst not in before
    assert a.blocks_of("r")[1] == dst
    assert a.blocks_of("w") == before          # writer untouched
    assert a.shared_blocks == 1                # only block 0 still shared
    # private block: no fork needed
    assert a.make_writable("r", 1) is None
    a.free("r")
    a.free("w")
    # zero leaks: everything is free or parked-cached
    st = a.stats()
    assert st["blocks_used"] == 0
    assert st["blocks_free"] + st["blocks_cached"] == a.num_blocks - 1


def test_cow_raises_when_pool_exhausted():
    a = BlockAllocator(num_blocks=3, block_size=4, prefix_cache=True)
    h = prefix_block_hashes([1, 2, 3, 4], 4)
    a.allocate("w", 1)
    a.register_blocks("w", h)
    a.acquire_prefix("r", h)
    a.allocate("w", 1)            # last free block
    with pytest.raises(MemoryError):
        a.make_writable("r", 0)


def test_register_first_writer_wins():
    a = _alloc()
    h = prefix_block_hashes(list(range(4)), 4)
    a.allocate("w1", 1)
    a.register_blocks("w1", h)
    a.allocate("w2", 1)
    a.register_blocks("w2", h)    # duplicate content: ignored
    assert a.match_prefix(h) == 1
    assert a.acquire_prefix("r", h) == a.blocks_of("w1")


def test_aux_is_per_sequence_never_per_shared_block():
    """The sampling-seed checkpoint must survive refcounted sharing:
    two streams on the same blocks keep distinct aux, and freeing one
    never clears the other's."""
    a = _alloc()
    h = prefix_block_hashes(list(range(8)), 4)
    a.allocate("w", 2)
    a.register_blocks("w", h)
    a.acquire_prefix("r", h)
    a.set_aux("w", seed=111)
    a.set_aux("r", seed=222)
    assert a.get_aux("w")["seed"] == 111
    assert a.get_aux("r")["seed"] == 222
    a.free("w")
    assert a.get_aux("w") is None
    assert a.get_aux("r")["seed"] == 222       # untouched by the free


def test_can_admit_is_conservative():
    """Whenever can_admit says yes with an expected prefix hit, the
    acquire+allocate(+CoW) that follows immediately must succeed."""
    rs = np.random.RandomState(7)
    for trial in range(50):
        bs = int(rs.randint(2, 6))
        a = BlockAllocator(num_blocks=int(rs.randint(4, 12)),
                           block_size=bs, prefix_cache=True)
        base = [int(t) for t in rs.randint(0, 50, bs * 3)]
        h = prefix_block_hashes(base, bs)
        if a.allocate("w", 3) is not None:
            a.register_blocks("w", h)
            if rs.rand() < 0.5:
                a.free("w")
        plen = int(rs.randint(1, 4 * bs))
        prompt = base[:plen] if rs.rand() < 0.7 else \
            [int(t) for t in rs.randint(50, 99, plen)]
        hashes = prefix_block_hashes(prompt, bs)
        matched = a.match_prefix(hashes)
        start = min(matched * bs, plen - 1)
        cow = matched * bs > start
        if not a.can_admit(plen, cached_blocks=matched, needs_cow=cow):
            continue
        got = a.acquire_prefix("r", hashes)
        need = a.blocks_for_tokens(plen) - len(got)
        if need > 0:
            assert a.allocate("r", need) is not None, \
                f"trial {trial}: can_admit lied on allocate"
        if len(got) * bs > min(len(got) * bs, plen - 1):
            a.make_writable("r", len(got) - 1)  # must not raise


def test_property_random_interleavings_never_leak():
    """alloc -> share -> write-fork -> free in random order against a
    shadow model: every block is free, parked-cached, or owned by at
    least one live sequence; the three partitions always sum to the
    pool; eviction never reclaims a refcount>0 block; free stays
    idempotent."""
    rs = np.random.RandomState(0)
    for trial in range(20):
        bs = 4
        a = BlockAllocator(num_blocks=int(rs.randint(6, 20)),
                           block_size=bs, prefix_cache=True)
        prompts = {f"p{i}": [int(t) for t in
                             rs.randint(0, 30, int(rs.randint(4, 17)))]
                   for i in range(4)}
        live = {}
        for step in range(120):
            op = rs.randint(0, 5)
            if op == 0 and len(live) < 6:          # admit
                sid = f"s{trial}-{step}"
                tokens = prompts[f"p{rs.randint(0, 4)}"]
                hashes = prefix_block_hashes(tokens, bs)
                matched = a.match_prefix(hashes)
                start = min(matched * bs, len(tokens) - 1)
                cow = matched * bs > start
                if a.can_admit(len(tokens), cached_blocks=matched,
                               needs_cow=cow):
                    got = a.acquire_prefix(sid, hashes)
                    need = a.blocks_for_tokens(len(tokens)) - len(got)
                    if need > 0:
                        assert a.allocate(sid, need) is not None
                    if len(got) * bs > start and got:
                        a.make_writable(sid, len(got) - 1)
                    live[sid] = hashes
            elif op == 1 and live:                 # register
                sid = list(live)[rs.randint(0, len(live))]
                a.register_blocks(sid, live[sid])
            elif op == 2 and live:                 # free (idempotent)
                sid = list(live)[rs.randint(0, len(live))]
                a.free(sid)
                assert a.free(sid) == 0
                del live[sid]
            elif op == 3 and live:                 # decode growth
                sid = list(live)[rs.randint(0, len(live))]
                a.allocate(sid, 1)                 # may refuse: fine
            else:                                  # fork a random row
                if live:
                    sid = list(live)[rs.randint(0, len(live))]
                    blocks = a.blocks_of(sid)
                    if blocks:
                        try:
                            a.make_writable(
                                sid, int(rs.randint(0, len(blocks))))
                        except MemoryError:
                            pass
            # -- invariants, every step --
            st = a.stats()
            owned = set()
            for sid in live:
                blks = a.blocks_of(sid)
                assert 0 not in blks              # trash block reserved
                owned.update(blks)
            assert len(owned) == st["blocks_used"], \
                "shared blocks must be counted once"
            assert st["blocks_used"] + st["blocks_free"] + \
                st["blocks_cached"] == a.num_blocks - 1, "leak"
        for sid in list(live):
            a.free(sid)
        st = a.stats()
        assert st["blocks_used"] == 0 and st["live_sequences"] == 0


def test_drop_cached_reclaims_only_parked_blocks():
    a = _alloc()
    h = prefix_block_hashes(list(range(8)), 4)
    a.allocate("w", 2)
    a.register_blocks("w", h)
    a.acquire_prefix("r", h)
    a.free("w")                    # blocks stay refcounted via r
    assert a.drop_cached() == 0
    a.free("r")
    assert a.cached_blocks == 2
    assert a.drop_cached() == 2
    assert a.free_blocks == a.num_blocks - 1
    assert a.match_prefix(h) == 0


# ------------------------------------------ engine admission (fake model)

class _PrefixFakeModel:
    """Deterministic jax-free model with the PagedLlamaModel surface:
    next token is a pure function of (last token, position[, seed]) —
    so streams are byte-comparable across prefix-cache on/off and
    across preempt-resume, exactly like the real model's greedy/seeded
    decode. Tracks prefill token counts so tests can assert the
    cache-hit skip actually happened."""

    def __init__(self, num_slots=2, block_size=4, num_blocks=32,
                 max_blocks_per_seq=8, max_prompt_len=24,
                 prefill_chunk=0):
        self.num_slots = num_slots
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks_per_seq
        self.max_context = block_size * max_blocks_per_seq
        self.max_prompt_len = max_prompt_len
        self.prefill_chunk_size = prefill_chunk
        self.suffix_chunk_size = prefill_chunk or block_size
        self.eos_id = None
        self.prefilled_tokens = 0
        self.copied = []          # (src, dst) CoW device copies

    @staticmethod
    def _next(tok, pos, temp=0.0, seed=0):
        if temp > 0:
            return (31 * int(seed) + 7 * int(pos) + 3 * int(tok)) % 97
        return (2 * int(tok) + int(pos)) % 97

    def prefill(self, prompt, row, sampling=None):
        self.prefilled_tokens += len(prompt)
        t, _, _, s = sampling or (0.0, 0, 1.0, 0)
        return self._next(prompt[-1], len(prompt), t, s)

    def prefill_chunk(self, chunk, start, total_len, row,
                      sampling=None):
        self.prefilled_tokens += len(chunk)
        t, _, _, s = sampling or (0.0, 0, 1.0, 0)
        return self._next(chunk[-1], total_len, t, s)

    def copy_block(self, src, dst):
        self.copied.append((int(src), int(dst)))

    def decode(self, tokens, block_tables, positions, sampling=None):
        if sampling is None:
            temps = seeds = [0] * len(tokens)
        else:
            temps, _, _, seeds = sampling
        return np.array([self._next(t, p + 1, tt, s)
                         for t, p, tt, s in zip(tokens, positions,
                                                temps, seeds)],
                        np.int32)


def _drain(handles, budget=60.0):
    deadline = time.monotonic() + budget
    while not all(h.done for h in handles):
        assert time.monotonic() < deadline, \
            [(h.outcome, h.error, h.tokens) for h in handles]
        time.sleep(0.002)
    return [list(h.tokens) for h in handles]


def _run_streams(prefix_cache, prompts, max_new=8, sampling=None,
                 sequential=True, **model_kw):
    m = _PrefixFakeModel(**model_kw)
    eng = LLMEngine(m, overlap=False, prefix_cache=prefix_cache).start()
    try:
        outs = []
        if sequential:
            for i, p in enumerate(prompts):
                h = eng.submit(p, max_new, rid=f"r{i}",
                               sampling=sampling)
                outs.extend(_drain([h]))
        else:
            hs = [eng.submit(p, max_new, rid=f"r{i}", sampling=sampling)
                  for i, p in enumerate(prompts)]
            outs = _drain(hs)
        return outs, eng.stats(), m
    finally:
        eng.stop()


SHARED = list(range(1, 13))       # 12 tokens = 3 full blocks, aligned


@pytest.mark.parametrize("chunk", [0, 4])
def test_engine_prefix_cache_streams_byte_identical(chunk):
    """The acceptance bit: greedy streams byte-identical with prefix
    caching on vs off, bucketed (chunk=0: suffix fed through the chunk
    path) AND chunked prefill — and the hit actually skipped prefill
    work."""
    prompts = [SHARED, SHARED + [77, 78], SHARED + [79], SHARED]
    off, _, m_off = _run_streams(False, prompts, prefill_chunk=chunk)
    on, st, m_on = _run_streams(True, prompts, prefill_chunk=chunk)
    assert on == off
    assert st["prefix_hit_tokens"] > 0
    assert st["prefix_miss_tokens"] < sum(len(p) for p in prompts)
    # cache hits -> strictly fewer prompt tokens through the device
    assert m_on.prefilled_tokens < m_off.prefilled_tokens
    # zero leaks: every stream done, blocks free or parked-cached
    assert st["blocks_used"] == 0
    assert st["blocks_free"] + st["blocks_cached"] == \
        st["num_blocks"] - 1


def _tick(eng):
    eng._sweep()
    eng._admit()
    eng._prefill_tick()
    eng._grow_or_preempt()
    eng._decode_tick()


def test_engine_cow_fork_copies_device_block():
    """Two LIVE streams on the same aligned prompt: the second must
    fork the final shared block (ref 2) and the engine must issue the
    device copy BEFORE the recompute write. White-box manual ticks so
    both streams are provably concurrent."""
    m = _PrefixFakeModel()
    eng = LLMEngine(m, prefix_cache=True)   # not started: manual ticks
    h1 = eng.submit(SHARED, 10, rid="a")
    for _ in range(3):                      # a prefilled + decoding
        _tick(eng)
    assert not h1.done and len(h1.tokens) >= 1
    h2 = eng.submit(SHARED, 4, rid="b")
    for _ in range(20):
        _tick(eng)
        if h1.done and h2.done:
            break
    assert h1.outcome == "ok" and h2.outcome == "ok"
    assert len(m.copied) == 1     # exactly one CoW device copy
    st = eng.stats()
    assert st["prefix_hit_tokens"] == len(SHARED) - 1
    eng.stop()
    # the no-cache reference agrees byte for byte
    ref, _, _ = _run_streams(False, [SHARED, SHARED], max_new=10)
    assert list(h1.tokens) == ref[0]
    assert list(h2.tokens) == ref[1][:4]


def test_cow_without_copy_block_fails_stream_loudly():
    """A model that cannot execute the CoW device copy must end the
    forked stream with an ERROR — never silently decode over a block
    whose prefix bytes were never copied."""

    class _NoCopy(_PrefixFakeModel):
        copy_block = None

    eng = LLMEngine(_NoCopy(), prefix_cache=True)
    h1 = eng.submit(SHARED, 10, rid="a")
    for _ in range(3):
        _tick(eng)
    assert not h1.done
    h2 = eng.submit(SHARED, 4, rid="b")   # aligned hit -> fork owed
    for _ in range(20):
        _tick(eng)
        if h1.done and h2.done:
            break
    assert h1.outcome == "ok"             # the writer is untouched
    assert h2.outcome == "error" and "copy_block" in h2.error
    assert eng.allocator.stats()["blocks_used"] == 0 or not h1.done
    eng.stop()
    assert eng.allocator.stats()["blocks_used"] == 0


def test_seed_replay_across_preempt_resume_on_cache_hit():
    """Satellite regression: a SAMPLED stream preempted mid-decode and
    resumed onto a prefix-cache hit must replay byte-identically (the
    seed checkpoint is per-sequence aux, never per-shared-block)."""
    sampling = dict(temperature=0.9, top_k=8, top_p=0.95, seed=1234)
    # reference: roomy pool, no preemption, no cache
    ref, _, _ = _run_streams(False, [SHARED], max_new=12,
                             sampling=sampling, num_blocks=32)
    # tight pool + a competing stream forces preemption; prefix cache
    # on means the resume re-matches its own re-registered prefix
    m = _PrefixFakeModel(num_blocks=10, num_slots=2)
    eng = LLMEngine(m, overlap=False, prefix_cache=True).start()
    try:
        h1 = eng.submit(SHARED, 12, rid="victim", sampling=sampling)
        h2 = eng.submit(list(range(20, 28)), 16, rid="hog",
                        sampling=sampling)
        outs = _drain([h1, h2])
        assert h1.outcome == "ok", (h1.outcome, h1.error)
        assert outs[0] == ref[0]
        st = eng.stats()
        assert st["blocks_used"] == 0
    finally:
        eng.stop()


def test_resumed_stream_rematches_prefix_cache():
    """A preempted stream's freed prefix stays registered (parked on
    the cached-free LRU), so its own resume admission lands on a cache
    hit — the same property an HA failover resume leans on
    replica-side. White-box ticks: the hog is admitted FIRST, so KV
    pressure always evicts the younger victim."""
    from zoo_tpu.obs.metrics import counter
    m = _PrefixFakeModel(num_blocks=9, num_slots=2, max_prompt_len=40,
                         max_blocks_per_seq=12)
    eng = LLMEngine(m, prefix_cache=True)
    preempts0 = counter("zoo_llm_preempt_total").value
    hog = eng.submit(list(range(60, 68)), 20, rid="hog")
    victim = eng.submit(SHARED, 8, rid="victim")
    for _ in range(80):
        _tick(eng)
        if hog.done and victim.done:
            break
    assert hog.outcome == "ok" and victim.outcome == "ok"
    assert counter("zoo_llm_preempt_total").value > preempts0, \
        "pool was not tight enough to force a preemption"
    st = eng.stats()
    assert st["prefix_hit_tokens"] > 0, \
        "the resume did not re-match the prefix cache"
    eng.stop()
    ref, _, _ = _run_streams(False, [SHARED], max_new=8,
                             num_blocks=32, max_prompt_len=40,
                             max_blocks_per_seq=12)
    assert list(victim.tokens) == ref[0]
