"""In-suite multichip smoke (the ``multichip`` marker): the same
measured acceptance checks the MULTICHIP harness scores, run as a
subprocess with the 8-device virtual CPU platform forced
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — sharded fit
matches the single-device loss curve, save@8 -> restore@4 -> restore@1
is bit-exact, sharded paged decode is token-identical to the unsharded
reference, the FSDP HLO lint passes, the pipeline plan's GPipe
schedule matches plain dp with the stacked body pipe-sharded and
collective-permute in the compiled step, and the moe plan's
expert-sharded FFN matches the replicated reference."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.multichip
@pytest.mark.timeout(480)
def test_check_multichip_script_runs():
    r = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_multichip.py")],
        capture_output=True, text=True, timeout=470, cwd=os.getcwd())
    assert r.returncode == 0, r.stdout + r.stderr
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("MULTICHIP_METRICS ")]
    assert line, r.stdout
    m = json.loads(line[-1].split(" ", 1)[1])
    # the acceptance numbers the harness scores, re-asserted here so a
    # regression fails CI before it fails the scorecard
    assert m["fsdp_loss_max_abs_diff"] <= 1e-5
    assert m["fsdp_param_bytes_frac"] <= 1.0 / m["n_devices"] + 0.05
    assert m["hlo_lint"] == "pass"
    assert m["fsdp_collectives"].get("all-gather", 0) > 0
    assert m["reshard_save8_restore4_bitexact"] is True
    assert m["reshard_restore1_bitexact"] is True
    assert m["llm_tp_token_identical"] is True
    assert m["llm_decode_compiles"] == 1
    assert m["llm_kv_blocks_leaked"] == 0
    # the plan-aware compiled-artifact lints (zoo-lint HLO passes)
    assert m["tp_hlo_lint"] == "pass"
    assert m["llm_decode_artifact_lint"] == "pass"
    # pipeline plan: GPipe schedule == dp, body really pipe-sharded,
    # collective-permute present (the "pipeline that isn't" lint)
    assert m["pipeline_loss_max_abs_diff"] <= 1e-5
    assert m["pipeline_body_bytes_frac"] <= 0.25 + 0.05
    assert m["pipeline_collectives"].get("collective-permute", 0) > 0
    assert m["pipeline_hlo_lint"] == "pass"
    # moe plan: expert-sharded FFN == replicated reference
    assert m["moe_out_max_abs_diff"] <= 1e-5
    assert m["moe_expert_bytes_frac"] <= 1.0 / m["n_devices"] + 0.05
    assert m["moe_hlo_lint"] == "pass"
