"""In-suite multichip smoke (the ``multichip`` marker): the same
measured acceptance checks the MULTICHIP harness scores, run as a
subprocess with the 8-device virtual CPU platform forced
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — sharded fit
matches the single-device loss curve, save@8 -> restore@4 -> restore@1
is bit-exact, sharded paged decode is token-identical to the unsharded
reference, and the FSDP HLO lint passes."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.multichip
@pytest.mark.timeout(300)
def test_check_multichip_script_runs():
    r = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_multichip.py")],
        capture_output=True, text=True, timeout=290, cwd=os.getcwd())
    assert r.returncode == 0, r.stdout + r.stderr
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("MULTICHIP_METRICS ")]
    assert line, r.stdout
    m = json.loads(line[-1].split(" ", 1)[1])
    # the acceptance numbers the harness scores, re-asserted here so a
    # regression fails CI before it fails the scorecard
    assert m["fsdp_loss_max_abs_diff"] <= 1e-5
    assert m["fsdp_param_bytes_frac"] <= 1.0 / m["n_devices"] + 0.05
    assert m["hlo_lint"] == "pass"
    assert m["fsdp_collectives"].get("all-gather", 0) > 0
    assert m["reshard_save8_restore4_bitexact"] is True
    assert m["reshard_restore1_bitexact"] is True
    assert m["llm_tp_token_identical"] is True
    assert m["llm_decode_compiles"] == 1
    assert m["llm_kv_blocks_leaked"] == 0
    # the plan-aware compiled-artifact lints (zoo-lint HLO passes)
    assert m["tp_hlo_lint"] == "pass"
    assert m["llm_decode_artifact_lint"] == "pass"
