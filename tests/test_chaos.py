"""Deterministic fleet chaos: ChaosSchedule, the wire ``chaos`` op,
replica quarantine, and the full mixed-op chaos storm smoke.

The schedule/state-machine tests are pure-python and fast; the
process-level pieces (quarantine probes, the storm) carry the ``chaos``
marker like their siblings.
"""

import os
import subprocess
import sys
import time

import pytest

from zoo_tpu.util.resilience import ChaosSchedule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------- ChaosSchedule

def test_chaos_schedule_parses_instants_windows_and_params():
    s = ChaosSchedule(
        "kill@2.0:replica=1;slow@0.5-3.0:replica=0,delay_ms=80;"
        "corrupt@1.0-2.0:p=0.25", seed=7, replicas=3)
    kinds = [e["kind"] for e in s.resolved()]
    assert kinds == ["slow", "corrupt", "kill"]  # sorted by t0
    slow = s.resolved()[0]
    assert slow["t0"] == 0.5 and slow["t1"] == 3.0
    assert slow["params"] == {"replica": 0, "delay_ms": 80}
    assert s.horizon == 3.0


def test_chaos_schedule_same_seed_same_sequence():
    spec = "kill@1.0~4.0:replica=?;slow@0.2~0.8-5.0:replica=?,delay_ms=50"
    a = ChaosSchedule(spec, seed=42, replicas=5)
    b = ChaosSchedule(spec, seed=42, replicas=5)
    assert a.resolved() == b.resolved()
    c = ChaosSchedule(spec, seed=43, replicas=5)
    assert a.resolved() != c.resolved()
    # draws landed inside their ranges
    kill = next(e for e in a.resolved() if e["kind"] == "kill")
    assert 1.0 <= kill["t0"] <= 4.0
    assert kill["params"]["replica"] in range(5)


def test_chaos_schedule_env_defaults(monkeypatch):
    monkeypatch.setenv("ZOO_CHAOS_SPEC", "kill@1.5:replica=0")
    monkeypatch.setenv("ZOO_CHAOS_SEED", "99")
    s = ChaosSchedule()
    assert s.seed == 99
    assert s.resolved() == [{"kind": "kill", "t0": 1.5, "t1": None,
                             "params": {"replica": 0}}]


def test_chaos_schedule_rejects_malformed():
    with pytest.raises(ValueError):
        ChaosSchedule("kill:replica=0", seed=0)  # no @time
    with pytest.raises(ValueError):
        ChaosSchedule("slow@3.0-1.0", seed=0)  # window closes early
    with pytest.raises(ValueError):
        ChaosSchedule("kill@1.0:replica=?", seed=0)  # ? needs replicas=
    with pytest.raises(ValueError):
        ChaosSchedule("kill@1.0:replica", seed=0)  # param missing '='


def test_chaos_schedule_run_dispatches_start_and_end():
    calls = []
    s = ChaosSchedule("a@0.01-0.05:x=1;b@0.02", seed=0)
    s.run({"a": lambda ev, ph: calls.append(("a", ph)),
           "b": lambda ev, ph: calls.append(("b", ph))})
    assert s.join(timeout=5.0)
    assert calls == [("a", "start"), ("b", "start"), ("a", "end")]


def test_chaos_schedule_action_errors_never_kill_the_run():
    calls = []

    def boom(ev, ph):
        raise RuntimeError("chaos action bug")

    s = ChaosSchedule("a@0.0;b@0.02", seed=0)
    s.run({"a": boom, "b": lambda ev, ph: calls.append("b")})
    assert s.join(timeout=5.0)
    assert calls == ["b"]


def test_chaos_schedule_reseeds_injector_for_replayable_pdraws():
    from zoo_tpu.util.resilience import FaultInjector
    seqs = []
    for _ in range(2):
        inj = FaultInjector()
        s = ChaosSchedule("noop@0.0", seed=123)
        s.run({"noop": lambda ev, ph: None}, injector=inj)
        assert s.join(timeout=5.0)
        inj.inject("x", exc=None, action=lambda **k: None, p=0.5)
        fired = []
        for _ in range(32):
            before = inj.fired("x")
            inj.fire("x")
            fired.append(inj.fired("x") > before)
        seqs.append(fired)
    assert seqs[0] == seqs[1], "p-draws did not replay under one seed"


# ------------------------------------------------- the wire chaos op

def test_chaos_op_refused_without_allow_env():
    import numpy as np

    from zoo_tpu.serving.ha import SyntheticModel
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import _Connection

    os.environ.pop("ZOO_CHAOS_ALLOW", None)
    srv = ServingServer(SyntheticModel(), port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        conn = _Connection(srv.host, srv.port)
        resp = conn.rpc({"op": "chaos", "site": "serving.infer",
                         "delay_ms": 50})
        assert "error" in resp and "ZOO_CHAOS_ALLOW" in resp["error"]
        conn.close()
        # the door still serves
        conn = _Connection(srv.host, srv.port)
        out = conn.rpc({"op": "predict", "uri": "u",
                        "data": np.ones((1, 2), np.float32)})
        np.testing.assert_allclose(out["result"], 2.0)
        conn.close()
    finally:
        srv.stop()


def test_chaos_op_arms_and_clears_local_injector(monkeypatch):
    import numpy as np

    from zoo_tpu.serving.ha import SyntheticModel
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import _Connection
    from zoo_tpu.util.resilience import clear_faults, default_injector

    monkeypatch.setenv("ZOO_CHAOS_ALLOW", "1")
    srv = ServingServer(SyntheticModel(), port=0, batch_size=2,
                        max_wait_ms=1.0).start()
    try:
        conn = _Connection(srv.host, srv.port)
        assert conn.rpc({"op": "chaos", "site": "serving.infer",
                         "delay_ms": 120})["ok"]
        t0 = time.perf_counter()
        conn.rpc({"op": "predict", "uri": "u",
                  "data": np.ones((1, 2), np.float32)})
        assert time.perf_counter() - t0 >= 0.1, \
            "armed delay did not slow the op"
        assert default_injector.fired("serving.infer") >= 1
        assert conn.rpc({"op": "chaos", "site": "serving.infer",
                         "clear": 1})["ok"]
        t0 = time.perf_counter()
        conn.rpc({"op": "predict", "uri": "u",
                  "data": np.ones((1, 2), np.float32)})
        assert time.perf_counter() - t0 < 0.1, "clear did not disarm"
        conn.close()
    finally:
        clear_faults()
        srv.stop()


# ------------------------------------------------- quarantine (chaos)

@pytest.mark.chaos
def test_exhausted_restart_budget_quarantines_not_group_teardown(
        monkeypatch):
    """A crash-looping seat past max_restarts is QUARANTINED (gauge +
    healthz verdict + siblings keep serving), then probed back on the
    backoff timer and re-admitted once a probe survives the heal
    window — never again the silent permanent death."""
    monkeypatch.setenv("ZOO_QUARANTINE_PROBE_S", "1.0")
    monkeypatch.setenv("ZOO_QUARANTINE_HEAL_S", "2.0")
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient

    group = ReplicaGroup("synthetic:double", num_replicas=2,
                         max_restarts=1).start(timeout=60)
    cli = HAServingClient(group.endpoints(), deadline_ms=8000,
                          hedge=False)
    try:
        # exhaust replica 0's budget: kill, wait for respawn, kill again
        for k in (1, 2):
            group.kill_replica(0)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                w = group._monitor.workers[0]
                if w.quarantined or (w.restarts == k
                                     and w.returncode is None):
                    break
                time.sleep(0.05)
            if group._monitor.workers[0].quarantined:
                break
            time.sleep(0.3)  # let the respawn finish booting
        assert group.quarantined() == ["serving-replica-0"]
        # the sibling keeps serving the whole time
        out = np.asarray(cli.predict(np.full((1, 2), 3.0, np.float32)))
        np.testing.assert_allclose(out, 6.0)
        # healthz accounts for the parked seat explicitly
        hz = group.healthz()
        assert hz[0] is not None and hz[0].get("quarantined")
        from zoo_tpu.obs.metrics import get_registry
        gauges = {g["name"]: g["value"]
                  for g in get_registry().snapshot()["gauges"]}
        assert gauges.get("zoo_serve_replicas_quarantined") == 1.0
        # probe respawn + heal window => re-admitted with fresh budget
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and group.quarantined():
            time.sleep(0.2)
        assert not group.quarantined(), "quarantine probe never healed"
        assert group._monitor.workers[0].restarts == 0
        out = np.asarray(cli.predict(np.full((1, 2), 4.0, np.float32)))
        np.testing.assert_allclose(out, 8.0)
    finally:
        cli.close()
        group.stop()


# ------------------------------------------------------ the storm

@pytest.mark.chaos
def test_check_chaos_storm_script_runs():
    """The seeded mixed-op chaos storm (scripts/check_chaos_storm.py):
    slow-replica ejection + frame corruption + SIGKILL + drops under
    sustained predict/generate load — byte-exact streams, zero garbage
    decodes, zero leaked KV blocks, replayable fault sequence."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_chaos_storm.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CHAOS STORM OK" in proc.stdout
