"""Sharding-quality checks: compiled-HLO collective assertions.

The dryrun's value depends on these assertions actually failing when a
sharding spec is broken — the replication regression they exist to catch
still trains with finite loss. ``test_broken_fsdp_spec_fails`` proves the
negative case with a deliberately broken placement.
"""

import numpy as np
import pytest

import jax

from zoo_tpu.parallel.hlo_check import (
    CollectiveError,
    assert_collectives,
    collective_counts,
)


def _small_ncf():
    from zoo_tpu.models.recommendation import NeuralCF
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    m = NeuralCF(user_count=64, item_count=64, class_num=5, user_embed=8,
                 item_embed=8, hidden_layers=(16, 8), mf_embed=8)
    m.compile(optimizer=Adam(lr=1e-3),
              loss="sparse_categorical_crossentropy")
    return m


def _xy(n=32):
    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(0, 64, n), rs.randint(0, 64, n)],
                 axis=1).astype(np.int32)
    return x, rs.randint(0, 5, n).astype(np.int32)


def test_collective_counts_parses_hlo_text():
    txt = """
HloModule jit_step
  %ag = f32[8,64]{1,0} all-gather(%p0), replica_groups={{0,1}}
  %ar.1 = f32[64]{0} all-reduce-start(%g), to_apply=%sum
  %ar.2 = f32[64]{0} all-reduce-done(%ar.1)
  %rs = f32[8]{0} reduce-scatter(%g2), dimensions={0}
  %cp = f32[4]{0} collective-permute(%x), source_target_pairs={{0,1}}
    """
    c = collective_counts(txt)
    assert c == {"all-gather": 1, "all-reduce": 1, "reduce-scatter": 1,
                 "collective-permute": 1}


def test_assert_collectives_modes():
    txt = "%a = f32[4] all-reduce(%g)"
    assert_collectives(txt, require=["all-reduce"], forbid=["all-gather"])
    with pytest.raises(CollectiveError, match="absent"):
        assert_collectives(txt, require=["all-gather"])
    with pytest.raises(CollectiveError, match="none of"):
        assert_collectives(txt, require_any=["all-gather",
                                             "reduce-scatter"])
    with pytest.raises(CollectiveError, match="forbidden"):
        assert_collectives(txt, forbid=["all-reduce"])


@pytest.fixture
def fsdp_ctx():
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the 8-device CPU mesh")
    init_orca_context(cluster_mode="local", devices=jax.devices()[:n],
                      mesh_axes={"data": n // 2, "fsdp": 2})
    yield
    stop_orca_context()


def test_correct_fsdp_spec_passes(fsdp_ctx):
    m = _small_ncf()
    x, y = _xy()
    hlo = m.lower_train_hlo(x, y, batch_size=8)
    assert_collectives(hlo, require=["all-gather"],
                       require_any=["reduce-scatter", "all-to-all",
                                    "all-reduce"],
                       label="fsdp step")


def test_broken_fsdp_spec_fails(fsdp_ctx):
    """A placement that silently replicates params under an fsdp mesh
    still trains — but the checker must refuse it."""
    from zoo_tpu.parallel.mesh import replicated_sharding

    m = _small_ncf()

    def broken_place(params):
        mesh = m._mesh()
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, replicated_sharding(mesh)), params)

    m._place = broken_place
    x, y = _xy()
    # the broken spec still fits with finite loss — exactly why a
    # run-and-check-loss dryrun can't catch it
    hist = m.fit(x, y, batch_size=8, nb_epoch=1, verbose=0)
    assert np.isfinite(hist["loss"][0])
    hlo = m.lower_train_hlo(x, y, batch_size=8)
    with pytest.raises(CollectiveError):
        assert_collectives(hlo, require=["all-gather"],
                           require_any=["reduce-scatter", "all-to-all",
                                        "all-reduce"],
                           label="broken fsdp step")
