"""Sharding-quality checks: compiled-HLO collective assertions.

The dryrun's value depends on these assertions actually failing when a
sharding spec is broken — the replication regression they exist to catch
still trains with finite loss. ``test_broken_fsdp_spec_fails`` proves the
negative case with a deliberately broken placement.
"""

import numpy as np
import pytest

import jax

from zoo_tpu.parallel.hlo_check import (
    CollectiveError,
    assert_collectives,
    collective_counts,
)


def _small_ncf():
    from zoo_tpu.models.recommendation import NeuralCF
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    m = NeuralCF(user_count=64, item_count=64, class_num=5, user_embed=8,
                 item_embed=8, hidden_layers=(16, 8), mf_embed=8)
    m.compile(optimizer=Adam(lr=1e-3),
              loss="sparse_categorical_crossentropy")
    return m


def _xy(n=32):
    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(0, 64, n), rs.randint(0, 64, n)],
                 axis=1).astype(np.int32)
    return x, rs.randint(0, 5, n).astype(np.int32)


def test_collective_counts_parses_hlo_text():
    txt = """
HloModule jit_step
  %ag = f32[8,64]{1,0} all-gather(%p0), replica_groups={{0,1}}
  %ar.1 = f32[64]{0} all-reduce-start(%g), to_apply=%sum
  %ar.2 = f32[64]{0} all-reduce-done(%ar.1)
  %rs = f32[8]{0} reduce-scatter(%g2), dimensions={0}
  %cp = f32[4]{0} collective-permute(%x), source_target_pairs={{0,1}}
    """
    c = collective_counts(txt)
    assert c == {"all-gather": 1, "all-reduce": 1, "reduce-scatter": 1,
                 "collective-permute": 1}


def test_assert_collectives_modes():
    txt = "%a = f32[4] all-reduce(%g)"
    assert_collectives(txt, require=["all-reduce"], forbid=["all-gather"])
    with pytest.raises(CollectiveError, match="absent"):
        assert_collectives(txt, require=["all-gather"])
    with pytest.raises(CollectiveError, match="none of"):
        assert_collectives(txt, require_any=["all-gather",
                                             "reduce-scatter"])
    with pytest.raises(CollectiveError, match="forbidden"):
        assert_collectives(txt, forbid=["all-reduce"])


@pytest.fixture
def fsdp_ctx():
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the 8-device CPU mesh")
    init_orca_context(cluster_mode="local", devices=jax.devices()[:n],
                      mesh_axes={"data": n // 2, "fsdp": 2})
    yield
    stop_orca_context()


def test_correct_fsdp_spec_passes(fsdp_ctx):
    m = _small_ncf()
    x, y = _xy()
    hlo = m.lower_train_hlo(x, y, batch_size=8)
    assert_collectives(hlo, require=["all-gather"],
                       require_any=["reduce-scatter", "all-to-all",
                                    "all-reduce"],
                       label="fsdp step")


def test_broken_fsdp_spec_fails(fsdp_ctx):
    """A placement that silently replicates params under an fsdp mesh
    still trains — but the checker must refuse it."""
    from zoo_tpu.parallel.mesh import replicated_sharding

    m = _small_ncf()

    def broken_place(params):
        mesh = m._mesh()
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, replicated_sharding(mesh)), params)

    m._place = broken_place
    x, y = _xy()
    # the broken spec still fits with finite loss — exactly why a
    # run-and-check-loss dryrun can't catch it
    hist = m.fit(x, y, batch_size=8, nb_epoch=1, verbose=0)
    assert np.isfinite(hist["loss"][0])
    hlo = m.lower_train_hlo(x, y, batch_size=8)
    with pytest.raises(CollectiveError):
        assert_collectives(hlo, require=["all-gather"],
                           require_any=["reduce-scatter", "all-to-all",
                                        "all-reduce"],
                           label="broken fsdp step")


# ------------------------------------------------- FSDP output lint

_LINT_HLO = """
HloModule jit_step
ENTRY %main.42 (p0: f32[8,16], p1: f32[2,4]) -> (f32[64,64], f32[2,4], f32[]) {
  %p0 = f32[8,16]{1,0} parameter(0)
  %p1 = f32[2,4]{1,0} parameter(1)
  %full = f32[64,64]{1,0} all-gather(%p0), replica_groups={{0,1}}
  %loss = f32[] constant(0)
  ROOT %t = (f32[64,64], f32[2,4], f32[]) tuple(%full, %p1, %loss)
}
"""


def test_entry_output_shapes_and_shaped_ops():
    from zoo_tpu.parallel.hlo_check import entry_output_shapes, shaped_ops

    assert entry_output_shapes(_LINT_HLO) == [(64, 64), (2, 4), ()]
    ops = shaped_ops(_LINT_HLO, "all-gather")
    assert ops == [("full", (64, 64))]


def test_fsdp_lint_catches_replicated_output():
    """The classic silent failure: a supposedly-ZeRO-sharded (64,64)
    param comes back FULL-shape in the entry outputs, produced by an
    all-gather — the lint must fail loudly and name the op."""
    from zoo_tpu.parallel.hlo_check import assert_fsdp_sharded

    with pytest.raises(CollectiveError, match="FSDP"):
        assert_fsdp_sharded(_LINT_HLO, [(64, 64)], label="lint-unit")
    try:
        assert_fsdp_sharded(_LINT_HLO, [(64, 64)], label="lint-unit")
    except CollectiveError as e:
        assert "full" in str(e)          # the offending op name
        assert "(64, 64)" in str(e)      # the offending shape


def test_fsdp_lint_passes_sharded_and_skips_collisions():
    from zoo_tpu.parallel.hlo_check import assert_fsdp_sharded

    # per-device (8,16) output for a (64,64) global param: sharded, fine
    assert_fsdp_sharded(_LINT_HLO, [(64, 128)], label="lint-unit")
    # a replicated param legitimately shares the (64,64) shape: the text
    # lint cannot tell them apart, so the collision is skipped
    assert_fsdp_sharded(_LINT_HLO, [(64, 64)],
                        replicated_shapes=[(64, 64)], label="lint-unit")
    # transient all-gather NOT in the outputs is the plan working
    ok = _LINT_HLO.replace(
        "ROOT %t = (f32[64,64], f32[2,4], f32[]) tuple(%full, %p1, %loss)",
        "ROOT %t = (f32[8,16], f32[2,4], f32[]) tuple(%p0, %p1, %loss)"
    ).replace("-> (f32[64,64], f32[2,4], f32[])",
              "-> (f32[8,16], f32[2,4], f32[])")
    assert_fsdp_sharded(ok, [(64, 64)], label="lint-unit")


def test_fsdp_lint_on_real_compiled_step(fsdp_ctx):
    """End to end on the live mesh: the REAL compiled fsdp train step
    passes; the deliberately replicated placement fails the lint (not
    just the collective-count check)."""
    from zoo_tpu.parallel.hlo_check import assert_fsdp_sharded
    from zoo_tpu.parallel.plans import fsdp_lint_shapes

    m = _small_ncf()
    x, y = _xy()
    hlo = m.lower_train_hlo(x, y, batch_size=8)
    sharded, replicated, local = fsdp_lint_shapes(m.params, m._mesh())
    assert sharded, "plan sharded nothing — test is vacuous"
    assert_fsdp_sharded(hlo, sharded, replicated, local_shapes=local,
                        label="ncf fsdp step")
