"""Foreign dataset ingestion: torch DataLoader and tf.data.Dataset feed
every estimator surface (reference: orca/data tf/torch bridges)."""

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense


def _model(inputs=1):
    m = Sequential()
    m.add(Dense(8, activation="relu", input_shape=(4,)))
    m.add(Dense(1))
    m.compile(optimizer="adam", loss="mse")
    return m


def test_torch_dataloader_fit_predict():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset

    rs = np.random.RandomState(0)
    x = rs.randn(64, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)
    loader = DataLoader(TensorDataset(torch.from_numpy(x),
                                      torch.from_numpy(y)), batch_size=16)
    m = _model()
    h = m.fit(loader, batch_size=16, nb_epoch=4, verbose=0)
    assert h["loss"][-1] < h["loss"][0]
    res = m.evaluate(loader, batch_size=32)
    assert np.isfinite(res["loss"])


def test_tf_dataset_fit():
    tf = pytest.importorskip("tensorflow")
    rs = np.random.RandomState(1)
    x = rs.randn(48, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)
    ds = tf.data.Dataset.from_tensor_slices((x, y)).batch(12)
    m = _model()
    h = m.fit(ds, batch_size=12, nb_epoch=4, verbose=0)
    assert h["loss"][-1] < h["loss"][0]


def test_orca_estimator_with_dataloader():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset
    from zoo_tpu.orca.learn.keras import Estimator

    rs = np.random.RandomState(2)
    x = rs.randn(32, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)
    loader = DataLoader(TensorDataset(torch.from_numpy(x),
                                      torch.from_numpy(y)), batch_size=8)
    est = Estimator.from_keras(_model())
    h = est.fit(loader, epochs=2, batch_size=8)
    assert len(h["loss"]) == 2


def test_empty_loader_raises():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset
    empty = DataLoader(TensorDataset(torch.zeros(0, 4)), batch_size=4)
    with pytest.raises(ValueError, match="empty"):
        _model().fit(empty, batch_size=4, nb_epoch=1, verbose=0)


def test_multi_input_tuple_batches():
    """(x1, x2, y) batches: all-but-last are inputs, last is labels."""
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset
    from zoo_tpu.pipeline.api.keras.engine.data_utils import to_xy_arrays

    rs = np.random.RandomState(3)
    a = rs.randn(20, 4).astype(np.float32)
    b = rs.randn(20, 3).astype(np.float32)
    y = rs.randn(20, 1).astype(np.float32)
    loader = DataLoader(TensorDataset(*(torch.from_numpy(v)
                                        for v in (a, b, y))), batch_size=5)
    xs, ys = to_xy_arrays(loader)
    assert len(xs) == 2 and xs[0].shape == (20, 4) and xs[1].shape == (20, 3)
    assert ys.shape == (20, 1)


def test_dict_collate_batches():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, Dataset
    from zoo_tpu.pipeline.api.keras.engine.data_utils import to_xy_arrays

    class D(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return {"x": np.float32([i, i + 1]), "y": np.float32([2 * i])}

    xs, ys = to_xy_arrays(DataLoader(D(), batch_size=4))
    assert xs[0].shape == (12, 2) and ys.shape == (12, 1)


def test_unbatched_tf_dataset_rejected():
    tf = pytest.importorskip("tensorflow")
    from zoo_tpu.pipeline.api.keras.engine.data_utils import to_xy_arrays
    ds = tf.data.Dataset.from_tensor_slices(
        np.zeros((8, 4), np.float32))  # per-sample, never batched
    with pytest.raises(ValueError, match="must be batched"):
        to_xy_arrays(ds)


def test_separate_y_with_loader_rejected():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset
    from zoo_tpu.pipeline.api.keras.engine.data_utils import to_xy_arrays
    loader = DataLoader(TensorDataset(torch.zeros(8, 4)), batch_size=4)
    with pytest.raises(ValueError, match="separate y"):
        to_xy_arrays(loader, y=np.zeros(8))


def test_dataloader_subclass_detected():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset
    from zoo_tpu.pipeline.api.keras.engine.data_utils import to_xy_arrays

    class MyLoader(DataLoader):
        pass

    xs, _ = to_xy_arrays(MyLoader(TensorDataset(torch.zeros(8, 4)),
                                  batch_size=4))
    assert xs[0].shape == (8, 4)


def test_tf2_estimator_dataset_path():
    tf = pytest.importorskip("tensorflow")
    from zoo_tpu.orca.learn.tf2 import Estimator

    def creator(config):
        m = tf.keras.Sequential([
            tf.keras.layers.Dense(4, activation="relu",
                                  input_shape=(4,)),
            tf.keras.layers.Dense(1)])
        m.compile(optimizer="adam", loss="mse")
        return m

    rs = np.random.RandomState(4)
    x = rs.randn(32, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)
    ds = tf.data.Dataset.from_tensor_slices((x, y)).batch(8)
    est = Estimator.from_keras(model_creator=creator)
    h = est.fit(ds, epochs=2, batch_size=8)
    assert len(h["loss"]) == 2


def test_orca_tf_dataset_builder():
    """reference orca.data.tf.Dataset: from_tensor_slices + map chain."""
    from zoo_tpu.orca.data.shard import LocalXShards
    from zoo_tpu.orca.data.tf.data import Dataset

    rs = np.random.RandomState(0)
    x = rs.randn(24, 4).astype(np.float32)
    y = rs.randint(0, 2, 24).astype(np.int64)
    shards = LocalXShards.partition({"x": x, "y": y}, num_shards=3)
    ds = Dataset.from_tensor_slices(shards)
    assert len(ds) == 24
    ds2 = ds.map(lambda xy: (xy[0] * 2.0, xy[1]))
    gx, gy = ds2.to_numpy()
    np.testing.assert_allclose(gx, x * 2.0, atol=1e-6)
    np.testing.assert_array_equal(gy, y)
    # original dataset unchanged (map is deferred + non-destructive)
    ox, _ = ds.to_numpy()
    np.testing.assert_allclose(ox, x, atol=1e-6)


def test_orca_tf_dataset_to_tf():
    tf = pytest.importorskip("tensorflow")
    from zoo_tpu.orca.data.tf.data import Dataset
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    ds = Dataset.from_tensor_slices({"x": x}).to_tf_dataset(batch_size=3)
    batches = list(ds.as_numpy_iterator())
    assert len(batches) == 2 and batches[0].shape == (3, 2)


def test_orca_tf_dataset_via_compat_path():
    from zoo.orca.data.tf.data import Dataset  # reference import line
    ds = Dataset.from_tensor_slices(np.zeros((4, 2), np.float32))
    assert len(ds) == 4


def test_orca_tf_dataset_feeds_estimator_directly():
    from zoo_tpu.orca.data.tf.data import Dataset
    rs = np.random.RandomState(5)
    x = rs.randn(32, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)
    ds = Dataset.from_tensor_slices({"x": x, "y": y})
    m = _model()
    h = m.fit(ds, batch_size=8, nb_epoch=3, verbose=0)
    assert h["loss"][-1] < h["loss"][0]


def test_orca_tf_dataset_ntuple_and_mismatch():
    from zoo_tpu.orca.data.tf.data import Dataset
    a = np.zeros((5, 2), np.float32)
    b = np.ones((5, 3), np.float32)
    w = np.full((5,), 2.0, np.float32)
    ds = Dataset.from_tensor_slices((a, b, w))
    assert len(ds) == 5
    xs, ys = ds.to_numpy()
    assert ys is None and len(xs) == 3 and xs[1].shape == (5, 3)
    with pytest.raises(ValueError, match="disagree on length"):
        Dataset.from_tensor_slices({"x": np.zeros((4, 2)),
                                    "y": np.zeros((3,))})


def test_orca_tf_dataset_dict_columns():
    from zoo_tpu.orca.data.tf.data import Dataset
    ds = Dataset.from_tensor_slices({"a": np.arange(4), "b": np.ones(4)})
    cols, ys = ds.to_numpy()
    assert ys is None and set(cols) == {"a", "b"}
    np.testing.assert_array_equal(cols["a"], np.arange(4))
