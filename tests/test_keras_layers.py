import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras.layers import (
    Activation, BatchNormalization, Dense, Dropout, Embedding, Flatten,
    Merge, Reshape, merge,
)


def _build_call(layer, x, training=False, rng=None):
    params = layer.build(jax.random.PRNGKey(0), (None,) + x.shape[1:])
    return params, layer.call(params, jnp.asarray(x),
                              training=training, rng=rng)


def test_dense_shapes_and_math():
    x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    layer = Dense(5, activation="relu")
    params, y = _build_call(layer, x)
    assert y.shape == (4, 5)
    expected = np.maximum(x @ np.asarray(params["W"]) + np.asarray(params["b"]), 0)
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5)
    assert layer.compute_output_shape((None, 3)) == (None, 5)


def test_dense_no_bias_and_init():
    layer = Dense(2, bias=False, init="zero")
    x = np.ones((2, 3), np.float32)
    params, y = _build_call(layer, x)
    assert "b" not in params
    np.testing.assert_array_equal(np.asarray(y), np.zeros((2, 2)))


def test_dropout_train_vs_eval():
    x = np.ones((8, 100), np.float32)
    layer = Dropout(0.5)
    _, y_eval = _build_call(layer, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_eval), x)
    _, y_train = _build_call(layer, x, training=True,
                             rng=jax.random.PRNGKey(1))
    arr = np.asarray(y_train)
    assert ((arr == 0) | (arr == 2.0)).all()
    assert 0.3 < (arr == 0).mean() < 0.7


def test_embedding():
    layer = Embedding(10, 4)
    ids = np.array([[1, 2], [3, 9]])
    params, y = _build_call(layer, ids)
    assert y.shape == (2, 2, 4)
    np.testing.assert_allclose(np.asarray(y)[0, 0], np.asarray(params["E"])[1])


def test_flatten_reshape():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    _, y = _build_call(Flatten(), x)
    assert y.shape == (2, 12)
    _, z = _build_call(Reshape((4, -1)), x)
    assert z.shape == (2, 4, 3)


def test_batchnorm_train_stats():
    x = np.random.RandomState(0).randn(64, 5).astype(np.float32) * 3 + 1
    layer = BatchNormalization()
    params = layer.build(jax.random.PRNGKey(0), (None, 5))
    y = layer.call(params, jnp.asarray(x), training=True)
    arr = np.asarray(y)
    np.testing.assert_allclose(arr.mean(axis=0), 0, atol=1e-4)
    np.testing.assert_allclose(arr.std(axis=0), 1, atol=1e-2)
    new_stats = layer.updated_stats(params, jnp.asarray(x))
    assert not np.allclose(np.asarray(new_stats["mean"]), 0)


def test_merge_modes():
    a = np.ones((2, 3), np.float32)
    b = np.full((2, 3), 2.0, np.float32)
    m = Merge(mode="concat")
    y = m.call({}, [jnp.asarray(a), jnp.asarray(b)])
    assert y.shape == (2, 6)
    y = Merge(mode="sum").call({}, [jnp.asarray(a), jnp.asarray(b)])
    np.testing.assert_array_equal(np.asarray(y), np.full((2, 3), 3.0))
    y = Merge(mode="dot").call({}, [jnp.asarray(a), jnp.asarray(b)])
    np.testing.assert_array_equal(np.asarray(y), np.full((2, 1), 6.0))
    assert Merge(mode="concat").compute_output_shape(
        [(None, 3), (None, 4)]) == (None, 7)
