"""Model-zoo specs: each model builds, trains a couple of steps (loss
decreases or stays finite) and predicts with the right shapes — the
reference's per-model spec pattern."""

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras.optimizers import Adam


@pytest.mark.heavy
def test_wide_and_deep(orca_ctx):
    from zoo_tpu.models.recommendation.wide_and_deep import (
        ColumnFeatureInfo,
        WideAndDeep,
    )

    rs = np.random.RandomState(0)
    n = 256
    ci = ColumnFeatureInfo(
        wide_base_cols=["gender"], wide_base_dims=[2],
        wide_cross_cols=["age_gender"], wide_cross_dims=[50],
        embed_cols=["user", "item"], embed_in_dims=[40, 60],
        embed_out_dims=[8, 8],
        continuous_cols=["age"])
    x = np.stack([
        rs.randint(0, 2, n), rs.randint(0, 50, n),
        rs.randint(0, 40, n), rs.randint(0, 60, n),
        rs.uniform(18, 60, n),
    ], axis=1).astype(np.float32)
    y = ((x[:, 0] + x[:, 2]) % 2).astype(np.int32)

    m = WideAndDeep(class_num=2, column_info=ci)
    m.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = m.fit(x, y, batch_size=32, nb_epoch=4, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    assert m.predict(x[:8]).shape == (8, 2)

    wide_only = WideAndDeep(class_num=2, column_info=ci, model_type="wide")
    wide_only.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy")
    assert np.isfinite(
        wide_only.fit(x, y, batch_size=32, nb_epoch=1,
                      verbose=0)["loss"][0])


@pytest.mark.slow
def test_text_classifier(orca_ctx):
    from zoo_tpu.models.textclassification import TextClassifier

    rs = np.random.RandomState(0)
    n, T, V = 128, 20, 50
    x = rs.randint(0, V, (n, T)).astype(np.int32)
    y = (x[:, 0] % 3).astype(np.int32)
    for encoder in ("cnn", "gru"):
        m = TextClassifier(class_num=3, token_length=8, sequence_length=T,
                           vocab=V, encoder=encoder, encoder_output_dim=16)
        m.compile(optimizer=Adam(lr=0.01),
                  loss="sparse_categorical_crossentropy")
        hist = m.fit(x, y, batch_size=32, nb_epoch=3, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        assert m.predict(x[:8]).shape == (8, 3)


def test_session_recommender(orca_ctx):
    from zoo_tpu.models.recommendation.session_recommender import (
        SessionRecommender,
    )

    rs = np.random.RandomState(0)
    n, L, items = 128, 6, 30
    x = rs.randint(1, items + 1, (n, L)).astype(np.int32)
    y = ((x[:, -1] + 1) % (items + 1)).astype(np.int32)
    m = SessionRecommender(item_count=items, item_embed=16,
                           rnn_hidden_layers=(16,), session_length=L)
    m.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy")
    hist = m.fit(x, y, batch_size=32, nb_epoch=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    recs = m.recommend_for_session(x[:4], max_items=3)
    assert len(recs) == 4 and len(recs[0]) == 3
    assert all(isinstance(i, int) for i, _ in recs[0])


def test_seq2seq_model(orca_ctx):
    from zoo_tpu.models.seq2seq import Seq2seq

    rs = np.random.RandomState(0)
    x = rs.randn(64, 6, 3).astype(np.float32)
    y = np.repeat(x.mean(axis=1, keepdims=True), 4, axis=1)[..., :2]
    m = Seq2seq(input_length=6, input_dim=3, target_length=4, output_dim=2,
                hidden_size=16)
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    hist = m.fit(x, y.reshape(64, -1).reshape(64, 4, 2), batch_size=32,
                 nb_epoch=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    assert m.predict(x[:8]).shape == (8, 4, 2)


def test_anomaly_detector_model(orca_ctx):
    from zoo_tpu.models.anomalydetection import AnomalyDetector

    series = np.sin(np.arange(300) / 10.0).astype(np.float32)
    x, y = AnomalyDetector.unroll(series, unroll_length=10)
    assert x.shape == (290, 10, 1)
    m = AnomalyDetector(feature_shape=(10, 1), hidden_layers=(8, 8),
                        dropouts=(0.0, 0.0))
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    hist = m.fit(x, y.reshape(-1, 1), batch_size=32, nb_epoch=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    preds = m.predict(x)
    # inject an anomaly and find it
    y_bad = y.copy()
    y_bad[100] += 10
    idx = m.detect_anomalies(y_bad, preds.ravel(), anomaly_size=1)
    assert idx == [100]


def test_knrm(orca_ctx):
    from zoo_tpu.models.ranking import KNRM

    rs = np.random.RandomState(0)
    n, q, d, V = 128, 5, 10, 40
    x = rs.randint(0, V, (n, q + d)).astype(np.int32)
    # relevant iff query token 0 appears in the doc
    y = np.array([1.0 if x[i, 0] in x[i, q:] else 0.0
                  for i in range(n)], np.float32).reshape(-1, 1)
    m = KNRM(text1_length=q, text2_length=d, vocab_size=V, embed_size=16,
             kernel_num=11)
    m.compile(optimizer=Adam(lr=0.01), loss="binary_crossentropy")
    hist = m.fit(x, y, batch_size=32, nb_epoch=5, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    assert m.predict(x[:8]).shape == (8, 1)


@pytest.mark.slow
def test_resnet18_tiny(orca_ctx):
    from zoo_tpu.models.image import resnet18

    rs = np.random.RandomState(0)
    x = rs.randn(16, 32, 32, 3).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    m = resnet18(class_num=2, input_shape=(32, 32, 3))
    m.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy")
    hist = m.fit(x, y, batch_size=8, nb_epoch=2, verbose=0)
    assert np.isfinite(hist["loss"]).all()
    assert m.predict(x[:4]).shape == (4, 2)
    # params exist for all BN layers (stats carried)
    n_bn = sum(1 for p in m.params.values()
               if isinstance(p, dict) and "stats" in p)
    assert n_bn > 10


@pytest.mark.heavy
def test_ssd_detection_pipeline(orca_ctx):
    """SSD: anchors, decode, NMS, end-to-end predict_detections layout."""
    import jax.numpy as jnp

    from zoo_tpu.models.image import SSD, decode_boxes, nms

    m = SSD(n_classes=4, input_size=64, feature_channels=(16, 32))
    assert m.anchors.shape[1] == 4
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
    dets = m.predict_detections(x, score_threshold=0.0, top_k=10)
    assert len(dets) == 2
    for det in dets:
        assert det.shape[1] == 6
        assert det.shape[0] <= 10
        labels = det[:, 0]
        assert ((labels >= 1) & (labels < 4)).all()  # bg never emitted

    # NMS suppresses an overlapping lower-scored box, keeps disjoint one
    boxes = jnp.asarray([[0.0, 0.0, 0.5, 0.5],
                         [0.01, 0.01, 0.51, 0.51],
                         [0.6, 0.6, 0.9, 0.9]])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    _, kept_scores, _ = nms(boxes, scores, top_k=3, iou_threshold=0.5)
    kept = np.asarray(kept_scores)
    assert kept[0] > 0 and kept[2] > 0 and kept[1] == 0

    # decode identity: zero deltas give the anchor box corners
    anchors = jnp.asarray([[0.5, 0.5, 0.2, 0.2]])
    out = np.asarray(decode_boxes(anchors, jnp.zeros((1, 4))))
    np.testing.assert_allclose(out, [[0.4, 0.4, 0.6, 0.6]], atol=1e-6)


@pytest.mark.heavy
def test_object_detector_image_set(orca_ctx):
    from zoo_tpu.feature.image import ImageSet
    from zoo_tpu.models.image import SSD, ObjectDetector

    m = SSD(n_classes=3, input_size=64, feature_channels=(16, 32))
    det = ObjectDetector(m, label_map={1: "cat", 2: "dog"})
    imgs = [np.random.randint(0, 255, (80, 100, 3), np.uint8)
            for _ in range(3)]
    iset = ImageSet.from_arrays(imgs)
    out = det.predict_image_set(iset, score_threshold=0.0)
    preds = out.get_predict()
    assert len(preds) == 3 and all(p.shape[1] == 6 for p in preds)
