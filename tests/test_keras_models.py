import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from zoo_tpu.pipeline.api.keras.layers import (
    BatchNormalization, Dense, Dropout, Embedding, Flatten, merge,
)


def _toy_regression(n=256, d=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    w = rs.randn(d, 1).astype(np.float32)
    y = x @ w + 0.01 * rs.randn(n, 1).astype(np.float32)
    return x, y


def test_sequential_fit_loss_decreases(orca_ctx):
    x, y = _toy_regression()
    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(4,)))
    model.add(Dense(1))
    from zoo_tpu.pipeline.api.keras.optimizers import Adam
    model.compile(optimizer=Adam(lr=0.01), loss="mse")
    history = model.fit(x, y, batch_size=32, nb_epoch=8, verbose=0)
    assert history["loss"][-1] < history["loss"][0] * 0.5
    preds = model.predict(x[:10])
    assert preds.shape == (10, 1)


def test_sequential_with_bn_dropout(orca_ctx):
    x, y = _toy_regression(n=128)
    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(4,)))
    model.add(BatchNormalization())
    model.add(Dropout(0.2))
    model.add(Dense(1))
    model.compile(optimizer="sgd", loss="mse")
    model.fit(x, y, batch_size=32, nb_epoch=2, verbose=0)
    stats = model.params["batchnormalization_1"]["stats"] \
        if "batchnormalization_1" in model.params else None
    # find the BN layer params regardless of auto-name counter
    bn = [p for p in model.params.values()
          if isinstance(p, dict) and "stats" in p][0]
    assert not np.allclose(np.asarray(bn["stats"]["mean"]), 0)


@pytest.mark.heavy
def test_functional_two_tower(orca_ctx):
    """Two-input functional model (the NCF topology shape)."""
    rs = np.random.RandomState(0)
    n = 256
    user = rs.randint(0, 20, (n,))
    item = rs.randint(0, 30, (n,))
    y = ((user + item) % 2).astype(np.float32).reshape(-1, 1)

    u_in = Input(shape=(1,))
    i_in = Input(shape=(1,))
    u_emb = Flatten()(Embedding(20, 8)(u_in))
    i_emb = Flatten()(Embedding(30, 8)(i_in))
    h = merge([u_emb, i_emb], mode="concat")
    h = Dense(16, activation="relu")(h)
    out = Dense(1, activation="sigmoid")(h)
    model = Model(input=[u_in, i_in], output=out)
    model.compile(optimizer="adam", loss="binary_crossentropy",
                  metrics=["accuracy"])
    # the parity target needs the embeddings to separate before accuracy
    # moves: 10 epochs sits at chance (0.59 measured), 25 reaches ~0.9+
    hist = model.fit([user.reshape(-1, 1), item.reshape(-1, 1)], y,
                     batch_size=32, nb_epoch=25, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    res = model.evaluate([user.reshape(-1, 1), item.reshape(-1, 1)], y,
                         batch_size=32)
    assert res["accuracy"] > 0.6


def test_evaluate_metrics_and_summary(orca_ctx):
    x, y = _toy_regression()
    model = Sequential()
    model.add(Dense(1, input_shape=(4,)))
    model.compile(optimizer="adam", loss="mse", metrics=["mae"])
    model.fit(x, y, batch_size=32, nb_epoch=3, verbose=0)
    res = model.evaluate(x, y)
    assert set(res) == {"loss", "mae"}
    scalars = model.get_train_summary("Loss")
    assert len(scalars) == 3 and scalars[0][1] >= scalars[-1][1]
    total = model.summary()
    assert total == 5  # 4 weights + 1 bias


def test_save_load_weights(orca_ctx, tmp_path):
    x, y = _toy_regression(n=64)
    model = Sequential()
    model.add(Dense(2, input_shape=(4,)))
    model.compile(optimizer="adam", loss="mse")
    model.fit(x, y, batch_size=32, nb_epoch=1, verbose=0)
    p = str(tmp_path / "w.pkl")
    model.save_weights(p)
    preds1 = model.predict(x[:8])

    model2 = Sequential()
    model2.add(Dense(2, input_shape=(4,)))
    model2.compile(optimizer="adam", loss="mse")
    model2.load_weights(p)  # position-keyed params restore across instances
    preds2 = model2.predict(x[:8])
    np.testing.assert_allclose(preds1, preds2, rtol=1e-5)


def test_mixed_bfloat16_policy_trains(orca_ctx):
    """mixed_bfloat16: f32 params/optimizer, bf16 compute with f32 islands
    in the normalizations — loss must still converge and predictions come
    back f32."""
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import BatchNormalization, Dense

    rs = np.random.RandomState(0)
    x = rs.randn(256, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    m = Sequential()
    m.add(Dense(16, input_shape=(8,), activation="relu"))
    m.add(BatchNormalization())
    m.add(Dense(2, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              dtype_policy="mixed_bfloat16")
    hist = m.fit(x, y, batch_size=32, nb_epoch=8, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.7
    preds = m.predict(x[:16])
    assert preds.dtype == np.float32
    assert preds.shape == (16, 2)
    # params stayed f32 (policy casts compute only; note bf16's numpy
    # dtype kind is 'V', so assert directly against bfloat16)
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(m.params)
    assert leaves and not any(
        l.dtype == jnp.bfloat16 for l in leaves if hasattr(l, "dtype"))


def test_epoch_scan_matches_host_fed_fit():
    """The whole-epoch single-dispatch path (small device-resident
    dataset: permutation-gather + full-epoch scan in one jit call) must
    produce the SAME loss trajectory as the host-fed per-superbatch
    path — same seed, same step order, same math."""
    import jax
    import jax.numpy as jnp

    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(cluster_mode="local", devices=[jax.devices()[0]])
    try:
        x, y = _toy_regression(n=256)

        def build():
            m = Sequential()
            m.add(Dense(8, activation="relu", input_shape=(4,)))
            m.add(Dense(1))
            from zoo_tpu.pipeline.api.keras.optimizers import Adam
            m.compile(optimizer=Adam(lr=0.01), loss="mse")
            return m

        host = build().fit(x, y, batch_size=32, nb_epoch=4, seed=7,
                           shuffle=True, verbose=0)
        m_dev = build()
        dev = m_dev.fit(jnp.asarray(x), jnp.asarray(y), batch_size=32,
                        nb_epoch=4, seed=7, shuffle=True, verbose=0)
        # the device-resident run must actually have taken the epoch path
        assert getattr(m_dev, "_jit_epoch_cache", None), \
            "epoch-scan path not taken"
        np.testing.assert_allclose(host["loss"], dev["loss"], rtol=2e-5)

        # shuffle=False takes the no-gather variant (reshape, no perm)
        host_nf = build().fit(x, y, batch_size=32, nb_epoch=2, seed=7,
                              shuffle=False, verbose=0)
        m_nf = build()
        dev_nf = m_nf.fit(jnp.asarray(x), jnp.asarray(y), batch_size=32,
                          nb_epoch=2, seed=7, shuffle=False, verbose=0)
        assert any(k[:3] == (8, 32, False)
                   for k in m_nf._jit_epoch_cache)
        np.testing.assert_allclose(host_nf["loss"], dev_nf["loss"],
                                   rtol=2e-5)
    finally:
        stop_orca_context()


def test_epoch_scan_matches_host_fed_on_dp_mesh():
    """The whole-epoch dispatch also runs on a multi-device DP mesh (the
    batch dim pinned onto the data axes inside the jit); trajectories
    must match the host-fed superbatch path. Explicit data=8 mesh: the
    mesh.size>1 sharding branch must actually execute."""
    import jax.numpy as jnp

    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(mesh_axes={"data": 8})
    try:
        x, y = _toy_regression(n=256)

        def build():
            m = Sequential()
            m.add(Dense(8, activation="relu", input_shape=(4,)))
            m.add(Dense(1))
            from zoo_tpu.pipeline.api.keras.optimizers import Adam
            m.compile(optimizer=Adam(lr=0.01), loss="mse")
            return m

        assert build()._mesh().size == 8  # the branch under test is live
        host = build().fit(x, y, batch_size=32, nb_epoch=3, seed=3,
                           shuffle=True, verbose=0)
        m_dev = build()
        dev = m_dev.fit(jnp.asarray(x), jnp.asarray(y), batch_size=32,
                        nb_epoch=3, seed=3, shuffle=True, verbose=0)
        assert getattr(m_dev, "_jit_epoch_cache", None), \
            "epoch-scan path not taken on the DP mesh"
        np.testing.assert_allclose(host["loss"], dev["loss"], rtol=2e-5)
    finally:
        stop_orca_context()


def test_recompile_invalidates_epoch_cache():
    """compile() (and the grad-clip setters) must drop the cached
    whole-epoch step: it bakes loss/optimizer/clip in at trace time, so
    a stale entry would silently train with the OLD settings."""
    import jax
    import jax.numpy as jnp

    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(cluster_mode="local", devices=[jax.devices()[0]])
    try:
        x, y = _toy_regression(n=64)
        m = Sequential()
        m.add(Dense(1, input_shape=(4,)))
        m.compile(optimizer="adam", loss="mse")
        m.fit(jnp.asarray(x), jnp.asarray(y), batch_size=16, nb_epoch=1,
              shuffle=False, verbose=0)
        assert m._jit_epoch_cache
        m.compile(optimizer="adam", loss="mae")
        assert not m._jit_epoch_cache
        m.fit(jnp.asarray(x), jnp.asarray(y), batch_size=16, nb_epoch=1,
              shuffle=False, verbose=0)
        assert m._jit_epoch_cache
        m.set_constant_gradient_clipping(-1.0, 1.0)
        assert not m._jit_epoch_cache
    finally:
        stop_orca_context()


def test_save_after_device_resident_fit(tmp_path):
    """A single-chip fit on an HBM-resident dataset caches a jitted
    staging fn; save()/to_bytes() must clear it like every other jit
    cache or cloudpickle dies on the PjitFunction."""
    import jax
    import jax.numpy as jnp

    from zoo_tpu.orca import init_orca_context, stop_orca_context

    init_orca_context(cluster_mode="local", devices=[jax.devices()[0]])
    try:
        x, y = _toy_regression(n=64)
        model = Sequential()
        model.add(Dense(2, input_shape=(4,)))
        model.compile(optimizer="adam", loss="mse")
        # batch 24: 64 % 24 != 0 keeps the whole-epoch path OFF so this
        # fit exercises the _jit_stage superbatch path it exists to cover
        model.fit(jnp.asarray(x), jnp.asarray(y), batch_size=24,
                  nb_epoch=1, shuffle=False, verbose=0)
        assert getattr(model, "_jit_stage", None) is not None
        p = str(tmp_path / "m.zoo")
        model.save(p)
        m2 = Sequential.load(p)
        np.testing.assert_allclose(np.asarray(model.predict(x[:4])),
                                   np.asarray(m2.predict(x[:4])),
                                   rtol=1e-5)
    finally:
        stop_orca_context()
