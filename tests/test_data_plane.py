"""Unit tests for the multi-host shard data plane (single-process parts:
assignment math, codec safety, the TCP exchange round trip)."""

import numpy as np
import pytest

from zoo_tpu.orca.data.plane import (
    ShardExchange,
    _decode_shard,
    _encode_shard,
    assign_shards,
)


def test_assign_balanced_noop():
    # already balanced: nothing moves
    plan = assign_shards([4, 4])
    assert plan == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_assign_locality_first():
    # host0 holds 6, host1 holds 2: only host0's surplus (ids 4, 5) moves
    plan = assign_shards([6, 2])
    assert plan[0] == [0, 1, 2, 3]
    assert plan[1] == [6, 7, 4, 5]
    moved = set(plan[1]) - {6, 7}
    assert moved == {4, 5}


def test_assign_remainder_and_empty_host():
    plan = assign_shards([7, 0, 2])
    # totals 9 over 3 hosts -> 3 each; every id assigned exactly once
    assert sorted(x for p in plan for x in p) == list(range(9))
    assert [len(p) for p in plan] == [3, 3, 3]
    # host2 keeps both of its own shards (ids 7, 8)
    assert {7, 8} <= set(plan[2])


def test_codec_roundtrip_and_no_pickle():
    shard = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
             "y": np.array([1, 2, 3], np.int64)}
    blob = _encode_shard(shard)
    out = _decode_shard(blob)
    assert set(out) == {"x", "y"}
    np.testing.assert_array_equal(out["x"], shard["x"])
    # object arrays (the pickle vector) are rejected at encode time
    with pytest.raises(TypeError):
        _encode_shard({"o": "not-an-array"})  # type: ignore[dict-item]


def test_exchange_fetch_roundtrip():
    shards = {7: {"x": np.ones((4, 2), np.float32)},
              9: {"x": np.zeros((1, 2), np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    try:
        got = ShardExchange.fetch(("127.0.0.1", ex.port), 7)
        np.testing.assert_array_equal(got["x"], shards[7]["x"])
        with pytest.raises(KeyError):
            ShardExchange.fetch(("127.0.0.1", ex.port), 8)
    finally:
        ex.close()


def test_rebalance_single_process_passthrough():
    from zoo_tpu.orca.data import LocalXShards, rebalance_shards

    shards = LocalXShards([{"x": np.ones((2, 2), np.float32)}])
    out = rebalance_shards(shards)
    assert out.num_partitions() == 1
