"""Unit tests for the multi-host shard data plane (single-process parts:
assignment math, codec safety, the TCP exchange round trip, the v2
pooled/pipelined client, and the staged ingest pipeline)."""

import os
import socket
import struct
import subprocess
import sys
import threading

import numpy as np
import pytest

from zoo_tpu.orca.data.ingest import PipelineStats, staged_pipeline
from zoo_tpu.orca.data.plane import (
    ProtocolError,
    ShardExchange,
    _ConnPool,
    _decode_shard,
    _encode_shard,
    _pool,
    assign_shards,
    fetch_many,
    iter_fetch,
)


@pytest.fixture(autouse=True)
def _fresh_pool():
    """Each test starts and ends with an empty connection pool — a
    pooled socket to a closed test exchange must not leak across."""
    _pool.clear()
    yield
    _pool.clear()


def test_assign_balanced_noop():
    # already balanced: nothing moves
    plan = assign_shards([4, 4])
    assert plan == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_assign_locality_first():
    # host0 holds 6, host1 holds 2: only host0's surplus (ids 4, 5) moves
    plan = assign_shards([6, 2])
    assert plan[0] == [0, 1, 2, 3]
    assert plan[1] == [6, 7, 4, 5]
    moved = set(plan[1]) - {6, 7}
    assert moved == {4, 5}


def test_assign_remainder_and_empty_host():
    plan = assign_shards([7, 0, 2])
    # totals 9 over 3 hosts -> 3 each; every id assigned exactly once
    assert sorted(x for p in plan for x in p) == list(range(9))
    assert [len(p) for p in plan] == [3, 3, 3]
    # host2 keeps both of its own shards (ids 7, 8)
    assert {7, 8} <= set(plan[2])


def test_codec_roundtrip_and_no_pickle():
    shard = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
             "y": np.array([1, 2, 3], np.int64)}
    blob = _encode_shard(shard)
    out = _decode_shard(blob)
    assert set(out) == {"x", "y"}
    np.testing.assert_array_equal(out["x"], shard["x"])
    # object arrays (the pickle vector) are rejected at encode time
    with pytest.raises(TypeError):
        _encode_shard({"o": "not-an-array"})  # type: ignore[dict-item]


def test_exchange_fetch_roundtrip():
    shards = {7: {"x": np.ones((4, 2), np.float32)},
              9: {"x": np.zeros((1, 2), np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    try:
        got = ShardExchange.fetch(("127.0.0.1", ex.port), 7)
        np.testing.assert_array_equal(got["x"], shards[7]["x"])
        with pytest.raises(KeyError):
            ShardExchange.fetch(("127.0.0.1", ex.port), 8)
    finally:
        ex.close()


def test_rebalance_single_process_passthrough():
    from zoo_tpu.orca.data import LocalXShards, rebalance_shards

    shards = LocalXShards([{"x": np.ones((2, 2), np.float32)}])
    out = rebalance_shards(shards)
    assert out.num_partitions() == 1


def test_rebalance_single_process_stage_fn():
    import jax

    from zoo_tpu.orca.data import LocalXShards, rebalance_shards

    shards = LocalXShards([{"x": np.full((2, 2), float(i), np.float32)}
                           for i in range(3)])
    out = rebalance_shards(shards, stage_fn=jax.device_put)
    parts = out.collect()
    assert len(parts) == 3
    for i, p in enumerate(parts):  # order preserved, values staged
        assert hasattr(p["x"], "devices")
        np.testing.assert_array_equal(np.asarray(p["x"]),
                                      np.full((2, 2), float(i)))


# ------------------------------------------------------------- codec v2

def test_codec_dtype_zoo_roundtrip():
    """Every estimator-relevant dtype survives the raw-tensor wire
    format: bool, (u)int8/32/64, f16/bf16/f32, 0-d and empty arrays."""
    import ml_dtypes

    rs = np.random.RandomState(0)
    shard = {
        "bool": np.array([True, False, True]),
        "i8": rs.randint(-128, 127, (5, 3)).astype(np.int8),
        "u8": rs.randint(0, 255, (4,)).astype(np.uint8),
        "i32": rs.randint(-1000, 1000, (2, 2, 2)).astype(np.int32),
        "u32": rs.randint(0, 1000, (3,)).astype(np.uint32),
        "i64": rs.randint(-10, 10, (6,)).astype(np.int64),
        "u64": rs.randint(0, 10, (2, 5)).astype(np.uint64),
        "f16": rs.randn(3, 4).astype(np.float16),
        "bf16": rs.randn(4, 2).astype(ml_dtypes.bfloat16),
        "f32": rs.randn(2, 3, 4).astype(np.float32),
        "scalar": np.array(3.5, np.float32),
        "empty": np.zeros((0, 7), np.int64),
    }
    out = _decode_shard(_encode_shard(shard))
    assert set(out) == set(shard)
    for k in shard:
        assert out[k].dtype == shard[k].dtype, k
        assert out[k].shape == shard[k].shape, k
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(shard[k]))


def test_codec_dtype_zoo_over_the_wire():
    import ml_dtypes

    shard = {"bf16": np.arange(6).astype(ml_dtypes.bfloat16).reshape(2, 3),
             "scalar": np.array(7, np.int32),
             "empty": np.zeros((0, 2), np.float16)}
    ex = ShardExchange({0: shard}, bind="127.0.0.1")
    try:
        got = ShardExchange.fetch(("127.0.0.1", ex.port), 0)
        for k in shard:
            assert got[k].dtype == shard[k].dtype
            np.testing.assert_array_equal(np.asarray(got[k]),
                                          np.asarray(shard[k]))
    finally:
        ex.close()


def test_codec_rejects_object_dtype():
    with pytest.raises(TypeError, match="object dtype"):
        _encode_shard({"o": np.array([{"pickle": "vector"}], object)})


def test_codec_rejects_structured_dtype_at_encode_time():
    """Structured/record dtypes have no round-trippable wire descriptor
    — they must fail at encode (and exchange construction), never as a
    decode error on the peer after bytes are on the wire."""
    rec = np.array([(1, 2.0)], dtype=[("a", "<i4"), ("b", "<f4")])
    with pytest.raises(TypeError, match="wire descriptor"):
        _encode_shard({"r": rec})
    with pytest.raises(TypeError, match="wire descriptor"):
        ShardExchange({0: {"r": rec}}, bind="127.0.0.1")


def test_iter_fetch_early_exit_does_not_block_on_stalled_peer():
    """Abandoning the fetch generator (consumer break / pipeline
    teardown) must not sit out the stalled chunks' full retry budgets."""
    import time

    fast = ShardExchange({0: {"x": np.zeros(4, np.float32)}},
                         bind="127.0.0.1")
    stalled = socket.socket()  # accepts, never answers
    stalled.bind(("127.0.0.1", 0))
    stalled.listen(4)
    try:
        gen = iter_fetch(
            [(("127.0.0.1", fast.port), [0]),
             (("127.0.0.1", stalled.getsockname()[1]), [1])],
            timeout=10.0, concurrency=1)
        next(gen)  # the fast peer's shard arrives
        t0 = time.perf_counter()
        gen.close()
        assert time.perf_counter() - t0 < 5.0
    finally:
        fast.close()
        stalled.close()


def test_codec_rejects_corrupt_payload_length():
    """A payload length that disagrees with shape x dtype is a corrupt
    or desynchronized stream: loud ProtocolError BEFORE any allocation
    (a trusted u64 would let one flipped bit demand a 2^60-byte
    buffer)."""
    blob = bytearray(_encode_shard(
        {"x": np.arange(6, dtype=np.float32).reshape(2, 3)}))
    # header: i32 count | u16 nlen + name | u16 dlen + descr | u8 ndim
    # | ndim*u64 dims | u64 nbytes
    nlen = 1
    (dlen,) = struct.unpack("!H", blob[6 + nlen:8 + nlen])
    off = 4 + 2 + nlen + 2 + dlen + 1 + 16
    blob[off:off + 8] = struct.pack("!Q", 1 << 60)
    with pytest.raises(ProtocolError, match="does not match shape"):
        _decode_shard(blob)


def test_exchange_serves_lazily_no_blob_copies():
    """v2 serves straight from the caller's arrays: constructing the
    exchange must not pre-encode (the v1 behavior doubled resident
    memory before a byte moved)."""
    arr = np.ones((8, 8), np.float32)
    ex = ShardExchange({0: {"x": arr}}, bind="127.0.0.1")
    try:
        assert not hasattr(ex, "_blobs")
        assert ex._shards[0]["x"] is arr
    finally:
        ex.close()


def test_v1_magic_rejected_loudly(caplog):
    """A protocol-v1 peer must fail loudly, not hang or corrupt: the
    server logs the version mismatch and drops the connection."""
    import logging

    ex = ShardExchange({0: {"x": np.zeros(2, np.float32)}},
                       bind="127.0.0.1")
    try:
        with caplog.at_level(logging.ERROR, "zoo_tpu.orca.data.plane"):
            with socket.create_connection(("127.0.0.1", ex.port),
                                          timeout=10) as s:
                s.sendall(b"ZSX1" + struct.pack("!I", 0))
                s.settimeout(10)
                try:
                    assert s.recv(1) == b""  # server closed on us
                except ConnectionError:
                    pass  # RST instead of FIN: also "closed on us"
        assert any("ZSX1" in r.message for r in caplog.records)
    finally:
        ex.close()


def test_client_raises_protocol_error_on_foreign_magic():
    """A v2 client reading a non-v2 response frame raises ProtocolError
    (never retried — a version mismatch is deterministic)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def fake_peer():
        conn, _ = srv.accept()
        with conn:
            conn.recv(64)  # swallow the request
            conn.sendall(b"ZSX9" + struct.pack("!Ii", 0, 0))

    t = threading.Thread(target=fake_peer, daemon=True)
    t.start()
    try:
        with pytest.raises(ProtocolError, match="version mismatch"):
            ShardExchange.fetch(("127.0.0.1", srv.getsockname()[1]), 0,
                                pool=False)
    finally:
        srv.close()
        t.join(timeout=10)


# ------------------------------------------------- pooling + pipelining

def test_persistent_connection_reuse():
    """N sequential fetches ride ONE connection per peer."""
    shards = {i: {"x": np.full((4,), float(i), np.float32)}
              for i in range(10)}
    ex = ShardExchange(shards, bind="127.0.0.1")
    try:
        addr = ("127.0.0.1", ex.port)
        for i in range(10):
            got = ShardExchange.fetch(addr, i)
            np.testing.assert_array_equal(np.asarray(got["x"]),
                                          shards[i]["x"])
        fetch_many(addr, list(range(10)))
        assert ex.connections_accepted == 1
        # the baseline mode really does dial per call
        ShardExchange.fetch(addr, 0, pool=False)
        assert ex.connections_accepted == 2
    finally:
        ex.close()


def test_multiget_streams_on_one_connection():
    shards = {i: {"x": np.full((3, 2), float(i), np.float32),
                  "y": np.array([i], np.int64)} for i in range(7)}
    ex = ShardExchange(shards, bind="127.0.0.1")
    try:
        out = fetch_many(("127.0.0.1", ex.port), [5, 1, 3])
        assert set(out) == {5, 1, 3}
        for g in out:
            np.testing.assert_array_equal(np.asarray(out[g]["x"]),
                                          shards[g]["x"])
        assert ex.connections_accepted == 1
        # a missing gid mid-stream is a plan bug: KeyError, not a retry
        with pytest.raises(KeyError):
            fetch_many(("127.0.0.1", ex.port), [2, 99, 4])
    finally:
        ex.close()


def test_concurrent_multi_peer_fetch():
    """iter_fetch fans out over several peers concurrently and returns
    every shard intact."""
    exchanges = []
    sources = []
    try:
        for p in range(3):
            shards = {p * 10 + i: {"x": np.full((16,), p * 10.0 + i,
                                                np.float32)}
                      for i in range(8)}
            ex = ShardExchange(shards, bind="127.0.0.1")
            exchanges.append(ex)
            sources.append((("127.0.0.1", ex.port), sorted(shards)))
        got = dict(iter_fetch(sources, concurrency=3))
        assert sorted(got) == sorted(g for _, gs in sources for g in gs)
        for gid, shard in got.items():
            np.testing.assert_array_equal(
                np.asarray(shard["x"]), np.full((16,), float(gid)))
    finally:
        for ex in exchanges:
            ex.close()


def test_pool_invalidated_when_peer_restarts():
    """A pooled connection to a dead peer is dropped and the retry
    re-dials — a restarted peer on the same port keeps working."""
    shards = {0: {"x": np.arange(4, dtype=np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    addr = ("127.0.0.1", ex.port)
    ShardExchange.fetch(addr, 0)  # pool a live connection
    port = ex.port
    ex.close()
    # restart on the SAME port: the pooled socket is now a corpse
    ex2 = _exchange_on_port(shards, port)
    try:
        got = ShardExchange.fetch(addr, 0)
        np.testing.assert_array_equal(np.asarray(got["x"]), shards[0]["x"])
    finally:
        ex2.close()


def _exchange_on_port(shards, port, tries=50):
    """A ShardExchange bound to a SPECIFIC port (tests only; brief bind
    retry while the previous incarnation's sockets drain)."""
    import time

    ex = ShardExchange.__new__(ShardExchange)
    ex._shards = dict(shards)
    ex._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ex._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    for attempt in range(tries):
        try:
            ex._srv.bind(("127.0.0.1", port))
            break
        except OSError:
            if attempt == tries - 1:
                raise
            time.sleep(0.1)
    ex._srv.listen(64)
    ex.port = port
    ex.connections_accepted = 0
    ex._closed = False
    ex._conns = set()
    ex._conns_lock = threading.Lock()
    ex._thread = threading.Thread(target=ex._serve, daemon=True)
    ex._thread.start()
    return ex


# ------------------------------------------------------- ingest pipeline

def test_staged_pipeline_order_and_stats():
    stats = PipelineStats()
    with staged_pipeline(iter(range(20)),
                         [("double", lambda x: 2 * x),
                          ("inc", lambda x: x + 1)],
                         stats=stats) as pipe:
        out = list(pipe)
    assert out == [2 * i + 1 for i in range(20)]
    assert stats.items["double"] == stats.items["inc"] == 20
    assert stats.overlap_ratio() == stats.overlap_ratio()  # not NaN


def test_staged_pipeline_propagates_stage_error():
    def boom(x):
        if x == 3:
            raise ValueError("stage blew up")
        return x

    with staged_pipeline(iter(range(10)), [("boom", boom)]) as pipe:
        with pytest.raises(ValueError, match="stage blew up"):
            list(pipe)


def test_staged_pipeline_close_releases_threads():
    release = threading.Event()

    def slow(x):
        release.wait(5)
        return x

    pipe = staged_pipeline(iter(range(100)), [("slow", slow)])
    it = iter(pipe)
    pipe.close()
    release.set()
    with pytest.raises(StopIteration):
        while True:
            next(it)


@pytest.mark.chaos
def test_peer_death_mid_stream_retries_without_deadlock():
    """A peer dying mid-pipelined-stream (connection drops after some
    responses were already sent) is retried on a fresh connection, and
    the ingest pipeline drains completely — no deadlock, no loss."""
    from zoo_tpu.util.resilience import RetryPolicy, inject

    shards = {i: {"x": np.full((32,), float(i), np.float32)}
              for i in range(12)}
    ex = ShardExchange(shards, bind="127.0.0.1")
    addr = ("127.0.0.1", ex.port)
    died = []

    def die_once(site, gid=None, **ctx):
        if gid == 5 and not died:
            died.append(1)
            raise ConnectionError("injected peer death mid-stream")

    retry = RetryPolicy(max_attempts=4, base_delay=0.01, max_delay=0.05)
    try:
        with inject("shard.serve", action=die_once):
            stats = PipelineStats()
            with staged_pipeline(
                    iter_fetch([(addr, sorted(shards))], retry=retry),
                    [("ingest", lambda kv: kv)], stats=stats) as pipe:
                got = dict(pipe)
        assert died, "the injected mid-stream death never fired"
        assert sorted(got) == sorted(shards)
        for gid in shards:
            np.testing.assert_array_equal(np.asarray(got[gid]["x"]),
                                          shards[gid]["x"])
        # the death cost exactly one extra dial (retry on a fresh conn)
        assert ex.connections_accepted == 2
    finally:
        ex.close()


def test_conn_pool_bounds_idle_sockets():
    pool = _ConnPool(max_idle_per_peer=2)
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    addr = ("127.0.0.1", srv.getsockname()[1])
    try:
        socks = [pool.acquire(addr, 5.0) for _ in range(4)]
        for s in socks:
            pool.release(addr, s)
        assert len(pool._idle[addr]) == 2  # the rest were closed
        pool.invalidate(addr)
        assert addr not in pool._idle
    finally:
        srv.close()


# ------------------------------------------------- adaptive readahead

def test_readahead_controller_walks_toward_hiding_fetch():
    """Source-bound windows grow concurrency (then chunk); over-
    provisioned windows step concurrency back down; bounds hold."""
    from zoo_tpu.orca.data.ingest import ReadaheadController
    from zoo_tpu.orca.data.plane import ExchangeConfig

    class FakeStats:
        def __init__(self):
            self.busy = {"source": 0.0}
            self._w = 0.0

        def wall(self):
            return self._w

    cfg = ExchangeConfig(multiget=8, concurrency=2)
    st = FakeStats()
    c = ReadaheadController(cfg, st, window=1, max_concurrency=8,
                            max_chunk=32)
    # fetch dominates the window -> concurrency doubles to its cap...
    for i in range(1, 3):
        st._w = float(i)
        st.busy["source"] = 0.9 * i
        c.on_chunk(8, 1 << 20, 0.1)
    assert cfg.concurrency == 8
    assert cfg.multiget == 8  # untouched while concurrency has headroom
    # ...then the chunk grows instead
    st._w, st.busy["source"] = 3.0, 2.7
    c.on_chunk(8, 1 << 20, 0.1)
    assert cfg.multiget == 16
    # fetch fully hidden -> unwind: width first, then the chunk back
    # toward its floor — and never below either floor
    for i in range(4, 24):
        st._w = float(i)
        st.busy["source"] = 2.7  # no new source time at all
        c.on_chunk(8, 1 << 20, 0.1)
    assert cfg.concurrency == 1
    assert cfg.multiget == c.min_chunk
    assert c.decisions, "controller recorded no decisions"


def test_iter_fetch_respects_controller_resizing():
    """A controller shrinking the chunk mid-exchange still yields every
    shard exactly once (lazy carving re-reads config.multiget)."""
    from zoo_tpu.orca.data.plane import ExchangeConfig

    shards = {i: {"x": np.full((8,), float(i), np.float32)}
              for i in range(24)}
    ex = ShardExchange(shards, bind="127.0.0.1")
    cfg = ExchangeConfig(multiget=8, concurrency=2)

    class ShrinkOnce:
        max_concurrency = 4

        def __init__(self):
            self.calls = 0

        def on_chunk(self, ngids, nbytes, seconds):
            self.calls += 1
            cfg.multiget = 3  # next chunks are carved smaller

    ctl = ShrinkOnce()
    try:
        got = dict(iter_fetch([(("127.0.0.1", ex.port), sorted(shards))],
                              config=cfg, controller=ctl))
        assert sorted(got) == sorted(shards)
        for g in shards:
            np.testing.assert_array_equal(np.asarray(got[g]["x"]),
                                          shards[g]["x"])
        assert ctl.calls >= 2
    finally:
        ex.close()


# ------------------------------------------------- staging buffer pool

def test_staging_buffer_pool_rotates_and_preserves_contents():
    from zoo_tpu.orca.data.ingest import StagingBufferPool

    rs = np.random.RandomState(0)
    arrs = [rs.randn(40, 3).astype(np.float32),
            rs.randint(0, 9, 40).astype(np.int64)]
    pool = StagingBufferPool(arrs, rows=8, nbufs=3)
    idx1, idx2 = np.arange(8), np.arange(8, 16)
    a = pool.take(arrs, idx1)
    b = pool.take(arrs, idx2)
    # distinct buffers: writing batch 2 must not disturb batch 1
    assert a[0].base is not b[0].base
    np.testing.assert_array_equal(a[0], arrs[0][idx1])
    np.testing.assert_array_equal(b[1], arrs[1][idx2])
    pool.recycle()  # oldest (a's buffer) returns to the pool
    c = pool.take(arrs, np.arange(16, 20))  # ragged tail: prefix view
    assert c[0].shape == (4, 3)
    np.testing.assert_array_equal(c[0], arrs[0][16:20])
    pool.reset()


def test_staging_buffer_pool_starvation_is_loud():
    from zoo_tpu.orca.data.ingest import StagingBufferPool

    arrs = [np.zeros((4, 2), np.float32)]
    pool = StagingBufferPool(arrs, rows=2, nbufs=1)
    pool.take(arrs, np.arange(2))
    with pytest.raises(RuntimeError, match="starved"):
        pool.take(arrs, np.arange(2), timeout=0.05)


def test_staging_buffer_pool_fences_stale_generation():
    """Stage threads surviving a non-joining pipeline teardown
    (``DoubleBufferedIterator.close()`` only signals, never joins)
    must not touch the next epoch's slots: ``take``/``recycle`` calls
    carrying a superseded generation token get plain slices / no-op
    instead of popping the new epoch's in-flight buffers mid-DMA."""
    from zoo_tpu.orca.data.ingest import StagingBufferPool

    arrs = [np.arange(20, dtype=np.float32).reshape(10, 2)]
    pool = StagingBufferPool(arrs, rows=4, nbufs=2)
    gen1 = pool.reset()
    pool.take(arrs, np.arange(4), gen=gen1)           # epoch 1 in flight
    gen2 = pool.reset()                               # epoch 2 begins
    new = pool.take(arrs, np.arange(4, 8), gen=gen2)  # epoch 2 oldest slot
    # zombie put thread from epoch 1 finishes: must NOT free epoch 2's
    # oldest slot (the silent-corruption path)
    pool.recycle(gen=gen1)
    # zombie slice thread from epoch 1: plain copies, pool untouched
    stale = pool.take(arrs, np.arange(4), gen=gen1)
    assert stale[0].base is not new[0].base
    np.testing.assert_array_equal(stale[0], arrs[0][:4])
    # epoch 2 still owns full capacity: its recycle frees ITS oldest,
    # and both slots remain reachable (a leaked slot would starve here)
    pool.recycle(gen=gen2)
    pool.take(arrs, np.arange(4), gen=gen2, timeout=0.5)
    pool.take(arrs, np.arange(4), gen=gen2, timeout=0.5)


def test_fit_host_feed_uses_staging_pool_and_matches_plain(monkeypatch):
    """The host-fed superbatch feed stages through the rotating buffer
    pool (on backends where device_put provably copies) and produces
    bit-identical training to the plain-allocation path."""
    import zoo_tpu.orca.data.ingest as ing
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    calls = []
    orig_take = ing.StagingBufferPool.take

    def spy(self, arrs, idx, **kw):
        calls.append(len(idx))
        return orig_take(self, arrs, idx, **kw)

    monkeypatch.setattr(ing.StagingBufferPool, "take", spy)
    rs = np.random.RandomState(0)
    x = rs.randn(256, 8).astype(np.float32)
    y = (x @ rs.randn(8, 1)).astype(np.float32)

    def run(staging):
        monkeypatch.setenv("ZOO_FEED_STAGING", staging)
        m = Sequential()
        m.add(Dense(8, input_shape=(8,), activation="relu"))
        m.add(Dense(1))
        m.compile(optimizer="sgd", loss="mse")
        return m.fit(x, y, batch_size=32, nb_epoch=2, shuffle=True,
                     seed=11, verbose=0)["loss"]

    pooled = run("auto")
    assert calls, "staging pool never engaged on the host-fed path"
    n_pooled = len(calls)
    plain = run("off")
    assert len(calls) == n_pooled, "ZOO_FEED_STAGING=off did not disable"
    np.testing.assert_allclose(pooled, plain, rtol=1e-6)


# ------------------------------------------------------------ CPU smoke

@pytest.mark.perf
@pytest.mark.timeout(120)
def test_check_data_plane_script_runs():
    """The 2-process exchange smoke (pipelined beats serial, pool
    metrics export) — the same command CI and operators run."""
    r = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_data_plane.py")],
        capture_output=True, text=True, timeout=110, cwd=os.getcwd())
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok:" in r.stdout
