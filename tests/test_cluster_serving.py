"""Cluster Serving with the reference Redis wire format.

Hermetic, like the reference's embedded-redis specs
(``RedisIOSpec.scala`` backed by ``zoo/pom.xml:568`` embedded-redis):
an in-process RESP server carries the real stream/hash protocol; the
client code is shaped exactly like reference ``serving/client.py``
(InputQueue.enqueue → XADD, OutputQueue.query → HGETALL of
``cluster-serving_<stream>:<uri>``)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.inference import InferenceModel
from zoo_tpu.serving import (
    ClusterServing,
    EmbeddedRedis,
    FrontEnd,
    InputQueue,
    OutputQueue,
)


@pytest.fixture()
def serving_stack(orca_ctx):
    r = EmbeddedRedis().start()
    m = Sequential()
    m.add(Dense(4, input_shape=(6,), activation="relu"))
    m.add(Dense(2))
    m.compile(optimizer="adam", loss="mse")
    m.build()
    im = InferenceModel()
    im.load_keras(m)
    cs = ClusterServing(im, redis_port=r.port).start()
    yield r, im, cs
    cs.stop()
    r.stop()


def _wait_query(oq, uri, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = oq.query(uri)
        if not (isinstance(out, str) and out == "[]"):
            return out
        time.sleep(0.02)
    raise TimeoutError(uri)


def test_enqueue_query_roundtrip(serving_stack):
    r, im, cs = serving_stack
    iq = InputQueue(port=r.port)
    oq = OutputQueue(port=r.port)
    x = np.random.RandomState(0).randn(6).astype(np.float32)
    iq.enqueue("req-1", t=x)
    out = _wait_query(oq, "req-1")
    ref = im.predict(x[None])[0]
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
    # query consumed nothing; query_and_delete removes
    assert not isinstance(oq.query("req-1"), str)
    oq.query_and_delete("req-1")
    assert oq.query("req-1") == "[]"


def test_sync_predict_and_batching(serving_stack):
    r, im, cs = serving_stack
    iq = InputQueue(port=r.port)
    rs = np.random.RandomState(1)
    xs = rs.randn(5, 6).astype(np.float32)
    outs = [np.asarray(iq.predict(xs[i])) for i in range(5)]
    refs = im.predict(xs)
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o, refs[i], atol=1e-5)
    assert cs.records_out >= 5
    stats = cs.metrics()
    assert stats["inference"]["count"] >= 1


def test_dequeue_all(serving_stack):
    r, im, cs = serving_stack
    iq = InputQueue(port=r.port)
    oq = OutputQueue(port=r.port)
    x = np.random.RandomState(2).randn(6).astype(np.float32)
    iq.enqueue("a", t=x)
    iq.enqueue("b", t=x * 2)
    _wait_query(oq, "a")
    _wait_query(oq, "b")
    res = oq.dequeue()
    assert set(res) == {"a", "b"}
    assert oq.dequeue() == {}  # drained


def test_http_frontend(serving_stack):
    r, im, cs = serving_stack
    iq = InputQueue(port=r.port)
    fe = FrontEnd(cs, iq).start()
    try:
        x = np.random.RandomState(3).randn(6).astype(np.float32)
        body = json.dumps({"instances": [{"t": x.tolist()}]}).encode()
        req = urllib.request.Request(
            f"http://{fe.host}:{fe.port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=15).read())
        val = json.loads(json.loads(resp["predictions"][0])["value"])
        got = np.asarray(val["data"]).reshape(val["shape"])
        ref = im.predict(x[None])[0]
        np.testing.assert_allclose(got, ref, atol=1e-4)
        met = json.loads(urllib.request.urlopen(
            f"http://{fe.host}:{fe.port}/metrics", timeout=15).read())
        assert met["records_out"] >= 1
    finally:
        fe.stop()


def test_nan_contract_on_bad_input(serving_stack):
    """Unpredictable records answer "NaN" (reference behavior for failed
    inference), not silence."""
    r, im, cs = serving_stack
    iq = InputQueue(port=r.port)
    oq = OutputQueue(port=r.port)
    bad = np.random.RandomState(4).randn(17).astype(np.float32)  # wrong dim
    iq.enqueue("bad-1", t=bad)
    out = _wait_query(oq, "bad-1")
    assert out == "NaN"


def test_string_and_sparse_schema_roundtrip(serving_stack):
    """The arrow schema must carry the reference's string-list and sparse
    forms too (serving side decodes them)."""
    from zoo_tpu.serving.client import decode_input_b64, encode_input_b64

    x = np.arange(6, dtype=np.float32)
    b64 = encode_input_b64(s=["a", "b", "c"], t=x.reshape(2, 3))
    out = decode_input_b64(b64)
    assert out["s"] == "a|b|c"
    np.testing.assert_allclose(out["t"], x.reshape(2, 3))
