"""Llama family (BASELINE stretch row): forward shapes, GQA vs MHA-repeat
equivalence, RoPE relative-position property, 8B config accounting, tiny
causal-LM training, and the FSDP/TP sharded train step on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zoo_tpu.models.llm import (
    Llama,
    LlamaConfig,
    llama3_8b_config,
    llama_param_count,
    tiny_llama_config,
)
from zoo_tpu.models.llm.llama import apply_rope, rope_frequencies
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential


def _build(cfg, **kw):
    layer = Llama(cfg, **kw)
    params = layer.build(jax.random.PRNGKey(0), (None, 16))
    return layer, params


@pytest.mark.heavy
def test_forward_shapes():
    cfg = tiny_llama_config()
    layer, params = _build(cfg)
    ids = np.random.RandomState(0).randint(0, cfg.vocab, (2, 16))
    out = layer.call(params, jnp.asarray(ids))
    assert out.shape == (2, 16, cfg.vocab)
    hidden = Llama(cfg, lm_head=False)
    p2 = hidden.build(jax.random.PRNGKey(0), (None, 16))
    assert hidden.call(p2, jnp.asarray(ids)).shape == (2, 16, cfg.hidden)


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = tiny_llama_config()
    layer, params = _build(cfg)
    rs = np.random.RandomState(1)
    ids = rs.randint(0, cfg.vocab, (1, 12))
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 7) % cfg.vocab
    a = np.asarray(layer.call(params, jnp.asarray(ids)))
    b = np.asarray(layer.call(params, jnp.asarray(ids2)))
    np.testing.assert_allclose(a[0, :-1], b[0, :-1], atol=1e-5)
    assert np.abs(a[0, -1] - b[0, -1]).max() > 1e-6


def test_gqa_equals_explicit_repeat():
    """n_kv_head < n_head must equal an MHA whose kv weights are the
    repeated group weights."""
    cfg = tiny_llama_config()
    layer, params = _build(cfg)
    mha_cfg = LlamaConfig(**{**cfg.__dict__, "n_kv_head": cfg.n_head})
    mha = Llama(mha_cfg)
    rep = cfg.n_head // cfg.n_kv_head
    hd = cfg.head_dim

    def widen(w):  # (hidden, kv_heads*hd) -> (hidden, n_head*hd)
        w3 = w.reshape(w.shape[0], cfg.n_kv_head, hd)
        return jnp.repeat(w3, rep, axis=1).reshape(w.shape[0], -1)

    p2 = jax.tree_util.tree_map(lambda x: x, params)
    p2["blocks"] = dict(params["blocks"])
    p2["blocks"]["wk"] = jax.vmap(widen)(params["blocks"]["wk"])
    p2["blocks"]["wv"] = jax.vmap(widen)(params["blocks"]["wv"])
    ids = np.random.RandomState(2).randint(0, cfg.vocab, (2, 8))
    a = np.asarray(layer.call(params, jnp.asarray(ids)))
    b = np.asarray(mha.call(p2, jnp.asarray(ids)))
    np.testing.assert_allclose(a, b, atol=1e-4)


def test_rope_relative_position():
    """RoPE: <rot(q,m), rot(k,n)> depends only on m-n."""
    cos, sin = rope_frequencies(8, 10, 10000.0)
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 1, 10, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 1, 10, 8).astype(np.float32))
    # put the same q-vector at positions 2 and 5, same k at 0 and 3
    q = q.at[0, 0, 5].set(q[0, 0, 2])
    k = k.at[0, 0, 3].set(k[0, 0, 0])
    qr, kr = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    dot_a = float(jnp.dot(qr[0, 0, 2], kr[0, 0, 0]))  # offset 2
    dot_b = float(jnp.dot(qr[0, 0, 5], kr[0, 0, 3]))  # offset 2 again
    np.testing.assert_allclose(dot_a, dot_b, rtol=1e-5)


def test_llama3_8b_param_count():
    cfg = llama3_8b_config()
    n = llama_param_count(cfg)
    assert 7.9e9 < n < 8.1e9, n  # ~8.03B (public card)
    # abstract build agrees with the analytic count — no 8B allocation
    layer = Llama(cfg)
    shapes = jax.eval_shape(
        lambda rng: layer.build(rng, (None, 128)), jax.random.PRNGKey(0))
    total = sum(int(np.prod(s.shape))
                for s in jax.tree_util.tree_leaves(shapes))
    assert total == n, (total, n)


def test_tiny_llama_trains_in_sequential():
    cfg = tiny_llama_config(vocab=64)
    m = Sequential(name="tiny_llama")
    m.add(Llama(cfg, input_shape=(12,)))
    m.compile(optimizer="adam",
              loss="sparse_categorical_crossentropy_from_logits")
    rs = np.random.RandomState(4)
    # learnable sequence: next token = (token + 1) % vocab
    starts = rs.randint(0, 64, (64, 1))
    ids = (starts + np.arange(13)) % 64
    x, y = ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32)
    h = m.fit(x, y, batch_size=32, nb_epoch=15, verbose=0)
    assert h["loss"][-1] < h["loss"][0] * 0.7, h["loss"]


def test_sharded_train_step_fsdp_tp():
    """One jitted train step with data×fsdp×model sharding on the 8-device
    CPU mesh (the BASELINE 'FSDP-style shard over ICI' functionality)."""
    from zoo_tpu.parallel.mesh import build_mesh
    from zoo_tpu.parallel.plans import leaf_sharding, place_params
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = build_mesh(axis_sizes={"data": 2, "fsdp": 2, "model": 2})
    cfg = tiny_llama_config(vocab=64)
    layer = Llama(cfg)
    params = layer.build(jax.random.PRNGKey(0), (None, 8))
    params = place_params(params, mesh)
    # at least one leaf must actually be model- or fsdp-sharded
    specs = {leaf_sharding(mesh, np.shape(l)).spec
             for l in jax.tree_util.tree_leaves(params)}
    assert any(s != P() for s in specs), specs

    ids = np.random.RandomState(5).randint(0, 64, (8, 8)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    batch_sh = NamedSharding(mesh, P(("data", "fsdp")))
    ids_g = jax.device_put(ids, batch_sh)
    labels_g = jax.device_put(labels, batch_sh)

    def loss_fn(p, b, lbl):
        logits = layer.call(p, b)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, lbl[..., None], axis=-1))

    @jax.jit
    def step(p, b, lbl):
        l, g = jax.value_and_grad(loss_fn)(p, b, lbl)
        return l, jax.tree_util.tree_map(lambda w, gr: w - 0.1 * gr, p, g)

    with mesh:
        l0, params = step(params, ids_g, labels_g)
        l1, params = step(params, ids_g, labels_g)
    assert np.isfinite(float(l0)) and float(l1) < float(l0)


@pytest.mark.slow
def test_remat_matches_no_remat():
    """jax.checkpoint must not change numerics; grads agree with the
    stored-activation path."""
    cfg = tiny_llama_config(vocab=32)
    plain = Llama(cfg)
    remat = Llama(cfg, remat=True)
    params = plain.build(jax.random.PRNGKey(0), (None, 8))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    np.testing.assert_allclose(np.asarray(plain.call(params, ids)),
                               np.asarray(remat.call(params, ids)),
                               atol=1e-5)

    def loss(layer, p):
        return jnp.sum(layer.call(p, ids).astype(jnp.float32) ** 2)

    g1 = jax.grad(lambda p: loss(plain, p))(params)
    g2 = jax.grad(lambda p: loss(remat, p))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


def test_ring_attention_impl_matches_dense():
    """attention_impl='ring': sequence-parallel Llama over the seq mesh
    axis produces the dense-path logits (long-context composition)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from zoo_tpu.parallel.mesh import build_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    mesh = build_mesh(jax.devices()[:4], axis_sizes={"seq": 4})
    cfg = tiny_llama_config(vocab=48)
    dense = Llama(cfg)
    ring = Llama(cfg, attention_impl="ring", mesh=mesh)
    params = dense.build(jax.random.PRNGKey(0), (None, 16))
    ids = np.random.RandomState(0).randint(0, 48, (2, 16)).astype(np.int32)
    ref = np.asarray(dense.call(params, jnp.asarray(ids)))

    ids_sharded = jax.device_put(
        ids, NamedSharding(mesh, P(None, "seq")))
    with mesh:
        got = np.asarray(jax.jit(
            lambda p, i: ring.call(p, i))(params, ids_sharded))
    np.testing.assert_allclose(got, ref, atol=2e-4)


def test_ring_impl_without_seq_mesh_raises():
    cfg = tiny_llama_config()
    layer = Llama(cfg, attention_impl="ring")
    params = layer.build(jax.random.PRNGKey(0), (None, 8))
    ids = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="seq axis"):
        layer.call(params, ids)


def test_ring_impl_rejects_seqless_explicit_mesh():
    from zoo_tpu.parallel.mesh import build_mesh
    mesh = build_mesh(jax.devices()[:2], axis_sizes={"data": 2})
    cfg = tiny_llama_config()
    layer = Llama(cfg, attention_impl="ring", mesh=mesh)
    params = layer.build(jax.random.PRNGKey(0), (None, 8))
    with pytest.raises(ValueError, match="seq axis"):
        layer.call(params, jnp.zeros((1, 8), jnp.int32))
