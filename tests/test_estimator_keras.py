"""End-to-end Orca Estimator tests — the rebuild of the reference's
"tiny model, train 2 epochs, assert loss/accuracy improved" pattern
(``test_estimator_pytorch_backend.py``, SURVEY §4.1)."""

import numpy as np
import pandas as pd
import pytest

from zoo_tpu.models.recommendation import NeuralCF, UserItemFeature
from zoo_tpu.orca.data import XShards
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.orca.learn.trigger import EveryEpoch, SeveralIteration
from zoo_tpu.pipeline.api.keras import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.api.keras.optimizers import Adam


def _ml_synth(n=512, users=40, items=60, classes=5, seed=0):
    rs = np.random.RandomState(seed)
    user = rs.randint(0, users, n)
    item = rs.randint(0, items, n)
    label = ((3 * user + 7 * item) % classes)
    return user, item, label


@pytest.mark.heavy
def test_ncf_estimator_xshards_fit(orca_ctx, tmp_path):
    user, item, label = _ml_synth()
    data = XShards.partition({
        "x": np.stack([user, item], axis=1).astype(np.int32),
        "y": label.astype(np.int32),
    }, num_shards=4)

    model = NeuralCF(user_count=40, item_count=60, class_num=5,
                     user_embed=8, item_embed=8, hidden_layers=(16, 8),
                     mf_embed=8)
    model.compile(optimizer=Adam(lr=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    est = Estimator.from_keras(model, model_dir=str(tmp_path / "run"))
    hist = est.fit(data, epochs=6, batch_size=64)
    assert hist["loss"][-1] < hist["loss"][0]

    res = est.evaluate(data, batch_size=64)
    assert res["accuracy"] > 0.3  # 5 classes, learnable rule

    preds = est.predict(data, batch_size=64)
    assert preds.shape == (512, 5)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)

    # checkpoints were written every epoch
    assert est._ckpt.all_steps() == [2, 3, 4, 5, 6]  # max_to_keep=5


def test_ncf_estimator_dataframe_cols(orca_ctx):
    user, item, label = _ml_synth(n=256)
    df = pd.DataFrame({"user": user, "item": item, "label": label})
    shards = XShards.partition
    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(2,)))
    model.add(Dense(5, activation="softmax"))
    model.compile(optimizer=Adam(lr=0.01),
                  loss="sparse_categorical_crossentropy")
    est = Estimator.from_keras(model)
    hist = est.fit(df, epochs=2, batch_size=32,
                   feature_cols=["user", "item"], label_cols=["label"])
    # two inputs stacked as separate features
    assert len(hist["loss"]) == 2


@pytest.mark.heavy
def test_checkpoint_resume(orca_ctx, tmp_path):
    user, item, label = _ml_synth(n=256)
    x = np.stack([user, item], axis=1).astype(np.int32)
    y = label.astype(np.int32)

    def make():
        m = NeuralCF(user_count=40, item_count=60, class_num=5,
                     user_embed=4, item_embed=4, hidden_layers=(8,),
                     include_mf=False)
        m.compile(optimizer=Adam(lr=0.01),
                  loss="sparse_categorical_crossentropy")
        return m

    est = Estimator.from_keras(make(), model_dir=str(tmp_path / "run"))
    est.fit({"x": x, "y": y}, epochs=2, batch_size=64)
    ref_preds = est.predict(x[:32])

    est2 = Estimator.from_keras(make(), model_dir=str(tmp_path / "run"))
    est2.load_orca_checkpoint()
    assert est2._epoch == 2
    got = est2.predict(x[:32])
    np.testing.assert_allclose(ref_preds, got, rtol=1e-4)

    # explicit version restore
    est3 = Estimator.from_keras(make())
    est3.load_orca_checkpoint(path=str(tmp_path / "run"), version=1)
    assert est3._epoch == 1


def test_recommender_helpers(orca_ctx):
    user, item, label = _ml_synth(n=256)
    model = NeuralCF(user_count=40, item_count=60, class_num=5,
                     user_embed=4, item_embed=4, hidden_layers=(8,),
                     include_mf=False)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.fit(np.stack([user, item], 1).astype(np.int32),
              label.astype(np.int32), batch_size=64, nb_epoch=1, verbose=0)
    pairs = [UserItemFeature(int(u), int(i)) for u, i in zip(user[:50],
                                                            item[:50])]
    preds = model.predict_user_item_pair(pairs)
    assert len(preds) == 50
    assert all(0 <= p.prediction < 5 for p in preds)
    top = model.recommend_for_user(pairs, max_items=2)
    per_user = {}
    for p in top:
        per_user[p.user_id] = per_user.get(p.user_id, 0) + 1
    assert max(per_user.values()) <= 2
