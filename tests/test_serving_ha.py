"""Serving high availability (docs/serving_ha.md): deadline propagation
and per-stage enforcement, bounded-queue admission control, request-id
idempotency (server dedup + client stale-frame discard), the HA client's
failover/hedging, and the 3-replica SIGKILL chaos smoke.

Everything here runs against stand-in models (no jax in the serving
path), so the whole file is tier-1 fast; the subprocess chaos smoke
carries the ``chaos`` marker like its siblings.
"""

import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from zoo_tpu.serving.server import ServingServer
from zoo_tpu.serving.tcp_client import TCPInputQueue, _Connection
from zoo_tpu.util.resilience import (
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    clear_faults,
    inject,
)


class _MarkerModel:
    """y = 2x, recording the marker value (column 0) of every row it
    actually computed — the witness that dropped/deduped requests never
    reached inference."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.rows = []
        self._lock = threading.Lock()

    def predict(self, x, batch_size=None):
        x = np.asarray(x)
        with self._lock:
            self.rows.extend(float(v) for v in x[:, 0])
        if self.delay:
            time.sleep(self.delay)
        return x * 2.0

    def seen(self, marker: float) -> int:
        with self._lock:
            return sum(1 for v in self.rows if v == marker)


def _x(marker: float, rows: int = 1) -> np.ndarray:
    return np.full((rows, 4), float(marker), np.float32)


# ------------------------------------------------------------ deadlines

def test_deadline_helper_semantics():
    assert Deadline.from_ms(None) is None
    dl = Deadline.from_ms(0)
    assert dl is not None and dl.expired()
    dl2 = Deadline.from_ms(60000)
    assert not dl2.expired()
    assert 59.0 < dl2.remaining() <= 60.0
    assert dl2.remaining_ms() > 59000


def test_deadline_expired_at_admission_never_computed():
    model = _MarkerModel()
    server = ServingServer(model, port=0, batch_size=4,
                           max_wait_ms=1.0).start()
    try:
        conn = _Connection(server.host, server.port)
        resp = conn.rpc({"op": "predict", "uri": "u", "data": _x(7.0),
                         "deadline_ms": 0.0})
        assert resp.get("expired") is True
        assert "deadline" in resp["error"]
        assert model.seen(7.0) == 0
        conn.close()
    finally:
        server.stop()


def test_deadline_expiry_before_inference_drops_unexecuted():
    """A request that expires while queued behind a slow batch is
    dropped at batch formation — answered "expired", never computed."""
    model = _MarkerModel(delay=0.35)
    server = ServingServer(model, port=0, batch_size=1,
                           max_wait_ms=0.0).start()
    try:
        occupant = threading.Thread(
            target=lambda: TCPInputQueue(server.host,
                                         server.port).predict(_x(1.0)))
        occupant.start()
        time.sleep(0.05)  # the batcher is now inside the slow predict
        q = TCPInputQueue(server.host, server.port)
        with pytest.raises(RuntimeError, match="deadline"):
            q.predict(_x(7.0), deadline_ms=100)
        occupant.join()
        # give the batcher time to pop-and-drop the stale entry
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and server._queue.qsize():
            time.sleep(0.01)
        time.sleep(0.05)
        assert model.seen(7.0) == 0, "expired request was computed"
        assert model.seen(1.0) == 1
        q.close()
    finally:
        server.stop()


def test_request_wait_knob_replaces_hardcoded_timeout(monkeypatch):
    """ZOO_SERVE_REQUEST_TIMEOUT bounds the no-deadline reply wait (the
    former hardcoded 120 s); the env knob is read at server build."""
    monkeypatch.setenv("ZOO_SERVE_REQUEST_TIMEOUT", "0.2")
    monkeypatch.setenv("ZOO_SERVE_HANDSHAKE_TIMEOUT", "3.5")
    model = _MarkerModel(delay=10.0)  # far past the knob
    server = ServingServer(model, port=0, batch_size=1,
                           max_wait_ms=0.0).start()
    try:
        assert server.request_timeout == 0.2
        assert server.handshake_timeout == 3.5
        q = TCPInputQueue(server.host, server.port)
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError,
                           match="ZOO_SERVE_REQUEST_TIMEOUT"):
            q.predict(_x(1.0))
        assert time.perf_counter() - t0 < 5.0
        q.close()
    finally:
        server.stop()


# ----------------------------------------------------- admission control

def test_queue_overflow_sheds_with_retry_hint():
    model = _MarkerModel(delay=0.25)
    server = ServingServer(model, port=0, batch_size=1, max_wait_ms=0.0,
                           max_queue=1).start()
    try:
        results = {"ok": 0, "shed": []}
        lock = threading.Lock()

        def hit(i):
            conn = _Connection(server.host, server.port)
            resp = conn.rpc({"op": "predict", "uri": f"r{i}",
                             "data": _x(float(i))})
            with lock:
                if resp.get("shed"):
                    results["shed"].append(resp)
                else:
                    assert "result" in resp
                    results["ok"] += 1
            conn.close()

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results["ok"] >= 1
        assert results["shed"], "bounded queue never shed"
        for resp in results["shed"]:
            assert resp["retryable"] is True
            assert isinstance(resp["retry_after_ms"], int)
            assert "queue full" in resp["error"]
    finally:
        server.stop()


# ------------------------------------------------- request-id idempotency

def test_request_id_echoed_and_replayed_not_reexecuted():
    model = _MarkerModel()
    server = ServingServer(model, port=0, batch_size=2,
                           max_wait_ms=1.0).start()
    try:
        conn = _Connection(server.host, server.port)
        r1 = conn.rpc({"op": "predict", "uri": "u", "data": _x(9.0),
                       "id": "fixed-req-id"})
        r2 = conn.rpc({"op": "predict", "uri": "u", "data": _x(9.0),
                       "id": "fixed-req-id"})
        assert r1["id"] == r2["id"] == "fixed-req-id"
        np.testing.assert_array_equal(r1["result"], r2["result"])
        assert model.seen(9.0) == 1, "duplicate id re-executed the model"
        conn.close()
    finally:
        server.stop()


def test_mid_rpc_reset_retry_is_idempotent():
    """Regression (fault-injected mid-RPC reset): the connection dies
    AFTER the request reached the server; the client's retry re-sends
    the SAME id and the server dedups — the model runs exactly once and
    the caller still gets the right answer."""
    model = _MarkerModel()
    server = ServingServer(model, port=0, batch_size=2,
                           max_wait_ms=1.0).start()
    try:
        clear_faults()
        with inject("serving.client.recv",
                    exc=ConnectionResetError("mid-RPC reset"),
                    times=1) as armed:
            q = TCPInputQueue(server.host, server.port)
            out = np.asarray(q.predict(_x(13.0)))
            assert armed.fired == 1
        np.testing.assert_allclose(out, _x(13.0) * 2.0)
        assert model.seen(13.0) == 1, \
            "retry after mid-RPC reset double-executed the request"
        q.close()
    finally:
        clear_faults()
        server.stop()


def test_stale_response_discarded_never_mismatched():
    """A frame carrying a DIFFERENT request id (a stale attempt's reply
    buffered on the stream) is discarded, never handed to the caller."""
    from zoo_tpu.serving.codec import dumps

    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    host, port = listener.getsockname()

    def frame(obj) -> bytes:
        payload = dumps(obj)
        return struct.pack(">I", len(payload)) + payload

    def fake_server():
        s, _ = listener.accept()
        from zoo_tpu.serving.server import _recv_msg
        msg = _recv_msg(s)
        # a stale frame first (wrong id, poisoned payload), then the
        # real answer
        s.sendall(frame({"uri": "u", "id": "SOMEONE-ELSE",
                         "result": np.full((1, 4), -1.0, np.float32)}))
        s.sendall(frame({"uri": "u", "id": msg["id"],
                         "result": np.full((1, 4), 42.0, np.float32)}))
        s.close()

    t = threading.Thread(target=fake_server, daemon=True)
    t.start()
    try:
        conn = _Connection(host, port)
        resp = conn.rpc({"op": "predict", "uri": "u", "data": _x(5.0)})
        np.testing.assert_allclose(resp["result"], 42.0)
        conn.close()
        t.join(timeout=5)
    finally:
        listener.close()


# --------------------------------------------------------- the HA client

def _dead_endpoint():
    """A (host, port) with nothing listening."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return ("127.0.0.1", port)


def test_ha_client_fails_over_to_healthy_replica():
    from zoo_tpu.serving.ha_client import HAServingClient

    model = _MarkerModel()
    server = ServingServer(model, port=0, batch_size=4,
                           max_wait_ms=1.0).start()
    try:
        cli = HAServingClient(
            [_dead_endpoint(), (server.host, server.port)],
            hedge=False, deadline_ms=8000)
        for i in range(4):  # every rotation start still lands somewhere
            out = np.asarray(cli.predict(_x(float(i))))
            np.testing.assert_allclose(out, _x(float(i)) * 2.0)
        cli.close()
    finally:
        server.stop()


def test_ha_client_hedge_wins_over_slow_primary():
    """Primary stalls past the hedge delay → ONE duplicate goes to the
    other replica (same id) and its answer is used. The replicas return
    different values so the winner is unambiguous."""
    from zoo_tpu.serving.ha_client import HAServingClient

    class _Scaled(_MarkerModel):
        def __init__(self, factor, delay):
            super().__init__(delay)
            self.factor = factor

        def predict(self, x, batch_size=None):
            super().predict(x, batch_size)
            return np.asarray(x) * self.factor

    slow = ServingServer(_Scaled(3.0, 0.6), port=0, batch_size=1,
                         max_wait_ms=0.0).start()
    fast = ServingServer(_Scaled(2.0, 0.0), port=0, batch_size=1,
                         max_wait_ms=0.0).start()
    try:
        cli = HAServingClient(
            [(slow.host, slow.port), (fast.host, fast.port)],
            hedge=True, hedge_delay_ms=20, deadline_ms=8000)
        from zoo_tpu.obs.metrics import get_registry

        def hedge_count(event):
            return sum(
                c["value"] for c in get_registry().snapshot()["counters"]
                if c["name"] == "zoo_serve_hedge_total"
                and c["labels"].get("event") == event)

        fired0, won0 = hedge_count("fired"), hedge_count("won")
        out = np.asarray(cli.predict(_x(4.0)))
        np.testing.assert_allclose(out, _x(4.0) * 2.0)  # the FAST replica
        assert hedge_count("fired") == fired0 + 1
        assert hedge_count("won") == won0 + 1
        cli.close()
    finally:
        slow.stop()
        fast.stop()


def test_ha_client_deadline_exhaustion_raises_typed_error():
    from zoo_tpu.serving.ha_client import HAServingClient

    model = _MarkerModel(delay=0.5)
    server = ServingServer(model, port=0, batch_size=1,
                           max_wait_ms=0.0).start()
    try:
        cli = HAServingClient([(server.host, server.port)], hedge=False,
                              deadline_ms=100)
        with pytest.raises(DeadlineExceeded):
            cli.predict(_x(1.0))
        cli.close()
    finally:
        server.stop()


def test_ha_client_all_replicas_down_is_retryable_error():
    from zoo_tpu.serving.ha_client import (
        HAServingClient,
        NoReplicaAvailable,
    )

    cli = HAServingClient([_dead_endpoint(), _dead_endpoint()],
                          hedge=False, deadline_ms=2000)
    with pytest.raises(NoReplicaAvailable):
        cli.predict(_x(1.0))
    cli.close()


def test_ha_client_retries_past_shedding_replica():
    """A retryable shed (breaker-open door) fails over to the next
    replica instead of surfacing to the caller."""
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.util.resilience import CircuitBreaker

    tripped = CircuitBreaker(failure_threshold=1, recovery_timeout=60.0)
    tripped.record_failure()  # open: its door sheds everything
    shedding = ServingServer(_MarkerModel(), port=0, batch_size=2,
                             max_wait_ms=1.0, breaker=tripped).start()
    healthy = ServingServer(_MarkerModel(), port=0, batch_size=2,
                            max_wait_ms=1.0).start()
    try:
        cli = HAServingClient(
            [(shedding.host, shedding.port), (healthy.host, healthy.port)],
            hedge=False, deadline_ms=8000)
        for i in range(3):
            out = np.asarray(cli.predict(_x(float(i))))
            np.testing.assert_allclose(out, _x(float(i)) * 2.0)
        cli.close()
    finally:
        shedding.stop()
        healthy.stop()


def test_reused_msg_dict_never_inherits_a_stale_id():
    """rpc() must not write the auto-stamped id into the caller's dict:
    a reused dict would silently replay the previous answer from the
    server's dedup cache."""
    model = _MarkerModel()
    server = ServingServer(model, port=0, batch_size=2,
                           max_wait_ms=1.0).start()
    try:
        conn = _Connection(server.host, server.port)
        msg = {"op": "predict", "uri": "u", "data": _x(1.0)}
        r1 = conn.rpc(msg)
        assert "id" not in msg and "deadline_ms" not in msg
        msg["data"] = _x(2.0)
        r2 = conn.rpc(msg, deadline=Deadline.from_ms(30000))
        np.testing.assert_allclose(np.asarray(r1["result"]), 2.0)
        np.testing.assert_allclose(np.asarray(r2["result"]), 4.0)
        assert model.seen(2.0) == 1, "second request was dedup-replayed"
        conn.close()
    finally:
        server.stop()


@pytest.mark.chaos
def test_ha_client_stats_tolerates_down_replica():
    """stats() returns None for a dead seat — even one whose connection
    was pooled while it was alive — instead of raising (regression: a
    pooled connection's failure surfaces as RetryError, not OSError)."""
    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient

    group = ReplicaGroup("synthetic:double", num_replicas=1,
                         max_restarts=0).start(timeout=60)
    cli = HAServingClient(group.endpoints(), hedge=False,
                          deadline_ms=5000)
    try:
        cli.predict(_x(1.0))  # pools a live connection to the endpoint
        assert cli.stats()[0] is not None
        group.kill_replica(0)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            out = cli.stats()  # must never raise while the seat is dead
            if out == [None]:
                break
            time.sleep(0.1)
        assert out == [None], out
    finally:
        group.stop()
        cli.close()


# ------------------------------------------------------ HTTP front door

def test_frontend_rejects_expired_http_deadline():
    from zoo_tpu.serving.cluster_serving import FrontEnd
    import json
    import urllib.error
    import urllib.request

    class _Serving:
        def metrics(self):
            return {}

    class _IQ:
        def predict(self, data):
            return np.zeros((1, 1), np.float32)

    fe = FrontEnd(_Serving(), _IQ(), host="127.0.0.1", port=0).start()
    try:
        body = json.dumps({"instances": [{"t": [1.0]}]}).encode()
        req = urllib.request.Request(
            f"http://{fe.host}:{fe.port}/predict", data=body,
            headers={"X-Zoo-Deadline-Ms": "0"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 504
        payload = json.loads(ei.value.read().decode())
        assert payload["expired"] is True
        # a live budget still serves
        req2 = urllib.request.Request(
            f"http://{fe.host}:{fe.port}/predict", data=body,
            headers={"X-Zoo-Deadline-Ms": "30000"})
        with urllib.request.urlopen(req2, timeout=10) as resp:
            assert resp.status == 200
    finally:
        fe.stop()


# ------------------------------------------------------------ chaos smoke

@pytest.mark.chaos
def test_check_serving_ha_script_runs():
    """The 3-replica SIGKILL smoke (scripts/check_serving_ha.py): a real
    supervised replica group survives one replica kill under sustained
    load with zero client-visible failures, respawns the seat, and
    probes 3/3 healthy — as a subprocess, the operator invocation."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_serving_ha.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "SERVING HA OK" in proc.stdout


@pytest.mark.chaos
def test_replica_group_restarts_dead_replica_inproc():
    """ReplicaGroup direct API: kill a replica, the supervisor respawns
    it on the SAME port, and a plain ping round-trips again."""
    from zoo_tpu.serving.ha import ReplicaGroup

    group = ReplicaGroup("synthetic:double", num_replicas=2,
                         max_restarts=1).start(timeout=60)
    try:
        eps = group.endpoints()
        assert len(eps) == 2
        group.kill_replica(0)
        deadline = time.monotonic() + 30
        revived = False
        from zoo_tpu.util.resilience import RetryError
        while time.monotonic() < deadline:
            try:
                conn = _Connection(*eps[0],
                                   retry=RetryPolicy(max_attempts=1))
                if conn.rpc({"op": "ping"}).get("ok"):
                    conn.close()
                    revived = True
                    break
            except (OSError, RetryError):
                time.sleep(0.1)
        assert revived, "killed replica never came back on its port"
        assert group.restarts() == 1
    finally:
        group.stop()
