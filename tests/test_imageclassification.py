"""Image-classification zoo: forward shapes at reduced resolution, the
ImageClassifier pipeline wrapper, and MobileNet depthwise training.

Mirrors the reference's imageclassification specs (predict over an
ImageSet with the family's preprocessing config attached)."""

import numpy as np
import pytest

from zoo_tpu.feature.image import ImageFeature, ImageSet
from zoo_tpu.models.image import (
    ImageClassifier,
    create_image_classifier,
    densenet121,
    inception_v1,
    mobilenet_v1,
    mobilenet_v2,
    squeezenet,
    vgg16,
)

SMALL = (64, 64, 3)



# compile-bound on a 1-core box: the --all tier runs these
pytestmark = pytest.mark.heavy

@pytest.mark.parametrize("builder", [
    # mobilenet_v1 is the fast-tier representative; the big builds are
    # 13-34s of pure compile each on a 1-core box — slow tier
    mobilenet_v1,
    pytest.param(inception_v1, marks=pytest.mark.slow),
    pytest.param(mobilenet_v2, marks=pytest.mark.slow),
    pytest.param(squeezenet, marks=pytest.mark.slow),
    pytest.param(densenet121, marks=pytest.mark.slow)])
def test_forward_shape(builder):
    model = builder(7, input_shape=SMALL)
    x = np.random.RandomState(0).rand(2, *SMALL).astype(np.float32)
    y = np.asarray(model.predict(x, batch_size=2))
    assert y.shape == (2, 7)
    np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-4)


def test_vgg_forward_shape():
    model = vgg16(5, input_shape=(32, 32, 3))
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    y = np.asarray(model.predict(x, batch_size=2))
    assert y.shape == (2, 5)


def test_catalogue_lookup():
    m = create_image_classifier("squeezenet", class_num=11)
    assert m.name == "squeezenet"
    with pytest.raises(ValueError, match="unknown image-classification"):
        create_image_classifier("resnet-9000")


@pytest.mark.slow
def test_mobilenet_trains():
    model = mobilenet_v1(3, input_shape=(32, 32, 3))
    x = np.random.RandomState(0).rand(12, 32, 32, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.arange(12) % 3]
    model.compile(optimizer="adam", loss="categorical_crossentropy")
    hist = model.fit(x, y, batch_size=12, nb_epoch=15, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_image_classifier_pipeline():
    clf = ImageClassifier.create("squeezenet", class_num=4,
                                 label_map={0: "cat", 1: "dog", 2: "fox",
                                            3: "owl"})
    rng = np.random.RandomState(1)
    feats = [ImageFeature(image=(rng.rand(300, 280, 3) * 255)
                          .astype(np.uint8)) for _ in range(3)]
    out = clf.predict_image_set(ImageSet(feats), top_k=2)
    for f in out.features:
        assert np.asarray(f["predict"]).shape == (4,)
        assert len(f["classes"]) == 2 and len(f["probs"]) == 2
        assert f["classes"][0] in ("cat", "dog", "fox", "owl")
        assert f["probs"][0] >= f["probs"][1]
    # predict is non-destructive: a second call sees the original uint8
    # images and reproduces the same probabilities
    first = [np.asarray(f["predict"]).copy() for f in out.features]
    assert out.features[0]["image"].dtype == np.uint8
    again = clf.predict_image_set(ImageSet(feats), top_k=2)
    for f, p in zip(again.features, first):
        np.testing.assert_allclose(np.asarray(f["predict"]), p, atol=1e-5)


def test_save_load_roundtrip(tmp_path):
    clf = ImageClassifier.create("mobilenet-v2", class_num=3)
    x = np.random.RandomState(2).rand(2, 224, 224, 3).astype(np.float32)
    ref = np.asarray(clf.model.predict(x, batch_size=2))
    p = str(tmp_path / "m.zoo")
    clf.save_model(p)
    clf2 = ImageClassifier.load_model(p)
    got = np.asarray(clf2.model.predict(x, batch_size=2))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_space_to_depth_stem_matches_conv():
    """The s2d stem is mathematically the 7x7/s2 SAME conv (same HWIO
    weights), cf. SpaceToDepthStem docstring."""
    import jax
    import jax.numpy as jnp

    from zoo_tpu.models.image.resnet import SpaceToDepthStem

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    stem = SpaceToDepthStem(8)
    params = stem.build(jax.random.PRNGKey(0), (None, 32, 32, 3))
    got = stem.call(params, x)
    want = jax.lax.conv_general_dilated(
        x, params["W"], (2, 2), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert got.shape == want.shape == (2, 16, 16, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_image3d_transforms():
    """feature/image3d (SURVEY #21 3D imaging): crops are exact slices,
    zero-angle rotation and identity affine are no-ops, real rotations
    keep shape, and the chain composes over an ImageSet."""
    from zoo_tpu.feature.common import ChainedPreprocessing
    from zoo_tpu.feature.image import ImageSet
    from zoo_tpu.feature.image3d import (
        AffineTransform3D,
        CenterCrop3D,
        Crop3D,
        RandomCrop3D,
        Rotate3D,
    )

    rs = np.random.RandomState(0)
    vol = rs.rand(12, 10, 8).astype(np.float32)

    out = Crop3D(start=(2, 1, 0), patch_size=(4, 4, 4)).map_image(vol)
    np.testing.assert_array_equal(out, vol[2:6, 1:5, 0:4])

    out = CenterCrop3D(patch_size=(6, 6, 6)).map_image(vol)
    np.testing.assert_array_equal(out, vol[3:9, 2:8, 1:7])

    out = RandomCrop3D(patch_size=(5, 5, 5)).map_image(vol)
    assert out.shape == (5, 5, 5)

    np.testing.assert_allclose(
        Rotate3D(rotation_angles=(0.0, 0.0, 0.0)).map_image(vol), vol)
    rot = Rotate3D(rotation_angles=(0.3, 0.0, 0.1)).map_image(vol)
    assert rot.shape == vol.shape and np.isfinite(rot).all()

    ident = AffineTransform3D(np.eye(3)).map_image(vol)
    np.testing.assert_allclose(ident, vol, atol=1e-5)
    shifted = AffineTransform3D(np.eye(3),
                                translation=(1, 0, 0)).map_image(vol)
    # translation by +1 in z pulls voxels from one plane over
    np.testing.assert_allclose(shifted[0], vol[1], atol=1e-5)

    s = ImageSet.from_arrays([vol], [1])
    s = s.transform(ChainedPreprocessing([
        CenterCrop3D(patch_size=(8, 8, 8)),
        Rotate3D(rotation_angles=(0.0, 0.0, 0.2))]))
    assert s.features[0]["image"].shape == (8, 8, 8)
    assert s.features[0]["label"] == 1
