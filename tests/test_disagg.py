"""Disaggregated serving (docs/disaggregated_serving.md): replica
roles, ``adopt_blocks`` KV adoption, the ``op=kv_migrate`` wire
handoff, and the HA client's role/prefix-affinity routing.

The allocator property test and routing unit tests are pure python;
the wire tests run REAL ServingServer doors over the synthetic
deterministic engine (jax-free, fast). The mid-handoff SIGKILL chaos
smoke lives in ``scripts/check_disagg.py`` and runs under the
``chaos`` marker at the bottom.
"""

import os
import random
import subprocess
import sys
import time
from collections import Counter

import numpy as np
import pytest

from zoo_tpu.serving.llm.engine import LLMEngine
from zoo_tpu.serving.llm.kv_cache import (
    BlockAllocator,
    prefix_block_hashes,
)
from zoo_tpu.serving.llm.synthetic import SyntheticLLMModel, reference

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drain(handles, budget=20.0):
    while not all(h.done for h in handles):
        budget -= 0.005
        if budget <= 0:
            raise AssertionError(
                f"streams stuck: {[h.outcome for h in handles]}")
        time.sleep(0.005)


# ------------------------------------------- adopt_blocks: property test

def _check_invariants(alloc: BlockAllocator):
    """The pool-conservation and refcount contracts that must hold
    after EVERY operation."""
    st = alloc.stats()
    assert st["blocks_used"] + st["blocks_free"] + st["blocks_cached"] \
        == alloc.num_blocks - 1, st
    # physical used blocks == distinct blocks across live tables
    distinct = {b for t in alloc._owners.values() for b in t}
    assert st["blocks_used"] == len(distinct), (st, distinct)
    # refcount of every live block == number of tables listing it;
    # cached/free blocks carry no refcount entry at all
    want = Counter(b for t in alloc._owners.values() for b in t)
    assert dict(alloc._ref) == dict(want), (alloc._ref, want)


def test_adopt_blocks_property_random_interleavings():
    """Seeded random alloc/adopt/free interleavings vs the invariant
    shadow: zero leaks, adopted hashes stay matchable, refcounts match
    the ownership tables exactly, exhaustion rolls back cleanly."""
    rng = random.Random(20817)
    alloc = BlockAllocator(num_blocks=24, block_size=4,
                           prefix_cache=True)
    live = []          # seq ids currently owning blocks
    chains = []        # hash chains seen (re-adoptable prefixes)
    seq_n = 0
    for step in range(400):
        op = rng.random()
        if op < 0.35 and len(live) < 10:
            # local allocate + register (a plain prefilled stream)
            seq_n += 1
            sid = f"loc{seq_n}"
            toks = [rng.randrange(97)
                    for _ in range(rng.randrange(4, 20))]
            hashes = prefix_block_hashes(toks, alloc.block_size)
            reused = alloc.acquire_prefix(sid, hashes)
            need = alloc.blocks_for_tokens(len(toks)) - len(reused)
            if need > 0 and alloc.allocate(sid, need) is None:
                alloc.free(sid)          # could not fund: abort
            else:
                alloc.register_blocks(sid, hashes)
                live.append(sid)
                if hashes:
                    chains.append(hashes)
        elif op < 0.65:
            # adopt a migrated sequence — half the time a previously
            # seen chain (cross-replica prefix convergence), half a
            # fresh one
            seq_n += 1
            sid = f"mig{seq_n}"
            if chains and rng.random() < 0.5:
                hashes = list(rng.choice(chains))
            else:
                toks = [rng.randrange(97)
                        for _ in range(rng.randrange(4, 20))]
                hashes = prefix_block_hashes(toks, alloc.block_size)
            if not hashes:
                continue
            n_blocks = len(hashes) + rng.randrange(0, 2)
            before = alloc.stats()
            got = alloc.adopt_blocks(sid, hashes, n_blocks)
            if got is None:
                # exhaustion: all-or-nothing rollback. Eviction of
                # refcount-0 cached blocks may have happened (cached →
                # free, a semantic no-op); ownership must be untouched
                after = alloc.stats()
                assert after["blocks_used"] == before["blocks_used"]
                assert after["live_sequences"] == \
                    before["live_sequences"]
                assert after["blocks_free"] + after["blocks_cached"] \
                    == before["blocks_free"] + before["blocks_cached"]
            else:
                table, n_reused = got
                assert len(table) == n_blocks
                assert len(set(table)) == n_blocks
                assert 0 <= n_reused < n_blocks
                # adopted hashes are matchable for the NEXT prompt
                assert alloc.match_prefix(hashes) >= 1
                live.append(sid)
                chains.append(hashes)
        elif live:
            alloc.free(live.pop(rng.randrange(len(live))))
        _check_invariants(alloc)
    for sid in live:
        alloc.free(sid)
    _check_invariants(alloc)
    st = alloc.stats()
    assert st["blocks_used"] == 0, f"leaked blocks: {st}"
    assert st["live_sequences"] == 0


def test_adopt_blocks_last_block_never_aliased():
    """Even a FULL hash match leaves the last table row private — it
    is the decode write frontier (the adoption-side mirror of the
    aligned-full-hit CoW rule)."""
    alloc = BlockAllocator(num_blocks=16, block_size=4,
                           prefix_cache=True)
    toks = list(range(12))
    hashes = prefix_block_hashes(toks, 4)
    table, n_reused = alloc.adopt_blocks("a", hashes, 3)
    assert n_reused == 0
    # a second adoption of the SAME chain aliases all but the last row
    table2, n_reused2 = alloc.adopt_blocks("b", hashes, 3)
    assert n_reused2 == 2
    assert table2[:2] == table[:2]
    assert table2[2] != table[2]
    alloc.free("a")
    alloc.free("b")
    assert alloc.stats()["blocks_used"] == 0


def test_adopt_blocks_without_prefix_cache_allocates_fresh():
    alloc = BlockAllocator(num_blocks=16, block_size=4)
    hashes = prefix_block_hashes(list(range(8)), 4)
    table, n_reused = alloc.adopt_blocks("a", hashes, 2)
    assert n_reused == 0 and len(table) == 2
    alloc.free("a")
    assert alloc.stats()["blocks_used"] == 0


# --------------------------------------- engine-level adopt-then-decode

def _engines(**kw):
    mk = dict(num_slots=2, block_size=4, num_blocks=32,
              max_blocks_per_seq=8, max_prompt_len=48)
    P = LLMEngine(SyntheticLLMModel(**mk), role="prefill", **kw).start()
    D = LLMEngine(SyntheticLLMModel(**mk), role="decode", **kw).start()
    return P, D


def _handoff(P, D, prompt, n, rid, sampling=None):
    """Drive the park→take→offer→release→adopt cycle by hand (the
    server does this over the wire; the bare engines expose each
    step)."""
    h1 = P.submit(prompt, n, rid=rid, sampling=sampling, handoff=True)
    _drain([h1])
    assert h1.outcome == "handoff", h1.outcome
    payload = P.take_handoff(rid)
    assert payload is not None
    assert D.offer_adopted(payload)
    P.release_handoff(rid)
    h2 = D.submit(prompt, n, rid=rid, sampling=sampling,
                  adopt=D.pop_adopted(rid))
    _drain([h2])
    assert h2.outcome == "ok", h2.outcome
    return h2.tokens


def test_engine_adopt_then_decode_token_identity_greedy():
    """A stream prefilled on a prefill engine and decoded on a decode
    engine emits EXACTLY the tokens a local prefill would — and the
    migration is real: handoffs counted both sides, zero leaked blocks
    on either end."""
    P, D = _engines()
    try:
        prompt = [(3 * i + 1) % 50 for i in range(18)]
        toks = _handoff(P, D, prompt, 8, "r-greedy")
        assert toks == reference(prompt, 8)
        assert P.stats()["handoffs_out"] == 1
        assert D.stats()["handoffs_in"] == 1
        assert P.stats()["blocks_used"] == 0
        assert D.stats()["blocks_used"] == 0
    finally:
        P.stop()
        D.stop()


def test_engine_adopt_then_decode_token_identity_seeded():
    P, D = _engines()
    try:
        prompt = [(5 * i + 2) % 50 for i in range(17)]
        sampling = {"temperature": 0.9, "seed": 11}
        toks = _handoff(P, D, prompt, 7, "r-seeded", sampling)
        assert toks == reference(prompt, 7, temp=0.9, seed=11)
    finally:
        P.stop()
        D.stop()


def test_engine_adoption_miss_replays_identically():
    """A lost/expired adoption payload degrades to a plain re-prefill
    with byte-identical output — the determinism contract that makes
    every handoff failure survivable."""
    P, D = _engines()
    try:
        prompt = [(7 * i + 3) % 50 for i in range(16)]
        h1 = P.submit(prompt, 6, rid="r-miss", handoff=True)
        _drain([h1])
        P.release_handoff("r-miss")   # payload never taken/pushed
        h2 = D.submit(prompt, 6, rid="r-miss")  # no adopt= staged
        _drain([h2])
        assert h2.tokens == reference(prompt, 6)
        assert D.stats()["handoffs_in"] == 0
        assert P.stats()["blocks_used"] == 0
        assert D.stats()["blocks_used"] == 0
    finally:
        P.stop()
        D.stop()


# ------------------------------------------------- wire-level kv_migrate

@pytest.fixture()
def disagg_pair():
    from zoo_tpu.serving.server import ServingServer
    P, D = _engines()
    sp = ServingServer(None, llm_engine=P, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    sd = ServingServer(None, llm_engine=D, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    yield sp, sd, P, D
    sp.stop()
    sd.stop()
    P.stop()
    D.stop()


def test_wire_handoff_stream_identity_and_role_advertise(disagg_pair):
    """The full two-leg stream through the HA client: leg 1 prefills
    on the prefill seat and pushes kv_migrate, leg 2 adopts and
    decodes — byte-identical to the single-replica reference, greedy
    AND seeded, with the roles learned from llm_stats."""
    from zoo_tpu.serving.ha_client import HAServingClient
    sp, sd, P, D = disagg_pair
    cli = HAServingClient([(sp.host, sp.port), (sd.host, sd.port)],
                          hedge=False, migrate_min_tokens=16)
    topo = cli.update_topology()
    roles = sorted((v or {}).get("role") for v in topo.values())
    assert roles == ["decode", "prefill"], topo
    prompt = [(3 * i + 1) % 50 for i in range(18)]
    assert list(cli.generate(prompt, 8)) == reference(prompt, 8)
    assert P.stats()["handoffs_out"] == 1
    assert D.stats()["handoffs_in"] == 1
    toks = list(cli.generate(prompt, 8, temperature=0.9, seed=5))
    assert toks == reference(prompt, 8, temp=0.9, seed=5)
    assert P.stats()["handoffs_out"] == 2
    time.sleep(0.2)
    assert P.stats()["blocks_used"] == 0
    assert D.stats()["blocks_used"] == 0
    cli.close()


def test_wire_short_prompt_skips_handoff(disagg_pair):
    from zoo_tpu.serving.ha_client import HAServingClient
    sp, sd, P, D = disagg_pair
    cli = HAServingClient([(sp.host, sp.port), (sd.host, sd.port)],
                          hedge=False, migrate_min_tokens=16)
    cli.update_topology()
    short = [(2 * i + 3) % 50 for i in range(6)]
    assert list(cli.generate(short, 5)) == reference(short, 5)
    assert P.stats()["handoffs_out"] == 0
    cli.close()


def test_wire_prefill_role_sheds_plain_generate(disagg_pair):
    """A plain generate at a prefill seat is shed retryable with
    reason=role, and the reply frame advertises the role (how a cold
    client learns topology from its first bounce)."""
    from zoo_tpu.serving.tcp_client import _Connection
    sp, _sd, _P, _D = disagg_pair
    conn = _Connection(sp.host, sp.port)
    frames = list(conn.stream({"op": "generate", "id": "t-shed",
                               "prompt": [1, 2, 3],
                               "max_new_tokens": 4}))
    conn.close()
    assert frames and frames[-1].get("shed") is True
    assert frames[-1].get("retryable") is True
    assert frames[-1].get("role") == "prefill"


def test_wire_cold_client_learns_roles_passively(disagg_pair):
    """No update_topology: the first stream bounces off the prefill
    seat's role shed, the client learns, and later long prompts ride
    the handoff path."""
    from zoo_tpu.serving.ha_client import HAServingClient
    sp, sd, P, D = disagg_pair
    cli = HAServingClient([(sp.host, sp.port), (sd.host, sd.port)],
                          hedge=False, migrate_min_tokens=16)
    short = [(2 * i + 3) % 50 for i in range(6)]
    for _ in range(2):   # at most one bounce teaches both seats
        assert list(cli.generate(short, 5)) == reference(short, 5)
    assert any(ep.seen_role == "prefill" for ep in cli._eps)
    prompt = [(3 * i + 1) % 50 for i in range(18)]
    assert list(cli.generate(prompt, 8)) == reference(prompt, 8)
    assert P.stats()["handoffs_out"] == 1
    assert D.stats()["handoffs_in"] == 1
    cli.close()


# ------------------------------------------------- routing unit tests

def _fake_client(n=3, **kw):
    from zoo_tpu.serving.ha_client import HAServingClient
    eps = [("127.0.0.1", 20000 + i) for i in range(n)]
    kw.setdefault("eject", False)
    kw.setdefault("hedge", False)
    return HAServingClient(eps, **kw)


def test_plan_generate_demotes_prefill_and_ranks_affinity():
    cli = _fake_client(3, migrate_min_tokens=8,
                       route_prefix_weight=1.0, route_occ_weight=0.5)
    a, b, c = cli._eps
    a.seen_role = "prefill"
    b.seen_role = "decode"
    c.seen_role = "decode"
    prompt = list(range(16))
    # affinity: seat c served this prefix before -> planned first
    cli._note_affinity(cli._prompt_sig(prompt), c)
    for _ in range(3):   # stable under the rotating rr cursor
        order, _sig = cli._plan_generate(prompt)
        assert order[0] is c
        assert order[-1] is a    # prefill seat rides the back
    pair = cli._handoff_pair(order, len(prompt))
    assert pair == (a, c)
    # below the migrate floor: no handoff pair
    assert cli._handoff_pair(order, 4) is None
    cli.close()


def test_plan_generate_occupancy_penalizes_busy_seat():
    cli = _fake_client(2, route_prefix_weight=0.0,
                       route_occ_weight=1.0)
    busy, idle = cli._eps
    busy.score.note_occupancy(1.0)
    idle.score.note_occupancy(0.0)
    for _ in range(2):
        order, _sig = cli._plan_generate(list(range(4)))
        assert order[0] is idle
    cli.close()


def test_handoff_pair_needs_both_roles():
    cli = _fake_client(2, migrate_min_tokens=4)
    order, _sig = cli._plan_generate(list(range(8)))
    assert cli._handoff_pair(order, 8) is None   # no prefill seat known
    cli._eps[0].seen_role = "prefill"
    cli._eps[1].seen_role = "decode"
    order, _sig = cli._plan_generate(list(range(8)))
    assert cli._handoff_pair(order, 8) == (cli._eps[0], cli._eps[1])
    cli.close()


def test_replica_score_carries_role_and_occupancy():
    from zoo_tpu.serving.ejection import ReplicaScore
    s = ReplicaScore("seat")
    s.note_role("decode")
    s.note_occupancy(1.0)
    s.note_occupancy(0.0)
    snap = s.snapshot()
    assert snap["role"] == "decode"
    assert 0.0 < snap["occupancy"] < 1.0   # EWMA, not last-write


# ------------------------------------------------------- chaos smoke

@pytest.mark.chaos
def test_check_disagg_script_runs():
    """The disaggregation chaos smoke (scripts/check_disagg.py):
    1 prefill + 2 decode replicas under a mixed storm with the
    prefill seat SIGKILLed mid-handoff — every stream byte-identical
    to the single-replica reference, zero leaked KV blocks on the
    survivors."""
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "check_disagg.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DISAGG CHAOS OK" in proc.stdout
