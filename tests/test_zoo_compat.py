"""The `zoo` compatibility package: reference import lines run
unmodified and resolve to the zoo_tpu implementations (identity, not
copies)."""

import numpy as np
import pytest


def test_reference_import_lines():
    from zoo.orca import init_orca_context, stop_orca_context  # noqa
    from zoo.orca.data import XShards  # noqa
    from zoo.orca.learn.keras import Estimator  # noqa
    from zoo.pipeline.api.keras.layers import Dense  # noqa
    from zoo.pipeline.api.net import Net  # noqa
    from zoo.chronos.data import TSDataset  # noqa
    from zoo.chronos.forecaster import LSTMForecaster  # noqa
    from zoo.friesian.feature import FeatureTable  # noqa
    from zoo.serving.client import InputQueue, OutputQueue  # noqa
    from zoo.models.recommendation import NeuralCF  # noqa
    from zoo.common.nncontext import init_nncontext  # noqa


def test_modules_are_identical():
    import zoo.pipeline.api.keras.layers as compat
    import zoo_tpu.pipeline.api.keras.layers as real
    assert compat is real
    assert compat.Dense is real.Dense


def test_missing_module_raises_normally():
    with pytest.raises(ModuleNotFoundError):
        import zoo.definitely_not_a_module  # noqa


def test_reference_style_training_script():
    """A verbatim reference-shaped script body (imports and all)."""
    from zoo.common.nncontext import init_nncontext
    from zoo.pipeline.api.keras.layers import Dense
    from zoo.pipeline.api.keras.models import Sequential

    sc = init_nncontext()
    assert sc is not None
    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(4,)))
    model.add(Dense(1))
    model.compile(optimizer="sgd", loss="mse")
    rs = np.random.RandomState(0)
    x = rs.randn(32, 4).astype(np.float32)
    y = (x @ rs.randn(4, 1)).astype(np.float32)
    h = model.fit(x, y, batch_size=16, nb_epoch=3, verbose=0)
    assert h["loss"][-1] < h["loss"][0]


def test_top_level_reference_idioms():
    from zoo import init_nncontext  # noqa — reference star-export idiom
    from zoo.common import init_nncontext as inn2  # noqa
    assert init_nncontext is inn2


def test_spec_not_clobbered():
    """Forwarding must not corrupt the real module's importlib metadata
    (reload/find_spec on the zoo_tpu name keep working)."""
    import importlib
    import zoo.orca  # noqa: F401 — triggers the forwarder
    import zoo_tpu.orca as real
    assert real.__name__ == "zoo_tpu.orca"
    assert real.__spec__.name == "zoo_tpu.orca"
    assert real.__path__  # non-empty: submodules stay importable
    importlib.reload(real)
    import zoo_tpu.orca.data  # noqa: F401 — would fail on a bad spec


def test_collapsed_fabric_shims_redirect():
    """Reference fabric import paths resolve and name the migration."""
    from zoo.orca.learn.horovod import HorovodRayRunner
    from zoo.orca.learn.mxnet import Estimator as MXEstimator
    from zoo.orca.learn.mpi import MPIEstimator
    with pytest.raises(NotImplementedError, match="mesh"):
        HorovodRayRunner()
    with pytest.raises(NotImplementedError, match="from_torch"):
        MXEstimator.from_mxnet()
    with pytest.raises(NotImplementedError, match="bootstrap"):
        MPIEstimator()


def test_tfpark_text_models_reference_path():
    """The reference's ``from zoo.tfpark.text.keras import NER`` line
    (``pyzoo/zoo/tfpark/text/keras/ner.py``) resolves unmodified."""
    from zoo.tfpark.text.keras import NER, IntentEntity, SequenceTagger

    import zoo_tpu.models.text as real

    assert NER is real.NER
    assert SequenceTagger is real.SequenceTagger
    assert IntentEntity is real.IntentEntity


def test_tfpark_kerasmodel_fit_from_tf_keras():
    """``from zoo.tfpark import KerasModel`` + fit on a compiled tf.keras
    model (reference ``tfpark/model.py:31``) — real delegation through
    the keras bridge onto the jitted fabric."""
    import numpy as np
    from zoo.tfpark import KerasModel, TFDataset

    import tensorflow as tf

    km = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(4,)),
        tf.keras.layers.Dense(8, activation="relu"),
        tf.keras.layers.Dense(2, activation="softmax"),
    ])
    km.compile(optimizer=tf.keras.optimizers.Adam(1e-3),
               loss="sparse_categorical_crossentropy")
    model = KerasModel(km)

    rs = np.random.RandomState(0)
    x = rs.randn(64, 4).astype(np.float32)
    y = rs.randint(0, 2, 64).astype(np.int32)
    hist = model.fit(TFDataset.from_ndarrays((x, y), batch_size=16),
                     epochs=2)
    assert np.isfinite(hist["loss"]).all()
    preds = model.predict(x[:8])
    assert preds.shape == (8, 2)
    loss1 = model.train_on_batch(x[:16], y[:16])
    assert np.isfinite(loss1)


def test_tfpark_migration_errors_name_targets():
    import pytest

    from zoo.tfpark import TFDataset, TFParkMigrationError

    # TFEstimator.from_model_fn TRAINS now (tests/test_tf1_training.py)
    with pytest.raises(TFParkMigrationError, match="XShards"):
        TFDataset.from_rdd(None)
    with pytest.raises(TFParkMigrationError, match="read_tfrecords"):
        TFDataset.from_tfrecord_file(None, "/tmp/x")


def test_tfpark_ganestimator_is_orca_gan():
    from zoo.tfpark import GANEstimator

    from zoo_tpu.orca.learn.gan import GANEstimator as orca_gan

    assert GANEstimator is orca_gan


def test_tfpark_tfdataset_from_dataframe_pandas():
    import numpy as np
    import pandas as pd

    from zoo.tfpark import TFDataset

    pdf = pd.DataFrame({"a": [1.0, 2.0], "b": [3.0, 4.0],
                        "y": [0.0, 1.0]})
    ds = TFDataset.from_dataframe(pdf, ["a", "b"], ["y"], batch_size=2)
    np.testing.assert_allclose(ds.x, [[1.0, 3.0], [2.0, 4.0]])
    np.testing.assert_allclose(ds.y, [0.0, 1.0])
