import jax.numpy as jnp
import numpy as np
import pytest

from zoo_tpu.pipeline.api import autograd as A
from zoo_tpu.pipeline.api.autograd import CustomLoss, Variable


def _run(var, inputs, values):
    from zoo_tpu.pipeline.api.keras.engine.topology import Model

    m = Model(input=[v.node for v in inputs], output=var.node)
    return np.asarray(m._forward({}, [jnp.asarray(v) for v in values],
                                 training=False, rng=None, collect=None))


def test_variable_operators():
    a = Variable(input_shape=(3,))
    b = Variable(input_shape=(3,))
    expr = (a + b) * 2 - a / (b + 1.0)
    x = np.array([[1.0, 2.0, 3.0]], np.float32)
    y = np.array([[4.0, 5.0, 6.0]], np.float32)
    out = _run(expr, [a, b], [x, y])
    np.testing.assert_allclose(out, (x + y) * 2 - x / (y + 1), rtol=1e-6)


def test_math_functions():
    a = Variable(input_shape=(4,))
    x = np.array([[0.5, -1.0, 2.0, -0.25]], np.float32)
    np.testing.assert_allclose(_run(A.abs(a), [a], [x]), np.abs(x))
    np.testing.assert_allclose(_run(A.square(a), [a], [x]), x ** 2)
    np.testing.assert_allclose(_run(A.exp(a), [a], [x]), np.exp(x),
                               rtol=1e-6)
    np.testing.assert_allclose(_run(A.clip(a, -0.5, 0.5), [a], [x]),
                               np.clip(x, -0.5, 0.5))
    from scipy.special import erf as sp_erf
    np.testing.assert_allclose(_run(A.erf(a), [a], [x]), sp_erf(x),
                               rtol=1e-5)
    np.testing.assert_allclose(_run(A.sum(a, axis=1, keepdims=True),
                                    [a], [x]), x.sum(1, keepdims=True))


def test_batch_dot_and_l2_normalize():
    a = Variable(input_shape=(2, 3))
    b = Variable(input_shape=(2, 3))
    x = np.random.RandomState(0).randn(4, 2, 3).astype(np.float32)
    y = np.random.RandomState(1).randn(4, 2, 3).astype(np.float32)
    out = _run(A.batch_dot(a, b, axes=(2, 2)), [a, b], [x, y])
    ref = np.einsum("bik,bjk->bij", x, y)
    np.testing.assert_allclose(out, ref, rtol=1e-5)

    n = _run(A.l2_normalize(a, axis=-1), [a], [x])
    np.testing.assert_allclose(np.linalg.norm(n, axis=-1),
                               np.ones((4, 2)), rtol=1e-5)


def test_custom_loss_in_compile(orca_ctx):
    """Train with a CustomLoss (mean absolute percentage-ish error) and
    check it actually optimizes — the reference's CustomLoss use case."""
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    y_true = Variable(input_shape=(1,))
    y_pred = Variable(input_shape=(1,))
    loss_var = A.mean(A.abs(y_true - y_pred), axis=1)
    loss = CustomLoss(loss_var, y_true, y_pred)

    rs = np.random.RandomState(0)
    x = rs.randn(128, 4).astype(np.float32)
    w = rs.randn(4, 1).astype(np.float32)
    y = x @ w
    m = Sequential()
    m.add(Dense(1, input_shape=(4,)))
    m.compile(optimizer=Adam(lr=0.05), loss=loss)
    # MAE under Adam descends ~linearly at ~lr per step (sign-like
    # gradients), ~0.25 loss/epoch here: 5 epochs lands just above the
    # halving bar; 10 is well past it (measured 0.39 vs bar 1.23)
    hist = m.fit(x, y, batch_size=32, nb_epoch=10, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
