"""Paged flash-prefill Pallas kernel (zoo_tpu/ops/pallas/paged_prefill.py):
numeric identity against the dense-gather reference across block-table
routing, batched sequences, GQA grouping, the causal-by-position mask
edges, and int8 in-register dequant — all through the Pallas
interpreter (the exact kernel TPU hardware compiles). The serving-level
token-identity checks (chunk prefill and the speculative verify
executable on ``ZOO_LLM_PREFILL_IMPL=flash``) live at the bottom.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zoo_tpu.ops.pallas.paged_prefill import paged_flash_prefill


def _dense_ref(q, kc, vc, bt, pos):
    """cache[block_table] gather + per-row position mask — the exact
    math model._prefill_attend runs on the dense anchor path."""
    S, C, H, D = q.shape
    nb, bs, n_kv, _ = kc.shape
    W = bt.shape[1]
    ctx = W * bs
    group = H // n_kv
    keys = kc[bt].reshape(S, ctx, n_kv, D)
    vals = vc[bt].reshape(S, ctx, n_kv, D)
    qg = q.reshape(S, C, n_kv, group, D)
    s = jnp.einsum("sckgd,stkd->sckgt", qg, keys).astype(
        jnp.float32) / jnp.sqrt(float(D))
    live = jnp.arange(ctx)[None, None, :] <= pos[:, :, None]
    s = jnp.where(live[:, :, None, None, :], s,
                  jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(vals.dtype)
    return jnp.einsum("sckgt,stkd->sckgd", p, vals).reshape(S, C, H, D)


def _case(S=2, C=5, H=4, n_kv=2, D=16, nb=12, bs=4, W=4, seed=0,
          starts=None):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(S, C, H, D).astype(np.float32))
    kc = jnp.asarray(rs.randn(nb, bs, n_kv, D).astype(np.float32))
    vc = jnp.asarray(rs.randn(nb, bs, n_kv, D).astype(np.float32))
    bt = jnp.asarray(rs.randint(1, nb, (S, W)).astype(np.int32))
    if starts is None:
        starts = rs.randint(0, W * bs - C, (S,))
    pos = jnp.asarray((np.asarray(starts)[:, None]
                       + np.arange(C)[None, :]).astype(np.int32))
    return q, kc, vc, bt, pos


@pytest.mark.parametrize("shape", [
    dict(S=1, C=4),                       # the chunk-prefill shape
    dict(S=3, C=5, W=6),                  # the verify shape
    dict(S=2, C=8, H=4, n_kv=1, D=8, bs=8, W=3),   # MQA
    dict(S=2, C=3, H=4, n_kv=4, nb=9),             # MHA
])
def test_kernel_matches_dense_reference(shape):
    q, kc, vc, bt, pos = _case(**shape)
    ref = _dense_ref(q, kc, vc, bt, pos)
    out = paged_flash_prefill(q, kc, vc, bt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_position_edges():
    """Row at position 0 (one live column), a chunk ending exactly on
    the table edge, and equal clamped positions (the pad-row shape the
    verify executable feeds)."""
    q, kc, vc, bt, _ = _case(S=3, C=3)
    pos = jnp.asarray(np.array([[0, 1, 2], [13, 14, 15],
                                [15, 15, 15]], np.int32))
    ref = _dense_ref(q, kc, vc, bt, pos)
    out = paged_flash_prefill(q, kc, vc, bt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_int8_dequant_matches_dense_widen():
    from zoo_tpu.util.quantize import absmax_scale, narrow_int8, \
        widen_int8

    rs = np.random.RandomState(21)
    S, C, H, n_kv, D, nb, bs, W = 2, 4, 4, 2, 16, 10, 4, 4
    q = jnp.asarray(rs.randn(S, C, H, D).astype(np.float32))
    kc = rs.randn(nb, bs, n_kv, D).astype(np.float32)
    vc = rs.randn(nb, bs, n_kv, D).astype(np.float32)
    ks = np.asarray(absmax_scale(kc, axis=-1))
    vs = np.asarray(absmax_scale(vc, axis=-1))
    kq = narrow_int8(kc, ks[..., None])
    vq = narrow_int8(vc, vs[..., None])
    bt = jnp.asarray(rs.randint(1, nb, (S, W)).astype(np.int32))
    pos = jnp.asarray(np.array([[0, 1, 2, 3], [9, 10, 11, 12]],
                               np.int32))
    ref = _dense_ref(q, jnp.asarray(widen_int8(kq, ks[..., None])),
                     jnp.asarray(widen_int8(vq, vs[..., None])),
                     bt, pos)
    out = paged_flash_prefill(
        q, jnp.asarray(kq), jnp.asarray(vq), bt, pos,
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs),
        interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_argument_validation():
    q, kc, vc, bt, pos = _case()
    with pytest.raises(ValueError, match="travel together"):
        paged_flash_prefill(q, kc, vc, bt, pos,
                            k_scale=jnp.zeros((12, 4, 2)),
                            interpret=True)
    with pytest.raises(ValueError, match="scale shape"):
        paged_flash_prefill(q, kc, vc, bt, pos,
                            k_scale=jnp.zeros((12, 4, 9)),
                            v_scale=jnp.zeros((12, 4, 9)),
                            interpret=True)
    with pytest.raises(ValueError, match="positions shape"):
        paged_flash_prefill(q, kc, vc, bt, pos[:, :2], interpret=True)


def test_kernel_under_jit():
    q, kc, vc, bt, pos = _case(seed=9)
    ref = _dense_ref(q, kc, vc, bt, pos)
    f = jax.jit(lambda *a: paged_flash_prefill(*a, interpret=True))
    np.testing.assert_allclose(np.asarray(f(q, kc, vc, bt, pos)),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)


# ------------------------------------------- serving-level token identity

def test_chunk_prefill_flash_impl_token_identical():
    """ZOO_LLM_PREFILL_IMPL semantics: the chunk executable on the
    flash kernel (interpreted on CPU) emits the same tokens as the
    dense anchor, greedy and sampled, with the census unchanged."""
    import time

    from zoo_tpu.models.llm.llama import tiny_llama_config
    from zoo_tpu.serving.llm.engine import LLMEngine
    from zoo_tpu.serving.llm.model import (
        PagedLlamaModel,
        resolve_prefill_impl,
    )

    assert resolve_prefill_impl("dense") == "dense"
    assert resolve_prefill_impl("flash") == "flash"
    with pytest.raises(ValueError):
        resolve_prefill_impl("mosaic")

    cfg = tiny_llama_config(vocab=64)
    kw = dict(seed=0, num_slots=2, block_size=4, num_blocks=32,
              max_blocks_per_seq=8, prefill_buckets=(8, 32),
              prefill_chunk=4)
    prompts = [np.arange(2, 12) % 64, np.arange(3, 9) % 64]
    sampling = [None, dict(temperature=0.8, seed=9)]

    def gen(model, spec=None):
        eng = LLMEngine(model).start()
        try:
            hs = [eng.submit(p, 8, rid=f"f{i}", sampling=s)
                  for i, (p, s) in enumerate(zip(prompts, sampling))]
            end = time.monotonic() + 300
            while not all(h.done for h in hs):
                assert time.monotonic() < end
                time.sleep(0.005)
            assert all(h.outcome == "ok" for h in hs), \
                [(h.outcome, h.error) for h in hs]
            return [list(h.tokens) for h in hs], eng.stats()
        finally:
            eng.stop()

    dense, _ = gen(PagedLlamaModel(cfg, prefill_impl="dense", **kw))
    flash_model = PagedLlamaModel(cfg, prefill_impl="flash", **kw)
    assert flash_model.prefill_attention_impl == "flash"
    flash, st = gen(flash_model)
    assert flash == dense
    assert st["prefill_attention_impl"] == "flash"
    assert st["compiles"]["prefill_chunk"] == 1

    # the verify executable rides the same impl switch
    spec_model = PagedLlamaModel(cfg, prefill_impl="flash", spec_k=3,
                                 **kw)
    spec, st2 = gen(spec_model)
    assert spec == dense
    assert st2["compiles"]["verify"] == 1
