"""Unit tests for the shared resilience core (zoo_tpu.util.resilience):
retry backoff math, circuit-breaker state machine, fault-injection
registry, heartbeat helpers, and the coordinator-port TOCTOU retry."""

import os
import socket
import time

import pytest

from zoo_tpu.util.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    FaultInjector,
    InjectedFault,
    RetryError,
    RetryPolicy,
    clear_faults,
    fault_point,
    heartbeat_age,
    inject,
    touch_heartbeat,
)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def _recording_policy(**kw):
    sleeps = []
    kw.setdefault("jitter", False)
    policy = RetryPolicy(sleep=sleeps.append, **kw)
    return policy, sleeps


def test_retry_succeeds_after_transients():
    policy, sleeps = _recording_policy(max_attempts=4, base_delay=0.1)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    assert policy.call(flaky) == "ok"
    assert len(calls) == 3
    # exponential: 0.1 after the 1st failure, 0.2 after the 2nd
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]


def test_retry_budget_exhausted_raises_with_cause():
    policy, _ = _recording_policy(max_attempts=2, base_delay=0.01)

    def dead():
        raise ConnectionError("always down")

    with pytest.raises(RetryError) as ei:
        policy.call(dead)
    assert ei.value.attempts == 2
    assert isinstance(ei.value.__cause__, ConnectionError)


def test_non_retryable_propagates_immediately():
    policy, sleeps = _recording_policy(max_attempts=5, base_delay=0.01)
    calls = []

    def bad_request():
        calls.append(1)
        raise KeyError("not a network problem")

    with pytest.raises(KeyError):
        policy.call(bad_request)
    assert len(calls) == 1 and sleeps == []


def test_retry_deadline_bounds_total_wait():
    # backoff after the first failure (1.0s) would blow the 0.5s
    # deadline: the policy must give up instead of sleeping past it
    policy, sleeps = _recording_policy(
        max_attempts=10, base_delay=1.0, deadline=0.5)

    def dead():
        raise ConnectionError("down")

    t0 = time.monotonic()
    with pytest.raises(RetryError, match="deadline"):
        policy.call(dead)
    assert time.monotonic() - t0 < 0.5
    assert sleeps == []


def test_backoff_caps_at_max_delay():
    policy, _ = _recording_policy(base_delay=0.1, max_delay=0.3)
    assert policy.backoff(1) == pytest.approx(0.1)
    assert policy.backoff(2) == pytest.approx(0.2)
    assert policy.backoff(5) == pytest.approx(0.3)  # capped


def test_jitter_stays_within_raw_backoff():
    policy = RetryPolicy(base_delay=0.1, jitter=True, rng=lambda: 0.5)
    assert policy.backoff(1) == pytest.approx(0.05)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_threshold_and_recovers():
    clock = _Clock()
    br = CircuitBreaker(failure_threshold=2, recovery_timeout=10.0,
                        clock=clock)
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    br.record_failure()
    assert br.state == CircuitBreaker.CLOSED  # one failure: still closed
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()

    clock.t = 11.0  # recovery timeout passed: half-open admits one probe
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow()
    assert not br.allow()  # only half_open_max probes
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()


def test_breaker_half_open_failure_reopens():
    clock = _Clock()
    br = CircuitBreaker(failure_threshold=1, recovery_timeout=5.0,
                        clock=clock)
    br.record_failure()
    clock.t = 6.0
    assert br.allow()  # the probe
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(failure_threshold=2)
    br.record_failure()
    br.record_success()
    br.record_failure()  # not consecutive: stays closed
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_call_wraps_and_raises_when_open():
    br = CircuitBreaker(failure_threshold=1, recovery_timeout=60.0)
    with pytest.raises(ValueError):
        br.call(lambda: (_ for _ in ()).throw(ValueError("boom")))
    with pytest.raises(CircuitOpenError):
        br.call(lambda: "never runs")


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_inject_times_bounded_then_disarms():
    inj = FaultInjector()
    inj.inject("site.a", exc=ConnectionError("flaky"), times=2)
    for _ in range(2):
        with pytest.raises(ConnectionError):
            inj.fire("site.a")
    inj.fire("site.a")  # 3rd call: disarmed, no raise
    assert inj.fired("site.a") == 2


def test_inject_action_callback_receives_context():
    inj = FaultInjector()
    seen = []
    inj.inject("site.b", action=lambda **ctx: seen.append(ctx))
    inj.fire("site.b", gid=7)
    assert seen == [{"site": "site.b", "gid": 7}]


def test_inject_default_exception_and_clear():
    inj = FaultInjector()
    inj.inject("site.c")
    with pytest.raises(InjectedFault):
        inj.fire("site.c")
    inj.clear("site.c")
    inj.fire("site.c")  # cleared: no-op


def test_module_level_context_manager_clears_on_exit():
    with inject("site.d", exc=OSError("x"), times=1) as armed:
        with pytest.raises(OSError):
            fault_point("site.d")
        assert armed.fired == 1
    fault_point("site.d")  # disarmed by __exit__
    clear_faults()


def test_unarmed_site_is_noop():
    fault_point("never.armed", anything="goes")


# ---------------------------------------------------------------------------
# heartbeat helpers
# ---------------------------------------------------------------------------

def test_heartbeat_touch_and_age(tmp_path):
    hb = str(tmp_path / "w0.heartbeat")
    assert heartbeat_age(hb) is None  # not created yet: still booting
    touch_heartbeat(hb)
    age = heartbeat_age(hb)
    assert age is not None and age < 5.0


def test_heartbeat_touch_without_config_is_noop(monkeypatch):
    monkeypatch.delenv("ZOO_HEARTBEAT_FILE", raising=False)
    touch_heartbeat()  # no path anywhere: must not raise


# ---------------------------------------------------------------------------
# coordinator port TOCTOU retry (zoo_tpu.orca.bootstrap satellite)
# ---------------------------------------------------------------------------

def test_pick_coordinator_port_retries_taken_port(monkeypatch):
    from zoo_tpu.orca import bootstrap

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    taken = blocker.getsockname()[1]
    try:
        real = bootstrap.free_port
        served = []

        def first_taken():
            # the TOCTOU race made concrete: the first candidate is
            # already owned by someone else by the time we re-probe
            served.append(1)
            return taken if len(served) == 1 else real()

        monkeypatch.setattr(bootstrap, "free_port", first_taken)
        port = bootstrap._pick_coordinator_port()
        assert port != taken
        with socket.socket() as s:
            s.bind(("127.0.0.1", port))  # actually bindable
        assert len(served) >= 2  # it retried rather than failing
    finally:
        blocker.close()


def test_pick_coordinator_port_gives_up_with_clear_error(monkeypatch):
    from zoo_tpu.orca import bootstrap

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    taken = blocker.getsockname()[1]
    try:
        monkeypatch.setattr(bootstrap, "free_port", lambda: taken)
        with pytest.raises(RuntimeError, match="coordinator port"):
            bootstrap._pick_coordinator_port(retries=3)
    finally:
        blocker.close()


# ------------------------------------------- breaker half-open hardening

def test_half_open_probe_quota_under_thread_race():
    """Property: many threads racing allow()/record_* never admit more
    than half_open_max probes per probe window, and the breaker never
    wedges — after every storm of racing callers there is eventually a
    window that admits a probe again."""
    import threading as _threading

    from zoo_tpu.util.resilience import CircuitBreaker

    now = [0.0]
    lock = _threading.Lock()
    breaker = CircuitBreaker(failure_threshold=1, recovery_timeout=10.0,
                             half_open_max=2, clock=lambda: now[0])
    breaker.record_failure()          # OPEN at t=0
    now[0] = 10.0                     # recovery due: next allow probes

    admitted = []
    barrier = _threading.Barrier(16)

    def racer(i):
        barrier.wait()
        for _ in range(50):
            if breaker.allow():
                with lock:
                    admitted.append(i)

    threads = [_threading.Thread(target=racer, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 16 threads x 50 allow() calls in ONE probe window: exactly the
    # quota got through
    assert len(admitted) == 2, f"{len(admitted)} probes admitted"
    # none of the probes ever reported a verdict (callers died): the
    # breaker must NOT be wedged — a fresh window re-admits probes
    now[0] = 20.0
    assert breaker.allow(), "breaker wedged after vanished probes"
    # ... still within quota in the new window
    assert breaker.allow()
    assert not breaker.allow()
    # a success verdict closes it for good
    breaker.record_success()
    assert breaker.state == CircuitBreaker.CLOSED
    assert breaker.allow()


def test_half_open_failure_reopens_and_success_closes_under_race():
    """Concurrent probes where one fails and one succeeds: the breaker
    lands in a legal state either way (never a stuck intermediate) and
    keeps serving verdicts."""
    from zoo_tpu.util.resilience import CircuitBreaker

    now = [0.0]
    breaker = CircuitBreaker(failure_threshold=1, recovery_timeout=5.0,
                             half_open_max=2, clock=lambda: now[0])
    breaker.record_failure()
    now[0] = 5.0
    assert breaker.allow() and breaker.allow()
    breaker.record_failure()   # probe 1 verdict: reopen
    assert breaker.state == CircuitBreaker.OPEN
    breaker.record_success()   # probe 2 verdict: close wins last
    assert breaker.state == CircuitBreaker.CLOSED
    assert breaker.allow()
