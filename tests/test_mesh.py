import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from zoo_tpu.parallel import (
    batch_sharding,
    build_mesh,
    fsdp_param_sharding,
    replicated_sharding,
)
from zoo_tpu.parallel.mesh import shard_params, validate_batch_size


def test_build_default_mesh():
    mesh = build_mesh()
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1


def test_build_mesh_wildcard_and_explicit():
    mesh = build_mesh(axis_sizes={"data": -1, "model": 2})
    assert mesh.shape["data"] == 4
    assert mesh.shape["model"] == 2
    with pytest.raises(ValueError):
        build_mesh(axis_sizes={"data": 3})
    with pytest.raises(ValueError):
        build_mesh(axis_sizes={"bogus": 2})


def test_batch_sharding_places_data():
    mesh = build_mesh()
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    arr = jax.device_put(x, batch_sharding(mesh, ndim=2))
    assert arr.sharding.is_equivalent_to(batch_sharding(mesh, 2), 2)
    # each of the 8 devices holds 2 rows
    assert arr.addressable_shards[0].data.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_fsdp_param_sharding_picks_divisible_dim():
    mesh = build_mesh(axis_sizes={"data": 2, "fsdp": 4})
    s = fsdp_param_sharding(mesh, (12, 7))
    assert s.spec[0] == "fsdp"  # 12 % 4 == 0 → dim 0
    s = fsdp_param_sharding(mesh, (7, 16))
    assert s.spec[1] == "fsdp"
    # nothing divisible → replicated
    s = fsdp_param_sharding(mesh, (7, 5))
    assert s.spec == P()


def test_shard_params_tree():
    mesh = build_mesh(axis_sizes={"fsdp": 8})
    params = {"w": jnp.ones((16, 4)), "b": jnp.ones((3,))}
    sharded = shard_params(params, mesh)
    assert sharded["w"].addressable_shards[0].data.shape == (2, 4)
    np.testing.assert_array_equal(np.asarray(sharded["w"]), np.ones((16, 4)))


def test_validate_batch_size():
    mesh = build_mesh()
    assert validate_batch_size(16, mesh) == 2
    with pytest.raises(ValueError):
        validate_batch_size(12, mesh)


def test_validate_batch_size_error_text():
    """The error must say WHAT must divide by WHAT — it is the first
    thing a user hits moving a single-chip script to a mesh."""
    mesh = build_mesh(axis_sizes={"data": 2, "fsdp": 4})
    with pytest.raises(ValueError,
                       match=r"batch_size \(12\) must be divisible by "
                             r"the number of data-parallel shards \(8\)"):
        validate_batch_size(12, mesh)


def test_factor_shape_edge_cases():
    """Mesh factoring at the world sizes the elastic path actually
    visits (8 → 6 → 1): wildcard absorption, full coverage checks, and
    the error modes."""
    from zoo_tpu.parallel.mesh import _factor_shape

    axes = ("data", "fsdp", "model")
    # 1 device: everything collapses to 1s
    assert _factor_shape(1, {"data": -1}, axes) == (1, 1, 1)
    assert _factor_shape(1, {}, axes) == (1, 1, 1)
    # 6 devices (a scale-down world size): wildcard absorbs the rest
    assert _factor_shape(6, {"data": -1, "model": 2}, axes) == (3, 1, 2)
    assert _factor_shape(6, {"data": 6}, axes) == (6, 1, 1)
    # 8 devices, fully explicit
    assert _factor_shape(8, {"data": 2, "fsdp": 2, "model": 2},
                         axes) == (2, 2, 2)
    with pytest.raises(ValueError, match="not divisible"):
        _factor_shape(6, {"data": 4}, axes)
    with pytest.raises(ValueError, match="cover 3 devices but 6"):
        _factor_shape(6, {"data": 3}, ("data",))
    with pytest.raises(ValueError, match="only one mesh axis may be -1"):
        _factor_shape(8, {"data": -1, "fsdp": -1}, axes)
    with pytest.raises(ValueError, match="positive size"):
        _factor_shape(8, {"data": 0}, axes)


def test_pick_divisible_dim_fallback_to_replication():
    """Nothing divides → None → the plan replicates instead of erroring
    (odd embedding vocab on an even mesh is a real case)."""
    from zoo_tpu.parallel.mesh import pick_divisible_dim

    assert pick_divisible_dim((7, 5), 4) is None
    assert pick_divisible_dim((12, 8), 4) == 0       # largest divisible
    assert pick_divisible_dim((12, 8), 4, taken=(0,)) == 1
    assert pick_divisible_dim((12, 7), 4, taken=(0,)) is None
    assert pick_divisible_dim((), 4) is None
    s = fsdp_param_sharding(build_mesh(axis_sizes={"fsdp": 8}), (7, 5))
    assert s.spec == P()


def test_mesh_axes_from_env(monkeypatch):
    from zoo_tpu.parallel.mesh import mesh_axes_from_env

    monkeypatch.delenv("ZOO_MESH_DATA", raising=False)
    assert mesh_axes_from_env() is None
    monkeypatch.setenv("ZOO_MESH_FSDP", "4")
    monkeypatch.setenv("ZOO_MESH_DATA", "-1")
    assert mesh_axes_from_env() == {"data": -1, "fsdp": 4}
    mesh = build_mesh(axis_sizes=mesh_axes_from_env())
    assert mesh.shape["fsdp"] == 4 and mesh.shape["data"] == 2


def test_psum_over_mesh_collective():
    """Real allreduce over the virtual mesh via shard_map — the rebuild's
    equivalent of the reference's DistriEstimatorSpec on local[4]."""
    from zoo_tpu.parallel.compat import shard_map

    mesh = build_mesh()
    x = jnp.arange(8.0)

    f = shard_map(lambda v: jax.lax.psum(v, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P("data"))
    out = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), x.sum()))
