"""ParquetDataset + runnable examples as integration tests."""

import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # runs every example script in a fresh subprocess


def test_parquet_write_read_arrays(tmp_path):
    from zoo_tpu.orca.data.parquet_dataset import ParquetDataset

    rs = np.random.RandomState(0)
    imgs = rs.rand(37, 8, 8, 3).astype(np.float32)
    labels = rs.randint(0, 3, 37)

    def gen():
        for i in range(37):
            yield {"image": imgs[i], "label": int(labels[i]),
                   "name": f"img{i}"}

    out = str(tmp_path / "ds")
    ParquetDataset.write(out, gen(),
                         {"image": "ndarray", "label": "scalar",
                          "name": "scalar"}, block_size=10)
    assert len([f for f in os.listdir(out)
                if f.endswith(".parquet")]) == 4  # 10+10+10+7
    data = ParquetDataset.read_as_arrays(out)
    np.testing.assert_allclose(data["image"], imgs, atol=1e-6)
    np.testing.assert_array_equal(data["label"], labels)
    assert data["name"][0] == "img0"


def test_parquet_read_batched_and_xshards(tmp_path):
    from zoo_tpu.orca.data.parquet_dataset import (
        ParquetDataset,
        write_ndarrays,
    )

    rs = np.random.RandomState(1)
    imgs = rs.rand(25, 4, 4).astype(np.float32)
    labels = rs.randint(0, 2, 25)
    out = str(tmp_path / "nd")
    write_ndarrays(imgs, labels, out, block_size=8)

    batches = list(ParquetDataset.read_batched(out, batch_size=10))
    assert [b["image"].shape[0] for b in batches] == [10, 10, 5]
    np.testing.assert_allclose(np.concatenate([b["image"] for b in batches]),
                               imgs, atol=1e-6)

    shards = ParquetDataset.read_as_xshards(out, num_shards=5)
    assert shards.num_partitions() == 5


def test_parquet_image_folder(tmp_path):
    from zoo_tpu.orca.data.parquet_dataset import (
        ParquetDataset,
        write_from_directory,
    )

    for cls in ("cat", "dog"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            (d / f"{i}.jpg").write_bytes(b"\xff\xd8FAKEJPEG" + bytes([i]))
    out = str(tmp_path / "pq")
    write_from_directory(str(tmp_path / "imgs"),
                         {"cat": 0, "dog": 1}, out, shuffle=False)
    data = ParquetDataset.read_as_arrays(out)
    assert sorted(data["label"].tolist()) == [0, 0, 0, 1, 1, 1]
    assert data["image"][0].startswith(b"\xff\xd8")


def test_pandas_read_parquet(tmp_path, orca_ctx):
    import pandas as pd

    from zoo_tpu.orca.data.pandas import read_parquet

    df = pd.DataFrame({"a": np.arange(20), "b": np.arange(20) * 2.0})
    p = str(tmp_path / "t.parquet")
    df.to_parquet(p)
    shards = read_parquet(p, num_shards=2)
    got = pd.concat(shards.collect()).sort_values("a").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, df)


_EXAMPLES = ["ncf_movielens.py", "dogs_vs_cats_resnet.py",
             "autots_forecasting.py", "cluster_serving_roundtrip.py",
             "text_classification.py", "torch_finetune.py",
             "image_classification_inference.py", "anomaly_detection.py",
             "wide_n_deep_recommendation.py", "variational_autoencoder.py",
             "seq2seq_forecast.py", "auto_xgboost_regression.py",
             "session_recommendation.py", "image_augmentation.py",
             "multihost_training.py", "image_similarity.py",
             "llama_pretrain.py", "qa_ranking_knrm.py",
             "nnframes_pipeline.py", "fraud_detection.py",
             "tfnet_image_inference.py", "object_detection_ssd.py",
             "quantized_inference.py", "serving_throughput.py",
             "tcmf_panel_forecast.py", "moe_llama_pretrain.py",
             "image_augmentation_3d.py", "autograd_custom_loss.py",
             "friesian_recsys_features.py", "inception_training.py",
             "elastic_training.py", "xshards_preprocessing.py",
             "tf1_graph_training.py"]


@pytest.mark.parametrize("script", _EXAMPLES)
def test_example_runs(script):
    """Each examples/ script must run end-to-end on the CPU mesh (the
    reference's run-example-tests*.sh role)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    args = [sys.executable, os.path.join("examples", script)]
    if script == "ncf_movielens.py":
        args += ["--epochs", "2"]
    if script == "autots_forecasting.py":
        args += ["--trials", "2", "--epochs", "2"]
    if script in ("text_classification.py", "torch_finetune.py"):
        args += ["--epochs", "2"]
    if script == "anomaly_detection.py":
        args += ["--epochs", "3"]
    if script == "auto_xgboost_regression.py":
        args += ["--samples", "4"]
    if script == "fraud_detection.py":
        args += ["--rows", "8000", "--epochs", "3"]
    if script == "object_detection_ssd.py":
        args += ["--out", "/tmp/zoo_detections.png"]
    if script == "serving_throughput.py":
        args += ["--clients", "2", "--records", "128"]
    proc = subprocess.run(args, capture_output=True, text=True, timeout=900,
                          env=env)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "OK" in proc.stdout


def test_pack_env_bundle(tmp_path):
    """scripts/pack_env.sh (the conda-pack deployment role) produces a
    tarball holding the repo source + an env manifest."""
    import subprocess
    import tarfile

    out = tmp_path / "bundle.tgz"
    proc = subprocess.run(["bash", os.path.join("scripts", "pack_env.sh"),
                           str(out)], check=True, timeout=300,
                          capture_output=True, text=True)
    # pin the branch: this image has no conda-pack/venv-pack, so the
    # manifest fallback must have been taken (a surprise env.tgz branch
    # would pack the multi-GB live env and time out)
    assert "wrote requirements.lock" in proc.stdout, proc.stdout
    with tarfile.open(out) as tf:
        names = tf.getnames()
    assert any(n.endswith("bundle/repo/zoo_tpu/__init__.py")
               for n in names), names[:5]
    assert any(n.endswith("requirements.lock") for n in names)
    # caches, envs and VCS must not ship
    assert not any("__pycache__" in n or "/.git/" in n
                   or "/.venv/" in n for n in names)
