"""The pipeline and moe plans as first-class fit-seam citizens
(docs/multichip.md): GPipe microbatch training matches plain dp loss
for loss, the stacked body really shards over the ``pipe`` axis, guard
rollback survives the stacked layout, and expert-sharded moe_ffn
matches the replicated reference."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zoo_tpu.orca import init_orca_context, stop_orca_context
from zoo_tpu.parallel import build_mesh
from zoo_tpu.parallel.plans import (
    PIPE_BODY_KEY,
    estimate_collective_bytes,
    place_params,
)
from zoo_tpu.pipeline.api.keras import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.api.keras.optimizers import Adam


def _deep_model(plan=None, body=4):
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    for _ in range(body):
        m.add(Dense(16, activation="relu"))
    m.add(Dense(1))
    m.compile(optimizer=Adam(lr=0.01), loss="mse", plan=plan)
    return m


def _data(n=128, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    return x, (x @ rs.randn(8, 1).astype(np.float32))


@pytest.mark.multichip
def test_pipeline_plan_matches_dp_and_shards_body():
    """Same model/seed/data: plain per-layer dp on one device vs
    plan="pipeline" on a data x pipe mesh — the GPipe schedule is a
    reordering of the same math, loss curves must agree; and the
    stacked body must land (1/pipe per device) on the pipe axis."""
    x, y = _data()

    def run(mesh_axes, devices, plan):
        init_orca_context(cluster_mode="local", devices=devices,
                          mesh_axes=mesh_axes)
        try:
            m = _deep_model(plan=plan)
            losses = m.fit(x, y, batch_size=32, nb_epoch=3,
                           verbose=0)["loss"]
            return losses, m
        finally:
            stop_orca_context()

    ref, _ = run(None, jax.devices()[:1], None)
    got, m = run({"data": 2, "pipe": 4}, jax.devices()[:8], "pipeline")
    np.testing.assert_allclose(got, ref, rtol=0, atol=1e-5)
    assert PIPE_BODY_KEY in m.params
    placed = m._place(m.params)
    w = placed[PIPE_BODY_KEY]["W"]
    assert w.shape == (4, 16, 16)
    assert w.addressable_shards[0].data.shape == (1, 16, 16), w.sharding


@pytest.mark.multichip
def test_guard_rollback_under_pipeline(tmp_path):
    """The PR 4 escalation ladder survives the stacked-body layout: a
    NaN batch streak under plan="pipeline" rolls back to the verified
    checkpoint (re-placed through the plan-aware _place) and training
    continues finite, body still stacked and sharded."""
    from zoo_tpu.orca.learn.guard import GuardConfig, TrainingGuard
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.util.resilience import inject

    def _poison(site=None, arrays=None, idx=None, **_):
        for a in arrays:
            a[:] = np.nan

    x, y = _data(n=256)
    init_orca_context(cluster_mode="local", devices=jax.devices()[:8],
                      mesh_axes={"data": 2, "pipe": 4})
    try:
        guard = TrainingGuard(config=GuardConfig(
            enabled=True, max_skips=4, preempt_signal="none"))
        est = Estimator.from_keras(
            _deep_model(plan="pipeline"),
            model_dir=str(tmp_path / "gpipe"), guard=guard)
        data = {"x": x, "y": y}
        h0 = est.fit(data, epochs=1, batch_size=32)
        with inject("fit.batch", action=_poison, exc=None, times=2):
            h = est.fit(data, epochs=3, batch_size=32)
        assert guard.rollbacks >= 1
        # an epoch the rollback wiped entirely raises EpochRolledBack
        # and the Estimator perimeter retrains it from the restored
        # checkpoint — every REPORTED epoch is a real, finite one
        assert np.isfinite(h0["loss"]).all(), h0["loss"]
        assert len(h["loss"]) == 3 and np.isfinite(h["loss"]).all(), \
            h["loss"]
        assert PIPE_BODY_KEY in est.model.params
        leaves = jax.tree_util.tree_leaves(est.model.params)
        assert all(np.isfinite(np.asarray(a)).all() for a in leaves)
    finally:
        stop_orca_context()


def test_pipeline_plan_needs_homogeneous_body():
    """No contiguous run of >= 2 identical layers -> loud refusal, not
    a silent fall-back to an unpipelined model."""
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(12, activation="relu"))   # widths all differ
    m.add(Dense(1))
    m.compile(optimizer=Adam(lr=0.01), loss="mse", plan="pipeline")
    with pytest.raises(ValueError, match="identical layers"):
        m.build()


def test_compile_rejects_unknown_plan():
    m = Sequential()
    m.add(Dense(4, input_shape=(8,)))
    with pytest.raises(KeyError):
        m.compile(optimizer="sgd", loss="mse", plan="no-such-plan")


@pytest.mark.multichip
def test_moe_plan_places_expert_leaves_and_matches_replicated():
    from zoo_tpu.ops.moe import init_moe_params, moe_ffn

    devices = jax.devices()[:8]
    mesh = build_mesh(devices, axis_sizes={"expert": 8})
    params = init_moe_params(jax.random.PRNGKey(0), hidden=16,
                             intermediate=32, n_experts=8)
    placed = place_params(params, mesh, "moe")
    assert placed["w_gate"].sharding.spec[0] == "expert"
    assert placed["w_down"].sharding.spec[0] == "expert"
    assert all(s is None for s in placed["router"].sharding.spec)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 64, 16),
                    np.float32)
    step = jax.jit(lambda p, t: moe_ffn(p, t, top_k=2,
                                        capacity_factor=1.25))
    y_ref, aux_ref = step(params, x)
    y_sh, aux_sh = step(placed, x)
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(float(aux_sh), float(aux_ref),
                               rtol=0, atol=1e-6)


def test_estimate_collective_bytes_pipeline_and_moe_terms():
    """The capacity-planning estimate knows the new plans: pipe/expert
    sharded leaves stop paying fsdp/data traffic for their sharded
    fraction, and activation_bytes turns into ppermute (GPipe boundary
    sends) / all-to-all (moe dispatch) terms."""
    devices = jax.devices()[:8]
    mesh_p = build_mesh(devices, axis_sizes={"data": 2, "pipe": 4})
    params = {PIPE_BODY_KEY: {"W": jnp.zeros((4, 16, 16))},
              "head": {"W": jnp.zeros((16, 1))}}
    est = estimate_collective_bytes(params, mesh_p, "pipeline",
                                    activation_bytes=1024,
                                    n_microbatch=4)
    assert est["ppermute"] == 2 * (4 + 4 - 1) * 1024 // 4
    assert est.get("all_to_all", 0) == 0
    mesh_e = build_mesh(devices, axis_sizes={"expert": 8})
    eparams = {"w_gate": jnp.zeros((8, 16, 32)),
               "router": jnp.zeros((16, 8))}
    est_e = estimate_collective_bytes(eparams, mesh_e, "moe",
                                      activation_bytes=1024)
    assert est_e["all_to_all"] == 4 * 1024
