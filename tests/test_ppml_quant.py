"""PPML crypto (EncryptSupportive) + encrypted-model and int8 inference
wiring (InferenceModel.load_encrypted / quantize_model)."""

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.inference.inference_model import (
    InferenceModel,
    quantize_model,
    save_encrypted,
)
from zoo_tpu.ppml import EncryptSupportive


def test_cbc_roundtrip_bytes():
    data = bytes(range(256)) * 33  # not block-aligned
    enc = EncryptSupportive.encrypt_bytes_with_aes_cbc(
        data, "secret", "salty")
    assert enc[:16] != data[:16] and len(enc) > len(data)
    dec = EncryptSupportive.decrypt_bytes_with_aes_cbc(
        enc, "secret", "salty")
    assert dec == data


def test_cbc_roundtrip_string_base64():
    msg = "hello TPU enclave ✓"
    enc = EncryptSupportive.encrypt_with_aes_cbc(msg, "s3cret", "NaCl")
    assert enc != msg
    assert EncryptSupportive.decrypt_with_aes_cbc(
        enc, "s3cret", "NaCl") == msg


def test_gcm_roundtrip_and_tamper_detection():
    data = b"model bytes " * 100
    enc = EncryptSupportive.encrypt_bytes_with_aes_gcm(data, "k", "s")
    assert EncryptSupportive.decrypt_bytes_with_aes_gcm(
        enc, "k", "s") == data
    tampered = enc[:20] + bytes([enc[20] ^ 0xFF]) + enc[21:]
    with pytest.raises(ValueError, match="decryption failed"):
        EncryptSupportive.decrypt_bytes_with_aes_gcm(tampered, "k", "s")


def test_wrong_secret_fails():
    enc = EncryptSupportive.encrypt_bytes_with_aes_cbc(b"x" * 64, "a", "b")
    with pytest.raises(ValueError):
        EncryptSupportive.decrypt_bytes_with_aes_cbc(enc, "WRONG", "b")


def test_key_lengths():
    for key_len in (128, 256):
        enc = EncryptSupportive.encrypt_bytes_with_aes_cbc(
            b"abc", "s", "t", key_len=key_len)
        assert EncryptSupportive.decrypt_bytes_with_aes_cbc(
            enc, "s", "t", key_len=key_len) == b"abc"


def _small_model():
    m = Sequential(name="enc_test")
    m.add(Dense(32, activation="relu", input_shape=(16,)))
    m.add(Dense(4))
    m.build()
    return m


def test_encrypted_model_roundtrip(tmp_path):
    model = _small_model()
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    ref = np.asarray(model.predict(x, batch_size=8))
    p = str(tmp_path / "m.enc")
    save_encrypted(model, p, "topsecret", "pepper")
    # ciphertext on disk: loading it unencrypted must fail
    with pytest.raises(Exception):
        InferenceModel().load(p)
    im = InferenceModel().load_encrypted(p, "topsecret", "pepper")
    got = np.asarray(im.predict(x, batch_size=8))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_quantize_model_close_and_int8(tmp_path):
    model = _small_model()
    x = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    ref = np.asarray(model.predict(x, batch_size=8))
    q = quantize_model(model)
    for key, group in q.params.items():
        if "dense" in key:
            assert group["W_q"].dtype == np.int8
            assert "W" not in group
    got = np.asarray(q.predict(x, batch_size=8))
    # int8 per-channel quantization: ~1% relative error budget
    assert np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-9) < 0.02


def test_load_quantized_from_disk(tmp_path):
    model = _small_model()
    x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
    ref = np.asarray(model.predict(x, batch_size=4))
    p = str(tmp_path / "m.zoo")
    model.save(p)
    im = InferenceModel().load(p, quantize=True)
    got = np.asarray(im.predict(x, batch_size=4))
    assert np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-9) < 0.02


def test_quantized_model_refuses_fit():
    model = quantize_model(_small_model())
    model.compile(optimizer="adam", loss="mse")
    x = np.zeros((4, 16), np.float32)
    with pytest.raises(RuntimeError, match="inference-only"):
        model.fit(x, np.zeros((4, 4), np.float32), batch_size=4,
                  nb_epoch=1, verbose=0)


def test_quantize_conv_model(orca_ctx):
    """Int8 covers conv nets (the reference's headline int8 use —
    SSD/VGG inference): quantized conv predictions stay close to float,
    weights shrink to int8."""
    import jax.numpy as jnp

    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import (
        Conv2D, Dense, Flatten, GlobalAveragePooling2D)
    from zoo_tpu.pipeline.inference.inference_model import quantize_model

    m = Sequential()
    m.add(Conv2D(8, 3, 3, border_mode="same", dim_ordering="tf",
                 activation="relu", input_shape=(8, 8, 3)))
    m.add(Conv2D(8, 3, 3, border_mode="same", dim_ordering="tf"))
    m.add(GlobalAveragePooling2D(dim_ordering="tf"))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    x = np.random.RandomState(0).rand(6, 8, 8, 3).astype(np.float32)
    m.build()
    ref = np.asarray(m.predict(x, batch_size=6))

    quantize_model(m)
    for layer in m.layers:
        p = m.params[m._key_of(layer)]
        if "W_q" in p:
            assert p["W_q"].dtype == jnp.int8
            assert "W" not in p
    got = np.asarray(m.predict(x, batch_size=6))
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=0.05)
    # int8 is inference-only
    import pytest

    with pytest.raises(RuntimeError, match="quantized"):
        m.fit(x, np.zeros(6, np.int32), batch_size=6, nb_epoch=1)


def test_quantize_auto_falls_back_when_int8_loses(monkeypatch):
    """auto mode measures int8 against the float forward and restores
    the float weights when int8 does not win (the BENCH_r05 pathology:
    resnet50_int8_speedup = 0.974 — int8 *slower* than bf16)."""
    from zoo_tpu.pipeline.inference import inference_model as im

    rates = iter([1000.0, 800.0])  # float first, then int8: int8 loses
    monkeypatch.setattr(im, "_time_forward",
                        lambda model, xs, reps=3: next(rates))
    model = _small_model()
    x = np.random.RandomState(3).randn(4, 16).astype(np.float32)
    ref = np.asarray(model.predict(x, batch_size=4))
    out = im.quantize_model(model, mode="auto")
    assert out._quant_path == "bf16-fallback"
    assert abs(out._quant_speedup - 0.8) < 1e-6
    for key, group in out.params.items():
        if "dense" in key:
            assert "W" in group and "W_q" not in group
    # the restored model still predicts EXACTLY like the original
    np.testing.assert_allclose(
        np.asarray(out.predict(x, batch_size=4)), ref, atol=1e-6)
    assert not getattr(out, "_quantized", False)  # fit() still allowed


def test_quantize_auto_keeps_int8_when_it_wins(monkeypatch):
    from zoo_tpu.pipeline.inference import inference_model as im

    rates = iter([1000.0, 2000.0])  # int8 2x faster
    monkeypatch.setattr(im, "_time_forward",
                        lambda model, xs, reps=3: next(rates))
    out = im.quantize_model(_small_model(), mode="auto")
    assert out._quant_path == "int8"
    assert any("W_q" in g for g in out.params.values()
               if isinstance(g, dict))


def test_quantize_mode_off_and_env_override(monkeypatch):
    from zoo_tpu.pipeline.inference import inference_model as im

    out = im.quantize_model(_small_model(), mode="off")
    assert out._quant_path == "bf16"
    assert all("W_q" not in g for g in out.params.values()
               if isinstance(g, dict))
    # ZOO_INT8_MODE fills in an UNSPECIFIED mode...
    monkeypatch.setenv("ZOO_INT8_MODE", "off")
    out2 = im.quantize_model(_small_model())
    assert out2._quant_path == "bf16"
    # ...but an explicit call-site mode always wins (bench relies on
    # mode="force" measuring real int8 whatever the ambient env says)
    out3 = im.quantize_model(_small_model(), mode="force")
    assert out3._quant_path == "int8"
    monkeypatch.setenv("ZOO_INT8_MODE", "bogus")
    with pytest.raises(ValueError, match="bogus"):
        im.quantize_model(_small_model())


def test_quantize_auto_measures_for_real():
    """No stubs: auto mode on a real model picks SOME path, the model
    stays usable, and the measured ratio is recorded."""
    from zoo_tpu.pipeline.inference import inference_model as im

    model = _small_model()
    x = np.random.RandomState(4).randn(4, 16).astype(np.float32)
    ref = np.asarray(model.predict(x, batch_size=4))
    out = im.quantize_model(model, mode="auto")
    assert out._quant_path in ("int8", "bf16-fallback")
    assert out._quant_speedup > 0
    got = np.asarray(out.predict(x, batch_size=4))
    assert np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-9) < 0.02


@pytest.fixture(autouse=True)
def _fresh_verdict_cache():
    """Each test measures its own world: the auto-verdict cache would
    otherwise replay a verdict stubbed by an earlier test (same probe
    architecture across the whole file)."""
    from zoo_tpu.pipeline.inference import inference_model as im

    im._AUTO_VERDICT_CACHE.clear()
    yield
    im._AUTO_VERDICT_CACHE.clear()


def test_quantize_auto_verdict_cached_per_architecture(monkeypatch):
    """The auto microbench runs ONCE per (architecture, sample shape):
    a second quantize_model of the same topology replays the cached
    verdict — no timing calls — the rolling-reload / A-B replica case."""
    from zoo_tpu.pipeline.inference import inference_model as im

    calls = []

    def timed(model, xs, reps=3):
        calls.append(1)
        return [1000.0, 2000.0][len(calls) - 1]  # int8 wins

    monkeypatch.setattr(im, "_time_forward", timed)
    out1 = im.quantize_model(_small_model(), mode="auto")
    assert out1._quant_path == "int8" and len(calls) == 2
    out2 = im.quantize_model(_small_model(), mode="auto")
    assert len(calls) == 2, "cache miss re-ran the microbench"
    assert out2._quant_path == "int8"
    assert out2._quant_speedup == out1._quant_speedup
    assert any("W_q" in g for g in out2.params.values()
               if isinstance(g, dict))
    # a DIFFERENT architecture is a different verdict
    m3 = Sequential()
    m3.add(Dense(8, input_shape=(16,)))
    m3.compile(optimizer="sgd", loss="mse")
    m3.build()
    calls.clear()
    im.quantize_model(m3, mode="auto")
    assert len(calls) == 2


def test_quantize_path_published_to_metrics():
    """Every quantize_model decision lands in the scrape as
    zoo_quant_path_info{path,speedup} with exactly one series at 1."""
    from zoo_tpu.obs.metrics import get_registry
    from zoo_tpu.pipeline.inference import inference_model as im

    im.quantize_model(_small_model(), mode="force")
    text = get_registry().render_prometheus()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("zoo_quant_path_info")]
    assert any('path="int8"' in ln and ln.rstrip().endswith(" 1")
               for ln in lines), lines
    im.quantize_model(_small_model(), mode="off")
    text = get_registry().render_prometheus()
    live = [ln for ln in text.splitlines()
            if ln.startswith("zoo_quant_path_info")
            and ln.rstrip().endswith(" 1")]
    assert len(live) == 1 and 'path="bf16"' in live[0], live
