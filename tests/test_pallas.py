"""Pallas kernel correctness vs dense JAX references (interpret mode on
the hermetic CPU rig; the same kernels compile via Mosaic on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zoo_tpu.ops.attention import dot_product_attention
from zoo_tpu.ops.pallas import (
    flash_attention, quantize_int8, quantized_matmul, quantized_dense,
    fused_apply_sgd, fused_apply_adam)


def _qkv(b=2, h=3, t=80, d=32, tk=None, seed=0):
    rs = np.random.RandomState(seed)
    tk = t if tk is None else tk
    q = jnp.asarray(rs.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, tk, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, tk, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_dense(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = dot_product_attention(q, k, v, causal=causal, impl="dense")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_cross_length():
    q, k, v = _qkv(t=40, tk=72)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = dot_product_attention(q, k, v, impl="dense")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("t,tk", [(4, 16), (1, 16), (40, 72)])
def test_flash_attention_causal_cross_length_end_aligned(t, tk):
    # Decode-style tq < tk: causal must be END-aligned (the last query row
    # sees every key), matching the dense path's tril(k=tk-tq).
    q, k, v = _qkv(t=t, tk=tk)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    ref = dot_product_attention(q, k, v, causal=True, impl="dense")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads_match_dense(causal):
    q, k, v = _qkv(b=1, h=2, t=48, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=16, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal,
                                             impl="dense") ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_quantized_matmul_close_to_f32():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(24, 96), jnp.float32)
    w = jnp.asarray(rs.randn(96, 40), jnp.float32)
    w_q, w_s = quantize_int8(w, axis=0)           # per-output-channel
    x_q, x_s = quantize_int8(x, axis=-1)          # per-row
    y = quantized_matmul(x_q, w_q, x_s, w_s, block_m=32, block_n=32,
                         block_k=32)
    ref = x @ w
    err = np.abs(np.asarray(y) - np.asarray(ref))
    scale = np.abs(np.asarray(ref)).mean()
    assert err.mean() / scale < 0.02, (err.mean(), scale)


def test_quantized_dense_bias_and_batch_dims():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(4, 6, 64), jnp.float32)
    w = jnp.asarray(rs.randn(64, 32), jnp.float32)
    b = jnp.asarray(rs.randn(32), jnp.float32)
    w_q, w_s = quantize_int8(w, axis=0)
    y = quantized_dense(x, w_q, w_s, bias=b)
    assert y.shape == (4, 6, 32)
    ref = x @ w + b
    rel = (np.abs(np.asarray(y - ref)).mean() /
           np.abs(np.asarray(ref)).mean())
    assert rel < 0.03, rel


def test_fused_sgd_matches_formula():
    rs = np.random.RandomState(3)
    p = jnp.asarray(rs.randn(13, 7), jnp.float32)   # odd shape → padding
    g = jnp.asarray(rs.randn(13, 7), jnp.float32)
    buf = jnp.zeros_like(p)
    p1, buf1 = fused_apply_sgd(p, g, buf, lr=0.1, momentum=0.9,
                               weight_decay=0.01)
    g_eff = g + 0.01 * p
    buf_ref = g_eff
    p_ref = p - 0.1 * buf_ref
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p_ref),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(buf1), np.asarray(buf_ref),
                               atol=1e-6)
    # second step exercises the momentum accumulation
    p2, buf2 = fused_apply_sgd(p1, g, buf1, lr=0.1, momentum=0.9,
                               weight_decay=0.0)
    buf_ref2 = 0.9 * buf_ref + g
    np.testing.assert_allclose(np.asarray(buf2), np.asarray(buf_ref2),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(p2),
                               np.asarray(p1 - 0.1 * buf_ref2), atol=1e-6)


def test_fused_adam_matches_optax():
    import optax
    rs = np.random.RandomState(4)
    p = jnp.asarray(rs.randn(33), jnp.float32)
    g = jnp.asarray(rs.randn(33), jnp.float32)
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)
    p1, m1, v1 = fused_apply_adam(p, g, m, v, step=1, lr=1e-2)

    opt = optax.adam(1e-2)
    state = opt.init(p)
    upd, _ = opt.update(g, state, p)
    p_ref = optax.apply_updates(p, upd)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p_ref),
                               atol=1e-5, rtol=1e-5)


def test_flash_gqa_matches_repeated_dense():
    """GQA-native flash: unrepeated kv heads through the kernel's index
    maps — values AND all three gradients must match dense attention on
    the explicitly repeated kv."""
    import jax
    import jax.numpy as jnp

    from zoo_tpu.ops.attention import dot_product_attention
    from zoo_tpu.ops.pallas import flash_attention

    rs = np.random.RandomState(0)
    B, HQ, HKV, T, D = 2, 6, 2, 32, 8
    q = jnp.asarray(rs.randn(B, HQ, T, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, HKV, T, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, HKV, T, D), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=16,
                                       block_k=16, interpret=True) ** 2)

    def loss_dense(q, k, v):
        rep = HQ // HKV
        return jnp.sum(dot_product_attention(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            causal=True, impl="dense") ** 2)

    rep = HQ // HKV
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=True, block_q=16,
                                   block_k=16, interpret=True)),
        np.asarray(dot_product_attention(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            causal=True, impl="dense")), atol=2e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(gf, gd, "qkv"):
        assert a.shape == b.shape, (nm, a.shape, b.shape)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, err_msg=f"d{nm}")


def test_flash_gqa_rejects_bad_head_ratio():
    import jax.numpy as jnp
    import pytest

    from zoo_tpu.ops.pallas import flash_attention

    q = jnp.zeros((1, 5, 16, 8))
    kv = jnp.zeros((1, 2, 16, 8))
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, kv, kv, interpret=True)


def test_fused_bottleneck_matches_xla():
    """The fused bottleneck kernel (interpret mode on CPU) matches the
    XLA conv composition; the no-fit geometry falls back cleanly."""
    from zoo_tpu.ops.pallas.fused_block import (
        _pick_k,
        _xla_block,
        fused_bottleneck,
    )

    rs = np.random.RandomState(0)
    b, h, w, cin, cmid = 4, 8, 8, 32, 16
    x = jnp.asarray(rs.randn(b, h, w, cin).astype(np.float32))
    w1 = jnp.asarray((rs.randn(cin, cmid) / np.sqrt(cin))
                     .astype(np.float32))
    w2 = jnp.asarray((rs.randn(3, 3, cmid, cmid) / np.sqrt(9 * cmid))
                     .astype(np.float32))
    w3 = jnp.asarray((rs.randn(cmid, cin) / np.sqrt(cmid))
                     .astype(np.float32))

    ref = np.asarray(_xla_block(x, w1, w2, w3))
    got = np.asarray(fused_bottleneck(x, w1, w2, w3, interpret=True))
    # the kernel computes in bf16 with f32 accumulation
    np.testing.assert_allclose(got, ref, atol=5e-2, rtol=5e-2)

    # package interpret contract: the off-TPU DEFAULT also runs the
    # (interpreted) kernel, bf16 tolerance — not the XLA fallback
    fb = np.asarray(fused_bottleneck(x, w1, w2, w3))
    np.testing.assert_allclose(fb, ref, atol=5e-2, rtol=5e-2)

    # VMEM planner: real geometries fit, absurd ones return 0
    assert _pick_k(128, 56, 56, 256, 64) >= 1
    assert _pick_k(128, 112, 112, 2048, 512) == 0

    # interpret mode has no VMEM: the kernel must still run (not the
    # fallback) even on a geometry the TPU planner rejects
    b2, h2, w2_, cin2, cmid2 = 2, 12, 12, 2048, 512
    assert _pick_k(b2, h2, w2_, cin2, cmid2) == 0
    xb = jnp.asarray(rs.randn(b2, h2, w2_, cin2).astype(np.float32))
    wb1 = jnp.asarray((rs.randn(cin2, cmid2) / np.sqrt(cin2))
                      .astype(np.float32))
    wb2 = jnp.asarray((rs.randn(3, 3, cmid2, cmid2)
                       / np.sqrt(9 * cmid2)).astype(np.float32))
    wb3 = jnp.asarray((rs.randn(cmid2, cin2) / np.sqrt(cmid2))
                      .astype(np.float32))
    big_ref = np.asarray(_xla_block(xb, wb1, wb2, wb3))
    big_got = np.asarray(fused_bottleneck(xb, wb1, wb2, wb3,
                                          interpret=True))
    np.testing.assert_allclose(big_got, big_ref, atol=8e-2, rtol=8e-2)


def test_fused_bottleneck_custom_vjp_matches_xla_grads():
    """fused_bottleneck is differentiable: its custom_vjp (recompute
    backward through the XLA composition) matches jax.grad of the XLA
    block within bf16-forward tolerance."""
    import jax as _jax

    from zoo_tpu.ops.pallas.fused_block import _xla_block, fused_bottleneck

    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 8, 8, 32).astype(np.float32))
    w1 = jnp.asarray((rs.randn(32, 8) * 0.1).astype(np.float32))
    w2 = jnp.asarray((rs.randn(3, 3, 8, 8) * 0.1).astype(np.float32))
    w3 = jnp.asarray((rs.randn(8, 32) * 0.1).astype(np.float32))

    def loss_fused(w1, w2, w3):
        return jnp.sum(fused_bottleneck(x, w1, w2, w3, True) ** 2)

    def loss_xla(w1, w2, w3):
        return jnp.sum(_xla_block(x, w1, w2, w3) ** 2)

    g1 = _jax.grad(loss_fused, argnums=(0, 1, 2))(w1, w2, w3)
    g2 = _jax.grad(loss_xla, argnums=(0, 1, 2))(w1, w2, w3)
    for a, b in zip(g1, g2):
        scale = float(jnp.max(jnp.abs(b)))
        assert float(jnp.max(jnp.abs(a - b))) < 0.01 * scale + 0.05


def test_fused_quantized_matmul_matches_two_pass():
    """The fused quantize->int8-dot->dequant kernel reproduces the
    two-pass reference (quantize_int8 + quantized_matmul) up to
    borderline activation rounding: XLA rewrites x/scale as
    x * (1/scale), which can flip a round() by one int8 step, so the
    bound is one dequantized ULP — not bit-exactness."""
    from zoo_tpu.ops.pallas import fused_quantized_matmul

    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(24, 96), jnp.float32)
    w = jnp.asarray(rs.randn(96, 40), jnp.float32)
    w_q, w_s = quantize_int8(w, axis=0)
    x_q, x_s = quantize_int8(x, axis=-1)
    ref = quantized_matmul(x_q, w_q, x_s, w_s, block_m=32, block_n=32,
                          block_k=32)
    got = fused_quantized_matmul(x, w_q, w_s, block_m=32, block_n=32,
                                 block_k=32)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=0)
    # and it tracks the f32 matmul to quantization noise
    rel = (np.abs(np.asarray(got) - np.asarray(x @ w)).mean()
           / np.abs(np.asarray(x @ w)).mean())
    assert rel < 0.02, rel


def test_fused_quantized_dense_paths_agree():
    """quantized_dense(impl=...) is the one int8 GEMM dispatch point:
    fused and unfused backends agree (1-ULP rounding tolerance) with
    bias and leading batch dims."""
    from zoo_tpu.ops.pallas import quantized_dense as qd

    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(4, 6, 64), jnp.float32)
    w = jnp.asarray(rs.randn(64, 32), jnp.float32)
    b = jnp.asarray(rs.randn(32), jnp.float32)
    w_q, w_s = quantize_int8(w, axis=0)
    y_f = qd(x, w_q, w_s, bias=b, impl="fused")
    y_u = qd(x, w_q, w_s, bias=b, impl="unfused")
    assert y_f.shape == y_u.shape == (4, 6, 32)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_u),
                               atol=1e-4, rtol=0)


def test_resolve_int8_matmul_dispatch(monkeypatch):
    from zoo_tpu.ops.pallas import resolve_int8_matmul

    assert resolve_int8_matmul() == "fused"          # auto default
    assert resolve_int8_matmul("unfused") == "unfused"
    monkeypatch.setenv("ZOO_INT8_MATMUL", "unfused")
    assert resolve_int8_matmul() == "unfused"
    assert resolve_int8_matmul("fused") == "fused"   # arg beats env
    monkeypatch.delenv("ZOO_INT8_MATMUL")
    with pytest.raises(ValueError):
        resolve_int8_matmul("no-such-impl")
