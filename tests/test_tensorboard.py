"""Event-file writer/read-back, including interop with TensorFlow's own
summary_iterator (proving the hand-rolled proto encoding is the real
format, not a private one)."""

import os
import time

import numpy as np
import pytest

from zoo_tpu import tensorboard as tb


def test_roundtrip_scalars(tmp_path):
    w = tb.EventWriter(str(tmp_path))
    for i in range(10):
        w.add_scalar("Loss", 1.0 / (i + 1), i)
        w.add_scalar("Throughput", 100.0 + i, i)
    w.flush()
    w.close()
    got = tb.read_scalars(str(tmp_path))
    assert set(got) == {"Loss", "Throughput"}
    steps = [s for s, _, _ in got["Loss"]]
    assert steps == list(range(10))
    np.testing.assert_allclose([v for _, _, v in got["Loss"]],
                               [1.0 / (i + 1) for i in range(10)],
                               rtol=1e-6)


def test_tensorflow_can_read_our_files(tmp_path):
    tf = pytest.importorskip("tensorflow")
    w = tb.EventWriter(str(tmp_path))
    w.add_scalar("acc", 0.75, 3)
    w.close()
    files = [f for f in os.listdir(tmp_path) if "tfevents" in f]
    assert len(files) == 1
    events = list(tf.compat.v1.train.summary_iterator(
        str(tmp_path / files[0])))
    assert events[0].file_version == "brain.Event:2"
    ev = events[1]
    assert ev.step == 3
    assert ev.summary.value[0].tag == "acc"
    assert abs(ev.summary.value[0].simple_value - 0.75) < 1e-6


def test_we_can_read_tensorflow_files(tmp_path):
    tf = pytest.importorskip("tensorflow")
    with tf.summary.create_file_writer(str(tmp_path)).as_default():
        for i in range(5):
            tf.summary.scalar("val_loss", 0.5 - 0.1 * i, step=i)
    got = tb.read_scalars(str(tmp_path), "val_loss")
    assert [s for s, _, _ in got["val_loss"]] == list(range(5))


def test_summary_api_and_disk_readback(tmp_path):
    s = tb.TrainSummary(str(tmp_path), app_name="myapp/train")
    for i in range(5):
        s.add_scalar("Loss", float(i), i)
    assert s.read_scalar("Loss") == [(i, float(i)) for i in range(5)]
    s.close()
    # a fresh Summary over the same dir reads scalars back from disk
    s2 = tb.TrainSummary(str(tmp_path), app_name="myapp/train")
    vals = s2.read_scalar("Loss")
    assert [v for _, v in vals] == [float(i) for i in range(5)]
    s2.close()


def test_keras_fit_writes_readable_summaries(tmp_path):
    from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
    from zoo_tpu.pipeline.api.keras.layers.core import Dense

    rs = np.random.RandomState(0)
    m = Sequential()
    m.add(Dense(1, input_shape=(4,)))
    m.compile(optimizer="sgd", loss="mse")
    m.set_tensorboard(str(tmp_path), "app")
    x = rs.randn(64, 4).astype(np.float32)
    y = rs.randn(64, 1).astype(np.float32)
    m.fit(x, y, batch_size=16, nb_epoch=3, verbose=0)
    hist = m.get_train_summary("Loss")
    assert len(hist) == 3
    thr = m.get_train_summary("Throughput")
    assert len(thr) == 3 and all(v > 0 for _, v in thr)
    # files really land on disk in TF event format
    m.train_summary._writer.flush()
    disk = tb.read_scalars(os.path.join(str(tmp_path), "app/train"))
    assert "Loss" in disk and len(disk["Loss"]) == 3


def test_negative_and_large_steps(tmp_path):
    w = tb.EventWriter(str(tmp_path))
    w.add_scalar("t", 1.5, 2 ** 40)
    w.close()
    got = tb.read_scalars(str(tmp_path), "t")
    assert got["t"][0][0] == 2 ** 40


def test_read_scalars_survives_truncated_tail(tmp_path):
    """Crash-safety parity with the checkpoint reader: a writer killed
    mid-record leaves a torn tail; read-back must return every scalar
    before the damage, not raise."""
    w = tb.EventWriter(str(tmp_path))
    for i in range(8):
        w.add_scalar("Loss", float(i), i)
    w.flush()
    w.close()
    fname = [f for f in os.listdir(tmp_path) if "tfevents" in f][0]
    path = str(tmp_path / fname)
    whole = open(path, "rb").read()
    # chop the last record mid-payload (header intact, payload short)
    with open(path, "wb") as f:
        f.write(whole[:-7])
    got = tb.read_scalars(str(tmp_path), "Loss")
    steps = [s for s, _, _ in got["Loss"]]
    assert steps == list(range(7))  # all but the torn final record


def test_read_scalars_skips_corrupt_record_keeps_earlier(tmp_path):
    w = tb.EventWriter(str(tmp_path))
    for i in range(5):
        w.add_scalar("acc", 0.1 * i, i)
    w.flush()
    w.close()
    fname = [f for f in os.listdir(tmp_path) if "tfevents" in f][0]
    path = str(tmp_path / fname)
    data = bytearray(open(path, "rb").read())
    # flip bytes inside the LAST record's payload: framing stays intact,
    # the payload CRC fails, the reader skips just that record
    data[-10] ^= 0xFF
    data[-11] ^= 0xFF
    with open(path, "wb") as f:
        f.write(data)
    got = tb.read_scalars(str(tmp_path), "acc")
    steps = [s for s, _, _ in got["acc"]]
    assert steps == list(range(4))

    # garbage appended after valid records (corrupt length header):
    # reader stops there, earlier scalars still come back
    with open(path, "ab") as f:
        f.write(os.urandom(64))
    got = tb.read_scalars(str(tmp_path), "acc")
    assert [s for s, _, _ in got["acc"]] == list(range(4))
