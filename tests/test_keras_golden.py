"""Golden fidelity suite: zoo keras-1 layers vs real tf.keras (Keras 3).

The reference validates every Keras layer against recorded Keras outputs
(``zoo/src/test/scala/.../keras/layers/*Spec.scala``, SURVEY §4.2); this
is the same contract run live — identical weights pushed into both
implementations, forward outputs compared, both paddings and both
dim_orderings where the layer has them.

Intentional divergences from Keras 3 (not bugs; we match keras-1 / the
reference):
* ``hard_sigmoid``: keras-1 uses ``clip(0.2x+0.5)``, Keras 3 uses
  ``relu6(x+3)/6`` — recurrent specs pin ``inner_activation="sigmoid"``
  on both sides so the comparison tests the cell math, not that alias.
* keras-1-only layers (SReLU, MaxoutDense, Highway, CAdd/CMul, ...)
  have no Keras-3 counterpart and are covered by the unit tests instead.
"""

import dataclasses
from typing import Callable, Optional, Tuple

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
import keras  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import jax  # noqa: E402

import zoo_tpu.pipeline.api.keras.layers as ZL  # noqa: E402


@dataclasses.dataclass
class Spec:
    name: str
    zoo: Callable[[], object]
    ref: Callable[[], object]
    shape: Tuple[int, ...]                  # input shape, no batch
    weights: Optional[Callable] = None      # zoo params -> keras weights
    tol: float = 1e-5
    int_input: Optional[int] = None         # vocab size for id inputs
    nchw: bool = False                      # zoo consumes/produces NCHW


def _wb(p):
    return [np.asarray(p["W"])] + ([np.asarray(p["b"])] if "b" in p else [])


def _dw_to_keras(w, cin):
    """zoo depthwise kernel (kh, kw, 1, cin*mult) [grouped-conv form] ->
    keras (kh, kw, cin, mult)."""
    w = np.asarray(w)
    kh, kw, _, cm = w.shape
    return w.reshape(kh, kw, cin, cm // cin)


def _rnn(p):
    return [np.asarray(p["W"]), np.asarray(p["U"]), np.asarray(p["b"])]


SPECS = [
    Spec("dense", lambda: ZL.Dense(7), lambda: keras.layers.Dense(7),
         (5,), _wb),
    Spec("dense_relu", lambda: ZL.Dense(7, activation="relu"),
         lambda: keras.layers.Dense(7, activation="relu"), (5,), _wb),
    Spec("activation_tanh", lambda: ZL.Activation("tanh"),
         lambda: keras.layers.Activation("tanh"), (6,)),
    Spec("activation_softmax", lambda: ZL.Activation("softmax"),
         lambda: keras.layers.Activation("softmax"), (6,)),
    Spec("dropout_eval", lambda: ZL.Dropout(0.5),
         lambda: keras.layers.Dropout(0.5), (6,)),
    Spec("flatten", lambda: ZL.Flatten(),
         lambda: keras.layers.Flatten(), (3, 4, 2)),
    Spec("reshape", lambda: ZL.Reshape((6, 2)),
         lambda: keras.layers.Reshape((6, 2)), (3, 4)),
    Spec("permute", lambda: ZL.Permute((2, 1)),
         lambda: keras.layers.Permute((2, 1)), (3, 4)),
    Spec("repeatvector", lambda: ZL.RepeatVector(5),
         lambda: keras.layers.RepeatVector(5), (4,)),
    Spec("embedding", lambda: ZL.Embedding(11, 6),
         lambda: keras.layers.Embedding(11, 6), (5,),
         lambda p: [np.asarray(p["E"])], int_input=11),
    Spec("masking_identity", lambda: ZL.Masking(0.0),
         lambda: keras.layers.Lambda(lambda v: v), (4, 3)),
    # -- convolutions -----------------------------------------------------
    Spec("conv1d_valid",
         lambda: ZL.Convolution1D(5, 3, border_mode="valid"),
         lambda: keras.layers.Conv1D(5, 3, padding="valid"),
         (8, 4), _wb, tol=1e-4),
    Spec("conv1d_same_stride2",
         lambda: ZL.Convolution1D(5, 3, border_mode="same",
                                  subsample_length=2),
         lambda: keras.layers.Conv1D(5, 3, padding="same", strides=2),
         (8, 4), _wb, tol=1e-4),
    Spec("conv2d_tf_valid",
         lambda: ZL.Convolution2D(5, 3, 3, dim_ordering="tf",
                                  border_mode="valid"),
         lambda: keras.layers.Conv2D(5, 3, padding="valid"),
         (8, 8, 3), _wb, tol=1e-4),
    Spec("conv2d_tf_same_stride2",
         lambda: ZL.Convolution2D(5, 3, 3, dim_ordering="tf",
                                  border_mode="same", subsample=(2, 2)),
         lambda: keras.layers.Conv2D(5, 3, padding="same", strides=2),
         (8, 8, 3), _wb, tol=1e-4),
    Spec("conv2d_th_valid",
         lambda: ZL.Convolution2D(5, 3, 3, dim_ordering="th",
                                  border_mode="valid"),
         lambda: keras.layers.Conv2D(5, 3, padding="valid"),
         (8, 8, 3), _wb, tol=1e-4, nchw=True),
    Spec("conv2d_th_same",
         lambda: ZL.Convolution2D(5, 3, 3, dim_ordering="th",
                                  border_mode="same"),
         lambda: keras.layers.Conv2D(5, 3, padding="same"),
         (8, 8, 3), _wb, tol=1e-4, nchw=True),
    Spec("atrous_conv2d",
         lambda: ZL.AtrousConvolution2D(4, 3, 3, atrous_rate=(2, 2),
                                        dim_ordering="tf"),
         lambda: keras.layers.Conv2D(4, 3, dilation_rate=2),
         (9, 9, 3), _wb, tol=1e-4),
    Spec("conv3d_valid",
         lambda: ZL.Convolution3D(3, 2, 2, 2, dim_ordering="tf",
                                  border_mode="valid"),
         lambda: keras.layers.Conv3D(3, 2, padding="valid"),
         (5, 5, 5, 2), _wb, tol=1e-4),
    Spec("separable_conv2d",
         lambda: ZL.SeparableConvolution2D(6, 3, 3, dim_ordering="tf"),
         lambda: keras.layers.SeparableConv2D(6, 3),
         (8, 8, 3),
         lambda p: [_dw_to_keras(p["depth_W"], 3),
                    np.asarray(p["point_W"]), np.asarray(p["b"])],
         tol=1e-4),
    Spec("depthwise_conv2d",
         lambda: ZL.DepthwiseConvolution2D(3, 3, depth_multiplier=2,
                                           dim_ordering="tf"),
         lambda: keras.layers.DepthwiseConv2D(3, depth_multiplier=2),
         (8, 8, 3),
         lambda p: [_dw_to_keras(p["W"], 3)] + (
             [np.asarray(p["b"])] if "b" in p else []),
         tol=1e-4),
    Spec("deconv2d",
         lambda: ZL.Deconvolution2D(4, 3, 3, dim_ordering="th"),
         lambda: keras.layers.Conv2DTranspose(4, 3, padding="valid"),
         (6, 6, 3),
         _wb,  # zoo HWOI kernel == keras Conv2DTranspose layout
         tol=1e-4, nchw=True),
    # -- pooling ----------------------------------------------------------
    Spec("maxpool1d", lambda: ZL.MaxPooling1D(2),
         lambda: keras.layers.MaxPooling1D(2), (8, 3)),
    Spec("avgpool1d", lambda: ZL.AveragePooling1D(2),
         lambda: keras.layers.AveragePooling1D(2), (8, 3)),
    Spec("maxpool2d_tf", lambda: ZL.MaxPooling2D((2, 2),
                                                 dim_ordering="tf"),
         lambda: keras.layers.MaxPooling2D(2), (8, 8, 3)),
    Spec("maxpool2d_th", lambda: ZL.MaxPooling2D((2, 2),
                                                 dim_ordering="th"),
         lambda: keras.layers.MaxPooling2D(2), (8, 8, 3), nchw=True),
    Spec("avgpool2d_same",
         lambda: ZL.AveragePooling2D((2, 2), border_mode="same",
                                     dim_ordering="tf"),
         lambda: keras.layers.AveragePooling2D(2, padding="same"),
         (7, 7, 3)),
    Spec("maxpool3d",
         lambda: ZL.MaxPooling3D((2, 2, 2), dim_ordering="tf"),
         lambda: keras.layers.MaxPooling3D(2), (6, 6, 6, 2)),
    Spec("gmaxpool1d", lambda: ZL.GlobalMaxPooling1D(),
         lambda: keras.layers.GlobalMaxPooling1D(), (8, 3)),
    Spec("gavgpool2d_tf", lambda: ZL.GlobalAveragePooling2D(
        dim_ordering="tf"),
         lambda: keras.layers.GlobalAveragePooling2D(), (6, 6, 3)),
    Spec("gmaxpool2d_th", lambda: ZL.GlobalMaxPooling2D(
        dim_ordering="th"),
         lambda: keras.layers.GlobalMaxPooling2D(), (6, 6, 3),
         nchw=True),
    # -- shape ops --------------------------------------------------------
    Spec("zeropad1d", lambda: ZL.ZeroPadding1D(2),
         lambda: keras.layers.ZeroPadding1D(2), (5, 3)),
    Spec("zeropad2d", lambda: ZL.ZeroPadding2D((1, 2),
                                               dim_ordering="tf"),
         lambda: keras.layers.ZeroPadding2D((1, 2)), (5, 5, 3)),
    Spec("cropping1d", lambda: ZL.Cropping1D((1, 2)),
         lambda: keras.layers.Cropping1D((1, 2)), (8, 3)),
    Spec("cropping2d",
         lambda: ZL.Cropping2D(((1, 1), (2, 1)), dim_ordering="tf"),
         lambda: keras.layers.Cropping2D(((1, 1), (2, 1))), (8, 8, 3)),
    Spec("upsampling1d", lambda: ZL.UpSampling1D(2),
         lambda: keras.layers.UpSampling1D(2), (4, 3)),
    Spec("upsampling2d", lambda: ZL.UpSampling2D((2, 2),
                                                 dim_ordering="tf"),
         lambda: keras.layers.UpSampling2D(2), (4, 4, 3)),
    Spec("upsampling3d",
         lambda: ZL.UpSampling3D((2, 2, 2), dim_ordering="tf"),
         lambda: keras.layers.UpSampling3D(2), (3, 3, 3, 2)),
    # -- normalization ----------------------------------------------------
    Spec("batchnorm_eval", lambda: ZL.BatchNormalization(epsilon=1e-3),
         lambda: keras.layers.BatchNormalization(epsilon=1e-3),
         (6,),
         lambda p: [np.asarray(p["gamma"]), np.asarray(p["beta"]),
                    np.asarray(p["stats"]["mean"]),
                    np.asarray(p["stats"]["var"])]),
    # -- advanced activations --------------------------------------------
    Spec("leakyrelu", lambda: ZL.LeakyReLU(0.3),
         lambda: keras.layers.LeakyReLU(negative_slope=0.3), (6,)),
    Spec("elu", lambda: ZL.ELU(1.0),
         lambda: keras.layers.ELU(1.0), (6,)),
    # Keras 3 removed ThresholdedReLU; golden-check against its formula
    Spec("thresholdedrelu", lambda: ZL.ThresholdedReLU(1.0),
         lambda: keras.layers.Lambda(
             lambda v: v * keras.ops.cast(v > 1.0, v.dtype)), (6,)),
    Spec("prelu", lambda: ZL.PReLU(),
         lambda: keras.layers.PReLU(shared_axes=None), (6,),
         lambda p: [np.asarray(p["alpha"])]),
    # -- recurrent (sigmoid inner to sidestep the hard_sigmoid alias
    #    divergence documented above) -------------------------------------
    Spec("simplernn",
         lambda: ZL.SimpleRNN(5, activation="tanh",
                              return_sequences=True),
         lambda: keras.layers.SimpleRNN(5, activation="tanh",
                                        return_sequences=True),
         (6, 3), _rnn),
    Spec("lstm",
         lambda: ZL.LSTM(5, activation="tanh",
                         inner_activation="sigmoid",
                         return_sequences=True),
         lambda: keras.layers.LSTM(5, activation="tanh",
                                   recurrent_activation="sigmoid",
                                   return_sequences=True,
                                   unit_forget_bias=False),
         (6, 3), _rnn, tol=1e-4),
    Spec("gru",
         lambda: ZL.GRU(5, activation="tanh",
                        inner_activation="sigmoid",
                        return_sequences=True),
         lambda: keras.layers.GRU(5, activation="tanh",
                                  recurrent_activation="sigmoid",
                                  return_sequences=True,
                                  reset_after=False),
         (6, 3), _rnn, tol=1e-4),
    Spec("lstm_last_step",
         lambda: ZL.LSTM(4, activation="tanh",
                         inner_activation="sigmoid"),
         lambda: keras.layers.LSTM(4, activation="tanh",
                                   recurrent_activation="sigmoid",
                                   unit_forget_bias=False),
         (5, 3), _rnn, tol=1e-4),
    # -- wrappers ---------------------------------------------------------
    Spec("timedistributed_dense",
         lambda: ZL.TimeDistributed(ZL.Dense(4)),
         lambda: keras.layers.TimeDistributed(keras.layers.Dense(4)),
         (5, 3),
         lambda p: _wb(p[next(iter(p))] if isinstance(
             next(iter(p.values())), dict) else p)),
    Spec("bidirectional_lstm_concat",
         lambda: ZL.Bidirectional(
             ZL.LSTM(4, activation="tanh", inner_activation="sigmoid",
                     return_sequences=True), merge_mode="concat"),
         lambda: keras.layers.Bidirectional(
             keras.layers.LSTM(4, activation="tanh",
                               recurrent_activation="sigmoid",
                               return_sequences=True,
                               unit_forget_bias=False),
             merge_mode="concat"),
         (6, 3),
         lambda p: _rnn(p["fw"]) + _rnn(p["bw"]),
         tol=1e-4),
    # -- noise (eval = identity) -----------------------------------------
    Spec("gaussian_noise_eval", lambda: ZL.GaussianNoise(0.5),
         lambda: keras.layers.GaussianNoise(0.5), (6,)),
    Spec("gaussian_dropout_eval", lambda: ZL.GaussianDropout(0.5),
         lambda: keras.layers.GaussianDropout(0.5), (6,)),
]


def _zoo_forward(spec, layer, params, x):
    xin = x
    if spec.nchw:
        perm = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
        xin = np.transpose(x, perm)
    out = np.asarray(layer.call(params, jnp.asarray(xin),
                                training=False))
    if spec.nchw and out.ndim == x.ndim:
        inv = (0,) + tuple(range(2, out.ndim)) + (1,)
        out = np.transpose(out, inv)
    return out


@pytest.mark.parametrize("spec", SPECS, ids=lambda s: s.name)
def test_golden_vs_tf_keras(orca_ctx, spec):
    rs = np.random.RandomState(0)
    if spec.int_input:
        x = rs.randint(0, spec.int_input, (4,) + spec.shape
                       ).astype(np.int32)
    else:
        x = rs.randn(4, *spec.shape).astype(np.float32)

    zoo = spec.zoo()
    params = zoo.build(jax.random.PRNGKey(0), (None,) + (
        spec.shape if not spec.nchw else
        (spec.shape[-1],) + spec.shape[:-1]))
    got = _zoo_forward(spec, zoo, params, x)

    ref = spec.ref()
    want = np.asarray(ref(x))  # builds the layer
    if spec.weights is not None:
        ref.set_weights(spec.weights(params))
        want = np.asarray(ref(x))

    assert got.shape == want.shape, (got.shape, want.shape)
    np.testing.assert_allclose(got, want, rtol=spec.tol,
                               atol=spec.tol,
                               err_msg=f"layer {spec.name} diverges "
                                       "from tf.keras")
