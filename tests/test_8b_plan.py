"""Llama-3-8B placement plan at REAL shapes (BASELINE stretch row).

The dryrun proves the multichip step executes at tiny widths; this
proves the sharding PLAN at the actual 8B shapes without materializing
a byte: abstract param tree via jax.eval_shape, placement via the same
leaf_sharding the fit path uses, then per-device memory accounting
against v5e HBM.
"""

import numpy as np
import pytest

import jax

from zoo_tpu.models.llm import Llama, llama3_8b_config, llama_param_count
from zoo_tpu.parallel import build_mesh
from zoo_tpu.parallel.plans import leaf_sharding


def test_llama3_8b_fsdp_tp_plan_fits_v5e_hbm():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = llama3_8b_config()
    n_params = llama_param_count(cfg)
    assert 7.5e9 < n_params < 8.5e9  # it really is the 8B config

    layer = Llama(cfg)
    tree = jax.eval_shape(
        lambda k: layer.build(k, (None, 8192)), jax.random.PRNGKey(0))

    mesh = build_mesh(jax.devices()[:8],
                      axis_sizes={"fsdp": 4, "model": 2})
    total_bytes = 0
    max_shard_bytes = 0
    unsharded_big = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sh = leaf_sharding(mesh, leaf.shape)
        spec = sh.spec
        shard_elems = np.prod(leaf.shape, dtype=np.int64)
        divisor = 1
        for dim, ax in zip(leaf.shape, tuple(spec) + (None,) * 8):
            if ax is not None:
                axes = ax if isinstance(ax, tuple) else (ax,)
                for a in axes:
                    divisor *= mesh.shape[a]
        shard_elems //= divisor
        nbytes = int(np.prod(leaf.shape, dtype=np.int64)) * 4
        total_bytes += nbytes
        max_shard_bytes += shard_elems * 4
        if divisor == 1 and nbytes > 64 << 20:
            unsharded_big.append((jax.tree_util.keystr(path),
                                  leaf.shape))
    # every >64MB tensor must be sharded by the plan — a replicated
    # embedding alone (128256 x 4096 f32 = 2.1GB) would blow the budget
    assert not unsharded_big, unsharded_big
    # the plan must divide the full tree by ~the mesh size (fully
    # sharded, not just the big leaves)
    assert max_shard_bytes < total_bytes / 6
    # params + grads + adam m/v, all f32 = 4x params of static state.
    # On THIS 8-chip mesh that is ~15GiB/chip — honestly NOT a v5e fit;
    # the plan's point is that per-chip state scales as 1/n_chips, so
    # doubling the fsdp axis (16 chips, the smallest real 8B pod) lands
    # at ~7.5GiB/chip with >8GiB of HBM left for activations at
    # seq 8192. Assert both sides of that claim.
    static_8 = 4 * max_shard_bytes
    static_16 = static_8 // 2           # fsdp 4 -> 8 halves every shard
    assert static_8 > 12 << 30          # 8 chips genuinely don't fit
    assert static_16 < 8 << 30, f"{static_16 / (1 << 30):.1f} GiB"
