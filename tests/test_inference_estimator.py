"""Inference-only estimator (reference OpenVINO estimator surface):
predict over arrays and XShards, fit refuses, int8 path."""

import numpy as np
import pytest

from zoo_tpu.orca.data.shard import LocalXShards
from zoo_tpu.orca.learn.inference import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    m = Sequential(name="inf_est")
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(3))
    m.build()
    p = str(tmp_path_factory.mktemp("m") / "m.zoo")
    m.save(p)
    x = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    return p, x, np.asarray(m.predict(x, batch_size=32))


def test_predict_arrays(saved_model):
    p, x, ref = saved_model
    est = Estimator.from_model(p)
    np.testing.assert_allclose(est.predict(x, batch_size=16), ref,
                               atol=1e-5)


def test_predict_xshards(saved_model):
    p, x, ref = saved_model
    est = Estimator.from_model(p)
    shards = LocalXShards.partition({"x": x}, num_shards=4)
    out = est.predict(shards, batch_size=16)
    got = np.concatenate([s["prediction"] for s in out.collect()])
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_fit_refuses(saved_model):
    p, _, _ = saved_model
    with pytest.raises(NotImplementedError, match="cannot fit"):
        Estimator.from_model(p).fit(None, epochs=1)


def test_quantized_path(saved_model):
    p, x, ref = saved_model
    est = Estimator.from_model(p, quantize=True)
    got = est.predict(x)
    rel = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-9)
    assert rel < 0.03


def test_openvino_shim_names_migrations(saved_model):
    with pytest.raises(NotImplementedError, match="from_tf"):
        Estimator.from_openvino(model_path="x.xml")


def test_bare_array_shards(saved_model):
    p, x, ref = saved_model
    est = Estimator.from_model(p)
    out = est.predict(LocalXShards.partition(x, num_shards=4))
    got = np.concatenate([s["prediction"] for s in out.collect()])
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_multi_output_model(tmp_path):
    from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
    a = Input(shape=(6,))
    model = Model(input=a, output=[Dense(2)(a), Dense(4)(a)])
    model.build()
    p = str(tmp_path / "multi.zoo")
    model.save(p)
    x = np.random.RandomState(1).randn(10, 6).astype(np.float32)
    out = Estimator.from_model(p).predict(x, batch_size=5)
    assert isinstance(out, list) and len(out) == 2
    assert out[0].shape == (10, 2) and out[1].shape == (10, 4)
