"""Conv / pooling / recurrent / advanced layer specs — per-layer
correctness against numpy references, the reference repo's per-layer spec
pattern (SURVEY §4.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras.layers import (
    GRU, LSTM, AveragePooling2D, Bidirectional, Conv1D, Conv2D,
    GlobalAveragePooling1D, GlobalMaxPooling2D, Highway, LeakyReLU,
    MaxPooling1D, MaxPooling2D, MaxoutDense, PReLU, SReLU, SimpleRNN,
    TimeDistributed, UpSampling2D, ZeroPadding2D, Dense,
)


def _bc(layer, x, **kw):
    params = layer.build(jax.random.PRNGKey(0), (None,) + x.shape[1:])
    return params, np.asarray(layer.call(params, jnp.asarray(x), **kw))


def test_conv2d_th_and_tf_agree():
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    th = Conv2D(4, 3, 3, dim_ordering="th")
    p, y_th = _bc(th, x)
    tf_layer = Conv2D(4, 3, 3, dim_ordering="tf")
    y_tf = tf_layer.call(p, jnp.transpose(jnp.asarray(x), (0, 2, 3, 1)))
    np.testing.assert_allclose(y_th, np.transpose(np.asarray(y_tf),
                                                  (0, 3, 1, 2)), atol=1e-5)
    assert y_th.shape == (2, 4, 6, 6)
    assert th.compute_output_shape((None, 3, 8, 8)) == (None, 4, 6, 6)


def test_conv2d_same_stride():
    x = np.random.RandomState(0).randn(1, 1, 7, 7).astype(np.float32)
    layer = Conv2D(2, 3, 3, border_mode="same", subsample=(2, 2))
    _, y = _bc(layer, x)
    assert y.shape == (1, 2, 4, 4)
    assert layer.compute_output_shape((None, 1, 7, 7)) == (None, 2, 4, 4)


def test_conv1d_matches_manual():
    x = np.random.RandomState(1).randn(2, 5, 3).astype(np.float32)
    layer = Conv1D(1, 2)
    p, y = _bc(layer, x)
    W = np.asarray(p["W"])  # (2, 3, 1)
    manual = sum(x[:, t:t + 4 - 3 + 1 + 3, :] for t in range(0))  # noqa
    # manual conv at position 0
    v0 = (x[0, 0] * W[0, :, 0]).sum() + (x[0, 1] * W[1, :, 0]).sum()
    np.testing.assert_allclose(y[0, 0, 0], v0, rtol=1e-5)
    assert y.shape == (2, 4, 1)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    _, y = _bc(MaxPooling2D((2, 2)), x)
    np.testing.assert_array_equal(y[0, 0], [[5, 7], [13, 15]])
    _, y = _bc(AveragePooling2D((2, 2)), x)
    np.testing.assert_array_equal(y[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    _, y = _bc(GlobalMaxPooling2D(), x)
    np.testing.assert_array_equal(y, [[15.0]])
    x1 = np.arange(12, dtype=np.float32).reshape(1, 6, 2)
    _, y = _bc(MaxPooling1D(2), x1)
    assert y.shape == (1, 3, 2)
    _, y = _bc(GlobalAveragePooling1D(), x1)
    np.testing.assert_allclose(y, [[5.0, 6.0]])


def test_padding_upsampling():
    x = np.ones((1, 2, 3, 3), np.float32)
    _, y = _bc(ZeroPadding2D((1, 2)), x)
    assert y.shape == (1, 2, 5, 7)
    assert y[0, 0, 0, 0] == 0 and y[0, 0, 1, 2] == 1
    _, y = _bc(UpSampling2D((2, 2)), x)
    assert y.shape == (1, 2, 6, 6)


def test_lstm_shapes_and_determinism():
    x = np.random.RandomState(0).randn(3, 7, 5).astype(np.float32)
    layer = LSTM(4)
    p, y = _bc(layer, x)
    assert y.shape == (3, 4)
    seq = LSTM(4, return_sequences=True)
    p2, y2 = _bc(seq, x)
    assert y2.shape == (3, 7, 4)
    # last step of the sequence equals the non-sequence output when params
    # are identical
    y3 = np.asarray(seq.call(p, jnp.asarray(x)))
    np.testing.assert_allclose(y3[:, -1], y, rtol=1e-5)


def test_simplernn_manual():
    x = np.random.RandomState(0).randn(2, 3, 4).astype(np.float32)
    layer = SimpleRNN(3, activation="tanh")
    p, y = _bc(layer, x)
    W, U, b = map(np.asarray, (p["W"], p["U"], p["b"]))
    h = np.zeros((2, 3), np.float32)
    for t in range(3):
        h = np.tanh(x[:, t] @ W + h @ U + b)
    np.testing.assert_allclose(y, h, rtol=1e-4)


def test_gru_and_backwards():
    x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
    _, y = _bc(GRU(4), x)
    assert y.shape == (2, 4)
    back = GRU(4, go_backwards=True)
    p, yb = _bc(back, x)
    fwd = GRU(4)
    y_rev = fwd.call(p, jnp.asarray(x[:, ::-1]))
    np.testing.assert_allclose(yb, np.asarray(y_rev), rtol=1e-5)


def test_bidirectional_and_timedistributed():
    x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
    bi = Bidirectional(LSTM(4, return_sequences=True))
    p, y = _bc(bi, x)
    assert y.shape == (2, 5, 8)
    td = TimeDistributed(Dense(6))
    p, y = _bc(td, x)
    assert y.shape == (2, 5, 6)
    assert td.compute_output_shape((None, 5, 3)) == (None, 5, 6)


def test_advanced_activations():
    x = np.array([[-2.0, -0.5, 0.5, 2.0]], np.float32)
    _, y = _bc(LeakyReLU(0.1), x)
    np.testing.assert_allclose(y, [[-0.2, -0.05, 0.5, 2.0]], rtol=1e-5)
    _, y = _bc(PReLU(), x)
    np.testing.assert_allclose(y, [[-0.5, -0.125, 0.5, 2.0]], rtol=1e-5)
    layer = SReLU()
    p, y = _bc(layer, x)
    assert y.shape == x.shape
    _, y = _bc(MaxoutDense(3, nb_feature=2), x)
    assert y.shape == (1, 3)
    _, y = _bc(Highway(activation="relu"), x)
    assert y.shape == x.shape


def test_layers_in_sequential_training(orca_ctx):
    """Conv + pool + LSTM stack end-to-end through fit."""
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Flatten
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    rs = np.random.RandomState(0)
    x = rs.randn(64, 1, 8, 8).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.float32).reshape(-1, 1)
    m = Sequential()
    m.add(Conv2D(4, 3, 3, activation="relu", input_shape=(1, 8, 8)))
    m.add(MaxPooling2D((2, 2)))
    m.add(Flatten())
    m.add(Dense(1, activation="sigmoid"))
    m.compile(optimizer=Adam(lr=0.01), loss="binary_crossentropy",
              metrics=["accuracy"])
    hist = m.fit(x, y, batch_size=16, nb_epoch=5, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
