"""Paged flash-decode Pallas kernel (zoo_tpu/ops/pallas/paged_decode.py):
numeric identity against the dense-gather reference across block-table
routing, GQA grouping, split-KV merge edges, and the tp=2 head-sharded
layout the serving path runs it under (docs/multichip.md).

All kernel runs here go through the Pallas interpreter (the exact same
kernel TPU hardware compiles); the serving-level token-identity checks
live in tests/test_llm_serving.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zoo_tpu.ops.pallas.paged_decode import (
    paged_flash_decode,
    resolve_num_splits,
)


def _dense_ref(q, kc, vc, bt, pos):
    """The PR 7 gather-attention math the kernel must reproduce."""
    S, H, D = q.shape
    n_blocks, bs, n_kv, _ = kc.shape
    W = bt.shape[1]
    ctx = W * bs
    group = H // n_kv
    keys = kc[bt].reshape(S, ctx, n_kv, D)
    vals = vc[bt].reshape(S, ctx, n_kv, D)
    qg = q.reshape(S, n_kv, group, D)
    s = jnp.einsum("skgd,stkd->skgt", qg, keys).astype(
        jnp.float32) / jnp.sqrt(float(D))
    live = jnp.arange(ctx)[None, :] <= pos[:, None]
    s = jnp.where(live[:, None, None, :], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(vals.dtype)
    return jnp.einsum("skgt,stkd->skgd", p, vals).reshape(S, H, D)


def _case(S=3, H=4, n_kv=2, D=16, n_blocks=12, bs=4, W=4, seed=0,
          positions=None):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(S, H, D).astype(np.float32))
    kc = jnp.asarray(rs.randn(n_blocks, bs, n_kv, D).astype(np.float32))
    vc = jnp.asarray(rs.randn(n_blocks, bs, n_kv, D).astype(np.float32))
    bt = jnp.asarray(rs.randint(1, n_blocks, (S, W)).astype(np.int32))
    if positions is None:
        positions = rs.randint(0, W * bs, (S,))
    pos = jnp.asarray(np.asarray(positions, np.int32))
    return q, kc, vc, bt, pos


@pytest.mark.parametrize("splits", [1, 2, 4])
def test_kernel_matches_dense_reference(splits):
    q, kc, vc, bt, pos = _case()
    ref = _dense_ref(q, kc, vc, bt, pos)
    out = paged_flash_decode(q, kc, vc, bt, pos, num_splits=splits,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_position_edges():
    """position 0 (one live token), a block boundary, and a full table
    — the masking/skip edges; plus the mid-split boundary where the
    log-sum-exp merge sees one live and one dead split."""
    q, kc, vc, bt, pos = _case(S=4, W=4, bs=4,
                               positions=[0, 3, 8, 15])
    ref = _dense_ref(q, kc, vc, bt, pos)
    out = paged_flash_decode(q, kc, vc, bt, pos, num_splits=2,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_gqa_and_mha_layouts():
    for n_kv in (1, 2, 4):   # MQA, grouped, MHA
        q, kc, vc, bt, pos = _case(H=4, n_kv=n_kv, seed=3 + n_kv)
        ref = _dense_ref(q, kc, vc, bt, pos)
        out = paged_flash_decode(q, kc, vc, bt, pos, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"n_kv={n_kv}")


def test_kernel_under_jit_with_donated_style_caches():
    q, kc, vc, bt, pos = _case(seed=9)
    ref = _dense_ref(q, kc, vc, bt, pos)
    f = jax.jit(lambda *a: paged_flash_decode(*a, interpret=True))
    np.testing.assert_allclose(np.asarray(f(q, kc, vc, bt, pos)),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_kernel_int8_dequant_matches_dense_widen():
    """The quantized-cache contract: the kernel fed int8 K/V plus
    per-(block, row, kv-head) absmax scales must equal the dense path's
    gather-then-widen on the SAME bytes — across splits and the
    position edges."""
    from zoo_tpu.util.quantize import absmax_scale, narrow_int8, \
        widen_int8

    rs = np.random.RandomState(21)
    S, H, n_kv, D, nb, bs, W = 3, 4, 2, 16, 12, 4, 4
    q = jnp.asarray(rs.randn(S, H, D).astype(np.float32))
    kc = rs.randn(nb, bs, n_kv, D).astype(np.float32)
    vc = rs.randn(nb, bs, n_kv, D).astype(np.float32)
    ks = np.asarray(absmax_scale(kc, axis=-1))       # (nb, bs, n_kv)
    vs = np.asarray(absmax_scale(vc, axis=-1))
    kq = narrow_int8(kc, ks[..., None])
    vq = narrow_int8(vc, vs[..., None])
    bt = jnp.asarray(rs.randint(1, nb, (S, W)).astype(np.int32))
    for splits, positions in ((1, None), (2, [0, 7, 15]),
                              (4, [3, 8, 12])):
        pos = jnp.asarray(np.asarray(
            positions if positions is not None
            else rs.randint(0, W * bs, (S,)), np.int32))
        ref = _dense_ref(q, jnp.asarray(widen_int8(kq, ks[..., None])),
                         jnp.asarray(widen_int8(vq, vs[..., None])),
                         bt, pos)
        out = paged_flash_decode(
            q, jnp.asarray(kq), jnp.asarray(vq), bt, pos,
            k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs),
            num_splits=splits, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"splits={splits}")


def test_kernel_scales_must_travel_together():
    q, kc, vc, bt, pos = _case()
    with pytest.raises(ValueError):
        paged_flash_decode(q, kc, vc, bt, pos,
                           k_scale=jnp.zeros((12, 4, 2)),
                           interpret=True)


def test_resolve_num_splits_divides_table():
    assert resolve_num_splits(16, 4) == 4
    assert resolve_num_splits(6, 4) == 3    # largest divisor <= 4
    assert resolve_num_splits(7, 4) == 1    # prime width
    assert resolve_num_splits(4, 99) == 4   # clamped to the width
    assert resolve_num_splits(5, 1) == 1


@pytest.mark.multichip
def test_kernel_tp2_head_sharded_matches_unsharded():
    """The tp=2 serving layout (docs/multichip.md): KV cache sharded on
    the kv-head axis, query heads sharded to match, the kernel run
    per-device under shard_map — must equal the unsharded kernel AND
    the dense reference."""
    from jax.sharding import Mesh, PartitionSpec as P

    from zoo_tpu.parallel.compat import shard_map

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    q, kc, vc, bt, pos = _case(S=3, H=4, n_kv=2, seed=11)
    ref = _dense_ref(q, kc, vc, bt, pos)
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    sharded = jax.jit(shard_map(
        lambda q_, k_, v_, b_, p_: paged_flash_decode(
            q_, k_, v_, b_, p_, interpret=True),
        mesh=mesh,
        in_specs=(P(None, "model", None), P(None, None, "model", None),
                  P(None, None, "model", None), P(None, None), P(None)),
        out_specs=P(None, "model", None)))
    out = sharded(q, kc, vc, bt, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    plain = paged_flash_decode(q, kc, vc, bt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(plain),
                               atol=1e-6, rtol=1e-6)


@pytest.mark.multichip
def test_paged_model_tp2_flash_token_identical():
    """End to end: a tp=2 PagedLlamaModel decoding through the
    shard_map'd flash kernel emits the same tokens as the single-device
    dense-gather model on the same weights."""
    from zoo_tpu.models.llm.llama import tiny_llama_config
    from zoo_tpu.parallel import build_mesh
    from zoo_tpu.serving.llm.engine import LLMEngine
    from zoo_tpu.serving.llm.model import PagedLlamaModel

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    cfg = tiny_llama_config(vocab=64)
    kw = dict(seed=0, num_slots=2, block_size=4, num_blocks=24,
              max_blocks_per_seq=6, prefill_buckets=(8, 16))
    base = PagedLlamaModel(cfg, **kw)
    mesh = build_mesh(jax.devices()[:2], axis_sizes={"model": 2})
    tp = PagedLlamaModel(cfg, mesh=mesh, decode_impl="flash", **kw)
    assert tp.tp == 2 and tp.decode_attention_impl == "flash"

    import time as _t

    def streams(model):
        eng = LLMEngine(model).start()
        try:
            rs = np.random.RandomState(5)
            hs = [eng.submit(rs.randint(0, cfg.vocab, (n,)), 6)
                  for n in (3, 9)]
            end = _t.monotonic() + 300
            while not all(h.done for h in hs):
                assert _t.monotonic() < end, \
                    [(h.outcome, h.error) for h in hs]
                _t.sleep(0.005)
            assert all(h.outcome == "ok" for h in hs), \
                [(h.outcome, h.error) for h in hs]
            return [h.tokens for h in hs]
        finally:
            eng.stop()

    assert streams(tp) == streams(base)
    counts = tp.compile_counts()
    if counts["decode"] >= 0:
        assert counts["decode"] == 1, counts
