"""LR schedules + gradient clipping (reference:
``pyzoo/zoo/orca/learn/optimizers/schedule.py``; clipping:
Scala ``pipeline/estimator/Estimator.scala`` constant/L2-norm clipping)."""

import numpy as np
import pytest

from zoo_tpu.orca.learn.optimizers.schedule import (
    Default, Exponential, MultiStep, Plateau, Poly, SequentialSchedule,
    Step, Warmup,
)


def _lr(sched, base, step):
    return float(sched.get_scheduler(base)(step))


def test_poly():
    assert _lr(Poly(2.0, 100), 1.0, 0) == pytest.approx(1.0)
    assert _lr(Poly(2.0, 100), 1.0, 50) == pytest.approx(0.25)
    assert _lr(Poly(2.0, 100), 1.0, 100) == pytest.approx(0.0)
    assert _lr(Poly(2.0, 100), 1.0, 200) == pytest.approx(0.0)  # clamped


def test_exponential():
    assert _lr(Exponential(100, 0.1), 1.0, 0) == pytest.approx(1.0)
    assert _lr(Exponential(100, 0.1), 1.0, 100) == pytest.approx(0.1)
    # staircase floors the exponent
    assert _lr(Exponential(100, 0.1, stair_case=True), 1.0, 150) == \
        pytest.approx(0.1)
    assert _lr(Exponential(100, 0.1, stair_case=False), 1.0, 50) == \
        pytest.approx(10 ** -0.5)


def test_step_multistep():
    s = Step(30, 0.5)
    assert _lr(s, 1.0, 29) == pytest.approx(1.0)
    assert _lr(s, 1.0, 30) == pytest.approx(0.5)
    assert _lr(s, 1.0, 60) == pytest.approx(0.25)
    m = MultiStep([2, 5], 0.3)
    assert _lr(m, 1.0, 1) == pytest.approx(1.0)
    assert _lr(m, 1.0, 2) == pytest.approx(0.3)
    assert _lr(m, 1.0, 5) == pytest.approx(0.09)


def test_warmup_sequential_default():
    assert _lr(Warmup(0.05), 0.1, 4) == pytest.approx(0.3)
    assert _lr(Default(), 0.7, 123) == pytest.approx(0.7)
    seq = SequentialSchedule(1).add(Warmup(0.1), 5).add(Poly(1.0, 10), 10)
    assert _lr(seq, 0.0, 3) == pytest.approx(0.3)
    # after the warmup segment, Poly runs on a re-based step counter
    assert _lr(seq, 0.0, 5) == pytest.approx(0.0)


def test_plateau_controller():
    pl = Plateau("Loss", factor=0.5, patience=2, min_lr=0.01).bind(0.4)
    assert pl.update(1.0) == pytest.approx(0.4)   # first obs = best
    assert pl.update(0.9) == pytest.approx(0.4)   # improved
    assert pl.update(0.95) == pytest.approx(0.4)  # wait 1
    assert pl.update(0.95) == pytest.approx(0.2)  # wait 2 -> reduce
    assert pl.update(0.95) == pytest.approx(0.2)
    assert pl.update(0.95) == pytest.approx(0.1)
    for _ in range(10):
        pl.update(0.95)
    assert pl.current_lr >= 0.01  # min_lr floor


def test_plateau_in_fit_reduces_lr():
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import SGD

    pl = Plateau("Loss", factor=0.1, patience=1)
    m = Sequential()
    m.add(Dense(1, input_shape=(4,)))
    m.compile(optimizer=SGD(lr=0.05, learningrate_schedule=pl), loss="mse")
    # all-zero inputs and targets -> loss is exactly 0 every epoch, so the
    # monitored metric never improves and the plateau fires after patience
    x = np.zeros((64, 4), np.float32)
    y = np.zeros((64, 1), np.float32)
    m.fit(x, y, batch_size=32, nb_epoch=6, verbose=0)
    assert pl.current_lr < 0.05


def test_scheduled_sgd_trains():
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import SGD

    m = Sequential()
    m.add(Dense(1, input_shape=(3,)))
    m.compile(optimizer=SGD(lr=0.1, learningrate_schedule=Step(20, 0.5)),
              loss="mse")
    rs = np.random.RandomState(1)
    x = rs.randn(128, 3).astype(np.float32)
    y = (x @ np.array([[1.0], [2.0], [-1.0]], np.float32))
    h = m.fit(x, y, batch_size=32, nb_epoch=5, verbose=0)
    assert h["loss"][-1] < h["loss"][0]


@pytest.mark.parametrize("kind", ["const", "l2"])
def test_gradient_clipping(kind):
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.orca.learn.keras.estimator import Estimator

    m = Sequential()
    m.add(Dense(1, input_shape=(3,)))
    m.compile(optimizer="sgd", loss="mse")
    est = Estimator.from_keras(m)
    if kind == "const":
        est.set_constant_gradient_clipping(-0.01, 0.01)
    else:
        est.set_l2_norm_gradient_clipping(0.01)
    rs = np.random.RandomState(2)
    x = rs.randn(64, 3).astype(np.float32)
    y = 100.0 * x[:, :1]  # huge targets -> huge unclipped grads
    m.build(input_shapes=[(None, 3)])
    p0 = {k: {kk: np.asarray(vv) for kk, vv in v.items()}
          for k, v in m.params.items()}
    est.fit({"x": x, "y": y}, epochs=1, batch_size=64)
    # with lr=0.01 and clipped grads, one step moves weights by <= lr*clip*n
    for k, v in m.params.items():
        for kk, vv in v.items():
            delta = np.abs(np.asarray(vv) - p0[k][kk]).max()
            assert delta < 0.01, f"{k}/{kk} moved {delta}: clip not applied"
    est.clear_gradient_clipping()
    est.fit({"x": x, "y": y}, epochs=1, batch_size=64)
    moved = max(np.abs(np.asarray(vv) - p0[k][kk]).max()
                for k, v in m.params.items() for kk, vv in v.items())
    assert moved > 0.01  # unclipped step is large


def test_fused_adamw_matches_optax_through_fit():
    """AdamWeightDecay(fused=True): the Pallas direct-apply path through
    the REAL fit loop (init_fused state, donate_argnums, opt-state reuse
    across fit calls) tracks the optax path step for step."""
    import numpy as np

    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import AdamWeightDecay

    rs = np.random.RandomState(0)
    x = rs.randn(128, 6).astype(np.float32)
    y = (x @ rs.randn(6, 1)).astype(np.float32)

    losses = {}
    for fused in (False, True):
        m = Sequential()
        m.add(Dense(8, activation="relu", input_shape=(6,)))
        m.add(Dense(1))
        m.compile(optimizer=AdamWeightDecay(lr=2e-3, fused=fused),
                  loss="mse")
        h1 = m.fit(x, y, batch_size=32, nb_epoch=2, verbose=0,
                   shuffle=False)
        # second fit reuses the optimizer state (step counter continuity)
        h2 = m.fit(x, y, batch_size=32, nb_epoch=2, verbose=0,
                   shuffle=False)
        losses[fused] = h1["loss"] + h2["loss"]
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=2e-3, atol=2e-4)
    assert losses[True][-1] < losses[True][0]


def test_fused_adamw_rejects_schedules():
    import pytest

    from zoo_tpu.orca.learn.optimizers.schedule import Poly
    from zoo_tpu.pipeline.api.keras.optimizers import AdamWeightDecay

    with pytest.raises(ValueError, match="constant lr"):
        AdamWeightDecay(lr=1e-3, fused=True,
                        learningrate_schedule=Poly(0.5, 100))
