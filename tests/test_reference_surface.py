"""The reference's user-facing import surface resolves here.

Statements below are the import lines that appear in the reference's
examples, apps, and docs (``pyzoo/zoo/examples``, ``apps/``, ``docs/``)
— the de-facto public API a migrating user's scripts contain. Every one
must import (resolving to the rebuild's implementation or a
migration-pointing callable — never a bare ModuleNotFoundError).
"""

import numpy as np
import pytest

_REFERENCE_IMPORTS = [
    # orca core
    "from zoo.orca import init_orca_context, stop_orca_context",
    "from zoo.orca import OrcaContext",
    "from zoo.orca.data import XShards, SharedValue",
    "import zoo.orca.data.pandas",
    "from zoo.orca.data.image.parquet_dataset import read_parquet, write_parquet",
    # orca estimators (all fabrics)
    "from zoo.orca.learn.tf.estimator import Estimator",
    "from zoo.orca.learn.tf2 import Estimator",
    "from zoo.orca.learn.pytorch import Estimator",
    "from zoo.orca.learn.bigdl import Estimator",
    "from zoo.orca.learn.openvino import Estimator",
    "from zoo.orca.learn.metrics import Accuracy",
    "from zoo.orca.learn.metrics import MSE",
    "from zoo.orca.learn.trigger import EveryEpoch",
    # orca automl
    "from zoo.orca.automl import hp",
    "from zoo.orca.automl.auto_estimator import AutoEstimator",
    "from zoo.orca.automl.xgboost import AutoXGBRegressor",
    "from zoo.orca.automl.xgboost import AutoXGBClassifier",
    "from zoo.orca.automl.pytorch_utils import LR_NAME",
    # legacy automl
    "from zoo.automl.common.metrics import Evaluator",
    "from zoo.automl.recipe.base import Recipe",
    # chronos (modern + legacy zouwu surfaces)
    "from zoo.chronos.data import TSDataset",
    "from zoo.chronos.autots.forecast import AutoTSTrainer, TSPipeline",
    "from zoo.chronos.config.recipe import LSTMGridRandomRecipe",
    "from zoo.chronos.model.forecast.lstm_forecaster import LSTMForecaster",
    "from zoo.chronos.model.forecast.tcn_forecaster import TCNForecaster",
    "from zoo.chronos.model.forecast.mtnet_forecaster import MTNetForecaster",
    "from zoo.chronos.model.forecast.tcmf_forecaster import TCMFForecaster",
    "from zoo.chronos.model.anomaly import DBScanDetector",
    "from zoo.chronos.preprocessing.utils import train_val_test_split",
    "from zoo.chronos.regression.time_sequence_predictor import "
    "TimeSequencePredictor",
    "from zoo.chronos.pipeline.time_sequence import load_ts_pipeline",
    # keras facade
    "from zoo.pipeline.api.keras.models import Sequential, Model",
    "from zoo.pipeline.api.keras.layers import Dense, Input, Flatten",
    "from zoo.pipeline.api.keras.layers import Mul, SparseDense, "
    "SparseEmbedding",
    "from zoo.pipeline.api.keras.objectives import "
    "SparseCategoricalCrossEntropy",
    "from zoo.pipeline.api.keras.metrics import Top1Accuracy",
    "from zoo.pipeline.api.keras.optimizers import Adam",
    # torch / tf compat
    "from zoo.pipeline.api.torch import TorchModel, TorchLoss, TorchOptim",
    "from zoo.tfpark import TFDataset, TFOptimizer, TFPredictor",
    "from zoo.tfpark import KerasModel, TFEstimator, ZooOptimizer, TFNet",
    "from zoo.tfpark.estimator import TFEstimator",
    "from zoo.tfpark.gan.gan_estimator import GANEstimator",
    "from zoo.tfpark.text.estimator import BERTClassifier, bert_input_fn",
    "from zoo.tfpark.text.keras import NER",
    "from zoo.util.tf import export_tf",
    "from zoo.util.utils import detect_conda_env_name",
    # nnframes / feature
    "from zoo.pipeline.nnframes import NNEstimator, NNClassifier, "
    "NNImageReader",
    "from zoo.feature.common import ChainedPreprocessing, FeatureSet",
    "from zoo.feature.image import ImageSet",
    "from zoo.feature.image3d.transformation import Rotate3D, Crop3D",
    "from zoo.feature.text import TextSet, DistributedTextSet",
    "from zoo.models.textmatching import KNRM",
    "from zoo.models.anomalydetection import AnomalyDetector",
    # serving / inference / misc
    "from zoo.pipeline.inference import InferenceModel",
    "from zoo.serving.client import InputQueue, OutputQueue",
    "from zoo.serving.client import http_response_to_ndarray",
    "from zoo.common import Sample, convert_to_safe_path",
    "from zoo.common.nncontext import init_nncontext",
    "from zoo.ray import RayContext",
    "from zoo import init_nncontext",
    "from zoo.orca.learn.mxnet import Estimator, create_config",
]


@pytest.mark.parametrize("stmt", _REFERENCE_IMPORTS,
                         ids=[s[:60] for s in _REFERENCE_IMPORTS])
def test_reference_import_resolves(stmt):
    exec(stmt, {})


def test_legacy_autots_trainer_end_to_end(orca_ctx):
    """The zouwu-era pandas API searches and forecasts end-to-end."""
    import pandas as pd

    from zoo.chronos.autots.forecast import AutoTSTrainer
    from zoo.chronos.config.recipe import SmokeRecipe
    from zoo.chronos.preprocessing.utils import train_val_test_split

    t = np.arange(300)
    df = pd.DataFrame({
        "datetime": pd.date_range("2020-01-01", periods=300, freq="h"),
        "value": np.sin(t / 8).astype(np.float32),
    })
    train_df, _, test_df = train_val_test_split(
        df, val_ratio=0, test_ratio=0.2, look_back=8)
    trainer = AutoTSTrainer(horizon=1, dt_col="datetime",
                            target_col="value")
    ppl = trainer.fit(train_df, recipe=SmokeRecipe())
    pred = ppl.predict(test_df)
    assert np.isfinite(np.asarray(pred)).all()
    res = ppl.evaluate(test_df, metrics=["mse"])
    assert np.isfinite(res["mse"])


def test_evaluator_and_preprocessing_utils():
    from zoo.automl.common.metrics import Evaluator

    # default multioutput='raw_values' matches the reference's
    # sklearn-backed return shape: one entry per output column
    np.testing.assert_allclose(
        Evaluator.evaluate("mse", [1.0, 2.0], [1.0, 2.0]), [0.0])
    assert Evaluator.evaluate(
        "mse", [1.0, 2.0], [1.0, 2.0], multioutput="uniform_average") == 0.0
    raw = Evaluator.evaluate("mae", np.ones((4, 2)), np.zeros((4, 2)),
                             multioutput="raw_values")
    np.testing.assert_allclose(raw, [1.0, 1.0])


def test_torch_model_compat_traces_and_predicts(orca_ctx):
    import torch

    from zoo.pipeline.api.torch import TorchModel, TorchOptim

    net = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                              torch.nn.Linear(8, 2))
    zmodel = TorchModel.from_pytorch(net, input_shape=(1, 4))
    x = np.random.RandomState(0).randn(6, 4).astype(np.float32)
    preds = np.asarray(zmodel.predict(x))
    assert preds.shape == (6, 2)
    opt = TorchOptim.from_pytorch(
        torch.optim.SGD(net.parameters(), lr=0.05, momentum=0.9))
    assert type(opt).__name__ == "SGD"


def test_estimator_from_bigdl_and_from_graph(orca_ctx):
    """The aliased bigdl/tf estimator factories behave: from_bigdl
    compiles+wraps (BigDL models here ARE keras-facade models);
    from_graph validates its inputs, never AttributeError."""
    from zoo.orca.learn.bigdl import Estimator as BigdlEstimator
    from zoo.orca.learn.tf.estimator import Estimator as TFEstimator
    from zoo.pipeline.api.keras.layers import Dense
    from zoo.pipeline.api.keras.models import Sequential

    m = Sequential()
    m.add(Dense(1, input_shape=(4,)))
    est = BigdlEstimator.from_bigdl(model=m, loss="mse", optimizer="sgd")
    rs = np.random.RandomState(0)
    data = {"x": rs.randn(64, 4).astype(np.float32),
            "y": rs.randn(64, 1).astype(np.float32)}
    h = est.fit(data, epochs=1, batch_size=32)
    assert np.isfinite(h["loss"][0])

    # from_graph now trains TF1 graphs (tests/test_tf1_training.py);
    # calling it without the graph's input placeholders is a clear error
    with pytest.raises(ValueError, match="inputs"):
        TFEstimator.from_graph(inputs=None, outputs=None)


def test_tfnet_from_export_folder(orca_ctx, tmp_path):
    """zoo.tfpark.TFNet delegates frozen-graph loading to the GraphDef
    interpreter and predicts."""
    import tensorflow as tf

    from zoo.tfpark import TFNet

    m = tf.keras.Sequential([
        tf.keras.Input(shape=(4,)),
        tf.keras.layers.Dense(3, activation="relu"),
    ])
    d = str(tmp_path / "sm")
    tf.saved_model.save(m, d)
    net = TFNet.from_export_folder(d)
    x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    out = np.asarray(net.predict(x)) if hasattr(net, "predict") \
        else np.asarray(net(x))
    assert out.shape == (5, 3)


def test_keras_layer_wrapper_and_zoo_optimizer(orca_ctx):
    import tensorflow as tf

    from zoo.pipeline.api.keras.layers import KerasLayerWrapper
    from zoo.tfpark import ZooOptimizer

    layer = KerasLayerWrapper(tf.keras.layers.Dense(3), input_shape=(4,))
    assert layer is not None
    # ZooOptimizer is the identity on the wrapped optimizer
    opt = object()
    assert ZooOptimizer(opt) is opt


def test_compat_layers_train(orca_ctx):
    """Mul / SparseDense participate in a real fit."""
    from zoo.pipeline.api.keras.layers import Dense, Mul
    from zoo.pipeline.api.keras.models import Sequential
    from zoo.pipeline.api.keras.objectives import MeanSquaredError

    rs = np.random.RandomState(0)
    x = rs.randn(128, 4).astype(np.float32)
    y = (3.0 * x.sum(axis=1, keepdims=True)).astype(np.float32)
    m = Sequential()
    m.add(Mul(input_shape=(4,)))
    m.add(Dense(1))
    m.compile(optimizer="adam", loss=MeanSquaredError())
    h = m.fit(x, y, batch_size=32, nb_epoch=4, verbose=0)
    assert h["loss"][-1] < h["loss"][0]
