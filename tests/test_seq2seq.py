"""The real seq2seq family: RNNEncoder/RNNDecoder/Bridge, teacher
forcing at train, greedy scan decode at predict, reference infer API.

reference: ``pyzoo/zoo/models/seq2seq/seq2seq.py`` /
``zoo/.../models/seq2seq/Seq2seq.scala`` (+ ``Bridge.scala``).
"""

import numpy as np
import pytest

from zoo.pipeline.api.keras.layers import Dense
from zoo.pipeline.api.keras.optimizers import Adam


def _data(rs, n=128, t=5, f=3):
    x = rs.randn(n, t, f).astype(np.float32)
    y = x[:, ::-1].copy()  # reversal
    dec_in = np.concatenate([np.zeros((n, 1, f), np.float32), y[:, :-1]],
                            axis=1)
    return x, y, dec_in


@pytest.mark.parametrize("rnn_type,bridge_type", [
    ("lstm", "dense"), ("gru", "densenonlinear")])
@pytest.mark.heavy
def test_seq2seq_teacher_forcing_trains(orca_ctx, rnn_type, bridge_type):
    from zoo.models.seq2seq import Bridge, RNNDecoder, RNNEncoder, Seq2seq

    rs = np.random.RandomState(0)
    x, y, dec_in = _data(rs)
    enc = RNNEncoder.initialize(rnn_type, 2, 24)
    dec = RNNDecoder.initialize(rnn_type, 2, 24)
    m = Seq2seq(enc, dec, (5, 3), (5, 3),
                Bridge.initialize(bridge_type, 24), Dense(3))
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    h = m.fit([x, dec_in], y, batch_size=32, nb_epoch=8, verbose=0)
    assert h["loss"][-1] < h["loss"][0] * 0.7
    # greedy predict: dec arg supplies start token + target length
    p = m.predict([x[:16], np.zeros((16, 5, 3), np.float32)],
                  batch_size=16)
    assert np.asarray(p).shape == (16, 5, 3)


def test_seq2seq_infer_api(orca_ctx):
    from zoo.models.seq2seq import Bridge, RNNDecoder, RNNEncoder, Seq2seq

    rs = np.random.RandomState(1)
    x, y, dec_in = _data(rs, n=64)
    m = Seq2seq(RNNEncoder.initialize("lstm", 1, 16),
                RNNDecoder.initialize("lstm", 1, 16),
                (5, 3), (5, 3), Bridge.initialize("dense", 16), Dense(3))
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    m.fit([x, dec_in], y, batch_size=32, nb_epoch=2, verbose=0)
    out = m.infer(x[0], start_sign=np.zeros(3), max_seq_len=4)
    # reference contract: [start; generated...]
    assert out.shape == (1, 5, 3)
    np.testing.assert_allclose(out[0, 0], np.zeros(3))


def test_seq2seq_passthrough_bridge_and_custom(orca_ctx):
    from zoo.models.seq2seq import Bridge, RNNDecoder, RNNEncoder, Seq2seq

    rs = np.random.RandomState(2)
    x, y, dec_in = _data(rs, n=64)
    # passthrough (bridge=None) requires matching sizes
    m = Seq2seq(RNNEncoder.initialize("lstm", 1, 16),
                RNNDecoder.initialize("lstm", 1, 16),
                (5, 3), (5, 3), None, Dense(3))
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    h = m.fit([x, dec_in], y, batch_size=32, nb_epoch=3, verbose=0)
    assert np.isfinite(h["loss"][-1])
    # customized bridge from a keras layer (reference
    # Bridge.initialize_from_keras_layer)
    m2 = Seq2seq(RNNEncoder.initialize("lstm", 1, 16),
                 RNNDecoder.initialize("lstm", 1, 16),
                 (5, 3), (5, 3),
                 Bridge.initialize_from_keras_layer(Dense(32)), Dense(3))
    m2.compile(optimizer=Adam(lr=0.01), loss="mse")
    h2 = m2.fit([x, dec_in], y, batch_size=32, nb_epoch=3, verbose=0)
    assert np.isfinite(h2["loss"][-1])


def test_simplified_ctor_still_works(orca_ctx):
    """The pre-round-5 single-input constructor keeps working (now with
    a state bridge + self-feeding decoder instead of context-repeat)."""
    from zoo.models.seq2seq import Seq2seq

    rs = np.random.RandomState(3)
    x = rs.randn(64, 6, 3).astype(np.float32)
    y = np.repeat(x.mean(axis=1, keepdims=True), 4, axis=1)[..., :2]
    m = Seq2seq(input_length=6, input_dim=3, target_length=4,
                output_dim=2, hidden_size=16)
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    h = m.fit(x, y, batch_size=32, nb_epoch=3, verbose=0)
    assert h["loss"][-1] < h["loss"][0]
    assert m.predict(x[:8]).shape == (8, 4, 2)


@pytest.mark.slow
def test_seq2seq_forecaster_beats_context_repeat(orca_ctx):
    """The round-5 'done' bar: the rewired Seq2SeqForecaster (teacher
    forcing + free-running fine-tune, greedy decode) beats the old
    context-repeat architecture on held-out sine data. Fully seeded —
    deterministic on CPU."""
    from zoo.chronos.forecaster import Seq2SeqForecaster
    from zoo.pipeline.api.keras.layers import (
        LSTM,
        RepeatVector,
        TimeDistributed,
    )
    from zoo.pipeline.api.keras.models import Sequential

    rs = np.random.RandomState(0)
    t = np.arange(4000) * 0.1
    sig = (np.sin(t) + 0.5 * np.sin(3.1 * t + 1.0)
           + 0.05 * rs.randn(len(t))).astype(np.float32)
    look, hor = 24, 12
    n = len(sig) - look - hor
    x = np.stack([sig[i:i + look] for i in range(n)])[..., None]
    y = np.stack([sig[i + look:i + look + hor] for i in range(n)])[..., None]
    tr, te = slice(0, 3000), slice(3000, n)

    f = Seq2SeqForecaster(past_seq_len=look, future_seq_len=hor,
                          input_feature_num=1, output_feature_num=1,
                          lstm_hidden_dim=32, lstm_layer_num=1, lr=0.005)
    f.fit((x[tr], y[tr]), epochs=30, batch_size=64)
    s2s_mse = f.evaluate((x[te], y[te]), metrics=["mse"])["mse"]

    b = Sequential()
    b.add(LSTM(32, input_shape=(look, 1)))
    b.add(RepeatVector(hor))
    b.add(LSTM(32, return_sequences=True))
    b.add(TimeDistributed(Dense(1)))
    b.compile(optimizer=Adam(lr=0.005), loss="mse")
    b.fit(x[tr], y[tr], batch_size=64, nb_epoch=30, verbose=0)
    pb = np.asarray(b.predict(x[te], batch_size=256))
    base_mse = float(np.mean((pb.reshape(-1) - y[te].reshape(-1)) ** 2))
    assert s2s_mse < base_mse, (s2s_mse, base_mse)


def test_seq2seq_forecaster_roundtrip(orca_ctx, tmp_path):
    from zoo.chronos.forecaster import Seq2SeqForecaster

    rs = np.random.RandomState(4)
    x = rs.randn(96, 12, 2).astype(np.float32)
    y = rs.randn(96, 4, 1).astype(np.float32)
    f = Seq2SeqForecaster(past_seq_len=12, future_seq_len=4,
                          input_feature_num=2, output_feature_num=1,
                          lstm_hidden_dim=16)
    f.fit((x, y), epochs=2, batch_size=32)
    p1 = f.predict((x[:8], None))
    assert p1.shape == (8, 4, 1)
    ev = f.evaluate((x, y), metrics=["mse", "mae"])
    assert set(ev) == {"mse", "mae"}
