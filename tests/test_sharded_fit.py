"""The GSPMD fit seam (docs/multichip.md): explicit-sharding train
steps, guard semantics under sharding, and the fused-optimizer gate.

Every orca estimator funnels through the one topology.py step seam, so
these tests drive plain keras models under meshes built the way
``init_orca_context`` builds them."""

import json
import os

import numpy as np
import pytest

import jax

from zoo_tpu.orca.learn.guard import GuardConfig, TrainingGuard
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.api.keras.optimizers import Adam, AdamWeightDecay
from zoo_tpu.util.resilience import inject


def _data(n=256, feat=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, feat).astype(np.float32)
    w = rs.randn(feat, 1).astype(np.float32)
    return {"x": x, "y": (x @ w).astype(np.float32)}


def _model():
    m = Sequential()
    m.add(Dense(16, input_shape=(8,), activation="relu"))
    m.add(Dense(1))
    m.compile(optimizer=Adam(lr=0.01), loss="mse")
    return m


@pytest.fixture
def mesh_ctx():
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    ctx = init_orca_context(cluster_mode="local",
                            mesh_axes={"fsdp": 8})
    yield ctx
    stop_orca_context()


def _poison(site=None, arrays=None, idx=None, **_):
    for a in arrays:
        a[:] = np.nan


def test_sharded_fit_state_actually_sharded(mesh_ctx):
    """After a fit on the fsdp mesh, params AND optimizer moments live
    sharded (per-device bytes ~1/8) — the explicit out_shardings
    contract, not just the input placement."""
    data = _data()
    m = _model()
    m.fit(data["x"], data["y"], batch_size=32, nb_epoch=1, verbose=0)
    w = m._place(m.params)["000_dense"]["W"]       # (8, 16) global
    assert w.addressable_shards[0].data.shape == (8, 2)
    mu = [l for l in jax.tree_util.tree_leaves(m._opt_state)
          if getattr(l, "shape", None) == (8, 16)]
    assert mu, "no (8,16) moment leaf found"
    for leaf in mu:
        assert leaf.addressable_shards[0].data.shape == (8, 2), \
            leaf.sharding


def test_guard_rollback_under_sharding_bit_exact(tmp_path):
    """The PR 4 escalation ladder survives the mesh unchanged: a NaN
    batch streak on the 8-device fsdp mesh rolls back to the verified
    checkpoint and continues, matching the single-device
    run's loss history and rollback count step for step."""
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    def run(mesh_axes, devices):
        init_orca_context(cluster_mode="local", devices=devices,
                          mesh_axes=mesh_axes)
        try:
            guard = TrainingGuard(config=GuardConfig(
                enabled=True, max_skips=4, preempt_signal="none"))
            est = Estimator.from_keras(
                _model(), model_dir=str(tmp_path / f"g{len(devices)}"),
                guard=guard)
            data = _data()
            h0 = est.fit(data, epochs=1, batch_size=32)
            with inject("fit.batch", action=_poison, exc=None, times=2):
                h = est.fit(data, epochs=3, batch_size=32)
            return h0["loss"] + h["loss"], guard.rollbacks, est
        finally:
            stop_orca_context()

    losses_1, rb_1, _ = run(None, jax.devices()[:1])
    losses_8, rb_8, est8 = run({"fsdp": 8}, jax.devices())
    assert rb_1 >= 1 and rb_8 == rb_1, (rb_1, rb_8)
    # identical escalation trajectory; the loss values match to float
    # tolerance (1 vs 8 devices changes the batch-mean reduction order
    # by design — mesh-vs-mesh IS bit-exact, see test_parallel's
    # fsdp-vs-dp parity)
    np.testing.assert_allclose(losses_8, losses_1, rtol=1e-5)
    assert np.isfinite(losses_8).all()
    leaves = jax.tree_util.tree_leaves(est8.model.params)
    assert all(np.isfinite(np.asarray(a)).all() for a in leaves)
    events = [json.loads(line) for line in open(
        os.path.join(str(tmp_path), "g8", "guard", "quarantine.jsonl"))]
    assert any(e["event"] == "rollback" for e in events)


def test_fused_optim_env_gate(monkeypatch):
    """ZOO_FUSED_OPTIM=1 flips AdamWeightDecay onto the direct-apply
    path for schedule-free configs; scheduled configs silently keep the
    optax path; an explicit argument always wins."""
    monkeypatch.delenv("ZOO_FUSED_OPTIM", raising=False)
    assert AdamWeightDecay().fused is False
    monkeypatch.setenv("ZOO_FUSED_OPTIM", "1")
    assert AdamWeightDecay().fused is True
    assert AdamWeightDecay(fused=False).fused is False
    assert AdamWeightDecay(total_steps=100).fused is False  # scheduled
    monkeypatch.setenv("ZOO_FUSED_OPTIM", "0")
    assert AdamWeightDecay().fused is False


def test_fused_optim_under_mesh_matches_optax(mesh_ctx):
    """The fused direct-apply path inside the SHARDED step (the
    elementwise reference form — a pallas_call has no SPMD partitioning
    rule) trains to ~the optax-path losses, with moments sharded."""
    data = _data()

    def run(fused):
        m = Sequential()
        m.add(Dense(16, input_shape=(8,), activation="relu"))
        m.add(Dense(1))
        m.compile(optimizer=AdamWeightDecay(lr=1e-2, fused=fused),
                  loss="mse")
        m.fit(data["x"], data["y"], batch_size=32, nb_epoch=3,
              verbose=0)
        return m

    mf, mo = run(True), run(False)
    for a, b in zip(jax.tree_util.tree_leaves(mf.params),
                    jax.tree_util.tree_leaves(mo.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    # fused moments carry the fsdp sharding like the optax state does
    w_m = mf._opt_state["m"]["000_dense"]["W"]
    assert w_m.addressable_shards[0].data.shape == (8, 2), w_m.sharding


def test_sharded_vs_single_device_losses_with_guard(mesh_ctx):
    """Guarded clean-data training on the mesh == unguarded on the
    mesh == single-device semantics (the lax.cond good branch and the
    sharding are both layout-only)."""
    data = _data()
    m1 = _model()
    h1 = m1.fit(data["x"], data["y"], batch_size=32, nb_epoch=2,
                verbose=0)
    m2 = _model()
    m2.set_guard(TrainingGuard(config=GuardConfig(
        enabled=True, preempt_signal="none")))
    h2 = m2.fit(data["x"], data["y"], batch_size=32, nb_epoch=2,
                verbose=0)
    assert h1["loss"] == h2["loss"]
