import numpy as np
import pandas as pd
import pytest

from zoo_tpu.chronos.data import TSDataset
from zoo_tpu.chronos.detector import AEDetector, DBScanDetector, ThresholdDetector
from zoo_tpu.chronos.forecaster import (
    LSTMForecaster,
    Seq2SeqForecaster,
    TCNForecaster,
)


def _sine_df(n=400, ids=None):
    t = pd.date_range("2024-01-01", periods=n, freq="h")
    rows = []
    for sid in (ids or ["a"]):
        v = np.sin(np.arange(n) * 2 * np.pi / 24) + \
            0.05 * np.random.RandomState(0).randn(n)
        rows.append(pd.DataFrame({"ts": t, "value": v, "id": sid}))
    return pd.concat(rows, ignore_index=True)


def test_tsdataset_roll_and_shapes():
    df = _sine_df(100)
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.roll(lookback=24, horizon=2)
    x, y = ts.to_numpy()
    assert x.shape == (100 - 24 - 2 + 1, 24, 1)
    assert y.shape == (75, 2, 1)
    # windows must be consistent: y[i] is the 2 steps after x[i]
    np.testing.assert_allclose(y[0][0, 0], df["value"].to_numpy()[24],
                               rtol=1e-6)


def test_tsdataset_multi_id_no_crossing():
    df = _sine_df(50, ids=["a", "b"])
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value",
                               id_col="id")
    ts.roll(lookback=10, horizon=1)
    x, y = ts.to_numpy()
    assert x.shape[0] == 2 * (50 - 10 - 1 + 1)


def test_tsdataset_impute_scale_dtfeatures():
    df = _sine_df(60)
    df.loc[5, "value"] = np.nan
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.impute(mode="linear")
    assert not ts.df["value"].isna().any()
    ts.gen_dt_feature(["HOUR", "WEEKDAY"])
    assert "HOUR" in ts.feature_col

    from sklearn.preprocessing import StandardScaler
    sc = StandardScaler()
    ts.scale(sc)
    assert abs(ts.df["value"].mean()) < 1e-6
    ts.roll(lookback=12, horizon=1)
    _, y = ts.to_numpy()
    back = ts.unscale_numpy(y)
    assert abs(back.mean()) > 0 or True  # inverse runs without error
    assert back.shape == y.shape


def test_tsdataset_split_and_resample():
    df = _sine_df(100)
    train, val, test = TSDataset.from_pandas(
        df, dt_col="ts", target_col="value", with_split=True,
        val_ratio=0.1, test_ratio=0.1)
    assert len(train.df) == 80 and len(val.df) == 10 and len(test.df) == 10
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.resample("2h")
    assert len(ts.df) == 50


@pytest.mark.heavy
def test_lstm_forecaster_learns(orca_ctx):
    df = _sine_df(300)
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.roll(lookback=24, horizon=1)
    f = LSTMForecaster(past_seq_len=24, input_feature_num=1,
                       output_feature_num=1, hidden_dim=16, lr=0.01)
    hist = f.fit(ts, epochs=3, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    res = f.evaluate(ts, metrics=["mse", "smape"])
    assert res["mse"] < 0.3
    preds = f.predict(ts)
    assert preds.shape == (ts.numpy_x.shape[0], 1, 1)


@pytest.mark.slow
def test_tcn_forecaster_multistep(orca_ctx):
    df = _sine_df(300)
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.roll(lookback=24, horizon=4)
    f = TCNForecaster.from_tsdataset(ts, num_channels=[8, 8], lr=0.01)
    hist = f.fit(ts, epochs=4, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    preds = f.predict(ts)
    assert preds.shape[1:] == (4, 1)
    res = f.evaluate(ts, metrics=["rmse"])
    assert res["rmse"] < 0.6


@pytest.mark.heavy
def test_seq2seq_forecaster(orca_ctx):
    df = _sine_df(200)
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.roll(lookback=16, horizon=3)
    f = Seq2SeqForecaster.from_tsdataset(ts, lstm_hidden_dim=16, lr=0.01)
    hist = f.fit(ts, epochs=3, batch_size=32)
    assert np.isfinite(hist["loss"]).all()
    assert f.predict(ts).shape[1:] == (3, 1)


def test_forecaster_save_load(orca_ctx, tmp_path):
    df = _sine_df(150)
    ts = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ts.roll(lookback=12, horizon=1)
    f = LSTMForecaster(12, 1, 1, hidden_dim=8)
    f.fit(ts, epochs=1, batch_size=32)
    p1 = f.predict(ts)
    f.save(str(tmp_path / "fc.pkl"))
    f2 = LSTMForecaster(12, 1, 1, hidden_dim=8)
    f2.load(str(tmp_path / "fc.pkl"))
    np.testing.assert_allclose(p1, f2.predict(ts), rtol=1e-5)


def test_threshold_detector():
    y = np.sin(np.arange(200) / 5.0)
    y_anom = y.copy()
    y_anom[[20, 100]] += 5.0
    d = ThresholdDetector().set_params(ratio=0.02)
    d.fit(y_anom, y)
    idx = d.anomaly_indexes()
    assert 20 in idx and 100 in idx


def test_ae_detector(orca_ctx):
    y = np.sin(np.arange(300) / 5.0)
    y[[50, 51, 200]] += 4.0
    d = AEDetector(roll_len=10, ratio=0.1, epochs=10)
    d.fit(y)
    idx = set(d.anomaly_indexes())
    assert idx & {49, 50, 51, 52}
    assert idx & {198, 199, 200, 201}


def test_dbscan_detector():
    y = np.concatenate([np.random.RandomState(0).randn(100),
                        np.array([15.0, -15.0])])
    d = DBScanDetector(eps=1.0, min_samples=3)
    d.fit(y)
    idx = d.anomaly_indexes()
    assert 100 in idx and 101 in idx


@pytest.mark.slow
def test_mtnet_forecaster(orca_ctx):
    from zoo_tpu.chronos.forecaster import MTNetForecaster

    rs = np.random.RandomState(0)
    t = np.arange(400, dtype=np.float32)
    series = np.sin(t * 0.2) + 0.05 * rs.randn(400)
    fc = MTNetForecaster(target_dim=1, feature_dim=1, long_series_num=3,
                         series_length=6, ar_window_size=4,
                         cnn_hid_size=16, rnn_hid_size=16, lr=0.01)
    L = fc.past_seq_len
    x = np.stack([series[i:i + L] for i in range(300)])[..., None]
    y = series[L:L + 300].reshape(-1, 1, 1)
    hist = fc.fit((x, y), epochs=6, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    preds = fc.predict((x[:16], None))
    assert preds.shape == (16, 1, 1)


def test_arima_forecaster():
    from zoo_tpu.chronos.forecaster import ARIMAForecaster

    rs = np.random.RandomState(0)
    n = 300
    y = np.zeros(n)
    for t in range(2, n):  # AR(2) process
        y[t] = 0.6 * y[t - 1] - 0.3 * y[t - 2] + rs.randn() * 0.5
    fc = ARIMAForecaster(p=2, d=0, q=1)
    res = fc.fit(y[:280])
    assert res["mse"] < 1.0
    pred = fc.predict(horizon=20)
    assert pred.shape == (20,)
    ev = fc.evaluate(y[280:], metrics=("mse", "smape"))
    # AR(2) with 0.5-sigma noise: forecast error near noise variance
    assert ev["mse"] < 2.0


def test_arima_differencing_and_roundtrip(tmp_path):
    from zoo_tpu.chronos.forecaster import ARIMAForecaster

    rs = np.random.RandomState(1)
    trend = np.cumsum(0.5 + 0.1 * rs.randn(200))  # random walk with drift
    fc = ARIMAForecaster(p=1, d=1, q=0)
    fc.fit(trend)
    pred = fc.predict(10)
    # drift ~0.5/step must be carried through the integration
    assert 1.0 < (pred[-1] - trend[-1]) < 10.0
    p = str(tmp_path / "arima.npz")
    fc.save(p)
    fc2 = ARIMAForecaster().load(p)
    np.testing.assert_allclose(fc2.predict(10), pred)


def test_tcmf_forecaster(tmp_path):
    from zoo_tpu.chronos.forecaster import TCMFForecaster

    rs = np.random.RandomState(0)
    t = np.arange(240, dtype=np.float32)
    basis = np.stack([np.sin(t * 0.1), np.cos(t * 0.07), t * 0.01])
    F = rs.randn(20, 3).astype(np.float32)
    Y = F @ basis + 0.01 * rs.randn(20, 240).astype(np.float32)
    fc = TCMFForecaster(rank=6, ar_lag=10, alt_iters=8)
    res = fc.fit({"y": Y[:, :200]})
    assert res["mse"] < 0.01  # low-rank panel reconstructs well
    pred = fc.predict(horizon=40)
    assert pred.shape == (20, 40)
    ev = fc.evaluate({"y": Y[:, 200:]})
    assert ev["mse"] < 0.5
    # incremental + save/load
    fc.fit_incremental({"y": Y[:, 200:220]})
    p = str(tmp_path / "tcmf.npz")
    fc.save(p)
    fc2 = TCMFForecaster.load(p)
    assert fc2.predict(5).shape == (20, 5)


def test_prophet_gated():
    from zoo_tpu.chronos.forecaster import ProphetForecaster

    with pytest.raises(ImportError, match="prophet"):
        ProphetForecaster()


def test_concurrent_search_engine(orca_ctx):
    import threading

    from zoo_tpu.automl.hp import grid_search
    from zoo_tpu.automl.search import LocalSearchEngine, TrialStopper

    import time as _time

    seen_threads = set()

    def trial(cfg):
        seen_threads.add(threading.get_ident())
        _time.sleep(0.05)  # force overlap so the pool fans out
        return {"mse": (cfg["a"] - 3) ** 2}

    eng = LocalSearchEngine(n_parallel=4)
    eng.compile(trial, {"a": grid_search([0, 1, 2, 3, 4, 5])}, n_sampling=1,
                metric="mse")
    trials = eng.run()
    assert len(trials) == 6
    assert eng.get_best_trial().config["a"] == 3
    assert len(seen_threads) > 1  # genuinely concurrent

    # reporter-driven early stop
    stopped_at = {}

    def trial_with_reporter(cfg, reporter):
        for step in range(100):
            metric = 100 - step
            if reporter(step, metric):
                stopped_at[cfg["a"]] = step
                break
        return {"mse": metric}

    eng2 = LocalSearchEngine(stopper=TrialStopper(max_steps=5))
    eng2.compile(trial_with_reporter, {"a": grid_search([1, 2])}, metric="mse")
    eng2.run()
    assert all(v == 5 for v in stopped_at.values())


# -- round-2 depth: rolling/global feature generation -------------------

def _two_id_df(n=60):
    import pandas as pd
    rows = []
    for sid in ("a", "b"):
        base = 1.0 if sid == "a" else 10.0
        t = np.arange(n)
        rows.append(pd.DataFrame({
            "datetime": pd.date_range("2024-01-01", periods=n, freq="h"),
            "id": sid,
            "value": base + np.sin(t / 5.0)}))
    return pd.concat(rows, ignore_index=True)


def test_gen_rolling_feature_minimal():
    from zoo_tpu.chronos.data import TSDataset
    ts = TSDataset.from_pandas(_two_id_df(), dt_col="datetime",
                               target_col="value", id_col="id")
    ts.gen_rolling_feature(window_size=6)
    for stat in ("mean", "std", "min", "max", "median"):
        assert f"value_rolling_{stat}" in ts.feature_col
    df = ts.to_pandas()
    assert not df.isna().any().any()
    # windows never cross id boundaries: id 'b' rows stay near base 10
    b = df[df.id == "b"]
    assert b["value_rolling_mean"].min() > 5.0


def test_gen_rolling_feature_comprehensive_and_roll():
    from zoo_tpu.chronos.data import TSDataset
    ts = TSDataset.from_pandas(_two_id_df(), dt_col="datetime",
                               target_col="value", id_col="id")
    ts.gen_rolling_feature(window_size=6, settings="comprehensive")
    assert "value_rolling_trend_slope" in ts.feature_col
    x, y = ts.roll(lookback=12, horizon=2).to_numpy()
    assert x.shape[-1] == 1 + len(ts.feature_col)
    assert np.isfinite(x).all() and np.isfinite(y).all()


def test_gen_global_feature():
    from zoo_tpu.chronos.data import TSDataset
    ts = TSDataset.from_pandas(_two_id_df(), dt_col="datetime",
                               target_col="value", id_col="id")
    ts.gen_global_feature(settings="comprehensive")
    df = ts.to_pandas()
    # constant per id, different across ids
    a = df[df.id == "a"]["value_global_mean"]
    b = df[df.id == "b"]["value_global_mean"]
    assert a.nunique() == 1 and b.nunique() == 1
    assert abs(a.iloc[0] - b.iloc[0]) > 5.0
    assert "value_global_autocorr1" in ts.feature_col
    with pytest.raises(ValueError, match="minimal"):
        ts.gen_global_feature(settings="weird")


def test_rolling_std_no_cross_id_leak():
    """First-row NaN std must fill from THIS id, not the previous one."""
    import pandas as pd
    from zoo_tpu.chronos.data import TSDataset
    n = 30
    rows = []
    for sid, scale in (("a", 1.0), ("b", 50.0)):
        rs = np.random.RandomState(0 if sid == "a" else 1)
        rows.append(pd.DataFrame({
            "datetime": pd.date_range("2024-01-01", periods=n, freq="h"),
            "id": sid, "value": scale * rs.randn(n)}))
    ts = TSDataset.from_pandas(pd.concat(rows, ignore_index=True),
                               dt_col="datetime", target_col="value",
                               id_col="id")
    ts.gen_rolling_feature(window_size=6)
    df = ts.to_pandas()
    b_first_std = df[df.id == "b"]["value_rolling_std"].iloc[0]
    assert b_first_std > 5.0, b_first_std  # from id b, not id a's ~1.0


def test_trend_slope_exact_on_linear_series():
    import pandas as pd
    from zoo_tpu.chronos.data import TSDataset
    n = 20
    df = pd.DataFrame({
        "datetime": pd.date_range("2024-01-01", periods=n, freq="h"),
        "value": np.arange(n, dtype=np.float64)})
    ts = TSDataset.from_pandas(df, dt_col="datetime", target_col="value")
    ts.gen_rolling_feature(window_size=6, settings="comprehensive")
    slopes = ts.to_pandas()["value_rolling_trend_slope"].to_numpy()
    # slope of a unit-slope line is 1.0 for every window size > 1
    np.testing.assert_allclose(slopes[1:], 1.0, atol=1e-9)


@pytest.mark.heavy
def test_tcmf_tcn_temporal_beats_ar(tmp_path):
    """temporal_model='tcn' (DeepGLO's actual temporal network) must beat
    the linear AR fallback on a panel whose factors follow threshold-AR
    (piecewise-linear limit cycle) dynamics — nonlinear, non-chaotic,
    exactly predictable, and outside any linear AR's class."""
    from zoo_tpu.chronos.forecaster import TCMFForecaster

    rs = np.random.RandomState(0)
    t = 240
    x1 = np.empty(t, np.float32)
    x1[0] = 0.2
    for i in range(1, t):
        x1[i] = 0.95 * x1[i - 1] + (0.4 if x1[i - 1] < 0 else -0.4)
    x2 = np.empty(t, np.float32)
    x2[0] = -0.3
    for i in range(1, t):
        x2[i] = 0.9 * x2[i - 1] + (0.5 if x2[i - 1] < 0.1 else -0.6)
    X = np.stack([x1, x2])
    F = rs.randn(30, 2).astype(np.float32)
    Y = (F @ X + 0.005 * rs.randn(30, t)).astype(np.float32)
    train, test = Y[:, :200], Y[:, 200:208]

    ar = TCMFForecaster(rank=2, ar_lag=8, temporal_model="ar")
    ar.fit({"y": train})
    mse_ar = float(np.mean((ar.predict(horizon=8) - test) ** 2))

    tcn = TCMFForecaster(rank=2, ar_lag=8, temporal_model="tcn",
                         tcn_epochs=200, dropout=0.0, lr=2e-3,
                         num_channels_X=[32, 32], kernel_size=4)
    tcn.fit({"y": train})
    mse_tcn = float(np.mean((tcn.predict(horizon=8) - test) ** 2))
    assert mse_tcn < 0.5 * mse_ar, (mse_tcn, mse_ar)

    # save/load roundtrip preserves the TCN temporal model
    p = str(tmp_path / "tcmf_tcn.npz")
    tcn.save(p)
    again = TCMFForecaster.load(p)
    assert again.temporal_model == "tcn" and again._tcn is not None
    np.testing.assert_allclose(again.predict(horizon=8),
                               tcn.predict(horizon=8), rtol=1e-4)

    import pytest as _pytest
    with _pytest.raises(ValueError, match="temporal_model"):
        TCMFForecaster(temporal_model="lstm")


def test_auto_arima_search(orca_ctx):
    """reference ``chronos/autots/model/auto_arima.py``: hp search over
    ARIMA orders; best model forecasts the held-out tail."""
    from zoo.chronos.autots.model.auto_arima import AutoARIMA
    from zoo_tpu.automl import hp

    rs = np.random.RandomState(0)
    n = 400
    e = rs.randn(n) * 0.2
    y = np.zeros(n)
    for i in range(1, n):
        y[i] = 0.8 * y[i - 1] + e[i] + 0.4 * e[i - 1]  # ARMA(1,1)
    auto = AutoARIMA(p=hp.grid_search([1, 2]), q=hp.grid_search([1, 2]),
                     seasonal=False, metric="mse")
    auto.fit(y[:360], validation_data=y[360:])
    best = auto.get_best_model()
    cfg = auto.get_best_config()
    assert set(cfg) >= {"p", "q"}
    pred = best.predict(horizon=10)
    assert pred.shape == (10,) and np.isfinite(pred).all()


def test_autots_statistical_family(orca_ctx):
    """AutoTS searches ARIMA alongside the deep forecasters
    (VERDICT r4 missing #7): model='arima' trials fit the raw series
    and the returned TSPipeline forecasts/evaluates."""
    import pandas as pd

    from zoo.chronos.autots import AutoTSEstimator
    from zoo.chronos.data import TSDataset
    from zoo_tpu.automl import hp

    rs = np.random.RandomState(1)
    n = 300
    e = rs.randn(n) * 0.2
    y = np.zeros(n)
    for i in range(1, n):
        y[i] = 0.7 * y[i - 1] + e[i]
    df = pd.DataFrame({
        "datetime": pd.date_range("2020-01-01", periods=n, freq="h"),
        "value": y.astype(np.float32)})
    ds = TSDataset.from_pandas(df, dt_col="datetime",
                               target_col="value")
    est = AutoTSEstimator(model="arima",
                          search_space={"p": hp.grid_search([1, 2]),
                                        "q": hp.grid_search([0, 1])},
                          future_seq_len=5)
    ppl = est.fit(ds, n_sampling=1, seed=0)
    # the shipped winner is refit on the FULL series, so predict()
    # forecasts past the end of the data (not from the holdout cut)
    assert ppl.forecaster._train.shape == (n,)
    pred = ppl.predict(ds)
    assert pred.shape == (5,) and np.isfinite(pred).all()
    ev = ppl.evaluate(ds, metrics=["mse"])
    assert np.isfinite(ev["mse"])
    assert set(est.get_best_config()) >= {"p", "q"}


def test_auto_prophet_gated(orca_ctx):
    from zoo.chronos.autots.model.auto_prophet import AutoProphet

    try:
        import prophet  # noqa: F401
        has_prophet = True
    except ImportError:
        has_prophet = False
    if has_prophet:
        pytest.skip("prophet present; gating not applicable")
    with pytest.raises(ImportError, match="prophet"):
        AutoProphet()
