"""Multihost obs: coordinator-side metric aggregation and trace-id
propagation on a real 2-process JAX CPU cluster (the reference's
"local topology, real fabric" trick, SURVEY §4.3). Workers record
different counter/gauge/histogram values; ``aggregate_cluster`` must
return the same merged view on both — counters summed, gauges
max/min'd, histogram buckets added — and ``share_trace_id`` must hand
every process the coordinator's trace id."""

import os
import socket
import subprocess
import sys

import pytest

# 2-process jax.distributed clusters — fresh JAX compile per process
pytestmark = [pytest.mark.slow, pytest.mark.obs]

_WORKER = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid, pcnt = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coordinator_address=coord, num_processes=pcnt,
                           process_id=pid)

from zoo_tpu.obs import (MetricsRegistry, aggregate_cluster,
                         share_trace_id, current_trace_id)

reg = MetricsRegistry()
# proc 0 records 3, proc 1 records 5 -> cluster total must be 8
reg.counter("t_retries_total", "x").inc(3 if pid == 0 else 5)
reg.gauge("t_depth", "x").set(10 * (pid + 1))         # 10 and 20
h = reg.histogram("t_lat_seconds", "x", buckets=(0.1, 1.0))
h.observe(0.05)                                        # both: bucket 0
if pid == 1:
    h.observe(5.0)                                     # only p1: +Inf

merged = aggregate_cluster(registry=reg, timeout_s=60)
assert merged["processes"] == 2, merged
c = {e["name"]: e["value"] for e in merged["counters"]}
assert c["t_retries_total"] == 8, merged["counters"]
g = {e["name"]: e for e in merged["gauges"]}
assert g["t_depth"]["max"] == 20 and g["t_depth"]["min"] == 10, g
hh = merged["histograms"][0]
assert hh["counts"] == [2, 0, 1], hh
assert hh["count"] == 3, hh

tid = share_trace_id(timeout_s=60)
assert tid == current_trace_id()
print(f"proc {pid} OK total={c['t_retries_total']} trace={tid}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.timeout(300)
def test_two_process_aggregation_and_trace_id(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK total=8.0" in out
    # both processes adopted the SAME trace id (the coordinator's)
    tids = {out.strip().rsplit("trace=", 1)[1].splitlines()[0]
            for out in outs}
    assert len(tids) == 1, tids
