"""Multi-host data path: per-process shards → globally-sharded batches.

Spawns a real 2-process JAX CPU cluster (``jax.distributed.initialize``
with a localhost coordinator — the reference's "local[4] = real fabric,
local topology" trick, SURVEY §4.3) and runs estimator ``fit`` where each
process holds only its half of the data. The global batch is assembled via
``jax.make_array_from_process_local_data`` inside ``_put_batch`` — no
driver-side collect (VERDICT round-1 item #5)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# 2-process jax.distributed clusters — fresh JAX compile per process
pytestmark = pytest.mark.slow

_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid, pcnt = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coordinator_address=coord, num_processes=pcnt,
                           process_id=pid)
assert jax.process_count() == pcnt
assert len(jax.devices()) == pcnt * 2  # 2 local devices per process

from zoo_tpu.orca import init_orca_context, stop_orca_context
from zoo_tpu.orca.data.shard import LocalXShards, shards_for_process
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

init_orca_context(cluster_mode="tpu")  # multi-process path

# every process builds the same logical dataset, then keeps its own shards
rs = np.random.RandomState(0)
x = rs.randn(256, 8).astype(np.float32)
w = rs.randn(8, 1).astype(np.float32)
y = (x @ w).astype(np.float32)
all_shards = LocalXShards.partition({"x": x, "y": y}, num_shards=8)
mine = shards_for_process(all_shards)
assert mine.num_partitions() == 8 // pcnt

m = Sequential()
m.add(Dense(16, input_shape=(8,), activation="relu"))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
est = Estimator.from_keras(m)
hist = est.fit(mine, epochs=3, batch_size=32)  # global batch 32 -> 16/proc
losses = hist["loss"]
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], losses

# multi-host predict: each process gets predictions for ITS local rows
from zoo_tpu.pipeline.api.keras.engine import data_utils
local_x = data_utils.to_xy_arrays(mine, None)[0][0]
preds = m.predict(local_x, batch_size=32)
assert preds.shape == (local_x.shape[0], 1), preds.shape
assert np.isfinite(preds).all()
print(f"proc {pid} OK losses={losses}")
stop_orca_context()
"""


@pytest.mark.timeout(300)
def test_two_process_cpu_cluster_fit(tmp_path):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK" in out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_REBALANCE_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid, pcnt = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coordinator_address=coord, num_processes=pcnt,
                           process_id=pid)

from zoo_tpu.orca import init_orca_context, stop_orca_context
from zoo_tpu.orca.data import LocalXShards, rebalance_shards
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

init_orca_context(cluster_mode="tpu")

# global dataset = 8 shards of 32 rows; host0 starts with shards 0..5,
# host1 with 6..7 (imbalanced). Only the surplus (4, 5) must move.
rs = np.random.RandomState(0)
x = rs.randn(256, 8).astype(np.float32)
w = rs.randn(8, 1).astype(np.float32)
y = (x @ w).astype(np.float32)
shard = lambda i: {"x": x[32 * i:32 * i + 32], "y": y[32 * i:32 * i + 32]}
mine = LocalXShards([shard(i) for i in ([0, 1, 2, 3, 4, 5] if pid == 0
                                        else [6, 7])])
bal = rebalance_shards(mine, bind_ip="127.0.0.1")
assert bal.num_partitions() == 4, bal.num_partitions()
got_rows = np.concatenate([s["x"] for s in bal.collect()])
want = (x[0:128] if pid == 0
        else np.concatenate([x[192:256], x[128:192]]))  # plan [6,7,4,5]
np.testing.assert_array_equal(got_rows, want)

m = Sequential()
m.add(Dense(16, input_shape=(8,), activation="relu"))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
est = Estimator.from_keras(m)
hist = est.fit(bal, epochs=3, batch_size=32, shuffle=False)
print(f"proc {pid} LOSSES={','.join(f'{l:.6f}' for l in hist['loss'])}")
stop_orca_context()
"""

_SINGLE_EQUIV = r"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from zoo_tpu.orca import init_orca_context, stop_orca_context
from zoo_tpu.orca.data import LocalXShards
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

init_orca_context(cluster_mode="local", mesh_axes={"data": 4})
rs = np.random.RandomState(0)
x = rs.randn(256, 8).astype(np.float32)
w = rs.randn(8, 1).astype(np.float32)
y = (x @ w).astype(np.float32)
# reorder rows so that contiguous global batches of 32 equal the
# 2-process run's assembled batches: [host0 rows 16b:16b+16,
# host1 rows 16b:16b+16] with host0 = rows 0..127 and host1 =
# rows [192:256]+[128:192] (the locality-first plan order)
h0 = x[0:128]; h1 = np.concatenate([x[192:256], x[128:192]])
g0 = y[0:128]; g1 = np.concatenate([y[192:256], y[128:192]])
xs, ys = [], []
for b in range(8):
    xs += [h0[16 * b:16 * b + 16], h1[16 * b:16 * b + 16]]
    ys += [g0[16 * b:16 * b + 16], g1[16 * b:16 * b + 16]]
xe, ye = np.concatenate(xs), np.concatenate(ys)

m = Sequential()
m.add(Dense(16, input_shape=(8,), activation="relu"))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
est = Estimator.from_keras(m)
hist = est.fit(LocalXShards.partition({"x": xe, "y": ye}, 4), epochs=3,
               batch_size=32, shuffle=False)
print(f"SINGLE LOSSES={','.join(f'{l:.6f}' for l in hist['loss'])}")
stop_orca_context()
"""


@pytest.mark.timeout(300)
def test_rebalanced_disjoint_shards_match_single_process(tmp_path):
    """2-process cluster: imbalanced shards -> locality-first rebalance ->
    train on DISJOINT halves; loss trajectory matches a single-process
    run over the identically-ordered dataset (VERDICT r2 missing #2)."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_REBALANCE_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"

    single = tmp_path / "single.py"
    single.write_text(_SINGLE_EQUIV)
    env1 = dict(env)
    env1["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    r = subprocess.run([sys.executable, str(single)], capture_output=True,
                       text=True, env=env1, timeout=240)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]

    def losses(txt, tag):
        line = [ln for ln in txt.splitlines() if tag in ln][0]
        return [float(v) for v in line.split("LOSSES=")[1].split(",")]

    multi = losses(outs[0], "proc 0 ")
    ref = losses(r.stdout, "SINGLE ")
    assert len(multi) == len(ref) == 3
    np.testing.assert_allclose(multi, ref, rtol=2e-3, atol=2e-4)
    # and the two processes agree with each other exactly
    assert losses(outs[0], "proc 0 ") == losses(outs[1], "proc 1 ")


_SPARK_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid, pcnt, staging = (sys.argv[1], int(sys.argv[2]),
                             int(sys.argv[3]), sys.argv[4])
jax.distributed.initialize(coordinator_address=coord, num_processes=pcnt,
                           process_id=pid)

from zoo_tpu.orca.data.spark import spark_dataframe_to_shards


class _Collected:
    def __init__(self, items):
        self.items = items

    def collect(self):
        return self.items


class _StubRDD:
    def __init__(self, parts):
        self._parts = parts

    def mapPartitionsWithIndex(self, f):
        out = []
        for i, part in enumerate(self._parts):
            out.extend(f(i, iter(part)))
        return _Collected(out)


class DataFrame:
    def __init__(self, rows, parts):
        n = len(rows) // parts
        self._parts = [rows[i * n:(i + 1) * n] for i in range(parts)]
        self.columns = list(rows[0].keys())

    @property
    def rdd(self):
        return _StubRDD(self._parts)


DataFrame.__module__ = "pyspark.sql.dataframe"

rows = [{"f": float(i), "label": float(i % 2)} for i in range(80)]
df = DataFrame(rows, parts=4)
shards = spark_dataframe_to_shards(df, ["f"], ["label"],
                                   staging_dir=staging)
vals = sorted(float(v) for s in shards.collect() for v in s["x"])
print(f"proc {pid} VALS={vals[0]}..{vals[-1]} n={len(vals)}")
# exactly ONE staging copy for the whole cluster: 4 shard files + manifest
import glob
files = glob.glob(os.path.join(staging, "zoo-*-p*.npz"))
assert len(files) == 4, files
print(f"proc {pid} SPARK-STAGE OK")
"""


@pytest.mark.timeout(300)
def test_spark_multihost_single_staging(tmp_path):
    """Multi-host fit(spark_df): the Spark job runs ONCE (process 0),
    peers read the shared manifest, per-process slices are disjoint."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    staging = tmp_path / "staging"
    staging.mkdir()
    script = tmp_path / "worker.py"
    script.write_text(_SPARK_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "2", str(staging)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} SPARK-STAGE OK" in out
    # disjoint row ranges across the two processes
    v0 = [ln for ln in outs[0].splitlines() if "VALS=" in ln][0]
    v1 = [ln for ln in outs[1].splitlines() if "VALS=" in ln][0]
    assert v0.split("VALS=")[1] != v1.split("VALS=")[1]


_DEAD_PEER_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid, pcnt = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coordinator_address=coord, num_processes=pcnt,
                           process_id=pid)

from zoo_tpu.orca import init_orca_context, stop_orca_context
from zoo_tpu.orca.data import LocalXShards, rebalance_shards
from zoo_tpu.util.resilience import inject

init_orca_context(cluster_mode="tpu")

# imbalanced: host1 must fetch host0's surplus shards over the network
rs = np.random.RandomState(0)
shard = lambda i: {"x": rs.randn(8, 4).astype(np.float32)}
mine = LocalXShards([shard(i) for i in range(6)] if pid == 0
                    else [shard(i) for i in range(2)])
if pid == 1:
    # every fetch attempt fails permanently == the serving peer is dead
    inject("shard.fetch", exc=ConnectionError("injected dead peer"))
try:
    rebalance_shards(mine, bind_ip="127.0.0.1", deadline=60.0)
    print(f"proc {pid} NO-ERROR")  # the bug: a host sailed through
except RuntimeError as e:
    assert "host" in str(e), e  # names the failed host(s)
    print(f"proc {pid} RAISED OK")
stop_orca_context()
"""


@pytest.mark.chaos
@pytest.mark.timeout(300)
def test_rebalance_dead_peer_raises_on_every_host(tmp_path):
    """A host whose fetch phase fails permanently must NOT strand its
    peers inside the teardown barrier: every host raises a RuntimeError
    naming the failed host(s), within the deadline (the pre-fix behavior
    was a cluster-wide hang in sync_global_devices)."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_DEAD_PEER_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} RAISED OK" in out, out[-2000:]
