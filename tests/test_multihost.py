"""Multi-host data path: per-process shards → globally-sharded batches.

Spawns a real 2-process JAX CPU cluster (``jax.distributed.initialize``
with a localhost coordinator — the reference's "local[4] = real fabric,
local topology" trick, SURVEY §4.3) and runs estimator ``fit`` where each
process holds only its half of the data. The global batch is assembled via
``jax.make_array_from_process_local_data`` inside ``_put_batch`` — no
driver-side collect (VERDICT round-1 item #5)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid, pcnt = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coordinator_address=coord, num_processes=pcnt,
                           process_id=pid)
assert jax.process_count() == pcnt
assert len(jax.devices()) == pcnt * 2  # 2 local devices per process

from zoo_tpu.orca import init_orca_context, stop_orca_context
from zoo_tpu.orca.data.shard import LocalXShards, shards_for_process
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

init_orca_context(cluster_mode="tpu")  # multi-process path

# every process builds the same logical dataset, then keeps its own shards
rs = np.random.RandomState(0)
x = rs.randn(256, 8).astype(np.float32)
w = rs.randn(8, 1).astype(np.float32)
y = (x @ w).astype(np.float32)
all_shards = LocalXShards.partition({"x": x, "y": y}, num_shards=8)
mine = shards_for_process(all_shards)
assert mine.num_partitions() == 8 // pcnt

m = Sequential()
m.add(Dense(16, input_shape=(8,), activation="relu"))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
est = Estimator.from_keras(m)
hist = est.fit(mine, epochs=3, batch_size=32)  # global batch 32 -> 16/proc
losses = hist["loss"]
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], losses

# multi-host predict: each process gets predictions for ITS local rows
from zoo_tpu.pipeline.api.keras.engine import data_utils
local_x = data_utils.to_xy_arrays(mine, None)[0][0]
preds = m.predict(local_x, batch_size=32)
assert preds.shape == (local_x.shape[0], 1), preds.shape
assert np.isfinite(preds).all()
print(f"proc {pid} OK losses={losses}")
stop_orca_context()
"""


@pytest.mark.timeout(300)
def test_two_process_cpu_cluster_fit(tmp_path):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK" in out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
