"""Gray-failure ejection: scorer EWMA math, the probation/ejection/
re-admission state machine on a fake clock, and the HA client's
routing integration against live replicas (chaos marker)."""

import time

import pytest

from zoo_tpu.serving.ejection import (
    ACTIVE,
    EJECTED,
    PROBATION,
    EjectionConfig,
    EjectionController,
)


def _cfg(**kw):
    base = dict(enabled=True, factor=3.0, min_ms=10.0, min_samples=3,
                alpha=0.5, probation_s=1.0, probe_interval_s=0.5,
                readmit_base_s=2.0, readmit_max_s=16.0, error_rate=0.6)
    base.update(kw)
    return EjectionConfig(**base)


def _controller(**kw):
    now = [0.0]
    ctl = EjectionController(_cfg(**kw), clock=lambda: now[0])
    return ctl, now


def _feed(score, ms, n, alpha=0.5):
    for _ in range(n):
        score.record(ms / 1000.0, alpha)


def test_scorer_ewma_and_error_decay():
    ctl, _ = _controller()
    s = ctl.new_score("a")
    s.record(0.010, 0.5)
    assert abs(s.ewma_ms - 10.0) < 1e-9
    s.record(0.030, 0.5)
    assert abs(s.ewma_ms - 20.0) < 1e-9
    s.record_error(0.5)
    assert abs(s.err - 0.5) < 1e-9
    s.record(0.020, 0.5)   # success decays the error EWMA
    assert abs(s.err - 0.25) < 1e-9
    assert s.n == 4


def test_outlier_walks_probation_then_ejected_then_readmitted():
    ctl, now = _controller()
    fast = [ctl.new_score(f"f{i}") for i in range(2)]
    slow = ctl.new_score("slow")
    scores = fast + [slow]
    for s in fast:
        _feed(s, 4.0, 6)
    _feed(slow, 200.0, 6)

    ctl.evaluate(scores)
    assert slow.state == PROBATION and all(
        s.state == ACTIVE for s in fast)
    # sustained degradation past probation_s => ejected, backoff armed
    now[0] = 1.5
    _feed(slow, 200.0, 2)
    ctl.evaluate(scores)
    assert slow.state == EJECTED
    assert slow.readmit_at == pytest.approx(1.5 + 2.0)
    # before the backoff expires nothing changes
    now[0] = 3.0
    ctl.evaluate(scores)
    assert slow.state == EJECTED
    # backoff expiry => probation PROBE with the score reset (fresh
    # evidence only — the stale slow EWMA must not re-eject it)
    now[0] = 3.6
    ctl.evaluate(scores)
    assert slow.state == PROBATION
    assert slow.ewma_ms is None and slow.n == 0
    # fast canary samples re-admit it
    _feed(slow, 4.0, 4)
    ctl.evaluate(scores)
    assert slow.state == ACTIVE
    assert slow.eject_count == 0  # recovery clears the backoff ladder
    events = [e[1] for e in ctl.events]
    assert events == ["probation", "ejected", "probe", "readmitted"]


def test_reeject_backoff_doubles_per_consecutive_ejection():
    ctl, now = _controller()
    fast = [ctl.new_score(f"f{i}") for i in range(2)]
    slow = ctl.new_score("slow")
    scores = fast + [slow]
    for s in fast:
        _feed(s, 4.0, 6)
    expect_backoff = [2.0, 4.0, 8.0]
    for k, backoff in enumerate(expect_backoff):
        _feed(slow, 200.0, 6)
        ctl.evaluate(scores)          # -> probation
        now[0] += 1.5
        _feed(slow, 200.0, 1)
        ctl.evaluate(scores)          # -> ejected
        assert slow.state == EJECTED
        assert slow.readmit_at == pytest.approx(now[0] + backoff)
        now[0] = slow.readmit_at + 0.1
        ctl.evaluate(scores)          # -> probe window
        assert slow.state == PROBATION


def test_error_rate_alone_triggers_probation():
    ctl, _ = _controller()
    a = ctl.new_score("a")
    b = ctl.new_score("b")
    _feed(a, 4.0, 6)
    for _ in range(6):
        b.record_error(0.5)
    ctl.evaluate([a, b])
    assert b.state == PROBATION and a.state == ACTIVE


def test_never_probation_last_active_seat_on_latency():
    """With no healthy peer to compare against, latency alone must not
    eject — the median would be the seat itself."""
    ctl, _ = _controller()
    only = ctl.new_score("only")
    other = ctl.new_score("other")
    other.state = EJECTED
    _feed(only, 500.0, 10)
    ctl.evaluate([only, other])
    assert only.state == ACTIVE


def test_absolute_floor_shields_fast_outliers():
    """3x the median is NOT an outlier while everything is under the
    min_ms floor — sub-floor jitter never ejects."""
    ctl, _ = _controller(min_ms=50.0)
    fast = [ctl.new_score(f"f{i}") for i in range(2)]
    mild = ctl.new_score("mild")
    for s in fast:
        _feed(s, 3.0, 6)
    _feed(mild, 30.0, 6)   # 10x the median but under the 50ms floor
    ctl.evaluate(fast + [mild])
    assert mild.state == ACTIVE


def test_canary_cadence_at_most_one_per_interval():
    ctl, now = _controller()
    s = ctl.new_score("p")
    s.state = PROBATION
    now[0] = 10.0
    assert ctl.take_canary(s)
    assert not ctl.take_canary(s)
    now[0] = 10.6
    assert ctl.take_canary(s)


def test_disabled_controller_never_transitions():
    ctl, _ = _controller(enabled=False)
    fast = ctl.new_score("f")
    slow = ctl.new_score("s")
    _feed(fast, 4.0, 6)
    _feed(slow, 500.0, 6)
    ctl.evaluate([fast, slow])
    assert slow.state == ACTIVE
    assert ctl.state_of(slow) == ACTIVE
    assert not ctl.take_canary(slow)


# ---------------------------------------------- live integration (chaos)

@pytest.mark.chaos
def test_slow_replica_ejected_and_readmitted_live():
    """End to end against real replica processes: a gray-slow replica
    (healthz fine, 40x slower via the wire chaos op) is ejected from
    the client rotation, traffic avoids it, and once the fault clears
    the canary probes re-admit it."""
    import numpy as np

    from zoo_tpu.serving.ha import ReplicaGroup
    from zoo_tpu.serving.ha_client import HAServingClient

    group = ReplicaGroup("synthetic:double:2", num_replicas=3,
                         max_restarts=2, batch_size=8, max_wait_ms=1.0,
                         env={"ZOO_CHAOS_ALLOW": "1"})
    group.start(timeout=60)
    cli = HAServingClient(
        group.endpoints(), deadline_ms=8000, hedge=False,
        ejection_config=_cfg(min_ms=20.0, probation_s=0.4,
                             probe_interval_s=0.25, readmit_base_s=0.4))
    x = np.ones((1, 4), np.float32)
    try:
        for _ in range(12):
            cli.predict(x)
        group.chaos_rpc(1, "serving.infer", delay_ms=80)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            cli.predict(x)
            if any(s["state"] == EJECTED
                   for s in cli.ejection_states().values()):
                break
        states = cli.ejection_states()
        assert any(s["state"] == EJECTED for s in states.values()), states
        # healthz still says 3/3 ok — gray, not dead: exactly the
        # failure crash detection cannot see
        hz = group.healthz()
        assert sum(1 for h in hz if h and h.get("ok")) == 3
        # fault clears -> canaries re-admit
        group.chaos_rpc(1, "serving.infer", clear=True)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            cli.predict(x)
            if all(s["state"] == ACTIVE
                   for s in cli.ejection_states().values()):
                break
            time.sleep(0.02)
        assert all(s["state"] == ACTIVE
                   for s in cli.ejection_states().values()), \
            cli.ejection_states()
        kinds = [e[1] for e in cli.ejection_events()]
        assert "ejected" in kinds and (
            "readmitted" in kinds), kinds
    finally:
        cli.close()
        group.stop()
