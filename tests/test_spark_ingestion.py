"""Spark DataFrame ingestion adapter, tested against a stubbed partition
iterator (pyspark is not in this image — VERDICT r2 missing #1). The stub
implements exactly the four-method surface the adapter uses."""

import numpy as np
import pandas as pd
import pytest

from zoo_tpu.orca.data.spark import (
    is_spark_dataframe,
    spark_dataframe_to_shards,
)


class _Collected:
    def __init__(self, items):
        self.items = items

    def collect(self):
        return self.items


class _StubRDD:
    def __init__(self, partitions):
        self._parts = partitions

    def mapPartitionsWithIndex(self, f):
        out = []
        for i, part in enumerate(self._parts):
            out.extend(f(i, iter(part)))
        return _Collected(out)


class DataFrame:  # noqa: N801 — must be named like pyspark's class
    """Pandas-backed stub of pyspark.sql.DataFrame."""

    def __init__(self, pdf: pd.DataFrame, num_partitions: int = 3):
        self._pdf = pdf
        bounds = np.linspace(0, len(pdf), num_partitions + 1).astype(int)
        self._parts = [
            [row._asdict() if hasattr(row, "_asdict") else dict(row)
             for _, row in pdf.iloc[bounds[i]:bounds[i + 1]].iterrows()]
            for i in range(num_partitions)]

    @property
    def columns(self):
        return list(self._pdf.columns)

    @property
    def rdd(self):
        return _StubRDD(self._parts)


DataFrame.__module__ = "pyspark.sql.dataframe"


def _make_df(n=60, parts=3):
    rs = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "f1": rs.randn(n).astype(np.float32),
        "f2": rs.randn(n).astype(np.float32),
        "label": (rs.rand(n) > 0.5).astype(np.float32),
    })
    return pdf, DataFrame(pdf, num_partitions=parts)


def test_detection_without_pyspark():
    _, df = _make_df()
    assert is_spark_dataframe(df)
    assert not is_spark_dataframe(pd.DataFrame({"a": [1]}))


def test_partitions_become_shards_no_driver_rows(tmp_path):
    pdf, df = _make_df(n=60, parts=3)
    # the adapter's driver-side traffic is path metadata only: capture it
    collected = {}
    orig = _StubRDD.mapPartitionsWithIndex

    def spy(self, f):
        r = orig(self, f)
        collected["meta"] = r.items
        return r

    _StubRDD.mapPartitionsWithIndex = spy
    try:
        shards = spark_dataframe_to_shards(
            df, ["f1", "f2"], ["label"], staging_dir=str(tmp_path),
            process_index=0, process_count=1)
    finally:
        _StubRDD.mapPartitionsWithIndex = orig
    for pid, path, n in collected["meta"]:
        assert isinstance(pid, int) and isinstance(path, str)
        assert isinstance(n, int)  # counts and paths — never row data
    assert shards.num_partitions() == 3
    x = np.concatenate([s["x"] for s in shards.collect()])
    y = np.concatenate([s["y"] for s in shards.collect()])
    np.testing.assert_allclose(
        x, np.stack([pdf["f1"], pdf["f2"]], axis=1), rtol=1e-6)
    np.testing.assert_allclose(y, pdf["label"].to_numpy())


def test_per_process_slices_are_disjoint(tmp_path):
    pdf, df = _make_df(n=60, parts=4)
    a = spark_dataframe_to_shards(df, ["f1"], ["label"],
                                  staging_dir=str(tmp_path),
                                  process_index=0, process_count=2)
    b = spark_dataframe_to_shards(df, ["f1"], ["label"],
                                  staging_dir=str(tmp_path),
                                  process_index=1, process_count=2)
    assert a.num_partitions() == b.num_partitions() == 2
    xa = np.concatenate([s["x"] for s in a.collect()])
    xb = np.concatenate([s["x"] for s in b.collect()])
    assert len(np.intersect1d(xa, xb)) == 0
    assert len(xa) + len(xb) == 60


def test_estimator_fit_spark_dataframe(tmp_path, monkeypatch):
    monkeypatch.setenv("ZOO_SPARK_STAGING", str(tmp_path))
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    _, df = _make_df(n=120, parts=3)
    m = Sequential()
    m.add(Dense(8, input_shape=(2,), activation="relu"))
    m.add(Dense(1, activation="sigmoid"))
    m.compile(optimizer="adam", loss="binary_crossentropy")
    est = Estimator.from_keras(m)
    hist = est.fit(df, epochs=2, batch_size=24,
                   feature_cols=["f1", "f2"], label_cols=["label"])
    assert np.isfinite(hist["loss"]).all()


def test_estimator_fit_spark_requires_feature_cols():
    _, df = _make_df()
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(1, input_shape=(2,)))
    m.compile(optimizer="adam", loss="mse")
    with pytest.raises(ValueError, match="feature_cols"):
        Estimator.from_keras(m).fit(df, epochs=1)


def test_nnestimator_fit_spark_dataframe(tmp_path, monkeypatch):
    monkeypatch.setenv("ZOO_SPARK_STAGING", str(tmp_path))
    from zoo_tpu.pipeline.nnframes import NNClassifier
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    rs = np.random.RandomState(1)
    pdf = pd.DataFrame({
        "features": list(rs.randn(48, 4).astype(np.float32)),
        "label": rs.randint(0, 2, 48).astype(np.float64),
    })
    df = DataFrame(pdf, num_partitions=2)
    m = Sequential()
    m.add(Dense(8, input_shape=(4,), activation="relu"))
    m.add(Dense(2, activation="softmax"))
    clf = NNClassifier(m, features_col="features", label_col="label") \
        .setMaxEpoch(2).setBatchSize(16)
    model = clf.fit(df)
    out = model.transform(pdf.head(8))
    assert "prediction" in out.columns
