"""Spark DataFrame ingestion adapter, tested against a stubbed partition
iterator.

pyspark cannot run here: the image has no JVM (``java`` absent) and
installs are not possible, so a live ``local[4]`` SparkContext is out of
reach — the constraint is recorded in ``docs/migration.md``. The stub
therefore conforms to the real ``RDD.mapPartitionsWithIndex`` contract
as closely as a JVM-less harness can:

* the shipped function is ROUND-TRIPPED through cloudpickle on every
  call (Spark's ``CloudPickleSerializer`` — exactly where closures
  break on real clusters);
* ``test_executor_subprocess_runs_pickled_writer`` executes the pickled
  writer in a FRESH python interpreter (a real executor boundary: no
  shared memory, staging-dir visibility for real);
* SQL schema edges are covered: null rows, ``Decimal``, ArrayType
  (nested lists), string columns.
"""

import numpy as np
import pandas as pd
import pytest

from zoo_tpu.orca.data.spark import (
    is_spark_dataframe,
    spark_dataframe_to_shards,
)


class _Collected:
    def __init__(self, items):
        self.items = items

    def collect(self):
        return self.items


class _StubRDD:
    def __init__(self, partitions):
        self._parts = partitions

    def mapPartitionsWithIndex(self, f):
        # the real contract: the function is serialized, shipped, and
        # deserialized on executors — a closure that only works
        # in-process must fail HERE, not on a live cluster
        import cloudpickle
        f = cloudpickle.loads(cloudpickle.dumps(f))
        out = []
        for i, part in enumerate(self._parts):
            out.extend(f(i, iter(part)))
        return _Collected(out)


class DataFrame:  # noqa: N801 — must be named like pyspark's class
    """Pandas-backed stub of pyspark.sql.DataFrame."""

    @staticmethod
    def _row(row):
        # real Spark delivers SQL NULL as python None in EVERY column
        # type; pandas holds NaN — convert so the stub is row-faithful
        d = row._asdict() if hasattr(row, "_asdict") else dict(row)
        return {k: None if (isinstance(v, float) and np.isnan(v)) else v
                for k, v in d.items()}

    def __init__(self, pdf: pd.DataFrame, num_partitions: int = 3):
        self._pdf = pdf
        bounds = np.linspace(0, len(pdf), num_partitions + 1).astype(int)
        self._parts = [
            [self._row(row)
             for _, row in pdf.iloc[bounds[i]:bounds[i + 1]].iterrows()]
            for i in range(num_partitions)]

    @property
    def columns(self):
        return list(self._pdf.columns)

    @property
    def rdd(self):
        return _StubRDD(self._parts)


DataFrame.__module__ = "pyspark.sql.dataframe"


def _make_df(n=60, parts=3):
    rs = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "f1": rs.randn(n).astype(np.float32),
        "f2": rs.randn(n).astype(np.float32),
        "label": (rs.rand(n) > 0.5).astype(np.float32),
    })
    return pdf, DataFrame(pdf, num_partitions=parts)


def test_detection_without_pyspark():
    _, df = _make_df()
    assert is_spark_dataframe(df)
    assert not is_spark_dataframe(pd.DataFrame({"a": [1]}))


def test_partitions_become_shards_no_driver_rows(tmp_path):
    pdf, df = _make_df(n=60, parts=3)
    # the adapter's driver-side traffic is path metadata only: capture it
    collected = {}
    orig = _StubRDD.mapPartitionsWithIndex

    def spy(self, f):
        r = orig(self, f)
        collected["meta"] = r.items
        return r

    _StubRDD.mapPartitionsWithIndex = spy
    try:
        shards = spark_dataframe_to_shards(
            df, ["f1", "f2"], ["label"], staging_dir=str(tmp_path),
            process_index=0, process_count=1)
    finally:
        _StubRDD.mapPartitionsWithIndex = orig
    for pid, path, n in collected["meta"]:
        assert isinstance(pid, int) and isinstance(path, str)
        assert isinstance(n, int)  # counts and paths — never row data
    assert shards.num_partitions() == 3
    x = np.concatenate([s["x"] for s in shards.collect()])
    y = np.concatenate([s["y"] for s in shards.collect()])
    np.testing.assert_allclose(
        x, np.stack([pdf["f1"], pdf["f2"]], axis=1), rtol=1e-6)
    np.testing.assert_allclose(y, pdf["label"].to_numpy())


def test_per_process_slices_are_disjoint(tmp_path):
    pdf, df = _make_df(n=60, parts=4)
    a = spark_dataframe_to_shards(df, ["f1"], ["label"],
                                  staging_dir=str(tmp_path),
                                  process_index=0, process_count=2)
    b = spark_dataframe_to_shards(df, ["f1"], ["label"],
                                  staging_dir=str(tmp_path),
                                  process_index=1, process_count=2)
    assert a.num_partitions() == b.num_partitions() == 2
    xa = np.concatenate([s["x"] for s in a.collect()])
    xb = np.concatenate([s["x"] for s in b.collect()])
    assert len(np.intersect1d(xa, xb)) == 0
    assert len(xa) + len(xb) == 60


def test_estimator_fit_spark_dataframe(tmp_path, monkeypatch):
    monkeypatch.setenv("ZOO_SPARK_STAGING", str(tmp_path))
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    _, df = _make_df(n=120, parts=3)
    m = Sequential()
    m.add(Dense(8, input_shape=(2,), activation="relu"))
    m.add(Dense(1, activation="sigmoid"))
    m.compile(optimizer="adam", loss="binary_crossentropy")
    est = Estimator.from_keras(m)
    hist = est.fit(df, epochs=2, batch_size=24,
                   feature_cols=["f1", "f2"], label_cols=["label"])
    assert np.isfinite(hist["loss"]).all()


def test_estimator_fit_spark_requires_feature_cols():
    _, df = _make_df()
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(1, input_shape=(2,)))
    m.compile(optimizer="adam", loss="mse")
    with pytest.raises(ValueError, match="feature_cols"):
        Estimator.from_keras(m).fit(df, epochs=1)


def test_executor_subprocess_runs_pickled_writer(tmp_path):
    """The shipped writer must survive a REAL executor boundary: plain
    pickle over a fresh python interpreter, no shared memory with the
    driver, results read back only through the staging dir."""
    import pickle
    import subprocess
    import sys

    from zoo_tpu.orca.data.spark import _partition_writer

    writer = _partition_writer(["f1", "label"], str(tmp_path), "subproc")
    rows = [{"f1": float(i), "label": float(i % 2)} for i in range(10)]
    payload = tmp_path / "task.pkl"
    with open(payload, "wb") as fh:
        pickle.dump((writer, 3, rows), fh)  # plain pickle, like a worker

    script = (
        "import pickle, sys\n"
        f"f, pid, rows = pickle.load(open({str(payload)!r}, 'rb'))\n"
        "meta = list(f(pid, iter(rows)))\n"
        f"pickle.dump(meta, open({str(tmp_path / 'meta.pkl')!r}, 'wb'))\n"
    )
    subprocess.run([sys.executable, "-c", script], check=True,
                   timeout=120)
    with open(tmp_path / "meta.pkl", "rb") as fh:
        meta = pickle.load(fh)
    (pid, path, n), = meta
    assert pid == 3 and n == 10
    with np.load(path, allow_pickle=False) as z:
        np.testing.assert_allclose(z["f1"], np.arange(10.0))


def test_schema_edge_cases(tmp_path):
    """Null rows, Decimal, ArrayType, and string columns — the SQL-type
    edges a real DataFrame delivers to the partition iterator."""
    from decimal import Decimal

    # nulls in a float column -> NaN
    pdf = pd.DataFrame({"f": [1.0, None, 3.0],
                        "label": [0.0, 1.0, 0.0]})
    shards = spark_dataframe_to_shards(
        DataFrame(pdf, 1), ["f"], ["label"], staging_dir=str(tmp_path),
        process_index=0, process_count=1)
    x = np.concatenate([s["x"] for s in shards.collect()])
    assert np.isnan(x[1]) and x[0] == 1.0

    # Decimal column -> float64 (Spark DecimalType rows arrive as Decimal)
    pdf = pd.DataFrame({"f": [Decimal("1.25"), Decimal("2.5")],
                        "label": [0.0, 1.0]})
    shards = spark_dataframe_to_shards(
        DataFrame(pdf, 1), ["f"], ["label"], staging_dir=str(tmp_path),
        process_index=0, process_count=1)
    x = np.concatenate([s["x"] for s in shards.collect()])
    np.testing.assert_allclose(x, [1.25, 2.5])

    # ArrayType column -> stacked 2-D features
    pdf = pd.DataFrame({"f": [[1.0, 2.0], [3.0, 4.0]],
                        "label": [0.0, 1.0]})
    shards = spark_dataframe_to_shards(
        DataFrame(pdf, 1), ["f"], ["label"], staging_dir=str(tmp_path),
        process_index=0, process_count=1)
    x = np.concatenate([s["x"] for s in shards.collect()])
    np.testing.assert_allclose(x, [[1.0, 2.0], [3.0, 4.0]])

    # null in a non-float column -> actionable error, not dtype=object
    pdf = pd.DataFrame({"f": ["a", None], "label": [0.0, 1.0]})
    with pytest.raises(ValueError, match="na.fill"):
        spark_dataframe_to_shards(
            DataFrame(pdf, 1), ["f"], ["label"],
            staging_dir=str(tmp_path), process_index=0, process_count=1)

    # string column -> actionable error (npz side is allow_pickle=False)
    pdf = pd.DataFrame({"f": ["a", "b"], "label": [0.0, 1.0]})
    with pytest.raises(TypeError, match="non-numeric"):
        spark_dataframe_to_shards(
            DataFrame(pdf, 1), ["f"], ["label"],
            staging_dir=str(tmp_path), process_index=0, process_count=1)

    # ragged ArrayType -> actionable error
    pdf = pd.DataFrame({"f": [[1.0, 2.0], [3.0]], "label": [0.0, 1.0]})
    with pytest.raises(ValueError, match="ragged"):
        spark_dataframe_to_shards(
            DataFrame(pdf, 1), ["f"], ["label"],
            staging_dir=str(tmp_path), process_index=0, process_count=1)


def test_nnestimator_fit_spark_dataframe(tmp_path, monkeypatch):
    monkeypatch.setenv("ZOO_SPARK_STAGING", str(tmp_path))
    from zoo_tpu.pipeline.nnframes import NNClassifier
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    rs = np.random.RandomState(1)
    pdf = pd.DataFrame({
        "features": list(rs.randn(48, 4).astype(np.float32)),
        "label": rs.randint(0, 2, 48).astype(np.float64),
    })
    df = DataFrame(pdf, num_partitions=2)
    m = Sequential()
    m.add(Dense(8, input_shape=(4,), activation="relu"))
    m.add(Dense(2, activation="softmax"))
    clf = NNClassifier(m, features_col="features", label_col="label") \
        .setMaxEpoch(2).setBatchSize(16)
    model = clf.fit(df)
    out = model.transform(pdf.head(8))
    assert "prediction" in out.columns
