"""TextSet pipeline, layer-zoo breadth, multi-output Model."""

import numpy as np
import pytest

from zoo_tpu.feature.text import LocalTextSet, TextSet, load_glove_matrix


def test_textset_chain(tmp_path):
    texts = ["The quick brown fox jumps over the lazy dog 42",
             "pack my box with five dozen liquor jugs",
             "the five boxing wizards jump quickly"]
    ts = LocalTextSet(texts=texts, labels=[0, 1, 1])
    ts.tokenize().normalize().word2idx().shape_sequence(len=8)
    ts.generate_sample()
    x, y = ts.to_arrays()
    assert x.shape == (3, 8) and x.dtype == np.int32
    assert list(y) == [0, 1, 1]
    wi = ts.get_word_index()
    assert wi and "the" in wi and "42" not in wi  # digits normalized away
    assert min(wi.values()) == 1  # 0 reserved for padding

    # word-index round trip
    p = tmp_path / "wi.json"
    ts.save_word_index(str(p))
    ts2 = LocalTextSet(texts=["a quick fox"]).tokenize().normalize()
    ts2.load_word_index(str(p))
    ts2.word2idx(existing_map=ts2.get_word_index())
    assert ts2.features[0]["indexedTokens"].tolist() == [
        wi["quick"], wi["fox"]]


def test_textset_read_dir_and_split(tmp_path):
    for cat, phrases in (("neg", ["bad terrible", "awful worse"]),
                         ("pos", ["great fine", "good nice", "super cool"])):
        d = tmp_path / "corpus" / cat
        d.mkdir(parents=True)
        for i, t in enumerate(phrases):
            (d / f"{i}.txt").write_text(t)
    ts = TextSet.read(str(tmp_path / "corpus"))
    assert len(ts) == 5
    assert sorted(set(ts.get_labels())) == [0, 1]
    tr, te = ts.random_split([0.6, 0.4])
    assert len(tr) + len(te) == 5


@pytest.mark.heavy
def test_textset_feeds_text_classifier(orca_ctx):
    """End-to-end: corpus -> chain -> TextClassifier trains (VERDICT #7
    'a text-classification example trains')."""
    from zoo_tpu.models.textclassification import TextClassifier

    rs = np.random.RandomState(0)
    pos_words = ["good", "great", "fine", "nice", "super"]
    neg_words = ["bad", "awful", "poor", "sad", "worse"]
    texts, labels = [], []
    for _ in range(120):
        lab = int(rs.randint(2))
        pool = pos_words if lab else neg_words
        texts.append(" ".join(rs.choice(pool, 6)))
        labels.append(lab)
    ts = LocalTextSet(texts=texts, labels=labels)
    ts.tokenize().normalize().word2idx().shape_sequence(len=10)
    x, y = ts.to_arrays()
    vocab = max(ts.get_word_index().values()) + 1

    m = TextClassifier(class_num=2, token_length=16, sequence_length=10,
                       vocab=vocab, encoder="cnn", encoder_output_dim=32,
                       hidden_drop=0.0)
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    hist = m.fit(x, y, batch_size=24, nb_epoch=8, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.7
    res = m.evaluate(x, y, batch_size=24)
    assert res["accuracy"] > 0.8


def test_glove_matrix_and_word_embedding(tmp_path, orca_ctx):
    glove = tmp_path / "glove.txt"
    glove.write_text("fox 1.0 0.0 2.0\ndog 0.5 0.5 0.5\n")
    wi = {"fox": 1, "dog": 2, "cat": 3}
    mat = load_glove_matrix(str(glove), wi)
    assert mat.shape == (4, 3)
    np.testing.assert_allclose(mat[1], [1.0, 0.0, 2.0])
    np.testing.assert_allclose(mat[3], 0.0)  # OOV row stays zero

    import jax

    from zoo_tpu.pipeline.api.keras.layers import WordEmbedding

    we = WordEmbedding(mat)
    p = we.build(jax.random.PRNGKey(0), (None, 2))
    out = np.asarray(we.call(p, np.array([[1, 2]], np.int32)))
    np.testing.assert_allclose(out[0, 0], [1.0, 0.0, 2.0])
    assert "stats" in p  # frozen: never gradient-updated


def test_new_elementwise_layers(orca_ctx):
    import jax

    from zoo_tpu.pipeline.api.keras import layers as L

    x = np.array([[-2.0, -0.3, 0.0, 0.4, 3.0]], np.float32)
    cases = [
        (L.AddConstant(1.0), x + 1),
        (L.MulConstant(2.0), x * 2),
        (L.Exp(), np.exp(x)),
        (L.Square(), x ** 2),
        (L.Negative(), -x),
        (L.HardTanh(), np.clip(x, -1, 1)),
        (L.HardShrink(0.5), np.where(np.abs(x) > 0.5, x, 0)),
        (L.SoftShrink(0.5), np.sign(x) * np.maximum(np.abs(x) - 0.5, 0)),
        (L.Threshold(0.0, -7.0), np.where(x > 0, x, -7.0)),
        (L.BinaryThreshold(0.0), (x > 0).astype(np.float32)),
        (L.Power(2.0, scale=2.0, shift=1.0), (1 + 2 * x) ** 2),
    ]
    for layer, want in cases:
        got = np.asarray(layer.call({}, x))
        np.testing.assert_allclose(got, want, atol=1e-5,
                                   err_msg=type(layer).__name__)
    # shaped ops
    assert np.asarray(L.Squeeze(1).call(
        {}, np.zeros((2, 1, 3)))).shape == (2, 3)
    assert np.asarray(L.ExpandDim(1).call(
        {}, np.zeros((2, 3)))).shape == (2, 1, 3)
    assert np.asarray(L.Select(1, 2).call(
        {}, np.zeros((2, 5)))).shape == (2,)
    assert np.asarray(L.Narrow(1, 1, 3).call(
        {}, np.zeros((2, 5)))).shape == (2, 3)
    assert np.asarray(L.Max(1).call({}, np.zeros((2, 5)))).shape == (2,)
    # parameterized
    import jax

    ca = L.CAdd((5,))
    p = ca.build(jax.random.PRNGKey(0), (None, 5))
    assert np.asarray(ca.call(p, x)).shape == x.shape


def test_conv3d_family_shapes(orca_ctx):
    import jax

    from zoo_tpu.pipeline.api.keras import layers as L

    x = np.random.RandomState(0).randn(2, 3, 8, 8, 8).astype(np.float32)
    conv = L.Convolution3D(4, 3, 3, 3)
    p = conv.build(jax.random.PRNGKey(0), (None, 3, 8, 8, 8))
    y = np.asarray(conv.call(p, x))
    assert y.shape == (2, 4, 6, 6, 6)
    assert conv.compute_output_shape((None, 3, 8, 8, 8)) == \
        (None, 4, 6, 6, 6)

    mp = L.MaxPooling3D()
    assert np.asarray(mp.call({}, x)).shape == (2, 3, 4, 4, 4)
    ap = L.AveragePooling3D()
    np.testing.assert_allclose(
        np.asarray(ap.call({}, np.ones((1, 1, 2, 2, 2), np.float32))), 1.0)
    up = L.UpSampling3D()
    assert np.asarray(up.call({}, x)).shape == (2, 3, 16, 16, 16)
    zp = L.ZeroPadding3D()
    assert np.asarray(zp.call({}, x)).shape == (2, 3, 10, 10, 10)
    cr = L.Cropping3D()
    assert np.asarray(cr.call({}, x)).shape == (2, 3, 6, 6, 6)
    gap = L.GlobalAveragePooling3D()
    assert np.asarray(gap.call({}, x)).shape == (2, 3)


def test_separable_deconv_local_layers(orca_ctx):
    import jax

    from zoo_tpu.pipeline.api.keras import layers as L

    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    sep = L.SeparableConvolution2D(6, 3, 3)
    p = sep.build(jax.random.PRNGKey(0), (None, 3, 8, 8))
    assert np.asarray(sep.call(p, x)).shape == (2, 6, 6, 6)

    dec = L.Deconvolution2D(4, 3, 3, subsample=(2, 2))
    p = dec.build(jax.random.PRNGKey(0), (None, 3, 8, 8))
    y = np.asarray(dec.call(p, x))
    assert y.shape == (2, 4, 17, 17)  # (8-1)*2+3

    lc1 = L.LocallyConnected1D(4, 3)
    p = lc1.build(jax.random.PRNGKey(0), (None, 10, 5))
    y = np.asarray(lc1.call(p, np.random.randn(2, 10, 5).astype(np.float32)))
    assert y.shape == (2, 8, 4)

    lc2 = L.LocallyConnected2D(4, 3, 3)
    p = lc2.build(jax.random.PRNGKey(0), (None, 3, 6, 6))
    assert np.asarray(lc2.call(p, x[:, :, :6, :6])).shape == (2, 4, 4, 4)


def test_convlstm2d(orca_ctx):
    import jax

    from zoo_tpu.pipeline.api.keras import layers as L

    x = np.random.RandomState(0).randn(2, 4, 3, 6, 6).astype(np.float32)
    cl = L.ConvLSTM2D(5, 3)
    p = cl.build(jax.random.PRNGKey(0), (None, 4, 3, 6, 6))
    y = np.asarray(cl.call(p, x))
    assert y.shape == (2, 5, 6, 6)
    cl2 = L.ConvLSTM2D(5, 3, return_sequences=True)
    p2 = cl2.build(jax.random.PRNGKey(0), (None, 4, 3, 6, 6))
    assert np.asarray(cl2.call(p2, x)).shape == (2, 4, 5, 6, 6)


def test_multi_output_model(orca_ctx):
    from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
    from zoo_tpu.pipeline.api.keras.layers import Dense

    inp = Input(shape=(8,))
    h = Dense(16, activation="relu")(inp)
    reg = Dense(1)(h)
    cls = Dense(2, activation="softmax")(h)
    m = Model(input=inp, output=[reg, cls])
    m.compile(optimizer="adam",
              loss=["mse", "sparse_categorical_crossentropy"])
    rs = np.random.RandomState(0)
    x = rs.randn(256, 8).astype(np.float32)
    y1 = x.sum(1, keepdims=True).astype(np.float32)
    y2 = (x[:, 0] > 0).astype(np.int32)
    hist = m.fit({"x": x, "y": [y1, y2]}, batch_size=32, nb_epoch=5,
                 verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    p1, p2 = m.predict(x[:16])
    assert p1.shape == (16, 1) and p2.shape == (16, 2)
