"""NNFrames, XGBoost/AutoXGBoost, GANEstimator, streaming evaluate."""

import numpy as np
import pandas as pd
import pytest

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense


def _frame(n=256, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 4).astype(np.float32)
    df = pd.DataFrame({f"f{i}": x[:, i] for i in range(4)})
    df["label"] = (x[:, 0] + x[:, 1] > 0).astype(np.int32)
    df["target"] = x.sum(axis=1).astype(np.float32)
    return df


def test_nnestimator_regression(orca_ctx):
    from zoo_tpu.pipeline.nnframes import NNEstimator

    df = _frame()
    m = Sequential()
    m.add(Dense(16, input_shape=(4,), activation="relu"))
    m.add(Dense(1))
    est = (NNEstimator(m, "mse",
                       features_col=["f0", "f1", "f2", "f3"],
                       label_col="target")
           .setBatchSize(32).setMaxEpoch(5).setLearningRate(0.01))
    nn_model = est.fit(df)
    out = nn_model.transform(df)
    assert "prediction" in out.columns
    mse = float(np.mean((out["prediction"] - df["target"]) ** 2))
    assert mse < df["target"].var()  # better than predicting the mean


def test_nnclassifier_and_xshards(orca_ctx):
    from zoo_tpu.orca.data.shard import LocalXShards
    from zoo_tpu.pipeline.nnframes import NNClassifier

    df = _frame()
    m = Sequential()
    m.add(Dense(16, input_shape=(4,), activation="relu"))
    m.add(Dense(2, activation="softmax"))
    clf = (NNClassifier(m, features_col=["f0", "f1", "f2", "f3"],
                        label_col="label")
           .setBatchSize(32).setMaxEpoch(6).setLearningRate(0.01))
    model = clf.fit(df)
    out = model.transform(df)
    acc = float(np.mean(out["prediction"] == df["label"]))
    assert acc > 0.8
    # transformer maps over shards too
    shards = LocalXShards.partition(df, num_shards=3)
    out_shards = model.transform(shards)
    got = pd.concat(out_shards.collect(), ignore_index=True)
    assert "prediction" in got.columns and len(got) == len(df)


def test_nn_image_reader_pipeline(orca_ctx, tmp_path):
    """NNImageReader.readImages -> sample_preprocessing chain ->
    NNClassifier fit/transform (the reference's image transfer-learning
    NNFrames flow, ``nn_image_reader.py:25`` + ``RowToImageFeature``)."""
    import cv2

    from zoo_tpu.feature.common import ChainedPreprocessing
    from zoo_tpu.feature.image import (
        ImageChannelNormalize,
        ImageMatToTensor,
        ImageResize,
    )
    from zoo_tpu.pipeline.api.keras.layers import Convolution2D, Flatten
    from zoo_tpu.pipeline.nnframes import (
        NNClassifier,
        NNImageReader,
        RowToImageFeature,
    )

    rs = np.random.RandomState(0)
    for cls, tint in (("red", (40, 40, 200)), ("blue", (200, 40, 40))):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(8):
            img = (rs.rand(12, 14, 3) * 50 + np.asarray(tint)
                   ).astype(np.uint8)
            cv2.imwrite(str(d / f"{i}.png"), img)

    df = NNImageReader.readImages(str(tmp_path / "imgs"))
    assert set(df.columns) >= {"image", "origin", "label"}
    assert len(df) == 16
    assert df.attrs["label_map"] == {"blue": 0, "red": 1}
    assert df["image"].iloc[0].shape == (12, 14, 3)

    chain = ChainedPreprocessing([
        RowToImageFeature(),
        ImageResize(8, 8),
        ImageChannelNormalize(127.5, 127.5, 127.5, 127.5, 127.5, 127.5),
        ImageMatToTensor(format="NHWC"),
    ])
    model = Sequential()
    model.add(Convolution2D(4, 3, 3, activation="relu",
                            dim_ordering="tf", input_shape=(8, 8, 3)))
    model.add(Flatten())
    model.add(Dense(2, activation="softmax"))
    clf = (NNClassifier(model, features_col="image")
           .setSamplePreprocessing(chain)
           .setBatchSize(8).setMaxEpoch(12).setLearningRate(0.01))
    nn_model = clf.fit(df)

    out = nn_model.transform(df)
    acc = float((out["prediction"].to_numpy()
                 == df["label"].to_numpy()).mean())
    assert acc >= 0.8, acc


def test_nn_image_reader_flat_dir_with_stray_subdir(tmp_path):
    """A flat image dir containing a junk subdir (.ipynb_checkpoints)
    must stay in flat mode, not flip into (empty) labeled mode."""
    import cv2

    from zoo_tpu.pipeline.nnframes import NNImageReader

    d = tmp_path / "flat"
    (d / ".ipynb_checkpoints").mkdir(parents=True)
    rs = np.random.RandomState(0)
    for i in range(3):
        cv2.imwrite(str(d / f"{i}.png"),
                    (rs.rand(6, 6, 3) * 255).astype(np.uint8))
    df = NNImageReader.readImages(str(d))
    assert len(df) == 3
    assert "label" not in df.columns


def test_xgboost_regressor_and_classifier():
    from zoo_tpu.orca.automl.xgboost import (
        XGBoostClassifier,
        XGBoostRegressor,
    )

    rs = np.random.RandomState(0)
    x = rs.randn(400, 5)
    y_reg = x[:, 0] * 2 + x[:, 1] - x[:, 2] + 0.1 * rs.randn(400)
    reg = XGBoostRegressor(n_estimators=50).fit(x[:300], y_reg[:300])
    res = reg.evaluate(x[300:], y_reg[300:], metrics=("mse", "mae"))
    assert res["mse"] < np.var(y_reg)

    y_clf = (x[:, 0] + x[:, 1] > 0).astype(int)
    clf = XGBoostClassifier(n_estimators=50).fit(x[:300], y_clf[:300])
    res = clf.evaluate(x[300:], y_clf[300:], metrics=("accuracy",))
    assert res["accuracy"] > 0.85


def test_auto_xgboost():
    from zoo_tpu.automl import hp
    from zoo_tpu.orca.automl.xgboost import AutoXGBoost

    rs = np.random.RandomState(1)
    x = rs.randn(300, 4)
    y = x[:, 0] - 2 * x[:, 1] + 0.05 * rs.randn(300)
    auto = AutoXGBoost(task="regression", n_parallel=2)
    auto.fit((x[:200], y[:200]), validation_data=(x[200:], y[200:]),
             search_space={"n_estimators": hp.grid_search([30, 60]),
                           "max_depth": hp.choice([3, 5])},
             n_sampling=1)
    assert auto.best_config is not None
    pred = auto.predict(x[200:])
    assert float(np.mean((pred - y[200:]) ** 2)) < np.var(y)


def test_gan_estimator(orca_ctx):
    from zoo_tpu.orca.learn.gan import GANEstimator
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    rs = np.random.RandomState(0)
    # 2-D ring-ish real distribution
    theta = rs.rand(512) * 2 * np.pi
    real = np.stack([np.cos(theta), np.sin(theta)], 1).astype(np.float32)
    real += 0.05 * rs.randn(512, 2).astype(np.float32)

    g = Sequential()
    g.add(Dense(32, input_shape=(8,), activation="relu"))
    g.add(Dense(2))
    d = Sequential()
    d.add(Dense(32, input_shape=(2,), activation="relu"))
    d.add(Dense(1))

    gan = GANEstimator(g, d, g_optimizer=Adam(lr=1e-3),
                       d_optimizer=Adam(lr=1e-3), noise_dim=8)
    hist = gan.fit(real, epochs=5, batch_size=64)
    assert len(hist["d_loss"]) == 5
    assert all(np.isfinite(v) for v in hist["d_loss"] + hist["g_loss"])
    samples = gan.generate(64)
    assert samples.shape == (64, 2)
    # generated radius should move toward the unit ring (~1.0)
    r = np.linalg.norm(samples, axis=1).mean()
    assert 0.3 < r < 2.5


def test_streaming_evaluate_matches_direct(orca_ctx):
    """The streaming evaluate must be EXACT (same numbers as a full-batch
    computation), including the ragged final batch."""
    rs = np.random.RandomState(0)
    x = rs.randn(203, 6).astype(np.float32)  # deliberately ragged vs 64
    y = (x[:, 0] > 0).astype(np.int32)
    m = Sequential()
    m.add(Dense(8, input_shape=(6,), activation="relu"))
    m.add(Dense(2, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=32, nb_epoch=2, verbose=0)
    res = m.evaluate(x, y, batch_size=64)
    # direct full-batch reference
    import jax.numpy as jnp

    preds = m.predict(x, batch_size=256)
    ref_loss = float(m.loss_fn(jnp.asarray(y), jnp.asarray(preds)))
    ref_acc = float(np.mean(np.argmax(preds, -1) == y))
    assert abs(res["loss"] - ref_loss) < 1e-5
    assert abs(res["accuracy"] - ref_acc) < 1e-6
