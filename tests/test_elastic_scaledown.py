"""Supervisor-level scale-down restart (VERDICT r4 missing #4).

A permanent worker loss (restart budget exhausted) must not kill the
job: the supervisor relaunches the remaining workers as a SMALLER mesh
and training resumes from the latest checkpoint with loss continuity —
the reference's within-job retry (``Topology.scala:1255-1337``) lifted
to the supervisor, plus the re-mesh the reference cannot do.
"""

import os
import socket
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # real multi-process jax clusters

_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from zoo_tpu.orca import init_orca_context, stop_orca_context
init_orca_context(cluster_mode="tpu")
world, pid = jax.process_count(), jax.process_index()
attempt = int(os.environ.get("ZOO_ELASTIC_ATTEMPT", "0"))
model_dir = sys.argv[1]

from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense

rs = np.random.RandomState(0)
x = rs.randn(192, 8).astype(np.float32)
w = rs.randn(8, 1).astype(np.float32)
y = (x @ w).astype(np.float32)

m = Sequential()
m.add(Dense(16, input_shape=(8,), activation="relu"))
m.add(Dense(1))
m.compile(optimizer="adam", loss="mse")
# only rank 0 owns the checkpoint dir (DP params are replicated);
# every rank READS it on resume
est = Estimator.from_keras(m, model_dir=model_dir if pid == 0 else None)
if attempt > 0:
    est.load_orca_checkpoint(path=model_dir)
    print(f"proc {pid} RESUMED world={world} at epoch {est._epoch}",
          flush=True)

TOTAL = 4
while est._epoch < TOTAL:
    h = est.fit({"x": x, "y": y}, epochs=1, batch_size=24)
    if pid == 0:
        print(f"EPOCH {est._epoch} world={world} "
              f"loss={h['loss'][-1]:.6f}", flush=True)
    if world == 3 and pid == 2 and est._epoch >= 2:
        os._exit(1)  # permanent loss of one host, mid-job
print(f"proc {pid} DONE world={world} epoch={est._epoch}", flush=True)
stop_orca_context()
"""


@pytest.mark.timeout(480)
def test_scale_down_resumes_on_smaller_mesh(tmp_path):
    from zoo_tpu.orca.bootstrap import run_elastic

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    model_dir = tmp_path / "model"
    log_dir = tmp_path / "logs"
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.getcwd() + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
        # 1-core dev box: let the relaunched (and sibling) workers reuse
        # compiled programs instead of re-tracing from scratch
        "JAX_COMPILATION_CACHE_DIR": str(tmp_path / "jaxcache"),
    }
    final_world = run_elastic(
        3, str(script), [str(model_dir)], min_workers=2,
        max_restarts=0, log_dir=str(log_dir), env=env,
        wait_timeout=420)
    assert final_world == 2

    logs = ""
    for f in sorted(log_dir.glob("*.log")):
        logs += f.read_text()
    # the relaunched run resumed from the checkpoint, not from scratch
    assert "RESUMED world=2" in logs
    import re
    resumed = re.search(r"RESUMED world=2 at epoch (\d+)", logs)
    assert resumed and int(resumed.group(1)) >= 1
    # every surviving rank completed the full epoch budget on 3 workers
    done = re.findall(r"proc \d+ DONE world=2 epoch=4", logs)
    assert len(done) == 2, logs[-2000:]
    # loss continuity: the epochs trained after the re-mesh continue
    # below the first epoch's loss (no restart-from-scratch jump)
    losses = {int(m.group(1)): float(m.group(2)) for m in
              re.finditer(r"EPOCH (\d+) world=\d+ loss=([0-9.]+)", logs)}
    assert set(losses) == set(range(1, 5)), sorted(losses)
    assert losses[4] < losses[1], losses
