import threading

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.inference import InferenceModel


def _trained_model(orca_ctx):
    rs = np.random.RandomState(0)
    x = rs.randn(128, 4).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32).reshape(-1, 1)
    m = Sequential()
    m.add(Dense(8, activation="relu", input_shape=(4,)))
    m.add(Dense(1, activation="sigmoid"))
    m.compile(optimizer="adam", loss="binary_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=1, verbose=0)
    return m, x


def test_full_model_save_load(orca_ctx, tmp_path):
    m, x = _trained_model(orca_ctx)
    ref = m.predict(x[:16])
    p = str(tmp_path / "model.zoo")
    m.save(p)
    from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
    m2 = KerasNet.load(p)
    np.testing.assert_allclose(m2.predict(x[:16]), ref, rtol=1e-5)
    # loaded model can continue training
    m2.compile(optimizer="adam", loss="binary_crossentropy")
    hist = m2.fit(x[:64], (x[:64].sum(1) > 0).astype(np.float32).reshape(-1, 1),
                  batch_size=32, nb_epoch=1, verbose=0)
    assert np.isfinite(hist["loss"][0])


def test_inference_model_pool(orca_ctx, tmp_path):
    m, x = _trained_model(orca_ctx)
    p = str(tmp_path / "model.zoo")
    m.save(p)
    inf = InferenceModel(supported_concurrent_num=2)
    inf.load(p, batch_size=16)
    ref = inf.predict(x[:16])
    assert ref.shape == (16, 1)

    # concurrent predicts from several threads all succeed
    results = {}
    def work(i):
        results[i] = inf.predict(x[i * 8:(i + 1) * 8])
    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(v.shape == (8, 1) for v in results.values())


def test_inference_model_from_torch(orca_ctx):
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    inf = InferenceModel().load_torch(net, input_shape=(4,), batch_size=8)
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    preds = inf.predict(x)
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(preds, ref, atol=1e-5)


def test_serving_end_to_end(orca_ctx):
    from zoo_tpu.serving import TCPInputQueue as InputQueue, TCPOutputQueue as OutputQueue, ServingServer

    m, x = _trained_model(orca_ctx)
    inf = InferenceModel(supported_concurrent_num=2).load_keras(
        m, batch_size=8)
    server = ServingServer(inf, port=0, batch_size=8,
                           max_wait_ms=10).start()
    try:
        iq = InputQueue(host=server.host, port=server.port)
        # sync batch predict
        preds = iq.predict(x[:12])
        np.testing.assert_allclose(preds, m.predict(x[:12]), atol=1e-5)

        # record-style enqueue + query
        iq.enqueue("req-1", t=x[0])
        out = OutputQueue(iq).query("req-1")
        assert out.shape == (1, 1)

        # concurrent clients hit the micro-batcher
        def client(i, results):
            c = InputQueue(host=server.host, port=server.port)
            results[i] = c.predict(x[i * 4:(i + 1) * 4])
            c.close()

        results = {}
        threads = [threading.Thread(target=client, args=(i, results))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            np.testing.assert_allclose(
                results[i], m.predict(x[i * 4:(i + 1) * 4]), atol=1e-5)

        stats = iq.stats()
        assert stats["inference"]["count"] >= 1
        iq.close()
    finally:
        server.stop()


def test_tcp_door_rejects_pickle_and_survives(orca_ctx):
    """Security contract (docs/serving.md): the TCP door never executes
    wire bytes. A pickle payload is dropped without unpickling, and the
    server keeps serving legitimate clients afterwards."""
    import pickle
    import socket
    import struct

    from zoo_tpu.serving import ServingServer, TCPInputQueue

    m, x = _trained_model(orca_ctx)
    inf = InferenceModel().load_keras(m, batch_size=8)
    server = ServingServer(inf, port=0, batch_size=8,
                           max_wait_ms=5).start()
    try:
        fired = []

        class Bomb:
            def __reduce__(self):
                return (fired.append, ("boom",))

        payload = pickle.dumps({"op": "predict", "uri": "u",
                                "data": Bomb()})
        with socket.create_connection((server.host, server.port),
                                      timeout=10) as s:
            s.sendall(struct.pack(">I", len(payload)) + payload)
            # server drops the connection instead of unpickling
            assert s.recv(1) == b""
        assert fired == []  # the payload never executed

        # and the server is still alive for a real client
        iq = TCPInputQueue(host=server.host, port=server.port)
        preds = iq.predict(x[:4])
        assert preds.shape[0] == 4
    finally:
        server.stop()


def test_serving_codec_roundtrip_types():
    from zoo_tpu.serving.codec import dumps, loads

    msg = {"op": "predict", "uri": "a/b", "n": 3, "f": 1.5, "ok": True,
           "none": None,
           "arr": np.arange(6, dtype=np.float32).reshape(2, 3),
           "nested": [{"t": (1, 2, np.ones(2, np.int64))}]}
    out = loads(dumps(msg))
    assert out["op"] == "predict" and out["n"] == 3 and out["none"] is None
    np.testing.assert_array_equal(out["arr"], msg["arr"])
    assert isinstance(out["nested"][0]["t"], tuple)
    np.testing.assert_array_equal(out["nested"][0]["t"][2],
                                  np.ones(2, np.int64))
    import pytest

    with pytest.raises(TypeError):
        dumps({"bad": object()})
    with pytest.raises(TypeError):
        dumps({"strs": np.array(["a", "b"])})


def test_serving_codec_rejects_malformed_frames():
    import json
    import struct

    import pytest

    from zoo_tpu.serving.codec import dumps, loads

    def frame(head: dict, body: bytes = b"") -> bytes:
        h = json.dumps(head).encode()
        return b"ZSRV" + struct.pack(">I", len(h)) + h + body

    good = dumps({"arr": np.arange(4, dtype=np.float32)})
    loads(good)  # sanity

    cases = [
        good[:6],                                     # truncated header
        frame({"tree": {"__nd__": 5, "dtype": "<f4", "shape": [1]},
               "bufs": [4]}, b"\x00" * 4),            # out-of-range index
        frame({"tree": None, "bufs": [64]}, b"\x00" * 4),  # over-length buf
        frame({"tree": {"__nd__": 0, "dtype": "<f4", "shape": [9]},
               "bufs": [4]}, b"\x00" * 4),            # shape > buffer
        frame({"bufs": []}),                          # missing tree
        b"ZSRV" + struct.pack(">I", 99) + b"{}",      # header past frame
        frame({"tree": {"__nd__": 0, "shape": [1]},
               "bufs": [4]}, b"\x00" * 4),            # missing dtype key
        frame({"tree": {"__nd__": 0, "dtype": "<U1", "shape": [1]},
               "bufs": [4]}, b"\x00" * 4),            # non-numeric dtype
    ]
    for blob in cases:
        with pytest.raises(ValueError):
            loads(blob)


def test_llama_remat_typo_rejected():
    import pytest

    from zoo_tpu.models.llm.llama import Llama

    with pytest.raises(ValueError, match="remat"):
        Llama(remat="dot")


def test_serving_replica_pool_overlaps(orca_ctx):
    """num_replicas worker pool behind the one TCP door (the reference's
    Flink task-slot parallelism, ClusterServing.scala:54-67): two slow
    replicas drain the shared queue concurrently — two in-flight
    requests finish in ~one model latency, not two."""
    import threading
    import time as _time

    import numpy as np

    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    class SlowModel:
        calls = []

        def predict(self, x, batch_size=8):
            SlowModel.calls.append(threading.current_thread().name)
            _time.sleep(0.4)
            return np.asarray(x) * 2.0

    server = ServingServer(SlowModel(), port=0, batch_size=1,
                           max_wait_ms=0.0, num_replicas=2).start()
    try:
        outs, lock = [], threading.Lock()

        def one():
            q = TCPInputQueue(server.host, server.port)
            r = q.predict(np.ones((1, 4), np.float32))
            with lock:
                outs.append(np.asarray(r))

        threads = [threading.Thread(target=one) for _ in range(2)]
        t0 = _time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _time.perf_counter() - t0
        assert len(outs) == 2
        for r in outs:
            np.testing.assert_allclose(r, 2.0)
        # two replicas overlap the 0.4s sleeps; a single replica would
        # serialize them (>= 0.8s)
        assert wall < 0.7, wall
        assert len({c for c in SlowModel.calls}) >= 2, SlowModel.calls
    finally:
        server.stop()


def test_serving_over_tls(orca_ctx, tmp_path):
    """Encrypted serving transport (the reference PPML
    trusted-realtime-ml door, ``ppml/trusted-realtime-ml/``): TLS on the
    TCP micro-batcher; a plaintext client is refused, a TLS client round
    trips."""
    import subprocess
    import sys as _sys

    import numpy as np

    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
    gen = subprocess.run(
        [_sys.executable, "-c", """
import datetime
from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID
import sys
k = rsa.generate_private_key(public_exponent=65537, key_size=2048)
name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, u"localhost")])
now = datetime.datetime.utcnow()
import ipaddress
cert = (x509.CertificateBuilder().subject_name(name).issuer_name(name)
        .public_key(k.public_key()).serial_number(1)
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.SubjectAlternativeName([
            x509.DNSName(u"localhost"),
            x509.IPAddress(ipaddress.ip_address(u"127.0.0.1"))]),
            critical=False)
        .sign(k, hashes.SHA256()))
open(sys.argv[1], "wb").write(cert.public_bytes(serialization.Encoding.PEM))
open(sys.argv[2], "wb").write(k.private_bytes(
    serialization.Encoding.PEM, serialization.PrivateFormat.TraditionalOpenSSL,
    serialization.NoEncryption()))
""", str(cert), str(key)], capture_output=True, text=True)
    if gen.returncode != 0:
        pytest.skip(f"no cryptography package for cert gen: "
                    f"{gen.stderr[-200:]}")

    class Echo:
        def predict(self, x, batch_size=8):
            return np.asarray(x) + 1.0

    server = ServingServer(Echo(), port=0, batch_size=4,
                           certfile=str(cert), keyfile=str(key)).start()
    try:
        # the AUTHENTICATED path: verify (default True) against the
        # self-signed cert as the CA — SAN covers 127.0.0.1
        q = TCPInputQueue(server.host, server.port, tls=True,
                          cafile=str(cert))
        out = q.predict(np.zeros((2, 3), np.float32))
        np.testing.assert_allclose(out, 1.0)
        # the dev-only opt-out (encryption without authentication)
        q3 = TCPInputQueue(server.host, server.port, tls=True,
                           verify=False)
        np.testing.assert_allclose(
            q3.predict(np.zeros((1, 3), np.float32)), 1.0)
        # plaintext client against the TLS door fails, never half-works
        with pytest.raises(Exception):
            q2 = TCPInputQueue(server.host, server.port)
            q2.predict(np.zeros((1, 3), np.float32))
    finally:
        server.stop()


class _ShapeRecordingModel:
    """Fake InferenceModel: records every batch row-count it was asked
    to run and returns row-identified outputs (catches padding leaks)."""

    def __init__(self, delay: float = 0.0):
        self.calls = []
        self.delay = delay
        self._lock = threading.Lock()

    def predict(self, x, batch_size=None):
        x = np.asarray(x)
        with self._lock:
            self.calls.append(x.shape[0])
        if self.delay:
            import time
            time.sleep(self.delay)
        return x * 2.0


def test_serving_pads_to_one_executable_shape():
    """The micro-batcher pads every inference batch UP to a whole
    multiple of batch_size: one compiled shape serves every occupancy
    (the bs8 p99 pathology was a fresh XLA compile per distinct
    occupancy), and padded rows never leak into responses."""
    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    model = _ShapeRecordingModel()
    server = ServingServer(model, port=0, batch_size=8,
                           max_wait_ms=1.0).start()
    try:
        results = {}
        lock = threading.Lock()

        def client(k):
            q = TCPInputQueue(server.host, server.port)
            for i in range(10):
                x = np.full((1, 4), 10.0 * k + i, np.float32)
                out = np.asarray(q.predict(x))
                with lock:
                    results[(k, i)] = (x, out)
            q.close()

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # every inference ran at the ONE padded shape
        assert model.calls and all(c == 8 for c in model.calls), \
            sorted(set(model.calls))
        # responses are per-request exact — no padded-row leakage
        for (k, i), (x, out) in results.items():
            assert out.shape == x.shape, (k, i)
            np.testing.assert_allclose(out, x * 2.0)
    finally:
        server.stop()


def test_serving_tail_latency_sane_under_concurrency():
    """Regression for the bs8 pathology (serving_bs8_p99_ms = 8643 vs
    110 at bs32): with the fixed-shape batcher, p99 under concurrent
    clients stays within a sane multiple of p50 — no multi-second
    stragglers."""
    import time

    from zoo_tpu.serving.server import ServingServer
    from zoo_tpu.serving.tcp_client import TCPInputQueue

    model = _ShapeRecordingModel(delay=0.002)
    server = ServingServer(model, port=0, batch_size=8,
                           max_wait_ms=1.0, num_replicas=2).start()
    try:
        # warm the whole path before timing (connection setup etc.)
        TCPInputQueue(server.host, server.port).predict(
            np.zeros((1, 4), np.float32))
        lats, lock = [], threading.Lock()

        def client(k):
            q = TCPInputQueue(server.host, server.port)
            mine = []
            for _ in range(25):
                t0 = time.perf_counter()
                q.predict(np.zeros((1, 4), np.float32))
                mine.append(time.perf_counter() - t0)
            with lock:
                lats.extend(mine)
            q.close()

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lats_ms = np.sort(np.asarray(lats)) * 1e3
        p50 = float(np.percentile(lats_ms, 50))
        p99 = float(np.percentile(lats_ms, 99))
        # generous CI bounds; the pre-fix pathology was ~80x p50 and
        # multi-SECOND absolute
        assert p99 < 1000.0, f"p99 {p99:.0f}ms is a multi-second tail"
        assert p99 <= max(30.0 * p50, 250.0), (p50, p99)
    finally:
        server.stop()
