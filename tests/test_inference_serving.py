import threading

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.pipeline.inference import InferenceModel


def _trained_model(orca_ctx):
    rs = np.random.RandomState(0)
    x = rs.randn(128, 4).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32).reshape(-1, 1)
    m = Sequential()
    m.add(Dense(8, activation="relu", input_shape=(4,)))
    m.add(Dense(1, activation="sigmoid"))
    m.compile(optimizer="adam", loss="binary_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=1, verbose=0)
    return m, x


def test_full_model_save_load(orca_ctx, tmp_path):
    m, x = _trained_model(orca_ctx)
    ref = m.predict(x[:16])
    p = str(tmp_path / "model.zoo")
    m.save(p)
    from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
    m2 = KerasNet.load(p)
    np.testing.assert_allclose(m2.predict(x[:16]), ref, rtol=1e-5)
    # loaded model can continue training
    m2.compile(optimizer="adam", loss="binary_crossentropy")
    hist = m2.fit(x[:64], (x[:64].sum(1) > 0).astype(np.float32).reshape(-1, 1),
                  batch_size=32, nb_epoch=1, verbose=0)
    assert np.isfinite(hist["loss"][0])


def test_inference_model_pool(orca_ctx, tmp_path):
    m, x = _trained_model(orca_ctx)
    p = str(tmp_path / "model.zoo")
    m.save(p)
    inf = InferenceModel(supported_concurrent_num=2)
    inf.load(p, batch_size=16)
    ref = inf.predict(x[:16])
    assert ref.shape == (16, 1)

    # concurrent predicts from several threads all succeed
    results = {}
    def work(i):
        results[i] = inf.predict(x[i * 8:(i + 1) * 8])
    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(v.shape == (8, 1) for v in results.values())


def test_inference_model_from_torch(orca_ctx):
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    inf = InferenceModel().load_torch(net, input_shape=(4,), batch_size=8)
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    preds = inf.predict(x)
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(preds, ref, atol=1e-5)


def test_serving_end_to_end(orca_ctx):
    from zoo_tpu.serving import TCPInputQueue as InputQueue, TCPOutputQueue as OutputQueue, ServingServer

    m, x = _trained_model(orca_ctx)
    inf = InferenceModel(supported_concurrent_num=2).load_keras(
        m, batch_size=8)
    server = ServingServer(inf, port=0, batch_size=8,
                           max_wait_ms=10).start()
    try:
        iq = InputQueue(host=server.host, port=server.port)
        # sync batch predict
        preds = iq.predict(x[:12])
        np.testing.assert_allclose(preds, m.predict(x[:12]), atol=1e-5)

        # record-style enqueue + query
        iq.enqueue("req-1", t=x[0])
        out = OutputQueue(iq).query("req-1")
        assert out.shape == (1, 1)

        # concurrent clients hit the micro-batcher
        def client(i, results):
            c = InputQueue(host=server.host, port=server.port)
            results[i] = c.predict(x[i * 4:(i + 1) * 4])
            c.close()

        results = {}
        threads = [threading.Thread(target=client, args=(i, results))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            np.testing.assert_allclose(
                results[i], m.predict(x[i * 4:(i + 1) * 4]), atol=1e-5)

        stats = iq.stats()
        assert stats["inference"]["count"] >= 1
        iq.close()
    finally:
        server.stop()
