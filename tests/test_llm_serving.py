"""LLM serving engine (docs/llm_serving.md): the paged KV block
allocator, the iteration-level (continuous) scheduler, the
prefill/decode split's correctness against the full-context Llama
reference, and the streaming generate op over the real TCP door with
HA failover-resume.

The allocator and scheduler tests run against pure-python fakes (no
jax), so most of this file is tier-1 cheap; the paged-model and wire
tests share ONE tiny compiled model via a module fixture. The 2-replica
SIGKILL smoke (scripts/check_llm_serving.py) runs as a subprocess under
the ``chaos`` marker like its serving-HA sibling.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from zoo_tpu.serving.llm.engine import AdmissionError, LLMEngine
from zoo_tpu.serving.llm.kv_cache import BlockAllocator
from zoo_tpu.serving.llm.spec import parse_llm_spec
from zoo_tpu.util.resilience import Deadline


# ------------------------------------------------------- block allocator

def test_allocator_alloc_free_reuse():
    a = BlockAllocator(num_blocks=8, block_size=4)
    assert a.free_blocks == 7  # block 0 is the reserved trash block
    got = a.allocate("s1", 3)
    assert len(got) == 3 and 0 not in got
    assert a.used_blocks == 3 and a.free_blocks == 4
    assert a.blocks_of("s1") == got
    assert a.free("s1") == 3
    assert a.used_blocks == 0 and a.free_blocks == 7
    # LIFO: the just-freed blocks come back first (warm reuse), in the
    # same order the sequence held them
    again = a.allocate("s2", 3)
    assert again == got


def test_allocator_never_hands_out_block_zero():
    a = BlockAllocator(num_blocks=6, block_size=2)
    got = a.allocate("s", 5)
    assert sorted(got) == [1, 2, 3, 4, 5]
    assert a.allocate("s2", 1) is None   # block 0 is never handed out


def test_allocator_all_or_nothing():
    a = BlockAllocator(num_blocks=5, block_size=4)  # 4 usable
    assert a.allocate("s1", 3) is not None
    # asking for more than the free list holds changes NOTHING
    assert a.allocate("s2", 2) is None
    assert a.used_blocks == 3 and a.free_blocks == 1
    assert a.blocks_of("s2") == []


def test_allocator_block_table_growth():
    a = BlockAllocator(num_blocks=10, block_size=2)
    first = a.allocate("s", a.blocks_for_tokens(3))   # 3 tokens -> 2
    assert len(first) == 2
    # crossing each block boundary appends to the SAME table, order
    # preserved (the block table is positional: row i covers tokens
    # [i*bs, (i+1)*bs) )
    for _ in range(3):
        assert a.allocate("s", 1) is not None
    table = a.blocks_of("s")
    assert len(table) == 5 and table[:2] == first


def test_allocator_admission_refusal_when_empty():
    a = BlockAllocator(num_blocks=4, block_size=4)  # 3 usable
    assert a.can_admit(prompt_len=7)   # 2 blocks for 7+1 tokens
    assert a.allocate("hog", 3) is not None
    assert not a.can_admit(prompt_len=1)
    assert a.allocate("late", 1) is None
    a.free("hog")
    assert a.can_admit(prompt_len=7)


def test_allocator_free_is_idempotent():
    a = BlockAllocator(num_blocks=6, block_size=2)
    a.allocate("s", 2)
    assert a.free("s") == 2
    assert a.free("s") == 0          # abort paths may race: no double free
    assert a.free("never-seen") == 0
    assert a.free_blocks == 5


def test_allocator_blocks_for_tokens_math():
    a = BlockAllocator(num_blocks=4, block_size=8)
    assert a.blocks_for_tokens(1) == 1
    assert a.blocks_for_tokens(8) == 1
    assert a.blocks_for_tokens(9) == 2
    assert a.blocks_for_tokens(0) == 1  # a sequence always owns a block


def test_allocator_publishes_gauges():
    from zoo_tpu.obs.metrics import gauge
    used = gauge("zoo_llm_kv_blocks_used")
    free = gauge("zoo_llm_kv_blocks_free")
    a = BlockAllocator(num_blocks=9, block_size=4)
    assert free.value == 8.0 and used.value == 0.0
    a.allocate("s", 5)
    assert used.value == 5.0 and free.value == 3.0
    a.free("s")
    assert used.value == 0.0 and free.value == 8.0


# ------------------------------------------------ scheduler (fake model)

class _FakeModel:
    """Deterministic greedy 'llm' with the PagedLlamaModel surface but
    no jax: the next token is a pure function of (last token, position)
    — ``(2*tok + pos) % 97`` — which makes preemption's
    re-prefill-from-prompt+generated provably seamless, exactly the
    property the real model gets from greedy decode."""

    def __init__(self, num_slots=2, block_size=4, num_blocks=8,
                 max_blocks_per_seq=4, max_prompt_len=12,
                 decode_delay=0.0, eos_id=None):
        self.num_slots = num_slots
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks_per_seq
        self.max_prompt_len = max_prompt_len
        self.decode_delay = decode_delay
        self.eos_id = eos_id
        self.prefills = []

    @staticmethod
    def _next(tok, pos):
        return (2 * int(tok) + int(pos)) % 97

    def prefill(self, prompt, block_table_row):
        self.prefills.append(len(prompt))
        return self._next(prompt[-1], len(prompt))

    def decode(self, tokens, block_tables, positions):
        if self.decode_delay:
            time.sleep(self.decode_delay)
        # ``positions[i]`` is the cache index the incoming token is
        # WRITTEN at, so the sequence is ``position + 1`` tokens long
        # once it lands — the same length prefill sees for the same
        # sequence, which is what makes preemption's re-prefill seamless
        return np.array([self._next(t, p + 1)
                         for t, p in zip(tokens, positions)], np.int32)


def _reference(prompt, n):
    """What any correct schedule must emit for ``prompt``."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        out.append(_FakeModel._next(seq[-1], len(seq)))
        seq.append(out[-1])
    return out


def _drain(handles, budget=20.0):
    deadline = time.monotonic() + budget
    while not all(h.done for h in handles):
        if time.monotonic() > deadline:
            raise AssertionError(
                f"streams stuck: {[h.outcome for h in handles]}")
        time.sleep(0.005)


def test_engine_continuous_more_streams_than_slots():
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=32,
                               max_blocks_per_seq=8)).start()
    try:
        prompts = [[3, 5], [7], [1, 2, 3], [9, 9], [4], [8, 1]]
        hs = [eng.submit(p, 5) for p in prompts]
        _drain(hs)
        for p, h in zip(prompts, hs):
            assert h.outcome == "ok"
            assert h.tokens == _reference(p, 5)
        assert eng.allocator.used_blocks == 0
        assert eng.allocator.live_sequences() == 0
    finally:
        eng.stop()


def test_engine_continuous_admits_into_freed_slots_midflight():
    """The Orca property itself: with 1 slot and bimodal lengths, a
    short stream admitted behind a long one starts as soon as ANY slot
    frees — i.e. the long stream is still running when the short one
    finishes (request-level batching would serialize whole waves)."""
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=64,
                               max_blocks_per_seq=8,
                               decode_delay=0.002)).start()
    try:
        long_h = eng.submit([1], 25)
        short = [eng.submit([2 + i], 2) for i in range(3)]
        _drain(short)
        assert not long_h.done, \
            "short streams should finish while the long one decodes"
        _drain([long_h])
        assert long_h.tokens == _reference([1], 25)
    finally:
        eng.stop()


def test_engine_oneshot_waits_for_batch_to_drain():
    """The request-level baseline the bench compares against: a wave is
    admitted only on an EMPTY batch, so a late request waits for every
    member of the running wave."""
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=64,
                               max_blocks_per_seq=8), mode="oneshot")
    # white-box: tick the scheduler by hand for determinism
    h1 = eng.submit([1], 4)
    h2 = eng.submit([2], 4)
    h3 = eng.submit([3], 2)   # wave 2
    for _ in range(3):
        eng._sweep(); eng._admit(); eng._grow_or_preempt()
        eng._decode_tick()
    assert h1.done and h2.done
    assert not h3.tokens, "oneshot admitted into a non-empty batch"
    for _ in range(2):
        eng._sweep(); eng._admit(); eng._grow_or_preempt()
        eng._decode_tick()
    assert h3.done and h3.tokens == _reference([3], 2)
    eng.stop()


def test_engine_deadline_dead_in_queue():
    eng = LLMEngine(_FakeModel()).start()
    try:
        h = eng.submit([1, 2], 4, deadline=Deadline.from_ms(0.0))
        _drain([h])
        assert h.outcome == "expired" and h.tokens == []
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_engine_deadline_expires_midstream_and_frees_blocks():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    try:
        h = eng.submit([5], 10_000, deadline=Deadline.from_ms(120.0))
        _drain([h], budget=10.0)
        assert h.outcome == "expired"
        assert 0 < len(h.tokens) < 10_000
        assert h.tokens == _reference([5], len(h.tokens))
        deadline = time.monotonic() + 5
        while eng.allocator.used_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.allocator.used_blocks == 0, "expiry leaked KV blocks"
    finally:
        eng.stop()


def test_engine_cancel_frees_blocks():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    try:
        h = eng.submit([5, 6], 10_000)
        while not h.tokens:
            time.sleep(0.005)
        assert eng.cancel(h.id)
        _drain([h])
        assert h.outcome == "cancelled"
        deadline = time.monotonic() + 5
        while eng.allocator.used_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.allocator.used_blocks == 0, "abort leaked KV blocks"
        assert not eng.cancel(h.id)   # already finished: no-op
    finally:
        eng.stop()


def test_engine_admission_sheds_when_waiting_queue_full():
    eng = LLMEngine(_FakeModel(num_slots=1, decode_delay=0.01),
                    max_waiting=2).start()
    try:
        running = eng.submit([1], 1000)
        while not running.tokens:
            time.sleep(0.005)
        eng.submit([2], 4)
        eng.submit([3], 4)
        with pytest.raises(AdmissionError) as ei:
            eng.submit([4], 4)
        assert ei.value.retry_after_ms > 0
    finally:
        eng.stop()


def test_engine_duplicate_rid_joins_stream():
    eng = LLMEngine(_FakeModel()).start()
    try:
        h1 = eng.submit([3, 4], 4, rid="r-1")
        h2 = eng.submit([9, 9, 9], 999, rid="r-1")  # args ignored: join
        assert h2 is h1
        _drain([h1])
        assert h1.tokens == _reference([3, 4], 4)
    finally:
        eng.stop()


def test_engine_prompt_too_long_and_empty_rejected():
    eng = LLMEngine(_FakeModel(max_prompt_len=8))
    with pytest.raises(ValueError):
        eng.submit(list(range(9)), 4)
    with pytest.raises(ValueError):
        eng.submit([], 4)
    with pytest.raises(ValueError):
        eng.submit([1], 0)
    eng.stop()


def test_engine_preempts_youngest_and_resumes_exactly():
    """KV pressure: two long streams on a pool that cannot hold both to
    completion. The youngest-admitted one is evicted (blocks freed,
    re-queued) and later RE-PREFILLED from prompt+generated; because
    decode is deterministic its final token stream is byte-identical to
    an uncontended run."""
    # 6 usable blocks, bs=2: each stream needs 1 block per 2 tokens;
    # two 12-token streams want 2x6 > 6 -> somebody must be preempted.
    # White-box manual ticks (engine not started): both streams are
    # admitted in the SAME tick, so concurrent growth — and therefore
    # the preemption — is deterministic, not a thread-timing accident.
    model = _FakeModel(num_slots=2, block_size=2, num_blocks=7,
                       max_blocks_per_seq=6, max_prompt_len=8)
    eng = LLMEngine(model)
    from zoo_tpu.obs.metrics import counter
    preempts0 = counter("zoo_llm_preempt_total").value
    a = eng.submit([1, 2], 9)
    b = eng.submit([3, 4], 9)
    for _ in range(60):
        eng._sweep(); eng._admit(); eng._grow_or_preempt()
        eng._decode_tick()
        if a.done and b.done:
            break
    assert a.outcome == "ok" and b.outcome == "ok"
    assert a.tokens == _reference([1, 2], 9)
    assert b.tokens == _reference([3, 4], 9)
    assert counter("zoo_llm_preempt_total").value > preempts0
    # the victim was re-prefilled with its context so far
    assert max(model.prefills) > 4
    assert eng.allocator.used_blocks == 0
    eng.stop()


def test_engine_rejects_prompt_larger_than_whole_pool():
    """A prompt whose blocks can NEVER be satisfied (bigger than the
    entire pool) must be rejected at submit — not parked at the head of
    the waiting queue forever, wedging everything behind it."""
    model = _FakeModel(num_slots=1, block_size=2, num_blocks=4,
                       max_blocks_per_seq=16, max_prompt_len=64)
    eng = LLMEngine(model).start()
    try:
        with pytest.raises(ValueError, match="whole pool"):
            eng.submit(list(range(20)), 4)   # 11 blocks > 3 usable
        # feasible traffic still flows
        h = eng.submit([1, 2], 2)
        _drain([h])
        assert h.outcome == "ok"
    finally:
        eng.stop()


def test_engine_sole_stream_out_of_pool_errors():
    """A stream that cannot grow and has no preemption victim must end
    loudly (error outcome), not wedge the scheduler."""
    model = _FakeModel(num_slots=1, block_size=2, num_blocks=3,
                       max_blocks_per_seq=16, max_prompt_len=3)
    eng = LLMEngine(model).start()
    try:
        h = eng.submit([1], 50)   # needs 25 blocks, pool holds 2
        _drain([h])
        assert h.outcome == "error"
        assert "kv cache exhausted" in h.error
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_engine_context_ceiling_truncates_ok():
    model = _FakeModel(num_slots=1, block_size=2, num_blocks=32,
                       max_blocks_per_seq=3, max_prompt_len=4)
    eng = LLMEngine(model).start()
    try:
        h = eng.submit([1, 2], 50)   # table caps context at 6 tokens
        _drain([h])
        assert h.outcome == "ok" and h.truncated
        assert len(h.tokens) < 50
        assert h.tokens == _reference([1, 2], len(h.tokens))
    finally:
        eng.stop()


def test_engine_eos_stops_stream():
    ref = _reference([6], 10)
    eos = ref[3]
    eng = LLMEngine(_FakeModel(eos_id=eos)).start()
    try:
        h = eng.submit([6], 10)
        _drain([h])
        assert h.outcome == "ok"
        assert h.tokens == ref[:4]   # eos token is emitted, then stop
    finally:
        eng.stop()


def test_engine_stop_frees_everything():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    h = eng.submit([1], 10_000)
    while not h.tokens:
        time.sleep(0.005)
    eng.stop()
    assert h.outcome == "cancelled"
    assert eng.allocator.used_blocks == 0


# ------------------------------------------------------------ spec parse

def test_parse_llm_spec_forms():
    cfg, eng = parse_llm_spec("llama:tiny")
    assert cfg["hidden"] == 64 and eng == {}
    cfg, eng = parse_llm_spec(
        "llama:tiny:seed=3,slots=4,block=8,blocks=64,buckets=16/64")
    assert eng == {"seed": 3, "num_slots": 4, "block_size": 8,
                   "num_blocks": 64, "prefill_buckets": (16, 64)}
    cfg, _ = parse_llm_spec(
        "llama:vocab=256,hidden=32,n_block=1,n_head=4,n_kv_head=2,"
        "intermediate=64")
    assert cfg["vocab"] == 256 and cfg["n_kv_head"] == 2
    with pytest.raises(ValueError):
        parse_llm_spec("llama:gguf")
    with pytest.raises(ValueError):
        parse_llm_spec("llama:tiny:slots")
    with pytest.raises(ValueError):
        parse_llm_spec("llama:tiny:warp=9")


# --------------------------------------------- paged model (jax, shared)

@pytest.fixture(scope="module")
def paged():
    """ONE tiny compiled model + its config, shared by every jax test
    in this file (each test runs its own engine; freed blocks are fully
    rewritten by the next owner, so sharing the cache is safe)."""
    from zoo_tpu.models.llm.llama import LlamaConfig
    from zoo_tpu.serving.llm.model import PagedLlamaModel
    cfg = LlamaConfig(vocab=64, hidden=32, n_block=2, n_head=4,
                      n_kv_head=2, intermediate=64, rope_theta=10000.0)
    model = PagedLlamaModel(cfg, seed=0, num_slots=2, block_size=4,
                            num_blocks=24, max_blocks_per_seq=6,
                            prefill_buckets=(8, 16))
    return cfg, model


def test_gqa_cache_layout(paged):
    """K/V are stored at num_kv_heads (2), NOT num_heads (4) — the GQA
    memory saving is real, not re-expanded into the cache."""
    cfg, model = paged
    import jax.numpy as jnp
    assert cfg.n_kv_head < cfg.n_head
    expect = (cfg.n_block, model.num_blocks, model.block_size,
              cfg.n_kv_head, cfg.head_dim)
    assert model._kc.shape == expect
    assert model._vc.shape == expect
    assert model._kc.dtype == jnp.float32


def test_paged_decode_matches_full_context_reference(paged):
    """The correctness anchor: greedy generation through the paged
    prefill + block-gathered decode must match token-for-token a greedy
    loop over the ORIGINAL full-context Llama forward (same params) —
    across a block boundary and a preemption-free multi-stream mix."""
    cfg, model = paged
    import jax.numpy as jnp
    from zoo_tpu.models.llm.llama import Llama

    layer = Llama(cfg, lm_head=True)

    def ref_generate(prompt, n):
        seq = list(int(t) for t in prompt)
        out = []
        for _ in range(n):
            logits = layer.call(model.params,
                                jnp.asarray([seq], jnp.int32))
            out.append(int(jnp.argmax(logits[0, -1])))
            seq.append(out[-1])
        return out

    eng = LLMEngine(model).start()
    try:
        rs = np.random.RandomState(7)
        prompts = [rs.randint(0, cfg.vocab, (n,)) for n in (3, 9, 14)]
        n_new = 9   # crosses the 4-token block boundary repeatedly
        hs = [eng.submit(p, n_new) for p in prompts]
        _drain(hs, budget=300.0)
        for p, h in zip(prompts, hs):
            assert h.outcome == "ok"
            assert h.tokens == ref_generate(p, n_new), \
                f"paged decode diverged for prompt len {len(p)}"
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_decode_compiles_exactly_one_executable(paged):
    """The fixed-shape contract: after streams of every shape mix, the
    decode jit cache holds ONE executable and prefill at most one per
    bucket — request churn must never recompile."""
    cfg, model = paged
    eng = LLMEngine(model).start()
    try:
        rs = np.random.RandomState(3)
        hs = [eng.submit(rs.randint(0, cfg.vocab, (n,)), 3)
              for n in (2, 7, 8, 13)]   # both buckets, varied fill
        _drain(hs, budget=300.0)
    finally:
        eng.stop()
    counts = model.compile_counts()
    if counts["decode"] < 0:
        pytest.skip("jit cache size API unavailable on this jax")
    assert counts["decode"] == 1, counts
    assert 0 < counts["prefill"] <= len(model.prefill_buckets), counts


# ------------------------------------------------- streaming over the wire

@pytest.fixture(scope="module")
def llm_server(paged):
    """The shared model behind a REAL ServingServer TCP door (llm-only
    replica: no predict model mounted)."""
    from zoo_tpu.serving.server import ServingServer
    _, model = paged
    eng = LLMEngine(model)
    server = ServingServer(None, llm_engine=eng.start(), port=0,
                           batch_size=2, max_wait_ms=1.0).start()
    yield server, eng
    server.stop()


def _stream_tokens(host, port, prompt, n, rid=None, resume_from=0,
                   deadline=None):
    from zoo_tpu.serving.tcp_client import _Connection
    conn = _Connection(host, port)
    frames, toks = [], []
    try:
        for f in conn.stream({"op": "generate", "id": rid,
                              "prompt": np.asarray(prompt, np.int32),
                              "max_new_tokens": n,
                              "resume_from": resume_from},
                             deadline=deadline):
            frames.append(f)
            toks.extend(f.get("tokens") or ())
    finally:
        conn.close()
    return toks, frames


def test_generate_streams_over_wire(paged, llm_server):
    cfg, model = paged
    server, eng = llm_server
    prompt = np.arange(1, 6) % cfg.vocab
    toks, frames = _stream_tokens(server.host, server.port, prompt, 6)
    assert len(toks) == 6
    assert frames[-1]["done"] and frames[-1]["outcome"] == "ok"
    assert frames[-1]["n_tokens"] == 6
    # a direct engine replay of the same rid would dedup; a fresh id
    # reproduces the same tokens (deterministic greedy decode)
    again, _ = _stream_tokens(server.host, server.port, prompt, 6)
    assert again == toks


def test_generate_resume_from_skips_prefix(paged, llm_server):
    cfg, _ = paged
    server, _ = llm_server
    prompt = np.arange(2, 8) % cfg.vocab
    full, _ = _stream_tokens(server.host, server.port, prompt, 6)
    suffix, frames = _stream_tokens(server.host, server.port, prompt, 6,
                                    resume_from=4)
    assert suffix == full[4:]
    assert frames[-1]["n_tokens"] == 6   # server-side count is total


def test_generate_dead_on_arrival_deadline(paged, llm_server):
    server, _ = llm_server
    from zoo_tpu.serving.tcp_client import _Connection
    conn = _Connection(server.host, server.port)
    try:
        frames = list(conn.stream({"op": "generate", "prompt": [1, 2],
                                   "max_new_tokens": 4,
                                   "deadline_ms": 0.0}))
    finally:
        conn.close()
    assert frames[-1].get("expired") and frames[-1]["outcome"] == "expired"


def test_generate_client_disconnect_frees_blocks(paged, llm_server):
    """The last subscriber dropping mid-stream cancels the stream and
    returns its KV blocks — an abandoned client must not pin the pool
    until max_new_tokens."""
    from zoo_tpu.serving.tcp_client import _Connection
    server, eng = llm_server
    before = eng.allocator.used_blocks
    conn = _Connection(server.host, server.port)
    it = conn.stream({"op": "generate", "prompt": [3, 1],
                      "max_new_tokens": 100_000})
    first = next(it)
    assert first.get("tokens") or first.get("done") is False
    conn.close()   # walk away mid-stream
    deadline = time.monotonic() + 10
    while eng.allocator.used_blocks > before and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert eng.allocator.used_blocks == before, "disconnect leaked blocks"


def test_ha_client_generate_failover_resumes_midstream(paged):
    """Mid-stream replica loss under HAServingClient.generate: the
    second replica (bit-identical weights, greedy decode) resumes from
    ``resume_from`` and the caller sees one gapless, duplicate-free
    token stream."""
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.server import ServingServer
    cfg, model = paged
    # two engines over the SAME model object = bit-identical weights
    # (they serialize on the model lock, like two processes on one chip)
    eng1, eng2 = LLMEngine(model).start(), LLMEngine(model).start()
    s1 = ServingServer(None, llm_engine=eng1, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    s2 = ServingServer(None, llm_engine=eng2, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    try:
        prompt = (np.arange(5) * 3 + 1) % cfg.vocab
        ref, _ = _stream_tokens(s2.host, s2.port, prompt, 8)
        cli = HAServingClient([(s1.host, s1.port), (s2.host, s2.port)],
                              hedge=False, deadline_ms=120_000)
        got = []
        for tok in cli.generate(prompt, 8):
            got.append(tok)
            if len(got) == 3:
                s1.stop()   # primary dies mid-stream
        assert got == ref, f"failover stream diverged: {got} vs {ref}"
        cli.close()
    finally:
        for srv, eng in ((s1, eng1), (s2, eng2)):
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — s1 already stopped
                pass
        assert eng1.allocator.used_blocks == 0
        assert eng2.allocator.used_blocks == 0


# ------------------------------------------------------------ chaos smoke

@pytest.mark.chaos
def test_check_llm_serving_script_runs():
    """The 2-replica SIGKILL smoke (scripts/check_llm_serving.py): a
    real supervised llama:tiny replica group streams concurrent
    mixed-length generations, loses one replica mid-stream, and the HA
    client contract holds — zero client-visible failures, token streams
    byte-identical to the reference, zero leaked KV blocks."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_llm_serving.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LLM SERVING OK" in proc.stdout


# -------------------------------------------------- tensor-parallel (mesh)

class TestTensorParallel:
    """mesh= support on PagedLlamaModel (docs/multichip.md): one set of
    weights + one paged KV cache span the mesh's model axis. The full
    token-identity acceptance check runs in scripts/check_multichip.py
    (multichip marker); these are the cheap unit guarantees."""

    def test_spec_parses_tp_knob(self):
        _, eng = parse_llm_spec("llama:tiny:tp=2,slots=4")
        assert eng["tp"] == 2 and eng["num_slots"] == 4

    def test_env_tp_knob(self, monkeypatch):
        from zoo_tpu.serving.llm.spec import _env_engine_defaults
        monkeypatch.setenv("ZOO_LLM_TP", "2")
        assert _env_engine_defaults()["tp"] == 2

    def test_kv_head_divisibility_enforced(self):
        """tiny config has n_kv_head=2: tp=3 cannot shard the KV cache
        on the heads axis and must refuse loudly at construction (not
        at first decode)."""
        import jax

        from zoo_tpu.models.llm.llama import tiny_llama_config
        from zoo_tpu.parallel import build_mesh
        from zoo_tpu.serving.llm.model import PagedLlamaModel

        if len(jax.devices()) < 3:
            pytest.skip("needs >= 3 devices")
        mesh = build_mesh(jax.devices()[:3], axis_sizes={"model": 3})
        with pytest.raises(ValueError, match="n_kv_head"):
            PagedLlamaModel(tiny_llama_config(), mesh=mesh)

    def test_tp_spec_needs_enough_devices(self, monkeypatch):
        import jax

        from zoo_tpu.serving.llm.spec import build_llm_engine
        n = len(jax.devices())
        with pytest.raises(ValueError, match="only"):
            build_llm_engine(f"llama:tiny:tp={n * 2}", start=False)

    def test_single_device_mesh_is_ignored(self):
        """mesh over one device (or size-1 model axis) degrades to the
        plain single-device layout — tp reported as 1."""
        import jax

        from zoo_tpu.models.llm.llama import tiny_llama_config
        from zoo_tpu.parallel import build_mesh
        from zoo_tpu.serving.llm.model import PagedLlamaModel

        mesh = build_mesh(jax.devices()[:1], axis_sizes={"data": 1})
        m = PagedLlamaModel(tiny_llama_config(), num_blocks=8, mesh=mesh)
        assert m.mesh is None and m.tp == 1
