"""LLM serving engine (docs/llm_serving.md): the paged KV block
allocator, the iteration-level (continuous) scheduler, the
prefill/decode split's correctness against the full-context Llama
reference, and the streaming generate op over the real TCP door with
HA failover-resume.

The allocator and scheduler tests run against pure-python fakes (no
jax), so most of this file is tier-1 cheap; the paged-model and wire
tests share ONE tiny compiled model via a module fixture. The 2-replica
SIGKILL smoke (scripts/check_llm_serving.py) runs as a subprocess under
the ``chaos`` marker like its serving-HA sibling.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from zoo_tpu.serving.llm.engine import AdmissionError, LLMEngine
from zoo_tpu.serving.llm.kv_cache import BlockAllocator
from zoo_tpu.serving.llm.spec import parse_llm_spec
from zoo_tpu.util.resilience import Deadline


# ------------------------------------------------------- block allocator

def test_allocator_alloc_free_reuse():
    a = BlockAllocator(num_blocks=8, block_size=4)
    assert a.free_blocks == 7  # block 0 is the reserved trash block
    got = a.allocate("s1", 3)
    assert len(got) == 3 and 0 not in got
    assert a.used_blocks == 3 and a.free_blocks == 4
    assert a.blocks_of("s1") == got
    assert a.free("s1") == 3
    assert a.used_blocks == 0 and a.free_blocks == 7
    # LIFO: the just-freed blocks come back first (warm reuse), in the
    # same order the sequence held them
    again = a.allocate("s2", 3)
    assert again == got


def test_allocator_never_hands_out_block_zero():
    a = BlockAllocator(num_blocks=6, block_size=2)
    got = a.allocate("s", 5)
    assert sorted(got) == [1, 2, 3, 4, 5]
    assert a.allocate("s2", 1) is None   # block 0 is never handed out


def test_allocator_all_or_nothing():
    a = BlockAllocator(num_blocks=5, block_size=4)  # 4 usable
    assert a.allocate("s1", 3) is not None
    # asking for more than the free list holds changes NOTHING
    assert a.allocate("s2", 2) is None
    assert a.used_blocks == 3 and a.free_blocks == 1
    assert a.blocks_of("s2") == []


def test_allocator_block_table_growth():
    a = BlockAllocator(num_blocks=10, block_size=2)
    first = a.allocate("s", a.blocks_for_tokens(3))   # 3 tokens -> 2
    assert len(first) == 2
    # crossing each block boundary appends to the SAME table, order
    # preserved (the block table is positional: row i covers tokens
    # [i*bs, (i+1)*bs) )
    for _ in range(3):
        assert a.allocate("s", 1) is not None
    table = a.blocks_of("s")
    assert len(table) == 5 and table[:2] == first


def test_allocator_admission_refusal_when_empty():
    a = BlockAllocator(num_blocks=4, block_size=4)  # 3 usable
    assert a.can_admit(prompt_len=7)   # 2 blocks for 7+1 tokens
    assert a.allocate("hog", 3) is not None
    assert not a.can_admit(prompt_len=1)
    assert a.allocate("late", 1) is None
    a.free("hog")
    assert a.can_admit(prompt_len=7)


def test_allocator_free_is_idempotent():
    a = BlockAllocator(num_blocks=6, block_size=2)
    a.allocate("s", 2)
    assert a.free("s") == 2
    assert a.free("s") == 0          # abort paths may race: no double free
    assert a.free("never-seen") == 0
    assert a.free_blocks == 5


def test_allocator_blocks_for_tokens_math():
    a = BlockAllocator(num_blocks=4, block_size=8)
    assert a.blocks_for_tokens(1) == 1
    assert a.blocks_for_tokens(8) == 1
    assert a.blocks_for_tokens(9) == 2
    assert a.blocks_for_tokens(0) == 1  # a sequence always owns a block


def test_allocator_publishes_gauges():
    from zoo_tpu.obs.metrics import gauge
    used = gauge("zoo_llm_kv_blocks_used")
    free = gauge("zoo_llm_kv_blocks_free")
    a = BlockAllocator(num_blocks=9, block_size=4)
    assert free.value == 8.0 and used.value == 0.0
    a.allocate("s", 5)
    assert used.value == 5.0 and free.value == 3.0
    a.free("s")
    assert used.value == 0.0 and free.value == 8.0


# ------------------------------------------------ scheduler (fake model)

class _FakeModel:
    """Deterministic 'llm' with the PagedLlamaModel surface but no jax.

    Greedy lanes: next token = ``(2*tok + pos) % 97``, a pure function
    of (last token, position). Sampled lanes (temperature > 0): next
    token = ``(31*seed + 7*pos + 3*tok) % 97`` — a pure function of the
    SLOT'S OWN (seed, position, last token) and nothing else. Both make
    preemption's re-prefill-from-prompt+generated provably seamless and
    per-slot isolation provable, exactly the properties the real model
    gets from greedy decode / ``fold_in(seed, token_index)`` sampling."""

    def __init__(self, num_slots=2, block_size=4, num_blocks=8,
                 max_blocks_per_seq=4, max_prompt_len=12,
                 decode_delay=0.0, eos_id=None, prefill_chunk=0):
        self.num_slots = num_slots
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks_per_seq
        self.max_context = block_size * max_blocks_per_seq
        self.max_prompt_len = max_prompt_len
        self.prefill_chunk_size = prefill_chunk
        self.decode_delay = decode_delay
        self.eos_id = eos_id
        self.prefills = []   # tokens fed per prefill/chunk call
        self.chunks = []     # (start, take) per chunk call

    @staticmethod
    def _next(tok, pos, temp=0.0, seed=0):
        if temp > 0:
            return (31 * int(seed) + 7 * int(pos) + 3 * int(tok)) % 97
        return (2 * int(tok) + int(pos)) % 97

    def prefill(self, prompt, block_table_row, sampling=None):
        self.prefills.append(len(prompt))
        t, _, _, s = sampling or (0.0, 0, 1.0, 0)
        return self._next(prompt[-1], len(prompt), t, s)

    def prefill_chunk(self, chunk, start, total_len, block_table_row,
                      sampling=None):
        self.chunks.append((int(start), len(chunk)))
        self.prefills.append(len(chunk))
        t, _, _, s = sampling or (0.0, 0, 1.0, 0)
        # only meaningful on the final chunk (contains the last token)
        return self._next(chunk[-1], total_len, t, s)

    def decode(self, tokens, block_tables, positions, sampling=None):
        if self.decode_delay:
            time.sleep(self.decode_delay)
        if sampling is None:
            temps = seeds = [0] * len(tokens)
        else:
            temps, _, _, seeds = sampling
        # ``positions[i]`` is the cache index the incoming token is
        # WRITTEN at, so the sequence is ``position + 1`` tokens long
        # once it lands — the same length prefill sees for the same
        # sequence, which is what makes preemption's re-prefill seamless
        return np.array([self._next(t, p + 1, tt, s)
                         for t, p, tt, s in zip(tokens, positions,
                                                temps, seeds)], np.int32)

    # the async dispatch surface the overlapped pipeline drives: the
    # fake 'device' is synchronous, so the batch is just the array
    def decode_step(self, prev, host_tokens, use_host, block_tables,
                    positions, sampling):
        prev = np.zeros_like(host_tokens) if prev is None else \
            np.asarray(prev)
        toks = np.where(np.asarray(use_host), host_tokens, prev)
        return self.decode(toks, block_tables, positions, sampling)

    def read_tokens(self, batch):
        return np.asarray(batch)


def _reference(prompt, n, temp=0.0, seed=0):
    """What any correct schedule must emit for ``prompt``."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        out.append(_FakeModel._next(seq[-1], len(seq), temp, seed))
        seq.append(out[-1])
    return out


def _drain(handles, budget=20.0):
    deadline = time.monotonic() + budget
    while not all(h.done for h in handles):
        if time.monotonic() > deadline:
            raise AssertionError(
                f"streams stuck: {[h.outcome for h in handles]}")
        time.sleep(0.005)


def test_engine_continuous_more_streams_than_slots():
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=32,
                               max_blocks_per_seq=8)).start()
    try:
        prompts = [[3, 5], [7], [1, 2, 3], [9, 9], [4], [8, 1]]
        hs = [eng.submit(p, 5) for p in prompts]
        _drain(hs)
        for p, h in zip(prompts, hs):
            assert h.outcome == "ok"
            assert h.tokens == _reference(p, 5)
        assert eng.allocator.used_blocks == 0
        assert eng.allocator.live_sequences() == 0
    finally:
        eng.stop()


def test_engine_continuous_admits_into_freed_slots_midflight():
    """The Orca property itself: with 1 slot and bimodal lengths, a
    short stream admitted behind a long one starts as soon as ANY slot
    frees — i.e. the long stream is still running when the short one
    finishes (request-level batching would serialize whole waves)."""
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=64,
                               max_blocks_per_seq=8,
                               decode_delay=0.002)).start()
    try:
        long_h = eng.submit([1], 25)
        short = [eng.submit([2 + i], 2) for i in range(3)]
        _drain(short)
        assert not long_h.done, \
            "short streams should finish while the long one decodes"
        _drain([long_h])
        assert long_h.tokens == _reference([1], 25)
    finally:
        eng.stop()


def test_engine_oneshot_waits_for_batch_to_drain():
    """The request-level baseline the bench compares against: a wave is
    admitted only on an EMPTY batch, so a late request waits for every
    member of the running wave."""
    eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=64,
                               max_blocks_per_seq=8), mode="oneshot")
    # white-box: tick the scheduler by hand for determinism
    h1 = eng.submit([1], 4)
    h2 = eng.submit([2], 4)
    h3 = eng.submit([3], 2)   # wave 2
    for _ in range(3):
        eng._sweep(); eng._admit(); eng._prefill_tick()
        eng._grow_or_preempt(); eng._decode_tick()
    assert h1.done and h2.done
    assert not h3.tokens, "oneshot admitted into a non-empty batch"
    for _ in range(2):
        eng._sweep(); eng._admit(); eng._prefill_tick()
        eng._grow_or_preempt(); eng._decode_tick()
    assert h3.done and h3.tokens == _reference([3], 2)
    eng.stop()


def test_engine_deadline_dead_in_queue():
    eng = LLMEngine(_FakeModel()).start()
    try:
        h = eng.submit([1, 2], 4, deadline=Deadline.from_ms(0.0))
        _drain([h])
        assert h.outcome == "expired" and h.tokens == []
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_engine_deadline_expires_midstream_and_frees_blocks():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    try:
        h = eng.submit([5], 10_000, deadline=Deadline.from_ms(120.0))
        _drain([h], budget=10.0)
        assert h.outcome == "expired"
        assert 0 < len(h.tokens) < 10_000
        assert h.tokens == _reference([5], len(h.tokens))
        deadline = time.monotonic() + 5
        while eng.allocator.used_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.allocator.used_blocks == 0, "expiry leaked KV blocks"
    finally:
        eng.stop()


def test_engine_cancel_frees_blocks():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    try:
        h = eng.submit([5, 6], 10_000)
        while not h.tokens:
            time.sleep(0.005)
        assert eng.cancel(h.id)
        _drain([h])
        assert h.outcome == "cancelled"
        deadline = time.monotonic() + 5
        while eng.allocator.used_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.allocator.used_blocks == 0, "abort leaked KV blocks"
        assert not eng.cancel(h.id)   # already finished: no-op
    finally:
        eng.stop()


def test_engine_admission_sheds_when_waiting_queue_full():
    eng = LLMEngine(_FakeModel(num_slots=1, decode_delay=0.01),
                    max_waiting=2).start()
    try:
        running = eng.submit([1], 1000)
        while not running.tokens:
            time.sleep(0.005)
        eng.submit([2], 4)
        eng.submit([3], 4)
        with pytest.raises(AdmissionError) as ei:
            eng.submit([4], 4)
        assert ei.value.retry_after_ms > 0
    finally:
        eng.stop()


def test_engine_duplicate_rid_joins_stream():
    eng = LLMEngine(_FakeModel()).start()
    try:
        h1 = eng.submit([3, 4], 4, rid="r-1")
        h2 = eng.submit([9, 9, 9], 999, rid="r-1")  # args ignored: join
        assert h2 is h1
        _drain([h1])
        assert h1.tokens == _reference([3, 4], 4)
    finally:
        eng.stop()


def test_engine_prompt_too_long_and_empty_rejected():
    eng = LLMEngine(_FakeModel(max_prompt_len=8))
    with pytest.raises(ValueError):
        eng.submit(list(range(9)), 4)
    with pytest.raises(ValueError):
        eng.submit([], 4)
    with pytest.raises(ValueError):
        eng.submit([1], 0)
    eng.stop()


def test_engine_preempts_youngest_and_resumes_exactly():
    """KV pressure: two long streams on a pool that cannot hold both to
    completion. The youngest-admitted one is evicted (blocks freed,
    re-queued) and later RE-PREFILLED from prompt+generated; because
    decode is deterministic its final token stream is byte-identical to
    an uncontended run."""
    # 6 usable blocks, bs=2: each stream needs 1 block per 2 tokens;
    # two 12-token streams want 2x6 > 6 -> somebody must be preempted.
    # White-box manual ticks (engine not started): both streams are
    # admitted in the SAME tick, so concurrent growth — and therefore
    # the preemption — is deterministic, not a thread-timing accident.
    model = _FakeModel(num_slots=2, block_size=2, num_blocks=7,
                       max_blocks_per_seq=6, max_prompt_len=8)
    eng = LLMEngine(model)
    from zoo_tpu.obs.metrics import counter
    preempts0 = counter("zoo_llm_preempt_total").value
    a = eng.submit([1, 2], 9)
    b = eng.submit([3, 4], 9)
    for _ in range(60):
        eng._sweep(); eng._admit(); eng._prefill_tick()
        eng._grow_or_preempt(); eng._decode_tick()
        if a.done and b.done:
            break
    assert a.outcome == "ok" and b.outcome == "ok"
    assert a.tokens == _reference([1, 2], 9)
    assert b.tokens == _reference([3, 4], 9)
    assert counter("zoo_llm_preempt_total").value > preempts0
    # the victim was re-prefilled with its context so far
    assert max(model.prefills) > 4
    assert eng.allocator.used_blocks == 0
    eng.stop()


def test_engine_rejects_prompt_larger_than_whole_pool():
    """A prompt whose blocks can NEVER be satisfied (bigger than the
    entire pool) must be rejected at submit — not parked at the head of
    the waiting queue forever, wedging everything behind it."""
    model = _FakeModel(num_slots=1, block_size=2, num_blocks=4,
                       max_blocks_per_seq=16, max_prompt_len=64)
    eng = LLMEngine(model).start()
    try:
        with pytest.raises(ValueError, match="whole pool"):
            eng.submit(list(range(20)), 4)   # 11 blocks > 3 usable
        # feasible traffic still flows
        h = eng.submit([1, 2], 2)
        _drain([h])
        assert h.outcome == "ok"
    finally:
        eng.stop()


def test_engine_sole_stream_out_of_pool_errors():
    """A stream that cannot grow and has no preemption victim must end
    loudly (error outcome), not wedge the scheduler."""
    model = _FakeModel(num_slots=1, block_size=2, num_blocks=3,
                       max_blocks_per_seq=16, max_prompt_len=3)
    eng = LLMEngine(model).start()
    try:
        h = eng.submit([1], 50)   # needs 25 blocks, pool holds 2
        _drain([h])
        assert h.outcome == "error"
        assert "kv cache exhausted" in h.error
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_engine_context_ceiling_truncates_ok():
    model = _FakeModel(num_slots=1, block_size=2, num_blocks=32,
                       max_blocks_per_seq=3, max_prompt_len=4)
    eng = LLMEngine(model).start()
    try:
        h = eng.submit([1, 2], 50)   # table caps context at 6 tokens
        _drain([h])
        assert h.outcome == "ok" and h.truncated
        assert len(h.tokens) < 50
        assert h.tokens == _reference([1, 2], len(h.tokens))
    finally:
        eng.stop()


def test_engine_eos_stops_stream():
    ref = _reference([6], 10)
    eos = ref[3]
    eng = LLMEngine(_FakeModel(eos_id=eos)).start()
    try:
        h = eng.submit([6], 10)
        _drain([h])
        assert h.outcome == "ok"
        assert h.tokens == ref[:4]   # eos token is emitted, then stop
    finally:
        eng.stop()


def test_engine_stop_frees_everything():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    h = eng.submit([1], 10_000)
    while not h.tokens:
        time.sleep(0.005)
    eng.stop()
    assert h.outcome == "cancelled"
    assert eng.allocator.used_blocks == 0


# --------------------------------------------- overlapped tick pipeline

def test_overlap_engine_matches_sync_engine():
    """The double-buffered pipeline is a pure latency optimization: for
    every stream it must emit exactly the tokens the synchronous
    (pre-overlap) loop emits."""
    prompts = [[3, 5], [7], [1, 2, 3], [9, 9], [4], [8, 1]]
    outs = []
    for overlap in (False, True):
        eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=32,
                                   max_blocks_per_seq=8),
                        overlap=overlap).start()
        try:
            assert eng.overlap is overlap
            hs = [eng.submit(p, 5) for p in prompts]
            _drain(hs)
            outs.append([h.tokens for h in hs])
            assert eng.allocator.used_blocks == 0
        finally:
            eng.stop()
    assert outs[0] == outs[1]
    for p, toks in zip(prompts, outs[1]):
        assert toks == _reference(p, 5)


def test_overlap_eos_discards_speculative_tokens():
    """Under overlap the engine keeps dispatching while a tick is in
    flight; when eos lands, the speculatively decoded extra tokens must
    be discarded — the stream ends exactly at eos like the sync loop."""
    ref = _reference([6], 10)
    eos = ref[3]
    eng = LLMEngine(_FakeModel(eos_id=eos, num_blocks=32,
                               max_blocks_per_seq=8,
                               decode_delay=0.002)).start()
    try:
        h = eng.submit([6], 50)
        _drain([h])
        assert h.outcome == "ok"
        assert h.tokens == ref[:4]   # eos emitted, then stop — no spill
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_overlap_publishes_tick_metrics():
    from zoo_tpu.obs.metrics import gauge, histogram
    hist = histogram("zoo_llm_tick_seconds", labels=("phase",))
    before = {ph: hist.labels(phase=ph).snapshot_value()["count"]
              for ph in ("schedule", "decode", "readback")}
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.001)).start()
    try:
        _drain([eng.submit([2, 3], 8)])
    finally:
        eng.stop()
    for ph in ("schedule", "decode", "readback"):
        after = hist.labels(phase=ph).snapshot_value()["count"]
        assert after > before[ph], f"no {ph} tick samples recorded"
    ratio = gauge("zoo_llm_tick_overlap_ratio").value
    assert 0.0 <= ratio <= 1.0


def test_overlap_readback_failure_fails_streams_loudly():
    """A failed readback (device error mid-stream) must END the
    affected streams with an error outcome — not leave a silent
    one-token hole and a wedged slot — and the engine must keep
    serving fresh streams afterwards (chain re-seeded)."""

    class _FlakyModel(_FakeModel):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.fail_at = 3
            self.reads = 0

        def read_tokens(self, batch):
            self.reads += 1
            if self.reads == self.fail_at:
                raise RuntimeError("injected readback failure")
            return super().read_tokens(batch)

    model = _FlakyModel(num_slots=2, num_blocks=32, max_blocks_per_seq=8)
    eng = LLMEngine(model, overlap=True).start()
    try:
        h = eng.submit([4], 30)
        _drain([h])
        assert h.outcome == "error"
        assert "tokens lost" in h.error
        # no silent hole: everything delivered is the exact prefix
        assert h.tokens == _reference([4], len(h.tokens))
        deadline = time.monotonic() + 5
        while eng.allocator.used_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.allocator.used_blocks == 0, "failure leaked blocks"
        # the engine survives and fresh streams decode correctly
        h2 = eng.submit([9], 5)
        _drain([h2])
        assert h2.outcome == "ok" and h2.tokens == _reference([9], 5)
    finally:
        eng.stop()


def test_prefill_failure_fails_stream_not_scheduler():
    """A prefill exception ends THAT stream with an error (blocks
    freed) — it must not kill the scheduler thread and wedge the
    queue behind it."""

    class _BadPrefill(_FakeModel):
        def prefill(self, prompt, row, sampling=None):
            if len(prompt) >= 5:
                raise RuntimeError("injected prefill failure")
            return super().prefill(prompt, row, sampling)

    eng = LLMEngine(_BadPrefill(num_slots=2, num_blocks=32,
                                max_blocks_per_seq=8)).start()
    try:
        bad = eng.submit([1, 2, 3, 4, 5], 4)
        good = eng.submit([7], 4)
        _drain([bad, good])
        assert bad.outcome == "error" and "prefill failed" in bad.error
        assert good.outcome == "ok" and good.tokens == _reference([7], 4)
        assert eng.allocator.used_blocks == 0, "failed prefill leaked"
    finally:
        eng.stop()


# ------------------------------------------------------------- sampling

def test_parse_sampling_defaults_env_and_errors(monkeypatch):
    from zoo_tpu.serving.llm.engine import parse_sampling, stream_seed
    assert parse_sampling(None, "r") == (0.0, 0, 1.0, stream_seed("r"))
    t, k, p, s = parse_sampling(
        dict(temperature=0.8, top_k=40, top_p=0.9, seed=7), "r")
    assert (t, k, p, s) == (0.8, 40, 0.9, 7)
    # env sets the deployment default; the request overrides it
    monkeypatch.setenv("ZOO_LLM_SAMPLING", "temperature=0.5,top_k=10")
    t, k, p, s = parse_sampling(None, "r")
    assert (t, k) == (0.5, 10) and s == stream_seed("r")
    t, k, _, _ = parse_sampling(dict(temperature=0.0), "r")
    assert (t, k) == (0.0, 10)
    monkeypatch.delenv("ZOO_LLM_SAMPLING")
    with pytest.raises(ValueError, match="unknown sampling"):
        parse_sampling(dict(temp=1.0), "r")
    with pytest.raises(ValueError, match="top_p"):
        parse_sampling(dict(top_p=0.0), "r")
    with pytest.raises(ValueError, match="temperature"):
        parse_sampling(dict(temperature=-1.0), "r")
    # the rid-derived seed is stable across processes/replicas
    assert stream_seed("some-rid") == stream_seed("some-rid")


def test_sampling_per_slot_isolation():
    """One stream's sampling params must never bleed into a neighbor
    slot: the same seeded stream decodes identically regardless of what
    its slot neighbors sample with."""
    ref = _reference([5], 6, temp=1.0, seed=42)
    for i, neighbor in enumerate((dict(temperature=5.0, seed=123),
                                  dict(temperature=0.0),
                                  dict(temperature=2.0, seed=9))):
        eng = LLMEngine(_FakeModel(num_slots=2, num_blocks=64,
                                   max_blocks_per_seq=8)).start()
        try:
            a = eng.submit([5], 6,
                           sampling=dict(temperature=1.0, seed=42))
            b = eng.submit([7], 6, sampling=neighbor)
            _drain([a, b])
        finally:
            eng.stop()
        assert a.tokens == ref, f"neighbor {i} bled into the stream"


def test_sampled_stream_survives_preemption_deterministically():
    """Seeded sampling across a mid-stream preemption: the PRNG draw is
    a pure function of (seed, token index), so the re-prefilled
    continuation is byte-identical to an uncontended run — same
    white-box setup as the greedy preemption test."""
    model = _FakeModel(num_slots=2, block_size=2, num_blocks=7,
                       max_blocks_per_seq=6, max_prompt_len=8)
    eng = LLMEngine(model, overlap=False)
    from zoo_tpu.obs.metrics import counter
    preempts0 = counter("zoo_llm_preempt_total").value
    samp = dict(temperature=1.0, seed=5)
    a = eng.submit([1, 2], 9, sampling=samp)
    b = eng.submit([3, 4], 9, sampling=samp)
    for _ in range(60):
        eng._sweep(); eng._admit(); eng._prefill_tick()
        eng._grow_or_preempt(); eng._decode_tick()
        if a.done and b.done:
            break
    assert a.outcome == "ok" and b.outcome == "ok"
    assert a.tokens == _reference([1, 2], 9, 1.0, 5)
    assert b.tokens == _reference([3, 4], 9, 1.0, 5)
    assert counter("zoo_llm_preempt_total").value > preempts0
    eng.stop()


def test_allocator_aux_checkpoints_with_block_table_entry():
    """The per-sequence PRNG seed rides the block-table entry: set on
    admission, readable while the sequence holds blocks, cleared with
    them on free."""
    a = BlockAllocator(num_blocks=8, block_size=4)
    a.allocate("s1", 2)
    a.set_aux("s1", seed=42, resumed_at=3)
    assert a.get_aux("s1") == {"seed": 42, "resumed_at": 3}
    assert a.get_aux("never") is None
    a.free("s1")
    assert a.get_aux("s1") is None


def test_engine_checkpoints_seed_in_block_table_entry():
    eng = LLMEngine(_FakeModel(num_blocks=32, max_blocks_per_seq=8,
                               decode_delay=0.01)).start()
    try:
        h = eng.submit([3], 1000, sampling=dict(temperature=1.0,
                                                seed=77))
        while not h.tokens:
            time.sleep(0.005)
        aux = eng.allocator.get_aux(h.id)
        assert aux is not None and aux["seed"] == 77
    finally:
        eng.stop()


# ------------------------------------------------------- chunked prefill

def test_chunked_prefill_interleaves_with_decode():
    """The anti-stall property: a long prompt's prefill advances one
    chunk per tick while an already-live stream keeps decoding — the
    whole-prompt stall the chunk executable removes."""
    model = _FakeModel(num_slots=2, num_blocks=64, max_blocks_per_seq=8,
                       max_prompt_len=32, prefill_chunk=4)
    eng = LLMEngine(model, overlap=False)

    def tick():
        eng._sweep(); eng._admit(); eng._prefill_tick()
        eng._grow_or_preempt(); eng._decode_tick()

    a = eng.submit([1], 30)
    for _ in range(3):
        tick()
    before = len(a.tokens)
    assert before > 0
    long_h = eng.submit(list(range(1, 13)), 4)   # 12 tokens = 3 chunks
    progress = []
    for _ in range(2):
        tick()
        progress.append(len(a.tokens))
    # two ticks in: the long prompt is still mid-prefill (2 of 3 chunks
    # fed), yet the short stream gained a token EVERY tick
    assert not long_h.tokens
    assert model.chunks[-2:] == [(0, 4), (4, 4)]
    assert progress == [before + 1, before + 2]
    for _ in range(40):
        tick()
        if a.done and long_h.done:
            break
    assert a.tokens == _reference([1], 30)
    assert long_h.tokens == _reference(list(range(1, 13)), 4)
    assert eng.allocator.used_blocks == 0
    eng.stop()


def test_chunked_prefill_preemption_resets_cleanly():
    """A stream preempted MID-PREFILL re-queues with just its prompt
    (nothing generated yet) and completes correctly later."""
    model = _FakeModel(num_slots=2, block_size=2, num_blocks=7,
                       max_blocks_per_seq=6, max_prompt_len=8,
                       prefill_chunk=2)
    eng = LLMEngine(model, overlap=False)
    a = eng.submit([1, 2], 9)
    b = eng.submit([3, 4], 9)
    for _ in range(80):
        eng._sweep(); eng._admit(); eng._prefill_tick()
        eng._grow_or_preempt(); eng._decode_tick()
        if a.done and b.done:
            break
    assert a.tokens == _reference([1, 2], 9)
    assert b.tokens == _reference([3, 4], 9)
    assert eng.allocator.used_blocks == 0
    eng.stop()


# ------------------------------------------------------------ spec parse

def test_parse_llm_spec_forms():
    cfg, eng = parse_llm_spec("llama:tiny")
    assert cfg["hidden"] == 64 and eng == {}
    cfg, eng = parse_llm_spec(
        "llama:tiny:seed=3,slots=4,block=8,blocks=64,buckets=16/64")
    assert eng == {"seed": 3, "num_slots": 4, "block_size": 8,
                   "num_blocks": 64, "prefill_buckets": (16, 64)}
    cfg, _ = parse_llm_spec(
        "llama:vocab=256,hidden=32,n_block=1,n_head=4,n_kv_head=2,"
        "intermediate=64")
    assert cfg["vocab"] == 256 and cfg["n_kv_head"] == 2
    with pytest.raises(ValueError):
        parse_llm_spec("llama:gguf")
    with pytest.raises(ValueError):
        parse_llm_spec("llama:tiny:slots")
    with pytest.raises(ValueError):
        parse_llm_spec("llama:tiny:warp=9")


# --------------------------------------------- paged model (jax, shared)

@pytest.fixture(scope="module")
def paged():
    """ONE tiny compiled model + its config, shared by every jax test
    in this file (each test runs its own engine; freed blocks are fully
    rewritten by the next owner, so sharing the cache is safe)."""
    from zoo_tpu.models.llm.llama import LlamaConfig
    from zoo_tpu.serving.llm.model import PagedLlamaModel
    cfg = LlamaConfig(vocab=64, hidden=32, n_block=2, n_head=4,
                      n_kv_head=2, intermediate=64, rope_theta=10000.0)
    model = PagedLlamaModel(cfg, seed=0, num_slots=2, block_size=4,
                            num_blocks=24, max_blocks_per_seq=6,
                            prefill_buckets=(8, 16))
    return cfg, model


def test_gqa_cache_layout(paged):
    """K/V are stored at num_kv_heads (2), NOT num_heads (4) — the GQA
    memory saving is real, not re-expanded into the cache."""
    cfg, model = paged
    import jax.numpy as jnp
    assert cfg.n_kv_head < cfg.n_head
    expect = (cfg.n_block, model.num_blocks, model.block_size,
              cfg.n_kv_head, cfg.head_dim)
    assert model._kc.shape == expect
    assert model._vc.shape == expect
    assert model._kc.dtype == jnp.float32


def test_paged_decode_matches_full_context_reference(paged):
    """The correctness anchor: greedy generation through the paged
    prefill + block-gathered decode must match token-for-token a greedy
    loop over the ORIGINAL full-context Llama forward (same params) —
    across a block boundary and a preemption-free multi-stream mix."""
    cfg, model = paged
    import jax.numpy as jnp
    from zoo_tpu.models.llm.llama import Llama

    layer = Llama(cfg, lm_head=True)

    def ref_generate(prompt, n):
        seq = list(int(t) for t in prompt)
        out = []
        for _ in range(n):
            logits = layer.call(model.params,
                                jnp.asarray([seq], jnp.int32))
            out.append(int(jnp.argmax(logits[0, -1])))
            seq.append(out[-1])
        return out

    eng = LLMEngine(model).start()
    try:
        rs = np.random.RandomState(7)
        prompts = [rs.randint(0, cfg.vocab, (n,)) for n in (3, 9, 14)]
        n_new = 9   # crosses the 4-token block boundary repeatedly
        hs = [eng.submit(p, n_new) for p in prompts]
        _drain(hs, budget=300.0)
        for p, h in zip(prompts, hs):
            assert h.outcome == "ok"
            assert h.tokens == ref_generate(p, n_new), \
                f"paged decode diverged for prompt len {len(p)}"
        assert eng.allocator.used_blocks == 0
    finally:
        eng.stop()


def test_decode_compiles_exactly_one_executable(paged):
    """The fixed-shape contract: after streams of every shape mix, the
    decode jit cache holds ONE executable and prefill at most one per
    bucket — request churn must never recompile."""
    cfg, model = paged
    eng = LLMEngine(model).start()
    try:
        rs = np.random.RandomState(3)
        hs = [eng.submit(rs.randint(0, cfg.vocab, (n,)), 3)
              for n in (2, 7, 8, 13)]   # both buckets, varied fill
        _drain(hs, budget=300.0)
    finally:
        eng.stop()
    counts = model.compile_counts()
    if counts["decode"] < 0:
        pytest.skip("jit cache size API unavailable on this jax")
    assert counts["decode"] == 1, counts
    assert 0 < counts["prefill"] <= len(model.prefill_buckets), counts
    # compiled-artifact contracts on the ONE decode executable: the
    # donated cache is aliased in the HLO (a dropped donation doubles
    # decode HBM) and the outfeed stays slots x 1 int32 ids, never
    # slots x vocab logits (zoo-lint HLO-DONATION / HLO-HOST-TRANSFER)
    from zoo_tpu.analysis.hlo import assert_llm_executable
    assert_llm_executable(model, "decode")


def _generate_all(model, prompts, n, sampling=None, rids=None,
                  budget=300.0):
    eng = LLMEngine(model).start()
    try:
        hs = [eng.submit(p, n, sampling=sampling,
                         rid=None if rids is None else rids[i])
              for i, p in enumerate(prompts)]
        _drain(hs, budget=budget)
        assert all(h.outcome == "ok" for h in hs), \
            [(h.outcome, h.error) for h in hs]
        assert eng.allocator.used_blocks == 0
        return [h.tokens for h in hs]
    finally:
        eng.stop()


@pytest.fixture(scope="module")
def paged_streams(paged):
    """Reference streams (greedy + seeded sampling) through the shared
    dense-gather model — the anchor the flash-kernel and chunked-prefill
    variants must reproduce byte-for-byte."""
    cfg, model = paged
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, cfg.vocab, (n,)) for n in (3, 9, 14)]
    greedy = _generate_all(model, prompts, 7)
    rids = [f"ref-{i}" for i in range(len(prompts))]
    samp = dict(temperature=0.9, top_k=16, top_p=0.95)
    sampled = _generate_all(model, prompts, 7, sampling=samp, rids=rids)
    assert sampled != greedy   # the sampler actually sampled
    return prompts, greedy, sampled, samp, rids


def test_paged_flash_decode_token_identical_to_dense(paged,
                                                     paged_streams):
    """The kernel-selection contract: decode through the paged
    flash-decode Pallas kernel (interpret off-TPU) emits byte-identical
    streams — greedy AND seeded sampling — to the dense-gather
    reference path on the same weights."""
    from zoo_tpu.serving.llm.model import PagedLlamaModel
    cfg, model = paged
    prompts, greedy, sampled, samp, rids = paged_streams
    flash = PagedLlamaModel(
        cfg, params=model.params, num_slots=2, block_size=4,
        num_blocks=24, max_blocks_per_seq=6, prefill_buckets=(8, 16),
        decode_impl="flash")
    assert flash.decode_attention_impl == "flash"
    assert _generate_all(flash, prompts, 7) == greedy
    assert _generate_all(flash, prompts, 7, sampling=samp,
                         rids=rids) == sampled


def test_chunked_prefill_streams_byte_identical(paged, paged_streams):
    """Chunked prefill is the same math fed through the cache in
    slices: every stream must match the whole-prompt bucket path
    byte-for-byte, and the prefill census must collapse to the ONE
    chunk executable."""
    from zoo_tpu.serving.llm.model import PagedLlamaModel
    cfg, model = paged
    prompts, greedy, sampled, samp, rids = paged_streams
    chunked = PagedLlamaModel(
        cfg, params=model.params, num_slots=2, block_size=4,
        num_blocks=24, max_blocks_per_seq=6, prefill_buckets=(8, 16),
        prefill_chunk=4)
    assert _generate_all(chunked, prompts, 7) == greedy
    assert _generate_all(chunked, prompts, 7, sampling=samp,
                         rids=rids) == sampled
    counts = chunked.compile_counts()
    if counts["decode"] >= 0:
        assert counts["prefill"] == 0 and counts["prefill_chunk"] == 1, \
            counts


def test_decode_host_transfer_is_token_ids_only(paged):
    """The transfer contract the on-device sampler exists for: per
    decode tick, exactly slots x 1 int32 ids cross to the host — never
    the slots x vocab logits."""
    from zoo_tpu.obs.metrics import counter
    cfg, model = paged
    fam = counter("zoo_llm_host_transfer_bytes_total", labels=("kind",))
    before = fam.labels(kind="tokens").value
    eng = LLMEngine(model).start()
    try:
        _drain([eng.submit(np.arange(1, 5) % cfg.vocab, 6)],
               budget=120.0)
    finally:
        eng.stop()
    # read AFTER stop(): the readback thread has joined, so the step
    # counter and the transfer counter are settled together
    steps = eng._decode_steps
    delta = fam.labels(kind="tokens").value - before
    assert steps > 0
    assert delta == steps * model.num_slots * 4, \
        (delta, steps, model.num_slots)


def test_preempt_resume_greedy_matches_host_argmax_reference():
    """On-device greedy across a REAL preemption equals a host-side
    argmax loop over the full-context forward (the pre-PR reference):
    a tiny pool forces eviction + re-prefill mid-stream."""
    import jax.numpy as jnp

    from zoo_tpu.models.llm.llama import Llama, LlamaConfig
    from zoo_tpu.obs.metrics import counter
    from zoo_tpu.serving.llm.model import PagedLlamaModel

    cfg = LlamaConfig(vocab=64, hidden=32, n_block=2, n_head=4,
                      n_kv_head=2, intermediate=64, rope_theta=10000.0)
    # 7 usable blocks x 4 tokens: two 20-token streams cannot coexist
    model = PagedLlamaModel(cfg, seed=0, num_slots=2, block_size=4,
                            num_blocks=8, max_blocks_per_seq=8,
                            prefill_buckets=(8, 32))
    layer = Llama(cfg, lm_head=True)

    def host_argmax(prompt, n):
        seq = [int(t) for t in prompt]
        out = []
        for _ in range(n):
            logits = layer.call(model.params,
                                jnp.asarray([seq], jnp.int32))
            out.append(int(jnp.argmax(logits[0, -1])))
            seq.append(out[-1])
        return out

    preempts0 = counter("zoo_llm_preempt_total").value
    prompts = [np.arange(2, 8) % cfg.vocab, np.arange(3, 9) % cfg.vocab]
    toks = _generate_all(model, prompts, 14, budget=300.0)
    assert counter("zoo_llm_preempt_total").value > preempts0, \
        "pool sizing failed to force a preemption"
    for p, got in zip(prompts, toks):
        assert got == host_argmax(p, 14), \
            "preempt-resume diverged from the host-argmax reference"


# ------------------------------------------------- streaming over the wire

@pytest.fixture(scope="module")
def llm_server(paged):
    """The shared model behind a REAL ServingServer TCP door (llm-only
    replica: no predict model mounted)."""
    from zoo_tpu.serving.server import ServingServer
    _, model = paged
    eng = LLMEngine(model)
    server = ServingServer(None, llm_engine=eng.start(), port=0,
                           batch_size=2, max_wait_ms=1.0).start()
    yield server, eng
    server.stop()


def _stream_tokens(host, port, prompt, n, rid=None, resume_from=0,
                   deadline=None, **sampling):
    from zoo_tpu.serving.tcp_client import _Connection
    conn = _Connection(host, port)
    frames, toks = [], []
    msg = {"op": "generate", "id": rid,
           "prompt": np.asarray(prompt, np.int32),
           "max_new_tokens": n, "resume_from": resume_from}
    msg.update(sampling)
    try:
        for f in conn.stream(msg, deadline=deadline):
            frames.append(f)
            toks.extend(f.get("tokens") or ())
    finally:
        conn.close()
    return toks, frames


def test_generate_streams_over_wire(paged, llm_server):
    cfg, model = paged
    server, eng = llm_server
    prompt = np.arange(1, 6) % cfg.vocab
    toks, frames = _stream_tokens(server.host, server.port, prompt, 6)
    assert len(toks) == 6
    assert frames[-1]["done"] and frames[-1]["outcome"] == "ok"
    assert frames[-1]["n_tokens"] == 6
    # a direct engine replay of the same rid would dedup; a fresh id
    # reproduces the same tokens (deterministic greedy decode)
    again, _ = _stream_tokens(server.host, server.port, prompt, 6)
    assert again == toks


def test_generate_sampling_on_the_wire(paged, llm_server):
    """temperature/top_k/top_p/seed ride the generate frame; an
    explicit seed makes the stream reproducible across fresh request
    ids, and sampling actually changes the tokens vs greedy."""
    cfg, _ = paged
    server, _ = llm_server
    prompt = np.arange(3, 9) % cfg.vocab
    greedy, _ = _stream_tokens(server.host, server.port, prompt, 6)
    kw = dict(temperature=0.9, top_k=16, top_p=0.95, seed=1234)
    a, frames = _stream_tokens(server.host, server.port, prompt, 6,
                               **kw)
    b, _ = _stream_tokens(server.host, server.port, prompt, 6, **kw)
    assert frames[-1]["outcome"] == "ok" and len(a) == 6
    assert a == b, "explicit seed must reproduce the stream"
    assert a != greedy, "sampling params were ignored on the wire"


def test_generate_resume_from_skips_prefix(paged, llm_server):
    cfg, _ = paged
    server, _ = llm_server
    prompt = np.arange(2, 8) % cfg.vocab
    full, _ = _stream_tokens(server.host, server.port, prompt, 6)
    suffix, frames = _stream_tokens(server.host, server.port, prompt, 6,
                                    resume_from=4)
    assert suffix == full[4:]
    assert frames[-1]["n_tokens"] == 6   # server-side count is total


def test_generate_dead_on_arrival_deadline(paged, llm_server):
    server, _ = llm_server
    from zoo_tpu.serving.tcp_client import _Connection
    conn = _Connection(server.host, server.port)
    try:
        frames = list(conn.stream({"op": "generate", "prompt": [1, 2],
                                   "max_new_tokens": 4,
                                   "deadline_ms": 0.0}))
    finally:
        conn.close()
    assert frames[-1].get("expired") and frames[-1]["outcome"] == "expired"


def test_generate_client_disconnect_frees_blocks(paged, llm_server):
    """The last subscriber dropping mid-stream cancels the stream and
    returns its KV blocks — an abandoned client must not pin the pool
    until max_new_tokens."""
    from zoo_tpu.serving.tcp_client import _Connection
    server, eng = llm_server
    before = eng.allocator.used_blocks
    conn = _Connection(server.host, server.port)
    it = conn.stream({"op": "generate", "prompt": [3, 1],
                      "max_new_tokens": 100_000})
    first = next(it)
    assert first.get("tokens") or first.get("done") is False
    conn.close()   # walk away mid-stream
    deadline = time.monotonic() + 10
    while eng.allocator.used_blocks > before and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert eng.allocator.used_blocks == before, "disconnect leaked blocks"


def test_ha_client_generate_failover_resumes_midstream(paged):
    """Mid-stream replica loss under HAServingClient.generate: the
    second replica (bit-identical weights, greedy decode) resumes from
    ``resume_from`` and the caller sees one gapless, duplicate-free
    token stream."""
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.server import ServingServer
    cfg, model = paged
    # two engines over the SAME model object = bit-identical weights
    # (they serialize on the model lock, like two processes on one chip)
    eng1, eng2 = LLMEngine(model).start(), LLMEngine(model).start()
    s1 = ServingServer(None, llm_engine=eng1, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    s2 = ServingServer(None, llm_engine=eng2, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    try:
        prompt = (np.arange(5) * 3 + 1) % cfg.vocab
        ref, _ = _stream_tokens(s2.host, s2.port, prompt, 8)
        cli = HAServingClient([(s1.host, s1.port), (s2.host, s2.port)],
                              hedge=False, deadline_ms=120_000)
        got = []
        for tok in cli.generate(prompt, 8):
            got.append(tok)
            if len(got) == 3:
                s1.stop()   # primary dies mid-stream
        assert got == ref, f"failover stream diverged: {got} vs {ref}"
        cli.close()
    finally:
        for srv, eng in ((s1, eng1), (s2, eng2)):
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — s1 already stopped
                pass
        assert eng1.allocator.used_blocks == 0
        assert eng2.allocator.used_blocks == 0


def test_ha_client_sampled_generate_failover_resumes_midstream(paged):
    """Seeded sampling across an HA failover-with-resume: the PRNG key
    is fold_in(seed, token index) and the seed rides the stream, so the
    surviving replica regenerates the exact suffix — one gapless,
    duplicate-free SAMPLED stream across a replica loss."""
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.server import ServingServer
    cfg, model = paged
    kw = dict(temperature=0.9, top_k=16, top_p=0.95, seed=99)
    eng1, eng2 = LLMEngine(model).start(), LLMEngine(model).start()
    s1 = ServingServer(None, llm_engine=eng1, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    s2 = ServingServer(None, llm_engine=eng2, port=0, batch_size=2,
                       max_wait_ms=1.0).start()
    try:
        prompt = (np.arange(5) * 5 + 2) % cfg.vocab
        ref, _ = _stream_tokens(s2.host, s2.port, prompt, 8, **kw)
        cli = HAServingClient([(s1.host, s1.port), (s2.host, s2.port)],
                              hedge=False, deadline_ms=120_000)
        got = []
        for tok in cli.generate(prompt, 8, **kw):
            got.append(tok)
            if len(got) == 3:
                s1.stop()   # primary dies mid-stream
        assert got == ref, f"sampled failover diverged: {got} vs {ref}"
        cli.close()
    finally:
        for srv in (s1, s2):
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — s1 already stopped
                pass
        assert eng1.allocator.used_blocks == 0
        assert eng2.allocator.used_blocks == 0


# ------------------- prefix caching + quantized KV cache (real model)

def _engine_tokens(model, prompts, max_new=6, prefix_cache=False,
                   sampling=None):
    """Run ``prompts`` sequentially (each waits for the previous, so
    registration is deterministic) and return their token streams plus
    the engine stats."""
    eng = LLMEngine(model, prefix_cache=prefix_cache).start()
    try:
        outs = []
        for i, p in enumerate(prompts):
            h = eng.submit(np.asarray(p, np.int32), max_new,
                           rid=f"px-{i}", sampling=sampling)
            _drain([h], budget=120.0)
            assert h.outcome == "ok", (h.outcome, h.error)
            outs.append(list(h.tokens))
        return outs, eng.stats()
    finally:
        eng.stop()


def _px_model(kv_dtype="f32", chunk=0, impl="dense"):
    from zoo_tpu.models.llm.llama import tiny_llama_config
    from zoo_tpu.serving.llm.model import PagedLlamaModel
    return PagedLlamaModel(tiny_llama_config(), seed=0, num_slots=2,
                           block_size=4, num_blocks=48,
                           max_blocks_per_seq=8, prefill_buckets=(8, 32),
                           kv_dtype=kv_dtype, prefill_chunk=chunk,
                           decode_impl=impl)


_PX_SHARED = list(range(1, 17))     # 16 tokens = 4 full blocks, aligned
_PX_PROMPTS = [_PX_SHARED, _PX_SHARED + [99, 98, 97],
               _PX_SHARED + [50], _PX_SHARED]


@pytest.mark.parametrize("chunk", [0, 4])
def test_prefix_cache_byte_identical_real_model(chunk):
    """Acceptance: greedy streams byte-identical with prefix caching on
    vs off — bucketed (chunk=0: novel suffix fed through the ONE chunk
    executable) AND chunked prefill — with real hits, a real CoW fork
    on the aligned repeat, and the executable census intact."""
    m_off = _px_model(chunk=chunk)
    off, _ = _engine_tokens(m_off, _PX_PROMPTS)
    m_on = _px_model(chunk=chunk)
    on, st = _engine_tokens(m_on, _PX_PROMPTS, prefix_cache=True)
    assert on == off
    assert st["prefix_hit_tokens"] > 0
    assert st["blocks_used"] == 0          # zero leaks
    counts = m_on.compile_counts()
    assert counts["decode"] == 1
    assert counts["prefill_chunk"] <= 1    # suffix feed is ONE exec
    if chunk:
        assert counts["prefill"] == 0      # bucket path never compiled


def test_prefix_cache_sampled_streams_identical_real_model():
    sampling = dict(temperature=0.8, top_k=12, top_p=0.9, seed=77)
    off, _ = _engine_tokens(_px_model(), _PX_PROMPTS, sampling=sampling)
    on, st = _engine_tokens(_px_model(), _PX_PROMPTS, sampling=sampling,
                            prefix_cache=True)
    assert on == off and st["prefix_hit_tokens"] > 0


def test_int8_cache_flash_dense_token_identity():
    """Acceptance: with the int8 KV cache, the paged flash kernel
    (interpreter = the exact kernel TPU compiles) and the dense-gather
    fallback agree token-for-token — and at test scale the quantized
    streams match the f32 reference ids outright."""
    ref, _ = _engine_tokens(_px_model("f32"), _PX_PROMPTS, max_new=8)
    dense, st = _engine_tokens(_px_model("int8", impl="dense"),
                               _PX_PROMPTS, max_new=8)
    flash, _ = _engine_tokens(_px_model("int8", impl="flash"),
                              _PX_PROMPTS, max_new=8)
    assert dense == flash                  # the hard contract
    assert dense == ref                    # tiny-scale quality parity
    assert st["kv_cache_dtype"] == "int8"


def test_int8_cache_with_prefix_cache_and_census():
    """Both features on at once: byte-identity to int8-without-cache,
    decode-compiles==1, chunk census unchanged, zero leaked blocks."""
    off, _ = _engine_tokens(_px_model("int8", chunk=4), _PX_PROMPTS)
    m = _px_model("int8", chunk=4)
    on, st = _engine_tokens(m, _PX_PROMPTS, prefix_cache=True)
    assert on == off
    counts = m.compile_counts()
    assert counts["decode"] == 1 and counts["prefill_chunk"] == 1
    assert st["blocks_used"] == 0
    assert st["kv_bytes_per_token"] < _px_model("bf16")\
        .kv_bytes_per_token


def test_kv_dtype_resolution_and_bytes_model():
    """auto records its selection (CPU -> f32, never silent), bad
    values are loud, and the bytes-per-token model halves bf16 -> int8
    modulo the scale rows."""
    from zoo_tpu.serving.llm.model import resolve_kv_dtype
    assert resolve_kv_dtype("int8") == "int8"
    assert resolve_kv_dtype("bf16") == "bf16"
    assert resolve_kv_dtype("auto") in ("int8", "f32")  # TPU vs CPU
    with pytest.raises(ValueError):
        resolve_kv_dtype("fp4")
    f32 = _px_model("f32")
    bf16 = _px_model("bf16")
    i8 = _px_model("int8")
    assert bf16.kv_bytes_per_token * 2 == f32.kv_bytes_per_token
    # int8 payload is half of bf16; the absmax scale rows ride on top
    c = f32.cfg
    scale_bytes = 2 * c.n_block * c.n_kv_head * 4
    assert i8.kv_bytes_per_token == \
        bf16.kv_bytes_per_token // 2 + scale_bytes
    assert i8.kv_cache_dtype_requested == "int8"
    import jax.numpy as jnp
    assert i8._kc.dtype == jnp.int8
    assert bf16._kc.dtype == jnp.bfloat16
    assert i8._cache["ks"].shape == (c.n_block, i8.num_blocks,
                                     i8.block_size, c.n_kv_head)


def test_spec_parses_kv_and_prefix_cache():
    from zoo_tpu.serving.llm.spec import build_llm_engine
    eng = build_llm_engine(
        "llama:tiny:slots=2,block=4,blocks=16,tables=4,buckets=8,"
        "kv=int8,prefix_cache=1", start=False)
    try:
        assert eng.prefix_cache is True
        assert eng.allocator.prefix_cache is True
        assert eng.model.kv_cache_dtype == "int8"
    finally:
        eng.stop()
    with pytest.raises(ValueError):
        build_llm_engine("llama:tiny:kv=fp4", start=False)


# ------------------------------------------------------------ chaos smoke

@pytest.mark.perf
def test_check_llm_decode_script_runs():
    """The decode hot-path smoke (scripts/check_llm_decode.py): a
    2-replica chunked-prefill group under concurrent mixed
    prefill/decode load — chunked streams byte-identical to the
    unchunked reference, decode-compiles==1, zero leaked KV blocks,
    and the overlapped tick pipeline's device-busy ratio above the CPU
    floor."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_llm_decode.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LLM DECODE OK" in proc.stdout


@pytest.mark.perf
def test_check_prefix_cache_script_runs():
    """The prefix-cache chaos smoke (scripts/check_prefix_cache.py): a
    2-replica group with prefix caching on, concurrent streams sharing
    a 400-token prefix — byte-identical to the no-cache reference
    across a mid-storm SIGKILL, hit-rate above the floor, zero leaked
    blocks, and the respawned replica re-warms."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_prefix_cache.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PREFIX CACHE OK" in proc.stdout


@pytest.mark.chaos
def test_check_llm_serving_script_runs():
    """The 2-replica SIGKILL smoke (scripts/check_llm_serving.py): a
    real supervised llama:tiny replica group streams concurrent
    mixed-length generations, loses one replica mid-stream, and the HA
    client contract holds — zero client-visible failures, token streams
    byte-identical to the reference, zero leaked KV blocks."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_llm_serving.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LLM SERVING OK" in proc.stdout


# -------------------------------------------------- tensor-parallel (mesh)

class TestTensorParallel:
    """mesh= support on PagedLlamaModel (docs/multichip.md): one set of
    weights + one paged KV cache span the mesh's model axis. The full
    token-identity acceptance check runs in scripts/check_multichip.py
    (multichip marker); these are the cheap unit guarantees."""

    def test_spec_parses_tp_knob(self):
        _, eng = parse_llm_spec("llama:tiny:tp=2,slots=4")
        assert eng["tp"] == 2 and eng["num_slots"] == 4

    def test_env_tp_knob(self, monkeypatch):
        from zoo_tpu.serving.llm.spec import _env_engine_defaults
        monkeypatch.setenv("ZOO_LLM_TP", "2")
        assert _env_engine_defaults()["tp"] == 2

    def test_kv_head_divisibility_enforced(self):
        """tiny config has n_kv_head=2: tp=3 cannot shard the KV cache
        on the heads axis and must refuse loudly at construction (not
        at first decode)."""
        import jax

        from zoo_tpu.models.llm.llama import tiny_llama_config
        from zoo_tpu.parallel import build_mesh
        from zoo_tpu.serving.llm.model import PagedLlamaModel

        if len(jax.devices()) < 3:
            pytest.skip("needs >= 3 devices")
        mesh = build_mesh(jax.devices()[:3], axis_sizes={"model": 3})
        with pytest.raises(ValueError, match="n_kv_head"):
            PagedLlamaModel(tiny_llama_config(), mesh=mesh)

    def test_tp_spec_needs_enough_devices(self, monkeypatch):
        import jax

        from zoo_tpu.serving.llm.spec import build_llm_engine
        n = len(jax.devices())
        with pytest.raises(ValueError, match="only"):
            build_llm_engine(f"llama:tiny:tp={n * 2}", start=False)

    def test_single_device_mesh_is_ignored(self):
        """mesh over one device (or size-1 model axis) degrades to the
        plain single-device layout — tp reported as 1."""
        import jax

        from zoo_tpu.models.llm.llama import tiny_llama_config
        from zoo_tpu.parallel import build_mesh
        from zoo_tpu.serving.llm.model import PagedLlamaModel

        mesh = build_mesh(jax.devices()[:1], axis_sizes={"data": 1})
        m = PagedLlamaModel(tiny_llama_config(), num_blocks=8, mesh=mesh)
        assert m.mesh is None and m.tp == 1
