import numpy as np
import pandas as pd
import pytest

from zoo_tpu.orca.data import XShards, LocalXShards


@pytest.fixture()
def csv_dir(tmp_path):
    for i in range(3):
        df = pd.DataFrame({
            "user": np.arange(i * 10, i * 10 + 10),
            "item": np.arange(10),
            "label": np.random.RandomState(i).randint(0, 2, 10),
        })
        df.to_csv(tmp_path / f"part{i}.csv", index=False)
    return str(tmp_path)


def test_partition_ndarray_and_dict():
    x = np.arange(100).reshape(50, 2)
    shards = XShards.partition(x, num_shards=4)
    assert shards.num_partitions() == 4
    np.testing.assert_array_equal(np.concatenate(shards.collect()), x)

    d = {"x": np.arange(10), "y": np.arange(10) * 2}
    shards = XShards.partition(d, num_shards=3)
    got = shards.stack_numpy()
    np.testing.assert_array_equal(got["x"], d["x"])
    np.testing.assert_array_equal(got["y"], d["y"])


def test_transform_and_repartition():
    shards = XShards.partition(np.arange(12.0), num_shards=3)
    doubled = shards.transform_shard(lambda a: a * 2)
    np.testing.assert_array_equal(np.concatenate(doubled.collect()),
                                  np.arange(12.0) * 2)
    re = doubled.repartition(5)
    assert re.num_partitions() == 5
    np.testing.assert_array_equal(np.concatenate(re.collect()),
                                  np.arange(12.0) * 2)


def test_read_csv(orca_ctx, csv_dir):
    from zoo_tpu.orca.data.pandas import read_csv

    shards = read_csv(csv_dir)
    assert shards.num_partitions() == 3
    assert len(shards) == 30
    stacked = shards.stack_numpy(["user", "label"])
    assert stacked["user"].shape == (30,)

    shards2 = read_csv(csv_dir, num_shards=2)
    assert shards2.num_partitions() == 2
    assert len(shards2) == 30


def test_read_csv_arrow_backend(orca_ctx, csv_dir):
    from zoo_tpu.orca import OrcaContext
    from zoo_tpu.orca.data.pandas import read_csv

    OrcaContext.pandas_read_backend = "arrow"
    try:
        shards = read_csv(csv_dir)
        assert len(shards) == 30
        assert set(shards.collect()[0].columns) == {"user", "item", "label"}
    finally:
        OrcaContext.pandas_read_backend = "pandas"


def test_shard_size_flag(orca_ctx, csv_dir):
    from zoo_tpu.orca import OrcaContext
    from zoo_tpu.orca.data.pandas import read_csv

    OrcaContext.shard_size = 7
    try:
        shards = read_csv(csv_dir)
        assert shards.num_partitions() == 5  # ceil(30/7)
        assert len(shards) == 30
    finally:
        OrcaContext.shard_size = None


def test_partition_by_and_unique():
    df = pd.DataFrame({"k": [1, 2, 1, 3, 2, 1], "v": range(6)})
    shards = LocalXShards([df.iloc[:3], df.iloc[3:]])
    parts = shards.partition_by("k", num_partitions=2)
    # all rows with the same key must be in the same partition
    for p in parts.collect():
        pass
    seen = {}
    for i, p in enumerate(parts.collect()):
        for k in p["k"].unique():
            assert seen.setdefault(k, i) == i
    u = LocalXShards([np.array([1, 2, 2]), np.array([3, 1])]).unique()
    np.testing.assert_array_equal(u, [1, 2, 3])


def test_split_and_zip():
    pairs = LocalXShards([(np.ones(2), np.zeros(2)), (np.ones(3), np.zeros(3))])
    xs, ys = pairs.split()
    assert xs.num_partitions() == 2
    z = xs.zip(ys)
    a, b = z.collect()[0]
    np.testing.assert_array_equal(a, np.ones(2))
    with pytest.raises(ValueError):
        xs.zip(LocalXShards([np.ones(1)]))


def test_save_load_pickle(tmp_path):
    shards = XShards.partition(np.arange(10), num_shards=2)
    shards.save_pickle(str(tmp_path / "pk"))
    back = LocalXShards.load_pickle(str(tmp_path / "pk"))
    assert back.num_partitions() == 2
    np.testing.assert_array_equal(np.concatenate(back.collect()), np.arange(10))


def test_host_local_to_global_from_shards(orca_ctx):
    import jax
    from jax.sharding import PartitionSpec as P
    from zoo_tpu.parallel.mesh import host_local_to_global

    shards = XShards.partition({"x": np.arange(16.0)}, num_shards=4)
    host = shards.stack_numpy()
    arr = host_local_to_global(orca_ctx.mesh, P("data"), host["x"])
    assert arr.shape == (16,)
    np.testing.assert_array_equal(np.asarray(arr), np.arange(16.0))
