"""TF1 graph-mode TRAINING through the zoo forwarder.

The reference's flagship training path: ``Estimator.from_graph``
(``pyzoo/zoo/orca/learn/tf/estimator.py:291``) and
``TFOptimizer.from_loss`` / ``from_train_op``
(``pyzoo/zoo/tfpark/tf_optimizer.py:464,514``) over user-built TF1
graphs. Here variables are captured as a JAX params pytree
(``bridges/tf_graph.capture_trainable_graph``) and jax.grad of the
interpreted loss trains on the mesh.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
tf1 = tf.compat.v1


@pytest.fixture(scope="module")
def lin_data():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(96, 5)).astype(np.float32)
    w = rng.normal(size=(5, 1)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(96, 1))).astype(np.float32)
    return x, w, y


def _linear_graph():
    g = tf1.Graph()
    with g.as_default():
        feat = tf1.placeholder(tf.float32, (None, 5), name="feat")
        lbl = tf1.placeholder(tf.float32, (None, 1), name="lbl")
        W = tf1.get_variable("W", shape=(5, 1),
                             initializer=tf1.zeros_initializer())
        b = tf1.get_variable("b", shape=(1,),
                             initializer=tf1.zeros_initializer())
        pred = tf.matmul(feat, W) + b
        loss = tf.reduce_mean(tf.square(pred - lbl))
    return g, feat, lbl, pred, loss, W


def test_estimator_from_graph_trains(orca_ctx, lin_data):
    from zoo.orca.learn.tf.estimator import Estimator

    x, w_true, y = lin_data
    g, feat, lbl, pred, loss, W = _linear_graph()
    est = Estimator.from_graph(inputs=[feat], outputs=[pred],
                               labels=[lbl], loss=loss,
                               optimizer="sgd")
    hist = est.fit({"x": x, "y": y}, epochs=25, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0] * 0.2
    # predict drives the captured forward with trained params
    p = est.predict({"x": x[:8]}, batch_size=8)
    assert p.shape == (8, 1)
    # evaluate returns the loss
    ev = est.evaluate({"x": x, "y": y}, batch_size=32)
    assert ev["loss"] == pytest.approx(hist["loss"][-1], rel=0.5)
    # trained weights are written back into the live session
    vals = est.get_model().run(W)
    assert np.linalg.norm(vals) > 0.1


def test_from_graph_classification_with_metrics(orca_ctx):
    from zoo.orca.learn.tf.estimator import Estimator

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    g = tf1.Graph()
    with g.as_default():
        feat = tf1.placeholder(tf.float32, (None, 8))
        lbl = tf1.placeholder(tf.int32, (None,))
        W = tf1.get_variable("W", shape=(8, 2),
                             initializer=tf1.glorot_uniform_initializer(
                                 seed=3))
        logits = tf.matmul(feat, W)
        loss = tf.reduce_mean(
            tf1.nn.sparse_softmax_cross_entropy_with_logits(
                labels=lbl, logits=logits))
        acc = tf.reduce_mean(tf.cast(tf.equal(
            tf.cast(tf.argmax(logits, 1), tf.int32), lbl), tf.float32))
    from zoo.orca.learn.optimizers import Adam
    est = Estimator.from_graph(inputs=[feat], outputs=[logits],
                               labels=[lbl], loss=loss,
                               optimizer=Adam(lr=0.05),
                               metrics={"acc": acc})
    before = est.evaluate({"x": x, "y": y})["acc"]
    est.fit({"x": x, "y": y}, epochs=10, batch_size=32)
    after = est.evaluate({"x": x, "y": y})["acc"]
    assert after > max(before, 0.8)


def test_tfoptimizer_from_loss_dataset_tensors(orca_ctx, lin_data):
    """The reference UX: build the model on dataset.tensors, from_loss
    locates the dataset through the loss graph."""
    from zoo.orca.learn.optimizers import SGD
    from zoo.orca.learn.trigger import MaxEpoch
    from zoo.tfpark import TFDataset, TFOptimizer

    x, w_true, y = lin_data
    g = tf1.Graph()
    with g.as_default():
        dataset = TFDataset.from_ndarrays((x, y), batch_size=32)
        feat, lbl = dataset.tensors
        W = tf1.get_variable("W", shape=(5, 1),
                             initializer=tf1.zeros_initializer())
        loss = tf.reduce_mean(tf.square(tf.matmul(feat, W) - lbl))
        opt = TFOptimizer.from_loss(loss, SGD(lr=0.05))
        hist = opt.optimize(end_trigger=MaxEpoch(20))
        assert hist["loss"][-1] < hist["loss"][0] * 0.1
        got = opt.sess.run(W)
    assert np.linalg.norm(got - w_true) < 0.3


def test_tfoptimizer_from_train_op_recovers_optimizer(orca_ctx,
                                                      lin_data):
    from zoo.orca.learn.trigger import MaxEpoch
    from zoo.tfpark import TFDataset, TFOptimizer

    x, _, y = lin_data
    g = tf1.Graph()
    with g.as_default():
        ds = TFDataset.from_ndarrays((x, y), batch_size=32)
        feat, lbl = ds.tensors
        W = tf1.get_variable("W", shape=(5, 1),
                             initializer=tf1.zeros_initializer())
        loss = tf.reduce_mean(tf.square(tf.matmul(feat, W) - lbl))
        train_op = tf1.train.GradientDescentOptimizer(0.05).minimize(
            loss)
        opt = TFOptimizer.from_train_op(train_op, loss)
        hist = opt.optimize(end_trigger=MaxEpoch(10))
    assert hist["loss"][-1] < hist["loss"][0] * 0.3


def test_from_train_op_schedule_lr_errors_gracefully(orca_ctx,
                                                     lin_data):
    """An lr behind a schedule subgraph is not a graph constant — the
    conversion must refuse with an actionable message, not train with a
    wrong rate."""
    from zoo.tfpark import TFDataset, TFOptimizer

    x, _, y = lin_data
    g = tf1.Graph()
    with g.as_default():
        ds = TFDataset.from_ndarrays((x, y), batch_size=32)
        feat, lbl = ds.tensors
        W = tf1.get_variable("W", shape=(5, 1),
                             initializer=tf1.zeros_initializer())
        loss = tf.reduce_mean(tf.square(tf.matmul(feat, W) - lbl))
        gs = tf1.train.get_or_create_global_step()
        lr = tf1.train.exponential_decay(0.1, gs, 100, 0.9)
        train_op = tf1.train.GradientDescentOptimizer(lr).minimize(
            loss, global_step=gs)
        with pytest.raises(NotImplementedError,
                           match="not a graph constant"):
            TFOptimizer.from_train_op(train_op, loss)


def test_from_loss_pretrained_session_weights_respected(orca_ctx,
                                                        lin_data):
    """from_loss(session=sess) must start from the session's CURRENT
    variable values (the pre-trained-model contract,
    tf_optimizer.py:514)."""
    from zoo.orca.learn.optimizers import SGD
    from zoo.tfpark import TFDataset, TFOptimizer
    from zoo.orca.learn.trigger import MaxEpoch

    x, w_true, y = lin_data
    g = tf1.Graph()
    with g.as_default():
        ds = TFDataset.from_ndarrays((x, y), batch_size=32)
        feat, lbl = ds.tensors
        W = tf1.get_variable("W", shape=(5, 1),
                             initializer=tf1.zeros_initializer())
        loss = tf.reduce_mean(tf.square(tf.matmul(feat, W) - lbl))
        sess = tf1.Session(graph=g)
        sess.run(tf1.global_variables_initializer())
        # "pre-trained": load the true weights before handing over
        init = W.initializer
        sess.run(init, feed_dict={init.inputs[1]: w_true})
        opt = TFOptimizer.from_loss(loss, SGD(lr=0.01), session=sess)
        hist = opt.optimize(end_trigger=MaxEpoch(1))
    # starting at the optimum, the first epoch's mean loss is already tiny
    assert hist["loss"][0] < 0.01


def test_graph_estimator_checkpoint_roundtrip(orca_ctx, lin_data,
                                              tmp_path):
    from zoo.orca.learn.tf.estimator import Estimator

    x, _, y = lin_data
    g, feat, lbl, pred, loss, W = _linear_graph()
    est = Estimator.from_graph(inputs=[feat], outputs=[pred],
                               labels=[lbl], loss=loss,
                               optimizer="sgd")
    est.fit({"x": x, "y": y}, epochs=5, batch_size=32)
    ck = est.save_checkpoint(str(tmp_path / "ck.pkl"))
    trained = est.predict({"x": x[:4]}, batch_size=4)

    g2, feat2, lbl2, pred2, loss2, W2 = _linear_graph()
    est2 = Estimator.from_graph(inputs=[feat2], outputs=[pred2],
                                labels=[lbl2], loss=loss2,
                                optimizer="sgd")
    est2.load_checkpoint(ck)
    np.testing.assert_allclose(est2.predict({"x": x[:4]}, batch_size=4),
                               trained, rtol=1e-5)


def test_from_graph_accepts_tf_train_optimizer(orca_ctx, lin_data):
    """The reference calling convention passes a tf.train optimizer;
    the hyperparameters are read off the instance."""
    from zoo.orca.learn.tf.estimator import Estimator

    x, _, y = lin_data
    g, feat, lbl, pred, loss, W = _linear_graph()
    with g.as_default():
        opt = tf1.train.GradientDescentOptimizer(0.05)
    est = Estimator.from_graph(inputs=[feat], outputs=[pred],
                               labels=[lbl], loss=loss, optimizer=opt)
    hist = est.fit({"x": x, "y": y}, epochs=15, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0] * 0.2


def test_from_loss_two_datasets_picks_feeding_one(orca_ctx, lin_data):
    """Two TFDatasets registering placeholders in one graph (train +
    val) must not confuse from_loss: loss ancestry disambiguates."""
    from zoo.orca.learn.optimizers import SGD
    from zoo.orca.learn.trigger import MaxEpoch
    from zoo.tfpark import TFDataset, TFOptimizer

    x, _, y = lin_data
    g = tf1.Graph()
    with g.as_default():
        ds_train = TFDataset.from_ndarrays((x, y), batch_size=32)
        feat, lbl = ds_train.tensors
        # a second dataset materializes placeholders AFTER the train one
        ds_val = TFDataset.from_ndarrays((np.zeros_like(x) + 100.0,
                                          np.zeros_like(y)),
                                         batch_size=32)
        vfeat, vlbl = ds_val.tensors
        W = tf1.get_variable("W", shape=(5, 1),
                             initializer=tf1.zeros_initializer())
        loss = tf.reduce_mean(tf.square(tf.matmul(feat, W) - lbl))
        _val_loss = tf.reduce_mean(
            tf.square(tf.matmul(vfeat, W) - vlbl))
        opt = TFOptimizer.from_loss(loss, SGD(lr=0.05))
        hist = opt.optimize(end_trigger=MaxEpoch(10))
    # trained on the REAL data (loss decreases), not the 100-valued val
    # arrays (whose least-squares solution differs wildly)
    assert hist["loss"][-1] < hist["loss"][0] * 0.2
    assert hist["loss"][0] < 50.0  # val arrays would start near 1e4


def test_trainable_graph_capture_is_pure(orca_ctx):
    """Interpreted loss is a pure jittable function: two calls with the
    same params/data agree, and grads are nonzero for used variables."""
    import jax

    from zoo_tpu.bridges.tf_graph import capture_trainable_graph

    g = tf1.Graph()
    with g.as_default():
        xp = tf1.placeholder(tf.float32, (None, 3))
        yp = tf1.placeholder(tf.float32, (None,))
        w = tf1.get_variable("w", shape=(3,),
                             initializer=tf1.ones_initializer())
        out = tf.reduce_sum(xp * w, axis=1)
        loss = tf.reduce_mean(tf.square(out - yp))
    trainable, sess, tvars = capture_trainable_graph(
        inputs=[xp], labels=[yp], loss=loss)
    assert set(trainable.params) == {"w"}
    x = np.ones((4, 3), np.float32)
    y = np.zeros((4,), np.float32)
    lf = jax.jit(lambda p: trainable.loss_fn(p, [x], [y]))
    l1, l2 = float(lf(trainable.params)), float(lf(trainable.params))
    assert l1 == l2 == pytest.approx(9.0)
    grads = jax.grad(lambda p: trainable.loss_fn(p, [x], [y]))(
        trainable.params)
    assert float(np.abs(np.asarray(grads["w"])).sum()) > 0


def test_tfestimator_model_fn_trains(orca_ctx):
    """``TFEstimator.from_model_fn`` (reference ``tfpark/estimator.py:30``)
    trains a TF1 model_fn graph end to end. ``ModeKeys``/``EstimatorSpec``
    come from zoo.tfpark — TensorFlow removed tf.estimator in 2.16."""
    from zoo.tfpark import EstimatorSpec, ModeKeys, TFDataset, TFEstimator

    rs = np.random.RandomState(0)
    x = rs.randn(128, 6).astype(np.float32)
    w_true = rs.randn(6, 1).astype(np.float32)
    y = (x @ w_true).astype(np.float32)

    def model_fn(features, labels, mode, params):
        W = tf1.get_variable("W", shape=(6, 1),
                             initializer=tf1.zeros_initializer())
        pred = tf.matmul(features, W)
        if mode == ModeKeys.PREDICT:
            return EstimatorSpec(mode, predictions={"pred": pred,
                                                    "twice": pred * 2})
        loss = tf.reduce_mean(tf.square(pred - labels))
        mae = tf.reduce_mean(tf.abs(pred - labels))
        train_op = tf1.train.GradientDescentOptimizer(0.1).minimize(loss)
        return EstimatorSpec(mode, predictions=pred, loss=loss,
                             train_op=train_op,
                             eval_metric_ops={"mae": mae})

    def input_fn():
        return TFDataset.from_ndarrays((x, y), batch_size=32)

    est = TFEstimator.from_model_fn(model_fn, params={})
    est.train(input_fn, steps=60)
    ev = est.evaluate(input_fn)
    assert ev["loss"] < 0.1, ev
    assert ev["mae"] < 0.4, ev  # eval_metric_ops carried through

    def pred_input_fn():
        return TFDataset.from_ndarrays(x[:16], batch_size=16)

    preds = est.predict(pred_input_fn, predict_keys="pred")
    assert preds.shape == (16, 1)
    # trained weights carried into the PREDICT-mode graph by name
    np.testing.assert_allclose(preds, (x[:16] @ w_true), atol=0.5)
    twice = est.predict(pred_input_fn, predict_keys="twice")
    np.testing.assert_allclose(twice, 2 * preds, rtol=1e-5)
    with pytest.raises(ValueError, match="unknown predict_keys"):
        est.predict(pred_input_fn, predict_keys="probabilities")
