"""Keras-2 façade: arg translation onto the keras-1 engine (reference:
``pyzoo/zoo/pipeline/api/keras2``)."""

import numpy as np
import pytest

from zoo_tpu.pipeline.api.keras2 import Input, Model, Sequential, layers as L


def test_dense_mlp_trains():
    m = Sequential(name="k2_mlp")
    m.add(L.Dense(32, activation="relu", input_shape=(16,),
                  kernel_initializer="glorot_uniform"))
    m.add(L.Dropout(rate=0.1))
    m.add(L.Dense(1, use_bias=False))
    m.compile(optimizer="adam", loss="mse")
    rs = np.random.RandomState(0)
    x = rs.randn(64, 16).astype(np.float32)
    y = x[:, :1]
    h = m.fit(x, y, batch_size=16, nb_epoch=4, verbose=0)
    assert h["loss"][-1] < h["loss"][0]
    # use_bias=False really dropped the bias
    last = [k for k in m.params if k.endswith("dense")][-1]
    assert "b" not in m.params[last]


def test_conv_pool_stack_shapes():
    m = Sequential(name="k2_conv")
    m.add(L.Conv2D(8, 3, padding="same", activation="relu",
                   input_shape=(16, 16, 3)))
    m.add(L.MaxPooling2D(pool_size=2))
    m.add(L.Conv2D(4, (3, 3), strides=(2, 2), padding="same"))
    m.add(L.GlobalAveragePooling2D())
    m.add(L.Dense(5, activation="softmax"))
    x = np.random.RandomState(1).rand(2, 16, 16, 3).astype(np.float32)
    y = np.asarray(m.predict(x, batch_size=2))
    assert y.shape == (2, 5)


def test_conv1d_and_pooling1d():
    m = Sequential()
    m.add(L.Conv1D(6, 3, strides=1, padding="valid",
                   input_shape=(10, 4)))
    m.add(L.MaxPooling1D(pool_size=2))
    m.add(L.GlobalMaxPooling1D())
    x = np.random.RandomState(2).rand(3, 10, 4).astype(np.float32)
    assert np.asarray(m.predict(x, batch_size=3)).shape == (3, 6)


def test_embedding_lstm():
    m = Sequential()
    m.add(L.Embedding(50, 8, input_length=6))
    m.add(L.LSTM(12, return_sequences=False))
    m.add(L.Dense(2, activation="softmax"))
    x = np.random.RandomState(3).randint(0, 50, (4, 6)).astype(np.int32)
    assert np.asarray(m.predict(x, batch_size=4)).shape == (4, 2)


def test_functional_merge_layers():
    a = Input(shape=(8,), name="a")
    b = Input(shape=(8,), name="b")
    mx = L.Maximum()([a, b])
    av = L.Average()([a, b])
    cat = L.Concatenate(axis=-1)([mx, av])
    out = L.Dense(3)(cat)
    model = Model(input=[a, b], output=out)
    xa = np.random.RandomState(4).randn(5, 8).astype(np.float32)
    xb = np.random.RandomState(5).randn(5, 8).astype(np.float32)
    y = np.asarray(model.predict([xa, xb], batch_size=5))
    assert y.shape == (5, 3)


def test_merge_semantics():
    a = Input(shape=(4,))
    b = Input(shape=(4,))
    model = Model(input=[a, b], output=L.Minimum()([a, b]))
    xa = np.array([[1., 5., 3., 0.]], np.float32)
    xb = np.array([[2., 4., 3., -1.]], np.float32)
    np.testing.assert_allclose(
        np.asarray(model.predict([xa, xb], batch_size=1)),
        np.minimum(xa, xb))


def test_advanced_activation_and_bn():
    m = Sequential()
    m.add(L.Dense(8, input_shape=(4,)))
    m.add(L.BatchNormalization())
    m.add(L.LeakyReLU(alpha=0.2))
    x = np.random.RandomState(6).randn(4, 4).astype(np.float32)
    assert np.asarray(m.predict(x, batch_size=4)).shape == (4, 8)
    with pytest.raises(ValueError, match="axis"):
        L.BatchNormalization(axis=1)


def test_unsupported_data_format_raises():
    with pytest.raises(ValueError, match="unknown data_format"):
        L.Conv2D(4, 3, data_format="weird")


def test_keras2_reference_parity_names():
    """Every public name in the reference keras2 package exists here
    (docs/keras-api.md parity list)."""
    import zoo_tpu.pipeline.api.keras2.layers as k2

    reference_names = [
        "Activation", "Average", "AveragePooling1D", "Conv1D", "Conv2D",
        "Cropping1D", "Dense", "Dropout", "Flatten",
        "GlobalAveragePooling1D", "GlobalAveragePooling2D",
        "GlobalMaxPooling1D", "LocallyConnected1D", "MaxPooling1D",
        "Maximum", "Minimum", "average", "maximum", "minimum",
    ]
    missing = [n for n in reference_names if not hasattr(k2, n)]
    assert not missing, missing


def test_keras2_functional_merges():
    import numpy as np

    from zoo_tpu.pipeline.api.keras2.layers import (Dense, average,
                                                    maximum, minimum)
    from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model

    a = Input(shape=(4,))
    d1 = Dense(3)(a)
    d2 = Dense(3)(a)
    for fn, np_fn in ((average, lambda x, y: (x + y) / 2),
                      (maximum, np.maximum), (minimum, np.minimum)):
        m = Model(input=a, output=fn([d1, d2]))
        x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
        out = m.predict(x, batch_size=5)
        assert out.shape == (5, 3)
