"""Fault-injected transient failures on the wired seams: the shard data
plane and the serving stack recover within their retry budgets; a broken
model trips the serving circuit breaker into load shedding."""

import time

import numpy as np
import pytest

from zoo_tpu.orca.data.plane import ShardExchange
from zoo_tpu.serving.server import ServingServer
from zoo_tpu.serving.tcp_client import TCPInputQueue
from zoo_tpu.util.resilience import (
    CircuitBreaker,
    RetryError,
    RetryPolicy,
    clear_faults,
    inject,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_faults()
    yield
    clear_faults()


def _fast_retry(attempts=4):
    return RetryPolicy(max_attempts=attempts, base_delay=0.01,
                       max_delay=0.05)


# ---------------------------------------------------------------------------
# shard.fetch
# ---------------------------------------------------------------------------

def test_shard_fetch_recovers_from_transient_faults():
    shards = {3: {"x": np.arange(8, dtype=np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    try:
        with inject("shard.fetch", exc=ConnectionError("flaky link"),
                    times=2) as armed:
            t0 = time.monotonic()
            got = ShardExchange.fetch(("127.0.0.1", ex.port), 3,
                                      retry=_fast_retry())
            assert time.monotonic() - t0 < 1.0  # backoff stays tiny
            assert armed.fired == 2  # both injected failures were hit
        np.testing.assert_array_equal(got["x"], shards[3]["x"])
    finally:
        ex.close()


def test_shard_fetch_exhausts_budget_on_permanent_fault():
    shards = {3: {"x": np.zeros(2, np.float32)}}
    ex = ShardExchange(shards, bind="127.0.0.1")
    try:
        with inject("shard.fetch", exc=ConnectionError("dead peer")):
            with pytest.raises(RetryError) as ei:
                ShardExchange.fetch(("127.0.0.1", ex.port), 3,
                                    retry=_fast_retry(attempts=3))
            assert ei.value.attempts == 3
    finally:
        ex.close()


def test_shard_fetch_missing_shard_is_not_retried():
    """KeyError (peer answers: not held) is a plan bug, not a transient —
    it must not burn the retry budget."""
    ex = ShardExchange({1: {"x": np.zeros(1, np.float32)}},
                       bind="127.0.0.1")
    try:
        t0 = time.monotonic()
        with pytest.raises(KeyError):
            ShardExchange.fetch(("127.0.0.1", ex.port), 99,
                                retry=RetryPolicy(max_attempts=5,
                                                  base_delay=0.5))
        assert time.monotonic() - t0 < 0.5  # no backoff sleeps happened
    finally:
        ex.close()


# ---------------------------------------------------------------------------
# serving client retry + server load shedding
# ---------------------------------------------------------------------------

class _DoublerModel:
    def predict(self, x, batch_size=None):
        return np.asarray(x) * 2.0


class _BrokenModel:
    def __init__(self):
        self.calls = 0
        self.healthy = False

    def predict(self, x, batch_size=None):
        self.calls += 1
        if not self.healthy:
            raise RuntimeError("model exploded")
        return np.asarray(x)


def test_serving_client_recovers_from_transient_faults():
    srv = ServingServer(_DoublerModel(), max_wait_ms=1.0).start()
    try:
        q = TCPInputQueue(host=srv.host, port=srv.port)
        q._conn._retry = _fast_retry()
        with inject("serving.request", exc=ConnectionError("blip"),
                    times=2) as armed:
            t0 = time.monotonic()
            out = q.predict(np.ones((2, 3), np.float32))
            assert time.monotonic() - t0 < 1.0
            assert armed.fired == 2
        np.testing.assert_array_equal(
            np.asarray(out), np.full((2, 3), 2.0, np.float32))
        q.close()
    finally:
        srv.stop()


def test_serving_client_reconnects_after_dropped_connection():
    """A poisoned stream (peer closed mid-RPC) must re-dial, not wedge."""
    srv = ServingServer(_DoublerModel(), max_wait_ms=1.0).start()
    try:
        q = TCPInputQueue(host=srv.host, port=srv.port)
        q._conn._retry = _fast_retry()
        q._conn._sock.close()  # simulate the server dropping us
        out = q.predict(np.ones((1, 2), np.float32))
        np.testing.assert_array_equal(
            np.asarray(out), np.full((1, 2), 2.0, np.float32))
        q.close()
    finally:
        srv.stop()


def test_breaker_sheds_load_and_recovers():
    model = _BrokenModel()
    breaker = CircuitBreaker(failure_threshold=1, recovery_timeout=0.2)
    srv = ServingServer(model, max_wait_ms=1.0, breaker=breaker).start()
    try:
        q = TCPInputQueue(host=srv.host, port=srv.port)
        # 1st request reaches the model and fails -> breaker opens
        with pytest.raises(RuntimeError, match="model exploded"):
            q.predict(np.ones((1, 2), np.float32))
        calls_after_trip = model.calls
        # while open, requests are rejected at the door: model untouched
        with pytest.raises(RuntimeError, match="shedding load"):
            q.predict(np.ones((1, 2), np.float32))
        assert model.calls == calls_after_trip
        # model heals; after the recovery timeout a probe closes the loop
        model.healthy = True
        time.sleep(0.25)
        out = q.predict(np.ones((1, 2), np.float32))
        assert np.asarray(out).shape == (1, 2)
        assert breaker.state == CircuitBreaker.CLOSED
        q.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# deterministic fault schedules (ZOO_FAULT_SEED / seed=)
# ---------------------------------------------------------------------------

def test_seeded_injector_replays_exact_schedule():
    """Two injectors with the same seed fire a probabilistic site on the
    exact same draws — a chaos run that found a bug replays bit-for-bit."""
    from zoo_tpu.util.resilience import FaultInjector, InjectedFault

    def schedule(seed):
        inj = FaultInjector(seed=seed)
        inj.inject("seam", exc=InjectedFault("boom"), p=0.5)
        fired = []
        for i in range(64):
            try:
                inj.fire("seam")
                fired.append(0)
            except InjectedFault:
                fired.append(1)
        return fired

    a, b = schedule(1234), schedule(1234)
    assert a == b and 0 < sum(a) < 64
    assert schedule(999) != a  # different seed, different schedule


def test_fault_seed_env_and_reseed(monkeypatch):
    from zoo_tpu.util.resilience import FaultInjector, InjectedFault

    monkeypatch.setenv("ZOO_FAULT_SEED", "42")
    inj = FaultInjector()
    assert inj.fault_seed == 42

    def draw(injector, n=32):
        injector.inject("seam", exc=InjectedFault("boom"), p=0.5,
                        times=None)
        out = []
        for _ in range(n):
            try:
                injector.fire("seam")
                out.append(0)
            except InjectedFault:
                out.append(1)
        injector.clear("seam")
        return out

    first = draw(inj)
    inj.reseed()  # re-reads $ZOO_FAULT_SEED: restart the sequence
    assert draw(inj) == first
    assert FaultInjector(seed=42).fault_seed == 42
