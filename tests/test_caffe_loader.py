"""Caffe loader: synthetic caffemodel binary + deploy prototxt round-trip.

Mirrors the reference's CaffeLoader specs (load a conv/pool/fc net, check
forward numerics) without needing caffe: the NetParameter is hand-encoded
with the same wire codec the loader decodes with."""

import numpy as np
import pytest

from zoo_tpu.tensorboard import proto as wire
from zoo_tpu.models.caffe_loader import (
    CaffeNetParameter, load_caffe, parse_prototxt)
from zoo_tpu.pipeline.api.net import Net


def _blob(arr):
    arr = np.asarray(arr, np.float32)
    shape = b"".join(wire.field_varint(1, d) for d in arr.shape)
    data = b"".join(wire.field_float(5, float(v)) for v in arr.reshape(-1))
    return wire.field_bytes(7, shape) + data


def _layer(name, type_, bottoms, tops, blobs=(), param_field=None,
           param_bytes=b""):
    out = wire.field_bytes(1, name.encode())
    out += wire.field_bytes(2, type_.encode())
    for b in bottoms:
        out += wire.field_bytes(3, b.encode())
    for t in tops:
        out += wire.field_bytes(4, t.encode())
    for bl in blobs:
        out += wire.field_bytes(7, _blob(bl))
    if param_field:
        out += wire.field_bytes(param_field, param_bytes)
    return out


def _make_model(tmp_path):
    rng = np.random.RandomState(0)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    fc_w = (0.1 * rng.randn(2, 4 * 4 * 4)).astype(np.float32)
    fc_b = (0.1 * rng.randn(2)).astype(np.float32)

    conv_param = (wire.field_varint(1, 4) + wire.field_varint(4, 3)
                  + wire.field_varint(6, 1) + wire.field_varint(3, 1))
    pool_param = (wire.field_varint(1, 0) + wire.field_varint(2, 2)
                  + wire.field_varint(3, 2))
    ip_param = wire.field_varint(1, 2)

    net = wire.field_bytes(1, b"testnet")
    net += wire.field_bytes(3, b"data")
    for d in (1, 3, 8, 8):
        net += wire.field_varint(4, d)
    net += wire.field_bytes(100, _layer("conv1", "Convolution", ["data"],
                                        ["conv1"], [w, b], 106, conv_param))
    net += wire.field_bytes(100, _layer("relu1", "ReLU", ["conv1"],
                                        ["conv1"]))
    net += wire.field_bytes(100, _layer("pool1", "Pooling", ["conv1"],
                                        ["pool1"], (), 121, pool_param))
    net += wire.field_bytes(100, _layer("fc1", "InnerProduct", ["pool1"],
                                        ["fc1"], [fc_w, fc_b], 117,
                                        ip_param))
    net += wire.field_bytes(100, _layer("prob", "Softmax", ["fc1"],
                                        ["prob"]))
    path = tmp_path / "model.caffemodel"
    path.write_bytes(net)
    return str(path), (w, b, fc_w, fc_b)


def _numpy_forward(x, w, b, fc_w, fc_b):
    n, _, h, w_ = x.shape
    co, ci, kh, kw = w.shape
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
    conv = np.zeros((n, co, h, w_), np.float32)
    for i in range(h):
        for j in range(w_):
            patch = xp[:, :, i:i + kh, j:j + kw]
            conv[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w) + b
    relu = np.maximum(conv, 0)
    pool = relu.reshape(n, co, 4, 2, 4, 2).max(axis=(3, 5))
    fc = pool.reshape(n, -1) @ fc_w.T + fc_b
    e = np.exp(fc - fc.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_binary_parse(tmp_path):
    path, _ = _make_model(tmp_path)
    with open(path, "rb") as f:
        net = CaffeNetParameter(f.read())
    assert net.name == "testnet"
    assert [l.type for l in net.layers] == [
        "Convolution", "ReLU", "Pooling", "InnerProduct", "Softmax"]
    assert net.inputs == ["data"]
    assert net.input_shapes == [(1, 3, 8, 8)]
    assert net.layers[0].blobs[0].shape == (4, 3, 3, 3)


def test_forward_matches_numpy(tmp_path):
    path, (w, b, fc_w, fc_b) = _make_model(tmp_path)
    model = Net.load_caffe(None, path)
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    y = np.asarray(model.predict(x, batch_size=2))
    ref = _numpy_forward(x, w, b, fc_w, fc_b)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_prototxt_topology(tmp_path):
    path, (w, b, fc_w, fc_b) = _make_model(tmp_path)
    deploy = """
    name: "testnet"
    input: "data"
    input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
            convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
    layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
    layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
            pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
    layer { name: "fc1" type: "InnerProduct" bottom: "pool1" top: "fc1"
            inner_product_param { num_output: 2 } }
    layer { name: "prob" type: "Softmax" bottom: "fc1" top: "prob" }
    """
    def_path = tmp_path / "deploy.prototxt"
    def_path.write_text(deploy)
    model = load_caffe(str(def_path), path)
    x = np.random.RandomState(2).randn(2, 3, 8, 8).astype(np.float32)
    y = np.asarray(model.predict(x, batch_size=2))
    ref = _numpy_forward(x, w, b, fc_w, fc_b)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_prototxt_parser_basics():
    net = parse_prototxt(
        'name: "n" # comment\nlayer { name: "l" include { phase: TRAIN } }')
    assert net["name"] == ["n"]
    assert net["layer"][0]["include"][0]["phase"] == ["TRAIN"]


def test_train_phase_layers_skipped(tmp_path):
    path, _ = _make_model(tmp_path)
    deploy = """
    input: "data"
    input_dim: 1 input_dim: 3 input_dim: 8 input_dim: 8
    layer { name: "aug" type: "Data" top: "data" include { phase: TRAIN } }
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
            convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
    """
    def_path = tmp_path / "d.prototxt"
    def_path.write_text(deploy)
    model = load_caffe(str(def_path), path)
    assert [l.name for l in model.caffe_layers] == ["conv1"]


def test_deconvolution_matches_torch(tmp_path):
    """Caffe Deconvolution == torch ConvTranspose2d (in, out/g, kh, kw)."""
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(4)
    w = rng.randn(3, 5, 4, 4).astype(np.float32)  # (in, out, kh, kw)
    b = rng.randn(5).astype(np.float32)
    conv_param = (wire.field_varint(1, 5) + wire.field_varint(4, 4)
                  + wire.field_varint(6, 2) + wire.field_varint(3, 1))
    net = wire.field_bytes(3, b"data")
    for d in (1, 3, 6, 6):
        net += wire.field_varint(4, d)
    net += wire.field_bytes(100, _layer("up", "Deconvolution", ["data"],
                                        ["up"], [w, b], 106, conv_param))
    path = tmp_path / "deconv.caffemodel"
    path.write_bytes(net)
    model = load_caffe(None, str(path))
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    y = np.asarray(model.predict(x, batch_size=2))
    tconv = torch.nn.ConvTranspose2d(3, 5, 4, stride=2, padding=1)
    with torch.no_grad():
        tconv.weight.copy_(torch.from_numpy(w))
        tconv.bias.copy_(torch.from_numpy(b))
        ref = tconv(torch.from_numpy(x)).numpy()
    assert y.shape == ref.shape == (2, 5, 12, 12)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_ave_pool_pad_denominator(tmp_path):
    """Caffe AVE pool divides by window clipped to the padded extent:
    corner of an all-ones input with k=3,s=2,p=1 is 4/9, not 1."""
    pool_param = (wire.field_varint(1, 1) + wire.field_varint(2, 3)
                  + wire.field_varint(3, 2) + wire.field_varint(4, 1))
    net = wire.field_bytes(3, b"data")
    for d in (1, 1, 4, 4):
        net += wire.field_varint(4, d)
    net += wire.field_bytes(100, _layer("p", "Pooling", ["data"], ["p"],
                                        (), 121, pool_param))
    path = tmp_path / "ave.caffemodel"
    path.write_bytes(net)
    model = load_caffe(None, str(path))
    y = np.asarray(model.predict(np.ones((1, 1, 4, 4), np.float32),
                                 batch_size=1))
    np.testing.assert_allclose(y[0, 0, 0, 0], 4.0 / 9.0, rtol=1e-5)
    np.testing.assert_allclose(y[0, 0, 1, 1], 1.0, rtol=1e-5)


def test_new_format_allcaps_types_not_mangled(tmp_path):
    """'ELU' is a legitimate new-format type name, not a V1 enum."""
    path, _ = _make_model(tmp_path)
    deploy = """
    input: "data"
    input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
            convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
    layer { name: "e" type: "ELU" bottom: "conv1" top: "e" }
    layer { name: "ip" type: "INNER_PRODUCT" bottom: "e" top: "ip"
            inner_product_param { num_output: 2 } }
    """
    def_path = tmp_path / "elu.prototxt"
    def_path.write_text(deploy)
    model = load_caffe(str(def_path), path)
    assert [l.type for l in model.caffe_layers] == [
        "Convolution", "ELU", "InnerProduct"]
    model.caffe_layers[2].blobs = []  # no weights for 'ip' in the binary
    types = [l.type for l in model.caffe_layers]
    assert "Elu" not in types


def test_missing_bottom_raises(tmp_path):
    path, _ = _make_model(tmp_path)
    deploy = """
    input: "data"
    input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
    layer { name: "conv1" type: "Convolution" bottom: "nope" top: "conv1"
            convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
    """
    def_path = tmp_path / "bad.prototxt"
    def_path.write_text(deploy)
    model = load_caffe(str(def_path), path)
    with pytest.raises(KeyError, match="undefined bottom"):
        model.predict(np.zeros((1, 3, 8, 8), np.float32), batch_size=1)


def test_finetune_caffe_model(tmp_path):
    """A loaded caffe net trains like any zoo model (blobs are params)."""
    path, _ = _make_model(tmp_path)
    model = Net.load_caffe(None, path)
    x = np.random.RandomState(3).randn(8, 3, 8, 8).astype(np.float32)
    yt = np.eye(2, dtype=np.float32)[np.arange(8) % 2]
    model.compile(optimizer="adam", loss="categorical_crossentropy")
    h0 = model.evaluate(x, yt, batch_size=8)
    model.fit(x, yt, batch_size=8, nb_epoch=12, verbose=0)
    h1 = model.evaluate(x, yt, batch_size=8)
    assert h1["loss"] < h0["loss"]
