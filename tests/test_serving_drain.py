"""ServingServer graceful drain: on SIGTERM/drain() the server stops
ACCEPTING predicts (retryable "draining" error at the door), finishes
every request already queued or in flight, flushes the metrics
snapshot, and only then closes — no accepted request is ever dropped.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from zoo_tpu.serving.server import ServingServer
from zoo_tpu.serving.tcp_client import TCPInputQueue

pytestmark = pytest.mark.chaos


class _SlowDouble:
    """Deterministic stand-in model: y = 2x, taking real wall time per
    batch so drain always races against in-flight work."""

    def __init__(self, delay=0.03):
        self.delay = delay

    def predict(self, arr, batch_size=None):
        time.sleep(self.delay)
        return np.asarray(arr) * 2.0


def test_drain_finishes_inflight_and_rejects_new(tmp_path):
    server = ServingServer(_SlowDouble(), port=0, batch_size=4,
                           max_wait_ms=2.0).start()
    snap_path = str(tmp_path / "drain-snapshot.jsonl")
    n_clients, per_client = 6, 4
    results = {}  # (client, i) -> "ok" | "draining" | "dropped"
    lock = threading.Lock()

    def client(cid):
        q = TCPInputQueue(host=server.host, port=server.port)
        for i in range(per_client):
            x = np.full((2, 3), float(cid * 10 + i), np.float32)
            try:
                out = q.predict(x)
                np.testing.assert_allclose(np.asarray(out), x * 2.0)
                tag = "ok"
            except RuntimeError as e:
                # the ONLY acceptable refusal is the drain-door error;
                # a timeout would mean an accepted request was dropped
                tag = "draining" if "draining" in str(e) else \
                    f"dropped:{e}"
            with lock:
                results[(cid, i)] = tag
        q.close()

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(0.08)  # let a few batches queue up / run
    drained = server.drain(timeout=30.0, snapshot_path=snap_path)
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)

    tags = list(results.values())
    assert len(tags) == n_clients * per_client
    assert not [t for t in tags if t.startswith("dropped")], tags
    # the drain raced real traffic: both outcomes must be present
    assert "ok" in tags, tags
    assert "draining" in tags, tags
    assert drained, "queued+in-flight work must finish inside timeout"

    # the final metrics snapshot survived the shutdown
    assert os.path.exists(snap_path)
    with open(snap_path) as f:
        snap = json.loads(f.readlines()[-1])
    counters = {(c["name"], c["labels"].get("outcome")): c["value"]
                for c in snap["metrics"]["counters"]
                if c["name"] == "zoo_serving_requests_total"}
    # handler threads tally "ok" after the batcher releases them, so the
    # snapshot may trail the last batch by a few — but it must carry the
    # bulk of the served traffic and the shed tally
    assert counters.get(("zoo_serving_requests_total", "ok"), 0) >= 1
    assert counters.get(("zoo_serving_requests_total", "shed"), 0) >= 1

    # post-drain the server is fully closed: fresh connections fail
    with pytest.raises(Exception):
        TCPInputQueue(host=server.host, port=server.port).predict(
            np.zeros((1, 3), np.float32))


def test_drain_handler_installs_only_on_main_thread():
    server = ServingServer(_SlowDouble(0.0), port=0, batch_size=2,
                           max_wait_ms=1.0).start()
    try:
        holder = {}

        def worker():
            holder["installed"] = server.install_drain_handler()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert holder["installed"] is False  # refused off-main
    finally:
        server.stop()


def test_sigterm_triggers_drain():
    """A real SIGTERM delivered to the process routes into drain():
    in-flight work completes, the door closes."""
    import signal

    server = ServingServer(_SlowDouble(0.02), port=0, batch_size=4,
                           max_wait_ms=2.0).start()
    prev = signal.getsignal(signal.SIGTERM)
    assert server.install_drain_handler(timeout=20.0)
    try:
        q = TCPInputQueue(host=server.host, port=server.port)
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(q.predict(x)), x * 2)
        os.kill(os.getpid(), signal.SIGTERM)
        # the drain runs on a helper thread; wait for the door to close
        deadline = time.monotonic() + 10
        closed = False
        while time.monotonic() < deadline:
            if server._stop.is_set():
                closed = True
                break
            time.sleep(0.02)
        assert closed, "SIGTERM never drained the server"
        q.close()
    finally:
        signal.signal(signal.SIGTERM, prev)
