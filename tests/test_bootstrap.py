"""Process supervision (orca.bootstrap): spawn/watch/restart/teardown of
a local multi-process JAX cluster (reference: RayContext +
ProcessMonitor + JVMGuard behaviors)."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from zoo_tpu.orca.bootstrap import (
    ProcessMonitor,
    WorkerProcess,
    free_port,
    launch_local_cluster,
)

# real subprocesses, each paying a fresh JAX import/compile
pytestmark = pytest.mark.slow


def _script(tmp_path, body, name="w.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_cluster_forms_and_completes(tmp_path):
    script = _script(tmp_path, f"""
        import os, sys
        sys.path.insert(0, {os.getcwd()!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from zoo_tpu.orca import init_orca_context
        init_orca_context(cluster_mode="tpu")
        assert jax.process_count() == 2, jax.process_count()
        pid = int(os.environ["ZOO_PROCESS_ID"])
        open(os.path.join({str(tmp_path)!r}, f"done{{pid}}"), "w").close()
    """)
    mon = launch_local_cluster(2, script, local_devices_per_proc=2)
    mon.wait(timeout=180)
    assert os.path.exists(str(tmp_path / "done0"))
    assert os.path.exists(str(tmp_path / "done1"))
    assert mon.alive() == []


def test_restart_budget_recovers_crash(tmp_path):
    marker = str(tmp_path / "crashed_once")
    script = _script(tmp_path, f"""
        import os, sys
        if not os.path.exists({marker!r}):
            open({marker!r}, "w").close()
            sys.exit(3)  # first attempt crashes
        open({marker!r} + ".ok", "w").close()
    """)
    w = WorkerProcess([sys.executable, script], dict(os.environ), "w0")
    mon = ProcessMonitor([w], max_restarts=1).start()
    mon.wait(timeout=60)
    assert os.path.exists(marker + ".ok")
    assert w.restarts == 1


def test_no_budget_fails_and_tears_down(tmp_path):
    crash = _script(tmp_path, "import sys; sys.exit(7)", "crash.py")
    hang = _script(tmp_path, "import time; time.sleep(600)", "hang.py")
    w0 = WorkerProcess([sys.executable, crash], dict(os.environ), "crash")
    w1 = WorkerProcess([sys.executable, hang], dict(os.environ), "hang")
    mon = ProcessMonitor([w0, w1], max_restarts=0).start()
    with pytest.raises(RuntimeError, match="rc=7"):
        mon.wait(timeout=60)
    # the healthy-but-hung peer was killed with the group
    deadline = time.time() + 10
    while w1.returncode is None and time.time() < deadline:
        time.sleep(0.1)
    assert w1.returncode is not None


def test_stop_kills_children(tmp_path):
    hang = _script(tmp_path, "import time; time.sleep(600)")
    w = WorkerProcess([sys.executable, hang], dict(os.environ), "h")
    mon = ProcessMonitor([w]).start()
    time.sleep(0.5)
    pid = w.proc.pid
    mon.stop()
    with pytest.raises(OSError):
        os.kill(pid, 0)  # gone (or reparented-and-dead → ESRCH)


def test_cli_entrypoint(tmp_path):
    ok = _script(tmp_path, "print('hi')", "ok.py")
    proc = subprocess.run(
        [sys.executable, "-m", "zoo_tpu.orca.bootstrap", "--nproc", "2",
         ok],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": os.getcwd() + os.pathsep +
             os.environ.get("PYTHONPATH", "")})
    assert proc.returncode == 0, proc.stderr[-1500:]


def test_free_port_is_bindable():
    import socket
    p = free_port()
    with socket.socket() as s:
        s.bind(("127.0.0.1", p))


def test_elastic_search_gated():
    """ES I/O degrades to a clear ImportError when the client package is
    absent (this image does not bundle it)."""
    from zoo_tpu.orca.data.elastic_search import elastic_search
    try:
        import elasticsearch  # noqa: F401
        pytest.skip("elasticsearch installed; gating not exercisable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="elasticsearch"):
        elastic_search.read_df({"es.nodes": "localhost"}, "idx")


def test_wait_timeout_zero_is_immediate(tmp_path):
    hang = tmp_path / "hang2.py"
    hang.write_text("import time; time.sleep(600)")
    w = WorkerProcess([sys.executable, str(hang)], dict(os.environ), "h2")
    mon = ProcessMonitor([w]).start()
    with pytest.raises(TimeoutError):
        mon.wait(timeout=0)
    assert w.returncode is not None  # torn down by the timeout path


def test_deliberate_stop_is_not_failure(tmp_path):
    hang = tmp_path / "hang3.py"
    hang.write_text("import time; time.sleep(600)")
    w = WorkerProcess([sys.executable, str(hang)], dict(os.environ), "h3")
    mon = ProcessMonitor([w], max_restarts=0).start()
    time.sleep(0.5)
    mon.stop()
    time.sleep(0.6)  # let the watcher observe the killed worker
    assert mon._failed is None
    mon.wait(timeout=5)  # returns: deliberate stop, not a crash


def test_hung_worker_detected_by_heartbeat(tmp_path):
    """A worker that stops beating (hung, not exited) is killed and
    charged to the restart budget like any crash."""
    hb = str(tmp_path / "w.heartbeat")
    script = _script(tmp_path, f"""
        import sys, time
        sys.path.insert(0, {os.getcwd()!r})
        from zoo_tpu.util.resilience import touch_heartbeat
        touch_heartbeat({hb!r})
        time.sleep(600)  # hangs: never beats again
    """, name="hung.py")
    w = WorkerProcess([sys.executable, script], dict(os.environ), "hw",
                      heartbeat_file=hb)
    mon = ProcessMonitor([w], max_restarts=0, poll_interval=0.1,
                         heartbeat_timeout=1.0).start()
    with pytest.raises(RuntimeError, match="heartbeat stale"):
        mon.wait(timeout=60)
    assert w.returncode is not None  # the hung process was killed


def test_hung_worker_restarts_within_budget(tmp_path):
    """First incarnation hangs after stamping once; the respawned one
    completes. The heartbeat path must spend the restart budget, not
    tear the group down."""
    marker = str(tmp_path / "hung_once")
    hb = str(tmp_path / "w2.heartbeat")
    script = _script(tmp_path, f"""
        import os, sys, time
        sys.path.insert(0, {os.getcwd()!r})
        from zoo_tpu.util.resilience import touch_heartbeat
        touch_heartbeat({hb!r})
        if not os.path.exists({marker!r}):
            open({marker!r}, "w").close()
            time.sleep(600)  # first run hangs
        open({marker!r} + ".ok", "w").close()
    """, name="hang_once.py")
    w = WorkerProcess([sys.executable, script], dict(os.environ), "hw2",
                      heartbeat_file=hb)
    mon = ProcessMonitor([w], max_restarts=1, poll_interval=0.1,
                         heartbeat_timeout=1.0).start()
    mon.wait(timeout=60)
    assert os.path.exists(marker + ".ok")
    assert w.restarts == 1


def test_heartbeat_env_reaches_workers(tmp_path):
    """launch_local_cluster with heartbeat_timeout hands every worker a
    ZOO_HEARTBEAT_FILE and the supervisor watches it."""
    script = _script(tmp_path, """
        import os
        assert os.environ.get("ZOO_HEARTBEAT_FILE"), "no heartbeat env"
        assert float(os.environ["ZOO_HEARTBEAT_INTERVAL"]) > 0
    """, name="hb_env.py")
    mon = launch_local_cluster(2, script, heartbeat_timeout=30.0,
                               log_dir=str(tmp_path / "logs"))
    mon.wait(timeout=60)
    assert mon.heartbeat_timeout == 30.0
    for w in mon.workers:
        assert w.heartbeat_file and os.path.exists(w.heartbeat_file)
