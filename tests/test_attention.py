import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zoo_tpu.ops.attention import dot_product_attention, merge_heads, split_heads
from zoo_tpu.pipeline.api.keras.layers.self_attention import (
    BERT,
    LayerNorm,
    TransformerLayer,
)


def test_dot_product_attention_matches_manual():
    rs = np.random.RandomState(0)
    q = rs.randn(1, 2, 4, 8).astype(np.float32)
    k = rs.randn(1, 2, 4, 8).astype(np.float32)
    v = rs.randn(1, 2, 4, 8).astype(np.float32)
    out = np.asarray(dot_product_attention(*map(jnp.asarray, (q, k, v))))
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(8)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    manual = np.einsum("bhqk,bhkd->bhqd", probs, v)
    np.testing.assert_allclose(out, manual, rtol=1e-4)


def test_attention_mask_blocks_positions():
    rs = np.random.RandomState(0)
    q = k = v = jnp.asarray(rs.randn(1, 1, 4, 4).astype(np.float32))
    mask = jnp.asarray([[True, True, False, False]])[:, None, None, :]
    out = dot_product_attention(q, k, v, mask=mask)
    # perturb masked-out positions; output must not change
    k2 = k.at[:, :, 2:].set(99.0)
    v2 = v.at[:, :, 2:].set(99.0)
    out2 = dot_product_attention(q, k2, v2, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-5)


def test_split_merge_heads_roundtrip():
    x = jnp.arange(2 * 3 * 8.0).reshape(2, 3, 8)
    np.testing.assert_array_equal(
        np.asarray(merge_heads(split_heads(x, 4))), np.asarray(x))


@pytest.mark.heavy
def test_transformer_causal_no_leak():
    t = TransformerLayer(vocab=50, seq_len=8, n_block=2, hidden_size=16,
                         n_head=2)
    p = t.build(jax.random.PRNGKey(0), (None, 8))
    ids = np.random.RandomState(0).randint(0, 50, (2, 8))
    y1 = np.asarray(t.call(p, jnp.asarray(ids)))
    ids2 = ids.copy()
    ids2[:, -1] = (ids2[:, -1] + 7) % 50
    y2 = np.asarray(t.call(p, jnp.asarray(ids2)))
    np.testing.assert_allclose(y1[:, :-1], y2[:, :-1], atol=1e-6)
    assert np.abs(y1[:, -1] - y2[:, -1]).max() > 1e-4


@pytest.mark.heavy
def test_bert_outputs_and_mask():
    b = BERT(vocab=60, hidden_size=16, n_block=2, n_head=2, seq_len=8,
             intermediate_size=32, max_position_len=8)
    p = b.build(jax.random.PRNGKey(0), (None, 8))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 60, (2, 8)))
    seg = jnp.zeros((2, 8), jnp.int32)
    mask = jnp.asarray(np.array([[1] * 8, [1] * 4 + [0] * 4]))
    seq = b.call(p, [ids, seg, mask])
    assert seq.shape == (2, 8, 16)
    pool = b.pooled_output(p, seq)
    assert pool.shape == (2, 16)
    # masked tokens must not affect unmasked outputs of row 1
    ids2 = np.asarray(ids).copy()
    ids2[1, 6] = (ids2[1, 6] + 3) % 60
    seq2 = b.call(p, [jnp.asarray(ids2), seg, mask])
    np.testing.assert_allclose(np.asarray(seq)[1, :4],
                               np.asarray(seq2)[1, :4], atol=1e-5)


def test_layernorm():
    ln = LayerNorm()
    p = ln.build(jax.random.PRNGKey(0), (None, 6))
    x = jnp.asarray(np.random.RandomState(0).randn(3, 6) * 5 + 2)
    y = np.asarray(ln.call(p, x))
    np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1, atol=1e-2)


@pytest.mark.slow
def test_tiny_bert_classifier_trains(orca_ctx):
    """BERT + pooler + head, end-to-end fit on a toy task: does the first
    token id determine the class."""
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.engine.base import Layer
    from zoo_tpu.pipeline.api.keras.layers import Dense, Lambda
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    rs = np.random.RandomState(0)
    n, T = 128, 8
    x = rs.randint(0, 20, (n, T)).astype(np.int32)
    y = (x[:, 0] % 2).astype(np.int32)

    m = Sequential()
    m.add(TransformerLayer(vocab=20, seq_len=T, n_block=1, hidden_size=16,
                           n_head=2, hidden_drop=0.0, attn_drop=0.0,
                           bidirectional=True, input_shape=(T,)))
    m.add(Lambda(lambda h: h[:, 0], output_shape=(16,)))
    m.add(Dense(2, activation="softmax"))
    m.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = m.fit(x, y, batch_size=32, nb_epoch=8, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.7


@pytest.mark.parametrize("remat", ["dots", True])
def test_transformer_remat_trains(orca_ctx, remat):
    """remat policies compile and train (the bench BERT row runs
    remat='dots'); loss matches the no-remat path step-for-step
    (remat changes memory, never math)."""
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense, Lambda, BERT

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 50, (32, 8)).astype(np.int32)
    y = rs.randint(0, 2, 32).astype(np.int32)

    losses = {}
    for rm in (False, remat):
        m = Sequential()
        m.add(BERT(vocab=50, hidden_size=16, n_block=2, n_head=2,
                   seq_len=8, intermediate_size=32, hidden_p_drop=0.0,
                   attn_p_drop=0.0, max_position_len=8, remat=rm,
                   input_shape=(8,)))
        m.add(Lambda(lambda h: h[:, 0], output_shape=(16,)))
        m.add(Dense(2))
        m.compile(optimizer="sgd",
                  loss="sparse_categorical_crossentropy_from_logits")
        h = m.fit(ids, y, batch_size=16, nb_epoch=2, shuffle=False,
                  verbose=0, seed=0)
        losses[rm] = h["loss"]
    np.testing.assert_allclose(losses[False], losses[remat], rtol=1e-4)
