"""TF2 creator Estimator, keras/frozen-graph bridges, ONNX loader.

The ONNX fixture is hand-encoded with the same wire codec the loader
decodes with, laid out per the public onnx.proto3 field numbers — the
``onnx`` package is not available in this environment (reference:
``onnx_loader.py:1`` builds the layer graph from a parsed ModelProto)."""

import numpy as np
import pytest

from zoo_tpu.tensorboard import proto as wire


# ---------------------------------------------------------- onnx encoder

def _tensor(name, arr):
    arr = np.asarray(arr)
    dt = {np.dtype("float32"): 1, np.dtype("int64"): 7}[arr.dtype]
    out = b""
    for d in arr.shape:
        out += wire.field_varint(1, d)
    out += wire.field_varint(2, dt)
    out += wire.field_bytes(8, name.encode())
    out += wire.field_bytes(9, arr.tobytes())
    return out


def _attr_i(name, v):
    return (wire.field_bytes(1, name.encode()) + wire.field_varint(3, v))


def _node(op, inputs, outputs, attrs=b""):
    out = b""
    for i in inputs:
        out += wire.field_bytes(1, i.encode())
    for o in outputs:
        out += wire.field_bytes(2, o.encode())
    out += wire.field_bytes(4, op.encode())
    if attrs:
        out += wire.field_message(5, attrs)
    return out


def _value_info(name):
    return wire.field_bytes(1, name.encode())


def _mlp_onnx():
    """x(4) -> Gemm(W1 8, transB) -> Relu -> Gemm(W2 2) -> out"""
    rs = np.random.RandomState(0)
    w1 = rs.randn(8, 4).astype(np.float32)   # onnx Gemm B often (out,in)
    b1 = rs.randn(8).astype(np.float32)
    w2 = rs.randn(2, 8).astype(np.float32)
    b2 = rs.randn(2).astype(np.float32)
    graph = b""
    graph += wire.field_message(1, _node(
        "Gemm", ["x", "w1", "b1"], ["h"], _attr_i("transB", 1)))
    graph += wire.field_message(1, _node("Relu", ["h"], ["hr"]))
    graph += wire.field_message(1, _node(
        "Gemm", ["hr", "w2", "b2"], ["y"], _attr_i("transB", 1)))
    for nm, a in (("w1", w1), ("b1", b1), ("w2", w2), ("b2", b2)):
        graph += wire.field_message(5, _tensor(nm, a))
    graph += wire.field_message(11, _value_info("x"))
    graph += wire.field_message(12, _value_info("y"))
    model = wire.field_varint(1, 8) + wire.field_message(7, graph)
    ref = (w1, b1, w2, b2)
    return model, ref


def test_onnx_load_and_forward(orca_ctx):
    from zoo_tpu.pipeline.api.onnx import load_onnx

    model_bytes, (w1, b1, w2, b2) = _mlp_onnx()
    net = load_onnx(model_bytes)
    x = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    got = net.predict(x, batch_size=16)
    ref = np.maximum(x @ w1.T + b1, 0) @ w2.T + b2
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_onnx_model_finetunes(orca_ctx):
    from zoo_tpu.pipeline.api.onnx import load_onnx

    model_bytes, _ = _mlp_onnx()
    net = load_onnx(model_bytes)
    net.compile(optimizer="adam", loss="mse")
    rs = np.random.RandomState(2)
    x = rs.randn(128, 4).astype(np.float32)
    y = rs.randn(128, 2).astype(np.float32)
    hist = net.fit(x, y, batch_size=32, nb_epoch=5, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_onnx_unknown_op_message(orca_ctx):
    from zoo_tpu.pipeline.api.onnx import load_onnx

    graph = wire.field_message(1, _node("FancyOp", ["x"], ["y"]))
    graph += wire.field_message(11, _value_info("x"))
    graph += wire.field_message(12, _value_info("y"))
    model = wire.field_message(7, graph)
    net = load_onnx(model)
    with pytest.raises(NotImplementedError, match="FancyOp"):
        net.predict(np.zeros((2, 4), np.float32), batch_size=2)


# ------------------------------------------------------------- tf paths

tf = pytest.importorskip("tensorflow")


def test_tf2_estimator_creator_flow(orca_ctx):
    from zoo_tpu.orca.learn.tf2 import Estimator

    def model_creator(config):
        m = tf.keras.Sequential([
            tf.keras.layers.Input(shape=(6,)),
            tf.keras.layers.Dense(12, activation="relu"),
            tf.keras.layers.Dense(2, activation="softmax"),
        ])
        m.compile(optimizer=tf.keras.optimizers.Adam(config["lr"]),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        return m

    rs = np.random.RandomState(0)
    x = rs.randn(256, 6).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    est = Estimator.from_keras(model_creator=model_creator,
                               config={"lr": 0.01})
    # converted forward must match keras exactly before training
    ref = est._kmodel.predict(x[:16], verbose=0)
    got = est.predict(x[:16])
    np.testing.assert_allclose(got, ref, atol=1e-4)

    hist = est.fit({"x": x, "y": y}, epochs=5, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    res = est.evaluate({"x": x, "y": y})
    assert res["accuracy"] > 0.7
    # trained weights flow back into the keras model
    km = est.get_model()
    np.testing.assert_allclose(km.predict(x[:16], verbose=0),
                               est.predict(x[:16]), atol=1e-3)


def test_tf2_estimator_data_creator(orca_ctx):
    from zoo_tpu.orca.learn.tf2 import Estimator

    rs = np.random.RandomState(0)
    x = rs.randn(128, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)

    def model_creator(config):
        m = tf.keras.Sequential([
            tf.keras.layers.Input(shape=(4,)),
            tf.keras.layers.Dense(8, activation="relu"),
            tf.keras.layers.Dense(2, activation="softmax")])
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        return m

    def data_creator(config, batch_size):
        return tf.data.Dataset.from_tensor_slices((x, y)).batch(batch_size)

    est = Estimator.from_keras(model_creator=model_creator)
    hist = est.fit(data_creator, epochs=2, batch_size=32)
    assert len(hist["loss"]) == 2


def test_frozen_graph_savedmodel(orca_ctx, tmp_path):
    from zoo_tpu.pipeline.inference import InferenceModel

    m = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(8, 8, 3)),
        tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(2),
    ])
    x = np.random.RandomState(0).randn(4, 8, 8, 3).astype(np.float32)
    ref = m.predict(x, verbose=0)
    d = str(tmp_path / "sm")
    tf.saved_model.save(m, d)
    im = InferenceModel()
    im.load_tf(d)
    got = im.predict(x)
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_frozen_graph_tf_function(orca_ctx):
    from zoo_tpu.bridges.tf_graph import convert_tf_callable

    @tf.function
    def fn(a, b):
        return tf.nn.softmax(tf.tanh(a @ tf.transpose(b)), axis=-1)

    aa = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    bb = np.random.RandomState(1).randn(6, 4).astype(np.float32)
    ref = fn(aa, bb).numpy()
    g = convert_tf_callable(fn, [aa, bb])
    import jax.numpy as jnp

    got = np.asarray(g(jnp.asarray(aa), jnp.asarray(bb)))
    np.testing.assert_allclose(got, ref, atol=1e-5)
