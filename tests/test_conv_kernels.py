"""Implicit-GEMM Pallas conv vs ``lax.conv_general_dilated`` (interpret
mode on the hermetic CPU rig — the same kernels compile via Mosaic on
TPU) plus the ``resolve_conv_impl`` dispatch contract (docs/kernels.md).

The 1x1 path is a pure strided GEMM and the int8 path dequantizes on
the same integer values as the reference, so both are exactly equal;
the 3x3 f32 path differs only by summation order."""

import numpy as np
import pytest

import jax.numpy as jnp

from zoo_tpu.ops.pallas import conv2d, conv2d_int8, resolve_conv_impl
from zoo_tpu.ops.pallas.conv import pallas_conv_supported
from zoo_tpu.ops.pallas.quant import quantize_conv_weights, quantized_conv2d


def _xw(h=8, w=8, c=8, k=3, o=24, n=2, seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(n, h, w, c), jnp.float32)
    wts = jnp.asarray(rs.randn(k, k, c, o), jnp.float32)
    return x, wts


@pytest.mark.parametrize("h,w,c,k,stride,padding", [
    (8, 8, 8, 1, 1, "SAME"),
    (8, 8, 8, 1, 2, "SAME"),
    (9, 9, 16, 1, 2, "VALID"),
    (8, 8, 8, 3, 1, "SAME"),
    (8, 8, 16, 3, 1, "VALID"),
    (7, 7, 130, 3, 1, "SAME"),     # channels past one lane tile
])
def test_conv2d_pallas_matches_lax(h, w, c, k, stride, padding):
    x, wts = _xw(h, w, c, k)
    out = conv2d(x, wts, strides=(stride, stride), padding=padding,
                 impl="pallas")
    ref = conv2d(x, wts, strides=(stride, stride), padding=padding,
                 impl="reference")
    assert out.shape == ref.shape
    # f32 sum-order differs (register accumulation vs XLA's schedule);
    # error grows with the 9*C reduction length, ~5e-5 at C=130
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=2e-4)


@pytest.mark.parametrize("k,stride,padding", [
    (1, 1, "SAME"), (1, 2, "VALID"), (3, 1, "SAME"), (3, 1, "VALID"),
])
def test_conv2d_int8_pallas_matches_reference_exactly(k, stride, padding):
    """Same quantized integers in, same dequant math out: the int8
    Pallas conv and the XLA reference agree bit for bit off-TPU."""
    x, wts = _xw(k=k)
    w_q, w_scale = quantize_conv_weights(wts)
    amax = jnp.max(jnp.abs(x), axis=(1, 2, 3), keepdims=True)
    x_scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    x_q = jnp.clip(jnp.round(x / x_scale), -127, 127)
    out = conv2d_int8(x_q, w_q, x_scale, w_scale.astype(jnp.float32),
                      strides=(stride, stride), padding=padding,
                      impl="pallas")
    ref = conv2d_int8(x_q, w_q, x_scale, w_scale.astype(jnp.float32),
                      strides=(stride, stride), padding=padding,
                      impl="reference")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_quantized_conv2d_impl_agnostic():
    """The quantize_model serving path (quantized_conv2d) produces the
    same activations whichever backend the dispatch picks."""
    x, wts = _xw(k=3)
    w_q, w_scale = quantize_conv_weights(wts)
    y_p = quantized_conv2d(x, w_q, w_scale, impl="pallas")
    y_r = quantized_conv2d(x, w_q, w_scale, impl="reference")
    np.testing.assert_array_equal(np.asarray(y_p), np.asarray(y_r))
    # and the int8 conv tracks the float conv to quantization noise
    ref = conv2d(x, wts, impl="reference")
    rel = (np.abs(np.asarray(y_p - ref)).mean()
           / np.abs(np.asarray(ref)).mean())
    assert rel < 0.03, rel


def test_pallas_conv_supported_matrix():
    assert pallas_conv_supported((1, 1), (1, 1), (1, 1))
    assert pallas_conv_supported((1, 1), (2, 2), (1, 1))
    assert pallas_conv_supported((3, 3), (1, 1), (1, 1))
    assert not pallas_conv_supported((3, 3), (2, 2), (1, 1))
    assert not pallas_conv_supported((5, 5), (1, 1), (1, 1))
    assert not pallas_conv_supported((3, 3), (1, 1), (2, 2))


def test_resolve_conv_impl_dispatch(monkeypatch):
    # auto off-TPU -> the XLA reference (bit-identical, no interpret tax)
    assert resolve_conv_impl(kernel=(3, 3)) == "reference"
    # env knob overrides auto at the single dispatch point
    monkeypatch.setenv("ZOO_CONV_IMPL", "pallas")
    assert resolve_conv_impl(kernel=(3, 3)) == "pallas"
    monkeypatch.setenv("ZOO_CONV_IMPL", "reference")
    assert resolve_conv_impl(kernel=(1, 1)) == "reference"
    monkeypatch.delenv("ZOO_CONV_IMPL")
    # a pallas request on an unsupported shape fails loudly, never
    # silently falls back
    with pytest.raises(ValueError, match="envelope"):
        resolve_conv_impl("pallas", kernel=(5, 5))
    with pytest.raises(ValueError):
        resolve_conv_impl("no-such-impl", kernel=(1, 1))
