"""Elastic training: retry-from-latest-checkpoint supervision.

Reference semantics: ``Topology.scala:1255-1337`` — on any Throwable the
optimizer reloads the newest ``model.N``/``optimMethod-*.N`` snapshot and
continues, bounded by ``bigdl.failure.retryTimes`` within a sliding time
window. The fault here is injected by sabotaging the jitted train step
mid-epoch — the supervisor must restore and finish with a decreasing loss
trajectory.
"""

import numpy as np
import pytest

from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense


def _make_model():
    m = Sequential()
    m.add(Dense(16, input_shape=(8,), activation="relu"))
    m.add(Dense(1))
    m.compile(optimizer="adam", loss="mse")
    return m


def _data(n=512, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    w = rs.randn(8, 1).astype(np.float32)
    return {"x": x, "y": (x @ w).astype(np.float32)}


class _SabotagedStep:
    """Wraps the jitted train step; raises once at a given global call."""

    def __init__(self, real, fail_at_call: int):
        self.real = real
        self.calls = 0
        self.fail_at = fail_at_call
        self.fired = False

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls == self.fail_at and not self.fired:
            self.fired = True
            raise RuntimeError("injected mid-epoch fault")
        return self.real(*args, **kwargs)


@pytest.mark.heavy
def test_elastic_retry_resumes_training(orca_ctx, tmp_path):
    data = _data()
    est = Estimator.from_keras(_make_model(), model_dir=str(tmp_path))

    # epoch 1 clean (checkpoint written), then sabotage epoch 2 mid-way
    h1 = est.fit(data, epochs=1, batch_size=64)
    assert est._ckpt.latest_step() == 1

    est.model.build()
    if est.model._jit_train is None:
        est.model._jit_train = est.model._build_train_step()
    sab = _SabotagedStep(est.model._jit_train, fail_at_call=3)
    est.model._jit_train = sab

    h2 = est.fit(data, epochs=2, batch_size=64)
    assert sab.fired  # the fault actually happened mid-epoch
    # supervisor restored and completed both epochs
    assert len(h2["loss"]) == 2
    assert est._epoch == 3
    # loss trajectory continues downward across the fault
    assert h2["loss"][-1] < h1["loss"][0]
    # post-fault the model is usable
    preds = est.predict(data["x"][:8])
    assert np.isfinite(preds).all()


def test_elastic_retries_exhaust(orca_ctx, tmp_path):
    data = _data(n=128)
    est = Estimator.from_keras(_make_model(), model_dir=str(tmp_path))
    est.fit(data, epochs=1, batch_size=64)

    class _AlwaysFail:
        def __call__(self, *a, **k):
            raise RuntimeError("permanent fault")

    est.model._jit_train = _AlwaysFail()
    with pytest.raises(RuntimeError, match="permanent fault"):
        est.fit(data, epochs=1, batch_size=64, max_failure_retries=2)


def test_failure_without_checkpoint_dir_propagates(orca_ctx):
    data = _data(n=128)
    est = Estimator.from_keras(_make_model())  # no model_dir → no ckpts
    est.fit(data, epochs=1, batch_size=64)

    class _AlwaysFail:
        def __call__(self, *a, **k):
            raise RuntimeError("no restore possible")

    est.model._jit_train = _AlwaysFail()
    with pytest.raises(RuntimeError, match="no restore possible"):
        est.fit(data, epochs=1, batch_size=64)


def test_optimizer_state_restored(orca_ctx, tmp_path):
    """The snapshot must carry optimizer state (momentum etc.), not just
    params — the reference reloads ``optimMethod-*.N`` too."""
    data = _data(n=128)
    est = Estimator.from_keras(_make_model(), model_dir=str(tmp_path))
    est.fit(data, epochs=2, batch_size=64)
    assert est.model._opt_state is not None
    est._restore_latest()
    restored = est.model._opt_state
    assert restored is not None
    # adam state: step count reflects training progress
    import jax

    leaves = jax.tree_util.tree_leaves(restored)
    assert any(np.asarray(l).size > 0 for l in leaves)


def test_checkpoint_roundtrip_with_sharded_state(tmp_path):
    """Elastic restart under FSDP: checkpoints written from mesh-sharded
    train state must restore into a placement-identical tree that
    continues the exact trajectory."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zoo_tpu.orca.learn.ckpt import CheckpointManager
    from zoo_tpu.parallel import build_mesh
    from zoo_tpu.parallel.plans import place_params

    n = len(jax.devices())
    if n < 4 or n % 2 or 8 % (n // 2):
        pytest.skip("needs a device count whose data axis divides the "
                    "8-row batch (the conftest's 8-device mesh)")
    mesh = build_mesh(jax.devices()[:n],
                      axis_sizes={"data": n // 2, "fsdp": 2})
    rs = np.random.RandomState(0)
    params = place_params(
        {"w1": rs.randn(16, 16).astype(np.float32),
         "w2": rs.randn(16, 4).astype(np.float32)}, mesh)
    x = jax.device_put(rs.randn(8, 16).astype(np.float32),
                       NamedSharding(mesh, P("data")))
    y = jax.device_put(rs.randn(8, 4).astype(np.float32),
                       NamedSharding(mesh, P("data")))

    @jax.jit
    def step(p, x, y):
        def loss(p):
            return ((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2).mean()
        l, g = jax.value_and_grad(loss)(p)
        return jax.tree_util.tree_map(lambda w, gr: w - 0.1 * gr, p, g), l

    with mesh:
        params, _ = step(params, x, y)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, params)
        # original path trains on
        cont, l_cont = step(params, x, y)
        # restart path: restore from disk, re-place on the mesh, train
        restored = place_params(mgr.restore(), mesh)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                    np.asarray(b)),
            restored, params)
        resumed, l_res = step(restored, x, y)
    assert float(l_cont) == pytest.approx(float(l_res), rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6),
        cont, resumed)


@pytest.mark.chaos
@pytest.mark.timeout(300)
def test_check_elastic_script_runs():
    """The 2→1 scale-down CPU smoke (scripts/check_elastic.py): worker 1
    dies permanently, the supervisor relaunches world 1, and the
    relaunched run resumes from the checkpoint ``ZOO_ELASTIC_ATTEMPT``
    signals — with heartbeat liveness enabled across both attempts (the
    stale-heartbeat-file carryover regression)."""
    import os
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_elastic.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=280)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ELASTIC OK" in out.stdout, out.stdout + out.stderr
