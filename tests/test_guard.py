"""Training guardian (zoo_tpu/orca/learn/guard.py): the escalation
ladder against REAL guarded fits — an injected NaN batch is skipped
without corrupting params, a forced divergence rolls back to the last
verified checkpoint and the run still converges, budget exhaustion
raises ``TrainingDiverged`` (never retried), and a preemption request
produces a checkpoint a fresh run resumes from. The jitted fold itself
must be a bit-exact no-op on clean data (guarded == unguarded losses).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from zoo_tpu.orca.learn.guard import (
    PREEMPT_EXIT_CODE,
    GuardConfig,
    Preempted,
    TrainingDiverged,
    TrainingGuard,
)
from zoo_tpu.orca.learn.keras import Estimator
from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import Dense
from zoo_tpu.util.resilience import inject

pytestmark = [pytest.mark.guard, pytest.mark.chaos]


def _data(n=256, feat=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, feat).astype(np.float32)
    w = rs.randn(feat, 1).astype(np.float32)
    return {"x": x, "y": (x @ w).astype(np.float32)}


def _model(seed=0):
    m = Sequential()
    m.add(Dense(16, input_shape=(8,), activation="relu"))
    m.add(Dense(1))
    m.compile(optimizer="adam", loss="mse")
    return m


def _poison(site=None, arrays=None, idx=None, **_):
    for a in arrays:
        a[:] = np.nan


def test_guarded_matches_unguarded_on_clean_data(tmp_path):
    """The in-step fold must not perturb healthy training by one ulp:
    the cond's good branch IS the unguarded update."""
    data = _data()
    e1 = Estimator.from_keras(_model(), guard=False)
    h1 = e1.fit(data, epochs=3, batch_size=32)
    e2 = Estimator.from_keras(_model(), guard=TrainingGuard(
        config=GuardConfig(enabled=True, preempt_signal="none")))
    h2 = e2.fit(data, epochs=3, batch_size=32)
    assert h1["loss"] == h2["loss"], (h1["loss"], h2["loss"])


def test_nan_batch_skipped_params_stay_finite(tmp_path):
    """Layer 1: a poison batch mid-fit is folded away — finite loss,
    finite params, quarantine JSONL + obs counter record the skip."""
    import jax

    data = _data()
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, max_skips=100, preempt_signal="none"))
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path),
                               guard=guard)
    est.fit(data, epochs=1, batch_size=32)
    with inject("fit.batch", action=_poison, exc=None, times=1) as armed:
        h = est.fit(data, epochs=1, batch_size=32)
    assert armed.fired == 1
    assert guard.nonfinite_steps > 0
    assert np.isfinite(h["loss"]).all(), h["loss"]
    leaves = jax.tree_util.tree_leaves(est.model.params)
    assert all(np.isfinite(np.asarray(a)).all() for a in leaves)
    qpath = os.path.join(str(tmp_path), "guard", "quarantine.jsonl")
    events = [json.loads(line) for line in open(qpath)]
    skip = [e for e in events if e["event"] == "nonfinite_steps"]
    assert skip and skip[0]["bad_in_window"] > 0
    assert skip[0]["batch_lo"] is not None  # provenance hint recorded


def test_divergence_rolls_back_and_converges(tmp_path):
    """Layer 2: a streak of poisoned superbatches triggers restore from
    the last verified checkpoint; once the fault schedule ends the run
    converges below its pre-fault loss."""
    data = _data()
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, max_skips=4, preempt_signal="none"))
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path),
                               guard=guard)
    h0 = est.fit(data, epochs=1, batch_size=32)
    with inject("fit.batch", action=_poison, exc=None, times=2):
        h = est.fit(data, epochs=4, batch_size=32)
    assert guard.rollbacks >= 1
    assert np.isfinite(h["loss"]).all()
    assert h["loss"][-1] < h0["loss"][0], (h0["loss"], h["loss"])
    events = [json.loads(line) for line in open(
        os.path.join(str(tmp_path), "guard", "quarantine.jsonl"))]
    assert any(e["event"] == "rollback" for e in events)


def test_budget_exhaustion_raises_diverged_not_retried(tmp_path):
    """A permanently poisoned stream exhausts the rollback budget and
    raises TrainingDiverged straight through the estimator's retry
    perimeter (retrying the same snapshot would diverge again)."""
    data = _data()
    data["x"][:128] = np.nan  # half the rows: every shuffled batch dies
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, max_skips=4, rollback_budget=2,
        preempt_signal="none"))
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path),
                               guard=guard)
    est.model.build()
    with pytest.raises(TrainingDiverged):
        est.fit(data, epochs=8, batch_size=32)
    assert guard.rollbacks == 2  # budget spent, then gave up


def test_no_checkpoint_escalates_to_diverged():
    """Without a model_dir there is nothing to roll back to: the ladder
    skips straight from streak to TrainingDiverged."""
    data = _data()
    data["x"][:] = np.nan
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, max_skips=4, preempt_signal="none"))
    est = Estimator.from_keras(_model(), guard=guard)
    with pytest.raises(TrainingDiverged):
        est.fit(data, epochs=2, batch_size=32)
    assert guard.rollbacks == 0


def test_preempt_checkpoints_and_resumes(tmp_path):
    """Layer 3: a preemption request checkpoints at the next step
    boundary and exits with the resume-don't-retry code; a fresh run
    resumes from that snapshot and completes."""
    data = _data()
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, preempt_signal="none"))
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path),
                               guard=guard)
    est.fit(data, epochs=1, batch_size=32)
    guard.request_preempt()
    with pytest.raises(Preempted) as ei:
        est.fit(data, epochs=5, batch_size=32)
    assert ei.value.code == PREEMPT_EXIT_CODE == 75
    assert guard.preempt_checkpoints == 1
    assert issubclass(Preempted, SystemExit)  # uncaught ⇒ exit code 75

    est2 = Estimator.from_keras(_model(), model_dir=str(tmp_path))
    est2.load_orca_checkpoint(path=str(tmp_path))
    h = est2.fit(data, epochs=2, batch_size=32)
    assert np.isfinite(h["loss"]).all()


def test_sigterm_routes_to_guard_during_fit(tmp_path):
    """During a guarded fit SIGTERM means checkpoint-and-exit(75), and
    the previous handler is restored afterwards."""
    import signal

    data = _data()
    before = signal.getsignal(signal.SIGTERM)
    guard = TrainingGuard(config=GuardConfig(enabled=True))
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path),
                               guard=guard)
    est.fit(data, epochs=1, batch_size=32)
    installed = {}

    # raise the signal from inside the fit via a poison-free fault hook
    def kick(site=None, arrays=None, idx=None, **_):
        if not installed:
            installed["x"] = True
            os.kill(os.getpid(), signal.SIGTERM)

    with inject("fit.batch", action=kick, exc=None, times=1):
        with pytest.raises(Preempted):
            est.fit(data, epochs=5, batch_size=32)
    assert guard.preempt_checkpoints == 1
    assert signal.getsignal(signal.SIGTERM) == before  # restored


def test_guard_disabled_by_env(monkeypatch, tmp_path):
    monkeypatch.setenv("ZOO_GUARD", "0")
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path))
    assert est._guard is None
    assert est.model._active_guard() is None


def test_epoch_dispatch_path_guarded(tmp_path):
    """Device-resident small datasets take the whole-epoch-in-one-
    dispatch path; the guard's counters must flow through it too."""
    import jax.numpy as jnp

    data = _data()
    xd, yd = jnp.asarray(data["x"]), jnp.asarray(data["y"])
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, preempt_signal="none"))
    est = Estimator.from_keras(_model(), model_dir=str(tmp_path),
                               guard=guard)
    h = est.fit({"x": xd, "y": yd}, epochs=2, batch_size=32)
    assert np.isfinite(h["loss"]).all()
    assert est.model._opt_state is not None
    # the carry was shed: saved aux must be plain optimizer state
    assert not (isinstance(est.model._opt_state, tuple)
                and len(est.model._opt_state) == 2
                and isinstance(est.model._opt_state[1], dict)
                and "bad" in est.model._opt_state[1])


def test_gan_guard_skips_poison_batch():
    """The GAN estimator's adversarial iteration folds away whole when
    a sub-loss goes non-finite."""
    from zoo_tpu.orca.learn.gan import GANEstimator

    rs = np.random.RandomState(0)
    real = rs.randn(64, 8).astype(np.float32)
    g = Sequential()
    g.add(Dense(16, input_shape=(8,), activation="relu"))
    g.add(Dense(8))
    d = Sequential()
    d.add(Dense(16, input_shape=(8,), activation="relu"))
    d.add(Dense(1))
    guard = TrainingGuard(config=GuardConfig(
        enabled=True, max_skips=1000, preempt_signal="none"))
    gan = GANEstimator(g, d, noise_dim=8, guard=guard)
    poisoned = real.copy()
    poisoned[:16] = np.nan
    h = gan.fit({"x": poisoned}, epochs=2, batch_size=16)
    assert guard.nonfinite_steps > 0
    assert np.isfinite(h["d_loss"]).all() and np.isfinite(
        h["g_loss"]).all()
    import jax
    for net in (gan.g, gan.d):
        assert all(np.isfinite(np.asarray(a)).all()
                   for a in jax.tree_util.tree_leaves(net.params))


def test_chronos_forecaster_inherits_guard(monkeypatch):
    """Chronos forecasters train through the guarded step: a poisoned
    window skips instead of NaN-ing the model."""
    from zoo_tpu.chronos.forecaster.lstm_forecaster import LSTMForecaster

    monkeypatch.setenv("ZOO_GUARD_MAX_SKIPS", "1000")
    monkeypatch.setenv("ZOO_PREEMPT", "none")
    rs = np.random.RandomState(0)
    x = rs.randn(128, 12, 2).astype(np.float32)
    y = rs.randn(128, 1, 2).astype(np.float32)
    f = LSTMForecaster(past_seq_len=12, input_feature_num=2,
                       output_feature_num=2)
    with inject("fit.batch", action=_poison, exc=None, times=1) as armed:
        f.fit((x, y), epochs=1, batch_size=32)
    assert armed.fired == 1
    g = f.model._active_guard()
    assert g is not None and g.nonfinite_steps > 0
    preds = f.predict((x, None))
    assert np.isfinite(preds).all()


def test_check_guard_script_runs():
    """The jax-free escalation-ladder smoke (scripts/check_guard.py)
    passes in-suite, like the perf/obs smokes."""
    out = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_guard.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "GUARD OK" in out.stdout, out.stdout + out.stderr
