import numpy as np
import pandas as pd
import pytest

from zoo_tpu.friesian.feature import FeatureTable, StringIndex


@pytest.fixture()
def tbl():
    return FeatureTable.from_pandas(pd.DataFrame({
        "user": ["a", "b", "a", "c", "b", "a"],
        "item": [1, 2, 3, 1, 2, 2],
        "price": [10.0, np.nan, 30.0, 40.0, 50.0, np.nan],
        "ts": [1, 2, 3, 4, 5, 6],
    }))


def test_fillna_fillmedian_log_clip(tbl):
    t = tbl.fillna(0.0, columns=["price"])
    assert t.df["price"].isna().sum() == 0
    t2 = tbl.fill_median(["price"])
    assert t2.df["price"].iloc[1] == 35.0  # median of 10,30,40,50
    t3 = tbl.fillna(0.0, ["price"]).log(["price"])
    np.testing.assert_allclose(t3.df["price"].iloc[0], np.log1p(10.0))
    t4 = tbl.clip(["item"], min=2)
    assert t4.df["item"].min() == 2
    # original untouched (ops return new tables)
    assert tbl.df["price"].isna().sum() == 2


def test_string_index_roundtrip(tbl):
    [idx] = tbl.gen_string_idx("user")
    assert idx.mapping["a"] == 1  # most frequent gets id 1
    enc = tbl.encode_string("user", [idx])
    assert enc.df["user"].tolist()[0] == 1
    enc2, [idx2] = tbl.category_encode("user")
    assert idx2.size == 3
    # unseen value maps to 0
    other = FeatureTable.from_pandas(pd.DataFrame({"user": ["zz"]}))
    assert other.encode_string("user", [idx]).df["user"].iloc[0] == 0


def test_cross_columns_and_one_hot(tbl):
    t = tbl.cross_columns([["user", "item"]], [100])
    assert "user_item" in t.df.columns
    assert t.df["user_item"].between(0, 99).all()
    t2 = tbl.one_hot_encode(["user"])
    assert {"user_a", "user_b", "user_c"} <= set(t2.df.columns)


def test_neg_sampling(tbl):
    t = tbl.select("user", "item")
    out = t.add_neg_samples(item_size=10, item_col="item", neg_num=2)
    assert len(out.df) == 6 * 3
    assert (out.df["label"] == 0).sum() == 12
    negs = out.df[out.df["label"] == 0]
    assert negs["item"].between(1, 10).all()


def test_hist_seq_and_pad(tbl):
    t = tbl.add_hist_seq(["item"], user_col="user", sort_col="ts",
                         min_len=1, max_len=2)
    row = t.df[t.df["user"] == "a"].iloc[-1]
    assert row["item_hist_seq"] == [3, 2][:-1] + [2] or \
        isinstance(row["item_hist_seq"], list)
    padded = t.pad(["item_hist_seq"], seq_len=4,
                   mask_cols=["item_hist_seq_mask"])
    assert all(len(v) == 4 for v in padded.df["item_hist_seq"])
    assert all(len(v) == 4 for v in padded.df["item_hist_seq_mask"])


def test_relational_and_shards(tbl):
    prices = FeatureTable.from_pandas(pd.DataFrame({
        "item": [1, 2, 3], "cat": ["x", "y", "z"]}))
    j = tbl.join(prices, on="item")
    assert "cat" in j.df.columns and len(j.df) == 6
    g = tbl.group_by("user", {"item": "count"})
    assert set(g.df.columns) >= {"user"}
    shards = tbl.to_shards(2)
    assert shards.num_partitions() == 2
    assert sum(len(s) for s in shards.collect()) == 6
    u = tbl.union(tbl)
    assert u.size() == 12


def test_normalize_minmax(tbl):
    t = tbl.fillna(0, ["price"]).normalize(["price"])
    assert abs(t.df["price"].mean()) < 1e-9
    t2 = tbl.fillna(0, ["price"]).min_max_scale(["price"])
    assert t2.df["price"].min() == 0.0 and t2.df["price"].max() == 1.0


# -- round-2 breadth: the reference methods added for parity ------------

def _tbl():
    from zoo_tpu.friesian.feature import FeatureTable
    return FeatureTable.from_dict({
        "user": [1, 1, 2, 2, 3, 3, 3, 4],
        "item": [10, 11, 10, 12, 11, 13, 10, 14],
        "cat": ["a", "b", "a", "c", "b", "a", "a", "d"],
        "score": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0]})


def test_basic_accessors_and_dedup():
    t = _tbl()
    assert t.columns == ["user", "item", "cat", "score"]
    assert t.col("user").tolist()[0] == 1
    d = t.union(t).distinct()
    assert d.size() == t.size()


def test_sample_split_shuffle():
    t = _tbl()
    assert t.sample(0.5, seed=1).size() == 4
    a, b = t.split([0.75, 0.25], seed=2)
    assert a.size() + b.size() == 8 and b.size() == 2
    sh = t.ordinal_shuffle_partition(seed=3)
    assert sorted(sh.col("score").tolist()) == sorted(
        t.col("score").tolist())


def test_column_ops_and_stats():
    t = _tbl().append_column("bias", 1).add(["score"], 10.0)
    assert t.col("bias").tolist() == [1] * 8
    assert t.col("score").tolist()[0] == 11.0
    st = t.get_stats(["score"], "avg")
    assert abs(st["score"] - 14.5) < 1e-9
    med = _tbl().median(["score"])
    assert med.col("median").tolist() == [4.5]


def test_merge_and_length():
    t = _tbl().merge_cols(["user", "item"], "ui")
    assert t.col("ui").tolist()[0] == [1, 10]
    t = t.add_length("ui")
    assert t.col("ui_length").tolist() == [2] * 8


def test_frequency_and_hashing():
    t = _tbl().filter_by_frequency(["cat"], min_freq=2)
    assert set(t.col("cat")) == {"a", "b"}
    h = _tbl().hash_encode(["cat"], bins=16)
    assert h.col("cat").dtype.kind in "iu"
    assert set(h.col("cat")) <= set(range(16))
    ch = _tbl().cross_hash_encode(["user", "cat"], 8, "uc")
    assert "uc" in ch.columns and set(ch.col("uc")) <= set(range(8))


def test_neg_hist_and_masks():
    from zoo_tpu.friesian.feature import FeatureTable
    t = FeatureTable.from_dict({
        "user": [1, 2], "hist": [[1, 2, 3], [4, 5]]})
    t2 = t.add_neg_hist_seq(item_size=20, item_history_col="hist",
                            neg_num=2)
    negs = t2.col("neg_hist").tolist()
    assert len(negs[0]) == 3 and len(negs[0][0]) == 2
    assert all(n != v for row, seq in zip(negs, t.col("hist"))
               for v, draws in zip(seq, row) for n in draws)
    t3 = t.mask_pad(["hist"], ["hist"], seq_len=4)
    assert t3.col("hist").tolist()[1] == [4, 5, 0, 0]
    assert t3.col("hist_mask").tolist()[1] == [1, 1, 0, 0]


def test_parquet_json_roundtrip(tmp_path):
    from zoo_tpu.friesian.feature import FeatureTable
    t = _tbl()
    p = str(tmp_path / "t.parquet")
    t.write_parquet(p)
    back = FeatureTable.read_parquet(p)
    pd_testing = __import__("pandas").testing
    pd_testing.assert_frame_equal(back.to_pandas(), t.to_pandas())
    jp = str(tmp_path / "t.json")
    t.to_pandas().to_json(jp, orient="records", lines=True)
    jback = FeatureTable.read_json(jp, orient="records", lines=True)
    pd_testing.assert_frame_equal(jback.to_pandas(), t.to_pandas(),
                                  check_dtype=False)  # json re-infers
    import pytest as _pytest
    with _pytest.raises(FileNotFoundError, match="no files"):
        FeatureTable.read_json(str(tmp_path / "missing_dir"))


def test_split_never_drops_rows():
    t = _tbl()  # 8 rows
    parts = t.split([1, 1, 1, 1, 1, 1], seed=0)
    assert sum(p.size() for p in parts) == 8


def test_merge_cols_preserves_dtypes():
    from zoo_tpu.friesian.feature import FeatureTable
    t = FeatureTable.from_dict({"user": [1, 2], "score": [1.5, 2.5]})
    merged = t.merge_cols(["user", "score"], "us").col("us").tolist()
    assert merged[0] == [1, 1.5]
    assert isinstance(merged[0][0], (int, __import__("numpy").integer))
