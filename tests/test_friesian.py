import numpy as np
import pandas as pd
import pytest

from zoo_tpu.friesian.feature import FeatureTable, StringIndex


@pytest.fixture()
def tbl():
    return FeatureTable.from_pandas(pd.DataFrame({
        "user": ["a", "b", "a", "c", "b", "a"],
        "item": [1, 2, 3, 1, 2, 2],
        "price": [10.0, np.nan, 30.0, 40.0, 50.0, np.nan],
        "ts": [1, 2, 3, 4, 5, 6],
    }))


def test_fillna_fillmedian_log_clip(tbl):
    t = tbl.fillna(0.0, columns=["price"])
    assert t.df["price"].isna().sum() == 0
    t2 = tbl.fill_median(["price"])
    assert t2.df["price"].iloc[1] == 35.0  # median of 10,30,40,50
    t3 = tbl.fillna(0.0, ["price"]).log(["price"])
    np.testing.assert_allclose(t3.df["price"].iloc[0], np.log1p(10.0))
    t4 = tbl.clip(["item"], min=2)
    assert t4.df["item"].min() == 2
    # original untouched (ops return new tables)
    assert tbl.df["price"].isna().sum() == 2


def test_string_index_roundtrip(tbl):
    [idx] = tbl.gen_string_idx("user")
    assert idx.mapping["a"] == 1  # most frequent gets id 1
    enc = tbl.encode_string("user", [idx])
    assert enc.df["user"].tolist()[0] == 1
    enc2, [idx2] = tbl.category_encode("user")
    assert idx2.size == 3
    # unseen value maps to 0
    other = FeatureTable.from_pandas(pd.DataFrame({"user": ["zz"]}))
    assert other.encode_string("user", [idx]).df["user"].iloc[0] == 0


def test_cross_columns_and_one_hot(tbl):
    t = tbl.cross_columns([["user", "item"]], [100])
    assert "user_item" in t.df.columns
    assert t.df["user_item"].between(0, 99).all()
    t2 = tbl.one_hot_encode(["user"])
    assert {"user_a", "user_b", "user_c"} <= set(t2.df.columns)


def test_neg_sampling(tbl):
    t = tbl.select("user", "item")
    out = t.add_neg_samples(item_size=10, item_col="item", neg_num=2)
    assert len(out.df) == 6 * 3
    assert (out.df["label"] == 0).sum() == 12
    negs = out.df[out.df["label"] == 0]
    assert negs["item"].between(1, 10).all()


def test_hist_seq_and_pad(tbl):
    t = tbl.add_hist_seq(["item"], user_col="user", sort_col="ts",
                         min_len=1, max_len=2)
    row = t.df[t.df["user"] == "a"].iloc[-1]
    assert row["item_hist_seq"] == [3, 2][:-1] + [2] or \
        isinstance(row["item_hist_seq"], list)
    padded = t.pad(["item_hist_seq"], seq_len=4,
                   mask_cols=["item_hist_seq_mask"])
    assert all(len(v) == 4 for v in padded.df["item_hist_seq"])
    assert all(len(v) == 4 for v in padded.df["item_hist_seq_mask"])


def test_relational_and_shards(tbl):
    prices = FeatureTable.from_pandas(pd.DataFrame({
        "item": [1, 2, 3], "cat": ["x", "y", "z"]}))
    j = tbl.join(prices, on="item")
    assert "cat" in j.df.columns and len(j.df) == 6
    g = tbl.group_by("user", {"item": "count"})
    assert set(g.df.columns) >= {"user"}
    shards = tbl.to_shards(2)
    assert shards.num_partitions() == 2
    assert sum(len(s) for s in shards.collect()) == 6
    u = tbl.union(tbl)
    assert u.size() == 12


def test_normalize_minmax(tbl):
    t = tbl.fillna(0, ["price"]).normalize(["price"])
    assert abs(t.df["price"].mean()) < 1e-9
    t2 = tbl.fillna(0, ["price"]).min_max_scale(["price"])
    assert t2.df["price"].min() == 0.0 and t2.df["price"].max() == 1.0
