"""The obs subsystem: registry semantics, Prometheus rendering + HTTP
exporter, trace spans, JSONL snapshots, merge math, the smoke script,
and the cross-layer end-to-end scrape (serving + fit + checkpoint +
retry/breaker all landing on one /metrics page)."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import zoo_tpu.obs as obs
from zoo_tpu.obs import (
    MetricsExporter,
    MetricsRegistry,
    StatTimer,
    merge_snapshots,
    read_trace,
    span,
    validate_prometheus_text,
    write_snapshot,
)

pytestmark = pytest.mark.obs


# ------------------------------------------------------------- registry

def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("t_requests_total", "requests", labels=("outcome",))
    c.labels(outcome="ok").inc()
    c.labels(outcome="ok").inc(2)
    c.labels(outcome="err").inc()
    assert c.labels(outcome="ok").value == 3
    assert c.labels(outcome="err").value == 1
    with pytest.raises(ValueError):
        c.labels(outcome="ok").inc(-1)  # counters only go up

    g = r.gauge("t_depth", "depth")
    g.set(5)
    g.inc()
    g.dec(3)
    assert g.value == 3

    h = r.histogram("t_lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 50.0):
        h.observe(v)
    snap = h.snapshot_value()
    assert snap["counts"] == [1, 1, 1]  # <=0.1, <=1.0, +Inf
    assert snap["count"] == 3
    assert abs(snap["sum"] - 50.55) < 1e-9


def test_get_or_create_and_type_mismatch():
    r = MetricsRegistry()
    a = r.counter("t_shared_total", "x")
    b = r.counter("t_shared_total", "x")
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("t_shared_total", "now a gauge?")
    with pytest.raises(ValueError):
        r.counter("t_shared_total", "x", labels=("k",))  # label mismatch
    with pytest.raises(ValueError):
        r.counter("bad name!", "x")
    with pytest.raises(ValueError):
        c = r.counter("t_lbl_total", "x", labels=("k",))
        c.labels(wrong="v")


def test_render_prometheus_is_valid_and_escaped():
    r = MetricsRegistry()
    r.counter("t_esc_total", 'has "quotes" and \\slashes\\',
              labels=("k",)).labels(k='va"l\\ue\n2').inc()
    r.histogram("t_h_seconds", "h", labels=("stage",),
                buckets=(0.001, 0.1)).labels(stage="s").observe(0.05)
    text = r.render_prometheus()
    assert validate_prometheus_text(text) == []
    assert '\\"quotes\\"' not in text  # help escapes \ and newline only
    assert 'k="va\\"l\\\\ue\\n2"' in text


def test_validator_catches_garbage():
    assert validate_prometheus_text("not a metric line at all{\n") != []
    # histogram with a non-cumulative bucket series
    bad = ("# HELP h x\n# TYPE h histogram\n"
           'h_bucket{le="0.1"} 5\nh_bucket{le="1"} 3\n'
           'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 5\n')
    assert any("cumulative" in e for e in validate_prometheus_text(bad))
    # sample without a TYPE line
    assert any("no # TYPE" in e
               for e in validate_prometheus_text("orphan_total 1\n"))


def test_stat_timer_unifies_stage_and_phase_timers():
    from zoo_tpu.common.profiling import PhaseTimer
    from zoo_tpu.serving.server import StageTimer

    assert PhaseTimer is StatTimer and StageTimer is StatTimer
    t = StatTimer()
    for dt in (0.01, 0.03):
        t.record(dt)
    s = t.stats()
    assert s["count"] == 2
    assert abs(s["avg_ms"] - 20.0) < 1e-6
    assert abs(s["max_ms"] - 30.0) < 1e-6
    assert abs(s["min_ms"] - 10.0) < 1e-6

    # histogram mirroring: the registry sees every record
    r = MetricsRegistry()
    h = r.histogram("t_stage_seconds", "x", buckets=(0.02,))
    t2 = StatTimer(histogram=h)
    t2.record(0.01)
    t2.record(0.5)
    assert h.snapshot_value()["counts"] == [1, 1]


def test_disabled_registry_under_1us():
    """Acceptance bound: a disabled registry's record hot path costs
    < 1 µs (it is one attribute check + early return). Measured on the
    CHILD metric — labels() documents "cache the returned child on hot
    paths", so the family proxy's __getattr__ dispatch is deliberately
    outside the bound."""
    r = MetricsRegistry()
    fam = r.counter("t_hot_total", "x")
    c, c_inc = fam.labels(), fam.labels().inc
    h_obs = r.histogram("t_hot_seconds", "x").labels().observe
    r.disable()
    n = 100_000
    best = float("inf")
    for _ in range(3):  # best-of-3 shields against CI scheduler noise
        t0 = time.perf_counter()
        for _ in range(n):
            c_inc()
        best = min(best, time.perf_counter() - t0)
    assert c.value == 0  # nothing recorded
    assert best / n < 1e-6, f"disabled inc cost {best / n * 1e9:.0f} ns"
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            h_obs(0.5)
        best = min(best, time.perf_counter() - t0)
    assert best / n < 1e-6, f"disabled observe cost {best / n * 1e9:.0f} ns"
    r.enable()
    c_inc()
    assert c.value == 1


# ---------------------------------------------------------------- spans

def test_spans_nest_and_record_errors(tmp_path):
    d = str(tmp_path / "trace")
    obs.trace_to(d)
    try:
        with span("outer", step=3):
            with span("inner"):
                pass
        with pytest.raises(RuntimeError):
            with span("boom"):
                raise RuntimeError("x")
    finally:
        obs.stop_tracing()
    evs = read_trace(d)
    by = {}
    for e in evs:
        by.setdefault((e["name"], e["ev"]), e)
    assert by[("outer", "B")]["attrs"] == {"step": 3}
    assert by[("inner", "B")]["parent"] == by[("outer", "B")]["span"]
    assert by[("outer", "B")]["parent"] is None
    assert by[("outer", "E")]["ok"] is True
    assert by[("outer", "E")]["dur_s"] >= 0
    assert by[("boom", "E")]["ok"] is False
    # all events share one process trace id
    assert len({e["trace"] for e in evs}) == 1


def test_span_disabled_is_cheap_noop(tmp_path):
    obs.stop_tracing()
    with span("nothing") as sid:
        assert sid is None
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with span("hot"):
            pass
    # generous bound: a no-op contextmanager round trip, not a write
    assert (time.perf_counter() - t0) / n < 20e-6


# ------------------------------------------------------------ exporters

def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def test_exporter_metrics_healthz_cluster(tmp_path, monkeypatch):
    r = MetricsRegistry()
    r.counter("t_exp_total", "x").inc(7)
    ex = MetricsExporter(registry=r).start()
    try:
        code, text = _get(ex.url + "/metrics")
        assert code == 200
        assert "t_exp_total 7" in text
        assert validate_prometheus_text(text) == []

        # no heartbeat configured: answering at all is healthy
        monkeypatch.delenv("ZOO_HEARTBEAT_FILE", raising=False)
        code, body = _get(ex.url + "/healthz")
        assert code == 200 and json.loads(body)["ok"] is True

        # fresh heartbeat: healthy, with an age
        hb = str(tmp_path / "hb")
        from zoo_tpu.util.resilience import touch_heartbeat
        touch_heartbeat(hb)
        monkeypatch.setenv("ZOO_HEARTBEAT_FILE", hb)
        code, body = _get(ex.url + "/healthz")
        assert code == 200
        assert json.loads(body)["heartbeat_age"] < 5

        # stale heartbeat: 503, same staleness rule ProcessMonitor uses
        with open(hb, "w") as f:
            f.write(repr(time.monotonic() - 3600))
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ex.url + "/healthz")
        assert ei.value.code == 503

        # no aggregation ran yet: /cluster is explicit about it
        monkeypatch.setattr("zoo_tpu.obs.aggregate._last_view", None)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ex.url + "/cluster")
        assert ei.value.code == 404
        # an aggregate_cluster() run is picked up with no extra wiring
        obs.aggregate_cluster(registry=r)
        code, body = _get(ex.url + "/cluster")
        assert code == 200
        assert json.loads(body)["counters"][0]["name"] == "t_exp_total"
        # an explicitly set view wins over the ambient one
        ex.set_cluster_view({"processes": 9, "counters": []})
        code, body = _get(ex.url + "/cluster")
        assert code == 200 and json.loads(body)["processes"] == 9
    finally:
        ex.stop()


def test_jsonl_snapshot_writer(tmp_path):
    r = MetricsRegistry()
    r.counter("t_snap_total", "x").inc(4)
    path = str(tmp_path / "metrics.jsonl")
    write_snapshot(path, r)
    r.counter("t_snap_total", "x").inc()
    write_snapshot(path, r, extra={"round": 2})
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert lines[0]["pid"] == os.getpid()
    assert lines[1]["extra"] == {"round": 2}
    vals = [e["value"] for rec in lines
            for e in rec["metrics"]["counters"]
            if e["name"] == "t_snap_total"]
    assert vals == [4, 5]


def test_check_metrics_export_script_runs():
    """The CI smoke script: exporter up, curl, validate — as a real
    subprocess, the same invocation an operator would use."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join("scripts", "check_metrics_export.py")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "valid Prometheus text" in proc.stdout


# ---------------------------------------------------------- aggregation

def test_merge_snapshots_semantics():
    def snap(c, g, counts):
        return {"counters": [{"name": "t_c_total", "labels": {}, "value": c}],
                "gauges": [{"name": "t_g", "labels": {}, "value": g}],
                "histograms": [{"name": "t_h_seconds", "labels": {},
                                "bounds": [0.1, 1.0],
                                "counts": counts,
                                "sum": sum(counts), "count": sum(counts)}]}

    m = merge_snapshots([snap(3, 10, [1, 0, 2]), snap(5, -2, [0, 4, 1])])
    assert m["processes"] == 2
    assert m["counters"] == [{"name": "t_c_total", "labels": {},
                              "value": 8.0}]
    assert m["gauges"] == [{"name": "t_g", "labels": {},
                            "max": 10.0, "min": -2.0}]
    h = m["histograms"][0]
    assert h["counts"] == [1, 4, 3]
    assert h["count"] == 8

    # label sets are distinct series
    a = {"counters": [{"name": "t", "labels": {"k": "1"}, "value": 1}],
         "gauges": [], "histograms": []}
    b = {"counters": [{"name": "t", "labels": {"k": "2"}, "value": 1}],
         "gauges": [], "histograms": []}
    assert len(merge_snapshots([a, b])["counters"]) == 2


def test_aggregate_cluster_single_process():
    r = MetricsRegistry()
    r.counter("t_agg_total", "x").inc(6)
    merged = obs.aggregate_cluster(registry=r)
    assert merged["processes"] == 1
    assert merged["counters"] == [{"name": "t_agg_total", "labels": {},
                                   "value": 6.0}]
    assert obs.last_cluster_view() is merged


# ----------------------------------------------------------- end-to-end

def test_metrics_end_to_end_serving_fit_checkpoint(orca_ctx, tmp_path):
    """The acceptance scrape: a model served through ServingServer, a
    short profiled Estimator.fit, a checkpoint save, a forced retry and
    a tripped breaker — then ONE GET /metrics shows serving batch/latency
    histograms, retry/breaker counters, checkpoint save durations and
    per-phase step-time stats, in valid Prometheus text."""
    from zoo_tpu.orca.learn.ckpt import CheckpointManager
    from zoo_tpu.orca.learn.keras import Estimator
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.inference import InferenceModel
    from zoo_tpu.serving import ServingServer, TCPInputQueue
    from zoo_tpu.util.resilience import (
        CircuitBreaker,
        RetryError,
        RetryPolicy,
    )

    # 1. short profiled fit through the Estimator
    rs = np.random.RandomState(0)
    x = rs.randn(64, 4).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
    m = Sequential()
    m.add(Dense(8, activation="relu", input_shape=(4,)))
    m.add(Dense(1, activation="sigmoid"))
    m.compile(optimizer="adam", loss="binary_crossentropy")
    est = Estimator.from_keras(m)
    est.set_profile()
    est.fit({"x": x, "y": y}, epochs=1, batch_size=16)

    # 2. serve it over the TCP door
    inf = InferenceModel().load_keras(m, batch_size=8)
    server = ServingServer(inf, port=0, batch_size=8,
                           max_wait_ms=5).start()
    try:
        q = TCPInputQueue(host=server.host, port=server.port)
        preds = q.predict(x[:12])
        assert np.asarray(preds).shape == (12, 1)
        q.close()
    finally:
        server.stop()

    # 3. checkpoint save + restore
    cm = CheckpointManager(str(tmp_path / "ckpt"))
    cm.save(1, {"w": np.arange(4.0)})
    cm.restore()

    # 4. a retry give-up and a breaker trip
    pol = RetryPolicy(max_attempts=2, sleep=lambda s: None)

    def dead():
        raise ConnectionError("down")

    with pytest.raises(RetryError):
        pol.call(dead)
    br = CircuitBreaker(failure_threshold=1, recovery_timeout=60)
    br.record_failure()

    # 4b. the serving-HA paths (docs/serving_ha.md) — shed at an
    # open-breaker door, a dead-on-arrival deadline, a failover past a
    # dead endpoint, and a hedge that wins over a stalled primary — so
    # the scrape below carries every zoo_serve_* family with real counts
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.tcp_client import _Connection

    class _Stall:
        def __init__(self, factor, delay):
            self.factor, self.delay = factor, delay

        def predict(self, xx, batch_size=None):
            import time as _t
            if self.delay:
                _t.sleep(self.delay)
            return np.asarray(xx) * self.factor

    tripped = CircuitBreaker(failure_threshold=1, recovery_timeout=60)
    tripped.record_failure()
    shed_srv = ServingServer(_Stall(2.0, 0.0), port=0, batch_size=2,
                             max_wait_ms=1.0, breaker=tripped).start()
    slow_srv = ServingServer(_Stall(3.0, 0.5), port=0, batch_size=1,
                             max_wait_ms=0.0).start()
    fast_srv = ServingServer(_Stall(2.0, 0.0), port=0, batch_size=2,
                             max_wait_ms=1.0, version="v9").start()
    try:
        conn = _Connection(shed_srv.host, shed_srv.port)
        resp = conn.rpc({"op": "predict", "uri": "u",
                         "data": np.zeros((1, 2), np.float32)})
        assert resp.get("shed") and resp.get("retryable")
        conn.close()
        conn = _Connection(fast_srv.host, fast_srv.port)
        resp = conn.rpc({"op": "predict", "uri": "u",
                         "data": np.zeros((1, 2), np.float32),
                         "deadline_ms": 0.0})
        assert resp.get("expired")
        conn.close()
        import socket as _socket
        probe = _socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead = probe.getsockname()
        probe.close()
        cli = HAServingClient([dead, (fast_srv.host, fast_srv.port)],
                              hedge=False, deadline_ms=8000)
        assert np.asarray(cli.predict(
            np.ones((1, 2), np.float32))).shape == (1, 2)
        cli.close()
        cli2 = HAServingClient(
            [(slow_srv.host, slow_srv.port),
             (fast_srv.host, fast_srv.port)],
            hedge=True, hedge_delay_ms=20, deadline_ms=8000)
        hedged = np.asarray(cli2.predict(np.ones((1, 2), np.float32)))
        np.testing.assert_allclose(hedged, 2.0)  # the fast replica won
        cli2.close()
        # the model-lifecycle families (docs/model_lifecycle.md): a
        # version-pinned mismatch bounce and a pinned A/B request
        conn = _Connection(fast_srv.host, fast_srv.port)
        resp = conn.rpc({"op": "predict", "uri": "u",
                         "data": np.zeros((1, 2), np.float32),
                         "model_version": "v8"})
        assert resp.get("version_mismatch") and resp["version"] == "v9"
        conn.close()
        cli3 = HAServingClient([(fast_srv.host, fast_srv.port)],
                               hedge=False, deadline_ms=8000)
        np.testing.assert_allclose(
            np.asarray(cli3.predict(np.ones((1, 2), np.float32),
                                    model_version="v9")), 2.0)
        cli3.close()
    finally:
        shed_srv.stop()
        slow_srv.stop()
        fast_srv.stop()

    # 4c. the overlapped tick pipeline's phase histograms + overlap
    # gauge (docs/llm_serving.md): one short jax-free engine run over a
    # deterministic fake model populates zoo_llm_tick_seconds{phase}
    # and zoo_llm_tick_overlap_ratio. Runs BEFORE the allocator probe
    # below — the engine's own allocator republishes the process-global
    # zoo_llm_kv_blocks_* gauges on every mutation, and the scrape
    # asserts the probe's values.
    from zoo_tpu.serving.llm.engine import LLMEngine

    class _TickModel:
        num_slots, block_size, num_blocks = 2, 4, 16
        max_blocks_per_seq, max_prompt_len = 4, 12
        max_context, prefill_chunk_size, eos_id = 16, 0, None
        suffix_chunk_size = 4
        kv_bytes_per_token = 160          # -> zoo_llm_kv_bytes_per_token
        spec_k = 2                        # -> the verify path + the
        #                                   zoo_llm_spec_* families

        def prefill(self, prompt, row, sampling=None):
            return (int(prompt[-1]) + 1) % 4

        def prefill_chunk(self, chunk, start, total, row,
                          sampling=None):
            return (int(chunk[-1]) + 1) % 4

        def decode_step(self, prev, host, use, tables, pos, lanes):
            import time as _t
            _t.sleep(0.001)
            return (np.where(np.asarray(use), host,
                             prev if prev is not None else 0) + 1) % 4

        def verify_step(self, tokens, tables, pos, lanes):
            import time as _t
            _t.sleep(0.001)
            return (np.asarray(tokens) + 1) % 4

        def read_tokens(self, batch):
            return np.asarray(batch)

    # prefix caching ON + speculative decoding ON: the second identical
    # prompt hits the first's registered blocks (populating
    # zoo_llm_prefix_cache_{hit,miss}_* and the shared/cached gauges),
    # and the cyclic prompt makes the prompt-lookup drafter propose
    # tokens the (x+1)%4 fake accepts — all jax-free
    llm_eng = LLMEngine(_TickModel(), overlap=True,
                        prefix_cache=True).start()
    try:
        for rid in ("scrape-a", "scrape-b"):
            h = llm_eng.submit([1, 2, 3, 1, 2, 3], 6, rid=rid)
            deadline = time.monotonic() + 30
            while not h.done and time.monotonic() < deadline:
                time.sleep(0.01)
            assert h.done
        llm_stats = llm_eng.stats()
        assert llm_stats["prefix_hit_tokens"] > 0
        assert llm_stats["spec_proposed_tokens"] > 0
        assert llm_stats["spec_accepted_tokens"] > 0
    finally:
        llm_eng.stop()

    # 4c-ter. multi-tenant QoS (docs/multitenancy.md): a tenancy-armed
    # engine drives the zoo_tenant_* families — an admitted stream and
    # a rate shed off the free tier's dry bucket, a class-0 preemption
    # of the youngest best-effort stream, and the per-tenant slot/KV
    # gauges the scheduler loop republishes
    from zoo_tpu.serving.llm.engine import AdmissionError
    from zoo_tpu.serving.tenancy import TenantRegistry

    # ticked WHITE-BOX (never .start()ed) so the preemption is
    # deterministic: a live engine loop finishes the best-effort
    # streams before the paid submit could ever contend for a slot
    qos_eng = LLMEngine(
        _TickModel(), overlap=False, prefix_cache=False,
        tenancy=TenantRegistry(
            spec="gold:class=0,rate=0;brz:class=1,rate=0;"
                 "free:class=1,rate=0.001,burst=1",
            qos=True))

    def _qtick(handles=(), ticks=1):
        for _ in range(ticks):
            if handles and all(h.done for h in handles):
                return
            qos_eng._sweep()
            qos_eng._admit()
            qos_eng._prefill_tick()
            qos_eng._grow_or_preempt()
            qos_eng._decode_tick()

    f1 = qos_eng.submit([1, 2, 3], 4, rid="ten-f1", tenant="free")
    with pytest.raises(AdmissionError):   # burst of 1 is spent
        qos_eng.submit([1, 2, 3], 4, rid="ten-f2", tenant="free")
    _qtick([f1], ticks=50)
    assert f1.done and f1.outcome == "ok"
    b1 = qos_eng.submit([1, 2, 3], 6, rid="ten-b1", tenant="brz")
    b2 = qos_eng.submit([2, 3, 1], 6, rid="ten-b2", tenant="brz")
    _qtick(ticks=2)                       # both brz slots live
    assert qos_eng.stats()["active"] == 2
    g1 = qos_eng.submit([3, 1, 2], 4, rid="ten-g1", tenant="gold")
    _qtick(ticks=2)                       # evict youngest brz, admit
    _qtick([b1, b2, g1], ticks=100)       # resume + drain everything
    assert all(h.done and h.outcome == "ok" for h in (b1, b2, g1))

    # 4c-bis. disaggregated serving (docs/disaggregated_serving.md):
    # one long prompt through a prefill+decode pair drives the whole
    # two-leg kv_migrate handoff — the prefill seat's push populates
    # zoo_llm_kv_migrated_bytes_total + zoo_llm_handoff_seconds, the
    # decode seat's adoption populates zoo_llm_kv_migrated_blocks_total,
    # and the client's routing plan stamps zoo_serve_route_affinity_total.
    # Runs BEFORE the 4d allocator probe for the same reason 4c does:
    # these engines' allocators republish the process-global
    # zoo_llm_kv_blocks_* gauges on every mutation.
    from zoo_tpu.serving.ha_client import HAServingClient
    from zoo_tpu.serving.llm.synthetic import SyntheticLLMModel, reference
    from zoo_tpu.serving.server import ServingServer

    mk = dict(num_slots=2, block_size=4, num_blocks=32,
              max_blocks_per_seq=8, max_prompt_len=48)
    pre_eng = LLMEngine(SyntheticLLMModel(**mk), role="prefill").start()
    dec_eng = LLMEngine(SyntheticLLMModel(**mk), role="decode").start()
    pre_srv = ServingServer(None, llm_engine=pre_eng, port=0,
                            batch_size=2, max_wait_ms=1.0).start()
    dec_srv = ServingServer(None, llm_engine=dec_eng, port=0,
                            batch_size=2, max_wait_ms=1.0).start()
    disagg_cli = HAServingClient(
        [(pre_srv.host, pre_srv.port), (dec_srv.host, dec_srv.port)],
        hedge=False, migrate_min_tokens=16)
    try:
        disagg_cli.update_topology()
        long_prompt = [(3 * i + 1) % 50 for i in range(18)]
        assert list(disagg_cli.generate(long_prompt, 6)) == \
            reference(long_prompt, 6)
        assert dec_eng.stats()["handoffs_in"] == 1
    finally:
        disagg_cli.close()
        pre_srv.stop()
        dec_srv.stop()
        pre_eng.stop()
        dec_eng.stop()

    # 4d. the paged-KV gauges: a jax-free allocator round-trip leaves
    # zoo_llm_kv_blocks_{used,free} at the pool's live accounting
    from zoo_tpu.serving.llm.kv_cache import (BlockAllocator,
                                              prefix_block_hashes)
    # first, a last-resort cross-tenant eviction: a 3-usable-block
    # pool where gold's ask can only be covered by reclaiming victim's
    # parked cache block (own + shared partitions both empty) bumps
    # zoo_tenant_kv_cross_evictions_total{tenant="gold"}
    t_alloc = BlockAllocator(num_blocks=4, block_size=4,
                             prefix_cache=True)
    t_alloc.set_tenant("t-v", "victim")
    t_alloc.allocate("t-v", 1)
    t_alloc.register_blocks(
        "t-v", prefix_block_hashes([1, 2, 3, 4], 4,
                                   salt=b"tenant:victim"))
    t_alloc.free("t-v")
    t_alloc.set_tenant("t-g", "gold")
    assert t_alloc.allocate("t-g", 3) is not None
    # ... then the plain probe LAST — the used/free gauges are
    # process-global, so the final _publish() is the scraped value
    alloc = BlockAllocator(num_blocks=17, block_size=8)
    alloc.allocate("scrape-seq", 4)

    # 4e. the SLO watchdog (docs/observability.md): two evaluation
    # passes over the process-global registry publish the zoo_slo_*
    # burn-rate/breach gauges the fleet alerts on
    from zoo_tpu.obs.metrics import counter as _counter
    from zoo_tpu.obs.slo import SLORule, SLOWatchdog, _error_rate
    watchdog = SLOWatchdog(
        rules=[SLORule("error_rate", _error_rate, 0.99)],
        window_s=60.0, interval_s=60.0)
    watchdog.tenant_shed_objective = 0.5   # arm the per-tenant burn
    watchdog.evaluate()
    # traffic must flow INSIDE the window for a burn-rate verdict
    _counter("zoo_serving_requests_total", labels=("outcome",)) \
        .labels(outcome="ok").inc()
    _counter("zoo_tenant_admitted_total", labels=("tenant",)) \
        .labels(tenant="gold").inc()
    _counter("zoo_tenant_shed_total", labels=("tenant", "reason")) \
        .labels(tenant="gold", reason="rate").inc()
    watchdog.evaluate()

    # 5. one scrape sees all of it
    ex = MetricsExporter().start()  # process-global registry
    try:
        code, text = _get(ex.url + "/metrics")
    finally:
        ex.stop()
    assert code == 200
    assert validate_prometheus_text(text) == []
    for needle in (
            'zoo_serving_stage_seconds_bucket{stage="inference"',
            "zoo_serving_batch_occupancy_bucket",
            'zoo_serving_requests_total{outcome="ok"}',
            "zoo_retry_attempts_total",
            "zoo_retry_giveups_total",
            'zoo_breaker_transitions_total{state="open"}',
            "zoo_ckpt_save_seconds_bucket",
            "zoo_ckpt_restore_seconds_count",
            'zoo_step_phase_seconds_bucket{phase="step"',
            'zoo_serve_shed_total{reason="breaker_open"}',
            'zoo_serve_deadline_expired_total{stage="admission"}',
            "zoo_serve_failover_total",
            'zoo_serve_hedge_total{event="fired"}',
            'zoo_serve_hedge_total{event="won"}',
            'zoo_serve_shed_total{reason="version_mismatch"}',
            'zoo_registry_version_info{version="v9"} 1',
            'zoo_serve_ab_requests_total{version="v9",outcome="ok"}',
            "zoo_llm_kv_blocks_used 4",
            "zoo_llm_kv_blocks_free 12",
            # the tick pipeline (PR 10): per-phase engine tick
            # histograms + the device-busy/wall overlap gauge
            'zoo_llm_tick_seconds_bucket{phase="schedule"',
            'zoo_llm_tick_seconds_bucket{phase="decode"',
            'zoo_llm_tick_seconds_bucket{phase="readback"',
            "zoo_llm_tick_overlap_ratio",
            # prefix caching + quantized KV (this PR): token hit/miss
            # counters, the shared-blocks gauge, and the per-token HBM
            # byte cost under the active cache dtype
            "zoo_llm_prefix_cache_hit_tokens_total",
            "zoo_llm_prefix_cache_miss_tokens_total",
            "zoo_llm_kv_blocks_shared",
            "zoo_llm_kv_bytes_per_token 160",
            # speculative decoding (this PR): proposed/accepted draft
            # tokens, the per-pass accept-length histogram, and the
            # drafter hit-rate gauge — republished from engine.stats()
            "zoo_llm_spec_proposed_tokens_total",
            "zoo_llm_spec_accepted_tokens_total",
            "zoo_llm_spec_accept_len_bucket",
            "zoo_llm_spec_draft_hit_rate",
            # per-stream token cadence (PR 13): the request-level
            # latency families the SLO watchdog burns against — the
            # engine runs above pushed multi-token streams, so both
            # carry real observations
            "zoo_llm_inter_token_seconds_bucket",
            'zoo_llm_stream_ttft_seconds_bucket{outcome="ok"',
            # disaggregated serving (this PR): the kv_migrate handoff
            # volume counters, the push-to-adopt latency histogram,
            # and the client's routing-decision tally — populated by
            # the 4c-bis two-leg handoff above
            "zoo_llm_kv_migrated_blocks_total",
            "zoo_llm_kv_migrated_bytes_total",
            "zoo_llm_handoff_seconds_bucket",
            'zoo_serve_route_affinity_total{reason="handoff"}',
            # the SLO watchdog's published verdict (4e above) and the
            # flight recorder's event tally
            'zoo_slo_burn_rate{slo="error_rate"}',
            'zoo_slo_breach{slo="error_rate"}',
            "zoo_flight_events_total",
            # the GSPMD layer (docs/multichip.md): the fixture's 8-device
            # mesh publishes its axis sizes, and the fit above ran DP
            # over it, so the plan's estimated grad all-reduce bytes
            # accumulated per executed step
            'zoo_mesh_axis_size{axis="data"}',
            'zoo_mesh_collective_bytes_total{op="all_reduce"}',
            # multi-tenant QoS (this PR): the 4c-ter engine's admit /
            # rate-shed / class-preempt tallies, the per-tenant
            # slot/KV gauges its scheduler loop republishes, the 4d
            # cross-partition eviction counter, and the 4e watchdog's
            # per-tenant shed burn verdict (family-prefix needles for
            # the multi-label families)
            'zoo_tenant_admitted_total{tenant="free"}',
            'zoo_tenant_shed_total{',
            'zoo_tenant_preempted_total{',
            'zoo_tenant_decode_slots{tenant="brz"}',
            'zoo_tenant_kv_blocks{tenant="gold"}',
            'zoo_tenant_kv_cross_evictions_total{tenant="gold"} 1',
            'zoo_tenant_burn_rate{',
    ):
        assert needle in text, f"/metrics is missing {needle}"
    # the fit really recorded step phases (count > 0, not just a family)
    for line in text.splitlines():
        if line.startswith('zoo_step_phase_seconds_count{phase="step"'):
            assert float(line.rsplit(" ", 1)[1]) > 0
            break
    else:
        raise AssertionError("no step-phase count sample")
    # the mesh gauges/counters carry real values, not just families
    for line in text.splitlines():
        if line.startswith('zoo_mesh_axis_size{axis="data"}'):
            assert float(line.rsplit(" ", 1)[1]) == 8.0
        if line.startswith('zoo_mesh_collective_bytes_total'
                           '{op="all_reduce"}'):
            assert float(line.rsplit(" ", 1)[1]) > 0
