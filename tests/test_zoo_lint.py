"""zoo-lint framework tests (the ``lint`` marker).

Three layers:

* fixture tests — one seeded violation per rule in a throwaway tree,
  asserting the finding lands with the right rule id, file and line
  (plus a negative twin and an allowlisted case);
* self-application — the real tree is lint-clean under the checked-in
  allowlist, the linter itself never imports jax, and the knob
  registry round-trips every ``ZOO_*`` name greppable in the tree;
* the in-suite strict gate — runs every AST pass over the repo and
  writes ``LINT.json`` beside the ``BENCH_*.json`` trajectory files.

The compiled-HLO passes are fixture-tested here on synthetic module
text; their real-executable wiring lives in the compile-census tests
(test_llm_serving / test_spec_decode / the multichip smoke).
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from zoo_tpu.analysis import (
    Context,
    apply_allowlist,
    findings_json,
    load_allowlist,
    run_passes,
)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    """Materialize ``{relpath: source}`` under tmp_path and return a
    Context rooted there (no allowlist unless the caller writes one)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Context(str(tmp_path),
                   allowlist_path=str(tmp_path / "zoo_lint_allow.txt"))


def _knob(name, **kw):
    from zoo_tpu.common.knobs import Knob
    kw.setdefault("type", "int")
    kw.setdefault("default", 1)
    kw.setdefault("help", "h")
    kw.setdefault("doc", "docs/x.md")
    return Knob(name=name, **kw)


# ---------------------------------------------------------------- knobs

class TestKnobPass:
    def test_undeclared_knob_caught_with_location(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                import os


                def f():  # zoo-lint: config-parse
                    return os.environ.get("ZOO_MYSTERY_KNOB")
            """,
            "docs/x.md": "ZOO_GOOD\n",
        })
        ctx.knob_registry = {}
        ctx.knob_table_docs = ()
        fs = run_passes(ctx, ["knobs"])
        hit = [f for f in fs if f.rule == "KNOB-UNDECLARED"]
        assert len(hit) == 1
        assert hit[0].file == "zoo_tpu/m.py" and hit[0].line == 5
        assert hit[0].detail == "ZOO_MYSTERY_KNOB"

    def test_registered_knob_is_clean_and_dead_knob_caught(self,
                                                           tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                import os


                def f():  # zoo-lint: config-parse
                    return os.environ.get("ZOO_GOOD")
            """,
            "zoo_tpu/common/__init__.py": "",
            "zoo_tpu/common/knobs.py": '_K = ("ZOO_GOOD", "ZOO_DEAD")\n',
            "docs/x.md": "ZOO_GOOD ZOO_DEAD\n",
        })
        ctx.knob_registry = {"ZOO_GOOD": _knob("ZOO_GOOD"),
                             "ZOO_DEAD": _knob("ZOO_DEAD")}
        ctx.knob_table_docs = ()
        fs = run_passes(ctx, ["knobs"])
        assert [f.detail for f in fs if f.rule == "KNOB-DEAD"] == \
            ["ZOO_DEAD"]
        assert not [f for f in fs
                    if f.rule == "KNOB-UNDECLARED"]

    def test_raw_env_read_outside_parse_site(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                import os


                def hot_path():
                    return os.environ.get("ZOO_GOOD")


                def blessed():  # zoo-lint: config-parse
                    return os.environ.get("ZOO_GOOD")
            """,
            "docs/x.md": "ZOO_GOOD\n",
        })
        ctx.knob_registry = {"ZOO_GOOD": _knob("ZOO_GOOD")}
        ctx.knob_table_docs = ()
        fs = [f for f in run_passes(ctx, ["knobs"])
              if f.rule == "KNOB-RAW-ENV"]
        assert len(fs) == 1
        assert (fs[0].file, fs[0].line) == ("zoo_tpu/m.py", 5)

    def test_raw_env_allowlisted(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": "import os\nV = os.environ.get('ZOO_GOOD')\n",
            "docs/x.md": "ZOO_GOOD\n",
            "zoo_lint_allow.txt":
                "KNOB-RAW-ENV zoo_tpu/m.py ZOO_GOOD  # fixture\n",
        })
        ctx.knob_registry = {"ZOO_GOOD": _knob("ZOO_GOOD")}
        ctx.knob_table_docs = ()
        fs = run_passes(ctx, ["knobs"])
        active, suppressed = apply_allowlist(
            fs, load_allowlist(ctx.allowlist_path))
        assert not [f for f in active if f.rule == "KNOB-RAW-ENV"]
        assert [f.rule for f in suppressed] == ["KNOB-RAW-ENV"]

    def test_undocumented_and_doc_drift(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                import os


                def f():  # zoo-lint: config-parse
                    return (os.environ.get("ZOO_GOOD"),
                            os.environ.get("ZOO_HIDDEN"))
            """,
            "docs/x.md": """\
                | Env | Default | Meaning |
                |---|---|---|
                <!-- zoo-knob-table:g begin -->
                | `ZOO_GOOD` | 999 | stale row |
                <!-- zoo-knob-table:g end -->
            """,
            "docs/y.md": "nothing here\n",
        })
        ctx.knob_registry = {
            "ZOO_GOOD": _knob("ZOO_GOOD", table="g"),
            "ZOO_HIDDEN": _knob("ZOO_HIDDEN", doc="docs/y.md"),
        }
        ctx.knob_table_docs = ("docs/x.md",)
        fs = run_passes(ctx, ["knobs"])
        assert [f.detail for f in fs
                if f.rule == "KNOB-UNDOCUMENTED"] == ["ZOO_HIDDEN"]
        drift = [f for f in fs if f.rule == "KNOB-DOC-DRIFT"]
        assert len(drift) == 1 and drift[0].file == "docs/x.md"
        assert drift[0].line == 3 and drift[0].detail == "g"

    def test_registry_value_alias_resolved(self, tmp_path):
        # the production call style: `from ... import value as
        # knob_value` — an unregistered name must NOT escape the lint
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                from zoo_tpu.common.knobs import value as knob_value

                X = knob_value("ZOO_NOT_REGISTERED")
            """,
            "docs/x.md": "x\n",
        })
        ctx.knob_registry = {}
        ctx.knob_table_docs = ()
        fs = [f for f in run_passes(ctx, ["knobs"])
              if f.rule == "KNOB-UNDECLARED"]
        assert len(fs) == 1 and fs[0].line == 3
        assert fs[0].detail == "ZOO_NOT_REGISTERED"

    def test_default_drift_caught(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                from zoo_tpu.util.resilience import env_int

                A = env_int("ZOO_GOOD", 1)    # matches the registry
                B = env_int("ZOO_GOOD", 99)   # drifted fallback
            """,
            "docs/x.md": "ZOO_GOOD\n",
        })
        ctx.knob_registry = {"ZOO_GOOD": _knob("ZOO_GOOD")}
        ctx.knob_table_docs = ()
        fs = [f for f in run_passes(ctx, ["knobs"])
              if f.rule == "KNOB-DEFAULT-DRIFT"]
        assert len(fs) == 1 and fs[0].line == 4
        assert "99" in fs[0].message and "1" in fs[0].message

    def test_env_constant_and_alias_resolution(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": """\
                import os

                MY_ENV = "ZOO_VIA_CONST"


                def f():
                    env = os.environ
                    return env.get(MY_ENV)
            """,
            "docs/x.md": "x\n",
        })
        ctx.knob_registry = {}
        ctx.knob_table_docs = ()
        fs = run_passes(ctx, ["knobs"])
        assert [f.detail for f in fs if f.rule == "KNOB-UNDECLARED"] \
            == ["ZOO_VIA_CONST"]
        assert [f.detail for f in fs if f.rule == "KNOB-RAW-ENV"] == \
            ["ZOO_VIA_CONST"]


# --------------------------------------------------------------- purity

class TestPurityPass:
    def test_jax_in_closure_caught(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/pure.py": """\
                # zoo-lint: jax-free
                from zoo_tpu import helper
            """,
            "zoo_tpu/helper.py": "import jax\n",
        })
        fs = run_passes(ctx, ["purity"])
        assert len(fs) == 1
        f = fs[0]
        assert f.rule == "PURITY-JAX"
        assert f.file == "zoo_tpu/pure.py" and f.line == 1
        assert "zoo_tpu/helper.py:1" in f.message

    def test_package_init_chain_counts(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/sub/__init__.py": "import jax.numpy\n",
            "zoo_tpu/sub/leaf.py": "X = 1\n",
            "zoo_tpu/pure.py": """\
                # zoo-lint: jax-free
                from zoo_tpu.sub.leaf import X
            """,
        })
        fs = run_passes(ctx, ["purity"])
        assert [f.rule for f in fs] == ["PURITY-JAX"]
        assert "zoo_tpu/sub/__init__.py" in fs[0].message

    def test_lazy_and_type_checking_imports_allowed(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/pure.py": """\
                # zoo-lint: jax-free
                from typing import TYPE_CHECKING

                if TYPE_CHECKING:
                    import jax


                def device_path():
                    import jax.numpy as jnp
                    return jnp
            """,
        })
        assert run_passes(ctx, ["purity"]) == []


# ---------------------------------------------------------------- locks

_LOCKED_CLASS = """\
    import threading


    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def %s
"""


class TestLockPass:
    def test_unguarded_access_caught(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": _LOCKED_CLASS % (
                "add(self, x):\n            self._items.append(x)\n"),
        })
        fs = run_passes(ctx, ["locks"])
        assert len(fs) == 1
        f = fs[0]
        assert f.rule == "LOCK-GUARD" and f.detail == "Box._items"
        assert f.file == "zoo_tpu/m.py" and f.line == 10

    def test_with_lock_and_escapes_clean(self, tmp_path):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": _LOCKED_CLASS % (
                "add(self, x):\n"
                "            with self._lock:\n"
                "                self._items.append(x)\n\n"
                "        def _drain_locked(self):\n"
                "            return list(self._items)\n\n"
                "        def peek(self):\n"
                "            return len(self._items)  "
                "# zoo-lint: holds-lock\n"),
        })
        assert run_passes(ctx, ["locks"]) == []


# ------------------------------------------------------------ telemetry

class TestTelemetryPass:
    def _ctx(self, tmp_path, body, metrics=None, events=None):
        ctx = _tree(tmp_path, {
            "zoo_tpu/__init__.py": "",
            "zoo_tpu/m.py": body,
        })
        ctx.metrics_catalog = metrics or {}
        ctx.event_catalog = frozenset(events or ())
        return ctx

    def test_undeclared_metric_and_event(self, tmp_path):
        ctx = self._ctx(tmp_path, """\
            from zoo_tpu.obs.metrics import counter
            from zoo_tpu.obs.flight import record_event

            C = counter("zoo_typo_total", "h", labels=("kind",))


            def f():
                record_event("unknown_kind")
        """)
        fs = run_passes(ctx, ["telemetry"])
        und = {f.detail: f for f in fs if f.rule == "TEL-UNDECLARED"}
        assert set(und) == {"zoo_typo_total", "event:unknown_kind"}
        assert und["zoo_typo_total"].line == 4

    def test_label_mismatch_and_dead_entry(self, tmp_path):
        ctx = self._ctx(
            tmp_path, """\
                from zoo_tpu.obs.metrics import gauge

                G = gauge("zoo_ok", "h", labels=("axis", "extra"))
            """,
            metrics={"zoo_ok": ("gauge", ("axis",)),
                     "zoo_never_created": ("counter", ())})
        fs = run_passes(ctx, ["telemetry"])
        assert [f.detail for f in fs if f.rule == "TEL-LABELS"] == \
            ["zoo_ok"]
        assert [f.detail for f in fs if f.rule == "TEL-DEAD"] == \
            ["zoo_never_created"]

    def test_aliased_ctor_and_matching_decl_clean(self, tmp_path):
        ctx = self._ctx(
            tmp_path, """\
                from zoo_tpu.obs.metrics import counter as _obs_counter

                C = _obs_counter("zoo_ok_total", "h", labels=("op",))
            """,
            metrics={"zoo_ok_total": ("counter", ("op",))})
        assert run_passes(ctx, ["telemetry"]) == []


# ------------------------------------------------------------------ hlo

_HLO_HEADER = (
    "HloModule jit_step, is_scheduled=true%s, "
    "entry_computation_layout={(%s)->(%s)}\n\n"
    "ENTRY %%main (p0: f32[4]) -> (s32[4,1]) {\n"
    "  ROOT %%t = (s32[4,1]{1,0}) tuple()\n}\n")


class TestHloPasses:
    def test_donation_dropped_caught(self):
        from zoo_tpu.analysis.hlo import (
            assert_donated,
            donation_findings,
        )
        good = _HLO_HEADER % (
            ", input_output_alias={ {0}: (1, {}, may-alias), "
            "{1}: (2, {}, may-alias) }",
            "f32[4]{0}, f32[8]{0}, f32[8]{0}", "f32[8]{0}, f32[8]{0}")
        assert donation_findings(good, 2, "fixture") == []
        bad = _HLO_HEADER % ("", "f32[4]{0}", "f32[4]{0}")
        fs = donation_findings(bad, 2, "fixture exec")
        assert len(fs) == 1 and fs[0].rule == "HLO-DONATION"
        assert fs[0].file == "fixture exec"
        assert "0 of 2" in fs[0].message
        with pytest.raises(AssertionError, match="donat"):
            assert_donated(bad, 2, "fixture exec")

    def test_host_transfer_logits_caught(self):
        from zoo_tpu.analysis.hlo import (
            assert_host_transfer,
            host_transfer_findings,
        )
        ok = _HLO_HEADER % ("", "f32[4]{0}",
                            "s32[4,1]{1,0}, f32[4,2,8]{2,1,0}")
        assert host_transfer_findings(ok, 4, 256) == []
        # slots x vocab logits in the entry outputs
        bad = _HLO_HEADER % ("", "f32[4]{0}",
                             "s32[4,1]{1,0}, f32[4,256]{1,0}")
        fs = host_transfer_findings(bad, 4, 256, label="decode exec")
        assert [f.rule for f in fs] == ["HLO-HOST-TRANSFER"]
        assert "vocab-sized" in fs[0].message
        # no token output at all
        none = _HLO_HEADER % ("", "f32[4]{0}", "f32[4,8]{1,0}")
        fs = host_transfer_findings(none, 4, 256)
        assert [f.detail for f in fs] == ["tokens"]
        with pytest.raises(AssertionError, match="vocab"):
            assert_host_transfer(bad, 4, 256)

    def test_sharding_plan_tp_params_caught(self):
        from zoo_tpu.analysis.hlo import (
            assert_plan_sharded,
            sharding_findings,
        )
        # megatron-sharded (64, 64) weight fed at FULL shape -> "TP
        # that isn't" on the entry parameters
        bad = _HLO_HEADER % ("", "f32[64,64]{1,0}, f32[4]{0}",
                             "s32[4,1]{1,0}")
        fs = sharding_findings(bad, [(64, 64)], [(4,)],
                               local_shapes=[(64, 32)],
                               check_params=True,
                               label="tp step")
        assert [f.rule for f in fs] == ["HLO-SHARDING"]
        assert "fed replicated" in fs[0].message
        good = _HLO_HEADER % ("", "f32[64,32]{1,0}, f32[4]{0}",
                              "s32[4,1]{1,0}")
        assert sharding_findings(good, [(64, 64)], [(4,)],
                                 local_shapes=[(64, 32)],
                                 check_params=True) == []
        with pytest.raises(AssertionError, match="TP that isn't"):
            assert_plan_sharded(bad, [(64, 64)], [(4,)],
                                local_shapes=[(64, 32)], plan="tp")

    def test_fsdp_output_rule_still_enforced(self):
        # the PR 8 rule through the generalized entry point: a
        # full-shape sharded tensor in the entry OUTPUTS
        from zoo_tpu.analysis.hlo import sharding_findings
        bad = _HLO_HEADER % ("", "f32[8,64]{1,0}",
                             "f32[64,64]{1,0}")
        fs = sharding_findings(bad, [(64, 64)],
                               local_shapes=[(8, 64)],
                               label="fsdp step")
        assert [f.rule for f in fs] == ["HLO-SHARDING"]
        assert "FSDP that isn't" in fs[0].message


# ------------------------------------------------- framework / allowlist

class TestFramework:
    def test_allowlist_requires_justification(self, tmp_path):
        from zoo_tpu.analysis import LintError
        p = tmp_path / "allow.txt"
        p.write_text("KNOB-DEAD zoo_tpu/m.py ZOO_X\n")
        with pytest.raises(LintError, match="justification"):
            load_allowlist(str(p))

    def test_stale_entries_reported_by_cli(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "zoo_lint.py"),
             "--allowlist", os.path.join(REPO, "zoo_lint_allow.txt"),
             "--strict"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_findings_json_shape(self):
        from zoo_tpu.analysis import Finding
        doc = json.loads(findings_json(
            [Finding("R-1", "a.py", 3, "m", "h", "d")], [],
            {"git_rev": "x"}))
        assert doc["n_active"] == 1
        assert doc["active"][0]["rule"] == "R-1"
        assert doc["active_by_rule"] == {"R-1": 1}


# ------------------------------------------------- self-application gate

class TestSelfApplication:
    def test_linter_never_imports_jax(self):
        """The purity contract applies to the lint runner itself: a
        fresh interpreter that runs every AST pass over the real tree
        must finish without jax in sys.modules."""
        code = (
            "import sys\n"
            "import zoo_tpu.analysis as A\n"
            "fs = A.run_passes(A.Context(%r))\n"
            "assert 'jax' not in sys.modules, 'linter imported jax'\n"
            "print('PURE', len(fs))\n" % REPO)
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, cwd=REPO,
                             timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        assert out.stdout.startswith("PURE"), out.stdout

    def test_knob_registry_roundtrips_greppable_names(self):
        """Every ZOO_* token greppable in the code tree resolves
        against the registry (exactly, or as a prefix of a registered
        family), and every registered knob is greppable somewhere —
        the registry and the tree can never drift apart silently."""
        from zoo_tpu.common.knobs import KNOBS
        tokens = set()
        roots = ["zoo_tpu", "scripts"]
        files = ["bench.py", "__graft_entry__.py"]
        for root in roots:
            for dirpath, dirnames, filenames in os.walk(
                    os.path.join(REPO, root)):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                files.extend(os.path.join(dirpath, fn)
                             for fn in filenames
                             if fn.endswith(".py"))
        for path in files:
            if not os.path.isabs(path):
                path = os.path.join(REPO, path)
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8", errors="replace") as f:
                # (?<!) excludes _ZOO_* private IPC vars — a leading
                # underscore is the "not a knob" convention
                tokens.update(re.findall(
                    r"(?<![A-Z0-9_])ZOO_[A-Z0-9_]+[A-Z0-9]", f.read()))
        assert tokens, "grep found nothing — wrong root?"
        unknown = {
            t for t in tokens
            if t not in KNOBS
            and not any(k.startswith(t) for k in KNOBS)}
        assert not unknown, (
            f"ZOO_* names in the tree but not in the registry: "
            f"{sorted(unknown)} — register them in "
            "zoo_tpu/common/knobs.py")
        src = "\n".join(open(p, encoding="utf-8",
                             errors="replace").read()
                        for p in files if os.path.exists(p)
                        and "common/knobs.py" not in p.replace(
                            os.sep, "/"))
        # f-string reads (`f"ZOO_MESH_{name}"`) keep a whole knob
        # family alive through their literal prefix
        prefixes = set(re.findall(r"(ZOO_[A-Z0-9_]+_)\{", src))
        dead = {k for k in KNOBS if k not in src
                and not any(k.startswith(p) for p in prefixes)}
        assert not dead, (
            f"registered knobs not greppable anywhere: {sorted(dead)}")

    def test_tree_is_lint_clean_and_emits_report(self):
        """The in-suite strict gate: every AST pass over the real
        tree, zero non-allowlisted findings, machine-readable report
        written beside the BENCH_*.json trajectory files."""
        ctx = Context(REPO)
        findings = run_passes(ctx)
        entries = load_allowlist(ctx.allowlist_path)
        active, suppressed = apply_allowlist(findings, entries)
        report = findings_json(active, suppressed,
                               {"source": "tests/test_zoo_lint.py"})
        with open(os.path.join(REPO, "LINT.json"), "w",
                  encoding="utf-8") as f:
            f.write(report)
        assert not active, "\n" + "\n".join(
            f.format() for f in active)
        stale = [e for e in entries if not e.used]
        assert not stale, f"stale allowlist entries: " \
            f"{[(e.rule, e.file, e.detail) for e in stale]}"

    def test_declared_jax_free_modules_cover_the_contract(self):
        """The modules the chaos smokes rely on importing without jax
        all carry the machine-readable marker (regression against the
        marker being dropped in a refactor)."""
        from zoo_tpu.analysis.purity import jax_free_modules
        declared = set(jax_free_modules(Context(REPO)))
        for must in (
                "zoo_tpu/orca/learn/guard.py",
                "zoo_tpu/serving/registry.py",
                "zoo_tpu/serving/llm/kv_cache.py",
                "zoo_tpu/serving/ejection.py",
                "zoo_tpu/serving/llm/synthetic.py",
                "zoo_tpu/util/manifest.py",
                "zoo_tpu/util/resilience.py",
                "zoo_tpu/common/knobs.py",
                "zoo_tpu/obs/catalog.py",
                "zoo_tpu/analysis/framework.py",
        ):
            assert must in declared, f"{must} lost its marker"
