"""Parallelism specs on the virtual 8-device mesh: FSDP/TP placement,
sharded training parity, ring attention vs dense attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from zoo_tpu.ops.attention import dot_product_attention
from zoo_tpu.parallel import build_mesh
from zoo_tpu.parallel.plans import leaf_sharding, place_params
from zoo_tpu.parallel.ring_attention import ring_attention


def test_leaf_sharding_plan():
    mesh = build_mesh(axis_sizes={"data": 2, "fsdp": 2, "model": 2})
    # 2-D weight: model on output dim, fsdp on input dim
    s = leaf_sharding(mesh, (16, 8))
    assert s.spec == P("fsdp", "model")
    # output dim not divisible -> row parallel
    s = leaf_sharding(mesh, (16, 7))
    assert s.spec == P("model", None) or s.spec[0] == "model"
    # bias vector: fsdp only
    s = leaf_sharding(mesh, (8,))
    assert s.spec == P("fsdp")
    # nothing divisible
    s = leaf_sharding(mesh, (3, 5))
    assert s.spec == P()


def test_fsdp_training_matches_dp(orca_ctx):
    """Same seed, same data: pure-DP mesh and DP×FSDP mesh must produce the
    same losses — ZeRO sharding is a layout, not a math change."""
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense
    from zoo_tpu.pipeline.api.keras.optimizers import Adam

    rs = np.random.RandomState(0)
    x = rs.randn(128, 8).astype(np.float32)
    w = rs.randn(8, 1).astype(np.float32)
    y = x @ w

    def run():
        m = Sequential()
        m.add(Dense(16, activation="relu", input_shape=(8,)))
        m.add(Dense(1))
        m.compile(optimizer=Adam(lr=0.01), loss="mse")
        return m.fit(x, y, batch_size=32, nb_epoch=3, verbose=0)["loss"]

    loss_dp = run()  # orca_ctx fixture mesh: data=8

    stop_orca_context()
    init_orca_context(mesh_axes={"data": 2, "fsdp": 4})
    try:
        loss_fsdp = run()
    finally:
        stop_orca_context()
        init_orca_context()  # restore for fixture teardown symmetry

    np.testing.assert_allclose(loss_dp, loss_fsdp, rtol=2e-3)


def test_tp_training_runs(orca_ctx):
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.pipeline.api.keras import Sequential
    from zoo_tpu.pipeline.api.keras.layers import Dense

    stop_orca_context()
    init_orca_context(mesh_axes={"data": 2, "model": 4})
    try:
        rs = np.random.RandomState(0)
        x = rs.randn(64, 8).astype(np.float32)
        y = rs.randn(64, 4).astype(np.float32)
        m = Sequential()
        m.add(Dense(16, activation="relu", input_shape=(8,)))
        m.add(Dense(4))
        m.compile(optimizer="adam", loss="mse")
        hist = m.fit(x, y, batch_size=16, nb_epoch=2, verbose=0)
        assert np.isfinite(hist["loss"]).all()
        # params actually carry the model axis
        placed = m._place(m.params)
        specs = [p.sharding.spec for p in jax.tree_util.tree_leaves(placed)
                 if hasattr(p, "sharding")]
        assert any("model" in str(s) for s in specs)
    finally:
        stop_orca_context()
        init_orca_context()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = build_mesh(axis_sizes={"seq": 8})
    rs = np.random.RandomState(0)
    B, H, T, D = 2, 2, 32, 8
    q = rs.randn(B, H, T, D).astype(np.float32)
    k = rs.randn(B, H, T, D).astype(np.float32)
    v = rs.randn(B, H, T, D).astype(np.float32)

    dense = np.asarray(dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))

    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") \
            else _null():
        ring = np.asarray(ring_attention(mesh, jnp.asarray(q),
                                         jnp.asarray(k), jnp.asarray(v),
                                         causal=causal))
    np.testing.assert_allclose(ring, dense, atol=2e-5)


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_ring_attention_jit_under_mesh():
    mesh = build_mesh(jax.devices()[:4], axis_sizes={"seq": 4})
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 2, 16, 4).astype(np.float32))

    out = jax.jit(lambda q: ring_attention(mesh, q, q, q, causal=True))(q)
    dense = dot_product_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=2e-5)


@pytest.mark.slow
def test_ring_attention_gradients_match_dense():
    """Training with sequence parallelism needs d(ring_attention); the
    shard_map/ppermute program must differentiate to the dense grads."""
    import jax
    import jax.numpy as jnp
    from zoo_tpu.ops.attention import dot_product_attention
    from zoo_tpu.parallel import build_mesh
    from zoo_tpu.parallel.ring_attention import ring_attention

    mesh = build_mesh(jax.devices()[:4], axis_sizes={"seq": 4})
    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(2, 2, 16, 8).astype(np.float32))
               for _ in range(3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(mesh, q, k, v, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(
            q, k, v, causal=True, impl="dense") ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


def test_plan_registry_transformer_pairing():
    """The name-aware megatron pairing (plans.py registry): column into
    the heads, row back out — for both llama and BERT/GPT leaf names —
    with fsdp layered on a remaining dim."""
    from zoo_tpu.parallel.plans import named_leaf_sharding

    mesh = build_mesh(axis_sizes={"data": 2, "fsdp": 2, "model": 2})
    col = {"wq", "wk", "wv", "w_gate", "w_up", "qkv_w", "fc1_w"}
    row = {"wo", "w_down", "proj_w", "fc2_w"}
    for name in col:
        s = named_leaf_sharding(mesh, f"blocks/{name}", (4, 16, 16))
        assert s.spec[-1] == "model", (name, s.spec)
    for name in row:
        s = named_leaf_sharding(mesh, f"blocks/{name}", (4, 16, 16))
        assert s.spec[-2] == "model", (name, s.spec)
        assert "fsdp" in str(s.spec)  # fsdp still shards a free dim
    # unknown names keep the shape-based default exactly
    from zoo_tpu.parallel.plans import leaf_sharding
    assert named_leaf_sharding(mesh, "embed", (64, 16)).spec == \
        leaf_sharding(mesh, (64, 16)).spec
    # non-divisible TP dim: the rule declines, default takes over
    s = named_leaf_sharding(mesh, "blocks/wo", (4, 7, 16))
    assert s.spec == leaf_sharding(mesh, (4, 7, 16)).spec


def test_plan_registry_explicit_and_unknown():
    from zoo_tpu.parallel.plans import (
        get_plan,
        named_leaf_sharding,
        register_plan,
    )

    mesh = build_mesh(axis_sizes={"data": -1, "model": 2})
    with pytest.raises(KeyError, match="unknown sharding plan"):
        get_plan("nope")

    @register_plan("test-replicate-all")
    def _rule(mesh, name, shape):
        from zoo_tpu.parallel.mesh import replicated_sharding
        return replicated_sharding(mesh)

    s = named_leaf_sharding(mesh, "blocks/wq", (16, 16),
                            plan="test-replicate-all")
    assert s.spec == P()


def test_sharding_tree_matches_placement(orca_ctx):
    """sharding_tree (the jit in/out_shardings input) must agree leaf
    for leaf with what place_params actually does."""
    from zoo_tpu.parallel.plans import sharding_tree

    mesh = build_mesh(axis_sizes={"fsdp": 4, "model": 2})
    params = {"blocks": {"wq": jnp.ones((2, 16, 16)),
                         "attn_norm": jnp.ones((2, 16))},
              "embed": jnp.ones((64, 16))}
    placed = place_params(params, mesh)
    tree = sharding_tree(params, mesh)
    flat_p = jax.tree_util.tree_leaves(placed)
    flat_s = jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: hasattr(x, "spec"))
    for arr, sh in zip(flat_p, flat_s):
        assert arr.sharding.is_equivalent_to(sh, arr.ndim), (
            arr.sharding, sh)


def test_estimate_collective_bytes():
    from zoo_tpu.parallel.plans import estimate_collective_bytes

    params = {"w": np.zeros((16, 16), np.float32),   # fsdp-sharded
              "odd": np.zeros((7, 5), np.float32)}   # replicated
    mesh = build_mesh(axis_sizes={"data": 2, "fsdp": 4})
    est = estimate_collective_bytes(params, mesh)
    wb = 16 * 16 * 4
    ob = 7 * 5 * 4
    assert est["all_gather"] == int(2 * wb * 3 / 4)
    assert est["reduce_scatter"] == int(wb * 3 / 4)
    assert est["all_reduce"] == int(2 * ob * 1 / 2)
    # pure DP: no gathers, everything all-reduces
    dp = estimate_collective_bytes(params, build_mesh(
        axis_sizes={"data": 8}))
    assert dp["all_gather"] == 0 and dp["reduce_scatter"] == 0
    assert dp["all_reduce"] > 0
