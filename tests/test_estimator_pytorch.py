"""Orca PyTorch Estimator — the reference's
``test_estimator_pytorch_backend.py`` pattern: tiny torch Net, train, assert
improvement; weights cross the bridge both ways."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from zoo_tpu.orca.learn.pytorch import Estimator  # noqa: E402


def _linear_data(n=256, d=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    w = rs.randn(d, 1).astype(np.float32)
    return x, (x @ w).astype(np.float32)


@pytest.mark.heavy
def test_from_torch_fit_improves(orca_ctx):
    x, y = _linear_data()
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    est = Estimator.from_torch(
        model=net,
        optimizer=torch.optim.Adam(net.parameters(), lr=0.01),
        loss=nn.MSELoss())
    hist = est.fit({"x": x, "y": y}, epochs=5, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    preds = est.predict(x[:16])
    assert preds.shape == (16, 1)


def test_bridge_forward_matches_torch(orca_ctx):
    """Converted model must reproduce torch's forward exactly (eval mode)."""
    torch.manual_seed(0)
    net = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 3),
                        nn.Softmax(dim=-1))
    x = np.random.RandomState(0).randn(10, 6).astype(np.float32)
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()

    est = Estimator.from_torch(model=net, loss=nn.MSELoss())
    got = est.predict(x)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_bridge_conv_matches_torch(orca_ctx):
    torch.manual_seed(0)
    net = nn.Sequential(nn.Conv2d(2, 4, 3), nn.ReLU(),
                        nn.MaxPool2d(2), nn.Flatten(), nn.Linear(4 * 3 * 3, 2))
    x = np.random.RandomState(0).randn(4, 2, 8, 8).astype(np.float32)
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    est = Estimator.from_torch(model=net, loss=nn.MSELoss())
    got = est.predict(x)
    np.testing.assert_allclose(got, ref, atol=1e-4)


@pytest.mark.heavy
def test_cross_entropy_classifier(orca_ctx):
    rs = np.random.RandomState(0)
    x = rs.randn(256, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64) + (x[:, 1] > 0).astype(np.int64)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 3))
    est = Estimator.from_torch(
        model=net, optimizer=torch.optim.Adam(net.parameters(), lr=0.01),
        loss=nn.CrossEntropyLoss(), metrics=["accuracy"])
    est.fit({"x": x, "y": y}, epochs=6, batch_size=32)
    res = est.evaluate({"x": x, "y": y})
    assert res["accuracy"] > 0.7


def test_trained_weights_flow_back_to_torch(orca_ctx):
    x, y = _linear_data(n=128)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    est = Estimator.from_torch(
        model=net, optimizer=torch.optim.Adam(net.parameters(), lr=0.02),
        loss=nn.MSELoss())
    est.fit({"x": x, "y": y}, epochs=4, batch_size=32)
    zoo_preds = est.predict(x[:16])
    trained = est.get_model()
    with torch.no_grad():
        torch_preds = trained(torch.from_numpy(x[:16])).numpy()
    np.testing.assert_allclose(zoo_preds, torch_preds, atol=1e-4)


def test_unsupported_op_message(orca_ctx):
    """Tracing sees through arbitrary modules, so 'unsupported' now means
    an ATen op with no JAX mapping — the error must name it."""
    class Weird(nn.Module):
        def forward(self, x):
            return torch.special.i0(x)  # bessel: deliberately unmapped

    net = nn.Sequential(nn.Linear(4, 4), Weird())
    est = Estimator.from_torch(model=net, loss=nn.MSELoss())
    with pytest.raises(NotImplementedError, match="aten"):
        est.predict(np.ones((8, 4), np.float32))


def test_custom_forward_multi_input(orca_ctx):
    """Round-1 gap: the structural bridge was Sequential-only/single-input;
    the traced bridge must carry custom forward graphs with two inputs."""
    class TwoTower(nn.Module):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 8)
            self.b = nn.Linear(3, 8)
            self.head = nn.Linear(8, 1)

        def forward(self, xa, xb):
            return self.head(torch.tanh(self.a(xa)) *
                             torch.sigmoid(self.b(xb)))

    rs = np.random.RandomState(0)
    xa = rs.randn(64, 4).astype(np.float32)
    xb = rs.randn(64, 3).astype(np.float32)
    y = (xa.sum(1, keepdims=True) > 0).astype(np.float32)
    net = TwoTower()
    est = Estimator.from_torch(model=net, loss=nn.MSELoss(),
                               optimizer=__import__("torch").optim.Adam(
                                   net.parameters(), lr=0.01))
    hist = est.fit({"x": [xa, xb], "y": y}, epochs=4, batch_size=16)
    assert hist["loss"][-1] < hist["loss"][0]
    # logits parity with torch on the trained weights
    import torch as t
    trained = est.get_model()
    with t.no_grad():
        ot = trained(t.from_numpy(xa), t.from_numpy(xb)).numpy()
    oj = est.predict({"x": [xa, xb]})
    assert np.abs(oj - ot).max() < 1e-3


def test_creator_functions(orca_ctx):
    """The reference's creator-function style must work too."""
    x, y = _linear_data(n=128)

    est = Estimator.from_torch(
        model_creator=lambda cfg: nn.Sequential(
            nn.Linear(4, cfg["hidden"]), nn.ReLU(),
            nn.Linear(cfg["hidden"], 1)),
        optimizer_creator=lambda model, cfg: torch.optim.SGD(
            model.parameters(), lr=cfg["lr"]),
        loss_creator=lambda cfg: nn.MSELoss(),
        config={"hidden": 8, "lr": 0.05})
    hist = est.fit({"x": x, "y": y}, epochs=3, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]


@pytest.mark.slow
def test_hf_bert_finetune_parity(orca_ctx):
    """VERDICT round-1 acceptance: a HuggingFace-style BERT classifier
    fine-tunes through Estimator.from_torch (traced bridge), and converted
    logits match torch CPU to 1e-3 before AND after training."""
    transformers = pytest.importorskip("transformers")
    from transformers import BertConfig, BertForSequenceClassification

    cfg = BertConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=64, num_labels=2,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    bert = BertForSequenceClassification(cfg).eval()

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (64, 12)).astype(np.int32)
    # learnable rule: label = first token parity
    y = (ids[:, 0] % 2).astype(np.int32)

    est = Estimator.from_torch(
        model=bert, loss=nn.CrossEntropyLoss(),
        optimizer=torch.optim.AdamW(bert.parameters(), lr=5e-3))

    # pre-training parity
    pre = est.predict({"x": ids})
    with torch.no_grad():
        pt = bert(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
    assert np.abs(pre - pt).max() < 1e-3

    hist = est.fit({"x": ids, "y": y}, epochs=6, batch_size=16)
    assert hist["loss"][-1] < hist["loss"][0]

    # post-training parity: trained weights written back to torch
    trained = est.get_model()
    with torch.no_grad():
        pt2 = trained(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
    post = est.predict({"x": ids})
    assert np.abs(post - pt2).max() < 1e-3
