"""Orca PyTorch Estimator — the reference's
``test_estimator_pytorch_backend.py`` pattern: tiny torch Net, train, assert
improvement; weights cross the bridge both ways."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from zoo_tpu.orca.learn.pytorch import Estimator  # noqa: E402


def _linear_data(n=256, d=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    w = rs.randn(d, 1).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def test_from_torch_fit_improves(orca_ctx):
    x, y = _linear_data()
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    est = Estimator.from_torch(
        model=net,
        optimizer=torch.optim.Adam(net.parameters(), lr=0.01),
        loss=nn.MSELoss())
    hist = est.fit({"x": x, "y": y}, epochs=5, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    preds = est.predict(x[:16])
    assert preds.shape == (16, 1)


def test_bridge_forward_matches_torch(orca_ctx):
    """Converted model must reproduce torch's forward exactly (eval mode)."""
    torch.manual_seed(0)
    net = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 3),
                        nn.Softmax(dim=-1))
    x = np.random.RandomState(0).randn(10, 6).astype(np.float32)
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()

    est = Estimator.from_torch(model=net, loss=nn.MSELoss())
    got = est.predict(x)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_bridge_conv_matches_torch(orca_ctx):
    torch.manual_seed(0)
    net = nn.Sequential(nn.Conv2d(2, 4, 3), nn.ReLU(),
                        nn.MaxPool2d(2), nn.Flatten(), nn.Linear(4 * 3 * 3, 2))
    x = np.random.RandomState(0).randn(4, 2, 8, 8).astype(np.float32)
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    est = Estimator.from_torch(model=net, loss=nn.MSELoss())
    got = est.predict(x)
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_cross_entropy_classifier(orca_ctx):
    rs = np.random.RandomState(0)
    x = rs.randn(256, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64) + (x[:, 1] > 0).astype(np.int64)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 3))
    est = Estimator.from_torch(
        model=net, optimizer=torch.optim.Adam(net.parameters(), lr=0.01),
        loss=nn.CrossEntropyLoss(), metrics=["accuracy"])
    est.fit({"x": x, "y": y}, epochs=6, batch_size=32)
    res = est.evaluate({"x": x, "y": y})
    assert res["accuracy"] > 0.7


def test_trained_weights_flow_back_to_torch(orca_ctx):
    x, y = _linear_data(n=128)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    est = Estimator.from_torch(
        model=net, optimizer=torch.optim.Adam(net.parameters(), lr=0.02),
        loss=nn.MSELoss())
    est.fit({"x": x, "y": y}, epochs=4, batch_size=32)
    zoo_preds = est.predict(x[:16])
    trained = est.get_model()
    with torch.no_grad():
        torch_preds = trained(torch.from_numpy(x[:16])).numpy()
    np.testing.assert_allclose(zoo_preds, torch_preds, atol=1e-4)


def test_unsupported_module_message(orca_ctx):
    class Weird(nn.Module):
        def forward(self, x):
            return x

    net = nn.Sequential(nn.Linear(4, 4), Weird())
    est = Estimator.from_torch(model=net, loss=nn.MSELoss())
    with pytest.raises(ValueError, match="Weird"):
        est.predict(np.ones((8, 4), np.float32))


def test_creator_functions(orca_ctx):
    """The reference's creator-function style must work too."""
    x, y = _linear_data(n=128)

    est = Estimator.from_torch(
        model_creator=lambda cfg: nn.Sequential(
            nn.Linear(4, cfg["hidden"]), nn.ReLU(),
            nn.Linear(cfg["hidden"], 1)),
        optimizer_creator=lambda model, cfg: torch.optim.SGD(
            model.parameters(), lr=cfg["lr"]),
        loss_creator=lambda cfg: nn.MSELoss(),
        config={"hidden": 8, "lr": 0.05})
    hist = est.fit({"x": x, "y": y}, epochs=3, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
