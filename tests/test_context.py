import pytest


def test_init_and_stop_orca_context():
    from zoo_tpu.orca import init_orca_context, stop_orca_context
    from zoo_tpu.common import get_runtime_context

    ctx = init_orca_context(cluster_mode="local", cores=2)
    assert ctx.num_devices == 8  # virtual CPU mesh from conftest
    assert ctx.mesh.shape["data"] == 8
    assert get_runtime_context() is ctx
    # idempotent second call returns the same context
    assert init_orca_context() is ctx
    stop_orca_context()
    assert get_runtime_context(required=False) is None


def test_mesh_axes_layout():
    from zoo_tpu.orca import init_orca_context, stop_orca_context

    ctx = init_orca_context(mesh_axes={"data": 2, "model": 4})
    try:
        assert ctx.mesh.shape["data"] == 2
        assert ctx.mesh.shape["model"] == 4
    finally:
        stop_orca_context()


def test_bad_cluster_mode():
    from zoo_tpu.orca import init_orca_context
    with pytest.raises(ValueError):
        init_orca_context(cluster_mode="not-a-mode")


def test_orca_context_flags():
    from zoo_tpu.orca import OrcaContext

    OrcaContext.pandas_read_backend = "arrow"
    assert OrcaContext.pandas_read_backend == "arrow"
    OrcaContext.pandas_read_backend = "pandas"
    with pytest.raises(ValueError):
        OrcaContext.pandas_read_backend = "dask"
    OrcaContext.shard_size = 1000
    assert OrcaContext.shard_size == 1000
    OrcaContext.shard_size = None
    OrcaContext.train_data_store = "DISK_2"
    assert OrcaContext.train_data_store == "DISK_2"
    OrcaContext.train_data_store = "DRAM"
